"""Unified single-claim TPU bench series (VERDICT r3 #1).

The chip sits behind a single-client claim tunnel that can be
unclaimable for hours.  Rounds 1-3 split the measurement across
separate scripts (bench.py, bench_profile.py, bench_decode.py,
bench_search.py), each its own PJRT client — so one claim window
yielded ONE metric and the next script had to win the tunnel again.

This module is the fix: ONE process, ONE client, the WHOLE series.
Once the claim lands, it runs every phase back to back and appends
each record to bench_results.jsonl the moment it completes, so a
single claim window produces the complete evidence set:

  embed          e2e embedding throughput + event-driven p50
                 set->vector with per-stage histogram quantiles
                 (the headline metric; written to a recovery file
                 the parent can read even if a later phase hangs)
  embed_sweep    e2e throughput across (batch_cap, inflight_depth)
                 configs — the which-knob-next data for the
                 throughput gap
  profile        device / sync / pipelined ms per (batch, bucket)
                 with TFLOP/s + MFU on TPU
  kernels        every Pallas kernel executed + checked vs the jnp
                 math on the same backend: flash fwd, blockwise bwd,
                 causal prefill w/ GQA, fused cosine top-k (f32+bf16)
  search         cosine top-k queries/sec over the largest lane the
                 remaining window affords (target 1M rows)
  decode         prefill / chunked / per-token-sync / batched /
                 speculative tokens per second, plus the paged-vs-
                 dense KV sweep (batch {8,32,64} over a fixed
                 8-window page pool)
  decode_quant   the same core decode with int8 weight residency
  multichip      pod-sharded paged decode: aggregate tok/s through
                 ShardedCompletionModel (kv-head-sharded pools,
                 shard_map'd ragged kernel) at batch {32,64} over a
                 tp mesh of every visible device — vs the r05
                 single-chip row; CPU-mesh rows are labeled smoke
  loadgen        open-loop multi-tenant serving under QoS: a full
                 in-process stack (tiny real models) serves mixed
                 3-tenant embed/search/complete traffic from `spt
                 loadgen`'s clock-driven arrivals — goodput vs shed
                 + per-tenant p99, cpu_smoke-labeled off-TPU
  decode_daemon  completion-daemon e2e + continuous serving (the
                 only phase that ever hung on-chip, so it runs LAST)

Phases are ordered headline-first / riskiest-last and each is fenced:
a phase failure logs and moves on (its record is simply absent), and
every phase checks the remaining window before starting.  The ledger
(bench_results.jsonl) is the single source of truth (VERDICT r3 #5);
docs quote it, never the other way around.

Entry points:
  python bench_series.py             run BENCH_PHASES (default: all)
  bench.py                           tunnel-disciplined parent; its
                                     child runs this series
  bench_profile/decode/search.py     thin shims over single phases

Env: BENCH_CPU=1 (host CPU), BENCH_PHASES=embed,kernels,...,
SPTPU_BENCH_DEADLINE_EPOCH (wall-clock budget; phases that can't fit
are skipped), SPTPU_BENCH_RESULTFILE (headline recovery file), plus
the per-phase knobs documented on each phase function.
"""
from __future__ import annotations

import functools
import io
import json
import os
import re
import sys
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

RESULTS_LOG = os.environ.get(
    "SPTPU_BENCH_LEDGER", os.path.join(REPO, "bench_results.jsonl"))
BASELINE_PER_CHIP = 12_500.0
# ledger timestamp format — shared with bench.py's age check
TS_FMT = "%Y-%m-%dT%H:%M:%S%z"

ALL_PHASES = ("embed", "embed_sweep", "profile", "dispatch", "kernels",
              "search", "restage", "decode", "decode_quant",
              "multichip", "loadgen", "prefix", "disagg", "tier",
              "decode_daemon", "store_ops")

# conservative floor (seconds) a phase needs to be worth starting;
# compile costs dominate these on a cold .xla_cache
PHASE_MIN_S = {"embed": 0, "embed_sweep": 120, "profile": 90,
               "dispatch": 20,
               "kernels": 120, "search": 150, "restage": 180,
               "decode": 180, "decode_quant": 150, "multichip": 120,
               "loadgen": 60, "prefix": 90, "disagg": 90, "tier": 60,
               "decode_daemon": 120, "store_ops": 15}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def append_ledger(rec: dict, *, stamp: bool = True) -> dict:
    """THE ledger append (every bench entry point routes here so the
    path, timestamp format, and durability stay in one place).
    Atomic single write + fsync: evidence must survive a later hang.

    A run with SPTPU_FAULT armed is a chaos drill, not a performance
    claim: the record is labeled so a before/after comparison can
    never mistake fault-degraded numbers for a regression."""
    rec = dict(rec)
    if stamp:
        rec["ts"] = time.strftime(TS_FMT)
    try:
        from libsplinter_tpu.utils import faults
        if faults.armed():
            rec["faults_armed"] = sorted(
                p["spec"] for p in faults.stats().values())
    except Exception:
        pass
    try:
        # devtime attribution columns (PR 17): runtime-cause compile
        # count so far (a non-zero here poisons the perf claim the
        # same way armed faults do) and the device-ms share of wall —
        # how much of this run the accelerator was actually working
        from libsplinter_tpu.obs.devtime import DEVTIME
        rec.setdefault("compile_events", DEVTIME.compile_events())
        rec.setdefault("device_ms_share",
                       round(DEVTIME.device_ms_share(), 4))
    except Exception:
        pass
    try:
        with open(RESULTS_LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        log(f"[series] ledger append failed: {e}")
    return rec


class SeriesCtx:
    """Shared state for one series run: backend, deadline, ledger."""

    def __init__(self, deadline_epoch: float | None = None):
        self.deadline = deadline_epoch or float(os.environ.get(
            "SPTPU_BENCH_DEADLINE_EPOCH", time.time() + 86400))
        self.backend = "?"
        self.n_devices = 0
        self.headline: dict | None = None
        self.records: list[dict] = []
        # phase name -> "ok" | "failed" | "skipped" (set by run_series)
        self.phase_status: dict[str, str] = {}

    def remaining(self) -> float:
        return self.deadline - time.time()

    def record(self, rec: dict) -> dict:
        """Append one measurement to the ledger immediately."""
        rec = append_ledger(rec)
        self.records.append(rec)
        return rec


def _stage(name: str) -> None:
    """Stage marker (see bench.py: the parent reads the stage file to
    attribute a hang post-mortem)."""
    log(f"STAGE {name} t={time.strftime('%H:%M:%S')}")
    path = os.environ.get("SPTPU_BENCH_STAGEFILE")
    if path:
        try:
            with open(path, "a") as f:
                f.write(f"{time.time():.1f} {name}\n")
        except OSError:
            pass


# ---------------------------------------------------------------------------
# phase: embed — the headline metric
# ---------------------------------------------------------------------------

def make_texts(n: int) -> list[str]:
    import numpy as np

    rng = np.random.default_rng(0)
    words = ["tpu", "vector", "store", "seqlock", "arena", "signal",
             "epoch", "shard", "bloom", "label", "kernel", "mesh",
             "gather", "commit", "batch", "embed"]
    return [" ".join(rng.choice(words, size=int(rng.integers(4, 24))))
            for _ in range(n)]


def _arm_texts(st, texts) -> None:
    """(Re-)arm bench keys: content write + VARTEXT type + the embed
    request label — the one protocol the embed phases share."""
    from libsplinter_tpu import T_VARTEXT
    from libsplinter_tpu.engine import protocol as P

    for i, t in enumerate(texts):
        key = f"bench/{i}"
        st.set(key, t)
        st.set_type(key, T_VARTEXT)
        st.label_or(key, P.LBL_EMBED_REQ)


def _bench_store_name(suffix: str) -> str:
    """Parent-chosen store name wherever one exists: bench.py unlinks
    SPTPU_BENCH_STORE on every failure path, so phases that reuse it
    cannot leak shm segments when the child is SIGKILLed (phases run
    sequentially; each closes+unlinks before the next creates)."""
    return os.environ.get("SPTPU_BENCH_STORE",
                          f"/spt-{suffix}-{os.getpid()}")


def phase_embed(ctx: SeriesCtx) -> dict:
    """End-to-end embedding throughput per chip + p50 set->vector on
    the event-driven wake path, with per-stage p50/p95/p99 sourced
    from the span histograms riding the __embedder_stats heartbeat
    (PIPELINE_STAGES: drain / tokenize / dispatch / device_wait /
    commit).

    Env: BENCH_TEXTS (16384), BENCH_BATCH (4096), BENCH_BUCKET (64),
    BENCH_BUCKETS (16,32,BUCKET), BENCH_P50_PROBES (30).

    Defaults are the best config from the measured on-chip
    (batch_cap x inflight_depth) sweep (2026-07-31: 512->3,237,
    2048->6,860/7,197, 4096->8,260 emb/s/chip — per-dispatch runtime
    RTT amortizes with batch, device_ms stays MXU-bound), not a guess."""
    import threading

    import numpy as np

    from libsplinter_tpu import Store, T_VARTEXT
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.embedder import Embedder
    from libsplinter_tpu.models import (EmbeddingModel, EncoderConfig,
                                        default_tokenizer)
    from libsplinter_tpu.utils.trace import tracer

    # tuned-for-TPU defaults; the CPU quick-track (BENCH_CPU=1) keeps
    # its fast contract — 16384 texts at the measured ~17 emb/s CPU
    # rate would run for tens of minutes and trip the attempt timeout
    on_cpu = os.environ.get("BENCH_CPU") == "1" or ctx.backend == "cpu"
    n_texts = int(os.environ.get("BENCH_TEXTS",
                                 "256" if on_cpu else "16384"))
    batch = int(os.environ.get("BENCH_BATCH",
                               "64" if on_cpu else "4096"))
    bucket = int(os.environ.get("BENCH_BUCKET", "64"))
    buckets = tuple(int(x) for x in os.environ.get(
        "BENCH_BUCKETS", f"16,32,{bucket}").split(",")) \
        if os.environ.get("BENCH_BUCKETS") != "" else (bucket,)
    # f16 on the wire halves the vector-fetch bytes (the measured
    # bottleneck when link bandwidth caps the drain); "f32" opts out
    fetch = os.environ.get("BENCH_FETCH", "int8")
    fetch_dtype = None if fetch in ("f32", "", "none") else fetch

    cfg = EncoderConfig(out_dim=768, max_len=2048)
    model = EmbeddingModel(cfg, buckets=buckets, fetch_dtype=fetch_dtype)
    tok = default_tokenizer(cfg.vocab_size)

    _stage("compile")
    t0 = time.perf_counter()
    for bsz in (1, batch):          # p50 probe path + throughput path
        for b in model.buckets[:-1] if len(model.buckets) > 1 \
                else model.buckets:
            ids = np.zeros((bsz, b), np.int32)
            lens = np.full((bsz,), b, np.int32)
            model.encode_ids(ids, lens)
    compile_s = time.perf_counter() - t0
    log(f"compile: {compile_s:.1f}s")

    _stage("stage-store")
    name = _bench_store_name("series")
    Store.unlink(name)
    # max_val 4096: the traced heartbeat (counters + spans + stage
    # quantiles + slow log) must land un-degraded for the stage table
    st = Store.create(name, nslots=max(8192, n_texts * 2), max_val=4096,
                      vec_dim=768)
    runner = None
    try:
        texts = make_texts(n_texts)
        _arm_texts(st, texts)

        emb = Embedder(st, model=model, tokenizer=tok, max_ctx=2048,
                       batch_cap=batch)
        emb.attach()

        # untimed first drain: absorbs every data-dependent program
        # compile (tail batches pad to powers of two)
        _stage("throughput-warm-drain")
        t0 = time.perf_counter()
        done = emb.run_once()
        log(f"warm drain: {done}/{n_texts} in "
            f"{time.perf_counter() - t0:.2f}s (compiles included)")

        _arm_texts(st, texts)               # re-arm every key

        _stage("throughput")
        t0 = time.perf_counter()
        done = emb.run_once()
        dt = time.perf_counter() - t0
        eps = done / dt if dt > 0 else 0.0
        log(f"embedded={done}/{n_texts} in {dt:.2f}s -> "
            f"{eps:,.0f} emb/s/chip")

        # p50 set->vector on the EVENT-DRIVEN wake path, with spans
        # enabled so the latency decomposes into per-stage HISTOGRAM
        # QUANTILES (obs/hist.py via utils/trace.py) riding the
        # __embedder_stats heartbeat — true p50/p95/p99 per stage,
        # never means dressed as percentiles.
        # The daemon thread MUST be stopped on every exit path: later
        # phases share this process, and a still-running daemon would
        # use the store after the finally below closes/unlinks it.
        _stage("p50-wake")
        was_enabled = tracer.enabled
        tracer.enabled = True
        tracer.reset()
        runner = threading.Thread(
            target=emb.run,
            kwargs=dict(idle_timeout_ms=20, sweep_interval_s=3600.0),
            daemon=True)
        try:
            runner.start()
            time.sleep(0.05)

            lat, lat_timeouts = [], 0
            n_probes = int(os.environ.get("BENCH_P50_PROBES", "30"))
            for i in range(n_probes):
                key = f"lat/{i}"
                t1 = time.perf_counter()
                st.set(key, "latency probe text sample")
                st.set_type(key, T_VARTEXT)
                st.label_or(key, P.LBL_EMBED_REQ)
                st.bump(key)
                idx = st.find_index(key)
                deadline = t1 + 10.0
                timed_out = False
                while st.labels_at(idx) & P.LBL_EMBED_REQ:
                    if time.perf_counter() > deadline:
                        timed_out = True
                        break
                    time.sleep(0.0001)
                if timed_out:
                    lat_timeouts += 1
                else:
                    lat.append((time.perf_counter() - t1) * 1000)
        finally:
            emb.stop()
            runner.join(timeout=5.0)
            # the stage quantiles ride the heartbeat (the contract the
            # obs layer pins: bench consumes what any watcher could)
            emb.publish_stats()
            hb = {}
            try:
                hb = json.loads(st.get(P.KEY_EMBED_STATS)
                                .rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                pass
            stage_q = hb.get("quantiles") or tracer.quantiles("embed.")
            slow_log = hb.get("slow_log") or []
            tracer.enabled = was_enabled
        p50 = float(np.percentile(lat, 50)) if lat else -1.0
        p95 = float(np.percentile(lat, 95)) if lat else -1.0
        p99 = float(np.percentile(lat, 99)) if lat else -1.0

        # per-stage p50/p95/p99 from the span histograms, keyed by the
        # PIPELINE_STAGES contract.  The old table reported arithmetic
        # means over drains under a "p50" name; these are true
        # percentiles of per-drain stage wall (the p50 loop drains one
        # request at a time, so per-drain ~= per-request here).
        # device_wait is host-BLOCKED time only; overlapped device
        # time shows up in overlap_ratio, not as a stage.
        def _q(stage: str) -> dict:
            a = stage_q.get(stage) or {}
            return {k: a.get(k, 0.0)
                    for k in ("p50_ms", "p95_ms", "p99_ms",
                              "max_ms", "n")}

        stage_tbl = {s: _q(s) for s in P.PIPELINE_STAGES}
        n_req = int(stage_tbl["commit"]["n"]) or 1
        pipeline_counters = {
            "requests": n_req,
            "overlap_ratio": round(emb.stats.overlap_ratio(), 4),
            "probe_lane_hits": emb.stats.probe_lane_hits,
            "blocking_waits": emb.stats.blocking_waits,
            "ready_commits": emb.stats.ready_commits,
            "inflight_peak": emb.stats.inflight_peak,
            # resident-ring evidence (PR 7): how many device dispatches
            # the throughput drains actually paid per batch
            "ring_dispatches": emb.stats.ring_dispatches,
            "resident_iterations": emb.stats.resident_iterations,
            "ring_occupancy_peak": emb.stats.ring_occupancy_peak,
        }
        log(f"p50 set->vector (event-driven): {p50:.2f} ms  p95: "
            f"{p95:.2f} ms  p99: {p99:.2f} ms  "
            f"timeouts={lat_timeouts}  stage_quantiles={stage_tbl}  "
            f"counters={pipeline_counters}")
    finally:
        if runner is not None and runner.is_alive():
            # a wedged daemon thread still holds the mapping: closing
            # it under the thread could crash the whole series — leak
            # the store instead (the bench parent unlinks the name on
            # every failure path)
            log("[series] WARNING: daemon thread did not stop; "
                "leaking the bench store to avoid use-after-close")
        else:
            st.close()
            Store.unlink(name)

    rec = ctx.record({
        "metric": "embeddings_per_sec_per_chip",
        "value": round(eps, 1),
        "unit": "embeddings/s",
        "vs_baseline": round(eps / BASELINE_PER_CHIP, 4),
        "detail": {
            "backend": ctx.backend, "n_chips_visible": ctx.n_devices,
            "bucket": bucket, "buckets": list(model.buckets[:-1]),
            "batch": batch, "n_texts": n_texts,
            "fetch_dtype": fetch_dtype or "f32",
            "compile_s": round(compile_s, 1),
            "p50_set_to_vector_ms": round(p50, 2),
            "p95_set_to_vector_ms": round(p95, 2),
            "p99_set_to_vector_ms": round(p99, 2),
            "p50_samples": len(lat), "p50_timeouts": lat_timeouts,
            "stage_quantiles": stage_tbl,
            "pipeline_counters": pipeline_counters,
            "slow_log": slow_log[-4:],
        }})
    ctx.headline = rec

    # recovery file: the parent prints this even if a LATER phase hangs
    # and the child is killed mid-series
    path = os.environ.get("SPTPU_BENCH_RESULTFILE")
    if path:
        try:
            with open(path, "w") as f:
                json.dump({k: v for k, v in rec.items() if k != "ts"}, f)
        except OSError:
            pass
    return rec


# ---------------------------------------------------------------------------
# phase: embed_sweep — throughput vs (batch_cap, inflight_depth)
# ---------------------------------------------------------------------------

def phase_embed_sweep(ctx: SeriesCtx) -> dict:
    """VERDICT r3 #2's data collector: e2e drain throughput across
    (batch_cap, inflight_depth) configs so the claim window that
    measures the baseline ALSO says which knob to turn next.  Config
    order puts the no-new-compile points first (depth variations reuse
    the embed phase's batch-512 programs); the batch-256/1024 points
    pay their own compiles (absorbed by an untimed first drain each).

    Env: SWEEP_TEXTS (4096), SWEEP_CONFIGS
    ("512x2,512x1,512x4,256x2,1024x2" as batchxdepth; an optional
    third field picks the wire dtype per config, e.g.
    "4096x2xf32,4096x2xf16" — tunnel conditions drift between claim
    windows, so a fetch-dtype comparison is only meaningful run
    back-to-back inside ONE window)."""
    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.embedder import Embedder
    from libsplinter_tpu.models import (EmbeddingModel, EncoderConfig,
                                        default_tokenizer)

    n_texts = int(os.environ.get("SWEEP_TEXTS", "4096"))
    default_fetch = os.environ.get("BENCH_FETCH", "int8")

    def _parse(c: str) -> tuple[int, int, str]:
        parts = c.split("x")
        batch, depth = int(parts[0]), int(parts[1])
        return batch, depth, (parts[2] if len(parts) > 2
                              else default_fetch)

    # default set (2026-07-31): the f32/f16/int8 wire A/B at the tuned
    # batch_cap (same-window, so tunnel drift can't confound it) and
    # the 8192 scaling point
    cfgs = [_parse(c) for c in os.environ.get(
        "SWEEP_CONFIGS",
        "4096x2xf32,4096x2xf16,4096x2xint8,8192x2xf16").split(",")]
    bucket = int(os.environ.get("BENCH_BUCKET", "64"))
    buckets = tuple(int(x) for x in os.environ.get(
        "BENCH_BUCKETS", f"16,32,{bucket}").split(","))

    cfg = EncoderConfig(out_dim=768, max_len=2048)
    models: dict[str, EmbeddingModel] = {}

    def _model(fetch: str) -> EmbeddingModel:
        key = "f32" if fetch in ("f32", "", "none") else fetch
        if key not in models:
            # share one param set across wire dtypes: only the jitted
            # output cast differs, and a duplicate flax init would
            # burn claim-window seconds and device memory for nothing
            donor = next(iter(models.values()), None)
            models[key] = EmbeddingModel(
                cfg, buckets=buckets,
                params=None if donor is None else donor.params,
                fetch_dtype=None if key == "f32" else key)
        return models[key]

    tok = default_tokenizer(cfg.vocab_size)
    texts = make_texts(n_texts)

    name = _bench_store_name("sweep")
    Store.unlink(name)
    st = Store.create(name, nslots=max(8192, n_texts * 2),
                      max_val=2048, vec_dim=768)
    rows = []
    try:
        # (batch_cap, fetch) pairs whose programs (incl. pow2 tail
        # shapes) are compiled — each wire dtype is its own XLA program
        warmed: set[tuple[int, str]] = set()
        for batch, depth, fetch in cfgs:
            # a compile-paying config costs a full untimed warm drain
            # on top of the timed one; starting it in a thin window
            # overruns the attempt budget -> killed child -> wedge
            need = 90 if (batch, fetch) in warmed else 300
            if ctx.remaining() < need:
                log(f"[sweep] {ctx.remaining():.0f}s left < {need}s "
                    f"needed; stopping before {batch}x{depth}x{fetch}")
                break
            # one config must not lose the window's already-measured
            # rows: a device OOM at an aggressive batch_cap records an
            # error row and the sweep moves on
            try:
                emb = Embedder(st, model=_model(fetch), tokenizer=tok,
                               max_ctx=2048, batch_cap=batch,
                               inflight_depth=depth)
                emb.attach()
                if (batch, fetch) not in warmed:
                    # untimed drain absorbs this batch_cap's compiles
                    # (tail shapes are texts+bucket-mix determined, so
                    # one warm per batch_cap covers its depth variants)
                    _arm_texts(st, texts)
                    emb.run_once()
                    warmed.add((batch, fetch))
                _arm_texts(st, texts)
                t0 = time.perf_counter()
                done = emb.run_once()
                dt = time.perf_counter() - t0
                r = {"batch_cap": batch, "inflight_depth": depth,
                     "fetch": fetch,
                     "emb_s": round(done / dt, 1) if dt > 0 else 0.0,
                     "drained": done}
            except Exception as exc:                # noqa: BLE001
                r = {"batch_cap": batch, "inflight_depth": depth,
                     "fetch": fetch, "emb_s": 0.0, "drained": 0,
                     "error": f"{type(exc).__name__}: {exc}"[:300]}
            rows.append(r)
            log(f"[sweep] {json.dumps(r)}")
    finally:
        st.close()
        Store.unlink(name)

    if not rows or all(r["emb_s"] <= 0 for r in rows):
        # a scarce claim window must never ledger a measured-looking
        # 0.0 — fail the phase instead (run_series marks it failed)
        raise RuntimeError("sweep window expired before any config ran"
                           if not rows else
                           f"every sweep config failed: {rows}")
    best = max(rows, key=lambda r: r["emb_s"])
    return ctx.record({
        "metric": "embed_sweep_best",
        "value": best["emb_s"], "unit": "embeddings/s",
        "vs_baseline": round(best["emb_s"] / BASELINE_PER_CHIP, 4),
        "detail": {"backend": ctx.backend, "n_texts": n_texts,
                   "buckets": list(buckets), "configs": rows,
                   "best": best}})


# ---------------------------------------------------------------------------
# phase: profile — device / sync / pipelined per shape
# ---------------------------------------------------------------------------

# bf16 peak FLOP/s per chip for MFU accounting, by device_kind prefix
# (the tunneled dev chip reports "TPU v5 lite").  Rows record the peak
# they were normalized against so the ledger stays self-describing.
_TPU_PEAKS = (("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
              ("v4", 275e12), ("v6", 918e12))


def _tpu_peak_flops() -> tuple[float, str]:
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "") or ""
    for pat, peak in _TPU_PEAKS:
        if pat in kind.lower():
            return peak, kind
    return 197e12, f"{kind or 'unknown'} (assumed v5e-class)"


def _encoder_flops(cfg, batch: int, seq: int) -> float:
    """Forward matmul FLOPs for one (batch, seq) encode.  Per token
    per layer (matmul = 2*m*n*k): QKV+O projections 8h^2, attention
    score+apply 4*S*h, MLP 6*h*mlp for the SwiGLU 'nomic' variant
    (gate+up+down) or 4*h*mlp for 'bert' (up+down); elementwise/norm
    terms are noise at these shapes."""
    h, f = cfg.hidden, cfg.mlp_dim
    mlp_mats = 6 if cfg.variant == "nomic" else 4
    per_tok_layer = 8 * h * h + 4 * seq * h + mlp_mats * h * f
    return float(batch * seq * cfg.layers * per_tok_layer)


def phase_profile(ctx: SeriesCtx) -> dict:
    """Decomposition: steady-state device ms, sync-dispatch ms, and
    async-pipelined ms per (batch, bucket) shape, with TFLOP/s and MFU
    (vs bf16 peak) on TPU so the gap to target is a measured number.
    Env: PROFILE_SHAPES (512x16,512x32,512x64,8x1024,1x16,1x64),
    PROFILE_REPS (10)."""
    import numpy as np

    import jax

    from libsplinter_tpu.models import EmbeddingModel, EncoderConfig

    shapes_env = os.environ.get(
        "PROFILE_SHAPES", "512x16,512x32,512x64,8x1024,1x16,1x64")
    reps = int(os.environ.get("PROFILE_REPS", "10"))
    cfg = EncoderConfig(out_dim=768, max_len=2048)
    shapes = [tuple(int(x) for x in s.split("x"))
              for s in shapes_env.split(",")]
    buckets = tuple(sorted({b for _, b in shapes}))
    model = EmbeddingModel(cfg, buckets=buckets)

    # Runtime floor probes: what ONE round trip through the PJRT
    # runtime (here: the axon tunnel) costs regardless of work.  These
    # attribute the e2e numbers — if null_dispatch_ms ~= the p50
    # set->vector, the latency lives in the runtime, not this stack.
    #   null_dispatch_ms: scalar add on device, block_until_ready
    #   h2d_put_ms:       device_put of a 512x16 int32 id batch (32 KB)
    #   d2h_fetch_ms:     np.asarray of a (768,) f32 device vector
    floor_reps = int(os.environ.get("PROFILE_FLOOR_REPS", "30"))
    # 0 disables the (auxiliary) probes instead of crashing the phase
    # on np.percentile([])

    def _p50(fn) -> float:
        fn()                                   # warm/compile
        ts = []
        for _ in range(floor_reps):
            t0 = time.perf_counter()
            fn()
            ts.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(ts, 50))

    if floor_reps > 0:
        x_dev = jax.device_put(np.float32(1.0))
        add1 = jax.jit(lambda x: x + 1.0)
        ids_probe = np.zeros((512, 16), np.int32)
        # a FRESH device array per rep: jax.Array caches the host copy
        # on first np.asarray, so re-fetching one array times a no-op
        vec_pool = iter([jax.device_put(np.zeros(768, np.float32))
                         for _ in range(floor_reps + 1)])
        floor = {
            "reps": floor_reps,
            "null_dispatch_ms": round(
                _p50(lambda: add1(x_dev).block_until_ready()), 3),
            "h2d_put_ms": round(
                _p50(lambda: jax.device_put(ids_probe)
                     .block_until_ready()), 3),
            "d2h_fetch_ms": round(
                _p50(lambda: np.asarray(next(vec_pool))), 3),
        }
        log(f"[profile] runtime floor: {json.dumps(floor)}")
    else:
        floor = {"reps": 0, "disabled": True}

    rows = []
    for bsz, bucket in shapes:
        ids_h = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (bsz, bucket)).astype(np.int32)
        lens_h = np.full((bsz,), bucket, np.int32)
        model.encode_ids(ids_h, lens_h)          # compile

        ids_d, lens_d = jax.device_put(ids_h), jax.device_put(lens_h)
        fn = model._fn
        fn(model.params, ids_d, lens_d).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(model.params, ids_d, lens_d)
        out.block_until_ready()
        dev_ms = (time.perf_counter() - t0) / reps * 1e3

        t0 = time.perf_counter()
        for _ in range(reps):
            model.encode_ids(ids_h, lens_h)
        e2e_ms = (time.perf_counter() - t0) / reps * 1e3

        t0 = time.perf_counter()
        pends = [model.encode_ids_async(ids_h, lens_h)
                 for _ in range(reps)]
        for p in pends:
            p.materialize()
        pipe_ms = (time.perf_counter() - t0) / reps * 1e3

        r = {"batch": bsz, "bucket": bucket,
             "device_ms": round(dev_ms, 2),
             "sync_ms": round(e2e_ms, 2),
             "pipelined_ms": round(pipe_ms, 2),
             "device_emb_s": round(bsz / dev_ms * 1e3, 0),
             "pipelined_emb_s": round(bsz / pipe_ms * 1e3, 0)}
        tflops = _encoder_flops(cfg, bsz, bucket) / (dev_ms / 1e3) / 1e12
        r["device_tflops"] = round(tflops, 2)
        if ctx.backend == "tpu":
            peak, kind = _tpu_peak_flops()
            r["mfu_pct"] = round(100 * tflops * 1e12 / peak, 1)
            r["mfu_peak_tflops"] = round(peak / 1e12)
            r["device_kind"] = kind
        rows.append(r)
        log(json.dumps(r))

    big = max(rows, key=lambda r: r["batch"])
    return ctx.record({
        "metric": "encode_device_ms_per_batch",
        "value": big["device_ms"], "unit": "ms", "vs_baseline": 0.0,
        "detail": {"backend": ctx.backend, "reps": reps,
                   "runtime_floor": floor, "shapes": rows}})


# ---------------------------------------------------------------------------
# phase: dispatch — the runtime dispatch floor and its depth amortization
# ---------------------------------------------------------------------------

def dispatch_depth_rows(depths=(1, 2, 4, 8), reps: int = 30) -> list:
    """Per-drain runtime dispatch cost amortized over depth, for BOTH
    PR-7 mechanisms (ISSUE 7; engine/resident.py):

      overlap    K un-awaited null dispatches held, then one blocking
                 drain of them all (the InflightWindow discipline) —
                 amortized per-drain cost = wall / K;
      resident   ONE dispatch whose lax.while_loop runs K iterations
                 (the resident-ring discipline; the trip count is a
                 scalar OPERAND, so every depth reuses one compiled
                 program) — amortized = wall / K.

    The work per iteration is a scalar add — pure dispatch/loop
    overhead, no compute to hide behind — so the rows attribute the
    floor itself, the way null_dispatch_ms did for depth 1 in r05.
    Returns [{depth, overlap_ms_per_drain, resident_ms_per_drain,
    ...}] with p50s over `reps`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    x = jax.device_put(np.float32(1.0))
    add1 = jax.jit(lambda v: v + 1.0)

    @jax.jit
    def ring(v, n):
        def body(c):
            i, acc = c
            return i + 1, acc + 1.0

        return jax.lax.while_loop(lambda c: c[0] < n, body,
                                  (jnp.int32(0), v))[1]

    add1(x).block_until_ready()                    # compile both once
    ring(x, jnp.int32(max(depths))).block_until_ready()

    def _p50(fn) -> float:
        fn()
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(ts, 50))

    rows = []
    for k in depths:
        def overlap(k=k):
            futs = [add1(x) for _ in range(k)]
            for f in futs:
                f.block_until_ready()

        def resident(k=k):
            ring(x, jnp.int32(k)).block_until_ready()

        o = _p50(overlap)
        rt = _p50(resident)
        rows.append({"depth": k,
                     "overlap_total_ms": round(o, 4),
                     "overlap_ms_per_drain": round(o / k, 4),
                     "resident_total_ms": round(rt, 4),
                     "resident_ms_per_drain": round(rt / k, 4)})
    return rows


def phase_dispatch(ctx: SeriesCtx) -> dict:
    """Dispatch-floor attribution arm: r05 measured null_dispatch_ms
    ~63 ms (94% of the 67.2 ms p50 set->vector) at depth 1 — the
    before-row.  This sweeps dispatch_depth in {1,2,4,8} and ledgers
    the amortized per-drain dispatch cost for the resident-ring and
    K-overlap paths, so the serving knobs (--ring-depth /
    --inflight-depth) have attribution data on the same backend the
    latencies were measured on.  Env: DISPATCH_DEPTHS (1,2,4,8),
    DISPATCH_REPS (30)."""
    depths = tuple(int(x) for x in os.environ.get(
        "DISPATCH_DEPTHS", "1,2,4,8").split(","))
    reps = int(os.environ.get("DISPATCH_REPS", "30"))
    rows = dispatch_depth_rows(depths, reps)
    d1 = rows[0]
    dk = rows[-1]

    def _x(a: float, b: float) -> float:
        return round(a / max(b, 1e-9), 1)

    detail = {
        "backend": ctx.backend, "reps": reps,
        # the r05 before-rows this arm attributes (BENCH_r05 profile
        # phase: the dispatch floor ~= the whole p50)
        "before": {"r05_null_dispatch_ms": 63.0,
                   "r05_p50_set_to_vector_ms": 67.2},
        "rows": rows,
        "resident_amortization_x": _x(d1["resident_ms_per_drain"],
                                      dk["resident_ms_per_drain"]),
        "overlap_amortization_x": _x(d1["overlap_ms_per_drain"],
                                     dk["overlap_ms_per_drain"]),
    }
    log(f"[dispatch] {json.dumps(detail['rows'])}")
    return ctx.record({
        "metric": "dispatch_depth",
        "value": dk["resident_ms_per_drain"],
        "unit": f"ms/drain (amortized, depth {dk['depth']})",
        "vs_baseline": 0.0,
        "detail": detail})


# ---------------------------------------------------------------------------
# phase: kernels — every Pallas kernel executed + checked on this backend
# ---------------------------------------------------------------------------

def phase_kernels(ctx: SeriesCtx) -> dict:
    """VERDICT r3 #4: run the full Pallas tier on the real backend once —
    flash forward, blockwise backward (grad check vs naive), causal
    prefill with GQA head routing, and the fused cosine top-k (f32 and
    bf16-MXU) over a large lane — asserting numerics against the jnp
    path on the SAME device and recording timings.

    On TPU the kernels lower through Mosaic (the thing interpret-mode
    tests cannot prove); on CPU (BENCH_CPU=1 quick-tracking) the same
    comparisons run with interpret=True at reduced sizes.

    Env: KERNELS_SEQ (512), KERNELS_ROWS (262144; auto-shrunk to fit
    the window), KERNELS_REPS (10)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from libsplinter_tpu.ops.flash_attention import (
        _causal_jnp, _mha_jnp, causal_flash_attention, flash_attention)
    from libsplinter_tpu.ops.similarity import cosine_topk

    on_tpu = ctx.backend == "tpu"
    interp = not on_tpu
    S = int(os.environ.get("KERNELS_SEQ", "512" if on_tpu else "128"))
    n_rows = int(os.environ.get("KERNELS_ROWS",
                                "262144" if on_tpu else "8192"))
    reps = int(os.environ.get("KERNELS_REPS", "10"))
    detail: dict = {"backend": ctx.backend, "interpret": interp,
                    "seq": S, "rows": n_rows}
    rng = np.random.default_rng(7)

    def timed(fn, *args, **kw):
        out = fn(*args, **kw)           # compile + warm
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args, **kw)
        jax.block_until_ready(out)
        return out, (time.perf_counter() - t0) / reps * 1e3

    # -- flash forward (bidirectional, masked) ------------------------------
    B, H, D = 4, 12, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
               for _ in range(3))
    lens = np.asarray([S, S - 3, S // 2, 5])
    mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])

    flash = lambda: flash_attention(q, k, v, mask, interpret=interp,
                                    force_pallas=True)
    out_f, flash_ms = timed(flash)
    out_ref = _mha_jnp(q, k, v, mask)
    # compare only valid rows: fully-masked rows are don't-care by the
    # encoder-pooling contract (see flash_attention.py docstring)
    w = mask.astype(jnp.float32)[:, :, None, None]
    fwd_diff = float(jnp.max(jnp.abs((out_f - out_ref) * w)))
    detail["flash_fwd"] = {"ms": round(flash_ms, 2),
                           "max_abs_diff": fwd_diff,
                           "ok": bool(fwd_diff < 2e-3)}
    log(f"flash fwd S={S}: {flash_ms:.2f} ms, diff={fwd_diff:.2e}")

    # -- flash blockwise backward (grad check vs naive) ---------------------
    # Correctness and timing are SEPARATE arms.  At default precision
    # Mosaic truncates f32 dot inputs to bf16 exactly like XLA does for
    # the naive einsums, so kernel-vs-naive diffs there are dominated
    # by the two paths' different rounding orders (~5e-3 relative,
    # deterministic — measured on-chip 2026-08-02), not kernel bugs.
    # The check therefore runs BOTH paths at Precision.HIGHEST, which
    # isolates the algorithm; the timing runs the production default.
    def loss_flash(q_, k_, v_, hi=False):
        return jnp.sum(flash_attention(q_, k_, v_, mask,
                                       interpret=interp,
                                       force_pallas=True,
                                       hi_prec=hi) * w)

    def loss_naive(q_, k_, v_):
        return jnp.sum(_mha_jnp(q_, k_, v_, mask) * w)

    grad_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    grad_flash_hi = jax.jit(jax.grad(
        functools.partial(loss_flash, hi=True), argnums=(0, 1, 2)))
    with jax.default_matmul_precision("highest"):
        grad_naive = jax.jit(jax.grad(loss_naive, argnums=(0, 1, 2)))
        nq, nk, nv = grad_naive(q, k, v)
    (dq, dk, dv), bwd_ms = timed(grad_flash, q, k, v)  # production arm
    gq, gk, gv = grad_flash_hi(q, k, v)                # checked arm
    bwd_diff = float(max(jnp.max(jnp.abs(a - b))
                         for a, b in ((gq, nq), (gk, nk), (gv, nv))))
    grad_scale = float(max(jnp.max(jnp.abs(g)) for g in (nq, nk, nv)))
    bwd_rel = bwd_diff / (grad_scale + 1e-9)
    # the production-precision gradients get their own (looser) sanity
    # bound vs the f32 oracle so a default-arm-only regression (e.g. a
    # demoted accumulator the HIGHEST decomposition would mask) still
    # fails the phase; 5e-2 clears the measured ~5e-3 rounding-order
    # noise with margin while catching order-of-magnitude breakage
    def_diff = float(max(jnp.max(jnp.abs(a - b))
                         for a, b in ((dq, nq), (dk, nk), (dv, nv))))
    def_rel = def_diff / (grad_scale + 1e-9)
    detail["flash_bwd"] = {"ms": round(bwd_ms, 2),
                           "max_abs_diff": bwd_diff,
                           "grad_scale": round(grad_scale, 3),
                           "rel_diff": bwd_rel,
                           "checked_at": "highest-vs-highest",
                           "default_rel_diff": def_rel,
                           "ok": bool(bwd_rel < 1e-3
                                      and def_rel < 5e-2)}
    log(f"flash bwd S={S}: {bwd_ms:.2f} ms, diff={bwd_diff:.2e} "
        f"(rel {bwd_rel:.2e} of grad scale {grad_scale:.1f}, "
        f"checked at highest precision; default-arm rel "
        f"{def_rel:.2e})")

    # -- causal prefill with GQA head routing -------------------------------
    Bp, Sp, T, Hq, KH = 2, max(S // 2, 64), S, 8, 2
    pos = T - Sp
    qc = jnp.asarray(rng.normal(size=(Bp, Sp, Hq, D)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(Bp, T, KH, D)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(Bp, T, KH, D)), jnp.float32)
    start = jnp.asarray([0, 7], jnp.int32)

    causal = lambda: causal_flash_attention(
        qc, kc, vc, pos, start, interpret=interp, force_pallas=True)
    out_c, causal_ms = timed(causal)
    rep = Hq // KH
    out_cr = _causal_jnp(qc, jnp.repeat(kc, rep, axis=2),
                         jnp.repeat(vc, rep, axis=2),
                         pos, start)
    causal_diff = float(jnp.max(jnp.abs(out_c - out_cr)))
    detail["causal_prefill_gqa"] = {
        "ms": round(causal_ms, 2), "max_abs_diff": causal_diff,
        "gqa_rep": rep, "ok": bool(causal_diff < 2e-3)}
    log(f"causal prefill S={Sp} T={T} GQA x{rep}: {causal_ms:.2f} ms, "
        f"diff={causal_diff:.2e}")

    # -- fused cosine top-k over a large lane (f32 + bf16 MXU) --------------
    lane = rng.normal(size=(n_rows, 768)).astype(np.float32)
    t0 = time.perf_counter()
    lane_dev = jax.device_put(lane)
    jax.block_until_ready(lane_dev)
    stage_s = time.perf_counter() - t0
    detail["lane_stage_s"] = round(stage_s, 2)
    detail["lane_stage_mb_s"] = round(lane.nbytes / 1e6 / stage_s, 1) \
        if stage_s > 0 else None
    query = lane[12345 % n_rows] + 0.05 * rng.normal(size=768) \
        .astype(np.float32)
    k_top = 10

    # the pallas path is what we're proving; the jnp path on the SAME
    # device is the oracle
    (s_p, i_p), pal_ms = timed(
        cosine_topk, lane_dev, query, k_top,
        use_pallas=(True if on_tpu else None))
    if on_tpu:
        (s_j, i_j), jnp_ms = timed(cosine_topk, lane_dev, query, k_top,
                                   use_pallas=False)
        overlap = len(set(map(int, i_p)) & set(map(int, i_j))) / k_top
        sdiff = float(np.max(np.abs(s_p - s_j)))
        (s_b, i_b), bf16_ms = timed(cosine_topk, lane_dev, query, k_top,
                                    use_pallas=True, mxu_bf16=True)
        bf16_overlap = len(set(map(int, i_b))
                           & set(map(int, i_j))) / k_top
        # tile-size sweep: which N-block suits this chip's VMEM (the
        # default-1024 timing seeds the dict so every tile lives in
        # one comparable field)
        bn_sweep = {"1024": round(pal_ms, 2)}
        for bn in (512, 2048, 4096):
            try:
                (_, _), bn_ms = timed(cosine_topk, lane_dev, query,
                                      k_top, use_pallas=True,
                                      block_n=bn)
                bn_sweep[str(bn)] = round(bn_ms, 2)
            except Exception as e:
                # first line only, ANSI escapes dropped: compile-server
                # errors are multiline and colorized
                stripped = re.sub(r"\x1b\[[0-9;]*m", "", str(e))
                msg = (stripped.splitlines() or [""])[0]
                bn_sweep[str(bn)] = f"failed: {msg}"[:120]
        detail["cosine_topk"] = {
            "pallas_ms": round(pal_ms, 2), "jnp_ms": round(jnp_ms, 2),
            "bf16_ms": round(bf16_ms, 2),
            "block_n_sweep_ms": bn_sweep,
            "topk_overlap_vs_jnp": overlap,
            "score_max_abs_diff": sdiff,
            "bf16_topk_overlap": bf16_overlap,
            "ok": bool(overlap >= 0.9 and sdiff < 1e-3
                       and bf16_overlap >= 0.8)}
        log(f"cosine_topk {n_rows}x768: pallas {pal_ms:.2f} ms vs jnp "
            f"{jnp_ms:.2f} ms, overlap={overlap:.2f}, bf16 {bf16_ms:.2f}"
            f" ms overlap={bf16_overlap:.2f}")
    else:
        detail["cosine_topk"] = {"jnp_ms": round(pal_ms, 2),
                                 "ok": True,
                                 "note": "cpu: jnp path only"}
        log(f"cosine_topk {n_rows}x768 (jnp/cpu): {pal_ms:.2f} ms")

    all_ok = all(v.get("ok", True) for v in detail.values()
                 if isinstance(v, dict))
    return ctx.record({
        "metric": "kernels_smoke",
        "value": 1.0 if all_ok else 0.0, "unit": "ok",
        "vs_baseline": 0.0, "detail": detail})


# ---------------------------------------------------------------------------
# phase: search — cosine top-k q/s at the largest affordable lane
# ---------------------------------------------------------------------------

def phase_search(ctx: SeriesCtx) -> dict:
    """BASELINE.md: cosine top-k over a 1M-vector arena.  Stages the
    lane (staging time is itself reported — it is the StagedLane
    restage cost at full-lane granularity), then measures:

      - legacy (unfused) single-query / QB=32 / QB=256 q/s — the rows
        comparable with BENCH_r05's 12.1 q/s single-query cliff;
      - the FUSED streaming kernel (score+select in VMEM, O(k*Q)
        off-chip) single-query and a QB sweep {1, 32, 256};
      - the coalescing search daemon end to end, with stage quantiles
        sourced from its own heartbeat (SEARCH_STAGES histograms).

    Env: SEARCH_N (1,000,000 on TPU / 100,000 on CPU), SEARCH_D (768),
    SEARCH_K (10), SEARCH_REPS (20), SEARCHD_N (8192), SEARCHD_WAVES
    (8)."""
    import numpy as np

    import jax

    from libsplinter_tpu.ops.similarity import cosine_topk, \
        cosine_topk_batch

    d = int(os.environ.get("SEARCH_D", "768"))
    k = int(os.environ.get("SEARCH_K", "10"))
    reps = int(os.environ.get("SEARCH_REPS", "20"))
    on_tpu = ctx.backend == "tpu"
    n = int(os.environ.get("SEARCH_N",
                           "1000000" if on_tpu else "100000"))
    use_pallas = on_tpu

    log(f"search lane=({n}, {d})")
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    lane = rng.normal(size=(n, d)).astype(np.float32)
    gen_s = time.perf_counter() - t0
    QB = 32
    # the big batch exposes the device's aggregate rate through a
    # high-RTT runtime: at ~70 ms/dispatch, single-query q/s measures
    # the tunnel, QB amortizes it
    QB2 = int(os.environ.get("SEARCH_QB2", "256"))
    queries = rng.normal(size=(max(reps, QB, QB2), d)) \
        .astype(np.float32)

    # probe the host->device bandwidth on a small slice first: over
    # the tunnel it is an unknown, and a 2.9 GB device_put that takes
    # most of the window would starve the remaining phases.  The probe
    # is 4096 rows (~12 MB — bounded even at 1 MB/s); n then shrinks
    # in 2x steps to an 8192-row floor until the projected staging
    # fits the budget, and a projection that exceeds the budget even
    # at the floor is logged rather than silently tolerated.
    probe_rows = min(4096, n)
    t0 = time.perf_counter()
    probe = jax.device_put(lane[:probe_rows])
    jax.block_until_ready(probe)
    probe_s = max(time.perf_counter() - t0, 1e-6)
    mb_s = probe_rows * d * 4 / 1e6 / probe_s
    budget_s = max(ctx.remaining() - 150, 30)

    def proj_s(rows: int) -> float:
        return rows * d * 4 / 1e6 / mb_s

    while n > 8192 and proj_s(n) > budget_s:
        n //= 2
    if n < lane.shape[0]:
        log(f"[search] staging at {mb_s:,.0f} MB/s would blow the "
            f"window; lane shrunk to {n} rows")
        lane = lane[:n]
    if proj_s(n) > budget_s:
        log(f"[search] WARNING: even {n} rows project to "
            f"{proj_s(n):.0f}s staging (> {budget_s:.0f}s budget); "
            f"proceeding — later phases may be skipped")
    del probe

    t0 = time.perf_counter()
    lane_dev = jax.device_put(lane)
    jax.block_until_ready(lane_dev)
    stage_s = time.perf_counter() - t0
    vnorm_dev = jax.device_put(np.linalg.norm(lane, axis=1)
                               .astype(np.float32))
    log(f"lane host-gen {gen_s:.1f}s, staged to device in {stage_s:.1f}s"
        f" ({lane.nbytes / 1e6 / max(stage_s, 1e-9):,.0f} MB/s)")

    def bench_kernel(mxu_bf16: bool, fused: bool | None = False) -> float:
        cosine_topk(lane_dev, queries[0], k, use_pallas=use_pallas,
                    mxu_bf16=mxu_bf16, vnorm=vnorm_dev, fused=fused)
        t0 = time.perf_counter()
        for i in range(reps):
            cosine_topk(lane_dev, queries[i], k, use_pallas=use_pallas,
                        mxu_bf16=mxu_bf16, vnorm=vnorm_dev, fused=fused)
        return reps / (time.perf_counter() - t0)

    def bench_batch(qb: int, fused: bool | None) -> float:
        qs_in = queries[:qb]
        qb = len(qs_in)          # queries may be shorter than the ask:
        # the rate must count the rows actually scored, not the target
        cosine_topk_batch(lane_dev, qs_in, k, use_pallas=use_pallas,
                          vnorm=vnorm_dev, fused=fused)
        reps_b = max(2, reps // qb)
        t0 = time.perf_counter()
        for _ in range(reps_b):
            cosine_topk_batch(lane_dev, qs_in, k, use_pallas=use_pallas,
                              vnorm=vnorm_dev, fused=fused)
        return reps_b * qb / (time.perf_counter() - t0)

    # legacy (unfused) rows stay fused=False so they remain comparable
    # with BENCH_r05's 12.1 q/s single / 2262.8 q/s QB=256 cliff
    qps_f32 = bench_kernel(False)
    qps_bf16 = bench_kernel(True) if on_tpu else 0.0
    log(f"kernel: {qps_f32:.1f} q/s f32 (unfused)"
        + (f", {qps_bf16:.1f} q/s bf16" if qps_bf16 else ""))

    qps_batch = bench_batch(QB, False)
    log(f"batched: {qps_batch:.1f} q/s aggregate (QB={QB}, unfused)")
    qps_batch_big = bench_batch(QB2, False) if QB2 > QB else 0.0
    if qps_batch_big:
        log(f"batched: {qps_batch_big:.1f} q/s aggregate (QB={QB2}, "
            f"unfused)")

    # fused streaming kernel (score + select in VMEM, O(k*Q) off-chip):
    # the QB sweep is the daemon's coalescing schedule.  On CPU the
    # fused selector falls back to the jnp score-matrix path, so the
    # sweep only measures something new on the pallas backend.
    fused_sweep = {}
    qps_fused_single = 0.0
    if on_tpu:
        # fenced per measurement: a Mosaic lowering failure on one
        # toolchain must cost that row, not the daemon section below
        try:
            qps_fused_single = bench_kernel(False, fused=True)
            log(f"fused kernel: {qps_fused_single:.1f} q/s single")
        except Exception as e:
            log(f"[search] fused single failed: {e}")
        for qb in (1, 32, 256):
            try:
                fused_sweep[str(qb)] = round(bench_batch(qb, True), 1)
                log(f"fused batched: {fused_sweep[str(qb)]} q/s "
                    f"aggregate (QB={qb})")
            except Exception as e:
                fused_sweep[str(qb)] = f"failed: {e}"[:120]

    # host numpy scan: vectorized stand-in for the reference's scalar C
    # scan (splinter_cli_cmd_search.c:374-412), i.e. a GENEROUS baseline
    nn = min(n, 100_000)
    sub = lane[:nn]
    norms = np.linalg.norm(sub, axis=1)
    t0 = time.perf_counter()
    reps_np = max(3, reps // 4)
    for i in range(reps_np):
        qv = queries[i]
        s = sub @ qv / np.maximum(norms * np.linalg.norm(qv), 1e-12)
        np.argpartition(-s, k)[:k]
    qps_np = reps_np / (time.perf_counter() - t0) * (nn / n)
    log(f"numpy scan (scaled to {n} rows): {qps_np:.2f} q/s")

    # search-daemon micro-bench: concurrent requests coalesce into
    # batched dispatches, stage quantiles come from the daemon's OWN
    # heartbeat (the histogram surface operators see), never re-timed
    # ad hoc here.  Fenced: a daemon failure costs this section only.
    daemon_detail = None
    try:
        daemon_detail = _search_daemon_bench(lane, queries, d, k)
    except Exception:
        log("[search] daemon micro-bench failed:")
        log(traceback.format_exc())

    best = max(qps_f32, qps_bf16, qps_fused_single)
    detail = {
        "backend": ctx.backend, "n": n, "d": d, "k": k,
        "qps_f32": round(qps_f32, 1),
        "qps_bf16_fast": round(qps_bf16, 1),
        "qps_batch32_aggregate": round(qps_batch, 1),
        "qb_big": QB2,
        "qps_batch_big_aggregate": round(qps_batch_big, 1),
        "bf16_speedup": round(qps_bf16 / qps_f32, 2)
        if qps_f32 > 0 and qps_bf16 > 0 else None,
        "qps_fused_single": round(qps_fused_single, 1),
        "qps_fused_qb_sweep": fused_sweep or None,
        "fused_vs_unfused_single": round(qps_fused_single / qps_f32, 2)
        if qps_fused_single > 0 and qps_f32 > 0 else None,
        "qps_numpy_hostscan": round(qps_np, 2),
        "lane_stage_s": round(stage_s, 2),
        "lane_mb": round(lane.nbytes / 1e6, 1),
    }
    if daemon_detail is not None:
        detail["daemon"] = daemon_detail
    return ctx.record({
        "metric": "search_queries_per_sec",
        "value": round(best, 1),
        "unit": "queries/s",
        "vs_baseline": round(best / qps_np, 2) if qps_np > 0 else 0.0,
        "detail": detail})


def _search_daemon_bench(lane, queries, d: int, k: int) -> dict:
    """Coalescing search daemon against a real store: waves of 32
    concurrent requests per drain, fused top-k dispatches, heartbeat-
    sourced SEARCH_STAGES quantiles.  Env: SEARCHD_N (store slots,
    default 8192), SEARCHD_WAVES (default 8)."""
    import json as _json

    from libsplinter_tpu import Store as _Store
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.searcher import Searcher
    from libsplinter_tpu.utils.trace import tracer

    nslots = int(os.environ.get("SEARCHD_N", "8192"))
    waves = int(os.environ.get("SEARCHD_WAVES", "8"))
    per_wave = 32
    name = _bench_store_name("srchd")
    _Store.unlink(name)
    st = _Store.create(name, nslots=nslots, max_val=4096, vec_dim=d)
    prev_traced = tracer.enabled
    tracer.enabled = True
    try:
        rows = min(nslots // 2, len(lane))
        for i in range(rows):
            st.set(f"doc/{i}", "x")
            st.vec_set(f"doc/{i}", lane[i])
        sr = Searcher(st)
        sr.attach()
        t0 = time.perf_counter()
        for w in range(waves):
            for j in range(per_wave):
                key = f"__sqtmp_bench{j}"
                st.set(key, _json.dumps({"k": k}))
                st.vec_set(key, queries[(w * per_wave + j)
                                        % len(queries)])
                st.label_or(key, P.LBL_SEARCH_REQ)
                st.bump(key)
            served = sr.run_once()
            assert served == per_wave, (served, per_wave)
        el = time.perf_counter() - t0
        sr.publish_stats()
        snap = _json.loads(st.get(P.KEY_SEARCH_STATS).rstrip(b"\0"))
        quant = {
            stage: {f: round(v[f], 3) for f in
                    ("p50_ms", "p95_ms", "p99_ms") if f in v}
            for stage, v in (snap.get("quantiles") or {}).items()}
        out = {
            "nslots": nslots, "rows": rows,
            "requests": sr.stats.requests,
            "served": sr.stats.served,
            "dispatches": sr.stats.dispatches,
            "coalesce_ratio": round(sr.stats.coalesce_ratio(), 2),
            "daemon_qps": round(waves * per_wave / el, 1),
            "stage_quantiles": quant,
        }
        log(f"[search] daemon: {out['served']} reqs in "
            f"{out['dispatches']} dispatches "
            f"({out['coalesce_ratio']}x coalesced), "
            f"{out['daemon_qps']} q/s e2e")
        return out
    finally:
        tracer.enabled = prev_traced
        st.close()
        _Store.unlink(name)


# ---------------------------------------------------------------------------
# phase: restage — StagedLane O(dirty) refresh cost at scale
# ---------------------------------------------------------------------------

def phase_restage(ctx: SeriesCtx) -> dict:
    """StagedLane full-upload vs O(dirty) refresh on a real store
    (VERDICT r3 #6's scaling property; the 1M CPU record is the
    at-size evidence, this phase adds the CHIP's transfer numbers at
    a bounded default).  Env: RESTAGE_N (131072 on TPU / 1,000,000 on
    CPU), RESTAGE_DIM (768)."""
    import resource

    import numpy as np

    import jax

    from libsplinter_tpu import Store
    from libsplinter_tpu.ops.staged_lane import StagedLane

    on_tpu = ctx.backend == "tpu"
    n = int(os.environ.get("RESTAGE_N",
                           "131072" if on_tpu else "1000000"))
    dim = int(os.environ.get("RESTAGE_DIM", "768"))
    name = _bench_store_name("restage")
    Store.unlink(name)
    nslots = 1
    while nslots < n * 2:
        nslots *= 2
    log(f"[restage] store nslots={nslots} dim={dim} "
        f"({nslots * dim * 4 / 1e9:.2f} GB lane)")
    st = Store.create(name, nslots=nslots, max_val=64, vec_dim=dim)
    try:
        t0 = time.perf_counter()
        for i in range(n):
            st.set(f"v/{i}", "x")
        fill_keys_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        rng = np.random.default_rng(0)
        view = st.vectors
        chunk = 65536
        for lo in range(0, nslots, chunk):
            hi = min(lo + chunk, nslots)
            view[lo:hi] = rng.standard_normal(
                (hi - lo, dim), dtype=np.float32)
        log(f"[restage] populated {n} keys in {fill_keys_s:.1f}s, "
            f"lane in {time.perf_counter() - t0:.1f}s")

        lane = StagedLane(st)
        t0 = time.perf_counter()
        jax.block_until_ready(lane.refresh())
        full_upload_s = time.perf_counter() - t0
        log(f"[restage] full upload: {full_upload_s:.2f}s "
            f"({nslots * dim * 4 / 1e6 / full_upload_s:,.0f} MB/s)")

        # f16-wire A/B in the SAME window (link conditions drift
        # between claims): second full upload with half the bytes.
        # TPU only — on the CPU backend the duplicate lane is host
        # RSS and would corrupt this phase's max_rss memory-diet
        # evidence (on TPU it is HBM, freed right after).
        f16_upload_s = None
        if on_tpu:
            lane16 = StagedLane(st, wire="f16")
            t0 = time.perf_counter()
            jax.block_until_ready(lane16.refresh())
            f16_upload_s = time.perf_counter() - t0
            del lane16                    # free the duplicate HBM lane
            log(f"[restage] f16-wire upload: {f16_upload_s:.2f}s "
                f"({nslots * dim * 2 / 1e6 / f16_upload_s:,.0f} "
                f"MB/s wire)")

        def timed_refresh() -> float:
            t0 = time.perf_counter()
            jax.block_until_ready(lane.refresh())
            return (time.perf_counter() - t0) * 1e3

        timed_refresh()
        clean_ms = min(timed_refresh() for _ in range(5))

        results = {}
        chunk_detail = {}
        # tolerant parse: a trailing comma or stray token must not
        # abort the phase, and counts past n are silently dropped
        dirty_counts = tuple(
            int(x.strip()) for x in os.environ.get(
                "RESTAGE_DIRTY", "128,8192,40000").split(",")
            if x.strip().isdigit() and int(x.strip()) <= n)
        for k in dirty_counts:
            # round 1 compiles this pad bucket's scatter; round 2 is
            # the steady state a live session pays
            for _ in (0, 1):
                staged_before = lane.rows_staged
                chunks_before = lane.scatter_chunks
                padded_before = lane.rows_padded
                idx = rng.choice(n, size=k, replace=False)
                for i in idx:
                    st.set(f"v/{i}", "y")
                ms = timed_refresh()
                moved = lane.rows_staged - staged_before
                assert moved == k, (moved, k)
                results[k] = ms
                chunk_detail[k] = {
                    "chunks": lane.scatter_chunks - chunks_before,
                    "rows_padded": lane.rows_padded - padded_before,
                }
            log(f"[restage] refresh after {k} dirty: "
                f"{results[k]:.1f} ms (warm, "
                f"{chunk_detail[k]['chunks']} chunks, "
                f"{chunk_detail[k]['rows_padded']} rows padded)")
    finally:
        st.close()
        Store.unlink(name)

    head = max(results) if results else None
    return ctx.record({
        "metric": "staged_lane_restage",
        "value": round(results[head], 1) if head is not None else 0.0,
        "unit": (f"ms ({head} dirty of {n})" if head is not None
                 else f"ms (no dirty counts <= {n} requested)"),
        "vs_baseline": 0.0,
        "detail": {
            "backend": ctx.backend, "n_keys": n, "nslots": nslots,
            "dim": dim,
            "lane_gb": round(nslots * dim * 4 / 1e9, 2),
            "full_upload_s": round(full_upload_s, 2),
            "upload_mb_s": round(nslots * dim * 4 / 1e6
                                 / full_upload_s, 1),
            "f16_wire_upload_s": round(f16_upload_s, 2)
            if f16_upload_s else None,
            "f16_wire_speedup": round(full_upload_s / f16_upload_s, 2)
            if f16_upload_s else None,
            "refresh_clean_ms": round(clean_ms, 1),
            **{f"refresh_{k}_dirty_ms": round(v, 1)
               for k, v in sorted(results.items())},
            # chunked-refresh accounting (the piecewise-linearity
            # evidence: chunks x bucket size, padding waste <= 2x)
            "refresh_chunks": {str(k): v for k, v
                               in sorted(chunk_detail.items())},
            "max_rss_gb": round(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1e6, 2),
        }})


# ---------------------------------------------------------------------------
# phases: decode / decode_quant / decode_daemon
# ---------------------------------------------------------------------------

def _decode_model(quant: bool):
    from libsplinter_tpu.models import CompletionModel, DecoderConfig

    geometry = os.environ.get("DECODE_GEOMETRY", "flagship")
    if geometry == "tiny":
        cfg = DecoderConfig.tiny(quantized=quant)
    else:
        # the completion daemon's default geometry (completer.py):
        # llama-tiny-class 12x768 with the byte tokenizer's padded vocab
        cfg = DecoderConfig(vocab_size=512, quantized=quant)
    return CompletionModel(cfg), cfg, geometry


def _decode_core(ctx: SeriesCtx, quant: bool) -> dict:
    """Prefill latency + chunked / per-token / wide-chunk / batched /
    speculative decode tokens per second.  Env: DECODE_TOKENS (256),
    DECODE_CHUNK (8), DECODE_GEOMETRY, DECODE_SPEC, DECODE_GAMMA.

    Every arm past the core measurement is BUDGET-GUARDED: BENCH_r05's
    series timed out inside phase-decode_quant after a second 57 s
    warmup compile (the chunk-32 program, freshly compiled for the
    int8 graph), which erased the later phases from the evidence set.
    Optional arms (chunk-32, the paged sweep, speculative) now check
    the remaining window — minus a tail reserve for decode_daemon +
    store_ops — before compiling anything, and skipped arms are
    ledgered in `budget_skipped` so a missing number reads as a
    deliberate skip, never a silent gap."""
    import numpy as np

    n_tokens = int(os.environ.get("DECODE_TOKENS", "256"))
    chunk = int(os.environ.get("DECODE_CHUNK", "8"))
    model, cfg, geometry = _decode_model(quant)

    # tail reserve: decode_daemon's floor + store_ops + slack — an
    # optional arm here must never eat the phases that follow
    tail_reserve = (PHASE_MIN_S["decode_daemon"]
                    + PHASE_MIN_S["store_ops"] + 30)
    budget_skipped: list[str] = []

    def room(arm: str, need_s: float) -> bool:
        left = ctx.remaining() - tail_reserve
        if left < need_s:
            budget_skipped.append(arm)
            log(f"[decode] SKIP {arm}: {left:.0f}s left after the "
                f"{tail_reserve}s tail reserve < {need_s:.0f}s")
            return False
        return True

    log(f"decode{' int8' if quant else ''}: warmup compile ...")
    t0 = time.perf_counter()
    model.warmup(chunk=chunk)
    model._chunk_program(1)
    log(f"compile: {time.perf_counter() - t0:.1f}s")

    prompt = np.ones((48,), np.int32)
    times = []
    for _ in range(5):
        model.reset()
        t0 = time.perf_counter()
        model.prefill(prompt)
        times.append((time.perf_counter() - t0) * 1000)
    prefill_ms = float(np.median(times))

    def tokens_per_sec(ch: int, n: int, m=None) -> float:
        m = model if m is None else m
        m.reset()
        m.prefill(prompt)
        n = min(n, cfg.max_len - m.pos - ch - 1)
        t0 = time.perf_counter()
        got = 0
        tok = 1
        while got < n:
            toks = m.decode_chunk(tok, ch)
            tok = int(toks[-1])
            got += ch
        return got / (time.perf_counter() - t0)

    tokens_per_sec(chunk, chunk * 2)
    tps_chunked = tokens_per_sec(chunk, n_tokens)
    tps_serial = tokens_per_sec(1, max(32, n_tokens // 4))
    tps_c32 = None
    if room("chunk32", 120):
        # the r05 killer: warmup(chunk=32) compiles a SECOND chunk
        # program (57 s on-chip for the int8 graph) — only worth it
        # when the window still fits the phases behind this one
        model.warmup(chunk=32)
        tokens_per_sec(32, 64)
        tps_c32 = tokens_per_sec(32, max(n_tokens, 128))
    log(f"decode: {tps_chunked:,.1f} tok/s (chunk={chunk}), "
        + (f"{tps_c32:,.1f} (chunk=32), " if tps_c32 is not None
           else "chunk=32 budget-skipped, ")
        + f"{tps_serial:,.1f} per-token sync")

    def batch_tokens_per_sec(bsz: int, n: int) -> float:
        prompts = [np.ones((24 + r,), np.int32) for r in range(bsz)]
        model.reset()
        t0 = time.perf_counter()
        got = 0
        for _col in model.generate_batch(prompts, n, chunk=chunk):
            got += bsz
        model.reset()
        return got / (time.perf_counter() - t0)

    batch_tokens_per_sec(8, chunk * 2)
    tps_b8 = batch_tokens_per_sec(8, n_tokens)
    log(f"batched decode: {tps_b8:,.1f} aggregate tok/s (batch=8)")

    # paged-vs-dense: the block-paged pool decodes the same geometry
    # at growing batch widths inside a FIXED cache budget (8 full
    # windows of pages — the r05 dense batch=8 HBM envelope), so the
    # sweep shows batch width, not cache padding, consuming HBM.
    # Env: DECODE_PAGED=0 skips, DECODE_PAGED_SWEEP=8,32,64 overrides
    # (CPU default stops at 8 to keep the host run bounded).
    paged_tps: dict[str, float] = {}
    paged_skipped: list[int] = []
    paged_int8_tps: dict[str, float] = {}
    paged_int8_skipped: list[int] = []
    paged_int4_tps: dict[str, float] = {}
    paged_int4_skipped: list[int] = []
    paged_page = 128
    paged_pool = 8 * (-(-cfg.max_len // paged_page))
    # the SAME byte envelope holds itemsize-times the pages when the
    # pool stores int8 (+ per-page scales, <1% at page 128) — that
    # page headroom IS the quantized lane's batch-width claim
    native_bytes = np.dtype(cfg.dtype).itemsize
    paged_pool_int8 = paged_pool * native_bytes
    # int4 packs two codes per byte: 2x int8's pages, 4x bf16's —
    # batch 256 inside the envelope that holds bf16 batch 64 (PR 20)
    paged_pool_int4 = paged_pool * native_bytes * 2

    def paged_row_budget(bsz: int, pool: int) -> int:
        """Decode tokens each row can take inside the FIXED pool.
        Pages allocate whole: rows grow in near-lockstep (prompts
        24..31, same chunk cadence), so each of the bsz rows can
        own at most pool // bsz pages — budgeting raw tokens
        (pool*page // bsz) would overshoot at the page boundary
        and exhaust the pool mid-sweep.  Margin: max prompt 31 +
        up to chunk-1 of final-chunk overshoot."""
        row_cap = (pool // bsz) * paged_page
        return min(row_cap, cfg.max_len) - 32 - chunk

    def paged_tokens_per_sec(bsz: int, n: int, pool: int,
                             kv_dtype: str | None = None) -> float:
        cache = model.init_paged(bsz, page=paged_page,
                                 pool_pages=pool, kv_dtype=kv_dtype)
        toks = np.zeros((bsz,), np.int32)
        for r in range(bsz):
            lg = model.paged_prefill_row(
                cache, np.ones((24 + r % 8,), np.int32), r)
            toks[r] = int(np.argmax(lg))
        n = min(n, paged_row_budget(bsz, pool))
        t0 = time.perf_counter()
        got = 0
        while got < n * bsz:
            blk = model.paged_decode_chunk(cache, toks, chunk)
            toks = blk[:, -1].astype(np.int32)
            got += bsz * chunk
        dt = time.perf_counter() - t0
        cache.reset()
        return got / dt

    def paged_sweep(widths, pool, kv_dtype, tps_out, skipped_out,
                    tag):
        for bsz in widths:
            if not room(f"{tag}_b{bsz}", 60):
                continue  # every unaffordable width gets its own
                          # budget_skipped entry, never a silent gap
            if paged_row_budget(bsz, pool) < chunk:
                # the claim under test is batch width inside the
                # FIXED envelope; growing the pool to fit a width it
                # can't hold would measure a different (bigger)
                # cache budget — skip loudly
                skipped_out.append(bsz)
                log(f"{tag} decode: batch={bsz} SKIPPED — the fixed "
                    f"{pool}-page pool leaves its rows no decode "
                    f"budget at this width")
                continue
            paged_tokens_per_sec(bsz, chunk * 2, pool,
                                 kv_dtype)       # warm/compile
            tps_out[str(bsz)] = round(
                paged_tokens_per_sec(bsz, n_tokens, pool, kv_dtype),
                1)
            log(f"{tag} decode: {tps_out[str(bsz)]:,.1f} aggregate "
                f"tok/s (batch={bsz}, pool={pool} pages of "
                f"{paged_page}"
                + (f", kv={kv_dtype}" if kv_dtype else "") + ")")

    if os.environ.get("DECODE_PAGED", "1") == "1" \
            and getattr(model, "paged_supported", False) \
            and room("paged_sweep", 120):
        sweep_default = "8" if os.environ.get("BENCH_CPU") == "1" \
            else "8,32,64"
        sweep = [int(x) for x in os.environ.get(
            "DECODE_PAGED_SWEEP", sweep_default).split(",") if x]
        paged_sweep(sweep, paged_pool, None, paged_tps,
                    paged_skipped, "paged")

        # int8 arm: the SAME byte envelope, kv_dtype=int8 — the
        # widths the doubled page count newly affords (the bf16
        # envelope can't hold batch 64/128 at all: their rows would
        # have no decode budget).  Env: DECODE_PAGED_INT8_SWEEP.
        int8_default = "32" if os.environ.get("BENCH_CPU") == "1" \
            else "32,64,128"
        int8_sweep = [int(x) for x in os.environ.get(
            "DECODE_PAGED_INT8_SWEEP", int8_default).split(",") if x]
        if room("paged_int8", 120):
            paged_sweep(int8_sweep, paged_pool_int8, "int8",
                        paged_int8_tps, paged_int8_skipped,
                        "paged_int8")

        # int4 arm (PR 20): the SAME byte envelope once more, packed
        # two codes per byte — the widths only the quarter-byte pool
        # affords (bf16 batch 64's bytes hold int4 batch 256).  Env:
        # DECODE_PAGED_INT4_SWEEP.
        int4_default = "64" if os.environ.get("BENCH_CPU") == "1" \
            else "64,128,256"
        int4_sweep = [int(x) for x in os.environ.get(
            "DECODE_PAGED_INT4_SWEEP", int4_default).split(",") if x]
        if room("paged_int4", 120):
            paged_sweep(int4_sweep, paged_pool_int4, "int4",
                        paged_int4_tps, paged_int4_skipped,
                        "paged_int4")

    tps_spec = accept = None
    draft_layers = 0
    if os.environ.get("DECODE_SPEC", "1") == "1" \
            and room("speculative", 120):
        from libsplinter_tpu.models import (SpeculativeCompletionModel,
                                            self_draft_model)
        gamma = int(os.environ.get("DECODE_GAMMA", "4"))
        # SELF-DRAFT (PR 9): the first ~3/4 of the target's own
        # layers propose — r05's random tiny draft measured 6.0 tok/s
        # at acceptance 0.05 and was demoted dead weight; the
        # truncated-view draft has REAL acceptance even on random
        # weights (~0.5 at 3/4 depth), and shares every byte with
        # the target
        draft_layers = int(os.environ.get(
            "DECODE_DRAFT_LAYERS", str(max(1, (3 * cfg.layers) // 4))))
        draft = self_draft_model(model, draft_layers)
        spec = SpeculativeCompletionModel(model, draft, gamma=gamma)
        spec.warmup()
        t0 = time.perf_counter()
        n_spec = sum(1 for _ in spec.generate_tokens(prompt, n_tokens))
        tps_spec = n_spec / (time.perf_counter() - t0)
        accept = spec.acceptance_rate
        spec.reset()
        log(f"speculative: {tps_spec:,.1f} tok/s (self-draft "
            f"layers={draft_layers}/{cfg.layers}, gamma={gamma}, "
            f"acceptance={accept:.2f}; r05 before-row: 6.0 tok/s at "
            f"0.05 with the random tiny draft)")

    # weights_int8 arm (PR 20): the SAME geometry with every
    # attention/MLP kernel held per-output-channel int8
    # (ChannelQuantDense — matmul on int8-resident weights, dequant
    # on the f32 MXU output).  Weight reads at half bf16 bandwidth
    # make the decode path's claim >=1.3x dense where it is
    # weight-bandwidth bound; off-TPU this row is a MECHANICAL smoke
    # (the graph runs, the ratio is ledgered), the TPU row is
    # BENCH_r06 debt.  Skipped in the Q8_0 phase: the residencies
    # are mutually exclusive.  Env: DECODE_WEIGHTS_INT8=0 skips.
    wq_tps = None
    if not quant and os.environ.get("DECODE_WEIGHTS_INT8", "1") == "1" \
            and room("weights_int8", 180):
        import dataclasses as _dc

        from libsplinter_tpu.models import CompletionModel
        log("weights_int8: warmup compile ...")
        wq_model = CompletionModel(_dc.replace(cfg, weights_int8=True))
        wq_model.warmup(chunk=chunk)
        tokens_per_sec(chunk, chunk * 2, wq_model)
        wq_tps = tokens_per_sec(chunk, n_tokens, wq_model)
        log(f"weights_int8 decode: {wq_tps:,.1f} tok/s (chunk={chunk},"
            f" {wq_tps / tps_chunked:.2f}x dense same-run)")

    return ctx.record({
        "metric": "decode_tokens_per_sec",
        "value": round(tps_chunked, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps_chunked / tps_serial, 3)
        if tps_serial > 0 else 0.0,
        "detail": {
            "backend": ctx.backend, "geometry": geometry,
            "quantized": quant,
            "layers": cfg.layers, "hidden": cfg.hidden,
            "chunk": chunk, "n_tokens": n_tokens,
            "prefill_ms_bucket64": round(prefill_ms, 2),
            "tokens_per_sec_serial_sync": round(tps_serial, 1),
            "tokens_per_sec_chunk32": (round(tps_c32, 1)
                                       if tps_c32 is not None else None),
            # arms the window could not afford (deliberate skips, not
            # silent gaps — the r05 timeout fix)
            "budget_skipped": budget_skipped,
            "tokens_per_sec_batch8_aggregate": round(tps_b8, 1),
            # the paged/dense ledger label: dense is the batch8 row
            # above, paged entries are keyed by sweep batch width
            "kv_cache_dense": {"batch": 8,
                               "tokens_per_sec": round(tps_b8, 1)},
            "kv_cache_paged": {
                "page": paged_page, "pool_pages": paged_pool,
                "tokens_per_sec_by_batch": paged_tps,
                # widths the FIXED envelope cannot hold are skipped,
                # never measured against a silently grown pool
                "skipped_batches": paged_skipped,
                "vs_dense_batch8": (
                    round(max(paged_tps.values()) / tps_b8, 3)
                    if paged_tps and tps_b8 > 0 else None),
            },
            # int8 arm: SAME byte envelope (pool_pages x itemsize
            # pages of int8 + scales), the widths quantization newly
            # affords.  r05 before-row: 612.3 aggregate tok/s at
            # batch 8, dense bf16 cache, single chip.
            "kv_cache_paged_int8": {
                "page": paged_page, "pool_pages": paged_pool_int8,
                "envelope_bytes_vs_native": "equal",
                "tokens_per_sec_by_batch": paged_int8_tps,
                "skipped_batches": paged_int8_skipped,
                "r05_dense_batch8_tokens_per_sec": 612.3,
                "vs_dense_batch8": (
                    round(max(paged_int8_tps.values()) / tps_b8, 3)
                    if paged_int8_tps and tps_b8 > 0 else None),
                # the >=2x-batch-width-inside-the-envelope claim:
                # widest int8-MEASURED width over widest native one
                "max_batch_vs_native": (
                    round(max(map(int, paged_int8_tps))
                          / max(map(int, paged_tps)), 2)
                    if paged_int8_tps and paged_tps else None),
            },
            # int4 arm (PR 20): SAME byte envelope at two codes per
            # byte — 2x int8's pages, 4x native bf16's.  The headline
            # row is batch 256 inside bf16 batch 64's bytes.
            "kv_cache_paged_int4": {
                "page": paged_page, "pool_pages": paged_pool_int4,
                "envelope_bytes_vs_native": "equal",
                "tokens_per_sec_by_batch": paged_int4_tps,
                "skipped_batches": paged_int4_skipped,
                "r05_dense_batch8_tokens_per_sec": 612.3,
                "vs_dense_batch8": (
                    round(max(paged_int4_tps.values()) / tps_b8, 3)
                    if paged_int4_tps and tps_b8 > 0 else None),
                # the 4x-batch-width-inside-the-envelope claim
                "max_batch_vs_native": (
                    round(max(map(int, paged_int4_tps))
                          / max(map(int, paged_tps)), 2)
                    if paged_int4_tps and paged_tps else None),
            },
            # weights_int8 arm (PR 20): per-output-channel int8
            # weight residency, dequant on the MXU f32 output.  The
            # acceptance bar (>=1.3x dense) is a WEIGHT-BANDWIDTH
            # claim — off-TPU the ratio is ledgered as a mechanical
            # smoke and the TPU row is explicit BENCH_r06 debt.
            "weights_int8": ({
                "tokens_per_sec": round(wq_tps, 1),
                "vs_dense_same_run": (round(wq_tps / tps_chunked, 3)
                                      if tps_chunked > 0 else None),
                "target": ">=1.3x dense bf16 (TPU, weight-bandwidth "
                          "bound)",
                "tpu_row": "BENCH_r06 debt — this run is a CPU/"
                           "mechanical smoke unless backend is tpu",
            } if wq_tps is not None else None),
            "tokens_per_sec_speculative": (round(tps_spec, 1)
                                           if tps_spec else None),
            "speculative_acceptance": (round(accept, 3)
                                       if accept is not None else None),
            "speculative_draft": (
                {"kind": "self", "layers": draft_layers,
                 "of_layers": cfg.layers,
                 # r05 before-row: the random tiny draft this PR
                 # retires — 6.0 tok/s at acceptance 0.05, below the
                 # 0.2 demotion floor
                 "r05_random_tiny_draft": {"tokens_per_sec": 6.0,
                                           "acceptance": 0.05}}
                if draft_layers else None),
        }})


def phase_decode(ctx: SeriesCtx) -> dict:
    return _decode_core(ctx, quant=False)


def phase_decode_quant(ctx: SeriesCtx) -> dict:
    return _decode_core(ctx, quant=True)


def phase_multichip(ctx: SeriesCtx) -> dict:
    """Pod-sharded paged decode (PR 8; ROADMAP item 1): aggregate
    paged tok/s through ShardedCompletionModel over a tp mesh spanning
    every visible device, batch {32, 64}, ledgered against the
    single-chip r05 row (612.3 aggregate tok/s, batch=8).  On a TPU
    pod the acceptance bar is >= 6x the single-chip aggregate on 8
    chips; on any other backend the row is a CPU-MESH SMOKE — labeled
    loudly as such in the record — proving the sharded lane runs
    mechanically, never a performance claim.

    Env: MULTICHIP_BATCHES (32,64), MULTICHIP_TOKENS (per-row decode
    budget; 16 CPU / 256 TPU), DECODE_CHUNK (8), DECODE_GEOMETRY."""
    import numpy as np

    R05_SINGLE_CHIP = 612.3   # BENCH_r05: dense batch=8 aggregate tok/s
    n_dev = ctx.n_devices
    on_cpu = os.environ.get("BENCH_CPU") == "1" or ctx.backend == "cpu"
    chunk = int(os.environ.get("DECODE_CHUNK", "8"))
    base_rec = {"metric": "multichip_paged_tokens_per_sec",
                "unit": "tokens/s (aggregate)"}
    if n_dev < 2:
        # a single-chip claim cannot exercise the arm — ledger the
        # skip explicitly so the series stays complete and honest
        log("[multichip] single device visible: no tp mesh to shard "
            "over; ledgering a skip row")
        return ctx.record({
            **base_rec, "value": 0.0, "vs_baseline": 0.0,
            "detail": {"backend": ctx.backend, "n_devices": n_dev,
                       "skipped": "single device — the paged "
                                  "multi-chip arm needs a pod claim"}})

    from libsplinter_tpu.models import DecoderConfig
    from libsplinter_tpu.parallel import ShardedCompletionModel
    from libsplinter_tpu.parallel.mesh import make_mesh

    geometry = os.environ.get("DECODE_GEOMETRY",
                              "tiny" if on_cpu else "flagship")
    if geometry == "tiny":
        cfg = DecoderConfig.tiny()
    else:
        cfg = DecoderConfig(vocab_size=512)
    # widest tp that divides the heads, the kv heads, and the device
    # count (the rest becomes dp; kv-head pool sharding needs tp | KH)
    tp = max(t for t in range(1, n_dev + 1)
             if cfg.heads % t == 0 and cfg.kv_heads % t == 0
             and n_dev % t == 0)
    mesh = make_mesh(tp=tp)
    model = ShardedCompletionModel(cfg, mesh)
    assert model.paged_supported, "sharded paged lane regressed"
    page = 16 if on_cpu else 128
    ppr = -(-cfg.max_len // page)
    batches = [int(x) for x in os.environ.get(
        "MULTICHIP_BATCHES", "32,64").split(",") if x]
    n_tokens = int(os.environ.get("MULTICHIP_TOKENS",
                                  "16" if on_cpu else "256"))

    def pool_for(bsz: int) -> int:
        if not on_cpu:
            # the r05 HBM envelope: 8 full windows of pages, same
            # fixed-budget discipline as _decode_core's paged sweep
            return 8 * ppr
        # CPU smoke: 2 pages per row so every width decodes a few
        # chunks (the envelope claim is the TPU arm's job)
        return max(8 * ppr, bsz * 2)

    def paged_tps(bsz: int, n: int) -> float:
        cache = model.init_paged(bsz, page=page,
                                 pool_pages=pool_for(bsz))
        row_cap = (pool_for(bsz) // bsz) * page
        n = max(chunk, min(n, min(row_cap, cfg.max_len) - 8 - chunk))
        toks = np.zeros((bsz,), np.int32)
        for r in range(bsz):
            lg = model.paged_prefill_row(
                cache, np.ones((4 + r % 4,), np.int32), r)
            toks[r] = int(np.argmax(lg))
        t0 = time.perf_counter()
        got = 0
        while got < n * bsz:
            blk = model.paged_decode_chunk(cache, toks, chunk)
            toks = blk[:, -1].astype(np.int32)
            got += bsz * chunk
        dt = time.perf_counter() - t0
        cache.reset()
        return got / dt

    tps_by_batch: dict[str, float] = {}
    budget_skipped: list[str] = []
    for bsz in batches:
        if ctx.remaining() < 120:
            # ledgered below, never a silent gap (same discipline as
            # _decode_core's budget_skipped)
            budget_skipped.append(f"batch{bsz}")
            log(f"[multichip] batch={bsz} budget-skipped "
                f"({ctx.remaining():.0f}s left)")
            continue
        paged_tps(bsz, chunk * 2)                 # warm/compile
        tps_by_batch[str(bsz)] = round(paged_tps(bsz, n_tokens), 1)
        log(f"multichip paged: {tps_by_batch[str(bsz)]:,.1f} aggregate "
            f"tok/s (batch={bsz}, tp={tp} over {n_dev} devices)")

    best = max(tps_by_batch.values()) if tps_by_batch else 0.0
    return ctx.record({
        **base_rec,
        "value": best,
        # vs_baseline: the >=6x-single-chip acceptance ratio on TPU;
        # meaningless (and labeled so) on a CPU mesh
        "vs_baseline": round(best / R05_SINGLE_CHIP, 3),
        "detail": {
            "backend": ctx.backend, "geometry": geometry,
            "n_devices": n_dev, "tp": tp, "dp": n_dev // tp,
            "page": page, "chunk": chunk,
            "pool_pages_by_batch": {str(b): pool_for(b)
                                    for b in batches},
            "tokens_per_sec_by_batch": tps_by_batch,
            "budget_skipped": budget_skipped,
            "r05_single_chip_dense_batch8": R05_SINGLE_CHIP,
            "vs_r05_single_chip": round(best / R05_SINGLE_CHIP, 3),
            "target": ">=6x single-chip aggregate tok/s on 8 chips",
            # LOUD smoke label: a CPU virtual mesh measures host
            # arithmetic, not ICI-sharded HBM bandwidth — this row is
            # mechanical evidence only until a pod claim lands
            "cpu_mesh_smoke": ctx.backend != "tpu",
        }})


def phase_loadgen(ctx: SeriesCtx) -> dict:
    """Open-loop multi-tenant serving under QoS (`spt loadgen`,
    cli/loadgen.py): a full in-process stack — real tiny encoder +
    decoder, the fused-top-k searcher — serves mixed 3-tenant
    embed/search/complete traffic with per-tenant admission
    (admit_cap + queue high water on the search lane) while the
    generator's clock, not the server, decides arrivals.  Ledgers
    goodput vs shed and per-tenant p99 sourced from the PR 2 log
    histograms — the first bench row that measures the system AS a
    multi-tenant server instead of a closed benchmark loop.  Off-TPU
    rows carry a LOUD cpu_smoke label.  Env: LOADGEN_S (duration,
    default 8), LOADGEN_RATE (aggregate req/s, default 60)."""
    import threading

    import numpy as np  # noqa: F401  (loadgen pulls it anyway)

    from libsplinter_tpu import Store
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.engine.embedder import Embedder
    from libsplinter_tpu.engine.searcher import Searcher
    from libsplinter_tpu.models import default_tokenizer
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)
    from libsplinter_tpu.models.encoder import (EmbeddingModel,
                                                EncoderConfig)

    duration = float(os.environ.get("LOADGEN_S", "8"))
    rate = float(os.environ.get("LOADGEN_RATE", "60"))
    name = _bench_store_name("loadgen")
    Store.unlink(name)
    st = Store.create(name, nslots=1024, max_val=2048, vec_dim=32)
    daemons: list = []
    ths: list = []
    try:
        ecfg = EncoderConfig.tiny(out_dim=st.vec_dim)
        emb = Embedder(st, model=EmbeddingModel(ecfg),
                       tokenizer=default_tokenizer(ecfg.vocab_size),
                       max_ctx=ecfg.max_len, batch_cap=32)
        dcfg = DecoderConfig.tiny()
        comp = Completer(
            st, model=CompletionModel(dcfg, temp=0.0, seed=1),
            max_new_tokens=8, flush_tokens=4, template="none",
            queue_high_water=256)
        sr = Searcher(st, admit_cap=64, queue_high_water=256)
        for d in (emb, sr, comp):
            d.attach()
            daemons.append(d)
        run_s = duration + 60
        ths = [threading.Thread(
            target=d.run, kwargs=dict(idle_timeout_ms=10,
                                      stop_after=run_s), daemon=True)
            for d in daemons]
        for t in ths:
            t.start()

        # 3 tenants at 3:2:1 offered rates, one shared deadline —
        # aggregate LOADGEN_RATE req/s open loop
        unit = rate / 6.0
        tenants = [TenantSpec(1, 3 * unit, deadline_ms=10_000),
                   TenantSpec(2, 2 * unit, deadline_ms=10_000),
                   TenantSpec(3, 1 * unit, deadline_ms=10_000)]
        gen = LoadGenerator(st, tenants, duration_s=duration,
                            corpus=32, seed=7, drain_s=30.0)
        rep = gen.run()

        per_tenant_p99 = {
            t: {lane: row.get("p99_ms") for lane, row in lanes.items()
                if "p99_ms" in row}
            for t, lanes in rep["per_tenant"].items()}
        rec = {
            "metric": "loadgen_goodput",
            "backend": ctx.backend,
            "duration_s": rep["duration_s"],
            "offered_rps": rate,
            "issued": rep["issued"],
            "goodput_rps": rep["goodput_rps"],
            "goodput_ratio": rep["goodput_ratio"],
            "shed": rep["shed"],
            "expired": rep["expired"],
            "lost": rep["lost"],
            "unserved": rep["unserved"],
            "per_tenant_p99_ms": per_tenant_p99,
            "tenant_rates": {"1": 3 * unit, "2": 2 * unit,
                             "3": unit},
        }
        if ctx.backend != "tpu":
            # tiny models on host CPU: a serving-layer smoke, not a
            # throughput claim — label it so no before/after compare
            # ever mistakes it for chip evidence
            rec["label"] = "cpu_smoke"
        log(f"loadgen: {rep['issued']} issued, goodput "
            f"{rep['goodput_rps']:.1f} rps "
            f"({rep['goodput_ratio']:.1%}), shed={rep['shed']} "
            f"lost={rep['lost']}")
        return ctx.record(rec)
    finally:
        for d in daemons:
            d.stop()
        for t in ths:
            t.join(timeout=15)
        st.close()
        Store.unlink(name)


def phase_prefix(ctx: SeriesCtx) -> dict:
    """Cross-request prefix sharing (ISSUE 14, ROADMAP item 2):
    hot-vs-cold admission-to-first-token through a real continuous
    completer (the radix prefix cache maps shared pages, cold pays
    the dense bucket prefill), plus the rows-per-page-envelope
    multiplier vs PR 5's private paging at a fixed pool budget.
    Off-TPU rows carry the LOUD cpu_smoke label — the >= 10x
    admission claim is a TPU ledger row; CPU gates at >= 5x via
    `make prefix-check`.  Env: PREFIX_TRIALS (default 5)."""
    import threading

    import numpy as np

    from libsplinter_tpu import Store
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)

    trials = int(os.environ.get("PREFIX_TRIALS", "5"))
    page = 32
    prompt = ("retrieval context: " * 70)[: 33 * page - 1]

    def first_token_ms(st, key: str) -> float:
        st.set(key, prompt)
        rendered = len(prompt.encode())
        t0 = time.perf_counter()
        st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
        st.bump(key)
        deadline = t0 + 120.0
        while time.perf_counter() < deadline:
            try:
                if st.value_len(key) > rendered:
                    return (time.perf_counter() - t0) * 1e3
            except KeyError:
                pass
            time.sleep(0.0002)
        raise RuntimeError(f"{key} never streamed")

    lat: dict[str, list[float]] = {}
    pfx_stats = None
    for tag, enable in (("cold", False), ("hot", True)):
        name = _bench_store_name(f"prefix-{tag}")
        Store.unlink(name)
        st = Store.create(name, nslots=256, max_val=8192, vec_dim=8)
        try:
            cfg = DecoderConfig.tiny(max_len=2048)
            model = CompletionModel(cfg, buckets=(1088,), temp=0.0,
                                    seed=1, suffix_buckets=(16,))
            comp = Completer(st, model=model, max_new_tokens=6,
                             flush_tokens=1, template="none",
                             batch_cap=4, page_size=page,
                             pool_pages=110, inflight_depth=1,
                             prefix_cache=enable)
            comp.attach()
            comp.warmup_paged()
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=5, stop_after=300.0),
                daemon=True)
            th.start()
            time.sleep(0.1)
            first_token_ms(st, f"{tag}/warm")   # seed tree / warm lane
            lat[tag] = []
            for i in range(trials):
                key = f"{tag}/{i}"
                lat[tag].append(first_token_ms(st, key))
                done_by = time.monotonic() + 60.0
                while not st.labels(key) & P.LBL_READY:
                    if time.monotonic() > done_by:
                        raise RuntimeError(f"{key} never READY")
                    time.sleep(0.001)
            if enable:
                pfx_stats = comp.prefix_cache.stats
            comp.stop()
            th.join(timeout=30)
        finally:
            st.close()
            Store.unlink(name)

    # rows-per-envelope at cache level: the same reservation math
    # run_continuous uses (worst case minus hit pages plus COW page)
    from libsplinter_tpu.engine.prefix_cache import PrefixCache
    cfg = DecoderConfig.tiny()
    m2 = CompletionModel(cfg, buckets=(32,), temp=0.0, seed=1)
    budget, prompt_pages, pg = 64, 15, 8
    ids = (np.arange(1, 1 + prompt_pages * pg, dtype=np.int32)
           % 200) + 1
    worst = (prompt_pages + 1) * pg
    private = m2.init_paged(32, page=pg, pool_pages=budget)
    rows_private = 0
    for r in range(32):
        if not private.ensure(r, worst):
            break
        rows_private += 1
    shared = m2.init_paged(32, page=pg, pool_pages=budget)
    pc = PrefixCache(pg)
    pc.attach(shared)
    shared.prefix_cache = pc
    m2.paged_prefill_row(shared, ids, 0)
    shared.ensure(0, worst)
    pc.insert(ids, shared, 0)
    rows_shared = 1
    for r in range(1, 32):
        bids, match = pc.lookup(ids)
        if (shared.pages_needed(worst) - len(bids) + 1
                > shared.available_pages):
            break
        shared.map_shared(r, bids)
        shared.lengths[r] = match - 1
        shared.ensure(r, worst)
        m2._cow_fixups(shared)          # the replay page is real cost
        rows_shared += 1

    cold_p50 = float(np.median(lat["cold"]))
    hot_p50 = float(np.median(lat["hot"]))
    rec = {
        "metric": "prefix_cache",
        "backend": ctx.backend,
        "prompt_tokens": len(prompt) + 1,
        "page": page,
        "cold_first_token_p50_ms": round(cold_p50, 3),
        "hot_first_token_p50_ms": round(hot_p50, 3),
        "admission_speedup": round(cold_p50 / hot_p50, 2)
        if hot_p50 > 0 else None,
        "rows_private": rows_private,
        "rows_shared": rows_shared,
        "rows_multiplier": round(rows_shared / rows_private, 2)
        if rows_private else None,
        "pool_budget_pages": budget,
        "detail": {
            "cold_ms": [round(x, 2) for x in lat["cold"]],
            "hot_ms": [round(x, 2) for x in lat["hot"]],
            "hits": pfx_stats.hits if pfx_stats else 0,
            "cow_copies": pfx_stats.cow_copies if pfx_stats else 0,
            "bytes_saved": pfx_stats.bytes_saved if pfx_stats else 0,
        },
    }
    if ctx.backend != "tpu":
        # tiny models on host CPU: a mechanism smoke, not the >= 10x
        # TPU claim — label it so no before/after compare ever
        # mistakes it for chip evidence
        rec["label"] = "cpu_smoke"
    log(f"prefix: first-token p50 cold {cold_p50:.1f} ms -> hot "
        f"{hot_p50:.1f} ms ({rec['admission_speedup']}x); rows "
        f"{rows_private} -> {rows_shared} in {budget} pages")
    return ctx.record(rec)


def phase_disagg(ctx: SeriesCtx) -> dict:
    """Disaggregated prefill/decode lanes (ISSUE 18): the same
    prefill-burst workload (steady decode floor + a prompt-heavy rate
    step) is served twice — once by a unified continuous completer,
    once by the split PrefillLane + DecodeLane pair — and the decode
    floor's inter-chunk p99 during the burst phase is ledgered for
    both (the split/unified ratio IS the disaggregation win: prefill
    bubbles stop landing inside decode token gaps).  A post-drain
    probe on the quiet split stack times DECODE_READY -> adoption
    (the page-handoff hop itself), and the row carries both lanes'
    heartbeat counters (handoffs, wire MB, refills).  The store uses
    max_val=16384 so the real wire-page export/import path is what
    gets measured, not the re-prefill fallback.  Off-TPU rows carry
    the LOUD cpu_smoke label.  Env: DISAGG_RATE (per-class req/s,
    default 3), DISAGG_PROFILE (default 1x:3,8x:5,1x:3)."""
    import threading

    import jax.numpy as jnp
    import numpy as np

    from libsplinter_tpu import Store
    from libsplinter_tpu.cli.loadgen import (LoadGenerator, TenantSpec,
                                             parse_rate_profile)
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.engine.disagg import DecodeLane, PrefillLane
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)

    rate = float(os.environ.get("DISAGG_RATE", "3"))
    prof = parse_rate_profile(
        os.environ.get("DISAGG_PROFILE", "1x:3,8x:5,1x:3"))
    burst_phase = max(range(len(prof)), key=lambda p: prof[p][0])

    # one model for both modes: identical buckets, zero recompiles
    # between the unified and split runs
    dcfg = DecoderConfig.tiny(dtype=jnp.float32)
    model = CompletionModel(dcfg, buckets=(32,), temp=0.0, seed=1,
                            suffix_buckets=(8,))
    KW = dict(max_new_tokens=10, flush_tokens=2, template="none",
              batch_cap=4, page_size=8)
    duration = sum(d for _, d in prof)

    def probe_handoff(st, key: str) -> float | None:
        """Time the DECODE_READY -> adopted (SERVICING re-raised) hop
        for one quiet request; None when the window was too short to
        observe (adoption faster than the poll resolution)."""
        st.set(key, f"probe {key}")
        st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
        st.bump(key)
        t_ho = None
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            lb = st.labels(key)
            now = time.perf_counter()
            if lb & P.LBL_DECODE_READY:
                if lb & P.LBL_SERVICING:
                    # adopted: only a valid sample if we saw the bare
                    # DECODE_READY window first
                    return (now - t_ho) * 1e3 if t_ho is not None \
                        else None
                if t_ho is None:
                    t_ho = now
            if lb & P.LBL_READY:
                return None
            time.sleep(0.0002)
        raise RuntimeError(f"{key} never handed off")

    def run_mode(tag: str, split: bool) -> tuple[dict, dict]:
        name = _bench_store_name(f"disagg-{tag}")
        Store.unlink(name)
        st = Store.create(name, nslots=1024, max_val=16384, vec_dim=8)
        daemons: list = []
        ths: list = []
        stats: dict = {}
        try:
            if split:
                daemons = [PrefillLane(st, model=model, **KW),
                           DecodeLane(st, model=model, **KW)]
            else:
                daemons = [Completer(st, model=model, **KW)]
            for d in daemons:
                d.attach()
            ths = [threading.Thread(
                target=d.run_continuous,
                kwargs=dict(idle_timeout_ms=10,
                            stop_after=duration + 90), daemon=True)
                for d in daemons]
            for t in ths:
                t.start()
            gen = LoadGenerator(st, [TenantSpec(1, rate,
                                                deadline_ms=30_000)],
                                duration_s=duration,
                                scenario="prefill-burst",
                                rate_profile=prof, corpus=32, seed=7,
                                drain_s=45.0)
            rep = gen.run()
            if split:
                # post-drain, quiet lanes: time the handoff hop itself
                samples = [probe_handoff(st, f"__probe/{i}")
                           for i in range(5)]
                samples = [s for s in samples if s is not None]
                stats["handoff_ms"] = samples
                stats["prefill"] = dict(daemons[0]._lane_stats)
                stats["decode"] = dict(daemons[1]._lane_stats)
            return rep, stats
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=30)
            st.close()
            Store.unlink(name)

    def floor_p99(rep: dict, phase: int) -> float | None:
        for row in rep.get("prefill_burst", []):
            if row.get("phase") == phase:
                return row.get("decode-floor", {}).get(
                    "interchunk_p99_ms")
        return None

    rep_u, _ = run_mode("unified", split=False)
    rep_s, lane_stats = run_mode("split", split=True)

    u99 = floor_p99(rep_u, burst_phase)
    s99 = floor_p99(rep_s, burst_phase)
    idle99 = floor_p99(rep_s, 0)
    ho = sorted(lane_stats.get("handoff_ms", []))
    rec = {
        "metric": "disagg_decode_p99",
        "backend": ctx.backend,
        "offered_rps_per_class": rate,
        "profile": [[m, d] for m, d in prof],
        "burst_phase": burst_phase,
        "unified_burst_interchunk_p99_ms": u99,
        "split_burst_interchunk_p99_ms": s99,
        "split_vs_unified": round(s99 / u99, 3)
        if u99 and s99 else None,
        "split_idle_interchunk_p99_ms": idle99,
        "handoff_p50_ms": round(float(np.median(ho)), 3)
        if ho else None,
        "handoff_samples": len(ho),
        "lane_stats": {k: lane_stats.get(k) for k in
                       ("prefill", "decode")},
        "detail": {"unified_burst": rep_u.get("prefill_burst"),
                   "split_burst": rep_s.get("prefill_burst")},
    }
    if ctx.backend != "tpu":
        # tiny models on host CPU: a mechanism smoke, not the decode
        # isolation claim — label it so no before/after compare ever
        # mistakes it for chip evidence
        rec["label"] = "cpu_smoke"
    log(f"disagg: burst-phase floor inter-chunk p99 unified "
        f"{u99} ms -> split {s99} ms (ratio "
        f"{rec['split_vs_unified']}); handoff p50 "
        f"{rec['handoff_p50_ms']} ms over {len(ho)} probes; "
        f"prefill {lane_stats.get('prefill')}")
    return ctx.record(rec)


def phase_tier(ctx: SeriesCtx) -> dict:
    """Tiered KV spill/readmit (ISSUE 19): price an evicted hot
    prompt's way back into HBM — tier readmission (one device_put +
    block-table write per page) vs the full re-prefill a tierless
    cache pays for the same prompt — plus the warm-restart snapshot
    round-trip (save + cold-attach restore) and the warm-footprint
    multiplier the DRAM tier buys per HBM pool envelope.  Off-TPU
    rows carry the LOUD cpu_smoke label — the readmit-vs-reprefill
    ratio is a TPU ledger claim; CPU correctness gates live in
    `make warm-check`.  Env: TIER_TRIALS (default 5), TIER_PAGES
    (prompt length in pages, default 12)."""
    import jax
    import numpy as np

    from libsplinter_tpu.engine.kv_tier import (HostTier, TierPersist,
                                                tier_geometry)
    from libsplinter_tpu.engine.prefix_cache import PrefixCache
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)

    trials = int(os.environ.get("TIER_TRIALS", "5"))
    n_pages = int(os.environ.get("TIER_PAGES", "12"))
    pg = 8
    pool = 4 * n_pages
    cfg = DecoderConfig.tiny(max_len=max(256, 2 * n_pages * pg))
    model = CompletionModel(cfg, buckets=(n_pages * pg + 32,),
                            temp=0.0, seed=1)
    ids = (np.arange(1, 1 + n_pages * pg, dtype=np.int32) % 200) + 1

    cache = model.init_paged(4, page=pg, pool_pages=pool)
    pc = PrefixCache(pg)
    pc.attach(cache)
    cache.prefix_cache = pc
    tier = HostTier(2 * n_pages)
    pc.bind_tier(
        tier,
        export_page=lambda bid: model.export_page_bytes(cache, bid),
        import_page=lambda bid, buf, sbuf: model.import_page_bytes(
            cache, bid, buf, sbuf))
    model.paged_prefill_row(cache, ids, 0)
    assert pc.insert(ids, cache, 0) == n_pages   # write-through spill
    cache.free_row(0)

    def demote_all():
        assert pc.reclaim(n_pages) == n_pages
        assert pc.demoted_pages() == n_pages

    def readmit_once(row: int) -> float:
        t0 = time.perf_counter()
        _, _, nodes = pc.lookup_tiered(ids)
        got = pc.readmit(nodes, cache)
        for b in got:
            cache._decref(b)
        cache.map_shared(row, got)
        cache.lengths[row] = len(ids) - 1
        jax.block_until_ready(cache.k_pools)
        dt = (time.perf_counter() - t0) * 1e3
        assert len(got) == n_pages
        cache.free_row(row)
        return dt

    demote_all()
    readmit_once(1)                     # compile the import program
    readmit_ms = []
    for _ in range(trials):
        demote_all()
        readmit_ms.append(readmit_once(1))

    # baseline: the same prompt re-prefilled into a tierless pool
    cache_b = model.init_paged(4, page=pg, pool_pages=pool)
    jax.block_until_ready(
        model.paged_prefill_row(cache_b, ids, 0))    # compile
    cache_b.free_row(0)
    reprefill_ms = []
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.block_until_ready(model.paged_prefill_row(cache_b, ids, 0))
        reprefill_ms.append((time.perf_counter() - t0) * 1e3)
        cache_b.free_row(0)

    # warm-restart round-trip: checkpoint the demoted chain, restore
    # it into a cold cache (what a respawned lane pays at attach)
    demote_all()
    geom = tier_geometry(model, cache)
    pname = _bench_store_name("tier") + "-kvtier"
    TierPersist.unlink(pname)
    persist = TierPersist(pname, capacity_pages=2 * n_pages,
                          max_len=cfg.max_len,
                          page_bytes=geom["page_bytes"])
    try:
        t0 = time.perf_counter()
        assert persist.save(pc, tier, geom)
        save_ms = (time.perf_counter() - t0) * 1e3
        cache_c = model.init_paged(4, page=pg, pool_pages=pool)
        pc_c = PrefixCache(pg)
        pc_c.attach(cache_c)
        tier_c = HostTier(2 * n_pages)
        pc_c.bind_tier(tier_c)
        t0 = time.perf_counter()
        restored, reason = persist.load(pc_c, tier_c, geom)
        restore_ms = (time.perf_counter() - t0) * 1e3
        assert restored == n_pages and reason == "", (restored, reason)
    finally:
        persist.close()
        TierPersist.unlink(pname)

    # rows-per-HBM-envelope, tier on vs off: stream distinct 3-page
    # prompt chains through a SMALL pool under zero-ref eviction
    # pressure, then count how many stay servable (full radix match,
    # HBM or DRAM) — the warm working set one HBM envelope retains
    chain_pages, n_chains = 3, 20
    envelope = 4 * chain_pages            # HBM holds 4 chains
    chains = [((np.arange(chain_pages * pg, dtype=np.int32)
                + 37 * i) % 199) + 1 for i in range(n_chains)]
    # short-context model so the tiny envelope still holds one full
    # window (the pool floor is max_len/page pages)
    model_e = CompletionModel(DecoderConfig.tiny(max_len=8 * pg),
                              buckets=(chain_pages * pg + pg,),
                              temp=0.0, seed=1)
    # write-through shadowing makes the DRAM tier a SUPERSET of the
    # HBM pool, so the warm set is bounded by the tier's capacity:
    # 2x the envelope of host RAM doubles the warm working set
    warm_chains = {}
    for tag, cap in (("off", 0), ("on", 2 * envelope)):
        c = model_e.init_paged(4, page=pg, pool_pages=envelope)
        p = PrefixCache(pg)
        p.attach(c)
        c.prefix_cache = p
        if cap:
            t2 = HostTier(cap)
            p.bind_tier(
                t2,
                export_page=lambda bid, c=c:
                model_e.export_page_bytes(c, bid),
                import_page=lambda bid, buf, sbuf, c=c:
                model_e.import_page_bytes(c, bid, buf, sbuf))
        for ch in chains:
            if c.available_pages < chain_pages:
                p.reclaim(chain_pages)
            model_e.paged_prefill_row(c, ch, 0)
            p.insert(ch, c, 0)
            c.free_row(0)
        warm_chains[tag] = sum(
            1 for ch in chains
            if (lambda r: (len(r[0]) * pg + len(r[2]) * pg)
                == chain_pages * pg)(p.lookup_tiered(ch)))

    re_p50 = float(np.median(readmit_ms))
    pf_p50 = float(np.median(reprefill_ms))
    rec = {
        "metric": "kv_tier",
        "backend": ctx.backend,
        "prompt_tokens": int(n_pages * pg),
        "page": pg,
        "page_bytes": geom["page_bytes"],
        "readmit_p50_ms": round(re_p50, 3),
        "reprefill_p50_ms": round(pf_p50, 3),
        "readmit_speedup": round(pf_p50 / re_p50, 2)
        if re_p50 > 0 else None,
        "readmit_us_per_page": round(re_p50 * 1e3 / n_pages, 1),
        "snapshot_save_ms": round(save_ms, 3),
        "snapshot_restore_ms": round(restore_ms, 3),
        "restored_pages": restored,
        "hbm_pool_pages": pool,
        "envelope_pages": envelope,
        "tier_capacity_pages": 2 * envelope,
        "warm_chains_tier_off": warm_chains["off"],
        "warm_chains_tier_on": warm_chains["on"],
        "warm_multiplier": round(
            warm_chains["on"] / warm_chains["off"], 2)
        if warm_chains["off"] else None,
        "detail": {
            "readmit_ms": [round(x, 2) for x in readmit_ms],
            "reprefill_ms": [round(x, 2) for x in reprefill_ms],
            "spills": tier.spills,
            "demotions": tier.demotions,
            "readmits": tier.readmits,
        },
    }
    if ctx.backend != "tpu":
        # tiny models on host CPU: a mechanism smoke, not the
        # readmit-vs-reprefill chip claim — label it so no
        # before/after compare ever mistakes it for chip evidence
        rec["label"] = "cpu_smoke"
    log(f"tier: readmit p50 {re_p50:.2f} ms vs re-prefill "
        f"{pf_p50:.2f} ms ({rec['readmit_speedup']}x) over "
        f"{n_pages} pages; warm chains per {envelope}-page envelope "
        f"{warm_chains['off']} -> {warm_chains['on']} "
        f"({rec['warm_multiplier']}x); snapshot save {save_ms:.2f} ms "
        f"/ restore {restore_ms:.2f} ms")
    return ctx.record(rec)


def phase_decode_daemon(ctx: SeriesCtx) -> dict:
    """Completion-daemon e2e latency + continuous serving.  Runs LAST:
    this phase (completer e2e) is the only one that ever hung on-chip
    (round-3 watchdog kill); faulthandler leaves a stack if it repeats.
    Env: DECODE_CHUNK (8)."""
    import threading

    import numpy as np

    from libsplinter_tpu import Store
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.completer import Completer

    chunk = int(os.environ.get("DECODE_CHUNK", "8"))
    quant = os.environ.get("DECODE_QUANT") == "1"
    model, cfg, geometry = _decode_model(quant)
    model.warmup(chunk=chunk)

    name = _bench_store_name("dec")
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=4096, vec_dim=8)
    hung = False
    try:
        comp = Completer(st, model=model, max_new_tokens=32,
                         flush_tokens=chunk, template="none")
        comp.attach()
        log("completer e2e ...")
        e2e = []
        probe_err: list[Exception] = []

        def _probe():
            try:
                for i in range(3):
                    key = f"q/{i}"
                    t0 = time.perf_counter()
                    st.set(key, "Say something interesting about TPUs.")
                    st.label_or(key, P.LBL_INFER_REQ)
                    st.bump(key)
                    comp.run_once()
                    e2e.append((time.perf_counter() - t0) * 1000)
                    log(f"completer e2e request {i}: {e2e[-1]:.0f} ms")
            except Exception as exc:       # surfaced on the main thread
                probe_err.append(exc)

        # bounded: the round-3 on-chip hang lived HERE (run_once blocked
        # in a device sync).  A daemon thread + join(timeout) turns a
        # repeat into a failed phase instead of a burned claim window —
        # this is the LAST series phase, so aborting loses nothing else.
        th = threading.Thread(target=_probe, daemon=True)
        th.start()
        th.join(timeout=float(os.environ.get("DECODE_E2E_TIMEOUT",
                                             "300")))
        if th.is_alive():
            import faulthandler
            hung = True                  # finally: must NOT unmap the
            faulthandler.dump_traceback(file=sys.stderr)  # stuck stack
            raise RuntimeError(
                "completer e2e hung past DECODE_E2E_TIMEOUT (round-3 "
                "on-chip mode); aborting the phase — all thread "
                "stacks incl. the stuck one dumped above")
        if probe_err:
            raise probe_err[0]
        e2e_ms = float(np.median(e2e))

        # the block-paged continuous lane: batch_cap at the new 32
        # default, pool capped at 8 windows of pages (the old dense
        # batch=8 cache HBM) — batch width rides live tokens
        comp2 = Completer(st, model=model, max_new_tokens=32,
                          flush_tokens=chunk, template="none",
                          batch_cap=32,
                          pool_pages=8 * (-(-cfg.max_len // 128)))
        comp2.attach()
        comp2.warmup_paged()          # compile outside the timed window
        runner = threading.Thread(
            target=comp2.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=600.0),
            daemon=True)
        runner.start()
        time.sleep(0.2)
        t0 = time.perf_counter()
        keys = []
        for i in range(12):
            key = f"c/{i}"
            keys.append(key)
            st.set(key, f"Question number {i} about accelerators?")
            st.label_or(key, P.LBL_INFER_REQ)
            st.bump(key)
            if i % 4 == 3:
                time.sleep(0.1)
        deadline = time.perf_counter() + 420
        while time.perf_counter() < deadline:
            if all(st.labels(k) & P.LBL_READY for k in keys):
                break
            time.sleep(0.01)
        cont_s = time.perf_counter() - t0
        comp2.stop()
        runner.join(timeout=5)
        done = sum(1 for k in keys if st.labels(k) & P.LBL_READY)
        cont_tps = comp2.stats.tokens / cont_s if done else 0.0
        log(f"continuous: {done}/12 ready in {cont_s:.2f}s, "
            f"{cont_tps:,.1f} aggregate tok/s")
    finally:
        if hung:
            # the stuck thread still holds pointers into the mapping;
            # closing would unmap under it (use-after-close segfault
            # before the failed phase_status could be recorded).  Only
            # remove the NAME — the mapping lives until process exit.
            Store.unlink(name)
        else:
            st.close()
            Store.unlink(name)

    return ctx.record({
        "metric": "completer_e2e_ms",
        "value": round(e2e_ms, 0), "unit": "ms", "vs_baseline": 0.0,
        "detail": {
            "backend": ctx.backend, "geometry": geometry,
            "quantized": quant,
            "completer_e2e_ms_32tok": round(e2e_ms, 0),
            "continuous_12req_s": round(cont_s, 2),
            "continuous_aggregate_tok_s": round(cont_tps, 1),
            "continuous_ready": done,
        }})


# ---------------------------------------------------------------------------
# the series driver
# ---------------------------------------------------------------------------

def phase_store_ops(ctx: SeriesCtx) -> dict:
    """Raw store throughput + cycles-per-op vs the reference's own
    published numbers (VERDICT r4 #5): MRSW and 32-writer MRMW ops/s
    from the native stress harnesses (spt_stress/spt_chi_sao --json)
    and the clean single-thread write CPO, ledgered alongside the
    reference contract (/root/reference/README.md:130-133: 3.2M MRSW,
    15.6M MRMW ops/s, CPO~937; splinter.h:553-555).  Host-only — no
    device is touched.  Env: STORE_OPS_MS (duration per tool, default
    3000), STORE_OPS_WRITERS (default 32)."""
    import subprocess

    dur = os.environ.get("STORE_OPS_MS", "3000")
    writers = os.environ.get("STORE_OPS_WRITERS", "32")
    build = os.path.join(REPO, "native", "build")
    # build/refresh the harnesses (make is a fast no-op when current) —
    # native/build is gitignored, so a fresh host has no binaries and a
    # stale pre---json binary would silently ignore the flag
    mk = subprocess.run(["make", "tests"],
                        cwd=os.path.join(REPO, "native"),
                        capture_output=True, text=True, timeout=120)
    if mk.returncode != 0:
        raise RuntimeError(f"make tests failed: {mk.stderr[-400:]}")

    tool_timeout = max(120.0, int(dur) / 1000.0 + 60.0)

    def run_tool(args):
        out = subprocess.run(args, capture_output=True, text=True,
                             timeout=tool_timeout, cwd=REPO)
        if out.returncode != 0:
            raise RuntimeError(
                f"{args[0]} rc={out.returncode}: {out.stderr[-400:]}")
        lines = [ln for ln in out.stdout.splitlines()
                 if ln.startswith("{")]
        if not lines:
            raise RuntimeError(
                f"{args[0]} emitted no JSON line — stale binary "
                f"without --json support? (rebuild: make -C native "
                f"tests)")
        return json.loads(lines[-1])

    mrsw_raw = run_tool([os.path.join(build, "spt_stress"),
                         "--duration-ms", dur, "--raw", "--json"])
    mrsw = run_tool([os.path.join(build, "spt_stress"),
                     "--duration-ms", dur, "--json"])
    mrmw = run_tool([os.path.join(build, "spt_chi_sao"),
                     "--writers", writers, "--duration-ms", dur,
                     "--json"])
    if mrsw_raw["corrupt"] or mrsw["corrupt"] or mrmw["corrupt"]:
        raise RuntimeError("integrity failure under stress")
    ncpu = os.cpu_count() or 1
    ref = {"mrsw_ops_per_sec": 3.2e6, "mrmw_ops_per_sec": 15.6e6,
           "write_cpo": 937.0}
    return ctx.record({
        "metric": "store_ops_per_sec",
        "value": round(mrsw_raw["ops_per_sec"], 0),
        "unit": "ops/s (raw MRSW, 1w+7r)",
        "vs_baseline": round(mrsw_raw["ops_per_sec"]
                             / ref["mrsw_ops_per_sec"], 3),
        "detail": {
            "backend": "host",
            "host_cores": ncpu,
            "mrsw_raw": mrsw_raw,
            "mrsw_structured": mrsw,
            "mrmw": mrmw,
            "write_cpo": mrsw_raw["write_cpo"],
            "cpo_vs_reference": round(
                mrsw_raw["write_cpo"] / ref["write_cpo"], 3),
            "mrmw_vs_reference": round(
                mrmw["ops_per_sec"] / ref["mrmw_ops_per_sec"], 3),
            "reference": ref,
            "note": ("reference numbers were published from a "
                     "many-core box; this host has "
                     f"{ncpu} core(s) — CPO is the core-count-"
                     "independent comparison"),
        },
    })


PHASE_FNS = {
    "embed": phase_embed,
    "embed_sweep": phase_embed_sweep,
    "profile": phase_profile,
    "dispatch": phase_dispatch,
    "kernels": phase_kernels,
    "search": phase_search,
    "restage": phase_restage,
    "decode": phase_decode,
    "decode_quant": phase_decode_quant,
    "multichip": phase_multichip,
    "loadgen": phase_loadgen,
    "prefix": phase_prefix,
    "disagg": phase_disagg,
    "tier": phase_tier,
    "decode_daemon": phase_decode_daemon,
    "store_ops": phase_store_ops,
}


def run_series(phases: tuple[str, ...] | None = None,
               deadline_epoch: float | None = None) -> SeriesCtx:
    """Claim the backend once, then run every requested phase with
    per-phase fencing.  Returns the ctx (ctx.headline = embed record)."""
    import faulthandler

    # a hung phase must leave a stack before any external kill (skipped
    # when stderr has no fileno, e.g. under pytest capture)
    try:
        faulthandler.dump_traceback_later(600, repeat=True,
                                          file=sys.stderr)
    except (ValueError, OSError, io.UnsupportedOperation):
        pass

    if phases is None:
        env = os.environ.get("BENCH_PHASES", "")
        phases = tuple(p.strip() for p in env.split(",") if p.strip())
        if not phases:
            # CPU mode is the quick-tracking path: embed only, so the
            # old `BENCH_CPU=1 python bench.py` contract stays fast.
            # A real (TPU) claim runs the full series by default.
            phases = ("embed",) if os.environ.get("BENCH_CPU") == "1" \
                else ALL_PHASES
    bad = set(phases) - set(ALL_PHASES)
    if bad:
        raise SystemExit(f"unknown phases: {sorted(bad)}")

    if os.environ.get("BENCH_CPU") == "1":
        from libsplinter_tpu.utils.jaxplatform import force_cpu
        force_cpu()
    from libsplinter_tpu.utils.jaxplatform import enable_compile_cache
    enable_compile_cache()

    ctx = SeriesCtx(deadline_epoch)

    _stage("client-init")           # first device access claims the tunnel
    import jax

    ctx.n_devices = len(jax.devices())
    ctx.backend = jax.default_backend()
    _stage("client-init-done")
    log(f"[series] backend={ctx.backend} devices={ctx.n_devices} "
        f"window={ctx.remaining():.0f}s phases={','.join(phases)}")

    for name in phases:
        left = ctx.remaining()
        # embed (the headline) always runs once the claim landed; the
        # rest must fit the remaining window
        if name != "embed" and left < PHASE_MIN_S[name]:
            log(f"[series] SKIP {name}: {left:.0f}s left "
                f"< {PHASE_MIN_S[name]}s floor")
            ctx.phase_status[name] = "skipped"
            continue
        _stage(f"phase-{name}")
        if os.environ.get("BENCH_TEST_CRASH_AT") == name:
            # test hook: hard-crash mid-phase (at most once when
            # BENCH_TEST_CRASH_ONCE names a flag file) so bench.py's
            # restricted-retry path has automated coverage
            flagp = os.environ.get("BENCH_TEST_CRASH_ONCE", "")
            if not flagp or not os.path.exists(flagp):
                if flagp:
                    open(flagp, "w").close()
                log(f"[series] TEST HOOK: crashing at {name}")
                os._exit(3)
        t0 = time.perf_counter()
        try:
            PHASE_FNS[name](ctx)
            ctx.phase_status[name] = "ok"
            log(f"[series] phase {name} done in "
                f"{time.perf_counter() - t0:.1f}s")
            # "-done" means SUCCEEDED: bench.py's mid-series retry
            # drops "-done" phases from the retry set, so a failed
            # phase (no ledger record) must not earn the marker
            _stage(f"phase-{name}-done")
        except Exception:
            ctx.phase_status[name] = "failed"
            log(f"[series] phase {name} FAILED after "
                f"{time.perf_counter() - t0:.1f}s:\n"
                f"{traceback.format_exc()}")
            _stage(f"phase-{name}-failed")
        if os.environ.get("BENCH_TEST_CRASH_AFTER") == name:
            # test hook: hard-crash AFTER a phase ledgered, on every
            # attempt — drives bench.py's end-of-window recovery of a
            # fresh in-window headline from a crashed (rc!=0) child
            log(f"[series] TEST HOOK: crashing after {name}")
            os._exit(3)
        if os.environ.get("BENCH_TEST_SLEEP_AFTER") == name:
            # test hook: simulate the round-3 on-chip hang (a phase
            # that never returns) so bench.py's recovery path has
            # automated coverage (tests/test_bench_parent.py)
            log(f"[series] TEST HOOK: sleeping forever after {name}")
            time.sleep(1 << 20)
    _stage("series-done")
    faulthandler.cancel_dump_traceback_later()
    return ctx


def main() -> int:
    ctx = run_series()
    if ctx.headline is not None:
        out = {k: v for k, v in ctx.headline.items() if k != "ts"}
        # the watcher keeps knocking on an incomplete series; the
        # driver's scoring consumer ignores the extra keys.  Complete
        # means ALL_PHASES ran ok — a phase-restricted run (retry after
        # a crash, user selection) must not masquerade as the full
        # evidence set (ADVICE r4).
        out["series_complete"] = all(
            ctx.phase_status.get(p) == "ok" for p in ALL_PHASES)
        out["phase_status"] = ctx.phase_status
        print(json.dumps(out), flush=True)
        return 0
    # headline missing (embed not requested or failed): still exit 0 if
    # any phase recorded — the ledger holds the evidence
    return 0 if ctx.records else 1


def shim_main(*phases: str) -> int:
    """Entry point for the thin standalone wrappers (bench_profile.py,
    bench_decode.py, bench_search.py): run the named phases and print
    the FIRST record — the wrapper's primary metric — as the script's
    ONE stdout JSON line (later phases still ledger their records)."""
    ctx = run_series(phases=phases)
    if not ctx.records:
        return 1
    print(json.dumps({k: v for k, v in ctx.records[0].items()
                      if k != "ts"}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
