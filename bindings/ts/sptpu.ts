/* TypeScript FFI bindings for the splinter-tpu native store (libsptpu.so).
 *
 * Capability parity with the reference's Bun/Deno bindings
 * (bindings/ts/splinter.ts: SplinterStore interface + SplinterWatcher async
 * poller), re-designed for this store's handle-based C ABI:
 *
 *   - every call carries an explicit store handle (the reference ABI holds
 *     one implicit global store per process);
 *   - negative-errno returns surface as plain numbers (0 ok, -N errno);
 *   - the embedding dimension is read from the store geometry instead of
 *     being compiled in (reference hardcodes 768);
 *   - extra surface the reference lacks: tandem keys, integer ops, bloom
 *     enumeration, event-bus drain, header stats.
 *
 * Works under BOTH Bun (bun:ffi) and Deno (Deno.dlopen); the `openStore` /
 * `createStore` factories pick the right backend at runtime.
 *
 * Usage (either runtime):
 *   import { createStore, SptWatcher } from "./sptpu.ts";
 *   const st = createStore("/my_bus", { nslots: 1024, maxVal: 4096, vecDim: 768 });
 *   st.set("greeting", "hello");
 *   st.setLabel("greeting", 1n);      // bloom bit 0 => wake the embedder
 *   st.bump("greeting");
 *   const vec = st.getEmbedding("greeting");   // Float32Array | null
 */

const KEY_MAX = 128;
const DIRTY_WORDS = 16;

export interface SptEntry {
  key: string;
  epoch: bigint;
}

export interface CreateOpts {
  nslots?: number;
  maxVal?: number;
  vecDim?: number;
  file?: boolean; // file-backed (persistent) instead of POSIX shm
}

/** Common store surface implemented by both runtime backends. */
export interface SptStore {
  close(): void;
  // KV
  set(key: string, value: string | Uint8Array): number;
  get(key: string): Uint8Array | null;
  getString(key: string): string | null;
  unset(key: string): number;
  append(key: string, value: string | Uint8Array): number;
  list(maxKeys?: number): SptEntry[];
  poll(key: string, timeoutMs: number): number;
  // metadata
  getEpoch(key: string): bigint;
  setLabel(key: string, mask: bigint): number;
  clearLabel(key: string, mask: bigint): number;
  getLabels(key: string): bigint;
  setType(key: string, typeFlag: number): number;
  getType(key: string): number;
  integerOp(key: string, op: number, operand: bigint): bigint | null;
  // tandem (ordered) keys: base, base.1, base.2, ...
  tandemSet(base: string, order: number, value: string | Uint8Array): number;
  tandemGet(base: string, order: number): Uint8Array | null;
  tandemCount(base: string): number;
  // signals
  getSignalCount(group: number): bigint;
  pulse(group: number): number;
  bump(key: string): number;
  signalWait(group: number, last: bigint,
             timeoutMs: number): bigint | null;
  // bulk lane (the TPU micro-batcher surface)
  findIndex(key: string): number;
  epochs(): BigUint64Array;
  vecGather(rows: Uint32Array): {
    vecs: Float32Array; epochs: BigUint64Array; stable: number;
  };
  vecCommitBatch(rows: Uint32Array, epochs: BigUint64Array,
                 vecs: Float32Array, writeOnce?: boolean): {
    committed: number; results: Int32Array;
  };
  watchRegister(key: string, group: number): number;
  watchUnregister(key: string, group: number): number;
  watchLabelRegister(bloomBit: number, group: number): number;
  watchLabelUnregister(bloomBit: number, group: number): number;
  // bloom enumeration: slot indices where (labels & mask) === mask
  enumerate(mask: bigint, maxOut?: number): Uint32Array;
  keyAt(idx: number): string | null;
  // embeddings
  vecDim(): number;
  getEmbedding(key: string): Float32Array | null;
  setEmbedding(key: string, vec: Float32Array): number;
  // event bus
  busInit(): number;
  busOpen(): number;
  busWait(timeoutMs: number): number;
  busDrain(): BigUint64Array; // 16-word dirty mask (fetch-and-clear)
  // geometry / stats
  nslots(): number;
  maxVal(): number;
}

/* ------------------------------------------------------------------ */
/* symbol table (shared shape between the two runtimes)               */
/* ------------------------------------------------------------------ */

// p = pointer, b = buffer (byte array in), c = cstring in, u32/u64/i32 ints
const SYMBOLS: Record<string, { args: string[]; ret: string }> = {
  spt_create: { args: ["b", "u32", "u32", "u32", "u32"], ret: "p" },
  spt_open: { args: ["b", "u32"], ret: "p" },
  spt_close: { args: ["p"], ret: "i32" },
  spt_unlink: { args: ["b", "u32"], ret: "i32" },
  spt_nslots: { args: ["p"], ret: "u32" },
  spt_max_val: { args: ["p"], ret: "u32" },
  spt_vec_dim: { args: ["p"], ret: "u32" },
  spt_set: { args: ["p", "b", "b", "u32"], ret: "i32" },
  spt_get: { args: ["p", "b", "b", "u32", "b"], ret: "i32" },
  spt_unset: { args: ["p", "b"], ret: "i32" },
  spt_append: { args: ["p", "b", "b", "u32"], ret: "i32" },
  spt_list: { args: ["p", "b", "u32"], ret: "i32" },
  spt_poll: { args: ["p", "b", "i32"], ret: "i32" },
  spt_find_index: { args: ["p", "b"], ret: "i32" },
  spt_key_at: { args: ["p", "u32", "b"], ret: "i32" },
  spt_epoch_at: { args: ["p", "u32"], ret: "u64" },
  spt_set_type: { args: ["p", "b", "u32"], ret: "i32" },
  spt_get_type: { args: ["p", "b", "b"], ret: "i32" },
  spt_integer_op: { args: ["p", "b", "i32", "u64", "b"], ret: "i32" },
  spt_tandem_set: { args: ["p", "b", "u32", "b", "u32"], ret: "i32" },
  spt_tandem_get: { args: ["p", "b", "u32", "b", "u32", "b"], ret: "i32" },
  spt_tandem_count: { args: ["p", "b"], ret: "i32" },
  spt_label_or: { args: ["p", "b", "u64"], ret: "i32" },
  spt_label_andnot: { args: ["p", "b", "u64"], ret: "i32" },
  spt_get_labels: { args: ["p", "b", "b"], ret: "i32" },
  spt_enumerate: { args: ["p", "u64", "b", "u32"], ret: "i32" },
  spt_watch_register: { args: ["p", "b", "u32"], ret: "i32" },
  spt_watch_unregister: { args: ["p", "b", "u32"], ret: "i32" },
  spt_watch_label_register: { args: ["p", "u32", "u32"], ret: "i32" },
  spt_watch_label_unregister: { args: ["p", "u32", "u32"], ret: "i32" },
  spt_signal_count: { args: ["p", "u32"], ret: "u64" },
  spt_signal_pulse: { args: ["p", "u32"], ret: "i32" },
  spt_bump: { args: ["p", "b"], ret: "i32" },
  spt_vec_set: { args: ["p", "b", "b", "u32"], ret: "i32" },
  spt_vec_get: { args: ["p", "b", "b", "u32"], ret: "i32" },
  spt_signal_wait: { args: ["p", "u32", "u64", "i32", "b"], ret: "i32" },
  spt_epochs: { args: ["p", "b"], ret: "i32" },
  spt_vec_gather: { args: ["p", "b", "u32", "b", "b"], ret: "i32" },
  spt_vec_commit_batch: {
    args: ["p", "b", "b", "b", "u32", "u32", "i32", "b"], ret: "i32" },
  spt_bus_init: { args: ["p"], ret: "i32" },
  spt_bus_open: { args: ["p"], ret: "i32" },
  spt_bus_wait: { args: ["p", "i32"], ret: "i32" },
  spt_bus_close: { args: ["p"], ret: "i32" },
  spt_bus_drain: { args: ["p", "b"], ret: "i32" },
  // host tokenizer (wptok.c): WordPiece / hashed fast path
  spt_wptok_create: { args: ["p", "u32", "i32"], ret: "p" },
  spt_wptok_create_hashed: { args: ["u32", "i32"], ret: "p" },
  spt_wptok_destroy: { args: ["p"], ret: "void" },
  spt_wptok_encode: { args: ["p", "b", "b", "u32"], ret: "i32" },
  spt_wptok_encode_batch: { args: ["p", "p", "u32", "u32", "b", "b"],
    ret: "i32" },
};

const enc = new TextEncoder();
const dec = new TextDecoder();

function cstr(s: string): Uint8Array {
  return enc.encode(s + "\0");
}

/** Byte view that RESPECTS a typed array's offset/length — passing
 *  `new Uint8Array(x.buffer)` would address the backing buffer's
 *  start, silently reading/writing the wrong memory for subarrays. */
function view(x: { buffer: ArrayBufferLike; byteOffset: number;
                   byteLength: number }): Uint8Array {
  return new Uint8Array(x.buffer, x.byteOffset, x.byteLength);
}

function toBytes(v: string | Uint8Array): Uint8Array {
  return typeof v === "string" ? enc.encode(v) : v;
}

/* ------------------------------------------------------------------ */
/* runtime adapters                                                    */
/* ------------------------------------------------------------------ */

type RawCall = (...args: unknown[]) => unknown;

interface Runtime {
  symbols: Record<string, RawCall>;
  close(): void;
}

declare const Bun: { version: string } | undefined;
// deno-lint-ignore no-explicit-any
declare const Deno: any;

function isBun(): boolean {
  return typeof Bun !== "undefined";
}

function isDeno(): boolean {
  // @ts-ignore: cross-runtime probe
  return typeof Deno !== "undefined" && !!Deno.dlopen;
}

async function loadBun(libPath: string): Promise<Runtime> {
  // @ts-ignore: bun-only module
  const { dlopen, FFIType, ptr } = await import("bun:ffi");
  const t: Record<string, unknown> = {
    p: FFIType.ptr,
    b: FFIType.ptr,
    u32: FFIType.u32,
    u64: FFIType.u64,
    i32: FFIType.i32,
    void: FFIType.void,
  };
  const defs: Record<string, unknown> = {};
  for (const [name, sig] of Object.entries(SYMBOLS)) {
    defs[name] = { args: sig.args.map((a) => t[a]), returns: t[sig.ret] };
  }
  const lib = dlopen(libPath, defs);
  const symbols: Record<string, RawCall> = {};
  for (const name of Object.keys(SYMBOLS)) {
    const sig = SYMBOLS[name];
    symbols[name] = (...args: unknown[]) => {
      const conv = args.map((a, i) =>
        sig.args[i] === "b" && a instanceof Uint8Array ? ptr(a) : a
      );
      return lib.symbols[name](...conv);
    };
  }
  return { symbols, close: () => lib.close() };
}

function loadDeno(libPath: string): Runtime {
  const t: Record<string, string> = {
    p: "pointer",
    b: "buffer",
    u32: "u32",
    u64: "u64",
    i32: "i32",
    void: "void",
  };
  const defs: Record<string, unknown> = {};
  for (const [name, sig] of Object.entries(SYMBOLS)) {
    defs[name] = {
      parameters: sig.args.map((a) => t[a]),
      result: t[sig.ret],
    };
  }
  const lib = Deno.dlopen(libPath, defs);
  return { symbols: lib.symbols, close: () => lib.close() };
}

/* ------------------------------------------------------------------ */
/* the store wrapper                                                   */
/* ------------------------------------------------------------------ */

export class Store implements SptStore {
  private rt: Runtime;
  private h: unknown; // spt_store*
  private dim: number;

  constructor(rt: Runtime, handle: unknown) {
    if (!handle) throw new Error("sptpu: null store handle");
    this.rt = rt;
    this.h = handle;
    this.dim = Number(this.rt.symbols.spt_vec_dim(this.h));
  }

  close(): void {
    this.rt.symbols.spt_close(this.h);
  }

  set(key: string, value: string | Uint8Array): number {
    const v = toBytes(value);
    return Number(this.rt.symbols.spt_set(this.h, cstr(key), v, v.length));
  }

  get(key: string): Uint8Array | null {
    const cap = this.maxVal();
    const buf = new Uint8Array(cap);
    const lenOut = new Uint8Array(4);
    const rc = Number(
      this.rt.symbols.spt_get(this.h, cstr(key), buf, cap, lenOut),
    );
    if (rc !== 0) return null;
    const len = new DataView(lenOut.buffer).getUint32(0, true);
    return buf.subarray(0, len);
  }

  getString(key: string): string | null {
    const b = this.get(key);
    return b === null ? null : dec.decode(b);
  }

  unset(key: string): number {
    return Number(this.rt.symbols.spt_unset(this.h, cstr(key)));
  }

  append(key: string, value: string | Uint8Array): number {
    const v = toBytes(value);
    return Number(this.rt.symbols.spt_append(this.h, cstr(key), v, v.length));
  }

  list(maxKeys = 4096): SptEntry[] {
    const buf = new Uint8Array(maxKeys * KEY_MAX);
    const n = Number(this.rt.symbols.spt_list(this.h, buf, maxKeys));
    const out: SptEntry[] = [];
    for (let i = 0; i < n; i++) {
      const row = buf.subarray(i * KEY_MAX, (i + 1) * KEY_MAX);
      const nul = row.indexOf(0);
      const key = dec.decode(row.subarray(0, nul < 0 ? KEY_MAX : nul));
      out.push({ key, epoch: this.getEpoch(key) });
    }
    return out;
  }

  poll(key: string, timeoutMs: number): number {
    return Number(this.rt.symbols.spt_poll(this.h, cstr(key), timeoutMs));
  }

  /** Slot index for a key (negative errno when absent) — the handle
   *  the bulk lane APIs (vecGather / vecCommitBatch) address rows by. */
  findIndex(key: string): number {
    return Number(this.rt.symbols.spt_find_index(this.h, cstr(key)));
  }

  getEpoch(key: string): bigint {
    const idx = this.findIndex(key);
    if (idx < 0) return -1n;
    return BigInt(this.rt.symbols.spt_epoch_at(this.h, idx) as bigint);
  }

  setLabel(key: string, mask: bigint): number {
    return Number(this.rt.symbols.spt_label_or(this.h, cstr(key), mask));
  }

  clearLabel(key: string, mask: bigint): number {
    return Number(this.rt.symbols.spt_label_andnot(this.h, cstr(key), mask));
  }

  getLabels(key: string): bigint {
    const out = new Uint8Array(8);
    const rc = Number(this.rt.symbols.spt_get_labels(this.h, cstr(key), out));
    if (rc !== 0) return 0n;
    return new DataView(out.buffer).getBigUint64(0, true);
  }

  setType(key: string, typeFlag: number): number {
    return Number(this.rt.symbols.spt_set_type(this.h, cstr(key), typeFlag));
  }

  getType(key: string): number {
    const out = new Uint8Array(4);
    const rc = Number(this.rt.symbols.spt_get_type(this.h, cstr(key), out));
    if (rc !== 0) return rc;
    return new DataView(out.buffer).getUint32(0, true);
  }

  integerOp(key: string, op: number, operand: bigint): bigint | null {
    const out = new Uint8Array(8);
    const rc = Number(
      this.rt.symbols.spt_integer_op(this.h, cstr(key), op, operand, out),
    );
    if (rc !== 0) return null;
    return new DataView(out.buffer).getBigUint64(0, true);
  }

  tandemSet(base: string, order: number, value: string | Uint8Array): number {
    const v = toBytes(value);
    return Number(
      this.rt.symbols.spt_tandem_set(this.h, cstr(base), order, v, v.length),
    );
  }

  tandemGet(base: string, order: number): Uint8Array | null {
    const cap = this.maxVal();
    const buf = new Uint8Array(cap);
    const lenOut = new Uint8Array(4);
    const rc = Number(
      this.rt.symbols.spt_tandem_get(this.h, cstr(base), order, buf, cap, lenOut),
    );
    if (rc !== 0) return null;
    const len = new DataView(lenOut.buffer).getUint32(0, true);
    return buf.subarray(0, len);
  }

  tandemCount(base: string): number {
    return Number(this.rt.symbols.spt_tandem_count(this.h, cstr(base)));
  }

  getSignalCount(group: number): bigint {
    return BigInt(this.rt.symbols.spt_signal_count(this.h, group) as bigint);
  }

  pulse(group: number): number {
    return Number(this.rt.symbols.spt_signal_pulse(this.h, group));
  }

  bump(key: string): number {
    return Number(this.rt.symbols.spt_bump(this.h, cstr(key)));
  }

  watchRegister(key: string, group: number): number {
    return Number(this.rt.symbols.spt_watch_register(this.h, cstr(key), group));
  }

  watchUnregister(key: string, group: number): number {
    return Number(
      this.rt.symbols.spt_watch_unregister(this.h, cstr(key), group),
    );
  }

  watchLabelRegister(bloomBit: number, group: number): number {
    return Number(
      this.rt.symbols.spt_watch_label_register(this.h, bloomBit, group),
    );
  }

  watchLabelUnregister(bloomBit: number, group: number): number {
    return Number(
      this.rt.symbols.spt_watch_label_unregister(this.h, bloomBit, group),
    );
  }

  enumerate(mask: bigint, maxOut = 4096): Uint32Array {
    const buf = new Uint32Array(maxOut);
    const n = Number(
      this.rt.symbols.spt_enumerate(
        this.h,
        mask,
        new Uint8Array(buf.buffer),
        maxOut,
      ),
    );
    return buf.subarray(0, Math.max(n, 0));
  }

  keyAt(idx: number): string | null {
    const buf = new Uint8Array(KEY_MAX);
    const rc = Number(this.rt.symbols.spt_key_at(this.h, idx, buf));
    if (rc !== 0) return null;
    const nul = buf.indexOf(0);
    return dec.decode(buf.subarray(0, nul < 0 ? KEY_MAX : nul));
  }

  vecDim(): number {
    return this.dim;
  }

  getEmbedding(key: string): Float32Array | null {
    const vec = new Float32Array(this.dim);
    const rc = Number(
      this.rt.symbols.spt_vec_get(
        this.h,
        cstr(key),
        new Uint8Array(vec.buffer),
        this.dim,
      ),
    );
    return rc === 0 ? vec : null;
  }

  setEmbedding(key: string, vec: Float32Array): number {
    if (vec.length !== this.dim) return -22; // -EINVAL
    return Number(
      this.rt.symbols.spt_vec_set(
        this.h,
        cstr(key),
        new Uint8Array(vec.buffer),
        this.dim,
      ),
    );
  }

  /** Block until the group's signal count changes from `last`
   *  (event-bus wake when armed, 1 ms poll otherwise).  Returns the
   *  new count, null on TIMEOUT; hard errors (bad group) throw rather
   *  than masquerade as timeouts. */
  signalWait(group: number, last: bigint,
             timeoutMs: number): bigint | null {
    const out = new BigUint64Array(1);
    const rc = Number(
      this.rt.symbols.spt_signal_wait(
        this.h, group, last, timeoutMs, view(out)),
    );
    if (rc === 0) return out[0];
    if (rc === -110) return null;     // -ETIMEDOUT
    throw new Error(`spt_signal_wait failed: errno ${-rc}`);
  }

  /** Bulk epoch snapshot (one acquire load per slot); diff two
   *  snapshots for the changed-row set.  Throws on a negative errno
   *  (stale handle): an all-zero array returned on failure would be
   *  indistinguishable from a legitimate snapshot and silently break
   *  diff-based change detectors. */
  epochs(): BigUint64Array {
    const out = new BigUint64Array(this.nslots());
    const rc = Number(this.rt.symbols.spt_epochs(this.h, view(out)));
    if (rc < 0) throw new Error(`spt_epochs failed: errno ${-rc}`);
    return out;
  }

  /** Torn-safe bulk gather of vector rows.  epochs[i] is the stable
   *  epoch, or SPT_GATHER_TORN (2^64-1) when the row was mid-write
   *  (retry next pass).  Returns {vecs, epochs, stable}. */
  vecGather(rows: Uint32Array): {
    vecs: Float32Array; epochs: BigUint64Array; stable: number;
  } {
    const vecs = new Float32Array(rows.length * this.dim);
    const eps = new BigUint64Array(rows.length);
    const stable = Number(
      this.rt.symbols.spt_vec_gather(
        this.h, view(rows), rows.length, view(vecs), view(eps)),
    );
    return { vecs, epochs: eps, stable };
  }

  /** Epoch-gated batch vector commit (the TPU micro-batcher's path):
   *  per-row results 0 committed / -ESTALE raced / -EEXIST write-once
   *  skip.  Returns {committed, results}; committed is -EINVAL (-22)
   *  on mismatched array lengths (the native side would otherwise
   *  read past the JS buffers). */
  vecCommitBatch(rows: Uint32Array, epochs: BigUint64Array,
                 vecs: Float32Array, writeOnce = false): {
    committed: number; results: Int32Array;
  } {
    const results = new Int32Array(rows.length);
    if (epochs.length !== rows.length ||
        vecs.length !== rows.length * this.dim) {
      return { committed: -22, results };
    }
    const committed = Number(
      this.rt.symbols.spt_vec_commit_batch(
        this.h, view(rows), view(epochs), view(vecs),
        rows.length, this.dim, writeOnce ? 1 : 0, view(results)),
    );
    return { committed, results };
  }

  busInit(): number {
    return Number(this.rt.symbols.spt_bus_init(this.h));
  }

  busOpen(): number {
    return Number(this.rt.symbols.spt_bus_open(this.h));
  }

  busWait(timeoutMs: number): number {
    return Number(this.rt.symbols.spt_bus_wait(this.h, timeoutMs));
  }

  busDrain(): BigUint64Array {
    const mask = new BigUint64Array(DIRTY_WORDS);
    this.rt.symbols.spt_bus_drain(this.h, new Uint8Array(mask.buffer));
    return mask;
  }

  nslots(): number {
    return Number(this.rt.symbols.spt_nslots(this.h));
  }

  maxVal(): number {
    return Number(this.rt.symbols.spt_max_val(this.h));
  }
}

/* ------------------------------------------------------------------ */
/* async watcher (reference parity: SplinterWatcher)                   */
/* ------------------------------------------------------------------ */

/** Polls a signal group and yields the new count each time it advances.
 *
 *   const w = new SptWatcher(store, 2);
 *   for await (const count of w) { ... }   // w.stop() to end
 */
export class SptWatcher implements AsyncIterable<bigint> {
  private store: SptStore;
  private group: number;
  private intervalMs: number;
  private running = false;

  constructor(store: SptStore, group: number, intervalMs = 25) {
    this.store = store;
    this.group = group;
    this.intervalMs = intervalMs;
  }

  stop(): void {
    this.running = false;
  }

  async *[Symbol.asyncIterator](): AsyncIterator<bigint> {
    this.running = true;
    let last = this.store.getSignalCount(this.group);
    while (this.running) {
      const now = this.store.getSignalCount(this.group);
      if (now !== last) {
        last = now;
        yield now;
      } else {
        await new Promise((r) => setTimeout(r, this.intervalMs));
      }
    }
  }
}

/* ------------------------------------------------------------------ */
/* factories                                                           */
/* ------------------------------------------------------------------ */

const BACKEND_FILE = 1;
const CREATE_EXCL = 2;

async function loadRuntime(libPath: string): Promise<Runtime> {
  if (isBun()) return await loadBun(libPath);
  if (isDeno()) return loadDeno(libPath);
  throw new Error("sptpu.ts requires Bun or Deno");
}

export async function openStore(
  libPath: string,
  name: string,
  opts: { file?: boolean } = {},
): Promise<Store> {
  const rt = await loadRuntime(libPath);
  const flags = opts.file ? BACKEND_FILE : 0;
  const h = rt.symbols.spt_open(cstr(name), flags);
  return new Store(rt, h);
}

export async function createStore(
  libPath: string,
  name: string,
  opts: CreateOpts = {},
): Promise<Store> {
  const rt = await loadRuntime(libPath);
  const flags = (opts.file ? BACKEND_FILE : 0) | CREATE_EXCL;
  const h = rt.symbols.spt_create(
    cstr(name),
    opts.nslots ?? 1024,
    opts.maxVal ?? 4096,
    opts.vecDim ?? 768,
    flags,
  );
  return new Store(rt, h);
}

export async function unlinkStore(
  libPath: string,
  name: string,
  opts: { file?: boolean } = {},
): Promise<number> {
  const rt = await loadRuntime(libPath);
  const rc = Number(
    rt.symbols.spt_unlink(cstr(name), opts.file ? BACKEND_FILE : 0),
  );
  rt.close();
  return rc;
}

/* type flags (sptpu.h) */
export const T_VOID = 0x00;
export const T_BIGINT = 0x01;
export const T_BIGUINT = 0x02;
export const T_JSON = 0x04;
export const T_BINARY = 0x08;
export const T_IMGDATA = 0x10;
export const T_AUDIO = 0x20;
export const T_VARTEXT = 0x40;

/* integer ops (spt_iop_t) */
export const IOP_AND = 0;
export const IOP_OR = 1;
export const IOP_XOR = 2;
export const IOP_NOT = 3;
export const IOP_INC = 4;
export const IOP_DEC = 5;
export const IOP_ADD = 6;
export const IOP_SUB = 7;
