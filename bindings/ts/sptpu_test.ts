/* Smoke tests for the TS FFI binding (reference parity:
 * bindings/ts/splinter_test.ts — set/get, epoch increment, named types,
 * signal counts, bump, embeddings round-trip).
 *
 * Run under Deno:
 *   deno test --allow-ffi --allow-env bindings/ts/sptpu_test.ts
 * or under Bun:
 *   bun test bindings/ts/sptpu_test.ts
 *
 * Env: SPTPU_LIB — path to libsptpu.so (default ../../native/build/libsptpu.so
 * relative to this file).
 */
import {
  createStore,
  IOP_INC,
  SptWatcher,
  T_BIGUINT,
  T_VARTEXT,
  unlinkStore,
} from "./sptpu.ts";

declare const Deno: {
  env: { get(k: string): string | undefined };
  test(name: string, fn: () => void | Promise<void>): void;
} | undefined;

const LIB = (typeof Deno !== "undefined" && Deno?.env.get("SPTPU_LIB")) ||
  (typeof process !== "undefined" && process.env?.SPTPU_LIB) ||
  new URL("../../native/build/libsptpu.so", import.meta.url).pathname;

function assert(cond: boolean, msg: string): void {
  if (!cond) throw new Error("FAIL: " + msg);
}

function assertEq<T>(a: T, b: T, msg: string): void {
  assert(a === b, `${msg} (${String(a)} !== ${String(b)})`);
}

export async function runAll(): Promise<void> {
  const name = `/sptpu-ts-test-${Math.floor(Math.random() * 1e9)}`;
  const st = await createStore(LIB, name, {
    nslots: 128,
    maxVal: 512,
    vecDim: 16,
  });
  try {
    // set/get round-trip
    assertEq(st.set("greeting", "hello ts"), 0, "set rc");
    assertEq(st.getString("greeting"), "hello ts", "get round-trip");

    // epoch increments by 2 per write (seqlock: odd while held)
    const e1 = st.getEpoch("greeting");
    st.set("greeting", "rewritten");
    const e2 = st.getEpoch("greeting");
    assertEq(e2 - e1, 2n, "epoch +2 per write");

    // named types + BIGUINT promotion + integer op
    st.set("counter", "41");
    assertEq(st.setType("counter", T_BIGUINT), 0, "biguint promote rc");
    const v = st.integerOp("counter", IOP_INC, 0n);
    assertEq(v, 42n, "INC over promoted biguint");

    // labels + enumeration
    st.set("doc", "labelled");
    st.setType("doc", T_VARTEXT);
    st.setLabel("doc", 1n << 9n);
    const hits = st.enumerate(1n << 9n);
    assertEq(hits.length, 1, "enumerate finds the labelled slot");
    assertEq(st.keyAt(hits[0]), "doc", "keyAt resolves index");

    // signals: bump pulses the watcher group
    st.watchRegister("doc", 7);
    const c0 = st.getSignalCount(7);
    st.bump("doc");
    assertEq(st.getSignalCount(7) - c0, 1n, "bump pulses group");

    // embedding round-trip through the contiguous vector lane
    const vec = new Float32Array(16).map((_, i) => i / 16);
    assertEq(st.setEmbedding("doc", vec), 0, "vec set rc");
    const got = st.getEmbedding("doc");
    assert(got !== null, "vec get");
    assert(Math.abs(got![5] - 5 / 16) < 1e-6, "vec content");

    // tandem keys
    st.tandemSet("chunks", 1, "part one");
    st.tandemSet("chunks", 2, "part two");
    assertEq(st.tandemCount("chunks"), 2, "tandem count");

    // append grows the value
    st.set("log", "a");
    st.append("log", "bc");
    assertEq(st.getString("log"), "abc", "append");

    // bulk lane APIs (the TPU micro-batcher's path over FFI)
    const idx = st.findIndex("doc");
    const rows = new Uint32Array([idx >>> 0]);
    const g0 = st.vecGather(rows);
    assertEq(g0.stable, 1, "gather stable");
    const bvec = new Float32Array(st.vecDim()).fill(0.5);
    const cb = st.vecCommitBatch(rows, g0.epochs, bvec);
    assertEq(cb.committed, 1, "batch commit");
    const g1 = st.vecGather(rows);
    assertEq(g1.vecs[0], 0.5, "committed value readable");
    const snap = st.epochs();
    assertEq(snap.length, st.nslots(), "epoch snapshot length");

    // async watcher observes a pulse
    const w = new SptWatcher(st, 7, 5);
    const seen: bigint[] = [];
    const task = (async () => {
      for await (const c of w) {
        seen.push(c);
        w.stop();
      }
    })();
    st.bump("doc");
    await task;
    assertEq(seen.length, 1, "watcher yielded");

    console.log("sptpu_test: all assertions passed");
  } finally {
    st.close();
    await unlinkStore(LIB, name);
  }
}

declare const process: { env?: Record<string, string> } | undefined;

if (typeof Deno !== "undefined" && Deno?.test) {
  Deno.test("sptpu ffi smoke", runAll);
} else {
  await runAll();
}
