/* internal.h — in-memory layout of the splinter-tpu store (not installed).
 *
 * Region layout (one mmap, shm or file):
 *   [ header 8192B | slot table nslots*192B | value arena nslots*max_val
 *     | vector lane nslots*vec_dim*4B (256-aligned) ]
 *
 * The vector lane is deliberately last and 256-byte aligned so the Python
 * tier can wrap it as one contiguous (nslots, dim) float32 numpy array and
 * stage dirty row-blocks to TPU HBM without gather-copies.
 */
#ifndef SPTPU_INTERNAL_H
#define SPTPU_INTERNAL_H

#define _GNU_SOURCE
#include "sptpu.h"
#include <stdatomic.h>
#include <stdbool.h>
#include <string.h>
#include <errno.h>

#define SPT_HDR_BYTES   8192u
#define SPT_SLOT_BYTES  192u
#define SPT_TOMBSTONE   1ull    /* hash value marking a deleted slot */

typedef struct {
  _Atomic uint64_t v;
  uint8_t pad[56];
} spt_sigctr;                    /* one counter per cache line */

typedef struct {
  _Atomic int64_t  pid;          /* 0 = free */
  _Atomic uint64_t shard_id;
  _Atomic uint64_t claimed_at;   /* microseconds, CLOCK_MONOTONIC-derived */
  _Atomic uint64_t duration_us;  /* 0 = born expired */
  _Atomic uint32_t intent;
  _Atomic uint32_t priority;
  uint8_t pad[24];
} spt_bid;                       /* 64B */

typedef struct {
  uint32_t magic, version;
  uint64_t map_size;
  uint32_t nslots, max_val, vec_dim;
  _Atomic uint32_t mop_mode;
  uint64_t slots_off, values_off, vectors_off;
  _Atomic uint64_t global_epoch;
  _Atomic uint32_t core_flags;
  _Atomic uint32_t user_flags;
  _Atomic uint64_t parse_failures;
  _Atomic uint64_t last_failure_epoch;
  _Atomic int64_t  bus_pid;      /* event bus owner pid (0 = unarmed) */
  _Atomic int32_t  bus_fd;       /* eventfd number IN THE OWNER PROCESS */
  _Atomic uint32_t bus_gen;      /* bumped each re-arm */
  _Atomic uint64_t dirty[SPT_DIRTY_WORDS];
  /* per bloom bit: 64-bit mask of signal groups pulsed when that label bit
   * is set on a written slot */
  _Atomic uint64_t bloom_groups[SPT_BLOOM_BITS];
  spt_bid bids[SPT_MAX_BIDS];                      /* 2048B */
  /* pad to 4096 then the signal arena fills the second 4K page */
  uint8_t pad_to_sig[4096 - 2048
                     - (2*4 + 8 + 4*4 + 3*8 + 8 + 2*4 + 2*8 + 8 + 4 + 4
                        + SPT_DIRTY_WORDS*8 + SPT_BLOOM_BITS*8)];
  spt_sigctr signals[SPT_SIGNAL_GROUPS];           /* 4096B */
} spt_hdr;

typedef struct {
  _Atomic uint64_t epoch;        /* seqlock: odd = writer active */
  _Atomic uint64_t hash;         /* 0 empty, 1 tombstone; publication point */
  _Atomic uint64_t labels;       /* bloom label bits */
  _Atomic uint64_t watcher_mask; /* signal groups pulsed on write */
  uint32_t val_len;
  _Atomic uint32_t flags;        /* type | user<<8 | system */
  int64_t ctime, atime;          /* spt_now() ticks */
  char key[SPT_KEY_MAX];
} __attribute__((aligned(64))) spt_slot;  /* 184 -> 192B, 64-aligned */

struct spt_store {
  spt_hdr  *h;
  spt_slot *slots;
  uint8_t  *values;
  float    *vectors;             /* NULL if vec_dim == 0 */
  uint8_t  *base;
  uint64_t  map_size;
  int       fd;
  uint32_t  flags;
  int       my_bus_fd;           /* this process's handle on the eventfd */
  uint32_t  my_bus_gen;
  int       bus_owner;           /* this handle armed the bus */
  char      name[256];
};

_Static_assert(sizeof(spt_sigctr) == 64, "sigctr cache line");
_Static_assert(sizeof(spt_bid) == 64, "bid size");
_Static_assert(sizeof(spt_slot) == SPT_SLOT_BYTES, "slot size");
_Static_assert(sizeof(spt_hdr) == SPT_HDR_BYTES, "header size");

/* FNV-1a 64-bit; 0/1 are reserved sentinels so remap them. */
static inline uint64_t spt_hash_key(const char *k) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (const unsigned char *p = (const unsigned char *)k; *p; ++p) {
    h ^= *p;
    h *= 0x100000001b3ull;
  }
  if (h <= SPT_TOMBSTONE) h += 0x9e3779b97f4a7c15ull;
  return h;
}

static inline uint8_t *slot_val(spt_store *st, uint32_t idx) {
  return st->values + (uint64_t)idx * st->h->max_val;
}
static inline float *slot_vec(spt_store *st, uint32_t idx) {
  return st->vectors ? st->vectors + (uint64_t)idx * st->h->vec_dim : NULL;
}

/* Probe for an existing key.  Returns slot index or -ENOENT.  Stops at the
 * first truly-empty slot (tombstones keep chains intact). */
int spt__probe_find(spt_store *st, const char *key, uint64_t h);
/* Probe for a write target: existing key, else first reusable
 * (tombstone/empty) along the chain.  Returns index or -ENOSPC.
 * *existed set to 1 when the key was already present. */
int spt__probe_claim(spt_store *st, const char *key, uint64_t h, int *existed);

/* Seqlock helpers.  Acquire CASes even->odd (else -EAGAIN); release
 * publishes even = acquired+1 and fires the post-write fanout. */
int  spt__lock(spt_slot *s, uint64_t *e_out);
void spt__unlock(spt_slot *s, uint64_t e_acquired);
void spt__fanout(spt_store *st, uint32_t idx, spt_slot *s);

uint64_t spt__now_us(void);
int spt__bus_ensure_open(spt_store *st);

#endif
