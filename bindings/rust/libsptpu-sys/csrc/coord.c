/* coord.c — coordination protocols of the splinter-tpu store:
 *   - signal arena: 64 cache-line-aligned atomic counters (pub/sub)
 *   - bloom-label -> signal-group routing
 *   - event bus: eventfd armed by an owner, re-opened cross-process via
 *     pidfd_getfd, with a 1024-bit dirty mask (slot idx % 1024)
 *   - shard bid table + deterministic read-only election + cooperative
 *     posix_madvise gated on sovereignty
 *   - raw tick clock (rdtsc / cntvct / CLOCK_MONOTONIC_RAW) + calibration
 *
 * Capability parity with the reference (splinter.c:889-1403, SURVEY.md
 * §2.1 L3 rows); TPU-first deltas: each bloom bit routes to a *mask* of
 * groups (reference: one group per bit), and spt_signal_wait gives FFI
 * callers a C-side blocking wait so the Python engine never spins.
 */
#include "internal.h"

#include <poll.h>
#include <stdio.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

/* ------------------------------------------------------------------ time */

uint64_t spt_now(void) {
#if defined(__x86_64__)
  uint32_t lo, hi;
  __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
#elif defined(__aarch64__)
  uint64_t v;
  __asm__ __volatile__("mrs %0, cntvct_el0" : "=r"(v));
  return v;
#else
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC_RAW, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
#endif
}

static uint64_t calibrate_ticks_per_us(void) {
  struct timespec a, b, req = {0, 2000000}; /* 2 ms */
  clock_gettime(CLOCK_MONOTONIC_RAW, &a);
  uint64_t t0 = spt_now();
  nanosleep(&req, NULL);
  uint64_t t1 = spt_now();
  clock_gettime(CLOCK_MONOTONIC_RAW, &b);
  uint64_t ns = (uint64_t)(b.tv_sec - a.tv_sec) * 1000000000ull +
                (uint64_t)(b.tv_nsec - a.tv_nsec);
  if (ns == 0 || t1 <= t0) return 1;
  uint64_t tpu = (t1 - t0) * 1000ull / ns;
  return tpu ? tpu : 1;
}

uint64_t spt_ticks_per_us(void) {
  static _Atomic uint64_t cached;
  uint64_t v = atomic_load_explicit(&cached, memory_order_relaxed);
  if (v) return v;
  v = calibrate_ticks_per_us();
  atomic_store_explicit(&cached, v, memory_order_relaxed);
  return v;
}

uint64_t spt__now_us(void) { return spt_now() / spt_ticks_per_us(); }

/* ---------------------------------------------------------- signal arena */

int spt_signal_pulse(spt_store *st, uint32_t group) {
  if (!st || group >= SPT_SIGNAL_GROUPS) return -EINVAL;
  atomic_fetch_add_explicit(&st->h->signals[group].v, 1,
                            memory_order_acq_rel);
  return 0;
}

uint64_t spt_signal_count(spt_store *st, uint32_t group) {
  if (!st || group >= SPT_SIGNAL_GROUPS) return 0;
  return atomic_load_explicit(&st->h->signals[group].v,
                              memory_order_acquire);
}

int spt_watch_register(spt_store *st, const char *key, uint32_t group) {
  if (!st || !key || group >= SPT_SIGNAL_GROUPS) return -EINVAL;
  int idx = spt_find_index(st, key);
  if (idx < 0) return idx;
  atomic_fetch_or_explicit(&st->slots[idx].watcher_mask, 1ull << group,
                           memory_order_acq_rel);
  return 0;
}

int spt_watch_unregister(spt_store *st, const char *key, uint32_t group) {
  if (!st || !key || group >= SPT_SIGNAL_GROUPS) return -EINVAL;
  int idx = spt_find_index(st, key);
  if (idx < 0) return idx;
  atomic_fetch_and_explicit(&st->slots[idx].watcher_mask,
                            ~(1ull << group), memory_order_acq_rel);
  return 0;
}

int spt_watch_label_register(spt_store *st, uint32_t bloom_bit,
                             uint32_t group) {
  if (!st || bloom_bit >= SPT_BLOOM_BITS || group >= SPT_SIGNAL_GROUPS)
    return -EINVAL;
  atomic_fetch_or_explicit(&st->h->bloom_groups[bloom_bit], 1ull << group,
                           memory_order_acq_rel);
  return 0;
}

int spt_watch_label_unregister(spt_store *st, uint32_t bloom_bit,
                               uint32_t group) {
  if (!st || bloom_bit >= SPT_BLOOM_BITS || group >= SPT_SIGNAL_GROUPS)
    return -EINVAL;
  atomic_fetch_and_explicit(&st->h->bloom_groups[bloom_bit],
                            ~(1ull << group), memory_order_acq_rel);
  return 0;
}

static void pulse_mask(spt_store *st, uint64_t groups) {
  while (groups) {
    uint32_t g = (uint32_t)__builtin_ctzll(groups);
    groups &= groups - 1;
    atomic_fetch_add_explicit(&st->h->signals[g].v, 1,
                              memory_order_acq_rel);
  }
}

static void bus_notify(spt_store *st, uint32_t idx);

/* Post-write fanout: pulse the slot's watcher groups, the groups bound to
 * each of its label bits, bump the store epoch, and ring the event bus. */
void spt__fanout(spt_store *st, uint32_t idx, spt_slot *s) {
  uint64_t groups =
      atomic_load_explicit(&s->watcher_mask, memory_order_acquire);
  uint64_t labels = atomic_load_explicit(&s->labels, memory_order_acquire);
  while (labels) {
    uint32_t b = (uint32_t)__builtin_ctzll(labels);
    labels &= labels - 1;
    groups |= atomic_load_explicit(&st->h->bloom_groups[b],
                                   memory_order_acquire);
  }
  pulse_mask(st, groups);
  atomic_fetch_add_explicit(&st->h->global_epoch, 1, memory_order_acq_rel);
  bus_notify(st, idx);
}

int spt_bump(spt_store *st, const char *key) {
  if (!st || !key) return -EINVAL;
  int idx = spt_find_index(st, key);
  if (idx < 0) return idx;
  spt__fanout(st, (uint32_t)idx, &st->slots[idx]);
  return 0;
}

int spt_signal_wait(spt_store *st, uint32_t group, uint64_t last,
                    int timeout_ms, uint64_t *count_out) {
  if (!st || group >= SPT_SIGNAL_GROUPS) return -EINVAL;
  uint64_t tpu = spt_ticks_per_us();
  uint64_t deadline =
      timeout_ms < 0 ? 0 : spt_now() + (uint64_t)timeout_ms * 1000 * tpu;
  struct timespec ts = {0, 1000000};
  for (;;) {
    uint64_t c = spt_signal_count(st, group);
    if (c != last) {
      if (count_out) *count_out = c;
      return 0;
    }
    if (timeout_ms >= 0 && spt_now() >= deadline) return -ETIMEDOUT;
    if (spt__bus_ensure_open(st) == 0)
      spt_bus_wait(st, 1);
    else
      nanosleep(&ts, NULL);
  }
}

/* -------------------------------------------------------------- event bus */

static void bus_notify(spt_store *st, uint32_t idx) {
  spt_hdr *h = st->h;
  if (atomic_load_explicit(&h->bus_pid, memory_order_acquire) == 0)
    return;                              /* bus not armed: free fast path */
  uint32_t bit = idx % (SPT_DIRTY_WORDS * 64);
  atomic_fetch_or_explicit(&h->dirty[bit / 64], 1ull << (bit % 64),
                           memory_order_acq_rel);
  if (spt__bus_ensure_open(st) == 0) {
    uint64_t one = 1;
    ssize_t r = write(st->my_bus_fd, &one, sizeof one);
    (void)r;
  }
}

int spt_bus_init(spt_store *st) {
  if (!st) return -EINVAL;
  int fd = eventfd(0, EFD_NONBLOCK);
  if (fd < 0) return -errno;
  spt_hdr *h = st->h;
  if (st->my_bus_fd >= 0) close(st->my_bus_fd);
  st->my_bus_fd = fd;
  atomic_store_explicit(&h->bus_fd, fd, memory_order_release);
  atomic_store_explicit(&h->bus_pid, (int64_t)getpid(),
                        memory_order_release);
  st->my_bus_gen =
      atomic_fetch_add_explicit(&h->bus_gen, 1, memory_order_acq_rel) + 1;
  st->bus_owner = 1;
  return 0;
}

#ifndef SYS_pidfd_open
#define SYS_pidfd_open 434
#endif
#ifndef SYS_pidfd_getfd
#define SYS_pidfd_getfd 438
#endif

int spt_bus_open(spt_store *st) {
  if (!st) return -EINVAL;
  spt_hdr *h = st->h;
  int64_t owner = atomic_load_explicit(&h->bus_pid, memory_order_acquire);
  if (owner == 0) return -ENOTCONN;
  if (owner == getpid()) {
    /* same process as the owner: the fd number in the header is valid
     * here — dup it for this handle */
    if (st->my_bus_fd >= 0) return 0;
    int fd = dup(atomic_load_explicit(&h->bus_fd, memory_order_acquire));
    if (fd < 0) return -EBADF;
    st->my_bus_fd = fd;
    st->my_bus_gen = atomic_load_explicit(&h->bus_gen, memory_order_acquire);
    return 0;
  }
  int pidfd = (int)syscall(SYS_pidfd_open, (pid_t)owner, 0);
  if (pidfd < 0) return errno == ENOSYS ? -ENOSYS : -errno;
  int target = atomic_load_explicit(&h->bus_fd, memory_order_acquire);
  int fd = (int)syscall(SYS_pidfd_getfd, pidfd, target, 0);
  int saved = errno;
  close(pidfd);
  if (fd < 0) return saved == ENOSYS ? -ENOSYS : -saved;
  if (st->my_bus_fd >= 0) close(st->my_bus_fd);
  st->my_bus_fd = fd;
  st->my_bus_gen = atomic_load_explicit(&h->bus_gen, memory_order_acquire);
  return 0;
}

int spt__bus_ensure_open(spt_store *st) {
  spt_hdr *h = st->h;
  if (atomic_load_explicit(&h->bus_pid, memory_order_acquire) == 0)
    return -ENOTCONN;
  uint32_t gen = atomic_load_explicit(&h->bus_gen, memory_order_acquire);
  if (st->my_bus_fd >= 0 && st->my_bus_gen == gen) return 0;
  if (st->my_bus_fd < 0 && st->my_bus_gen == gen && gen != 0)
    return -ENOSYS;   /* attach already failed for this arming: don't
                         re-run pidfd syscalls on every write */
  int rc = spt_bus_open(st);
  if (rc < 0) st->my_bus_gen = gen;   /* cache the failure per-generation */
  return rc;
}

int spt_bus_wait(spt_store *st, int timeout_ms) {
  if (!st) return -EINVAL;
  int rc = spt__bus_ensure_open(st);
  if (rc < 0) return rc;
  struct pollfd p = {.fd = st->my_bus_fd, .events = POLLIN};
  int n = poll(&p, 1, timeout_ms);
  if (n < 0) return -errno;
  if (n == 0) return -ETIMEDOUT;
  uint64_t v;
  ssize_t r = read(st->my_bus_fd, &v, sizeof v); /* drain the counter */
  (void)r;
  return 0;
}

int spt_bus_close(spt_store *st) {
  if (!st) return -EINVAL;
  spt_hdr *h = st->h;
  if (st->my_bus_fd >= 0) {
    if (st->bus_owner &&
        atomic_load_explicit(&h->bus_pid, memory_order_acquire) ==
            getpid()) {
      atomic_store_explicit(&h->bus_pid, 0, memory_order_release);
      atomic_store_explicit(&h->bus_fd, -1, memory_order_release);
    }
    close(st->my_bus_fd);
    st->my_bus_fd = -1;
    st->bus_owner = 0;
  }
  return 0;
}

int spt_bus_drain(spt_store *st, uint64_t dirty_out[SPT_DIRTY_WORDS]) {
  if (!st || !dirty_out) return -EINVAL;
  int bits = 0;
  for (int w = 0; w < SPT_DIRTY_WORDS; w++) {
    uint64_t v = atomic_exchange_explicit(&st->h->dirty[w], 0,
                                          memory_order_acq_rel);
    dirty_out[w] = v;
    bits += __builtin_popcountll(v);
  }
  return bits;
}

int spt_bus_peek(spt_store *st, uint64_t dirty_out[SPT_DIRTY_WORDS]) {
  if (!st || !dirty_out) return -EINVAL;
  int bits = 0;
  for (int w = 0; w < SPT_DIRTY_WORDS; w++) {
    uint64_t v =
        atomic_load_explicit(&st->h->dirty[w], memory_order_acquire);
    dirty_out[w] = v;
    bits += __builtin_popcountll(v);
  }
  return bits;
}

/* ----------------------------------------------------- shard bid election */

static int bid_live(const spt_bid *b, uint64_t now_us) {
  if (atomic_load_explicit((_Atomic int64_t *)&b->pid,
                           memory_order_acquire) == 0)
    return 0;
  uint64_t dur =
      atomic_load_explicit((_Atomic uint64_t *)&b->duration_us,
                           memory_order_acquire);
  if (dur == 0) return 0;                    /* born expired */
  uint64_t at = atomic_load_explicit((_Atomic uint64_t *)&b->claimed_at,
                                     memory_order_acquire);
  return now_us < at + dur;
}

int spt_shard_claim_ex(spt_store *st, uint64_t shard_id, int64_t pid,
                       spt_advice_t intent, uint32_t priority,
                       uint64_t duration_us, uint64_t claimed_at_us) {
  if (!st) return -EINVAL;
  for (int i = 0; i < SPT_MAX_BIDS; i++) {
    spt_bid *b = &st->h->bids[i];
    int64_t expect = 0;
    if (atomic_compare_exchange_strong_explicit(&b->pid, &expect, pid,
                                                memory_order_acq_rel,
                                                memory_order_acquire)) {
      atomic_store_explicit(&b->shard_id, shard_id, memory_order_relaxed);
      atomic_store_explicit(&b->intent, (uint32_t)intent,
                            memory_order_relaxed);
      atomic_store_explicit(&b->priority, priority, memory_order_relaxed);
      atomic_store_explicit(&b->duration_us, duration_us,
                            memory_order_relaxed);
      atomic_store_explicit(&b->claimed_at, claimed_at_us,
                            memory_order_release);
      return i;
    }
  }
  return -ENOSPC;
}

int spt_shard_claim(spt_store *st, uint64_t shard_id, spt_advice_t intent,
                    uint32_t priority, uint64_t duration_us) {
  return spt_shard_claim_ex(st, shard_id, (int64_t)getpid(), intent,
                            priority, duration_us, spt__now_us());
}

int spt_shard_rebid(spt_store *st, int bid_idx) {
  if (!st || bid_idx < 0 || bid_idx >= SPT_MAX_BIDS) return -EINVAL;
  spt_bid *b = &st->h->bids[bid_idx];
  if (atomic_load_explicit(&b->pid, memory_order_acquire) == 0)
    return -ENOENT;
  atomic_store_explicit(&b->claimed_at, spt__now_us(),
                        memory_order_release);
  return 0;
}

int spt_shard_release(spt_store *st, int bid_idx) {
  if (!st || bid_idx < 0 || bid_idx >= SPT_MAX_BIDS) return -EINVAL;
  atomic_store_explicit(&st->h->bids[bid_idx].pid, 0,
                        memory_order_release);
  return 0;
}

/* Deterministic, read-only election over the bid table:
 *   - only live (unexpired, pid!=0) bids compete;
 *   - DONTNEED bids ("soft bumpers") cannot win while any live non-DONTNEED
 *     bid exists;
 *   - winner = highest priority, ties -> earliest claimed_at -> lowest pid.
 * Every process computes the same winner from the same table. */
int spt_shard_election(spt_store *st) {
  if (!st) return -EINVAL;
  uint64_t now = spt__now_us();
  int winner = -1;
  int winner_bumper = 0;
  uint32_t w_prio = 0;
  uint64_t w_at = 0;
  int64_t w_pid = 0;
  for (int i = 0; i < SPT_MAX_BIDS; i++) {
    spt_bid *b = &st->h->bids[i];
    if (!bid_live(b, now)) continue;
    int is_bumper =
        atomic_load_explicit(&b->intent, memory_order_acquire) ==
        (uint32_t)SPT_ADV_DONTNEED;
    uint32_t prio = atomic_load_explicit(&b->priority, memory_order_acquire);
    uint64_t at = atomic_load_explicit(&b->claimed_at, memory_order_acquire);
    int64_t pid = atomic_load_explicit(&b->pid, memory_order_acquire);
    int better;
    if (winner < 0) better = 1;
    else if (winner_bumper && !is_bumper) better = 1;   /* real beats bumper */
    else if (!winner_bumper && is_bumper) better = 0;
    else if (prio != w_prio) better = prio > w_prio;
    else if (at != w_at) better = at < w_at;
    else better = pid < w_pid;
    if (better) {
      winner = i;
      winner_bumper = is_bumper;
      w_prio = prio;
      w_at = at;
      w_pid = pid;
    }
  }
  return winner >= 0 ? winner : -ENOENT;
}

int spt_bid_info(spt_store *st, int bid_idx, spt_bid_view *out) {
  if (!st || !out || bid_idx < 0 || bid_idx >= SPT_MAX_BIDS) return -EINVAL;
  spt_bid *b = &st->h->bids[bid_idx];
  out->pid = atomic_load_explicit(&b->pid, memory_order_acquire);
  out->shard_id = atomic_load_explicit(&b->shard_id, memory_order_acquire);
  out->claimed_at =
      atomic_load_explicit(&b->claimed_at, memory_order_acquire);
  out->duration = atomic_load_explicit(&b->duration_us, memory_order_acquire);
  out->intent = atomic_load_explicit(&b->intent, memory_order_acquire);
  out->priority = atomic_load_explicit(&b->priority, memory_order_acquire);
  out->live = bid_live(b, spt__now_us());
  return 0;
}

static int advice_to_posix(spt_advice_t a) {
  switch (a) {
    case SPT_ADV_SEQUENTIAL: return POSIX_MADV_SEQUENTIAL;
    case SPT_ADV_RANDOM:     return POSIX_MADV_RANDOM;
    case SPT_ADV_WILLNEED:   return POSIX_MADV_WILLNEED;
    case SPT_ADV_DONTNEED:   return POSIX_MADV_DONTNEED;
    default:                 return POSIX_MADV_NORMAL;
  }
}

int spt_madvise(spt_store *st, int bid_idx, uint64_t offset, uint64_t len,
                spt_advice_t advice, int timeout_ms) {
  if (!st || bid_idx < 0 || bid_idx >= SPT_MAX_BIDS) return -EINVAL;
  spt_bid *b = &st->h->bids[bid_idx];
  if (atomic_load_explicit(&b->pid, memory_order_acquire) !=
      (int64_t)getpid())
    return -EPERM;                          /* must hold the bid yourself */
  if (!bid_live(b, spt__now_us())) return -EPERM;
  if (len == 0) { offset = 0; len = st->map_size; }
  if (offset + len > st->map_size) return -EINVAL;
  /* page-align the window */
  uint64_t page = 4096;
  uint64_t start = offset & ~(page - 1);
  uint64_t end = (offset + len + page - 1) & ~(page - 1);

  uint64_t tpu = spt_ticks_per_us();
  uint64_t deadline =
      timeout_ms <= 0 ? 0 : spt_now() + (uint64_t)timeout_ms * 1000 * tpu;
  struct timespec ts = {0, 5000000};        /* 5 ms */
  for (;;) {
    int sovereign = spt_shard_election(st);
    if (sovereign == bid_idx) {
      int rc = posix_madvise(st->base + start, end - start,
                             advice_to_posix(advice));
      return rc == 0 ? 0 : -rc;
    }
    if (timeout_ms == 0) return -EAGAIN;    /* defer */
    if (timeout_ms > 0 && spt_now() >= deadline) return -ETIMEDOUT;
    if (spt__bus_ensure_open(st) == 0)
      spt_bus_wait(st, 5);
    else
      nanosleep(&ts, NULL);
  }
}
