/* sptpu.h — public C ABI of the splinter-tpu native core store.
 *
 * A lock-free, seqlock-protected, shared-memory key/value + embedding-vector
 * store designed for a TPU-VM host.  Capability parity with the reference
 * store (splinterhq/libsplinter: splinter.h, splinter.c — see SURVEY.md §2.1),
 * re-designed TPU-first:
 *
 *   - The embedding vectors live in a SEPARATE, CONTIGUOUS float lane
 *     (struct-of-arrays) instead of inline in each slot
 *     (reference keeps them inline: splinter.h:252-254).  A contiguous
 *     (nslots, dim) float32 matrix is what the JAX/Pallas tier stages to HBM
 *     with one DMA; per-slot epochs still govern both value and vector.
 *   - One library, runtime backend selection (shm vs file-backed) instead of
 *     the reference's two compile-time variants (CMakeLists.txt:94-114).
 *   - Negative-errno return discipline (-EAGAIN, -ENOENT, ...) instead of
 *     -1 + errno: FFI callers (ctypes) read the code straight off the return.
 *   - Index-based accessors (slot index <-> key) so the batching engine can
 *     work directly off the event-bus dirty mask without re-hashing keys.
 *   - Tombstoned open addressing: unset leaves a reusable tombstone so probe
 *     chains stay intact and lookup misses stop at the first truly-empty
 *     slot (the reference's probe scans the whole table).
 *
 * Concurrency contract (same protocol as the reference, splinter.h:368-412):
 *   per-slot 64-bit epoch seqlock.  Odd epoch = writer active.  Writers CAS
 *   epoch e -> e+1 (must be even), publish, then store e+2.  Readers load the
 *   epoch before and after a read; odd or changed => retry (-EAGAIN).
 *   -EAGAIN is a SIGNAL, not an error: the caller retries.
 *   A writer that dies mid-write leaves an odd epoch; spt_retrain() is the
 *   sanctioned recovery (drives the epoch backward — "revalidate me").
 */
#ifndef SPTPU_H
#define SPTPU_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

#define SPT_MAGIC           0x53505455u /* "SPTU" */
#define SPT_FORMAT_VERSION  1u

#define SPT_KEY_MAX         128   /* bytes incl. NUL */
#define SPT_SIGNAL_GROUPS   64
#define SPT_MAX_BIDS        32
#define SPT_DIRTY_WORDS     16    /* 1024 dirty bits: slot_idx % 1024 */
#define SPT_BLOOM_BITS      64

/* --- open/create flags ------------------------------------------------- */
#define SPT_BACKEND_SHM     0u        /* POSIX shm (default) */
#define SPT_BACKEND_FILE    (1u<<0)   /* regular file mapping = persistence */
#define SPT_CREATE_EXCL     (1u<<1)   /* create: fail if store exists      */

/* --- slot type flags (low byte of slot->flags) ------------------------- */
#define SPT_T_VOID      0x00u
#define SPT_T_BIGINT    0x01u
#define SPT_T_BIGUINT   0x02u
#define SPT_T_JSON      0x04u
#define SPT_T_BINARY    0x08u
#define SPT_T_IMGDATA   0x10u
#define SPT_T_AUDIO     0x20u
#define SPT_T_VARTEXT   0x40u
#define SPT_T_MASK      0xFFu
/* bits 8..15: per-slot user flags; bit 16: system scratchpad */
#define SPT_F_USER_SHIFT 8
#define SPT_F_USER_MASK  0xFF00u
#define SPT_F_SYSTEM     (1u<<16)

/* --- atomic integer ops (BIGUINT slots) -------------------------------- */
typedef enum {
  SPT_IOP_AND = 0, SPT_IOP_OR, SPT_IOP_XOR, SPT_IOP_NOT,
  SPT_IOP_INC, SPT_IOP_DEC, SPT_IOP_ADD, SPT_IOP_SUB,
} spt_iop_t;

/* --- cooperative advisement intents (map to posix_madvise) ------------- */
typedef enum {
  SPT_ADV_NORMAL = 0, SPT_ADV_SEQUENTIAL, SPT_ADV_RANDOM,
  SPT_ADV_WILLNEED, SPT_ADV_DONTNEED,
} spt_advice_t;

/* --- mop (scrub) modes -------------------------------------------------- */
#define SPT_MOP_OFF     0u
#define SPT_MOP_HYBRID  1u   /* zero stale tail rounded to 64B slop (default) */
#define SPT_MOP_FULL    2u   /* zero the whole value region on every write    */

typedef struct spt_store spt_store;

/* Snapshot views (plain structs, torn-read-safe copies). */
typedef struct {
  uint32_t magic, version;
  uint32_t nslots, max_val, vec_dim, mop_mode;
  uint64_t map_size, global_epoch;
  uint32_t core_flags, user_flags;
  uint64_t parse_failures, last_failure_epoch;
  int64_t  bus_pid;
  uint32_t used_slots;      /* live keys at snapshot time */
} spt_header_view;

typedef struct {
  uint64_t epoch, hash, labels, watcher_mask;
  uint32_t val_len, flags;
  int64_t  ctime, atime;
  int32_t  index;
  char     key[SPT_KEY_MAX];
} spt_slot_view;

typedef struct {
  int64_t  pid;
  uint64_t shard_id, claimed_at, duration;
  uint32_t intent, priority;
  int32_t  live;            /* 1 if unexpired at snapshot time */
} spt_bid_view;

/* ---- lifecycle --------------------------------------------------------- */
spt_store *spt_create(const char *name, uint32_t nslots, uint32_t max_val,
                      uint32_t vec_dim, uint32_t flags);
spt_store *spt_open(const char *name, uint32_t flags);
/* Open + mbind(MPOL_BIND) the mapping to a NUMA node (reference parity:
 * splinter.c:250-264).  *bind_rc gets 0 or -errno for the bind itself;
 * the open succeeds either way (bind failure is advisory). */
spt_store *spt_open_numa(const char *name, uint32_t flags, int node,
                         int *bind_rc);
int  spt_close(spt_store *st);                    /* unmap; store survives  */
int  spt_unlink(const char *name, uint32_t flags);/* destroy backing object */

/* ---- geometry / raw access (for numpy/JAX zero-copy staging) ----------- */
uint32_t spt_nslots(const spt_store *st);
uint32_t spt_max_val(const spt_store *st);
uint32_t spt_vec_dim(const spt_store *st);
void    *spt_vec_lane(spt_store *st);    /* base of (nslots, dim) f32 matrix */
void    *spt_values_base(spt_store *st);
int      spt_last_error(void);

/* ---- KV ops ------------------------------------------------------------ */
int spt_set(spt_store *st, const char *key, const void *val, uint32_t len);
/* buf==NULL: size query (len_out set, no copy). 0 ok / -EAGAIN / -ENOENT */
int spt_get(spt_store *st, const char *key, void *buf, uint32_t cap,
            uint32_t *len_out);
int spt_unset(spt_store *st, const char *key);
int spt_append(spt_store *st, const char *key, const void *val, uint32_t len);
/* Copy up to max_keys NUL-terminated keys into keys (stride SPT_KEY_MAX).
 * Returns count. */
int spt_list(spt_store *st, char *keys, uint32_t max_keys);
/* Block until the slot's epoch changes from its value at call time.
 * timeout_ms<0: wait forever. 0 ok / -ETIMEDOUT / -ENOENT. */
int spt_poll(spt_store *st, const char *key, int timeout_ms);

/* Zero-copy read protocol: capture a raw pointer + the epoch; compute; then
 * verify the epoch is unchanged (spt_epoch_at) before trusting the bytes. */
int spt_get_raw(spt_store *st, const char *key, const void **ptr,
                uint32_t *len_out, uint64_t *epoch_out);

/* ---- index-based access (engine fast path) ----------------------------- */
int      spt_find_index(spt_store *st, const char *key);  /* idx / -ENOENT */
int      spt_key_at(spt_store *st, uint32_t idx, char *key_out);
uint64_t spt_epoch_at(spt_store *st, uint32_t idx);
int      spt_get_at(spt_store *st, uint32_t idx, void *buf, uint32_t cap,
                    uint32_t *len_out);
uint64_t spt_labels_at(spt_store *st, uint32_t idx);
uint32_t spt_flags_at(spt_store *st, uint32_t idx);

/* ---- snapshots --------------------------------------------------------- */
int spt_header_snapshot(spt_store *st, spt_header_view *out);
int spt_slot_snapshot(spt_store *st, const char *key, spt_slot_view *out);
int spt_slot_snapshot_at(spt_store *st, uint32_t idx, spt_slot_view *out);

/* ---- typed slots ------------------------------------------------------- */
/* Setting SPT_T_BIGUINT on an ASCII-digits slot converts it in place to a
 * host-endian uint64 (val_len becomes 8) — "BIGUINT promotion". */
int spt_set_type(spt_store *st, const char *key, uint32_t type_flag);
int spt_get_type(spt_store *st, const char *key, uint32_t *type_out);
/* -EPROTOTYPE unless the slot is SPT_T_BIGUINT. */
int spt_integer_op(spt_store *st, const char *key, spt_iop_t op,
                   uint64_t operand, uint64_t *result_out);

/* ---- tandem (ordered) keys: base, base.1, base.2, ... ------------------ */
#define SPT_ORDER_SEP "."
int spt_tandem_set(spt_store *st, const char *base, uint32_t order,
                   const void *val, uint32_t len);
int spt_tandem_get(spt_store *st, const char *base, uint32_t order,
                   void *buf, uint32_t cap, uint32_t *len_out);
int spt_tandem_unset(spt_store *st, const char *base, uint32_t max_order);
int spt_tandem_count(spt_store *st, const char *base);

/* ---- bloom labels ------------------------------------------------------ */
int      spt_label_or(spt_store *st, const char *key, uint64_t mask);
int      spt_label_andnot(spt_store *st, const char *key, uint64_t mask);
int      spt_get_labels(spt_store *st, const char *key, uint64_t *out);
/* slot indices whose (labels & mask) == mask; returns count */
int      spt_enumerate(spt_store *st, uint64_t mask, uint32_t *idx_out,
                       uint32_t max_out);

/* ---- signal arena (64 cache-line counters, pub/sub) -------------------- */
int      spt_watch_register(spt_store *st, const char *key, uint32_t group);
int      spt_watch_unregister(spt_store *st, const char *key, uint32_t group);
/* Bind a bloom BIT INDEX (0..63) to a signal group: any write to a slot
 * carrying that label bit pulses the group. */
int      spt_watch_label_register(spt_store *st, uint32_t bloom_bit,
                                  uint32_t group);
int      spt_watch_label_unregister(spt_store *st, uint32_t bloom_bit,
                                    uint32_t group);
uint64_t spt_signal_count(spt_store *st, uint32_t group);
int      spt_signal_pulse(spt_store *st, uint32_t group);
/* Pulse a key's watcher groups + label-bound groups WITHOUT writing ("bump"). */
int      spt_bump(spt_store *st, const char *key);
/* Block until group count != last (returns new count via out).
 * Uses the event bus when armed, 1 ms sleep loop otherwise. */
int      spt_signal_wait(spt_store *st, uint32_t group, uint64_t last,
                         int timeout_ms, uint64_t *count_out);

/* ---- event bus (eventfd + dirty mask) ---------------------------------- */
int spt_bus_init(spt_store *st);   /* become bus owner (arm the eventfd)    */
int spt_bus_open(spt_store *st);   /* peer: re-open owner fd via pidfd_getfd;
                                      -ENOTCONN if no owner; -ENOSYS if the
                                      kernel lacks pidfd (callers fall back
                                      to polling spt_bus_drain) */
int spt_bus_wait(spt_store *st, int timeout_ms); /* 0 woke / -ETIMEDOUT */
int spt_bus_close(spt_store *st);
/* Atomically fetch-and-clear the 1024-bit dirty mask (16 words). Returns
 * number of set bits. Bit b = some slot with idx%1024==b was written. */
int spt_bus_drain(spt_store *st, uint64_t dirty_out[SPT_DIRTY_WORDS]);
int spt_bus_peek(spt_store *st, uint64_t dirty_out[SPT_DIRTY_WORDS]);

/* ---- shard bids & cooperative advisement ------------------------------- */
/* Claim a bid slot. duration_us==0 => bid is born expired (test hook).
 * Returns bid index 0..31, or -ENOSPC. */
int spt_shard_claim(spt_store *st, uint64_t shard_id, spt_advice_t intent,
                    uint32_t priority, uint64_t duration_us);
/* Forge a bid for an arbitrary pid/claimed_at — deterministic multi-process
 * election tests without spawning processes (reference: splinter.h:1142-1152). */
int spt_shard_claim_ex(spt_store *st, uint64_t shard_id, int64_t pid,
                       spt_advice_t intent, uint32_t priority,
                       uint64_t duration_us, uint64_t claimed_at_us);
int spt_shard_rebid(spt_store *st, int bid_idx);
int spt_shard_release(spt_store *st, int bid_idx);
/* Deterministic, read-only election: highest priority live bid wins; ties ->
 * earliest claimed_at -> lowest pid.  DONTNEED bids ("soft bumpers") cannot
 * win while any live non-DONTNEED bid exists.  Returns winning bid index or
 * -ENOENT when no live bids. */
int spt_shard_election(spt_store *st);
int spt_bid_info(spt_store *st, int bid_idx, spt_bid_view *out);

/* Cooperative madvise over the arena: only the election sovereign actually
 * issues posix_madvise.  offset/len in bytes relative to the mapping (len==0
 * => whole mapping).  timeout_ms==0 => -EAGAIN if not sovereign (defer);
 * >0 bounded wait; <0 wait forever.  Caller must hold live bid bid_idx. */
int spt_madvise(spt_store *st, int bid_idx, uint64_t offset, uint64_t len,
                spt_advice_t advice, int timeout_ms);

/* ---- mop / purge ------------------------------------------------------- */
int      spt_set_mop(spt_store *st, uint32_t mode);
uint32_t spt_get_mop(spt_store *st);
int      spt_purge(spt_store *st);  /* store-wide stale-tail sweep */

/* ---- recovery ---------------------------------------------------------- */
/* Backward-epoch recovery of a slot stuck odd by a dead writer: forces the
 * epoch to 3 (odd), zeroes the vector, then publishes epoch 4.  A BACKWARD
 * epoch tells observers "revalidate me". */
int spt_retrain(spt_store *st, const char *key);

/* ---- system keys & user flags ------------------------------------------ */
int spt_set_system(spt_store *st, const char *key); /* BINARY scratchpad
                                                       spanning max_val */
int spt_slot_usr_set(spt_store *st, const char *key, uint8_t bits);
int spt_slot_usr_get(spt_store *st, const char *key, uint8_t *out);
int spt_config_set_user(spt_store *st, uint32_t bits);   /* low 4 bits */
uint32_t spt_config_get_user(spt_store *st);

/* ---- timestamps -------------------------------------------------------- */
uint64_t spt_now(void);          /* raw tick counter (rdtsc/cntvct/monotonic) */
uint64_t spt_ticks_per_us(void); /* calibrated once per process */
/* Backfill a slot's ctime/atime to (now - ticks_ago). which: 0 ctime,
 * 1 atime, 2 both. */
int spt_stamp(spt_store *st, const char *key, int which, uint64_t ticks_ago);

/* ---- embedding vector lane --------------------------------------------- */
int spt_vec_set(spt_store *st, const char *key, const float *vec,
                uint32_t dim);
int spt_vec_get(spt_store *st, const char *key, float *out, uint32_t dim);
int spt_vec_set_at(spt_store *st, uint32_t idx, const float *vec,
                   uint32_t dim);
int spt_vec_get_at(spt_store *st, uint32_t idx, float *out, uint32_t dim);
/* Write a batch of vectors, each gated on its captured epoch: vector i is
 * committed iff slot rows[i] still has epoch epochs[i] (and, if write_once,
 * a currently all-zero vector).  Per-row results: 0 committed / -ESTALE
 * raced / -EEXIST write-once skip.  Returns number committed.  This is the
 * TPU micro-batcher's commit path (reference checks epoch per key serially:
 * splinference.cpp:275-287). */
int spt_vec_commit_batch(spt_store *st, const uint32_t *rows,
                         const uint64_t *epochs, const float *vecs,
                         uint32_t n, uint32_t dim, int write_once,
                         int32_t *results);

/* Bulk epoch snapshot: one acquire load per slot into out (nslots u64).
 * Returns nslots.  Consecutive snapshots diffed on the host give the
 * changed-row set — the device-lane cache's dirty detector. */
int spt_epochs(spt_store *st, uint64_t *out);
/* Torn-safe gather of vector rows: per row, epoch-before (odd => skip),
 * memcpy into out[i*dim], epoch-after recheck.  epochs_out[i] = the stable
 * epoch (0 for a stable never-written slot, whose row is zeros), or
 * SPT_GATHER_TORN if the row was mid-write / contended / out of range
 * (caller retries next pass).  Returns the number of stable rows. */
#define SPT_GATHER_TORN UINT64_MAX
int spt_vec_gather(spt_store *st, const uint32_t *rows, uint32_t n,
                   float *out, uint64_t *epochs_out);

/* ---- diagnostics ------------------------------------------------------- */
int spt_report_parse_failure(spt_store *st);

/* Build identity stamped at compile time (git describe + UTC date),
 * surfaced by the CLI `caps` command.  Parity with the reference's
 * generated build hash (scripts/genbuildh -> build.h, surfaced by its
 * caps module). */
const char *spt_build_id(void);

/* ---- host tokenizer (wptok.c) ------------------------------------------
 * Native tokenization for the embedding daemon's hot path (the
 * reference tokenizes natively via llama.cpp, splinference.cpp:209-217).
 * ASCII fast path: inputs with bytes >= 0x80 return -EDOM and the
 * Python caller falls back to its full-Unicode implementation. */
typedef struct spt_wptok spt_wptok;

/* WordPiece over a BERT-family vocab (greedy longest-match, "##"
 * continuations, optional ASCII lowercasing).  Requires [CLS]/[SEP]/
 * [UNK] in the vocab ([PAD] defaults to id 0); returns NULL otherwise. */
spt_wptok *spt_wptok_create(const char *const *tokens, uint32_t n_tokens,
                            int lower);
/* Hashed-vocabulary fallback: word -> 4 + fnv1a64(word) % (vocab-4);
 * ids 0..3 = PAD/CLS/SEP/UNK.  Mirrors models/tokenizer.HashTokenizer. */
spt_wptok *spt_wptok_create_hashed(uint32_t vocab_size, int lower);
void spt_wptok_destroy(spt_wptok *t);

/* Encode one text: out = [CLS] ids... [SEP].  Returns the id count,
 * -EDOM for non-ASCII input (use the host-language fallback), -ERANGE
 * when cap is too small (cap >= strlen(text)+3 always suffices). */
int spt_wptok_encode(const spt_wptok *t, const char *text, uint32_t *out,
                     uint32_t cap);
/* Encode+pad a batch into ids (count x max_len, padded with [PAD]) and
 * lens (count).  Rows the fast path cannot handle (non-ASCII) get
 * lens[i] = UINT32_MAX and an all-PAD row — re-encode those in the
 * caller.  Truncation keeps the trailing [SEP] (tokenizer.py parity). */
int spt_wptok_encode_batch(const spt_wptok *t, const char *const *texts,
                           uint32_t count, uint32_t max_len,
                           uint32_t *ids, uint32_t *lens);

#ifdef __cplusplus
}
#endif
#endif /* SPTPU_H */
