/* wptok.c — native host tokenizer for the embedding engine.
 *
 * The reference tokenizes in native code via llama.cpp's C tokenizer
 * (splinference.cpp:209-217).  The TPU framework's embedding daemon
 * must feed a chip that sustains >10k embeddings/sec; a pure-Python
 * WordPiece loop tops out around 3-24k texts/sec and becomes the
 * pipeline bottleneck, so the hot path lives here:
 *
 *   - WordPiece mode: greedy longest-match-first with "##"
 *     continuations over a caller-supplied vocab (BERT family), exact
 *     parity with models/tokenizer.py's pure-Python implementation;
 *   - hashed mode: FNV-1a 64 word hashing into [4, vocab) — parity
 *     with HashTokenizer, the no-vocab fallback;
 *   - batch API: one call tokenizes + pads a whole micro-batch
 *     (ctypes releases the GIL for the duration).
 *
 * ASCII fast path by contract: inputs containing bytes >= 0x80 return
 * -EDOM and the Python caller falls back to its full-Unicode
 * implementation (NFD strip, Unicode categories).  The split rules
 * below mirror Python str semantics exactly for ASCII:
 *   space = 0x09..0x0D, 0x1C..0x1F, 0x20   (str.isspace)
 *   punct = 33..47, 58..64, 91..96, 123..126
 *   other control bytes join words (same as Python, where category Cc
 *   is neither space nor punctuation)
 */
#define _GNU_SOURCE
#include <errno.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "sptpu.h"

#define WPT_MAX_WORD 100u        /* chars per word before UNK (Python parity) */

typedef struct {
  uint32_t off;                  /* into blob */
  uint32_t id;
  uint16_t len;
  uint16_t used;
} wpt_entry;

struct spt_wptok {
  /* wordpiece mode */
  char *blob;                    /* all vocab bytes, concatenated */
  wpt_entry *table;              /* open-addressing, power-of-2 */
  uint32_t cap;                  /* table capacity */
  /* both modes */
  uint32_t vocab_size;
  uint32_t cls_id, sep_id, pad_id, unk_id;
  int lower;
  int hashed;                    /* 1 = FNV word-hash mode, no vocab */
};

static inline int wpt_isspace(unsigned char c) {
  return (c >= 0x09 && c <= 0x0D) || (c >= 0x1C && c <= 0x20);
}

static inline int wpt_ispunct(unsigned char c) {
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
         (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

static inline uint64_t fnv1a64(const char *s, size_t n, uint64_t h) {
  for (size_t i = 0; i < n; i++)
    h = (h ^ (unsigned char)s[i]) * 0x100000001b3ULL;
  return h;
}
#define FNV_BASIS 0xcbf29ce484222325ULL

/* -------------------------------------------------------------- lookup */

static int wpt_find(const spt_wptok *t, const char *piece, size_t len,
                    int continuation, uint32_t *id_out) {
  uint64_t h = FNV_BASIS;
  if (continuation) h = fnv1a64("##", 2, h);
  h = fnv1a64(piece, len, h);
  size_t total = len + (continuation ? 2 : 0);
  uint32_t mask = t->cap - 1;
  for (uint32_t i = (uint32_t)h & mask;; i = (i + 1) & mask) {
    const wpt_entry *e = &t->table[i];
    if (!e->used) return 0;
    if (e->len == total) {
      const char *tok = t->blob + e->off;
      if (continuation) {
        if (tok[0] == '#' && tok[1] == '#' &&
            memcmp(tok + 2, piece, len) == 0) {
          *id_out = e->id;
          return 1;
        }
      } else if (memcmp(tok, piece, len) == 0) {
        *id_out = e->id;
        return 1;
      }
    }
  }
}

static int wpt_insert(spt_wptok *t, const char *tok, size_t len,
                      uint32_t id, uint32_t off) {
  uint64_t h = fnv1a64(tok, len, FNV_BASIS);
  uint32_t mask = t->cap - 1;
  for (uint32_t i = (uint32_t)h & mask;; i = (i + 1) & mask) {
    wpt_entry *e = &t->table[i];
    if (!e->used) {
      e->off = off;
      e->len = (uint16_t)len;
      e->id = id;
      e->used = 1;
      return 0;
    }
    /* duplicate tokens: first id wins (dict semantics differ — Python
     * keeps the LAST duplicate's index; real vocabs have no dups, and
     * the tokenizer_golden tests pin the behavior on trained vocabs */
    if (e->len == len && memcmp(t->blob + e->off, tok, len) == 0)
      return 0;
  }
}

/* ------------------------------------------------------------ creation */

void spt_wptok_destroy(spt_wptok *t) {
  if (!t) return;
  free(t->blob);
  free(t->table);
  free(t);
}

spt_wptok *spt_wptok_create(const char *const *tokens, uint32_t n,
                            int lower) {
  if (!tokens || n == 0) return NULL;
  spt_wptok *t = calloc(1, sizeof(*t));
  if (!t) return NULL;
  t->lower = lower;
  t->vocab_size = n;
  t->hashed = 0;

  size_t blob_sz = 0;
  for (uint32_t i = 0; i < n; i++) blob_sz += strlen(tokens[i]);
  t->blob = malloc(blob_sz ? blob_sz : 1);
  uint32_t cap = 16;
  while (cap < 2 * n) cap <<= 1;
  t->cap = cap;
  t->table = calloc(cap, sizeof(wpt_entry));
  if (!t->blob || !t->table) {
    spt_wptok_destroy(t);
    return NULL;
  }

  t->cls_id = t->sep_id = t->unk_id = UINT32_MAX;
  t->pad_id = 0;
  uint32_t off = 0;
  for (uint32_t i = 0; i < n; i++) {
    size_t len = strlen(tokens[i]);
    if (len > UINT16_MAX) {
      spt_wptok_destroy(t);
      return NULL;
    }
    memcpy(t->blob + off, tokens[i], len);
    wpt_insert(t, t->blob + off, len, i, off);
    if (len == 5 && memcmp(tokens[i], "[CLS]", 5) == 0) t->cls_id = i;
    if (len == 5 && memcmp(tokens[i], "[SEP]", 5) == 0) t->sep_id = i;
    if (len == 5 && memcmp(tokens[i], "[UNK]", 5) == 0) t->unk_id = i;
    if (len == 5 && memcmp(tokens[i], "[PAD]", 5) == 0) t->pad_id = i;
    off += (uint32_t)len;
  }
  if (t->cls_id == UINT32_MAX || t->sep_id == UINT32_MAX ||
      t->unk_id == UINT32_MAX) {
    spt_wptok_destroy(t);          /* not a BERT-family vocab */
    return NULL;
  }
  return t;
}

spt_wptok *spt_wptok_create_hashed(uint32_t vocab_size, int lower) {
  if (vocab_size < 8) return NULL;
  spt_wptok *t = calloc(1, sizeof(*t));
  if (!t) return NULL;
  t->hashed = 1;
  t->lower = lower;
  t->vocab_size = vocab_size;
  t->pad_id = 0;
  t->cls_id = 1;
  t->sep_id = 2;
  t->unk_id = 3;
  return t;
}

/* ------------------------------------------------------------ encoding */

static uint32_t hash_word_id(const spt_wptok *t, const char *w,
                             size_t len) {
  uint64_t h = fnv1a64(w, len, FNV_BASIS);
  return 4u + (uint32_t)(h % (uint64_t)(t->vocab_size - 4));
}

/* emit ids for one word; returns count written (<= word len), cap
 * pre-checked by caller */
static uint32_t encode_word(const spt_wptok *t, const char *w,
                            size_t len, uint32_t *out) {
  if (t->hashed) {
    out[0] = hash_word_id(t, w, len);
    return 1;
  }
  if (len > WPT_MAX_WORD) {
    out[0] = t->unk_id;
    return 1;
  }
  uint32_t n = 0;
  size_t start = 0;
  while (start < len) {
    size_t end = len;
    uint32_t id = 0;
    int found = 0;
    while (end > start) {
      if (wpt_find(t, w + start, end - start, start > 0, &id)) {
        found = 1;
        break;
      }
      end--;
    }
    if (!found) {                 /* whole word becomes UNK */
      out[0] = t->unk_id;
      return 1;
    }
    out[n++] = id;
    start = end;
  }
  return n;
}

int spt_wptok_encode(const spt_wptok *t, const char *text, uint32_t *out,
                     uint32_t cap) {
  if (!t || !text || !out) return -EINVAL;
  size_t tlen = strlen(text);
  for (size_t i = 0; i < tlen; i++)
    if ((unsigned char)text[i] >= 0x80) return -EDOM;
  if (cap < 2) return -ERANGE;

  uint32_t n = 0;
  out[n++] = t->cls_id;
  char word[WPT_MAX_WORD + 2];
  size_t wlen = 0;
  int overlong = 0;

  for (size_t i = 0; i <= tlen; i++) {
    unsigned char c = i < tlen ? (unsigned char)text[i] : ' ';
    if (t->lower && c >= 'A' && c <= 'Z') c += 32;
    if (wpt_isspace(c) || wpt_ispunct(c)) {
      if (wlen || overlong) {
        if (n + (overlong ? 1 : wlen) + 1 > cap) return -ERANGE;
        if (overlong)
          out[n++] = t->unk_id;   /* only wordpiece mode reaches this:
                                   * hashed overlong returned -EDOM */
        else
          n += encode_word(t, word, wlen, out + n);
        wlen = 0;
        overlong = 0;
      }
      if (wpt_ispunct(c)) {
        if (n + 2 > cap) return -ERANGE;
        char pc = (char)c;
        n += encode_word(t, &pc, 1, out + n);
      }
    } else {
      if (wlen >= WPT_MAX_WORD) {
        /* words beyond the bound: wordpiece mode maps them to UNK;
         * hashed mode must hash the FULL word, so overflow falls back
         * (caller re-encodes in Python — rare pathological input) */
        if (t->hashed) return -EDOM;
        overlong = 1;
        wlen = 0;                 /* keep scanning to the boundary */
      }
      if (!overlong) word[wlen++] = (char)c;
    }
  }
  if (n + 1 > cap) return -ERANGE;
  out[n++] = t->sep_id;
  return (int)n;
}

int spt_wptok_encode_batch(const spt_wptok *t, const char *const *texts,
                           uint32_t count, uint32_t max_len,
                           uint32_t *ids, uint32_t *lens) {
  if (!t || !texts || !ids || !lens || max_len < 2) return -EINVAL;
  /* scratch big enough for any outcome before truncation */
  uint32_t scratch_cap = 4096;
  uint32_t *scratch = malloc(scratch_cap * sizeof(uint32_t));
  if (!scratch) return -ENOMEM;

  for (uint32_t i = 0; i < count; i++) {
    size_t need = strlen(texts[i]) + 3;
    if (need > scratch_cap) {
      uint32_t nc = scratch_cap;
      while (nc < need) nc *= 2;
      uint32_t *ns = realloc(scratch, nc * sizeof(uint32_t));
      if (!ns) {
        free(scratch);
        return -ENOMEM;
      }
      scratch = ns;
      scratch_cap = nc;
    }
    int rc = spt_wptok_encode(t, texts[i], scratch, scratch_cap);
    uint32_t *row = ids + (size_t)i * max_len;
    if (rc < 0) {
      /* -EDOM (non-ASCII): mark for the caller's Python fallback */
      lens[i] = UINT32_MAX;
      for (uint32_t j = 0; j < max_len; j++) row[j] = t->pad_id;
      continue;
    }
    uint32_t n = (uint32_t)rc;
    if (n > max_len) {            /* truncate, keep trailing SEP */
      n = max_len;
      scratch[max_len - 1] = t->sep_id;
    }
    memcpy(row, scratch, n * sizeof(uint32_t));
    for (uint32_t j = n; j < max_len; j++) row[j] = t->pad_id;
    lens[i] = n;
  }
  free(scratch);
  return 0;
}
