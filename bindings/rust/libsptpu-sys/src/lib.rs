//! Raw FFI bindings to the splinter-tpu native store (`libsptpu`).
//!
//! Hand-maintained against `native/include/sptpu.h` (capability parity with
//! the reference's bindgen-generated libsplinter-sys crate).  Everything is
//! `unsafe extern "C"`; returns follow the library's negative-errno
//! discipline (0 ok, `-EAGAIN` retry, `-ENOENT` missing, ...).
//!
//! ```no_run
//! use libsptpu_sys::*;
//! use std::ffi::CString;
//! unsafe {
//!     let name = CString::new("/demo").unwrap();
//!     let st = spt_create(name.as_ptr(), 1024, 4096, 768, SPT_CREATE_EXCL);
//!     assert!(!st.is_null());
//!     let k = CString::new("greeting").unwrap();
//!     let v = b"hello rust";
//!     spt_set(st, k.as_ptr(), v.as_ptr() as *const _, v.len() as u32);
//!     spt_close(st);
//! }
//! ```
#![allow(non_camel_case_types)]

use std::os::raw::{c_char, c_int, c_void};

pub const SPT_KEY_MAX: usize = 128;
pub const SPT_SIGNAL_GROUPS: u32 = 64;
pub const SPT_MAX_BIDS: u32 = 32;
pub const SPT_DIRTY_WORDS: usize = 16;

pub const SPT_BACKEND_SHM: u32 = 0;
pub const SPT_BACKEND_FILE: u32 = 1 << 0;
pub const SPT_CREATE_EXCL: u32 = 1 << 1;

pub const SPT_T_VOID: u32 = 0x00;
pub const SPT_T_BIGINT: u32 = 0x01;
pub const SPT_T_BIGUINT: u32 = 0x02;
pub const SPT_T_JSON: u32 = 0x04;
pub const SPT_T_BINARY: u32 = 0x08;
pub const SPT_T_IMGDATA: u32 = 0x10;
pub const SPT_T_AUDIO: u32 = 0x20;
pub const SPT_T_VARTEXT: u32 = 0x40;
pub const SPT_F_SYSTEM: u32 = 1 << 16;

pub const SPT_MOP_OFF: u32 = 0;
pub const SPT_MOP_HYBRID: u32 = 1;
pub const SPT_MOP_FULL: u32 = 2;

#[repr(C)]
#[derive(Debug, Clone, Copy, PartialEq, Eq)]
pub enum spt_iop_t {
    AND = 0,
    OR,
    XOR,
    NOT,
    INC,
    DEC,
    ADD,
    SUB,
}

#[repr(C)]
#[derive(Debug, Clone, Copy, PartialEq, Eq)]
pub enum spt_advice_t {
    NORMAL = 0,
    SEQUENTIAL,
    RANDOM,
    WILLNEED,
    DONTNEED,
}

/// Opaque store handle.
#[repr(C)]
pub struct spt_store {
    _priv: [u8; 0],
}

#[repr(C)]
#[derive(Debug, Clone, Copy, Default)]
pub struct spt_header_view {
    pub magic: u32,
    pub version: u32,
    pub nslots: u32,
    pub max_val: u32,
    pub vec_dim: u32,
    pub mop_mode: u32,
    pub map_size: u64,
    pub global_epoch: u64,
    pub core_flags: u32,
    pub user_flags: u32,
    pub parse_failures: u64,
    pub last_failure_epoch: u64,
    pub bus_pid: i64,
    pub used_slots: u32,
}

#[repr(C)]
#[derive(Debug, Clone, Copy)]
pub struct spt_slot_view {
    pub epoch: u64,
    pub hash: u64,
    pub labels: u64,
    pub watcher_mask: u64,
    pub val_len: u32,
    pub flags: u32,
    pub ctime: i64,
    pub atime: i64,
    pub index: i32,
    pub key: [c_char; SPT_KEY_MAX],
}

#[repr(C)]
#[derive(Debug, Clone, Copy, Default)]
pub struct spt_bid_view {
    pub pid: i64,
    pub shard_id: u64,
    pub claimed_at: u64,
    pub duration: u64,
    pub intent: u32,
    pub priority: u32,
    pub live: i32,
}

extern "C" {
    // lifecycle
    pub fn spt_create(name: *const c_char, nslots: u32, max_val: u32,
                      vec_dim: u32, flags: u32) -> *mut spt_store;
    pub fn spt_open(name: *const c_char, flags: u32) -> *mut spt_store;
    pub fn spt_open_numa(name: *const c_char, flags: u32, node: c_int,
                         bind_rc: *mut c_int) -> *mut spt_store;
    pub fn spt_close(st: *mut spt_store) -> c_int;
    pub fn spt_unlink(name: *const c_char, flags: u32) -> c_int;

    // geometry / raw access
    pub fn spt_nslots(st: *const spt_store) -> u32;
    pub fn spt_max_val(st: *const spt_store) -> u32;
    pub fn spt_vec_dim(st: *const spt_store) -> u32;
    pub fn spt_vec_lane(st: *mut spt_store) -> *mut c_void;
    pub fn spt_values_base(st: *mut spt_store) -> *mut c_void;
    pub fn spt_last_error() -> c_int;

    // KV
    pub fn spt_set(st: *mut spt_store, key: *const c_char, val: *const c_void,
                   len: u32) -> c_int;
    pub fn spt_get(st: *mut spt_store, key: *const c_char, buf: *mut c_void,
                   cap: u32, len_out: *mut u32) -> c_int;
    pub fn spt_unset(st: *mut spt_store, key: *const c_char) -> c_int;
    pub fn spt_append(st: *mut spt_store, key: *const c_char,
                      val: *const c_void, len: u32) -> c_int;
    pub fn spt_list(st: *mut spt_store, keys: *mut c_char, max_keys: u32)
                    -> c_int;
    pub fn spt_poll(st: *mut spt_store, key: *const c_char, timeout_ms: c_int)
                    -> c_int;
    pub fn spt_get_raw(st: *mut spt_store, key: *const c_char,
                       ptr: *mut *const c_void, len_out: *mut u32,
                       epoch_out: *mut u64) -> c_int;

    // index-based access
    pub fn spt_find_index(st: *mut spt_store, key: *const c_char) -> c_int;
    pub fn spt_key_at(st: *mut spt_store, idx: u32, key_out: *mut c_char)
                      -> c_int;
    pub fn spt_epoch_at(st: *mut spt_store, idx: u32) -> u64;
    pub fn spt_get_at(st: *mut spt_store, idx: u32, buf: *mut c_void,
                      cap: u32, len_out: *mut u32) -> c_int;
    pub fn spt_labels_at(st: *mut spt_store, idx: u32) -> u64;
    pub fn spt_flags_at(st: *mut spt_store, idx: u32) -> u32;

    // snapshots
    pub fn spt_header_snapshot(st: *mut spt_store, out: *mut spt_header_view)
                               -> c_int;
    pub fn spt_slot_snapshot(st: *mut spt_store, key: *const c_char,
                             out: *mut spt_slot_view) -> c_int;
    pub fn spt_slot_snapshot_at(st: *mut spt_store, idx: u32,
                                out: *mut spt_slot_view) -> c_int;

    // typed slots / integer ops
    pub fn spt_set_type(st: *mut spt_store, key: *const c_char,
                        type_flag: u32) -> c_int;
    pub fn spt_get_type(st: *mut spt_store, key: *const c_char,
                        type_out: *mut u32) -> c_int;
    pub fn spt_integer_op(st: *mut spt_store, key: *const c_char,
                          op: spt_iop_t, operand: u64, result_out: *mut u64)
                          -> c_int;

    // tandem keys
    pub fn spt_tandem_set(st: *mut spt_store, base: *const c_char, order: u32,
                          val: *const c_void, len: u32) -> c_int;
    pub fn spt_tandem_get(st: *mut spt_store, base: *const c_char, order: u32,
                          buf: *mut c_void, cap: u32, len_out: *mut u32)
                          -> c_int;
    pub fn spt_tandem_unset(st: *mut spt_store, base: *const c_char,
                            max_order: u32) -> c_int;
    pub fn spt_tandem_count(st: *mut spt_store, base: *const c_char) -> c_int;

    // bloom labels
    pub fn spt_label_or(st: *mut spt_store, key: *const c_char, mask: u64)
                        -> c_int;
    pub fn spt_label_andnot(st: *mut spt_store, key: *const c_char, mask: u64)
                            -> c_int;
    pub fn spt_get_labels(st: *mut spt_store, key: *const c_char,
                          out: *mut u64) -> c_int;
    pub fn spt_enumerate(st: *mut spt_store, mask: u64, idx_out: *mut u32,
                         max_out: u32) -> c_int;

    // signal arena
    pub fn spt_watch_register(st: *mut spt_store, key: *const c_char,
                              group: u32) -> c_int;
    pub fn spt_watch_unregister(st: *mut spt_store, key: *const c_char,
                                group: u32) -> c_int;
    pub fn spt_watch_label_register(st: *mut spt_store, bloom_bit: u32,
                                    group: u32) -> c_int;
    pub fn spt_watch_label_unregister(st: *mut spt_store, bloom_bit: u32,
                                      group: u32) -> c_int;
    pub fn spt_signal_count(st: *mut spt_store, group: u32) -> u64;
    pub fn spt_signal_pulse(st: *mut spt_store, group: u32) -> c_int;
    pub fn spt_bump(st: *mut spt_store, key: *const c_char) -> c_int;
    pub fn spt_signal_wait(st: *mut spt_store, group: u32, last: u64,
                           timeout_ms: c_int, count_out: *mut u64) -> c_int;

    // event bus
    pub fn spt_bus_init(st: *mut spt_store) -> c_int;
    pub fn spt_bus_open(st: *mut spt_store) -> c_int;
    pub fn spt_bus_wait(st: *mut spt_store, timeout_ms: c_int) -> c_int;
    pub fn spt_bus_close(st: *mut spt_store) -> c_int;
    pub fn spt_bus_drain(st: *mut spt_store,
                         dirty_out: *mut u64 /* [SPT_DIRTY_WORDS] */) -> c_int;
    pub fn spt_bus_peek(st: *mut spt_store,
                        dirty_out: *mut u64 /* [SPT_DIRTY_WORDS] */) -> c_int;

    // shard bids & advisement
    pub fn spt_shard_claim(st: *mut spt_store, shard_id: u64,
                           intent: spt_advice_t, priority: u32,
                           duration_us: u64) -> c_int;
    pub fn spt_shard_claim_ex(st: *mut spt_store, shard_id: u64, pid: i64,
                              intent: spt_advice_t, priority: u32,
                              duration_us: u64, claimed_at_us: u64) -> c_int;
    pub fn spt_shard_rebid(st: *mut spt_store, bid_idx: c_int) -> c_int;
    pub fn spt_shard_release(st: *mut spt_store, bid_idx: c_int) -> c_int;
    pub fn spt_shard_election(st: *mut spt_store) -> c_int;
    pub fn spt_bid_info(st: *mut spt_store, bid_idx: c_int,
                        out: *mut spt_bid_view) -> c_int;
    pub fn spt_madvise(st: *mut spt_store, bid_idx: c_int, offset: u64,
                       len: u64, advice: spt_advice_t, timeout_ms: c_int)
                       -> c_int;

    // mop / purge / recovery
    pub fn spt_set_mop(st: *mut spt_store, mode: u32) -> c_int;
    pub fn spt_get_mop(st: *mut spt_store) -> u32;
    pub fn spt_purge(st: *mut spt_store) -> c_int;
    pub fn spt_retrain(st: *mut spt_store, key: *const c_char) -> c_int;

    // system keys & flags
    pub fn spt_set_system(st: *mut spt_store, key: *const c_char) -> c_int;
    pub fn spt_slot_usr_set(st: *mut spt_store, key: *const c_char, bits: u8)
                            -> c_int;
    pub fn spt_slot_usr_get(st: *mut spt_store, key: *const c_char,
                            out: *mut u8) -> c_int;
    pub fn spt_config_set_user(st: *mut spt_store, bits: u32) -> c_int;
    pub fn spt_config_get_user(st: *mut spt_store) -> u32;

    // timestamps
    pub fn spt_now() -> u64;
    pub fn spt_ticks_per_us() -> u64;
    pub fn spt_stamp(st: *mut spt_store, key: *const c_char, which: c_int,
                     ticks_ago: u64) -> c_int;

    // embedding vector lane
    pub fn spt_vec_set(st: *mut spt_store, key: *const c_char,
                       vec: *const f32, dim: u32) -> c_int;
    pub fn spt_vec_get(st: *mut spt_store, key: *const c_char, out: *mut f32,
                       dim: u32) -> c_int;
    pub fn spt_vec_set_at(st: *mut spt_store, idx: u32, vec: *const f32,
                          dim: u32) -> c_int;
    pub fn spt_vec_get_at(st: *mut spt_store, idx: u32, out: *mut f32,
                          dim: u32) -> c_int;
    pub fn spt_vec_commit_batch(st: *mut spt_store, rows: *const u32,
                                epochs: *const u64, vecs: *const f32, n: u32,
                                dim: u32, write_once: c_int,
                                results: *mut i32) -> c_int;

    pub fn spt_epochs(st: *mut spt_store, out: *mut u64) -> c_int;
    /* epochs_out[i] == SPT_GATHER_TORN (u64::MAX) => torn row, retry */
    pub fn spt_vec_gather(st: *mut spt_store, rows: *const u32, n: u32,
                          out: *mut f32, epochs_out: *mut u64) -> c_int;

    // diagnostics
    pub fn spt_report_parse_failure(st: *mut spt_store) -> c_int;
}

#[cfg(test)]
mod tests {
    use super::*;
    use std::ffi::CString;

    #[test]
    fn round_trip() {
        unsafe {
            let name =
                CString::new(format!("/sptpu-rs-{}", std::process::id()))
                    .unwrap();
            let st = spt_create(name.as_ptr(), 64, 256, 8, SPT_CREATE_EXCL);
            assert!(!st.is_null(), "create failed: {}", spt_last_error());

            let k = CString::new("greeting").unwrap();
            let v = b"hello rust";
            assert_eq!(
                spt_set(st, k.as_ptr(), v.as_ptr() as *const _, v.len() as u32),
                0
            );

            let mut buf = [0u8; 256];
            let mut len = 0u32;
            assert_eq!(
                spt_get(st, k.as_ptr(), buf.as_mut_ptr() as *mut _,
                        buf.len() as u32, &mut len),
                0
            );
            assert_eq!(&buf[..len as usize], v);

            let idx = spt_find_index(st, k.as_ptr());
            assert!(idx >= 0);
            assert_eq!(spt_epoch_at(st, idx as u32), 2);

            spt_close(st);
            spt_unlink(name.as_ptr(), 0);
        }
    }
}
