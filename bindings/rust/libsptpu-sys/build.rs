fn main() {
    println!("cargo:rerun-if-changed=csrc/store.c");
    println!("cargo:rerun-if-changed=csrc/coord.c");
    println!("cargo:rerun-if-changed=csrc/wptok.c");
    println!("cargo:rerun-if-changed=csrc/internal.h");
    println!("cargo:rerun-if-changed=csrc/sptpu.h");

    cc::Build::new()
        .file("csrc/store.c")
        .file("csrc/coord.c")
        .file("csrc/wptok.c")
        .include("csrc")
        .flag_if_supported("-std=c11")
        .flag_if_supported("-pthread")
        .opt_level(2)
        .compile("sptpu");

    // librt for shm_open on older glibc; harmless elsewhere on Linux
    println!("cargo:rustc-link-lib=rt");
}
