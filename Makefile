# libsplinter-tpu — top-level bootstrap (VERDICT r3 #8).
#
# One command from a clean checkout to a green suite:
#
#   make all        native lib + tools, TAP unit tier, full pytest
#   make quick      native lib + TAP tier + pytest smoke subset (~2 min)
#   make check      the native check tier (TAP + MRSW stress + MRMW
#                   chi-sao) + full pytest
#   make memcheck   valgrind (if installed) or ASan/UBSan native tier
#   make bench-cpu  quick host-CPU bench (embed + store_ops phases)
#   make obs-check  observability tier: tracing-overhead budget
#                   (scripts/obs_overhead_check.py, <3% vs disabled)
#                   + the `-m obs` pytest group
#   make search-check  fused top-k tier: interpret-mode kernel parity
#                   vs the lax.top_k reference + the search daemon's
#                   coalescing smoke (N clients « N dispatches)
#   make decode-check  paged decode tier: interpret-mode ragged
#                   paged-attention parity vs dense flash, pool
#                   alloc/free leak checks, the paged continuous-
#                   batching smoke (token-exact vs dense, joiner
#                   past the dense window), and spec-demotion (CPU)
#   make chaos-check   fault-injection tier: SPTPU_FAULT unit tests,
#                   supervisor backoff/breaker, and the CPU-only
#                   crash-at-every-stage recovery matrix (child
#                   daemons crashed mid-drain via crash@k, restarted,
#                   convergence asserted; `pytest -m chaos`)
#   make dispatch-check  dispatch-floor tier: resident-ring /
#                   K-overlap parity vs the per-call paths (byte-
#                   identical vectors, search results, decode tokens)
#                   + the depth-amortization smoke (per-drain host
#                   overhead must shrink monotonically with depth;
#                   scripts/dispatch_amortization_check.py)
#   make pod-check  pod-sharded paged decode tier (fast, CPU
#                   8-device mesh): sharded-paged vs single-chip-
#                   paged vs serial token-exact parity, the
#                   shard_map'd ragged/flash kernels in interpret
#                   mode, mid-flight joiner, pool backpressure,
#                   shard-labeled heartbeat gauges, and sharded-
#                   dispatch fault containment
#   make qos-check  multi-tenant QoS tier (fast, CPU): weighted
#                   fairness within 2x under 10:1 offered-load skew,
#                   typed overloaded shedding + retry_after_ms at the
#                   queue high-water mark, deadline fast-fail on a
#                   real searcher (scripts/qos_fairness_check.py) +
#                   the `tests/test_qos.py` fast tier (admission
#                   policy units, all three lanes, loadgen smoke)
#   make pipeline-check  pipeline-lane tier (fast, CPU): sandbox
#                   containment (hostile scripts die typed while
#                   siblings complete), scripted-chain end-to-end
#                   parity, and the script-vs-client-chaining latency
#                   smoke (stored-script rag-churn p50 >= 30% below
#                   the client-side chain;
#                   scripts/pipeline_latency_check.py)
#   make trace-check  cross-lane tracing + telemetry tier (fast,
#                   CPU): trace-context stamp round-trips, span-ring
#                   wire protocol (staging, crash recovery with
#                   restart-gap attribution, bounded multi-writer
#                   ring), orphan sweeps (raced rewrites cannot leak
#                   staging rows), span-tree assembly parity for both
#                   chain forms, the Chrome/Perfetto export schema
#                   check, telemetry-ring persistence across sampler
#                   restarts, and the EXTENDED obs-overhead gate
#                   (span stamping + a concurrently-scraping sampler
#                   must stay under the same <3% budget)
#   make lint-check  splint static-analysis tier (pure stdlib ast,
#                   no jax, no native build needed): protocol-
#                   registry sync rules (label-bit collisions, raw
#                   bit literals, fault-site catalog + chaos
#                   reachability, metrics/heartbeat sync, generated
#                   doc tables) + JAX dispatch-hazard rules (host
#                   syncs in drain loops, donated-buffer reuse,
#                   missing out_shardings pins, unseeded fault-path
#                   randomness), then the splint test tier.
#                   Non-zero exit on any unsuppressed finding.
#   make prefix-check  cross-request prefix-sharing tier (fast,
#                   CPU): refcount churn drill (zero leaks / double
#                   frees, refcount-0 <=> free XOR tree-retained),
#                   COW-vs-private byte-exact greedy decode (f32 +
#                   int8, single-chip + tp=2), >= 4x rows per page
#                   budget, LRU eviction + tenant quotas, mid-flight
#                   joiner parity, loadgen --shared-prefix, and the
#                   hot-vs-cold admission-to-first-token gate
#                   (scripts/prefix_speedup_check.py, >= 5x on the
#                   in-process CPU stack)
#   make disagg-check  disaggregated prefill/decode tier (fast,
#                   CPU): PrefillLane + DecodeLane on one store,
#                   driven through loadgen's prefill-burst scenario —
#                   the decode floor's inter-chunk p99 under a 10x
#                   prefill rate step must stay within 1.2x of the
#                   prefill-idle baseline (plus a small absolute
#                   slack), with zero admitted loss and the page
#                   handoff running the real wire export/import path
#                   (scripts/disagg_check.py) + the test_disagg.py
#                   fast tier (byte-exactness vs the unified
#                   completer, handoff crash drills both directions)
#   make warm-check  tiered-KV warm-restart tier (fast, CPU): one
#                   supervised completer lane with the host-DRAM
#                   spill tier + persistent radix index armed,
#                   SIGKILLed mid-loadgen — the respawn must attach
#                   WARM (index restored, hot set readmitted from the
#                   tier instead of re-prefilled, greedy bytes
#                   identical across the restart), with zero admitted
#                   loss and post-restart first-token p50 <= 2x the
#                   pre-restart baseline
#                   (scripts/warm_restart_check.py) + the
#                   test_kv_tier.py fast tier (write-through spill /
#                   readmit byte-exactness, torn-snapshot taxonomy,
#                   capacity-drop pruning)
#   make scale-check  elastic-lane tier (fast, CPU): stripe-map
#                   protocol + striped replica groups (R=2 byte-
#                   identical to R=1, no double-claims, no orphans
#                   across a re-stripe), supervisor replica sets +
#                   scale-down drain/reclaim, autoscaler hysteresis
#                   (no flapping on oscillating input), loadgen rate
#                   profiles, then the in-process 1x->4x->1x rate-
#                   step gate (scripts/scale_step_check.py: replicas
#                   follow the step, zero admitted-request loss
#                   through scale-up AND scale-down)
#   make quant-check  quantized-KV tier (fast, CPU): int8-vs-f32
#                   ragged paged-attention parity (interpret mode),
#                   multi-query verify stack, quantize-on-commit /
#                   rescale-on-append error budgets, spec-paged
#                   greedy exactness, compile-count pinning, and the
#                   pool-bytes gate (int8 == 1/2 bf16 == 1/4 f32,
#                   measured from placed buffers;
#                   scripts/quant_pool_bytes_check.py)
#   make compile-check  device-time/compile-attribution tier (fast,
#                   CPU): devtime registry + compile-ring unit tests,
#                   then the post-warmup no-recompile gate over the
#                   pod-sharded paged drill in both directions —
#                   clean passes, a seeded out_shardings drop is
#                   caught by program name + shapes key
#                   (scripts/compile_gate_check.py)
#   make clean
#
# Parity: the reference's `configure` + shim Makefile + bigbang.sh
# (/root/reference/configure:1-60) — here there are no external deps to
# install (jax & friends are baked into the image; the native tier
# needs only cc + make), so bootstrap is just build + test.  The build
# hash the reference stamps via scripts/genbuildh lands in
# native/build/libsptpu.so as spt_build_id(), surfaced by `caps`.

PY ?= python

all: native
	native/build/spt_unit
	$(PY) -m pytest tests/ -x -q

native:
	$(MAKE) -C native all tests

quick: native
	native/build/spt_unit
	$(PY) -m pytest tests/test_store.py tests/test_embedder.py \
		tests/test_cli.py -q

# the full sweep excludes the chaos tier, which runs once on its own
# line (it needs JAX_PLATFORMS=cpu for the crash-matrix children and
# would otherwise run twice); search-check/decode-check/chaos-check/
# pod-check stay standalone fast gates, same pattern as obs-check's
# `-m obs` group — the full pytest sweep below collects their tiers too
check: native
	$(MAKE) -C native check
	$(PY) scripts/splint_check.py
	$(PY) scripts/obs_overhead_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/dispatch_amortization_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/quant_pool_bytes_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/qos_fairness_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/pipeline_latency_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/prefix_speedup_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/scale_step_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/disagg_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/warm_restart_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/compile_gate_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/compile_gate_check.py --seed-recompile
	$(PY) -m pytest tests/ -q -m "not chaos"
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos

obs-check: native
	$(PY) scripts/obs_overhead_check.py
	$(PY) -m pytest tests/ -q -m obs

search-check: native
	$(PY) -m pytest tests/test_fused_topk.py tests/test_searcher.py -q

decode-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_paged_attention.py \
		tests/test_paged_continuous.py -q

chaos-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos

dispatch-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resident.py -q \
		-m "not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/dispatch_amortization_check.py

pod-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_sharded_paged.py \
		tests/test_sharded_decode.py -q -m "not slow"

scale-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_elastic.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/scale_step_check.py

disagg-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_disagg.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/disagg_check.py

warm-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_kv_tier.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/warm_restart_check.py

quant-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_quant_kv.py \
		tests/test_quant_int4.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) scripts/quant_pool_bytes_check.py

prefix-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_prefix_cache.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/prefix_speedup_check.py

# no `native` dep: splint is stdlib-ast only and must be runnable
# before (or without) any build step — the cheapest pre-commit gate
lint-check:
	$(PY) scripts/splint_check.py
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_splint.py -q

qos-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_qos.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/qos_fairness_check.py

trace-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_spans.py \
		tests/test_telemetry.py -q -m "not slow and not chaos"
	$(PY) scripts/obs_overhead_check.py

# the post-warmup no-recompile gate (obs/devtime.py compile ledger)
# over the pod-sharded paged drill, both directions: clean must pass,
# the seeded out_shardings drop must be CAUGHT by name + shapes key
compile-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_devtime.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/compile_gate_check.py
	JAX_PLATFORMS=cpu $(PY) scripts/compile_gate_check.py --seed-recompile

pipeline-check: native
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pipeliner.py -q \
		-m "not slow and not chaos"
	JAX_PLATFORMS=cpu $(PY) scripts/pipeline_latency_check.py

memcheck: native
	$(MAKE) -C native memcheck

bench-cpu:
	BENCH_CPU=1 BENCH_TEXTS=256 BENCH_BATCH=64 \
	    BENCH_PHASES=embed,store_ops $(PY) bench.py

clean:
	$(MAKE) -C native clean

.PHONY: all native quick check obs-check search-check decode-check \
	chaos-check dispatch-check pod-check quant-check prefix-check \
	qos-check pipeline-check trace-check lint-check scale-check \
	disagg-check warm-check compile-check memcheck bench-cpu clean
