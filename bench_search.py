"""Similarity-kernel benchmark: cosine top-k over a large vector lane.

Thin standalone wrapper over bench_series.phase_search (the single
implementation every tunnel client runs, VERDICT r3 #1).  BASELINE.md
row: "Cosine top-k over 1M-vector arena — Pallas kernel (beat the
reference's O(N*768) scalar scan, splinter_cli_cmd_search.c:374-412)".

Prints ONE JSON line {"metric": "search_queries_per_sec", ...};
vs_baseline = kernel qps / numpy host-scan qps.  The detail section
carries fused-vs-unfused q/s, the fused QB sweep {1, 32, 256}, and
the search daemon's coalescing stats + heartbeat-sourced stage
quantiles (bench_series.phase_search).  Appends to
bench_results.jsonl.

Run strictly alone: the tunneled TPU admits one client.  Env:
BENCH_CPU=1, SEARCH_N (default 1,000,000 on TPU / 100,000 on CPU),
SEARCH_D (768), SEARCH_K (10), SEARCH_REPS (20), SEARCHD_N (8192),
SEARCHD_WAVES (8).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_series import shim_main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(shim_main("search"))
