"""Similarity-kernel benchmark: cosine top-k over a large vector lane.

BASELINE.md row: "Cosine top-k over 1M-vector arena — Pallas kernel
(beat the reference's O(N*768) scalar scan,
splinter_cli_cmd_search.c:374-412)".  Measures:

  - fused cosine+top-k queries/sec over an (N, 768) lane (the CLI
    search hot path after staging) with the f32 kernel;
  - the same with --fast's bf16 MXU path (mxu_bf16=True) — the number
    that justifies the flag's existence;
  - a numpy dot-product scan as the host-side stand-in for the
    reference's CPU scan (the reference is scalar C, i.e. strictly
    slower than numpy's vectorized BLAS loop).

Prints ONE JSON line {"metric": "search_queries_per_sec", ...};
vs_baseline = kernel qps / numpy qps.  Appends to bench_results.jsonl.

Env: BENCH_CPU=1 (jnp path on host CPU), SEARCH_N (default 1,000,000 on
TPU / 100,000 on CPU), SEARCH_D (768), SEARCH_K (10), SEARCH_REPS (20).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CPU_MODE = os.environ.get("BENCH_CPU") == "1"
D = int(os.environ.get("SEARCH_D", "768"))
K = int(os.environ.get("SEARCH_K", "10"))
REPS = int(os.environ.get("SEARCH_REPS", "20"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    import faulthandler

    import numpy as np

    # a hang (tunnel stall, surprise compile) must leave a stack in
    # the log before the watcher's timeout SIGKILLs us
    faulthandler.dump_traceback_later(300, repeat=True, file=sys.stderr)

    if CPU_MODE:
        from libsplinter_tpu.utils.jaxplatform import force_cpu
        force_cpu()
    from libsplinter_tpu.utils.jaxplatform import enable_compile_cache
    enable_compile_cache()
    import jax

    from libsplinter_tpu.ops.similarity import cosine_topk

    backend = jax.default_backend()
    n = int(os.environ.get("SEARCH_N",
                           "1000000" if backend == "tpu" else "100000"))
    log(f"backend={backend} lane=({n}, {D})")

    rng = np.random.default_rng(0)
    lane = rng.normal(size=(n, D)).astype(np.float32)
    QB = 32                           # batched-query point size
    use_pallas = backend == "tpu"
    # enough rows for the QB-query batch regardless of REPS
    queries = rng.normal(size=(max(REPS, QB), D)).astype(np.float32)
    lane_dev = jax.device_put(lane)
    # session steady state: the lane is staged once (StagedLane), so its
    # row norms are lane-static data computed at stage time
    vnorm_dev = jax.device_put(np.linalg.norm(lane, axis=1)
                               .astype(np.float32))

    def bench_kernel(mxu_bf16: bool) -> float:
        cosine_topk(lane_dev, queries[0], K, use_pallas=use_pallas,
                    mxu_bf16=mxu_bf16, vnorm=vnorm_dev)  # compile+warm
        t0 = time.perf_counter()
        for i in range(REPS):
            cosine_topk(lane_dev, queries[i], K,
                        use_pallas=use_pallas, mxu_bf16=mxu_bf16,
                        vnorm=vnorm_dev)
        return REPS / (time.perf_counter() - t0)

    qps_f32 = bench_kernel(False)
    qps_bf16 = bench_kernel(True) if backend == "tpu" else 0.0
    log(f"kernel: {qps_f32:.1f} q/s f32"
        + (f", {qps_bf16:.1f} q/s bf16" if qps_bf16 else ""))

    # batched queries: one kernel pass scoring QB queries amortizes
    # the lane read (the dominant cost at 1M rows)
    from libsplinter_tpu.ops.similarity import cosine_topk_batch
    cosine_topk_batch(lane_dev, queries[:QB], K, use_pallas=use_pallas,
                      vnorm=vnorm_dev)            # compile+warm
    t0 = time.perf_counter()
    reps_b = max(2, REPS // QB)
    for _ in range(reps_b):
        cosine_topk_batch(lane_dev, queries[:QB], K,
                          use_pallas=use_pallas, vnorm=vnorm_dev)
    qps_batch = reps_b * QB / (time.perf_counter() - t0)
    log(f"batched: {qps_batch:.1f} q/s aggregate (QB={QB})")

    # host numpy scan (vectorized stand-in for the reference's scalar C)
    nn = min(n, 100_000)              # numpy at 1M x 768 is minutes
    sub = lane[:nn]
    norms = np.linalg.norm(sub, axis=1)
    t0 = time.perf_counter()
    reps_np = max(3, REPS // 4)
    for i in range(reps_np):
        q = queries[i]
        s = sub @ q / np.maximum(norms * np.linalg.norm(q), 1e-12)
        np.argpartition(-s, K)[:K]
    qps_np = reps_np / (time.perf_counter() - t0) * (nn / n)
    log(f"numpy scan (scaled to {n} rows): {qps_np:.2f} q/s")

    best = max(qps_f32, qps_bf16)
    rec = {
        "metric": "search_queries_per_sec",
        "value": round(best, 1),
        "unit": "queries/s",
        "vs_baseline": round(best / qps_np, 2) if qps_np > 0 else 0.0,
        "detail": {
            "backend": backend, "n": n, "d": D, "k": K,
            "qps_f32": round(qps_f32, 1),
            "qps_bf16_fast": round(qps_bf16, 1),
            "qps_batch32_aggregate": round(qps_batch, 1),
            "bf16_speedup": round(qps_bf16 / qps_f32, 2)
            if qps_f32 > 0 and qps_bf16 > 0 else None,
            "qps_numpy_hostscan": round(qps_np, 2),
        },
    }
    print(json.dumps(rec), flush=True)
    try:
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_results.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
