"""Decode-path benchmark: completion tokens/sec + daemon e2e latency.

Measures the three numbers the completion story is judged on
(VERDICT r2 #4; the reference's streaming cadence is
splainference.cpp:333-354 — a serial per-token llama.cpp decode with an
8-token flush):

  - prefill latency for a bucketed prompt (one compiled program);
  - steady-state decode tokens/sec through CompletionModel's
    chunk-at-a-time on-device lax.scan loop (the KV cache never
    round-trips to the host; the host syncs once per chunk);
  - completion-daemon end-to-end latency: prompt set in the native
    store -> label wake -> Completer drains -> first flush appended.

Prints ONE JSON line:
  {"metric": "decode_tokens_per_sec", "value": N, "unit": "tokens/s",
   "vs_baseline": N}

The reference publishes no tokens/sec number (BASELINE.md), so
vs_baseline compares against its architectural cadence instead: the
serial loop syncs host<->device per token, ours per chunk; we report
value / (value measured with chunk=1) — i.e. the speedup the chunked
design buys over the reference's per-token sync pattern ON THE SAME
hardware and weights.  >1.0 means the TPU-first design wins.

Env knobs: BENCH_CPU=1 (force host CPU), DECODE_TOKENS (default 256),
DECODE_CHUNK (default 8), DECODE_GEOMETRY=tiny|flagship (default
flagship; tiny for quick CI-style runs).

Run it on the real chip opportunistically (the tunnel is single-client;
see bench.py's docstring): `python bench_decode.py`.  Results append to
bench_results.jsonl with timestamps for docs/performance.md.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_TOKENS = int(os.environ.get("DECODE_TOKENS", "256"))
CHUNK = int(os.environ.get("DECODE_CHUNK", "8"))
GEOMETRY = os.environ.get("DECODE_GEOMETRY", "flagship")
CPU_MODE = os.environ.get("BENCH_CPU") == "1"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    import faulthandler

    import numpy as np

    # a phase that hangs (tunnel stall, surprise compile) must leave a
    # stack in the log before the watcher's timeout SIGKILLs us
    faulthandler.dump_traceback_later(300, repeat=True, file=sys.stderr)

    if CPU_MODE:
        from libsplinter_tpu.utils.jaxplatform import force_cpu
        force_cpu()
    from libsplinter_tpu.utils.jaxplatform import enable_compile_cache
    enable_compile_cache()
    import jax

    from libsplinter_tpu.models import CompletionModel, DecoderConfig

    backend = jax.default_backend()
    log(f"backend={backend}")

    quant = os.environ.get("DECODE_QUANT") == "1"
    if GEOMETRY == "tiny":
        cfg = DecoderConfig.tiny(quantized=quant)
    else:
        # the completion daemon's default geometry (completer.py):
        # llama-tiny-class 12x768 with the byte tokenizer's padded vocab
        cfg = DecoderConfig(vocab_size=512, quantized=quant)
    model = CompletionModel(cfg)

    log("warmup compile (prefill buckets + decode + chunk programs) ...")
    t0 = time.perf_counter()
    model.warmup(chunk=CHUNK)
    model._chunk_program(1)         # the per-token baseline program
    log(f"compile: {time.perf_counter()-t0:.1f}s")

    prompt = np.ones((48,), np.int32)

    # -- prefill latency ---------------------------------------------------
    times = []
    for _ in range(5):
        model.reset()
        t0 = time.perf_counter()
        model.prefill(prompt)
        times.append((time.perf_counter() - t0) * 1000)
    prefill_ms = float(np.median(times))

    # -- steady-state chunked decode --------------------------------------
    def tokens_per_sec(chunk: int, n: int) -> float:
        model.reset()
        model.prefill(prompt)
        # never overrun the KV window (tiny geometries have small ones)
        n = min(n, cfg.max_len - model.pos - chunk - 1)
        t0 = time.perf_counter()
        got = 0
        tok = 1
        while got < n:
            toks = model.decode_chunk(tok, chunk)
            tok = int(toks[-1])
            got += chunk
        dt = time.perf_counter() - t0
        return got / dt

    tokens_per_sec(CHUNK, CHUNK * 2)          # warm the path
    tps_chunked = tokens_per_sec(CHUNK, N_TOKENS)
    # the reference's cadence: host<->device sync every token
    tps_serial = tokens_per_sec(1, max(32, N_TOKENS // 4))
    # wide-chunk point: how far does amortizing the host sync scale?
    model.warmup(chunk=32)
    tokens_per_sec(32, 64)
    tps_c32 = tokens_per_sec(32, max(N_TOKENS, 128))
    log(f"decode: {tps_chunked:,.1f} tok/s chunked (chunk={CHUNK}), "
        f"{tps_c32:,.1f} tok/s (chunk=32), "
        f"{tps_serial:,.1f} tok/s per-token sync")

    # batched serving: aggregate tok/s over 8 concurrent rows — the
    # completion daemon's batch_cap path (engine/completer.py
    # process_batch); a decode step for 8 rows costs ~one row's step
    def batch_tokens_per_sec(bsz: int, n: int) -> float:
        prompts = [np.ones((24 + r,), np.int32) for r in range(bsz)]
        model.reset()
        t0 = time.perf_counter()
        got = 0
        for _col in model.generate_batch(prompts, n, chunk=CHUNK):
            got += bsz
        model.reset()
        return got / (time.perf_counter() - t0)

    batch_tokens_per_sec(8, CHUNK * 2)        # warm (prefill + chunk progs)
    tps_b8 = batch_tokens_per_sec(8, N_TOKENS)
    log(f"batched decode: {tps_b8:,.1f} aggregate tok/s (batch=8, "
        f"chunk={CHUNK})")

    # speculative decoding: tiny draft proposes gamma tokens per
    # target verify forward (models/speculative.py)
    tps_spec = accept = None
    if os.environ.get("DECODE_SPEC", "1") == "1":
        from libsplinter_tpu.models import (DecoderConfig as _DC,
                                            SpeculativeCompletionModel)
        gamma = int(os.environ.get("DECODE_GAMMA", "4"))
        draft = CompletionModel(
            _DC.tiny(vocab_size=cfg.vocab_size, max_len=cfg.max_len),
            buckets=(64,), temp=model.temp, top_p=model.top_p,
            seed=123)   # distinct weights: tiny-geometry runs would
        #               otherwise make draft == target (vacuous accept)
        spec = SpeculativeCompletionModel(model, draft, gamma=gamma)
        spec.warmup()
        t0 = time.perf_counter()
        n_spec = sum(1 for _ in spec.generate_tokens(prompt, N_TOKENS))
        tps_spec = n_spec / (time.perf_counter() - t0)
        accept = spec.acceptance_rate
        spec.reset()
        log(f"speculative decode: {tps_spec:,.1f} tok/s "
            f"(gamma={gamma}, acceptance={accept:.2f})")

    # -- completion daemon e2e --------------------------------------------
    import threading

    from libsplinter_tpu import Store
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.completer import Completer

    name = f"/spt-bench-dec-{os.getpid()}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=4096, vec_dim=8)
    comp = Completer(st, model=model, max_new_tokens=32,
                     flush_tokens=CHUNK, template="none")
    comp.attach()
    log("completer e2e ...")
    e2e = []
    for i in range(3):
        key = f"q/{i}"
        t0 = time.perf_counter()
        st.set(key, "Say something interesting about TPUs.")
        st.label_or(key, P.LBL_INFER_REQ)
        st.bump(key)
        comp.run_once()
        e2e.append((time.perf_counter() - t0) * 1000)
        log(f"completer e2e request {i}: {e2e[-1]:.0f} ms")
    e2e_ms = float(np.median(e2e))
    log(f"completer e2e (32 new tokens): {e2e_ms:.0f} ms")

    # -- continuous serving: 12 staggered requests through the slot
    #    scheduler (engine/completer.py run_continuous)
    comp2 = Completer(st, model=model, max_new_tokens=32,
                      flush_tokens=CHUNK, template="none", batch_cap=8)
    comp2.attach()
    runner = threading.Thread(
        target=comp2.run_continuous,
        kwargs=dict(idle_timeout_ms=20, stop_after=600.0), daemon=True)
    runner.start()
    time.sleep(0.2)
    t0 = time.perf_counter()
    keys = []
    for i in range(12):
        key = f"c/{i}"
        keys.append(key)
        st.set(key, f"Question number {i} about accelerators?")
        st.label_or(key, P.LBL_INFER_REQ)
        st.bump(key)
        if i % 4 == 3:
            time.sleep(0.1)           # staggered arrival waves
    deadline = time.perf_counter() + 420
    while time.perf_counter() < deadline:
        if all(st.labels(k) & P.LBL_READY for k in keys):
            break
        time.sleep(0.01)
    cont_s = time.perf_counter() - t0
    comp2.stop()
    runner.join(timeout=5)
    done = sum(1 for k in keys if st.labels(k) & P.LBL_READY)
    cont_tps = comp2.stats.tokens / cont_s if done else 0.0
    log(f"continuous serving: {done}/12 ready in {cont_s:.2f}s, "
        f"{cont_tps:,.1f} aggregate tok/s (batch_cap=8)")
    st.close()
    Store.unlink(name)

    rec = {
        "metric": "decode_tokens_per_sec",
        "value": round(tps_chunked, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps_chunked / tps_serial, 3)
        if tps_serial > 0 else 0.0,
        "detail": {
            "backend": backend, "geometry": GEOMETRY,
            "quantized": quant,
            "layers": cfg.layers, "hidden": cfg.hidden,
            "chunk": CHUNK, "n_tokens": N_TOKENS,
            "prefill_ms_bucket64": round(prefill_ms, 2),
            "tokens_per_sec_serial_sync": round(tps_serial, 1),
            "tokens_per_sec_chunk32": round(tps_c32, 1),
            "tokens_per_sec_batch8_aggregate": round(tps_b8, 1),
            "tokens_per_sec_speculative": (round(tps_spec, 1)
                                           if tps_spec else None),
            "speculative_acceptance": (round(accept, 3)
                                       if accept is not None else None),
            "completer_e2e_ms_32tok": round(e2e_ms, 0),
            "continuous_12req_s": round(cont_s, 2),
            "continuous_aggregate_tok_s": round(cont_tps, 1),
            "continuous_ready": done,
        },
    }
    print(json.dumps(rec), flush=True)
    try:
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_results.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
