"""Decode-path benchmark: completion tokens/sec + daemon e2e latency.

Thin standalone wrapper over bench_series' decode phases (the single
implementation every tunnel client runs, VERDICT r3 #1):

  decode         prefill latency, chunked / per-token-sync / wide-chunk
                 / batched / speculative tokens per second (the
                 reference's cadence is a serial per-token llama.cpp
                 decode with an 8-token flush, splainference.cpp:333-354;
                 vs_baseline = chunked / per-token-sync on the SAME
                 hardware and weights), plus the paged-vs-dense KV
                 sweep: block-paged decode at batch {8, 32, 64} inside
                 a FIXED pool of 8 windows' pages (the r05 dense
                 batch=8 cache HBM envelope) — ledgered under the
                 kv_cache_dense / kv_cache_paged detail labels
  decode_daemon  completion-daemon e2e + continuous serving (now the
                 block-paged lane: batch_cap 32 default)

Prints ONE JSON line {"metric": "decode_tokens_per_sec", ...}; every
phase record appends to bench_results.jsonl.

Run strictly alone: the tunneled TPU admits one client.  Env:
BENCH_CPU=1, DECODE_TOKENS (256), DECODE_CHUNK (8),
DECODE_GEOMETRY=tiny|flagship, DECODE_QUANT=1 (int8 weight residency),
DECODE_DAEMON=0 (skip the daemon phase), DECODE_PAGED=0 (skip the
paged sweep), DECODE_PAGED_SWEEP=8,32,64 (batch widths; CPU default 8).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_series import shim_main  # noqa: E402

if __name__ == "__main__":
    phases = ["decode_quant" if os.environ.get("DECODE_QUANT") == "1"
              else "decode"]
    if os.environ.get("DECODE_DAEMON", "1") == "1":
        phases.append("decode_daemon")
    raise SystemExit(shim_main(*phases))
