"""Cross-lane distributed-tracing tier (`make trace-check`): the
trace-context stamp extension (trace id + parent span), the span-ring
wire protocol (staging rows, crash recovery with restart-gap
attribution, the atomically-claimed bounded ring), orphan sweeps (the
`__sr_` reaper discipline — raced rewrites cannot leak staging rows),
span-tree assembly parity across BOTH chain forms (client-chained
verbs and a stored script in the pipeline lane), the Chrome/Perfetto
export schema, loadgen head sampling, and the trace-through-chaos
drill (a supervised mid-chain lane crash yields a complete tree with
the restart gap visible, zero admitted loss)."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.client import (submit_completion,
                                           submit_embed)
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.engine.pipeliner import Pipeliner, submit_script
from libsplinter_tpu.engine.searcher import Searcher, submit_search
from libsplinter_tpu.obs import spans as S
from libsplinter_tpu.scripting.library import seed_library
from libsplinter_tpu.utils import faults

CHILD = os.path.join(os.path.dirname(__file__), "chaos_child.py")


@pytest.fixture(autouse=True)
def _no_faults():
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------ trace-context stamps

class TestTraceContext:
    def test_root_stamp_roundtrip(self, store):
        store.set("r", "req")
        span = P.stamp_trace(store, "r")
        idx = store.find_index("r")
        ctx = P.read_trace_ctx(store, idx, epoch=store.epoch_at(idx))
        assert ctx is not None
        tid, ts, parent, sp = ctx
        assert tid == span and sp == tid and parent == 0
        assert ts > 0
        # legacy 2-field view agrees
        assert P.read_trace_stamp(store, idx) == (tid, ts)

    def test_hop_stamp_joins_existing_trace(self, store):
        store.set("h", "hop")
        root = P.next_trace_id()
        span = P.stamp_trace(store, "h", trace_id=root, parent=root)
        idx = store.find_index("h")
        tid, _, parent, sp = P.read_trace_ctx(store, idx)
        assert tid == root and parent == root
        assert sp == span and sp != root      # fresh span id per hop

    def test_legacy_three_field_stamp_parses(self, store):
        store.set("l", "old")
        idx = store.find_index("l")
        store.set(P.trace_stamp_key(idx),
                  f"123456:1.5:{store.epoch_at(idx)}")
        tid, ts, parent, sp = P.read_trace_ctx(
            store, idx, epoch=store.epoch_at(idx))
        assert (tid, ts, parent, sp) == (123456, 1.5, 0, 123456)

    def test_stale_stamp_consumed_label_and_all(self, store):
        store.set("s", "one")
        P.stamp_trace(store, "s")
        store.set("s", "two")              # epoch moves: stamp stale
        idx = store.find_index("s")
        assert P.read_trace_ctx(store, idx,
                                epoch=store.epoch_at(idx)) is None
        with pytest.raises(KeyError):
            store.get(P.trace_stamp_key(idx))
        assert not store.labels("s") & P.LBL_TRACED

    def test_stamp_trace_ctx_forms(self, store):
        store.set("c", "x")
        assert P.stamp_trace_ctx(store, "c", None) is None
        assert P.stamp_trace_ctx(store, "c", True) is not None
        t = P.next_trace_id()
        sp = P.stamp_trace_ctx(store, "c", (t, 7))
        idx = store.find_index("c")
        tid, _, parent, got = P.read_trace_ctx(store, idx)
        assert (tid, parent, got) == (t, 7, sp)


# ------------------------------------------------------ the SpanWriter

class TestSpanWriter:
    def test_unstaged_begin_consumes_commit_buffers_flush_lands(
            self, store):
        store.set("q", "req")
        span_id = P.stamp_trace(store, "q")
        idx = store.find_index("q")
        w = S.SpanWriter(store, "searcher")
        pend = w.begin(idx, store.epoch_at(idx), tenant=3)
        assert pend is not None and pend.span == span_id
        # consume-early: the stamp + label retired at begin
        with pytest.raises(KeyError):
            store.get(P.trace_stamp_key(idx))
        assert not store.labels("q") & P.LBL_TRACED
        assert w.commit(pend, stages={"wake": 0.1})
        assert w.counters()["pending"] == 1
        assert S.collect_spans(store, pend.tid) == []   # buffered
        assert w.flush() == 1
        recs = S.collect_spans(store, pend.tid)
        assert len(recs) == 1
        r = recs[0]
        assert r["lane"] == "searcher" and r["key"] == "q"
        assert r["tenant"] == 3 and r["status"] == "ok"
        assert r["queue_ms"] >= 0 and r["service_ms"] >= 0
        assert r["stages"] == {"wake": 0.1}

    def test_begin_without_stamp_returns_none(self, store):
        store.set("n", "plain")
        idx = store.find_index("n")
        w = S.SpanWriter(store, "embedder")
        assert w.begin(idx, store.epoch_at(idx)) is None

    def test_staged_stamp_survives_until_commit(self, store):
        store.set("p", "script")
        P.stamp_trace(store, "p")
        idx = store.find_index("p")
        w = S.SpanWriter(store, "pipeliner", staged=True, eager=True)
        pend = w.begin(idx, store.epoch_at(idx))
        # consume-late: stamp AND staging row both live mid-service
        assert store.get(P.trace_stamp_key(idx))
        assert P.span_stage_key(idx) in store
        w.commit(pend)
        with pytest.raises(KeyError):
            store.get(P.trace_stamp_key(idx))
        assert P.span_stage_key(idx) not in store
        assert len(S.collect_spans(store, pend.tid)) == 1  # eager

    def test_crash_recovery_attempts_and_gap(self, store):
        """A staged writer that died mid-service: the restarted
        lane's begin() recovers the SAME span identity, bumps the
        attempt count, and attributes the restart gap."""
        store.set("x", "chain req")
        P.stamp_trace(store, "x")
        idx = store.find_index("x")
        e = store.epoch_at(idx)
        w1 = S.SpanWriter(store, "pipeliner", staged=True)
        p1 = w1.begin(idx, e)
        assert p1.attempts == 1
        time.sleep(0.05)                    # the "crash" window
        w2 = S.SpanWriter(store, "pipeliner", staged=True,
                          eager=True)       # the restarted lane
        p2 = w2.begin(idx, e)
        assert w2.recovered == 1
        assert p2.span == p1.span and p2.tid == p1.tid
        assert p2.attempts == 2
        assert p2.gap_ms >= 40.0
        assert p2.t_queue == p1.t_queue     # original queue clock
        w2.commit(p2)
        rec = S.collect_spans(store, p2.tid)[0]
        assert rec["attempts"] == 2 and rec["gap_ms"] >= 40.0

    def test_ring_bounded_and_multiwriter(self, store):
        n = S.span_ring_size(store)
        w1 = S.SpanWriter(store, "embedder", eager=True)
        w2 = S.SpanWriter(store, "searcher", eager=True)
        for i in range(n + 10):
            key = f"rb{i}"
            store.set(key, "r")
            P.stamp_trace(store, key)
            idx = store.find_index(key)
            w = w1 if i % 2 else w2
            w.commit(w.begin(idx, store.epoch_at(idx)))
        ring_keys = [k for k in store.list()
                     if k.startswith(P.SPAN_RING_PREFIX)
                     and k != P.KEY_SPAN_HEAD
                     and k[len(P.SPAN_RING_PREFIX):].isdigit()]
        assert len(ring_keys) <= n
        # the newest spans survived the wrap
        spans = S.collect_spans(store)
        assert len(spans) == n
        assert w1.committed + w2.committed == n + 10

    def test_newcomers_stamp_not_destroyed_by_staged_commit(
            self, store):
        """Consume-late cleanup is content-gated: a client that
        re-stamped the slot mid-service keeps its fresh stamp."""
        store.set("z", "first")
        P.stamp_trace(store, "z")
        idx = store.find_index("z")
        w = S.SpanWriter(store, "pipeliner", staged=True, eager=True)
        pend = w.begin(idx, store.epoch_at(idx))
        store.set("z", "second")            # client rewrote + re-
        fresh = P.stamp_trace(store, "z")   # stamped mid-service
        w.commit(pend)
        tid, _, _, sp = P.read_trace_ctx(store, idx)
        assert sp == fresh                  # newcomer's stamp intact


# ------------------------------------------------------------- sweeps

class TestSweeps:
    def _stage(self, store, key: str) -> int:
        store.set(key, "req")
        P.stamp_trace(store, key)
        idx = store.find_index(key)
        w = S.SpanWriter(store, "pipeliner", staged=True)
        assert w.begin(idx, store.epoch_at(idx)) is not None
        assert P.span_stage_key(idx) in store
        return idx

    def test_sweep_retires_epoch_moved(self, store):
        idx = self._stage(store, "sw1")
        store.set("sw1", "rewritten")       # raced rewrite
        assert S.sweep_span_stages(store) >= 1
        assert P.span_stage_key(idx) not in store

    def test_sweep_retires_ttl_expired(self, store):
        idx = self._stage(store, "sw2")
        assert S.sweep_span_stages(store) == 0   # fresh: kept
        assert S.sweep_span_stages(
            store, now=time.time() + S.STAGE_TTL_S + 1) >= 1
        assert P.span_stage_key(idx) not in store

    def test_sweep_retires_vanished_slot(self, store):
        idx = self._stage(store, "sw3")
        store.unset("sw3")
        S.sweep_span_stages(store)
        assert P.span_stage_key(idx) not in store

    def test_shed_orphan_stamp_retires_span_stage(self, store):
        """The lanes' dirty-mask discard path: a staging row whose
        request slot epoch moved (or whose labels cleared without a
        commit) is shed like an orphan trace stamp."""
        idx = self._stage(store, "sh1")
        store.set("sh1", "rewritten")
        sk = P.span_stage_key(idx)
        store.label_or(sk, P.LBL_DEBUG)     # surface via dirty mask
        sidx = store.find_index(sk)
        assert P.shed_orphan_stamp(store, sidx, store.labels_at(sidx))
        assert sk not in store

    def test_shed_orphan_keeps_pending_request_stage(self, store):
        store.set("sh2", "req")
        P.stamp_trace(store, "sh2")
        store.label_or("sh2", P.LBL_SCRIPT_REQ)   # still pending
        idx = store.find_index("sh2")
        w = S.SpanWriter(store, "pipeliner", staged=True)
        w.begin(idx, store.epoch_at(idx))
        sk = P.span_stage_key(idx)
        store.label_or(sk, P.LBL_DEBUG)
        sidx = store.find_index(sk)
        assert not P.shed_orphan_stamp(store, sidx,
                                       store.labels_at(sidx))
        assert sk in store                  # in-service: kept

    def test_churn_raced_rewrites_cannot_leak(self, store):
        """The satellite churn drill: scripts admitted (staged spans
        written), then raced by client rewrites before they commit —
        after the pump + the reaper cadence, no `__sp_` staging row
        survives and the ring stays bounded."""
        pl = Pipeliner(store)
        pl.attach()
        for i in range(24):
            key = f"ch{i}"
            store.set(key, json.dumps(
                {"script": "splinter.sleep(0.2) return 1"}))
            P.stamp_trace(store, key)
            store.label_or(key, P.LBL_SCRIPT_REQ | P.LBL_WAITING)
            store.bump(key)
            pl.pump()                       # admit (stages the span)
            store.set(key, f"raced rewrite {i}")   # client rewrites
            pl.pump()                       # observes the race
        # drain whatever re-parsed as garbage requests, then reap
        pl.run_once(timeout_s=10)
        pl.sweep_results()
        leaked = [k for k in store.list()
                  if k.startswith(P.SPAN_STAGE_PREFIX)]
        assert leaked == [], leaked
        ring = [k for k in store.list()
                if k.startswith(P.SPAN_RING_PREFIX)
                and k[len(P.SPAN_RING_PREFIX):].isdigit()]
        assert len(ring) <= S.span_ring_size(store)


# ----------------------------------------- typed statuses on rejects

class TestTypedStatusSpans:
    def test_embedder_shed_commits_typed_span(self, store):
        """A shed/expired traced embed request still gets its span —
        with the typed status — instead of silently vanishing from
        the tree (every other lane already commits one)."""
        emb = Embedder(store, encoder_fn=lambda ts: np.zeros(
            (len(ts), store.vec_dim), np.float32), max_ctx=64)
        emb.attach()
        store.set("shed1", "text")
        tid = P.stamp_trace(store, "shed1")
        idx = store.find_index("shed1")
        emb._shed_row(idx, tenant=2)
        emb.spans.flush()
        recs = S.collect_spans(store, tid)
        assert len(recs) == 1
        assert recs[0]["status"] == P.ERR_OVERLOADED
        assert recs[0]["tenant"] == 2
        with pytest.raises(KeyError):     # context retired with it
            store.get(P.trace_stamp_key(idx))

    def test_searcher_failed_request_span_not_ok(self, store):
        """A request failed with an error record must not render as
        an ok span in the tree."""
        sr = Searcher(store, interpret=True)
        sr.attach()
        store.set("sq", json.dumps({"k": 2}))
        v = np.zeros(store.vec_dim, np.float32)
        v[0] = 1.0
        store.vec_set("sq", v)
        P.stamp_trace(store, "sq")
        store.label_or("sq", P.LBL_SEARCH_REQ | P.LBL_WAITING)
        # poison every scoring path: the request fails terminally
        faults.arm("searcher.dispatch:raise@1-100")
        tid = None
        try:
            sr.run_once()
        finally:
            faults.disarm()
        sr.spans.flush()
        recs = [r for r in S.collect_spans(store)
                if r["lane"] == "searcher"]
        assert recs, "no searcher span committed"
        assert all(r["status"] != "ok" for r in recs), recs

    def test_pipeliner_ring_slot_reuse_no_stale_verbs(self, store,
                                                      monkeypatch):
        """FlightRecorder slots are reused dicts: a verb-free script
        landing in a slot whose previous occupant dispatched verbs
        must not inherit phantom counts."""
        from libsplinter_tpu.utils.trace import tracer

        monkeypatch.setattr(tracer, "enabled", True)
        pl = Pipeliner(store)
        pl.recorder._ring = [None]        # capacity 1: instant reuse
        pl.attach()
        store.set("v1", json.dumps(
            {"script": "splinter.sleep(0) return 1"}))
        P.stamp_trace(store, "v1")
        store.label_or("v1", P.LBL_SCRIPT_REQ)
        store.bump("v1")
        pl.run_once(timeout_s=5)
        assert pl.recorder.tail(1)[0]["verbs"] == {"sleep": 1}
        store.set("v2", json.dumps({"script": "return 2"}))
        P.stamp_trace(store, "v2")
        store.label_or("v2", P.LBL_SCRIPT_REQ)
        store.bump("v2")
        pl.run_once(timeout_s=5)
        rec = pl.recorder.tail(1)[0]
        assert rec["key"] == "v2"
        assert not rec["verbs"], rec      # no phantom inheritance


# ------------------------------------------------- assembly + export

def _mkspan(tid, span, parent, lane, t_admit, **kw):
    return {"tid": tid, "span": span, "parent": parent, "lane": lane,
            "key": f"k{span}", "idx": span, "e": 2, "status": "ok",
            "t_queue": t_admit - 0.001, "t_admit": t_admit,
            "t_commit": t_admit + 0.01, "queue_ms": 1.0,
            "service_ms": 10.0, "ts": t_admit + 0.01, **kw}


class TestAssembly:
    def test_tree_parent_links_and_sibling_order(self):
        spans = [_mkspan(9, 1, 0, "pipeliner", 100.0),
                 _mkspan(9, 3, 1, "searcher", 102.0),
                 _mkspan(9, 2, 1, "embedder", 101.0)]
        tree = S.assemble_tree(spans)
        root = tree["root"]
        assert root["span"]["lane"] == "pipeliner"
        kids = [n["span"]["lane"] for n in root["children"]]
        assert set(kids) == {"embedder", "searcher"}
        text = "\n".join(S.render_tree(tree))
        assert "queue=" in text and "service=" in text

    def test_orphan_parents_hang_under_synthesized_root(self):
        tid = 7
        spans = [_mkspan(tid, 2, tid, "embedder", 1.0),
                 _mkspan(tid, 3, tid, "searcher", 2.0)]
        tree = S.assemble_tree(spans)
        assert tree["root"]["span"] is None       # synthesized
        assert len(tree["root"]["children"]) == 2

    def test_chrome_export_schema(self):
        spans = [_mkspan(5, 1, 0, "pipeliner", 100.0,
                         stages={"exec": 1.0}),
                 _mkspan(5, 2, 1, "embedder", 101.0)]
        doc = S.to_chrome_trace(spans)
        body = json.loads(json.dumps(doc))        # round-trips
        assert body["displayTimeUnit"] == "ms"
        evs = body["traceEvents"]
        assert evs
        for e in evs:
            assert isinstance(e["name"], str)
            assert e["ph"] in ("X", "M")
            assert isinstance(e["pid"], int)
            if e["ph"] == "X":
                assert isinstance(e["ts"], (int, float))
                assert e["dur"] > 0
        # one metadata event names each lane's process
        meta = [e for e in evs if e["ph"] == "M"]
        assert {e["args"]["name"] for e in meta} == \
            {"lane:pipeliner", "lane:embedder"}
        # queue slices carry their own category
        assert any(e.get("cat") == "queue" for e in evs)


# ------------------------------------------- end-to-end chain trees

def _stack(store, stop_after=90.0):
    def enc(texts):
        out = np.zeros((len(texts), store.vec_dim), np.float32)
        for i, t in enumerate(texts):
            out[i, hash(t) % store.vec_dim] = 1.0
        return out

    emb = Embedder(store, encoder_fn=enc, max_ctx=64)
    sr = Searcher(store)
    comp = Completer(store, generate_fn=lambda p: iter([b"answer"]),
                     template="none")
    pl = Pipeliner(store)
    daemons = (emb, sr, comp, pl)
    for d in daemons:
        d.attach()
    # short flush cadences so span records land promptly
    ths = [threading.Thread(target=emb.run,
                            kwargs=dict(idle_timeout_ms=10,
                                        stop_after=stop_after,
                                        sweep_interval_s=0.25),
                            daemon=True),
           threading.Thread(target=sr.run,
                            kwargs=dict(idle_timeout_ms=10,
                                        stop_after=stop_after,
                                        heartbeat_interval_s=0.25),
                            daemon=True),
           threading.Thread(target=comp.run,
                            kwargs=dict(idle_timeout_ms=10,
                                        stop_after=stop_after),
                            daemon=True),
           threading.Thread(target=pl.run,
                            kwargs=dict(idle_timeout_ms=10,
                                        stop_after=stop_after),
                            daemon=True)]
    for t in ths:
        t.start()
    return daemons, ths


def _seed_docs(store, n=8):
    rng = np.random.default_rng(0)
    for i in range(n):
        k = f"lgd{i}"
        store.set(k, f"seed doc {i}")
        v = rng.standard_normal(store.vec_dim).astype(np.float32)
        store.vec_set(k, v / np.linalg.norm(v))


def _await_lanes(store, tid, want, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        recs = S.collect_spans(store, tid)
        if want <= {r["lane"] for r in recs}:
            return recs
        time.sleep(0.1)
    return S.collect_spans(store, tid)


class TestChainTrees:
    def test_client_chained_trace_tree(self, store):
        """Acceptance: ONE trace id spans the whole client-chained
        rag flow — each hop a span with its queue/service split."""
        daemons, ths = _stack(store)
        _seed_docs(store)
        try:
            tid = P.next_trace_id()
            assert submit_embed(store, "cd", "chain doc",
                                trace=(tid, tid),
                                timeout_ms=15_000) is True
            store.set("cq", "scratch")
            store.vec_set("cq", store.vec_get("cd"))
            res = submit_search(store, "cq", 3, trace=(tid, tid),
                                timeout_ms=15_000)
            assert res and "keys" in res, res
            out = submit_completion(store, "cc", "ctx: x",
                                    trace=(tid, tid),
                                    timeout_ms=15_000)
            assert isinstance(out, bytes), out
            recs = _await_lanes(
                store, tid, {"embedder", "searcher", "completer"})
            lanes = {r["lane"] for r in recs}
            assert {"embedder", "searcher", "completer"} <= lanes, \
                recs
            for r in recs:
                assert r["tid"] == tid
                assert r["queue_ms"] >= 0 and r["service_ms"] >= 0
                assert r["status"] == "ok"
            tree = S.assemble_tree(recs)
            # hops are siblings under the synthesized client root
            assert len(tree["root"]["children"]) >= 3
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=15)

    def test_stored_script_trace_tree_and_cli(self, store, capsys,
                                              monkeypatch):
        """Acceptance: the SAME chain as a stored script yields one
        tree rooted at the pipeliner's script span, verbs beneath it;
        `spt trace show` renders it and `spt trace export` emits
        loadable Chrome trace JSON."""
        from libsplinter_tpu.cli.main import main

        daemons, ths = _stack(store)
        _seed_docs(store)
        seed_library(store)
        try:
            tid = P.next_trace_id()
            rec = submit_script(store, "screq", name="rag-churn",
                                args=["sdoc", 1, 3],
                                trace=(tid, 0), timeout_ms=30_000)
            assert rec and rec.get("ok"), rec
            recs = _await_lanes(
                store, tid,
                {"pipeliner", "embedder", "searcher", "completer"})
            lanes = {r["lane"] for r in recs}
            assert {"pipeliner", "embedder", "searcher",
                    "completer"} <= lanes, recs
            tree = S.assemble_tree(recs)
            root = tree["root"]
            assert root["span"]["lane"] == "pipeliner"
            assert len(root["children"]) >= 3
            script_span = root["span"]["span"]
            for child in root["children"]:
                assert child["span"]["parent"] == script_span

            monkeypatch.setenv("SPTPU_DEFAULT_STORE", store.name)
            monkeypatch.delenv("SPTPU_NS_PREFIX", raising=False)
            assert main(["trace", "show", f"{tid:#x}"]) == 0
            out = capsys.readouterr().out
            assert "pipeliner" in out and "queue=" in out
            assert main(["trace", "export", f"{tid:#x}"]) == 0
            doc = json.loads(capsys.readouterr().out)
            assert doc["traceEvents"]
            names = {e["args"]["name"] for e in doc["traceEvents"]
                     if e["ph"] == "M"}
            assert "lane:pipeliner" in names
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=15)

    def test_loadgen_trace_sample_reports_slowest(self, store):
        """Satellite: `--trace-sample p` stamps sampled arrivals and
        the summary carries each tenant's slowest trace ids."""
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        daemons, ths = _stack(store)
        try:
            gen = LoadGenerator(
                store, [TenantSpec(1, 12.0, deadline_ms=10_000)],
                duration_s=1.2, corpus=8, seed=3,
                mix={"embed": 1.0, "search": 1.0},
                trace_sample=1.0)
            rep = gen.run()
            assert rep["ok"] >= 1, rep
            slow = rep["per_tenant"]["1"]["slow_traces"]
            assert 1 <= len(slow) <= 3
            for row in slow:
                assert row["trace"].startswith("0x")
                assert row["ms"] > 0
            # deterministic under seed: the sampled set replays
            gen2 = LoadGenerator(
                store, [TenantSpec(1, 12.0, deadline_ms=10_000)],
                duration_s=1.2, corpus=8, seed=3,
                mix={"embed": 1.0, "search": 1.0}, trace_sample=0.0)
            rep2 = gen2.run()
            assert "slow_traces" not in rep2["per_tenant"]["1"]
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=15)


# ------------------------------------------------ trace-through-chaos

@pytest.mark.slow
@pytest.mark.chaos
def test_trace_through_supervised_crash(store, monkeypatch):
    """Satellite: a supervised mid-chain pipeliner crash
    (`pipeliner.exec:crash@2` — after the embed hop resolves) still
    yields a COMPLETE span tree for the traced script: the restarted
    lane recovers the staged span, the script span shows attempts>=2
    with the restart gap, every downstream hop is present, and the
    admitted script is not lost (its result commits ok)."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    monkeypatch.setenv("SPTPU_FAULT", "pipeliner.exec:crash@2")
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")

    daemons, ths = _stack(store, stop_after=240.0)
    daemons[-1].stop()                 # the SUPERVISED child serves
    _seed_docs(store)
    seed_library(store)

    holder: dict = {}

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, CHILD, "pipeliner", store.name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(store.name, lanes=("pipeliner",), spawn_fn=spawn,
                     store=store, backoff_base_ms=100,
                     backoff_max_ms=1500, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 240.0})
    t.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if P.heartbeat_live(store, P.KEY_SCRIPT_STATS,
                                max_age_s=30):
                break
            time.sleep(0.2)
        else:
            pytest.fail("pipeliner never came up under supervision")
        tid = P.next_trace_id()
        rec = submit_script(store, "chaosreq", name="rag-churn",
                            args=["cdoc", 1, 3], trace=(tid, 0),
                            timeout_ms=120_000)
        # zero admitted-request loss: the re-run commits a result
        assert rec is not None and rec.get("ok"), rec
        assert sup.lanes["pipeliner"].restarts >= 1
        recs = _await_lanes(
            store, tid,
            {"pipeliner", "embedder", "searcher", "completer"},
            timeout_s=30.0)
        lanes = {r["lane"] for r in recs}
        assert {"pipeliner", "embedder", "searcher",
                "completer"} <= lanes, recs
        script = [r for r in recs if r["lane"] == "pipeliner"]
        assert len(script) == 1, script
        # the restart gap is visible on the affected span
        assert script[0].get("attempts", 1) >= 2, script
        assert script[0].get("gap_ms", 0) > 0, script
        assert script[0]["status"] == "ok"
        # and the tree is complete: verbs hang under the script span
        tree = S.assemble_tree(recs)
        assert tree["root"]["span"]["lane"] == "pipeliner"
        assert len(tree["root"]["children"]) >= 3
    finally:
        sup.stop()
        t.join(timeout=30)
        sup.shutdown()
        for d in daemons:
            d.stop()
        for th in ths:
            th.join(timeout=15)
