"""Fault-injection layer (utils/faults.py): spec compilation, trigger
semantics, actions, and the crash action's unclean-exit contract.
`make chaos-check` runs this tier alongside the crash-recovery
matrix."""
from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from libsplinter_tpu.store import Eagain
from libsplinter_tpu.utils import faults
from libsplinter_tpu.utils.faults import (CRASH_EXIT_CODE, FaultInjected,
                                          FaultSpecError, fault)

pytestmark = pytest.mark.chaos

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with no faults armed."""
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------- parsing

def test_parse_full_spec():
    n = faults.arm("searcher.commit:crash@3,embedder.encode:raise@p0.1,"
                   "store.set:eagain,completer.commit:stall250@2-4")
    assert n == 4
    s = faults.stats()
    assert s["searcher.commit"]["spec"] == "searcher.commit:crash@3"
    assert s["embedder.encode"]["spec"] == "embedder.encode:raise@p0.1"
    assert s["store.set"]["spec"] == "store.set:eagain"
    assert s["completer.commit"]["spec"] == "completer.commit:stall250@2-4"


def test_registered_sites_shares_the_grammar():
    """registered_sites() is the spec-grammar entry point splint and
    the chaos drills share: spec -> site names in spec order, armed
    plan by default, and a typo fails at parse like arm() would."""
    assert faults.registered_sites(
        "searcher.commit:crash@3, embedder.encode:raise@p0.1,"
        "completer.commit:stall250@2-4") == (
        "searcher.commit", "embedder.encode", "completer.commit")
    assert faults.registered_sites("") == ()
    faults.arm("store.set:eagain")
    assert faults.registered_sites() == ("store.set",)
    faults.disarm()
    assert faults.registered_sites() == ()
    with pytest.raises(FaultSpecError):
        faults.registered_sites("store.set-eagain")


def test_parse_rejects_garbage():
    for bad in ("nosite", "a.b:explode", "a.b:raise@p7", "a.b:crash@0",
                "a.b:crash@5-2", "a.b:stallfast", "a.b:raise@x"):
        with pytest.raises(FaultSpecError):
            faults.arm(bad)


def test_arm_reads_env(monkeypatch):
    monkeypatch.setenv("SPTPU_FAULT", "x.y:raise@1")
    assert faults.arm() == 1
    assert faults.armed()
    monkeypatch.delenv("SPTPU_FAULT")
    assert faults.arm() == 0
    assert not faults.armed()


# ------------------------------------------------------------ triggers

def test_nth_hit_fires_once():
    faults.arm("s.x:raise@3")
    fault("s.x")
    fault("s.x")
    with pytest.raises(FaultInjected):
        fault("s.x")
    fault("s.x")                      # 4th hit: window passed
    st = faults.stats()["s.x"]
    assert st["hits"] == 4 and st["fired"] == 1


def test_hit_range_defeats_retry_ladders():
    faults.arm("s.x:raise@2-3")
    fault("s.x")                      # hit 1: clean
    for _ in range(2):                # hits 2..3: fire
        with pytest.raises(FaultInjected):
            fault("s.x")
    fault("s.x")                      # hit 4: clean again


def test_every_hit_without_trigger():
    faults.arm("s.x:raise")
    for _ in range(3):
        with pytest.raises(FaultInjected):
            fault("s.x")
    assert faults.stats()["s.x"]["fired"] == 3


def test_probability_deterministic_under_seed(monkeypatch):
    monkeypatch.setenv("SPTPU_FAULT_SEED", "1234")
    faults.arm("s.x:raise@p0.5")
    outcomes = []
    for _ in range(64):
        try:
            fault("s.x")
            outcomes.append(False)
        except FaultInjected:
            outcomes.append(True)
    assert 8 < sum(outcomes) < 56     # actually probabilistic
    faults.arm("s.x:raise@p0.5")      # same seed: same sequence
    outcomes2 = []
    for _ in range(64):
        try:
            fault("s.x")
            outcomes2.append(False)
        except FaultInjected:
            outcomes2.append(True)
    assert outcomes == outcomes2


def test_unmatched_site_is_free():
    faults.arm("s.x:raise")
    fault("other.site")               # no entry: no-op
    assert "other.site" not in faults.stats()


# ------------------------------------------------------------- actions

def test_eagain_action_raises_store_eagain():
    faults.arm("s.x:eagain@1")
    with pytest.raises(Eagain):
        fault("s.x")


def test_stall_action_sleeps():
    faults.arm("s.x:stall80@1")
    t0 = time.perf_counter()
    fault("s.x")
    assert (time.perf_counter() - t0) >= 0.06
    t0 = time.perf_counter()
    fault("s.x")                      # past the window: no stall
    assert (time.perf_counter() - t0) < 0.05


def test_crash_action_is_unclean_exit():
    """crash = os._exit(137): no atexit, no finally — the closest
    Python gets to dying at the faulted instruction.  Loads faults.py
    by file path so the child skips the full package import."""
    path = os.path.join(ROOT, "libsplinter_tpu", "utils", "faults.py")
    code = (
        "import atexit, importlib.util, sys\n"
        "atexit.register(lambda: print('ATEXIT RAN'))\n"
        f"spec = importlib.util.spec_from_file_location('flt', {path!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "sys.modules['flt'] = m\n"    # dataclasses resolve via sys.modules
        "spec.loader.exec_module(m)\n"
        "m.arm('s.x:crash@1')\n"
        "try:\n"
        "    m.fault('s.x')\n"
        "finally:\n"
        "    print('FINALLY RAN')\n"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == CRASH_EXIT_CODE
    assert "FINALLY RAN" not in out.stdout
    assert "ATEXIT RAN" not in out.stdout


def test_disarmed_fault_is_noop_hot_path():
    fault("anything.at.all")          # must simply return


# ---------------------------------------------------- daemon heartbeat

def test_armed_faults_ride_the_searcher_heartbeat(store):
    """With SPTPU_FAULT armed, the daemon heartbeat carries the site
    accounting so `spt metrics` can show which points a drill hit."""
    import json

    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.searcher import Searcher

    faults.arm("searcher.gather:stall1@999")   # armed, never fires
    sr = Searcher(store)
    sr.attach()
    sr.run_once()
    sr.publish_stats()
    snap = json.loads(store.get(P.KEY_SEARCH_STATS).rstrip(b"\0"))
    assert snap["faults"]["searcher.gather"]["hits"] >= 1
    assert snap["faults"]["searcher.gather"]["fired"] == 0
    assert snap["generation"] == 1
    assert snap["pid"] == os.getpid()
