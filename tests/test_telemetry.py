"""Telemetry-history tier (`make trace-check`): the sampler's
fixed-size time-series rings (store-resident — they survive the
sampler), queue depth measured from labels rather than trusted from
heartbeats, per-gauge bounding + max_val degradation, supervised
restart with rings intact, and the operator surfaces (`spt metrics
--history`, `spt top --once`)."""
import json
import subprocess
import sys
import time

import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.telemetry import (SCRAPE_LANES,
                                              TelemetrySampler,
                                              read_history)


def _fake_heartbeat(store, key, **fields):
    P.publish_heartbeat(store, key, dict(fields))


class TestSampler:
    def test_rings_accumulate_counters_and_queue_depth(self, store):
        _fake_heartbeat(store, P.KEY_EMBED_STATS, shed=2, deferred=1,
                        deadline_expired=0, embedded=42)
        for i in range(3):
            store.set(f"q{i}", "waiting")
            store.label_or(f"q{i}", P.LBL_EMBED_REQ)
        tel = TelemetrySampler(store, interval_s=0.1)
        tel.attach()
        assert tel.sample_once() >= 1
        hist = read_history(store, "embedder")
        assert hist is not None
        g = hist["gauges"]
        assert g["queue_depth"][-1][1] == 3.0     # measured, not told
        assert g["shed"][-1][1] == 2.0
        assert g["progress"][-1][1] == 42.0       # embedded
        assert tel.stats.samples == 1
        # every scrape lane gets a ring (gauge floor: queue_depth)
        for lane in SCRAPE_LANES:
            assert read_history(store, lane) is not None

    def test_ring_len_bounded(self, store):
        _fake_heartbeat(store, P.KEY_SEARCH_STATS, shed=0, served=1)
        tel = TelemetrySampler(store, interval_s=0.1, ring_len=4)
        tel.attach()
        for k in range(10):
            tel.sample_once(now=1000.0 + k)
        g = read_history(store, "searcher")["gauges"]
        assert len(g["queue_depth"]) == 4
        assert g["queue_depth"][0][0] == 1006.0   # oldest retained

    def test_stage_p99_and_tenant_gauges(self, store):
        _fake_heartbeat(
            store, P.KEY_SCRIPT_STATS, scripts_completed=5,
            quantiles={"e2e": {"p99_ms": 12.5},
                       "exec": {"p99_ms": 3.25}},
            tenants={"1": {"admitted": 7, "served_tokens": 90}})
        tel = TelemetrySampler(store)
        tel.attach()
        tel.sample_once()
        g = read_history(store, "pipeliner")["gauges"]
        assert g["p99_e2e_ms"][-1][1] == 12.5
        assert g["p99_exec_ms"][-1][1] == 3.25
        assert g["tenant1_admitted"][-1][1] == 7.0
        assert g["tenant1_served_tokens"][-1][1] == 90.0

    def test_restart_resumes_rings_in_store(self, store):
        """The acceptance property: rings are STORE state — a new
        sampler generation appends to the history the dead one
        left."""
        _fake_heartbeat(store, P.KEY_EMBED_STATS, embedded=1)
        t1 = TelemetrySampler(store)
        t1.attach()
        for k in range(3):
            t1.sample_once(now=2000.0 + k)
        gen1 = t1.generation
        del t1                                    # the "crash"
        t2 = TelemetrySampler(store)
        t2.attach()                               # the restart
        assert t2.generation == gen1 + 1
        t2.sample_once(now=2010.0)
        ring = read_history(store, "embedder")["gauges"]["queue_depth"]
        assert len(ring) == 4                     # 3 old + 1 new
        assert ring[0][0] == 2000.0

    def test_oversized_ring_shrinks_not_drops(self, tmp_path):
        name = f"/spt-tele-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=64, max_val=256, vec_dim=0)
        try:
            _fake_heartbeat(st, P.KEY_EMBED_STATS, shed=1, deferred=2,
                            deadline_expired=3, embedded=4)
            tel = TelemetrySampler(st, ring_len=64)
            tel.attach()
            for k in range(40):
                tel.sample_once(now=3000.0 + k)
            hist = read_history(st, "embedder")
            assert hist is not None               # still renders
            assert tel.stats.shrinks > 0          # degraded, not lost
            assert tel.stats.write_errors == 0
            for ring in hist["gauges"].values():
                assert 1 <= len(ring) < 64
        finally:
            st.close()
            Store.unlink(name)

    def test_sampler_heartbeat_publishes(self, store):
        tel = TelemetrySampler(store)
        tel.attach()
        tel.sample_once()
        tel.publish_stats()
        snap = json.loads(
            store.get(P.KEY_TELEMETRY_STATS).rstrip(b"\0"))
        assert snap["samples"] == 1
        assert snap["generation"] == tel.generation
        assert snap["points"] > 0


class TestOperatorSurfaces:
    def _sampled(self, store, monkeypatch):
        _fake_heartbeat(store, P.KEY_EMBED_STATS, shed=1, embedded=9)
        _fake_heartbeat(store, P.KEY_SEARCH_STATS, shed=0, served=4)
        tel = TelemetrySampler(store)
        tel.attach()
        for k in range(5):
            tel.sample_once(now=4000.0 + k)
        tel.publish_stats()
        monkeypatch.setenv("SPTPU_DEFAULT_STORE", store.name)
        monkeypatch.delenv("SPTPU_NS_PREFIX", raising=False)

    def test_metrics_history_renders_gauges(self, store, capsys,
                                            monkeypatch):
        """Acceptance: `spt metrics --history` renders >= 2 gauges'
        time series per (sampled) lane."""
        from libsplinter_tpu.cli.main import main

        self._sampled(store, monkeypatch)
        assert main(["metrics", "--history"]) == 0
        out = capsys.readouterr().out
        for lane in ("embedder", "searcher"):
            assert f"[{lane}]" in out
        # per-lane gauge floor: queue_depth + at least one counter
        assert out.count("queue_depth") >= 2
        assert "shed" in out and "progress" in out
        assert "last=" in out and "min=" in out

    def test_metrics_exposition_covers_telemetry_lane(
            self, store, capsys, monkeypatch):
        from libsplinter_tpu.cli.main import main

        self._sampled(store, monkeypatch)
        assert main(["metrics"]) == 0
        out = capsys.readouterr().out
        assert "sptpu_telemetry_samples" in out
        assert "sptpu_telemetry_points" in out

    def test_top_once_renders_frame(self, store, capsys, monkeypatch):
        from libsplinter_tpu.cli.main import main

        self._sampled(store, monkeypatch)
        assert main(["top", "--once"]) == 0
        out = capsys.readouterr().out
        assert "spt top" in out
        for lane in ("embedder", "searcher", "completer",
                     "pipeliner"):
            assert lane in out
        assert "telemetry" in out
        assert "queue" in out

    def test_top_frames_loop(self, store, capsys, monkeypatch):
        from libsplinter_tpu.cli.main import main

        self._sampled(store, monkeypatch)
        assert main(["top", "--frames", "2", "--interval",
                     "0.05"]) == 0
        assert capsys.readouterr().out.count("spt top") == 2


class TestSupervised:
    def test_registered_as_supervisable_lane(self):
        from libsplinter_tpu.engine.supervisor import LANES

        spec = LANES["telemetry"]
        assert spec.module == "libsplinter_tpu.engine.telemetry"
        assert spec.heartbeat_key == P.KEY_TELEMETRY_STATS
        assert spec.max_replicas == 1    # the sampler never stripes

    @pytest.mark.slow
    def test_supervised_restart_keeps_rings(self, store):
        """Acceptance: kill the live sampler child mid-run — the
        supervisor respawns it, the generation bumps, and the rings
        keep growing from where the dead generation left them."""
        from libsplinter_tpu.engine.supervisor import Supervisor

        _fake_heartbeat(store, P.KEY_EMBED_STATS, embedded=1)

        def spawn(lane):
            return subprocess.Popen(
                [sys.executable, "-m",
                 "libsplinter_tpu.engine.telemetry",
                 "--store", store.name, "--interval-s", "0.1"])

        sup = Supervisor(store.name, lanes=("telemetry",),
                         spawn_fn=spawn, store=store,
                         backoff_base_ms=100, backoff_max_ms=1000,
                         breaker_threshold=10, breaker_window_s=60,
                         startup_grace_s=60, healthy_after_s=1.0)
        t0 = time.monotonic()

        def ring_len():
            h = read_history(store, "embedder")
            return len(h["gauges"]["queue_depth"]) if h else 0

        try:
            while ring_len() < 3 and time.monotonic() - t0 < 30:
                sup.poll_once()
                time.sleep(0.1)
            assert ring_len() >= 3, "sampler never produced history"
            n_before = ring_len()
            gen_before = sup.lanes["telemetry"].generation
            sup.lanes["telemetry"].proc.kill()    # the chaos moment
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                sup.poll_once()
                if sup.lanes["telemetry"].generation > gen_before \
                        and ring_len() > n_before:
                    break
                time.sleep(0.1)
            assert sup.lanes["telemetry"].generation > gen_before
            assert ring_len() > n_before          # rings intact AND
            # the ring still starts with pre-crash samples (intact,
            # not recreated) unless it wrapped
            snap = json.loads(
                store.get(P.KEY_TELEMETRY_STATS).rstrip(b"\0"))
            assert snap["generation"] >= 2        # growing
        finally:
            sup.shutdown()
