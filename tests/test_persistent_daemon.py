"""Daemons over the persistent (file-backed) store: the reference
doubles every binary for its persistent variant (CMakeLists dual
targets); here the backend is a runtime flag, so the serving lattice
must hold over it — including daemon restart against the surviving
file (the store IS the checkpoint, SURVEY.md §5)."""
from __future__ import annotations

import os

import numpy as np
import pytest

from libsplinter_tpu import Store, T_VARTEXT
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig


@pytest.fixture
def pstore(tmp_path):
    path = str(tmp_path / "persist.spt")
    st = Store.create(path, nslots=64, max_val=1024, vec_dim=8,
                      persistent=True)
    yield path, st
    try:
        st.close()
    except Exception:
        pass
    if os.path.exists(path):
        os.unlink(path)


def test_embedder_over_persistent_store(pstore):
    path, st = pstore
    emb = Embedder(st, encoder_fn=lambda ts: np.full(
        (len(ts), 8), 2.0, np.float32), max_ctx=64)
    emb.attach()
    st.set("k", "persistent text")
    st.set_type("k", T_VARTEXT)
    st.label_or("k", P.LBL_EMBED_REQ)
    assert emb.run_once() == 1
    assert st.vec_get("k")[0] == 2.0

    # the file survives close; a fresh open sees the committed vector
    st.close()
    st2 = Store.open(path, persistent=True)
    try:
        assert st2.vec_get("k")[0] == 2.0
        assert not st2.labels("k") & P.LBL_EMBED_REQ
    finally:
        st2.close()


def test_completer_restart_drains_surviving_requests(pstore):
    """A WAITING key written before a crash survives in the file; the
    restarted daemon's cold-start drain services it (the reference's
    splainference cold-start, over OUR persistent backend)."""
    path, st = pstore
    st.set("q", "question before the crash")
    st.label_or("q", P.LBL_INFER_REQ | P.LBL_WAITING)
    st.close()                        # "crash": nothing serviced it

    st2 = Store.open(path, persistent=True)
    try:
        model = CompletionModel(DecoderConfig.tiny(), buckets=(32,),
                                temp=0.0)
        comp = Completer(st2, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        assert comp.run_once() == 1
        assert st2.labels("q") & P.LBL_READY
    finally:
        st2.close()
