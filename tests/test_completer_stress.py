"""Completion daemon under concurrent writer churn: clients post,
overwrite, and delete prompts while the continuous scheduler serves.
The invariant is liveness — no key may end wedged in SERVICING, and
the daemon must survive every race (the engine-level analog of the
chi-sao harness, run against the LIVE serving loop)."""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig

N_CLIENTS = 6
REQS_PER_CLIENT = 5


@pytest.mark.slow
def test_continuous_daemon_survives_writer_churn(tmp_path):
    name = f"/spt-cstress-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=2048, vec_dim=8)
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=12,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        runner = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=240.0),
            daemon=True)
        runner.start()
        time.sleep(0.2)

        def client(c: int):
            rng = np.random.default_rng(c)
            for r in range(REQS_PER_CLIENT):
                k = f"c{c}/r{r}"
                st.set(k, f"client {c} request {r}")
                st.label_or(k, P.LBL_INFER_REQ)
                st.bump(k)
                if rng.uniform() < 0.3:
                    # churn: overwrite the prompt right after posting
                    # (the daemon may catch either version; the label
                    # protocol must resolve it without wedging)
                    st.set(k, f"client {c} request {r} v2")
                    st.label_or(k, P.LBL_INFER_REQ)
                    st.bump(k)
                time.sleep(float(rng.uniform(0.005, 0.05)))

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True)
                   for c in range(N_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "client wedged"

        keys = [f"c{c}/r{r}" for c in range(N_CLIENTS)
                for r in range(REQS_PER_CLIENT)]
        deadline = time.time() + 180
        while time.time() < deadline:
            if all(st.labels(k) & P.LBL_READY
                   and not st.labels(k) & P.LBL_SERVICING
                   for k in keys):
                break
            time.sleep(0.1)
        assert runner.is_alive(), "daemon crashed under churn"
        comp.stop()
        runner.join(timeout=10)

        wedged = [k for k in keys
                  if st.labels(k) & P.LBL_SERVICING
                  or not st.labels(k) & P.LBL_READY]
        assert not wedged, (wedged[:6], comp.stats)
        assert comp.stats.completions >= len(keys)
        print(f"stats: {comp.stats}")
    finally:
        st.close()
        Store.unlink(name)
