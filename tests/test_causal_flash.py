"""Causal (decoder-prefill) flash kernel: blockwise attention over the
KV cache with the slot-causal + left-pad-start mask, equal to the
decoder's naive masked softmax."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig
from libsplinter_tpu.ops.flash_attention import (_causal_jnp,
                                                 causal_flash_attention)


def _rand(shape, seed=0):
    return np.random.default_rng(seed).normal(
        0, 1, shape).astype(np.float32)


@pytest.mark.parametrize("S,T,bq,pos,starts,kh", [
    (32, 64, 16, 0, (0, 5), 2),   # prefill at slot 0, left-padded rows
    (24, 64, 16, 8, (0, 0), 2),   # joiner-style offset prefill, padded S
    (16, 32, 16, 16, (4, 12), 2),  # chunk at the window tail
    (32, 64, 16, 0, (0, 3), 1),   # GQA: 4 query heads share 1 kv head
])
def test_causal_kernel_matches_naive(S, T, bq, pos, starts, kh):
    B, H, D = 2, 4, 8
    q = jnp.asarray(_rand((B, S, H, D), 1))
    kk = jnp.asarray(_rand((B, T, kh, D), 2))     # UNREPEATED kv heads
    vv = jnp.asarray(_rand((B, T, kh, D), 3))
    start = jnp.asarray(np.asarray(starts, np.int32))
    got = causal_flash_attention(q, kk, vv, jnp.int32(pos), start,
                                 block_q=bq, interpret=True)
    rep = H // kh
    kkr = jnp.repeat(kk, rep, axis=2)
    vvr = jnp.repeat(vv, rep, axis=2)
    want = _causal_jnp(q, kkr, vvr, jnp.int32(pos), start)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_decoder_flash_prefill_matches_naive(monkeypatch):
    """Same params: generation through the causal kernel prefill
    equals the naive-path generation token for token, serial and
    batched (left-padded starts).  interpret is forced through the
    decoder's own call site so CI exercises the ACTUAL kernel, not
    the CPU jnp fallback."""
    import functools

    import libsplinter_tpu.ops.flash_attention as fa

    monkeypatch.setattr(
        fa, "causal_flash_attention",
        functools.partial(fa.causal_flash_attention, interpret=True))
    base = DecoderConfig.tiny(dtype=jnp.float32)          # naive
    flsh = DecoderConfig.tiny(dtype=jnp.float32, flash_min_seq=16)
    mb = CompletionModel(base, buckets=(16, 32), temp=0.0, seed=3)
    mf = CompletionModel(flsh, buckets=(16, 32), temp=0.0,
                         params=mb.params)
    prompts = [np.arange(1, 20, dtype=np.int32),          # bucket 32
               np.array([5, 4, 3], np.int32)]
    for p in prompts:
        want = [int(x) for x in mb.generate_tokens(p, 10, chunk=4)]
        mb.reset()
        got = [int(x) for x in mf.generate_tokens(p, 10, chunk=4)]
        mf.reset()
        assert got == want, (got, want)
    bwant = [list(map(int, c))
             for c in mb.generate_batch(prompts, 8, chunk=4)]
    mb.reset()
    bgot = [list(map(int, c))
            for c in mf.generate_batch(prompts, 8, chunk=4)]
    mf.reset()
    assert bgot == bwant


def test_causal_kernel_requires_no_grad():
    """Serving-only contract: jax.grad through the kernel path raises
    instead of silently producing wrong gradients."""
    q = jnp.asarray(_rand((1, 16, 2, 8), 1))
    kv = jnp.asarray(_rand((1, 32, 2, 8), 2))

    def loss(q):
        return jnp.sum(causal_flash_attention(
            q, kv, kv, jnp.int32(0), None, block_q=16,
            interpret=True) ** 2)

    # the forward itself must be healthy — otherwise ANY failure would
    # satisfy the raises check below without testing the contract
    assert np.isfinite(float(loss(q)))
    with pytest.raises(Exception):
        jax.grad(loss)(q)
