"""Coordination protocols: signal arena, bloom->group routing, event bus +
dirty mask, and the full shard election matrix (priority, expiry,
claimed_at/pid tie-breaks, DONTNEED bumper, rebid revival, ENOSPC on the
33rd bid, sovereign/non-sovereign madvise) — parity with
splinter_test.c:416-513 per SURVEY.md §4, with forged bids standing in for
other processes (the reference's determinism trick)."""
import os
import threading
import time

import pytest

import libsplinter_tpu as sp
from libsplinter_tpu import Store

WILLNEED = sp.ADV_WILLNEED
DONTNEED = sp.ADV_DONTNEED
SEQ = sp.ADV_SEQUENTIAL
HOUR_US = 3_600_000_000


# ---------------------------------------------------------------- signals

def test_signal_pulse_and_count(store):
    assert store.signal_count(5) == 0
    store.pulse(5)
    store.pulse(5)
    assert store.signal_count(5) == 2
    assert store.signal_count(6) == 0


def test_watch_register_pulses_on_write(store):
    store.set("watched", b"v0")
    store.watch_register("watched", 7)
    c0 = store.signal_count(7)
    store.set("watched", b"v1")
    assert store.signal_count(7) == c0 + 1
    store.set("unrelated", b"x")
    assert store.signal_count(7) == c0 + 1


def test_watch_unregister(store):
    store.set("w", b"x")
    store.watch_register("w", 3)
    store.watch_unregister("w", 3)
    c0 = store.signal_count(3)
    store.set("w", b"y")
    assert store.signal_count(3) == c0


def test_label_watch_routes_by_bloom_bit(store):
    # bloom bit 0 (label 0x1) -> group 9: the embedding-daemon wake pattern
    store.watch_label_register(0, 9)
    store.set("doc", b"text")
    c0 = store.signal_count(9)
    store.label_or("doc", 0x1)
    store.bump("doc")
    assert store.signal_count(9) == c0 + 1
    # subsequent writes to the labelled key keep pulsing
    store.set("doc", b"more text")
    assert store.signal_count(9) == c0 + 2


def test_label_watch_multiple_groups_per_bit(store):
    """TPU-first delta: one bloom bit can fan out to several groups."""
    store.watch_label_register(2, 11)
    store.watch_label_register(2, 12)
    store.set("multi", b"x")
    store.label_or("multi", 0x4)
    store.bump("multi")
    assert store.signal_count(11) == 1
    assert store.signal_count(12) == 1


def test_bump_pulses_without_write(store):
    store.set("b", b"x")
    store.watch_register("b", 4)
    e0 = store.epoch("b")
    store.bump("b")
    assert store.epoch("b") == e0  # no write happened
    assert store.signal_count(4) == 1


def test_bump_missing_key(store):
    with pytest.raises(KeyError):
        store.bump("ghost")


def test_signal_wait_timeout(store):
    assert store.signal_wait(8, last=0, timeout_ms=30) is None


def test_signal_wait_wakes(store):
    done = {}

    def waiter():
        done["count"] = store.signal_wait(13, last=0, timeout_ms=3000)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.03)
    w = Store.open(store.name)
    w.pulse(13)
    w.close()
    t.join()
    assert done["count"] == 1


# --------------------------------------------------------------- event bus

def test_bus_init_and_dirty_mask(store):
    store.bus_init()
    assert store.header().bus_pid == os.getpid()
    store.set("d1", b"x")
    store.set("d2", b"y")
    bits = store.drain_dirty()
    idx1, idx2 = store.find_index("d1"), store.find_index("d2")
    assert idx1 % 1024 in bits and idx2 % 1024 in bits
    # drain clears
    assert store.drain_dirty() == []


def test_bus_peek_does_not_clear(store):
    store.bus_init()
    store.set("p", b"x")
    words = store.drain_dirty()  # clear
    store.set("p", b"y")
    import ctypes
    assert len(store.drain_dirty()) == 1  # p only, after a peek-like cycle


def test_bus_not_armed_no_dirty_tracking(store):
    store.set("quiet", b"x")
    assert store.drain_dirty() == []   # fast path: unarmed bus skips marks


def test_bus_wait_wakes_on_write(store):
    store.bus_init()
    woke = {}

    def writer():
        time.sleep(0.03)
        w = Store.open(store.name)
        w.set("wake", b"x")
        w.close()

    t = threading.Thread(target=writer)
    t.start()
    woke["r"] = store.bus_wait(2000)
    t.join()
    assert woke["r"] is True
    assert len(store.drain_dirty()) >= 1


def test_bus_wait_timeout(store):
    store.bus_init()
    store.drain_dirty()
    t0 = time.monotonic()
    assert store.bus_wait(50) is False
    assert time.monotonic() - t0 < 1.0


def test_bus_unarmed_wait_returns_false(store):
    assert store.bus_wait(10) is False


def test_dirty_to_indices_small_store(store):
    store.bus_init()
    store.set("m1", b"x")
    bits = store.drain_dirty()
    idxs = store.dirty_to_indices(bits)
    assert store.find_index("m1") in idxs


# ------------------------------------------------------------ shard bids

def test_claim_and_election_single(store):
    b = store.shard_claim(0x5F10, WILLNEED, priority=40,
                          duration_us=HOUR_US)
    assert b >= 0
    assert store.shard_election() == b
    info = store.bid_info(b)
    assert info.pid == os.getpid()
    assert info.shard_id == 0x5F10
    assert info.live


def test_election_no_bids(store):
    assert store.shard_election() is None


def _now_us():
    return Store.now() // Store.ticks_per_us()


def test_election_priority_wins(store):
    now = _now_us()
    lo = store.shard_claim_ex(1, pid=100, intent=WILLNEED, priority=10,
                              duration_us=HOUR_US, claimed_at_us=now)
    hi = store.shard_claim_ex(2, pid=200, intent=WILLNEED, priority=200,
                              duration_us=HOUR_US, claimed_at_us=now + 1000)
    assert store.shard_election() == hi
    store.shard_release(hi)
    assert store.shard_election() == lo


def test_election_tie_earliest_claim(store):
    now = _now_us()
    late = store.shard_claim_ex(1, pid=100, intent=WILLNEED, priority=50,
                                duration_us=HOUR_US, claimed_at_us=now + 5000)
    early = store.shard_claim_ex(2, pid=200, intent=WILLNEED, priority=50,
                                 duration_us=HOUR_US, claimed_at_us=now)
    assert store.shard_election() == early
    store.shard_release(early)
    assert store.shard_election() == late


def test_election_tie_lowest_pid(store):
    now = _now_us()
    b1 = store.shard_claim_ex(1, pid=999, intent=WILLNEED, priority=50,
                              duration_us=HOUR_US, claimed_at_us=now)
    b2 = store.shard_claim_ex(2, pid=111, intent=WILLNEED, priority=50,
                              duration_us=HOUR_US, claimed_at_us=now)
    assert store.shard_election() == b2
    store.shard_release(b2)
    assert store.shard_election() == b1


def test_expired_bid_cannot_win(store):
    dead = store.shard_claim_ex(1, pid=100, intent=WILLNEED, priority=200,
                                duration_us=0,  # duration 0 = born expired
                                claimed_at_us=1000)
    live = store.shard_claim_ex(2, pid=200, intent=WILLNEED, priority=10,
                                duration_us=HOUR_US,
                                claimed_at_us=_now_us())
    assert store.shard_election() == live
    assert not store.bid_info(dead).live


def test_dontneed_bumper_cannot_beat_live_real_bid(store):
    now = _now_us()
    bumper = store.shard_claim_ex(1, pid=100, intent=DONTNEED,
                                  priority=255, duration_us=HOUR_US,
                                  claimed_at_us=now)
    real = store.shard_claim_ex(2, pid=200, intent=WILLNEED, priority=1,
                                duration_us=HOUR_US, claimed_at_us=now + 1000)
    assert store.shard_election() == real
    # once the real bid is gone the bumper may win
    store.shard_release(real)
    assert store.shard_election() == bumper


def test_rebid_revives(store):
    b = store.shard_claim_ex(1, pid=os.getpid(), intent=WILLNEED,
                             priority=50, duration_us=1_000_000,
                             claimed_at_us=1)  # ancient claim -> expired
    assert not store.bid_info(b).live
    store.shard_rebid(b)  # refresh claimed_at with a real timestamp
    assert store.bid_info(b).live


def test_enospc_on_33rd_bid(store):
    now = _now_us()
    for i in range(32):
        assert store.shard_claim_ex(i, pid=100 + i, intent=WILLNEED,
                                    priority=1, duration_us=HOUR_US,
                                    claimed_at_us=now) >= 0
    with pytest.raises(OSError):
        store.shard_claim(999, WILLNEED, 1, HOUR_US)


def test_release_frees_slot(store):
    for i in range(32):
        store.shard_claim_ex(i, pid=100 + i, intent=WILLNEED, priority=1,
                             duration_us=HOUR_US, claimed_at_us=_now_us())
    store.shard_release(17)
    assert store.shard_claim(1000, WILLNEED, 1, HOUR_US) == 17


def test_madvise_sovereign_issues(store):
    b = store.shard_claim(0x5F10, WILLNEED, priority=40,
                          duration_us=HOUR_US)
    assert store.madvise(b, sp.ADV_WILLNEED, timeout_ms=0) is True


def test_madvise_non_sovereign_defers(store):
    # a forged higher-priority bid holds sovereignty
    store.shard_claim_ex(1, pid=424242, intent=WILLNEED, priority=250,
                         duration_us=HOUR_US, claimed_at_us=_now_us())
    mine = store.shard_claim(2, WILLNEED, priority=1, duration_us=HOUR_US)
    assert store.madvise(mine, sp.ADV_WILLNEED, timeout_ms=0) is False
    # bounded wait also times out while the usurper is live
    assert store.madvise(mine, sp.ADV_WILLNEED, timeout_ms=30) is False


def test_madvise_requires_own_live_bid(store):
    forged = store.shard_claim_ex(1, pid=424242, intent=WILLNEED,
                                  priority=1, duration_us=HOUR_US,
                                  claimed_at_us=_now_us())
    with pytest.raises(OSError):
        store.madvise(forged, sp.ADV_WILLNEED, timeout_ms=0)


def test_madvise_window(store):
    b = store.shard_claim(3, SEQ, priority=9, duration_us=HOUR_US)
    # advise just the vector lane region
    assert store.madvise(b, sp.ADV_SEQUENTIAL, offset=8192, length=4096,
                         timeout_ms=0) is True


def test_bid_table_dump(store):
    store.shard_claim(0xAB, WILLNEED, 7, HOUR_US)
    table = store.bid_table()
    assert len(table) == 32
    assert any(e.shard_id == 0xAB and e.live for e in table)


# -------------------------------------------------- cross-process election

def test_forged_multiprocess_election_matrix(store):
    """Three 'processes' bid; every observer computes the same winner."""
    now_us = _now_us()
    store.shard_claim_ex(0x5F10, pid=1001, intent=WILLNEED, priority=40,
                         duration_us=HOUR_US, claimed_at_us=now_us)
    store.shard_claim_ex(0x5F10, pid=1002, intent=SEQ, priority=20,
                         duration_us=HOUR_US, claimed_at_us=now_us)
    winner = store.shard_claim_ex(0x5F1A, pid=1003, intent=WILLNEED,
                                  priority=200, duration_us=HOUR_US,
                                  claimed_at_us=now_us)
    # a second mapping of the same store sees the same election
    peer = Store.open(store.name)
    try:
        assert peer.shard_election() == winner == store.shard_election()
    finally:
        peer.close()
