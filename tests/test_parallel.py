"""Mesh/sharding layer on the virtual 8-device CPU mesh: tp param
sharding, dp batch sharding, sharded train step, sharded top-k with
all-gather merge."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from libsplinter_tpu.models import EncoderConfig
from libsplinter_tpu.parallel import (make_mesh, make_sharded_train_step,
                                      make_train_step, shard_vectors,
                                      sharded_topk)


def test_make_mesh_shapes():
    m = make_mesh(dp=4, tp=2)
    assert m.shape == {"dp": 4, "tp": 2, "sp": 1, "ep": 1, "pp": 1}
    m2 = make_mesh(tp=2)          # dp inferred = 4
    assert m2.shape["dp"] == 4
    m3 = make_mesh(tp=2, ep=2)    # dp inferred = 2
    assert m3.shape == {"dp": 2, "tp": 2, "sp": 1, "ep": 2, "pp": 1}
    with pytest.raises(ValueError):
        make_mesh(dp=3, tp=3)


def test_train_step_single_device():
    cfg = EncoderConfig.tiny(out_dim=16)
    init_fn, step_fn = make_train_step(cfg)
    ids = np.ones((4, 16), np.int32)
    mask = np.ones((4, 16), bool)
    state = init_fn(jax.random.PRNGKey(0), ids, mask)
    batch = {"ids_a": ids, "mask_a": mask,
             "ids_b": ids + 1, "mask_b": mask}
    state2, loss = jax.jit(step_fn)(state, batch)
    assert np.isfinite(float(loss))
    assert int(state2.step) == 1


def test_sharded_train_step_dp_tp():
    """Full train step jit over a 4x2 (dp, tp) mesh; params tp-sharded,
    batch dp-sharded; one step must run and produce a finite loss."""
    cfg = EncoderConfig.tiny(out_dim=16)
    mesh = make_mesh(dp=4, tp=2)
    sharded_init = make_sharded_train_step(cfg, mesh)
    ids = np.ones((8, 16), np.int32)
    mask = np.ones((8, 16), bool)
    state, step = sharded_init(jax.random.PRNGKey(0), ids[:1], mask[:1])
    batch = {"ids_a": ids, "mask_a": mask,
             "ids_b": (ids + 1) % cfg.vocab_size, "mask_b": mask}
    state2, loss = step(state, batch)
    assert np.isfinite(float(loss))
    # a tp-sharded kernel is actually distributed over the tp axis
    qkv = state2.params["params"]["layer_0"]["attn"]["qkv"]["kernel"]
    spec = qkv.sharding.spec
    assert "tp" in str(spec)
    # second step reuses the compiled program
    state3, loss3 = step(state2, batch)
    assert int(state3.step) == 2


def test_sharded_topk_matches_dense():
    mesh = make_mesh(dp=8)
    rng = np.random.default_rng(0)
    vectors = rng.normal(size=(1024, 64)).astype(np.float32)
    query = rng.normal(size=64).astype(np.float32)
    v_sharded = shard_vectors(mesh, vectors)
    s, i = sharded_topk(mesh, v_sharded, query, k=10)
    # dense reference
    vn = vectors / np.linalg.norm(vectors, axis=1, keepdims=True)
    qn = query / np.linalg.norm(query)
    ref = np.argsort(-(vn @ qn))[:10]
    np.testing.assert_array_equal(np.sort(i), np.sort(ref))


def test_sharded_topk_mask():
    mesh = make_mesh(dp=8)
    rng = np.random.default_rng(1)
    vectors = rng.normal(size=(512, 32)).astype(np.float32)
    query = vectors[100]
    mask = np.ones(512, np.float32)
    mask[100] = 0.0
    s, i = sharded_topk(mesh, vectors, query, k=5, mask=mask)
    assert 100 not in i


def test_multihost_single_process_noop(monkeypatch):
    from libsplinter_tpu.parallel import multihost
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert multihost.init_distributed() is False
    pid, pcount = multihost.process_span()
    assert (pid, pcount) == (0, 1)
