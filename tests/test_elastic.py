"""Elastic lanes: the stripe-map protocol, striped replica groups
(R=2 byte-identical to R=1, no double-claims, no orphans across a
re-stripe), the supervisor's replica sets + scale-down drain
protocol + straggler reclaim, the autoscaler's hysteresis (no
flapping on oscillating input), telemetry queue-depth under stripes,
loadgen rate profiles, and mid-decode deadline aborts.  `make
scale-check` runs the fast tier of this file + the in-process
rate-step gate (scripts/scale_step_check.py)."""
from __future__ import annotations

import json
import os
import time
import uuid

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.autoscaler import AutoScaler
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.engine.searcher import Searcher
from libsplinter_tpu.engine.supervisor import (LANES, LaneSpec,
                                               Supervisor,
                                               parse_scale_spec)


@pytest.fixture
def store():
    name = f"/spt-el-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
    yield st
    st.close()
    Store.unlink(name)


# ------------------------------------------------- stripe protocol

class TestStripeProtocol:
    def test_map_roundtrip_and_epoch_bump(self, store):
        owners = {0: [0, 2, 4], 1: [1, 3, 5]}
        e1 = P.write_stripe_map(store, "embedder", owners, width=6)
        rec = P.read_stripe_map(store, "embedder")
        assert e1 == 1 and rec["epoch"] == 1 and rec["width"] == 6
        assert rec["owners"] == {"0": [0, 2, 4], "1": [1, 3, 5]}
        assert rec["closed"] == []
        e2 = P.write_stripe_map(store, "embedder", {0: [0, 1, 2]},
                                width=6, closed=[3, 4, 5])
        assert e2 == 2
        rec = P.read_stripe_map(store, "embedder")
        assert rec["epoch"] == 2 and rec["closed"] == [3, 4, 5]
        P.clear_stripe_map(store, "embedder")
        assert P.read_stripe_map(store, "embedder") is None

    def test_default_owners_disjoint_and_covering(self):
        for r in (1, 2, 3, 5, 8):
            owners = P.default_stripe_owners(r, 16)
            seen = [s for ss in owners.values() for s in ss]
            assert sorted(seen) == list(range(16))   # cover, disjoint
            assert set(owners) == set(range(r))
            sizes = [len(ss) for ss in owners.values()]
            assert max(sizes) - min(sizes) <= 1      # balanced

    def test_replica_key_roundtrip(self):
        base = P.KEY_EMBED_STATS
        assert P.replica_stats_key(base, 0) == base
        assert P.replica_stats_key(base, 2) == f"{base}.r2"
        assert P.parse_replica_key(base, base) == 0
        assert P.parse_replica_key(f"{base}.r3", base) == 3
        assert P.parse_replica_key(f"{base}.rx", base) is None
        assert P.parse_replica_key("__other", base) is None

    def test_replica_heartbeat_discovery(self, store):
        base = P.KEY_SEARCH_STATS
        P.publish_heartbeat(store, base, {"served": 1})
        P.publish_heartbeat(store, P.replica_stats_key(base, 2),
                            {"served": 2})
        P.publish_heartbeat(store, P.replica_stats_key(base, 1),
                            {"served": 3})
        keys = P.replica_heartbeat_keys(store, base)
        assert keys == [(0, base), (1, f"{base}.r1"),
                        (2, f"{base}.r2")]

    def test_stripe_view_fallbacks_and_retire(self, store):
        v0 = P.StripeView(store, "searcher", 0)
        v1 = P.StripeView(store, "searcher", 1)
        v0.refresh(), v1.refresh()
        # no map: replica 0 owns everything, replica 1 owns NOTHING
        assert all(v0.owns(i) for i in range(40))
        assert not any(v1.owns(i) for i in range(40))
        assert not v0.retired and not v1.retired
        P.write_stripe_map(store, "searcher",
                           P.default_stripe_owners(2, 16), width=16)
        v0.refresh(), v1.refresh()
        for i in range(40):
            assert v0.owns(i) != v1.owns(i)      # disjoint, covering
        # retire signal: a live map assigning replica 1 nothing
        P.write_stripe_map(store, "searcher", {0: list(range(16))},
                           width=16)
        assert v1.poll_retired()
        assert not v0.poll_retired()             # replica 0 never

    def test_scale_targets_roundtrip(self, store):
        assert P.read_scale_targets(store) == {}
        P.write_scale_target(store, "embedder", 3, src="auto")
        P.write_scale_target(store, "searcher", 2, src="manual")
        t = P.read_scale_targets(store)
        assert t["embedder"]["r"] == 3 and t["embedder"]["src"] == "auto"
        assert t["searcher"]["src"] == "manual"
        P.write_scale_target(store, "searcher", None)
        assert "searcher" not in P.read_scale_targets(store)

    def test_parse_scale_spec(self):
        assert parse_scale_spec(["embedder=1:4"]) == {
            "embedder": (1, 4)}
        assert parse_scale_spec(["searcher=3"]) == {
            "searcher": (1, 3)}
        for bad in ("embedder", "embedder=", "embedder=4:1",
                    "embedder=0:4", "=1:2",
                    "embeder=1:4",        # typo'd lane: fail at PARSE
                    "telemetry=1:2"):     # unscalable lane
            with pytest.raises(ValueError):
                parse_scale_spec([bad])


# ------------------------------------------- striped replica groups

def _mk_embedder(store, replica, served):
    def enc(texts):
        served.extend(texts)
        # deterministic pure function of the text: byte-identical
        # across any replica assignment
        return np.array([[float(len(t) % 7 + 1)] * store.vec_dim
                         for t in texts], np.float32)
    return Embedder(store, encoder_fn=enc, max_ctx=64,
                    replica=replica)


def _submit_embeds(store, n):
    keys = [f"doc{i}" for i in range(n)]
    for i, k in enumerate(keys):
        store.set(k, f"text number {i} with tail {'x' * (i % 5)}")
        store.label_or(k, P.LBL_EMBED_REQ | P.LBL_WAITING)
        store.bump(k)
    return keys


class TestStripedReplicas:
    def test_two_embedder_replicas_disjoint_and_byte_identical(
            self, store):
        """R=2 serves the same request set as R=1, byte-identical,
        with every request embedded EXACTLY once (no double-claims:
        the encoder call log is the claim log)."""
        P.write_stripe_map(store, "embedder",
                           P.default_stripe_owners(2, 16))
        served0, served1 = [], []
        e0 = _mk_embedder(store, 0, served0)
        e1 = _mk_embedder(store, 1, served1)
        e0.attach(), e1.attach()
        keys = _submit_embeds(store, 24)
        texts = {store.get(k).rstrip(b"\0").decode() for k in keys}
        for _ in range(4):
            e0.run_once(), e1.run_once()
        assert not store.enumerate_indices(P.LBL_EMBED_REQ)
        # exactly-once: the union is the request set, no overlap
        assert set(served0) | set(served1) == texts
        assert len(served0) + len(served1) == len(texts)
        assert served0 and served1       # both replicas actually drained
        # byte-identical to the single-replica deployment
        vecs = {k: store.vec_get(k).copy() for k in keys}
        for k in keys:
            t = store.get(k).rstrip(b"\0").decode()
            want = np.full(store.vec_dim, float(len(t) % 7 + 1),
                           np.float32)
            assert np.array_equal(vecs[k], want)
        # replica heartbeats land suffixed
        e0.publish_stats(), e1.publish_stats()
        assert P.KEY_EMBED_STATS in store
        assert f"{P.KEY_EMBED_STATS}.r1" in store
        snap1 = json.loads(
            store.get(f"{P.KEY_EMBED_STATS}.r1").rstrip(b"\0"))
        assert snap1["replica"] == 1
        assert snap1["stripe"]["stripes"] == 8

    def test_two_searcher_replicas_identical_to_single(self, store):
        """R=2 searchers answer every request with the same hits a
        single searcher produces, each request serviced by exactly
        one replica."""
        rng = np.random.default_rng(3)
        docs = rng.normal(size=(32, store.vec_dim)).astype(np.float32)
        for i in range(32):
            store.set(f"doc/{i}", f"text {i}")
            store.vec_set(f"doc/{i}", docs[i])
            # bloom-scoped corpus: the candidate set is the labeled
            # docs, independent of how drains slice the request set
            store.label_or(f"doc/{i}", P.LBL_CHUNK)
        qs = rng.normal(size=(10, store.vec_dim)).astype(np.float32)
        keys = [f"q{i}" for i in range(10)]

        def submit_all(st):
            for k, q in zip(keys, qs):
                st.set(k, json.dumps({"k": 4, "bloom": P.LBL_CHUNK}))
                st.vec_set(k, q)
                st.label_or(k, P.LBL_SEARCH_REQ | P.LBL_WAITING)
                st.bump(k)

        # reference: one unstriped searcher on an identical store
        submit_all(store)
        ref = Searcher(store)
        ref.attach()
        assert ref.run_once() == 10
        want = {}
        for k in keys:
            idx = store.find_index(k)
            want[k] = json.loads(store.get(
                P.search_result_key(idx)).rstrip(b"\0"))["keys"]
            store.unset(P.search_result_key(idx))
        # striped pair re-serves the same set
        P.write_stripe_map(store, "searcher",
                           P.default_stripe_owners(2, 16))
        submit_all(store)
        s0 = Searcher(store, replica=0)
        s1 = Searcher(store, replica=1)
        s0.attach(), s1.attach()
        n0 = s0.run_once()
        n1 = s1.run_once()
        assert n0 + n1 == 10 and n0 and n1       # disjoint split
        assert not store.enumerate_indices(P.LBL_SEARCH_REQ)
        for k in keys:
            got = json.loads(store.get(P.search_result_key(
                store.find_index(k))).rstrip(b"\0"))["keys"]
            assert got == want[k]

    def test_restripe_epoch_bump_leaves_no_orphans(self, store):
        """The handoff contract: requests parked in a replica's
        stripes are picked up by the NEW owner at its next drain
        after the epoch-bumped map write — zero orphaned WAITING
        rows."""
        served = []
        emb = _mk_embedder(store, 0, served)
        emb.attach()
        # everything assigned to (absent) replica 1: replica 0 drains
        # nothing
        P.write_stripe_map(store, "embedder",
                           {1: list(range(16))}, width=16)
        _submit_embeds(store, 12)
        assert emb.run_once() == 0
        assert len(store.enumerate_indices(P.LBL_EMBED_REQ)) == 12
        # the re-stripe: replica 0 takes over at its NEXT drain
        e = P.write_stripe_map(store, "embedder",
                               {0: list(range(16))}, width=16)
        assert e == 2
        emb.run_once()
        assert not store.enumerate_indices(P.LBL_EMBED_REQ)
        assert len(served) == 12                 # all exactly once

    def test_telemetry_queue_depth_counts_whole_lane(self, store):
        """The satellite guarantee: queue depth is measured by label
        enumeration over the WHOLE lane — a striped deployment must
        never ring one replica's share as the lane queue."""
        from libsplinter_tpu.engine.telemetry import TelemetrySampler

        P.write_stripe_map(store, "embedder",
                           P.default_stripe_owners(2, 16))
        _submit_embeds(store, 17)
        # replica heartbeats: counters SUM, replicas gauge counts
        P.publish_heartbeat(store, P.KEY_EMBED_STATS,
                            {"embedded": 5, "shed": 1, "replica": 0})
        P.publish_heartbeat(store, f"{P.KEY_EMBED_STATS}.r1",
                            {"embedded": 7, "shed": 2, "replica": 1})
        tel = TelemetrySampler(store, interval_s=0.1)
        tel.sample_once()
        rec = json.loads(store.get(
            P.telemetry_key("embedder")).rstrip(b"\0"))
        g = rec["gauges"]
        assert g["queue_depth"][-1][1] == 17.0   # whole lane
        assert g["progress"][-1][1] == 12.0      # summed replicas
        assert g["shed"][-1][1] == 3.0
        assert g["replicas"][-1][1] == 2.0


# ------------------------------------- supervisor replica scaling

def _sleeper():
    import subprocess
    import sys

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(600)"])
    return spawn


@pytest.mark.chaos
class TestSupervisorScaling:
    def test_lane_spec_replica_ceilings(self):
        assert isinstance(LANES["embedder"], LaneSpec)
        assert LANES["embedder"].max_replicas > 1
        assert LANES["telemetry"].max_replicas == 1
        assert LANES["autoscaler"].module == \
            "libsplinter_tpu.engine.autoscaler"

    def test_scale_up_spawns_and_stripes(self, store):
        sup = Supervisor(store.name, lanes=("embedder",),
                         spawn_fn=_sleeper(), store=store,
                         scale={"embedder": (1, 4)},
                         scale_knobs={"up_threshold": 4.0})
        try:
            # policy published for the controller
            pol = P.read_scale_policy(store)
            assert pol["lanes"]["embedder"] == {
                "min": 1, "max": 4, "signal": "queue"}
            assert pol["up_threshold"] == 4.0
            P.write_scale_target(store, "embedder", 3, src="manual")
            sup.poll_once()
            assert sorted(sup.replicas["embedder"]) == [0, 1, 2]
            for r, ln in sup.replicas["embedder"].items():
                assert ln.pid and ln.replica == r
            # scale-up phase 1: the new replicas are PENDING — the
            # incumbents keep serving their planned shares (full
            # coverage through the child startup; an attach that
            # owned stripes could steal an incumbent's in-flight
            # rows)
            rec = P.read_stripe_map(store, "embedder")
            assert set(rec["owners"]) == {"0"}
            assert rec["closed"] == []
            assert set(rec["pending"]) == {"1", "2"}
            owned = {s for ss in rec["owners"].values() for s in ss}
            assert owned == set(range(rec["width"]))   # no hole
            # phase 2: heartbeats land -> promotion -> full cover
            for r in (1, 2):
                P.publish_heartbeat(
                    store, P.replica_stats_key(P.KEY_EMBED_STATS, r),
                    {"embedded": 0})
            sup.poll_once()
            rec = P.read_stripe_map(store, "embedder")
            seen = sorted(s for ss in rec["owners"].values()
                          for s in ss)
            assert seen == list(range(rec["width"]))  # full cover
            assert set(rec["owners"]) == {"0", "1", "2"}
            assert rec["closed"] == []
            snap = json.loads(store.get(
                P.KEY_SUPERVISOR_STATS).rstrip(b"\0"))
            assert snap["lanes"]["embedder"]["r"] == 3
            assert "1" in snap["lanes"]["embedder"]["replicas"]
            # a target past the bounds clamps
            P.write_scale_target(store, "embedder", 99, src="manual")
            sup.poll_once()
            assert len(sup.replicas["embedder"]) == 4
        finally:
            sup.shutdown()

    def test_scale_down_drain_then_reap(self, store):
        sup = Supervisor(store.name, lanes=("embedder",),
                         spawn_fn=_sleeper(), store=store,
                         scale={"embedder": (1, 4)},
                         drain_deadline_s=0.3)
        try:
            P.write_scale_target(store, "embedder", 3, src="manual")
            sup.poll_once()
            assert len(sup.replicas["embedder"]) == 3
            for r in (1, 2):          # promote: first heartbeats
                P.publish_heartbeat(
                    store, P.replica_stats_key(P.KEY_EMBED_STATS, r),
                    {"embedded": 0})
            sup.poll_once()
            rec = P.read_stripe_map(store, "embedder")
            assert set(rec["owners"]) == {"0", "1", "2"}
            P.write_scale_target(store, "embedder", 1, src="manual")
            sup.poll_once()
            # phase 1: both extra replicas draining, stripes CLOSED
            retiring = [ln for ln in
                        sup.replicas["embedder"].values()
                        if ln.retiring]
            assert len(retiring) == 2
            rec = P.read_stripe_map(store, "embedder")
            closed = set(rec["closed"])
            assert closed                    # parked, owned by nobody
            owned = {s for ss in rec["owners"].values() for s in ss}
            assert owned | closed == set(range(rec["width"]))
            assert not owned & closed
            # sleeper children never exit on their own: the drain
            # deadline reaps them
            deadline = time.monotonic() + 10
            while len(sup.replicas["embedder"]) > 1 \
                    and time.monotonic() < deadline:
                sup.poll_once()
                time.sleep(0.05)
            assert sorted(sup.replicas["embedder"]) == [0]
            assert sup.retired == 2
            # back to the single-replica default: map cleared
            assert P.read_stripe_map(store, "embedder") is None
        finally:
            sup.shutdown()

    def test_reclaim_strands_nothing_on_crash_mid_scale_down(
            self, store):
        """The chaos drill's core invariant at unit scale: a replica
        crash-KILLED mid-scale-down (in-flight SERVICING row, drain
        incomplete) still strands nothing — the supervisor's
        straggler reclaim re-queues the row for the survivors."""
        sup = Supervisor(store.name, lanes=("completer",),
                         spawn_fn=_sleeper(), store=store,
                         scale={"completer": (1, 4)},
                         drain_deadline_s=5.0)
        try:
            P.write_scale_target(store, "completer", 2, src="manual")
            sup.poll_once()
            # promote r1 (its first heartbeat): the parked share
            # becomes its own
            P.publish_heartbeat(
                store, P.replica_stats_key(P.KEY_COMPLETE_STATS, 1),
                {"completions": 0})
            sup.poll_once()
            rec = P.read_stripe_map(store, "completer")
            r1_stripes = set(rec["owners"]["1"])
            # a request claimed (SERVICING) by replica 1, mid-stream
            key = None
            for i in range(64):
                store.set(f"k{i}", "prompt")
                idx = store.find_index(f"k{i}")
                if P.stripe_of(idx, rec["width"]) in r1_stripes:
                    key = f"k{i}"
                    break
                store.unset(f"k{i}")
            assert key is not None
            store.label_or(key, P.LBL_SERVICING)
            # scale down; then crash-kill the RETIRING replica before
            # it drains
            P.write_scale_target(store, "completer", 1, src="manual")
            sup.poll_once()
            ln = next(ln for ln in sup.replicas["completer"].values()
                      if ln.retiring)
            ln.proc.kill()
            deadline = time.monotonic() + 10
            while len(sup.replicas["completer"]) > 1 \
                    and time.monotonic() < deadline:
                sup.poll_once()
                time.sleep(0.05)
            assert sorted(sup.replicas["completer"]) == [0]
            labels = store.labels(key)
            assert not labels & P.LBL_SERVICING
            assert labels & P.LBL_INFER_REQ      # re-queued, not lost
            assert labels & P.LBL_WAITING
        finally:
            sup.shutdown()

    def test_retire_fault_site_live_and_survivable(self, store):
        """`supervisor.retire` chaos reachability (splint SPL104):
        the fault raises out of poll_once on its hit window — run()'s
        step firewall is the production containment — and the next
        step retires normally."""
        from libsplinter_tpu.utils import faults

        sup = Supervisor(store.name, lanes=("embedder",),
                         spawn_fn=_sleeper(), store=store,
                         scale={"embedder": (1, 3)},
                         drain_deadline_s=0.1)
        faults.arm("supervisor.retire:raise@1")
        try:
            P.write_scale_target(store, "embedder", 2, src="manual")
            sup.poll_once()
            assert len(sup.replicas["embedder"]) == 2
            P.write_scale_target(store, "embedder", 1, src="manual")
            with pytest.raises(faults.FaultInjected):
                sup.poll_once()
            sup.poll_once()              # window passed: retire runs
            assert any(ln.retiring or ln.replica == 0
                       for ln in sup.replicas["embedder"].values())
            deadline = time.monotonic() + 10
            while len(sup.replicas["embedder"]) > 1 \
                    and time.monotonic() < deadline:
                sup.poll_once()
                time.sleep(0.05)
            assert sorted(sup.replicas["embedder"]) == [0]
        finally:
            faults.disarm()
            sup.shutdown()


# ------------------------------------------------- the autoscaler

_ring_ticks = iter(range(1, 1_000_000))


def _ring(store, lane, queue_vals, shed_vals=None):
    # every write is a FRESH sampler tick (distinct point ts): the
    # controller's stale-sample guard refuses to re-count a point
    base = float(next(_ring_ticks)) * 100.0
    gauges = {"queue_depth": [[base + i, float(v)]
                              for i, v in enumerate(queue_vals)]}
    if shed_vals is not None:
        gauges["shed"] = [[base + i, float(v)]
                          for i, v in enumerate(shed_vals)]
    store.set(P.telemetry_key(lane), json.dumps(
        {"v": 1, "lane": lane, "interval_s": 0.1, "n": 1,
         "ts": time.time(), "gauges": gauges}))


def _policy(store, lane="embedder", lo=1, hi=4):
    store.set(P.KEY_SCALE_POLICY, json.dumps(
        {"v": 1, "lanes": {lane: {"min": lo, "max": hi}}}))


def _pool_policy(store, lane="decode", lo=1, hi=4):
    store.set(P.KEY_SCALE_POLICY, json.dumps(
        {"v": 1, "lanes": {lane: {"min": lo, "max": hi,
                                  "signal": "pool"}}}))


def _pool_ring(store, lane, occ, readmits=None, used=60.0,
               free=40.0):
    """One fresh pool-signal sampler tick: occupancy plus the pool
    size gauges, and optionally a (prev, last) tier_readmits counter
    pair — the inputs of the PR 20 readmit discount."""
    base = float(next(_ring_ticks)) * 100.0
    gauges = {"queue_depth": [[base, 0.0]],
              "pool_occ": [[base, float(occ)]],
              "pages_used": [[base, float(used)]],
              "pages_free": [[base, float(free)]]}
    if readmits is not None:
        gauges["tier_readmits"] = [
            [base - 1.0, float(readmits[0])],
            [base, float(readmits[1])]]
    store.set(P.telemetry_key(lane), json.dumps(
        {"v": 1, "lane": lane, "interval_s": 0.1, "n": 1,
         "ts": time.time(), "gauges": gauges}))


def _sup_stats(store, lane="embedder", r=1):
    P.publish_heartbeat(store, P.KEY_SUPERVISOR_STATS,
                        {"polls": 1, "lanes": {lane: {
                            "state": "running", "r": r}}})


class TestAutoscaler:
    def test_scale_up_sizes_to_backlog_in_one_action(self, store):
        _policy(store)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store, up_threshold=8.0, up_consecutive=2,
                         cooldown_s=0.0)
        _ring(store, "embedder", [32.0])
        assert ctl.decide_once(0.0) == 0     # streak 1: not yet
        _ring(store, "embedder", [32.0])     # a fresh sampler tick
        assert ctl.decide_once(1.0) == 1     # sustained: act
        tgt = P.read_scale_targets(store)["embedder"]
        assert tgt["r"] == 4 and tgt["src"] == "auto"  # ceil(32/8)
        assert ctl.stats.scale_ups == 1

    def test_no_flap_on_oscillating_input(self, store):
        """The hysteresis acceptance: a queue oscillating between
        pressure and idle every sample never moves the target."""
        _policy(store)
        _sup_stats(store, r=2)
        ctl = AutoScaler(store, up_threshold=8.0, down_threshold=1.0,
                         up_consecutive=2, down_consecutive=3,
                         cooldown_s=0.0)
        for i in range(12):
            _ring(store, "embedder",
                  [40.0 if i % 2 == 0 else 0.0])
            ctl.decide_once(float(i))
        assert ctl.stats.decisions == 0
        assert "embedder" not in P.read_scale_targets(store)

    def test_scale_down_slow_with_cooldown(self, store):
        _policy(store)
        _sup_stats(store, r=3)
        ctl = AutoScaler(store, up_threshold=8.0, down_threshold=1.0,
                         down_consecutive=3, cooldown_s=100.0)
        for i in range(8):
            _ring(store, "embedder", [0.0])
            ctl.decide_once(float(i))
        # one step down only (by ONE replica), then cooldown holds
        assert ctl.stats.scale_downs == 1
        assert P.read_scale_targets(store)["embedder"]["r"] == 2

    def test_stale_sample_never_recounted(self, store):
        """A controller ticking FASTER than the sampler must not
        turn one pressured telemetry point into a consecutive run —
        the streaks pause until a fresh sample lands."""
        _policy(store)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store, up_threshold=8.0, up_consecutive=2,
                         cooldown_s=0.0)
        _ring(store, "embedder", [64.0])     # ONE pressured sample
        for i in range(6):                   # re-read 6x: no action
            assert ctl.decide_once(float(i)) == 0
        assert ctl.stats.decisions == 0
        _ring(store, "embedder", [64.0])     # the SECOND real sample
        assert ctl.decide_once(7.0) == 1     # now it is sustained

    def test_shed_movement_votes_up(self, store):
        _policy(store)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store, up_threshold=100.0,  # queue never
                         up_consecutive=2, cooldown_s=0.0)
        _ring(store, "embedder", [2.0], shed_vals=[0.0])
        ctl.decide_once(0.0)
        _ring(store, "embedder", [2.0], shed_vals=[5.0])
        ctl.decide_once(1.0)
        _ring(store, "embedder", [2.0], shed_vals=[9.0])
        assert ctl.decide_once(2.0) == 1     # shed slope = overload
        assert P.read_scale_targets(store)["embedder"]["r"] == 2

    def test_manual_hold_respected(self, store):
        _policy(store)
        _sup_stats(store, r=1)
        P.write_scale_target(store, "embedder", 2, src="manual")
        ctl = AutoScaler(store, up_threshold=1.0, up_consecutive=1,
                         cooldown_s=0.0)
        _ring(store, "embedder", [50.0])
        for i in range(3):
            ctl.decide_once(float(i))
        assert ctl.stats.holds == 3
        assert P.read_scale_targets(store)["embedder"]["src"] == \
            "manual"

    def test_policy_floor_lifts_idle_lane(self, store):
        _policy(store, lo=2, hi=4)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store, cooldown_s=0.0)
        _ring(store, "embedder", [0.0])
        assert ctl.decide_once(0.0) == 1
        assert P.read_scale_targets(store)["embedder"]["r"] == 2

    def test_no_telemetry_no_action(self, store):
        _policy(store)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store)
        assert ctl.decide_once(0.0) == 0
        assert ctl.stats.no_data == 1

    @pytest.mark.chaos
    def test_decide_fault_site_live(self, store):
        """`autoscaler.decide` chaos reachability (splint SPL104)."""
        from libsplinter_tpu.utils import faults

        _policy(store)
        ctl = AutoScaler(store)
        faults.arm("autoscaler.decide:raise@1")
        try:
            with pytest.raises(faults.FaultInjected):
                ctl.decide_once(0.0)
            ctl.decide_once(1.0)         # window passed: cycle runs
        finally:
            faults.disarm()

    def test_pool_readmit_discount_suppresses_warm_burst(self, store):
        """PR 20: a warm-restart readmit burst inflates pool_occ with
        pages that cost nothing to drop again — the discount keeps
        the (unchanged) hysteresis from voting scale-up on it, while
        the SAME occupancy with a quiet tier still scales up."""
        from libsplinter_tpu.engine.autoscaler import (
            POOL_UP_THRESHOLD, READMIT_DISCOUNT_CAP)

        _pool_policy(store, "decode")
        _sup_stats(store, "decode", r=1)
        ctl = AutoScaler(store, up_consecutive=2, cooldown_s=0.0)
        # occupancy 0.85 >= 0.80, but 10 of the 100 pages were
        # readmitted this tick: effective 0.75 — never votes up
        for i in range(4):
            _pool_ring(store, "decode", 0.85,
                       readmits=(10.0 * i, 10.0 * (i + 1)),
                       used=85.0, free=15.0)
            assert ctl.decide_once(float(i)) == 0
        assert ctl.lanes["decode"].up_streak == 0
        assert ctl.lanes["decode"].readmit_discount == 0.1
        ctl.publish_stats()
        snap = json.loads(store.get(
            P.KEY_AUTOSCALER_STATS).rstrip(b"\0"))
        assert snap["lanes"]["decode"]["readmit_discount"] == 0.1
        # tier quiet (counter flat): the same occupancy is genuine
        # demand and the normal two-tick up vote fires
        for i in range(2):
            _pool_ring(store, "decode", 0.85,
                       readmits=(40.0, 40.0), used=85.0, free=15.0)
            ctl.decide_once(10.0 + i)
        assert ctl.stats.scale_ups == 1
        assert P.read_scale_targets(store)["decode"]["r"] == 2
        assert POOL_UP_THRESHOLD == 0.80          # band untouched
        assert READMIT_DISCOUNT_CAP == 0.5

    def test_pool_readmit_discount_capped_and_robust(self, store):
        """The discount is bounded (a pathological counter cannot
        hide saturation below the cap) and degrades to 0.0 on any
        missing/stale input instead of skipping the decision."""
        from libsplinter_tpu.engine.autoscaler import AutoScaler as A

        # pure-input unit: missing rec / rings / flat counter -> 0
        assert A._readmit_discount(None) == 0.0
        assert A._readmit_discount({"gauges": {}}) == 0.0
        g = {"tier_readmits": [[1.0, 5.0], [2.0, 5.0]],
             "pages_used": [[2.0, 50.0]], "pages_free": [[2.0, 50.0]]}
        assert A._readmit_discount({"gauges": g}) == 0.0   # flat
        g["tier_readmits"] = [[1.0, 0.0], [2.0, 90.0]]
        assert A._readmit_discount({"gauges": g}) == 0.5   # capped
        g["pages_free"] = [[2.0, 0.0]]
        g["pages_used"] = [[2.0, 0.0]]
        assert A._readmit_discount({"gauges": g}) == 0.0   # no pool
        # capped end to end: occ 1.0 minus the 0.5 cap stays in the
        # dead band (no up vote, no down vote — streaks reset)
        _pool_policy(store, "decode")
        _sup_stats(store, "decode", r=2)
        ctl = AutoScaler(store, up_consecutive=1,
                         down_consecutive=1, cooldown_s=0.0)
        _pool_ring(store, "decode", 1.0, readmits=(0.0, 90.0),
                   used=100.0, free=0.0)
        assert ctl.decide_once(0.0) == 0
        assert ctl.lanes["decode"].readmit_discount == 0.5
        assert ctl.lanes["decode"].up_streak == 0
        assert ctl.lanes["decode"].down_streak == 0

    def test_heartbeat_and_scale_status(self, store, capsys):
        _policy(store)
        _sup_stats(store, r=1)
        ctl = AutoScaler(store, up_threshold=8.0, up_consecutive=1,
                         cooldown_s=0.0)
        _ring(store, "embedder", [32.0])
        ctl.attach()
        ctl.decide_once(0.0)
        ctl.publish_stats()
        snap = json.loads(store.get(
            P.KEY_AUTOSCALER_STATS).rstrip(b"\0"))
        assert snap["lanes"]["embedder"]["target"] == 4
        assert snap["history"]
        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(store.name)
        try:
            COMMANDS["scale"][0](ses, ["status"])
            out = capsys.readouterr().out
            assert "embedder" in out and "1:4" in out
            # manual override + clear
            COMMANDS["scale"][0](ses, ["set", "embedder=2"])
            tgt = P.read_scale_targets(store)["embedder"]
            assert tgt["r"] == 2 and tgt["src"] == "manual"
            COMMANDS["scale"][0](ses, ["set", "embedder=auto"])
            assert "embedder" not in P.read_scale_targets(store)
        finally:
            ses.close()


# --------------------------------------- replica operator surfaces

class TestReplicaSurfaces:
    def test_metrics_renders_replica_blocks(self, store, capsys):
        P.publish_heartbeat(store, P.KEY_EMBED_STATS,
                            {"embedded": 4, "replica": 0})
        P.publish_heartbeat(store, f"{P.KEY_EMBED_STATS}.r1",
                            {"embedded": 6, "replica": 1,
                             "stripe": {"replica": 1, "epoch": 2,
                                        "width": 16, "stripes": 8}})
        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(store.name)
        try:
            COMMANDS["metrics"][0](ses, [])
        finally:
            ses.close()
        out = capsys.readouterr().out
        assert "sptpu_embedder_embedded 4" in out
        assert "sptpu_embedder_r1_embedded 6" in out
        assert "sptpu_embedder_r1_stripe_stripes 8" in out

    def test_top_shows_replica_rows_and_dead_marker(self, store,
                                                    capsys):
        P.publish_heartbeat(store, P.KEY_EMBED_STATS, {"embedded": 4})
        # a DEAD replica: pid that cannot exist
        store.set(f"{P.KEY_EMBED_STATS}.r1", json.dumps(
            {"ts": time.time(), "pid": 2 ** 22 + 12345,
             "embedded": 6}))
        store.label_or(f"{P.KEY_EMBED_STATS}.r1", P.LBL_DEBUG)
        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(store.name)
        try:
            COMMANDS["top"][0](ses, ["--once"])
        finally:
            ses.close()
        out = capsys.readouterr().out
        assert "1/2up" in out                    # lane aggregate
        assert "├r0" in out and "├r1" in out     # per-replica rows
        assert "[DEAD" in out                    # not a stale merge
        assert " 10 " in out or "10" in out      # summed progress

    def test_health_lists_replicas(self, store, capsys):
        P.publish_heartbeat(store, P.KEY_SEARCH_STATS, {"served": 1})
        P.publish_heartbeat(store, f"{P.KEY_SEARCH_STATS}.r2",
                            {"served": 2})
        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(store.name)
        try:
            COMMANDS["health"][0](ses, [])
        finally:
            ses.close()
        out = capsys.readouterr().out
        assert "searcher.r2" in out


# ------------------------------------------- loadgen rate profiles

class TestRateProfile:
    def test_parse(self):
        from libsplinter_tpu.cli.loadgen import parse_rate_profile

        assert parse_rate_profile("1x:10,8x:20,1x:10") == [
            (1.0, 10.0), (8.0, 20.0), (1.0, 10.0)]
        assert parse_rate_profile("2:5") == [(2.0, 5.0)]
        for bad in ("", "1x", "x:5", "1x:0", "-1x:5"):
            with pytest.raises(ValueError):
                parse_rate_profile(bad)

    def test_schedule_steps_rate_deterministically(self, store):
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        gen = LoadGenerator(
            store, [TenantSpec(tenant=1, rate=10.0)],
            arrivals="fixed", seed=7,
            rate_profile=[(1.0, 1.0), (4.0, 1.0), (1.0, 1.0)])
        assert gen.duration_s == 3.0
        sched = gen._schedule()
        by_phase: dict[int, int] = {}
        for when, _t, phase in sched:
            assert phase == gen._phase_at(when)
            by_phase[phase] = by_phase.get(phase, 0) + 1
        # fixed arrivals: ~10 in phase 0, ~40 in phase 1, ~10 in 2
        assert 8 <= by_phase[0] <= 12
        assert 35 <= by_phase[1] <= 44
        assert 8 <= by_phase.get(2, 0) <= 12
        # seeded determinism
        gen2 = LoadGenerator(
            store, [TenantSpec(tenant=1, rate=10.0)],
            arrivals="fixed", seed=7,
            rate_profile=[(1.0, 1.0), (4.0, 1.0), (1.0, 1.0)])
        assert [w for w, _, _ in gen2._schedule()] == \
            [w for w, _, _ in sched]

    def test_report_carries_per_phase_sections(self, store):
        """A short un-served run still reports per-phase issue
        counts (everything lands unserved — no daemons)."""
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        gen = LoadGenerator(
            store, [TenantSpec(tenant=1, rate=30.0)],
            mix={"embed": 1.0}, arrivals="fixed", seed=1,
            drain_s=0.1,
            rate_profile=[(1.0, 0.3), (4.0, 0.3)])
        rep = gen.run()
        rows = rep["rate_profile"]
        assert [r["phase"] for r in rows] == [0, 1]
        assert rows[1]["issued"] > rows[0]["issued"] * 2
        assert sum(r["issued"] for r in rows) == rep["issued"]


# ---------------------------- the supervised full-stack chaos drill

@pytest.mark.slow
@pytest.mark.chaos
class TestSupervisedScaleDrill:
    def test_scale_up_down_with_crash_kill_strands_nothing(
            self, store):
        """The tentpole's proof at full supervision: real pipeliner
        children (jax-free — restarts cost ms) scale 1 -> 3 under
        load, then back to 1 — and a replica is crash-KILLED mid-
        scale-down while holding in-flight scripts.  The supervisor's
        drain protocol + straggler reclaim must leave EVERY admitted
        request with a terminal result: zero loss through scale-up
        AND scale-down."""
        import signal
        import threading

        sup = Supervisor(store.name, lanes=("pipeliner",),
                         scale={"pipeliner": (1, 3)},
                         drain_deadline_s=6.0,
                         startup_grace_s=60, store=store)
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                try:
                    sup.poll_once()
                except Exception:
                    pass
                time.sleep(0.1)

        th = threading.Thread(target=pump, daemon=True)
        th.start()
        submitted: list[str] = []
        n = 0

        def submit(count, sleep_s=0.02):
            nonlocal n
            for _ in range(count):
                n += 1
                key = f"job{n}"
                store.set(key, json.dumps({
                    "script": f"splinter.sleep({sleep_s}) "
                              f"return {n}"}))
                store.label_or(key, P.LBL_SCRIPT_REQ | P.LBL_WAITING)
                store.bump(key)
                submitted.append(key)

        def live_replicas():
            return [r for r, ln in sup.replicas["pipeliner"].items()
                    if not ln.retiring and ln.pid
                     and P.pid_alive(ln.pid)]

        def wait_for(cond, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if cond():
                    return True
                time.sleep(0.1)
            return False

        try:
            from libsplinter_tpu.engine.pipeliner import daemon_live
            assert wait_for(lambda: daemon_live(store)), \
                "replica 0 never came up"
            submit(6)                         # 1x phase
            # scale UP under load
            P.write_scale_target(store, "pipeliner", 3, src="manual")
            assert wait_for(lambda: len(live_replicas()) == 3), \
                "scale-up never reached 3 replicas"
            submit(36, sleep_s=0.05)          # 8x burst
            time.sleep(0.4)                   # replicas mid-flight
            # scale DOWN with work outstanding...
            P.write_scale_target(store, "pipeliner", 1, src="manual")
            assert wait_for(lambda: any(
                ln.retiring for ln in
                sup.replicas["pipeliner"].values()), 15), \
                "no replica entered the drain protocol"
            # ...and crash-kill one RETIRING replica mid-drain
            victim = next(ln for ln in
                          sup.replicas["pipeliner"].values()
                          if ln.retiring)
            os.kill(victim.pid, signal.SIGKILL)
            submit(6)                         # back to 1x
            assert wait_for(
                lambda: sorted(sup.replicas["pipeliner"]) == [0],
                40), "scale-down never converged to replica 0"
            # ZERO admitted loss: every request reaches a terminal
            # ok record (crash-stranded scripts re-run on replica 0
            # — LBL_SCRIPT_REQ stays set through execution)
            def all_done():
                for k in submitted:
                    if store.labels(k) & P.LBL_SCRIPT_REQ:
                        return False
                return True
            assert wait_for(all_done, 60), "requests still pending"
            lost = []
            for k in submitted:
                try:
                    rec = json.loads(store.get(P.script_result_key(
                        store.find_index(k))).rstrip(b"\0"))
                except (KeyError, OSError, ValueError):
                    lost.append(k)
                    continue
                if not rec.get("ok"):
                    lost.append((k, rec))
            assert not lost, f"admitted requests lost: {lost[:5]}"
            # the books balance: supervisor retired both replicas
            assert sup.retired == 2
            assert P.read_stripe_map(store, "pipeliner") is None
            # retired replicas take their suffixed heartbeat keys
            # with them — `spt top` must not render [DEAD] ghosts
            assert f"{P.KEY_SCRIPT_STATS}.r1" not in store
            assert f"{P.KEY_SCRIPT_STATS}.r2" not in store
            assert P.replica_heartbeat_keys(
                store, P.KEY_SCRIPT_STATS) == [(0, P.KEY_SCRIPT_STATS)]
        finally:
            stop.set()
            th.join(timeout=5)
            sup.shutdown()


# --------------------------------- mid-decode deadline aborts

@pytest.mark.slow
class TestMidDecodeDeadline:
    def test_expired_row_killed_at_chunk_edge(self, tmp_path):
        """A row whose deadline passes mid-decode is retired with the
        typed DEADLINE_EXPIRED record, its pages return to the pool
        immediately, and killed_mid_decode counts it — an expired row
        must stop consuming pool and batch slots."""
        import threading

        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        name = f"/spt-mdk-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
        try:
            model = CompletionModel(
                DecoderConfig.tiny(max_len=128, dtype=jnp.float32))
            comp = Completer(st, model=model, max_new_tokens=110,
                             flush_tokens=1, template="none",
                             batch_cap=4, page_size=16)
            comp.warmup_paged()       # no serve-time compiles: the
            # deadline below must expire in DECODE, not in a compile
            key, slow = "req-dl", "req-slow"
            st.set(key, "a prompt that will outlive its deadline")
            st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
            assert P.stamp_deadline(st, key, time.time() + 0.12)
            st.bump(key)
            st.set(slow, "sibling without a deadline")
            st.label_or(slow, P.LBL_INFER_REQ | P.LBL_WAITING)
            st.bump(slow)
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=20, stop_after=30.0),
                daemon=True)
            th.start()
            deadline = time.time() + 25
            while time.time() < deadline:
                if st.labels(key) & P.LBL_READY \
                        and st.labels(slow) & P.LBL_READY:
                    break
                time.sleep(0.05)
            comp.stop()
            th.join(timeout=30)
            assert st.labels(key) & P.LBL_READY
            rec = P.parse_error_payload(st.get(key))
            assert rec is not None and rec["err"] == P.ERR_DEADLINE
            assert comp.stats.killed_mid_decode >= 1
            # the sibling (no deadline) streamed to completion
            assert st.labels(slow) & P.LBL_READY
            assert P.parse_error_payload(st.get(slow)) is None
            # pages freed: nothing live once both rows closed
            assert comp._paged_cache.used_pages == 0
            comp.publish_stats()
            snap = json.loads(st.get(
                P.KEY_COMPLETE_STATS).rstrip(b"\0"))
            assert snap["killed_mid_decode"] >= 1
        finally:
            st.close()
            Store.unlink(name)
