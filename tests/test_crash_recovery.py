"""Crash-at-every-stage recovery matrix.

For each instrumented fault site, a CHILD daemon process is driven
into an os._exit(137) crash mid-drain via SPTPU_FAULT=<site>:crash@1;
the parent then runs a fresh daemon over the same store and asserts
the request lifecycle converges: no stuck labels, no lost committed
epochs, no duplicate/leaked __sr_ rows, clients unblocked with
correct results.  The supervisor acceptance test closes the loop:
`spt supervise` observes the crash, restarts the lane, and a live
submit_search round-trips within one backoff.

The per-site matrix spawns one jax-importing child per site, so the
bulk of it is marked slow (chaos-check runs it; tier-1 keeps the
representative subset).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

from libsplinter_tpu import Store, T_VARTEXT
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.searcher import (Searcher, consume_result,
                                             submit_search)
from libsplinter_tpu.utils import faults
from libsplinter_tpu.utils.faults import CRASH_EXIT_CODE

pytestmark = pytest.mark.chaos

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "chaos_child.py")

# every site a `crash` can fire at mid-drain, per daemon role.  The
# store.* sites are exercised through the searcher's commit path (the
# result write is its first store.set of the drain).
SEARCHER_SITES = ("searcher.gather", "searcher.dispatch",
                  "searcher.select", "searcher.commit", "store.set")
EMBEDDER_SITES = ("embedder.drain", "embedder.encode",
                  "embedder.commit", "store.vec_commit")
COMPLETER_SITES = ("completer.render", "completer.generate",
                   "completer.commit")
# completer.sharded_dispatch is only reachable through the pod-sharded
# continuous lane: its crash drill runs through the completer_sharded
# chaos_child role under `spt supervise` (see
# test_supervise_restores_sharded_completer_lane), not this matrix


@pytest.fixture
def cstore():
    name = f"/spt-chaos-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=128, max_val=2048, vec_dim=16)
    yield st
    st.close()
    Store.unlink(name)


def _run_child(role: str, store_name: str, fault_spec: str,
               timeout: float = 120.0):
    # validate the drill's spec through THE grammar entry point
    # (utils/faults.registered_sites) before spawning: a typo'd spec
    # must fail the test at parse time, not silently arm nothing and
    # let the child "survive" a fault that never existed
    assert faults.registered_sites(fault_spec)
    env = dict(os.environ)
    env["SPTPU_FAULT"] = fault_spec
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, CHILD, role, store_name],
        env=env, capture_output=True, text=True, timeout=timeout)


def _fill_docs(store, n, rng):
    vecs = rng.normal(size=(n, store.vec_dim)).astype(np.float32)
    for i in range(n):
        store.set(f"doc/{i}", f"text {i}")
        store.vec_set(f"doc/{i}", vecs[i])
    return vecs


def _stage_search_requests(store, rng, n=2, k=3):
    keys = [f"__sqtmp_{2000 + i}" for i in range(n)]
    for key in keys:
        store.set(key, json.dumps({"k": k}))
        store.vec_set(key, rng.normal(size=store.vec_dim)
                      .astype(np.float32))
        store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
        store.bump(key)
    return keys


def _assert_search_converged(store, keys):
    """The recovery invariants: labels clear, every request answered
    exactly once, and after consumption zero __sr_ rows remain."""
    for key in keys:
        assert not store.labels(key) & (P.LBL_SEARCH_REQ
                                        | P.LBL_WAITING), key
        rec = json.loads(store.get(
            P.search_result_key(store.find_index(key))).rstrip(b"\0"))
        assert rec.get("keys"), rec   # a real answer, not an error
        assert all(k.startswith("doc/") for k in rec["keys"])
        consume_result(store, key)
    leaked = [k for k in store.list()
              if k.startswith(P.SEARCH_RESULT_PREFIX)]
    assert leaked == [], f"leaked result rows: {leaked}"


# --------------------------------------------------- searcher matrix

def _searcher_site_recovers(cstore, site):
    rng = np.random.default_rng(17)
    _fill_docs(cstore, 24, rng)
    keys = _stage_search_requests(cstore, rng)

    out = _run_child("searcher", cstore.name, f"{site}:crash@1")
    assert out.returncode == CRASH_EXIT_CODE, (site, out.stderr[-800:])

    # stranded state is allowed mid-crash; a restarted daemon's first
    # drain + sweep must reclaim it all
    sr = Searcher(cstore)
    sr.attach()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        sr.run_once()
        if not cstore.enumerate_indices(P.LBL_SEARCH_REQ):
            break
    sr.sweep_results()
    _assert_search_converged(cstore, keys)
    if not site.startswith("store."):
        # the restart is visible in the generation counter (a store.*
        # crash can fire inside attach()'s own bump, before the
        # counter exists — the child then dies pre-generation)
        assert sr.generation == 2
    assert sr.generation >= 1


def test_searcher_crash_at_commit_recovers(cstore):
    """Tier-1 representative: the widest window (result row possibly
    written, labels still set — the re-serve must overwrite, not
    duplicate)."""
    _searcher_site_recovers(cstore, "searcher.commit")


@pytest.mark.slow
@pytest.mark.parametrize("site", [s for s in SEARCHER_SITES
                                  if s != "searcher.commit"])
def test_searcher_crash_at_site_recovers(cstore, site):
    _searcher_site_recovers(cstore, site)


# --------------------------------------------------- embedder matrix

def _embedder_site_recovers(cstore, site):
    for i in range(3):
        cstore.set(f"txt/{i}", f"embed me {i}")
        cstore.set_type(f"txt/{i}", T_VARTEXT)
        cstore.label_or(f"txt/{i}", P.LBL_EMBED_REQ | P.LBL_WAITING)
        cstore.bump(f"txt/{i}")

    out = _run_child("embedder", cstore.name, f"{site}:crash@1")
    assert out.returncode == CRASH_EXIT_CODE, (site, out.stderr[-800:])

    from libsplinter_tpu.engine.embedder import Embedder
    emb = Embedder(cstore, encoder_fn=lambda ts: np.full(
        (len(ts), cstore.vec_dim), 0.5, np.float32), max_ctx=64)
    emb.attach()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        emb.run_once()
        if not cstore.enumerate_indices(P.LBL_EMBED_REQ):
            break
    for i in range(3):
        assert not cstore.labels(f"txt/{i}") & (P.LBL_EMBED_REQ
                                                | P.LBL_WAITING)
        assert cstore.vec_get(f"txt/{i}")[0] == 0.5   # committed epoch
    assert emb.generation == 2


def test_embedder_crash_at_commit_recovers(cstore):
    """Tier-1 representative: mid-commit death (some vectors may have
    landed; the restart must re-baseline, not double-commit)."""
    _embedder_site_recovers(cstore, "embedder.commit")


@pytest.mark.slow
@pytest.mark.parametrize("site", [s for s in EMBEDDER_SITES
                                  if s != "embedder.commit"])
def test_embedder_crash_at_site_recovers(cstore, site):
    _embedder_site_recovers(cstore, site)


# -------------------------------------------------- completer matrix

@pytest.mark.slow
@pytest.mark.parametrize("site", COMPLETER_SITES)
def test_completer_crash_at_site_recovers(cstore, site):
    """A crash after the WAITING->SERVICING claim strands the key in
    SERVICING (no label watch will ever fire for it again): the
    restarted daemon's attach() reclaim must re-queue and serve it."""
    cstore.set("q", "ping?")
    cstore.label_or("q", P.LBL_INFER_REQ | P.LBL_WAITING)
    cstore.bump("q")

    out = _run_child("completer", cstore.name, f"{site}:crash@1")
    assert out.returncode == CRASH_EXIT_CODE, (site, out.stderr[-800:])

    from libsplinter_tpu.engine.completer import Completer
    comp = Completer(cstore, generate_fn=lambda p: iter([b"pong "]),
                     template="none")
    comp.attach()                     # reclaims stranded SERVICING rows
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        comp.run_once()
        if cstore.labels("q") & P.LBL_READY:
            break
    assert cstore.labels("q") & P.LBL_READY
    assert not cstore.labels("q") & (P.LBL_INFER_REQ | P.LBL_SERVICING)
    assert b"pong" in cstore.get("q")
    if site != "completer.render":    # render dies before the claim
        assert comp.stats.reclaimed >= 1


def test_completer_drain_fault_requeues_servicing(cstore):
    """An exception escaping process_key AFTER the WAITING->SERVICING
    claim (here: an injected _finalize fault) in a LIVE daemon must not
    wedge the key: the run_once firewall flips it back to WAITING and
    the next sweep serves it — no crash, so the attach() reclaim never
    gets a chance to."""
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.utils import faults

    cstore.set("q", "ping?")
    cstore.label_or("q", P.LBL_INFER_REQ | P.LBL_WAITING)
    cstore.bump("q")
    comp = Completer(cstore, generate_fn=lambda p: iter([b"pong "]),
                     template="none")
    comp.attach()
    faults.arm("completer.commit:raise@1")
    try:
        assert comp.run_once() == 0
    finally:
        faults.disarm()
    assert comp.stats.faults == 1
    assert comp.stats.reclaimed == 1
    assert not cstore.labels("q") & P.LBL_SERVICING
    assert cstore.labels("q") & P.LBL_INFER_REQ
    assert comp.run_once() == 1       # fault window passed: served
    assert cstore.labels("q") & P.LBL_READY
    assert b"pong" in cstore.get("q")


# ------------------------------------------- supervisor acceptance

def _supervised_search_recovers(cstore, site, monkeypatch):
    """`spt supervise` + SPTPU_FAULT crash: the lane dies mid-drain,
    the supervisor restarts it (fault stripped from the respawn), and
    a live submit_search returns a correct result — within one
    restart backoff."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    rng = np.random.default_rng(23)
    vecs = _fill_docs(cstore, 16, rng)
    keys = _stage_search_requests(cstore, rng)

    monkeypatch.setenv("SPTPU_FAULT", f"{site}:crash@1")
    monkeypatch.setenv("SPTPU_FORCE_CPU", "1")
    sup = Supervisor(cstore.name, lanes=("searcher",), store=cstore,
                     backoff_base_ms=100, backoff_max_ms=2000,
                     breaker_threshold=8, breaker_window_s=120,
                     startup_grace_s=300)
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 120.0})
    t.start()
    try:
        qkey = "__sqtmp_live"
        cstore.set(qkey, "placeholder")
        cstore.vec_set(qkey, vecs[5])
        rec = submit_search(cstore, qkey, 3, timeout_ms=90_000)
        assert rec is not None and rec["keys"][0] == "doc/5", rec
        consume_result(cstore, qkey)
        ln = sup.lanes["searcher"]
        assert ln.restarts >= 1       # the crash was observed
        assert ln.state != "down"     # one crash never trips the breaker
        # stranded pre-crash requests drained too; zero stuck bits
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if not cstore.enumerate_indices(P.LBL_SEARCH_REQ):
                break
            time.sleep(0.2)
        _assert_search_converged(cstore, keys)
        cstore.unset(qkey)
    finally:
        sup.stop()
        t.join()
        sup.shutdown()


def test_supervise_restores_searcher_lane(cstore, monkeypatch):
    """Acceptance: crash at the drain's entry, supervised recovery,
    correct answer for a request submitted AFTER the crash."""
    _supervised_search_recovers(cstore, "searcher.gather", monkeypatch)


@pytest.mark.slow
@pytest.mark.parametrize("site", [s for s in SEARCHER_SITES
                                  if s != "searcher.gather"])
def test_supervise_restores_searcher_lane_all_sites(cstore, site,
                                                    monkeypatch):
    _supervised_search_recovers(cstore, site, monkeypatch)


@pytest.mark.slow
@pytest.mark.parametrize("site", EMBEDDER_SITES)
def test_supervise_restores_embedder_lane(cstore, site, monkeypatch):
    """The embed lane under supervision: crash mid-drain, restart,
    and the pending embed requests all commit."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    for i in range(3):
        cstore.set(f"txt/{i}", f"embed me {i}")
        cstore.set_type(f"txt/{i}", T_VARTEXT)
        cstore.label_or(f"txt/{i}", P.LBL_EMBED_REQ | P.LBL_WAITING)
        cstore.bump(f"txt/{i}")

    monkeypatch.setenv("SPTPU_FAULT", f"{site}:crash@1")
    monkeypatch.setenv("SPTPU_FORCE_CPU", "1")
    sup = Supervisor(cstore.name, lanes=("embedder",), store=cstore,
                     backoff_base_ms=100, backoff_max_ms=2000,
                     breaker_threshold=8, breaker_window_s=120,
                     startup_grace_s=300)
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 120.0})
    t.start()
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            labels = [cstore.labels(f"txt/{i}") for i in range(3)]
            if not any(lb & P.LBL_EMBED_REQ for lb in labels):
                break
            time.sleep(0.25)
        for i in range(3):
            assert not cstore.labels(f"txt/{i}") & P.LBL_EMBED_REQ
            assert np.abs(cstore.vec_get(f"txt/{i}")).max() > 0
        assert sup.lanes["embedder"].restarts >= 1
    finally:
        sup.stop()
        t.join()
        sup.shutdown()


@pytest.mark.slow
def test_supervise_restores_sharded_completer_lane(cstore, monkeypatch):
    """PR-8 chaos coverage: the pod-sharded continuous completer lane
    (tests/chaos_child.py completer_sharded — ShardedCompletionModel
    over the virtual 8-device CPU mesh) crashes at its FIRST sharded
    paged dispatch; `spt supervise` observes the crash, strips the
    fault from the respawn, and both the stranded pre-crash request
    and a post-crash request converge to READY."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    monkeypatch.setenv("SPTPU_FAULT",
                       "completer.sharded_dispatch:crash@1")
    # the child lane runs long; the supervisor's stop tears it down
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
    cstore.set("q", "hello sharded pod")
    cstore.label_or("q", P.LBL_INFER_REQ)
    cstore.bump("q")

    holder: dict = {}

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, CHILD, "completer_sharded", cstore.name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(cstore.name, lanes=("completer",), spawn_fn=spawn,
                     store=cstore, backoff_base_ms=100,
                     backoff_max_ms=2000, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 240.0})
    t.start()
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if cstore.labels("q") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q") & P.LBL_READY, sup.lanes
        assert sup.lanes["completer"].restarts >= 1   # crash observed
        assert sup.lanes["completer"].state != "down"
        # a request submitted AFTER the crash round-trips too (the
        # generation-2 child serves with the fault stripped)
        cstore.set("q2", "again, sharded")
        cstore.label_or("q2", P.LBL_INFER_REQ)
        cstore.bump("q2")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if cstore.labels("q2") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q2") & P.LBL_READY
        # the slot holds the rendered prompt (+ any generated pieces —
        # the tiny random weights may greedily sample eos first, which
        # is a legitimate zero-token completion)
        assert cstore.get("q2").rstrip(b"\0").startswith(
            b"again, sharded")
        assert not cstore.labels("q2") & (P.LBL_INFER_REQ
                                          | P.LBL_SERVICING)
    finally:
        sup.stop()
        t.join()
        sup.shutdown()


@pytest.mark.slow
def test_supervise_restores_quantized_commit_crash(cstore, monkeypatch):
    """PR-9 chaos coverage: the int8-quantized continuous lane
    (tests/chaos_child.py completer_quant) crashes MID-QUANTIZED-
    COMMIT — completer.kv_quant_commit fires after the request is
    claimed (SERVICING) and right before the commit scatter quantizes
    its prompt K/V into pool pages.  `spt supervise` observes the
    crash, strips the fault from the respawn, and both the stranded
    pre-crash request and a post-crash request converge to READY —
    the restarted lane's pool is freshly built, so no half-quantized
    page can ever serve (no poisoned pages by construction: the pool
    dies with the process, and the heartbeat's pages_free confirms a
    clean pool after the requests finish)."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    monkeypatch.setenv("SPTPU_FAULT",
                       "completer.kv_quant_commit:crash@1")
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
    cstore.set("q", "hello quantized pool")
    cstore.label_or("q", P.LBL_INFER_REQ)
    cstore.bump("q")

    holder: dict = {}

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, CHILD, "completer_quant", cstore.name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(cstore.name, lanes=("completer",), spawn_fn=spawn,
                     store=cstore, backoff_base_ms=100,
                     backoff_max_ms=2000, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 240.0})
    t.start()
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if cstore.labels("q") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q") & P.LBL_READY, sup.lanes
        assert sup.lanes["completer"].restarts >= 1   # crash observed
        assert sup.lanes["completer"].state != "down"
        # a request submitted AFTER the crash round-trips too
        cstore.set("q2", "again, quantized")
        cstore.label_or("q2", P.LBL_INFER_REQ)
        cstore.bump("q2")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if cstore.labels("q2") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q2") & P.LBL_READY
        assert cstore.get("q2").rstrip(b"\0").startswith(
            b"again, quantized")
        assert not cstore.labels("q2") & (P.LBL_INFER_REQ
                                          | P.LBL_SERVICING)
        # the generation-2 heartbeat shows the quantized pool CLEAN
        # after both requests finished: every page back on the free
        # list (a poisoned/leaked page would show as pages_used > 0).
        # Poll past the 2 s heartbeat cadence so we read a beat
        # published AFTER the second request freed its pages.
        deadline = time.monotonic() + 30
        hb = {}
        while time.monotonic() < deadline:
            try:
                hb = json.loads(cstore.get("__completer_stats")
                                .rstrip(b"\0"))
            except (KeyError, ValueError):
                hb = {}
            if hb.get("kv_dtype") == "int8" \
                    and hb.get("pages_used") == 0:
                break
            time.sleep(0.5)
        assert hb.get("kv_dtype") == "int8", hb
        assert hb.get("pages_used") == 0, hb
    finally:
        sup.stop()
        t.join()
        sup.shutdown()
