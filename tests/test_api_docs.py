"""docs/api/ is generated from sptpu.h (scripts/gen_api_docs.py,
VERDICT r4 #9) — these tests keep it complete and in sync."""
from __future__ import annotations

import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(ROOT, "native", "include", "sptpu.h")
DOCS = os.path.join(ROOT, "docs", "api")


def header_functions() -> set[str]:
    """Every function declared in the public header."""
    with open(HEADER) as f:
        src = f.read()
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)   # strip comments
    names = set()
    for m in re.finditer(
            r"\b(spt_[A-Za-z0-9_]+)\s*\(", src):
        # a '(' directly after the name inside a declaration line;
        # exclude macro uses (none in the header) and the struct tag
        names.add(m.group(1))
    return names


def test_every_header_function_documented():
    funcs = header_functions()
    assert len(funcs) >= 70, f"expected the ~70-symbol ABI, got {len(funcs)}"
    documented = set()
    for fn in os.listdir(DOCS):
        if not fn.endswith(".md"):
            continue
        with open(os.path.join(DOCS, fn)) as f:
            for m in re.finditer(r"^## `(spt_[A-Za-z0-9_]+)`", f.read(),
                                 re.M):
                documented.add(m.group(1))
    missing = funcs - documented
    assert not missing, f"undocumented ABI functions: {sorted(missing)}"


def test_docs_in_sync_with_header(tmp_path):
    """Regenerating must reproduce the committed pages byte-for-byte."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gen_api_docs.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    gen = sorted(os.listdir(tmp_path))
    committed = sorted(p for p in os.listdir(DOCS) if p.endswith(".md"))
    assert gen == committed, (
        f"page set drifted: generated {gen} vs committed {committed} "
        f"— run scripts/gen_api_docs.py")
    for name in gen:
        with open(os.path.join(tmp_path, name)) as f:
            want = f.read()
        with open(os.path.join(DOCS, name)) as f:
            have = f.read()
        assert have == want, (
            f"docs/api/{name} is stale — run scripts/gen_api_docs.py")


def test_index_links_resolve():
    with open(os.path.join(DOCS, "index.md")) as f:
        idx = f.read()
    for m in re.finditer(r"\]\(([a-z0-9-]+\.md)\)", idx):
        assert os.path.exists(os.path.join(DOCS, m.group(1))), \
            f"index links to missing page {m.group(1)}"


# --- splint-registry-derived tables (PR 11) ---------------------------
# The label-bit table (bloom-labels appendix) and the operations.md
# fault-point + rule catalogs are GENERATED from the splint registry
# (libsplinter_tpu/analysis).  The byte-sync test above already pins
# docs/api; these pin the operations.md marked regions, which live
# outside the regenerated page set.

def _load_gen_api_docs():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_gen_api_docs_test",
        os.path.join(ROOT, "scripts", "gen_api_docs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_label_bit_table_derived_from_registry():
    gen = _load_gen_api_docs()
    splint = gen.load_splint()
    table = splint.registry.render_label_table(
        splint.extract_registry())
    with open(os.path.join(DOCS, "bloom-labels.md")) as f:
        page = f.read()
    assert table in page, (
        "bloom-labels label-bit table stale vs protocol.py — run "
        "scripts/gen_api_docs.py")
    # every live LBL_ constant has a row
    for name in splint.extract_registry().labels:
        assert f"`{name}`" in table


def test_operations_fault_catalog_derived_from_sites():
    gen = _load_gen_api_docs()
    splint = gen.load_splint()
    table = splint.registry.render_fault_table(root=ROOT)
    with open(os.path.join(ROOT, "docs", "operations.md")) as f:
        ops = f.read()
    assert splint.registry.OPERATIONS_BEGIN in ops
    assert table in ops, (
        "operations.md fault catalog stale vs the instrumented "
        "sites — run scripts/gen_api_docs.py")
    # every discovered fault() call site has a row
    for site in {s.site for s in splint.fault_sites(ROOT)}:
        assert f"`{site}`" in table


def test_operations_rule_catalog_derived_from_registry():
    gen = _load_gen_api_docs()
    splint = gen.load_splint()
    import sys as _sys
    core = _sys.modules[splint.__name__ + ".core"]
    with open(os.path.join(ROOT, "docs", "operations.md")) as f:
        ops = f.read()
    assert core.RULES_BEGIN in ops
    assert core.render_rule_table() in ops, (
        "operations.md splint rule catalog stale — run "
        "scripts/gen_api_docs.py")
