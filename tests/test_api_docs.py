"""docs/api/ is generated from sptpu.h (scripts/gen_api_docs.py,
VERDICT r4 #9) — these tests keep it complete and in sync."""
from __future__ import annotations

import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(ROOT, "native", "include", "sptpu.h")
DOCS = os.path.join(ROOT, "docs", "api")


def header_functions() -> set[str]:
    """Every function declared in the public header."""
    with open(HEADER) as f:
        src = f.read()
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)   # strip comments
    names = set()
    for m in re.finditer(
            r"\b(spt_[A-Za-z0-9_]+)\s*\(", src):
        # a '(' directly after the name inside a declaration line;
        # exclude macro uses (none in the header) and the struct tag
        names.add(m.group(1))
    return names


def test_every_header_function_documented():
    funcs = header_functions()
    assert len(funcs) >= 70, f"expected the ~70-symbol ABI, got {len(funcs)}"
    documented = set()
    for fn in os.listdir(DOCS):
        if not fn.endswith(".md"):
            continue
        with open(os.path.join(DOCS, fn)) as f:
            for m in re.finditer(r"^## `(spt_[A-Za-z0-9_]+)`", f.read(),
                                 re.M):
                documented.add(m.group(1))
    missing = funcs - documented
    assert not missing, f"undocumented ABI functions: {sorted(missing)}"


def test_docs_in_sync_with_header(tmp_path):
    """Regenerating must reproduce the committed pages byte-for-byte."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gen_api_docs.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    gen = sorted(os.listdir(tmp_path))
    committed = sorted(p for p in os.listdir(DOCS) if p.endswith(".md"))
    assert gen == committed, (
        f"page set drifted: generated {gen} vs committed {committed} "
        f"— run scripts/gen_api_docs.py")
    for name in gen:
        with open(os.path.join(tmp_path, name)) as f:
            want = f.read()
        with open(os.path.join(DOCS, name)) as f:
            have = f.read()
        assert have == want, (
            f"docs/api/{name} is stale — run scripts/gen_api_docs.py")


def test_index_links_resolve():
    with open(os.path.join(DOCS, "index.md")) as f:
        idx = f.read()
    for m in re.finditer(r"\]\(([a-z0-9-]+\.md)\)", idx):
        assert os.path.exists(os.path.join(DOCS, m.group(1))), \
            f"index links to missing page {m.group(1)}"
