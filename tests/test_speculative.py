"""Speculative decoding (models/speculative.py).

Correctness bars: greedy speculative output is token-identical to the
target decoding alone (any draft); a draft that IS the target accepts
every proposal; the filtered-probability helper matches the sampler
chain's distribution; cache discipline survives many steps and
rejections.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import (CompletionModel,
                                            DecoderConfig, _sample_graph)
from libsplinter_tpu.models.speculative import (SpeculativeCompletionModel,
                                                _filtered_probs)

CFG = DecoderConfig.tiny(dtype=jnp.float32)
SMALL = DecoderConfig.tiny(dtype=jnp.float32, layers=1, hidden=32,
                           heads=2, kv_heads=2, mlp_dim=64)
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2], np.int32)


def _target():
    return CompletionModel(CFG, buckets=(16,), temp=0.0, seed=2)


def _draft():
    return CompletionModel(SMALL, buckets=(16,), temp=0.0, seed=5)


def test_greedy_equals_target_only():
    """Whatever the draft proposes, greedy speculative output must be
    exactly the target's own greedy sequence."""
    t = _target()
    want = [int(x) for x in t.generate_tokens(PROMPT, 24, chunk=8)]
    t.reset()
    for gamma in (1, 3, 4):
        spec = SpeculativeCompletionModel(_target(), _draft(),
                                          gamma=gamma)
        got = [int(x) for x in spec.generate_tokens(PROMPT, 24)]
        spec.reset()
        assert got == want, f"gamma={gamma}: {got} != {want}"


def test_draft_equals_target_accepts_everything():
    """With the draft sharing the target's params, the acceptance
    ratio is 1 everywhere: every proposal accepted."""
    t = _target()
    d = CompletionModel(CFG, buckets=(16,), temp=0.0, seed=2)
    spec = SpeculativeCompletionModel(t, d, gamma=4)
    out = [x for x in spec.generate_tokens(PROMPT, 20)]
    assert len(out) == 20
    assert spec.acceptance_rate == 1.0


def test_eos_stops_mid_step():
    t = _target()
    toks = [int(x) for x in t.generate_tokens(PROMPT, 24, chunk=8)]
    t.reset()
    eos = toks[5]                     # force a stop partway through
    spec = SpeculativeCompletionModel(_target(), _draft(), gamma=4)
    got = [int(x) for x in spec.generate_tokens(PROMPT, 24, eos_id=eos)]
    assert got[-1] == eos
    assert eos not in got[:-1]
    assert got == toks[: toks.index(eos) + 1]


def test_filtered_probs_matches_sampler_chain():
    """_filtered_probs must be the categorical distribution
    _sample_graph draws from: empirical frequencies agree."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, 32).astype(np.float32))
    p = np.asarray(_filtered_probs(logits, top_p=0.8, temp=0.9))
    assert abs(p.sum() - 1.0) < 1e-5
    draws = np.array([int(_sample_graph(jax.random.PRNGKey(i), logits,
                                        0.8, 0.9)) for i in range(400)])
    freq = np.bincount(draws, minlength=32) / len(draws)
    # support must match exactly; frequencies within sampling noise
    assert set(np.nonzero(freq)[0]) <= set(np.nonzero(p > 1e-9)[0])
    top = int(np.argmax(p))
    assert abs(freq[top] - p[top]) < 0.08


def test_filtered_probs_greedy_one_hot():
    logits = jnp.asarray(np.array([0.1, 3.0, -1.0], np.float32))
    p = np.asarray(_filtered_probs(logits, top_p=0.9, temp=0.0))
    assert p[1] == 1.0 and p.sum() == 1.0


def test_sampled_mode_runs_and_counts():
    """temp>0: generation completes, stats tally, tokens in vocab."""
    t = CompletionModel(CFG, buckets=(16,), temp=0.7, seed=2)
    spec = SpeculativeCompletionModel(t, _draft(), gamma=3)
    out = [int(x) for x in spec.generate_tokens(PROMPT, 18)]
    assert len(out) == 18
    assert all(0 <= x < CFG.vocab_size for x in out)
    assert spec.stats_proposed > 0
    assert 0.0 <= spec.acceptance_rate <= 1.0


def test_speculative_over_quantized_target():
    """Features compose: an int8-resident target behind speculative
    decoding still matches ITS own greedy output."""
    qcfg = DecoderConfig.tiny(dtype=jnp.float32, quantized=True)
    t = CompletionModel(qcfg, buckets=(16,), temp=0.0, seed=2)
    want = [int(x) for x in t.generate_tokens(PROMPT, 14, chunk=4)]
    t.reset()
    spec = SpeculativeCompletionModel(
        CompletionModel(qcfg, buckets=(16,), temp=0.0, seed=2),
        _draft(), gamma=3)
    got = [int(x) for x in spec.generate_tokens(PROMPT, 14)]
    spec.reset()
    assert got == want


def test_window_tail_respected():
    """Generation near the context window shrinks gamma instead of
    overrunning the cache."""
    cfg = DecoderConfig.tiny(dtype=jnp.float32, max_len=32)
    t = CompletionModel(cfg, buckets=(16,), temp=0.0, seed=2)
    d = CompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32, layers=1, max_len=32),
        buckets=(16,), temp=0.0, seed=5)
    spec = SpeculativeCompletionModel(t, d, gamma=4)
    out = [int(x) for x in spec.generate_tokens(PROMPT, 64)]
    # window 32, prompt 7: at most ~24 decodable tokens, never a crash
    assert 1 <= len(out) <= 25
    assert t._pos < cfg.max_len
