"""Run the native C test tiers from pytest so `pytest tests/` covers the
whole stack (reference: CTest wires splinter_test + stress + chi_sao,
CMakeLists.txt:267-329)."""
import pathlib
import subprocess

import pytest

NATIVE = pathlib.Path(__file__).parent.parent / "native"


def _build(target: str) -> None:
    subprocess.run(["make", "-s", target], cwd=NATIVE, check=True,
                   capture_output=True, timeout=300)


def test_native_tap_unit_suite():
    """The C TAP behavioral suite, both shm and file backends."""
    _build("tests")
    r = subprocess.run([str(NATIVE / "build" / "spt_unit")],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"TAP failures:\n{r.stdout}"
    assert "0 failed" in r.stdout


@pytest.mark.slow
def test_native_stress_short():
    """MRSW integrity under fire, short run (CTest runs 7.5 s;
    CI-speed 2 s here — the full duration is `make check`)."""
    _build("tests")
    r = subprocess.run([str(NATIVE / "build" / "spt_stress"),
                        "--duration-ms", "2000"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "corrupt=0" in r.stdout
