"""Pod-sharded search end to end (VERDICT r1 item 3).

Single-process tests shard one host's lane over the virtual 8-device
CPU mesh; the multi-process test launches TWO real worker processes
wired by jax.distributed (2 virtual hosts, cross-process collectives)
and asserts the merged global result is identical on both workers and
equal to a dense single-host reference over the concatenated lanes.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import uuid

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.parallel import PodSearch

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fill(store, vecs):
    for i in range(len(vecs)):
        store.set(f"doc/{i}", f"text {i}")
        store.vec_set(f"doc/{i}", vecs[i])


def _dense_topk(lane, q, k):
    norms = np.linalg.norm(lane, axis=1) * np.linalg.norm(q)
    with np.errstate(invalid="ignore"):
        scores = np.where(norms > 0, lane @ q / np.maximum(norms, 1e-12),
                          -np.inf)
    order = np.argsort(-scores)[:k]
    return scores[order], order


class TestSingleProcess:
    def test_matches_dense_reference(self, store):
        dim = store.vec_dim
        rng = np.random.default_rng(11)
        vecs = rng.normal(size=(64, dim)).astype(np.float32)
        _fill(store, vecs)
        ps = PodSearch(store)
        q = rng.normal(size=dim).astype(np.float32)
        hits = ps.search(q, k=5)
        lane = np.array(store.vectors)
        want_s, want_i = _dense_topk(lane, q, 5)
        assert [h["slot"] for h in hits] == list(want_i)
        np.testing.assert_allclose([h["similarity"] for h in hits],
                                   want_s, rtol=1e-5)
        assert all(h["host"] == 0 for h in hits)
        # keys resolve through the store
        assert all(h["key"] == store.key_at(h["slot"]) for h in hits)

    def test_non_divisible_nslots_pads(self):
        name = f"/spt-pod-pad-{os.getpid()}"
        Store.unlink(name)
        st = Store.create(name, nslots=100, max_val=128, vec_dim=16)
        try:
            rng = np.random.default_rng(3)
            vecs = rng.normal(size=(50, 16)).astype(np.float32)
            _fill(st, vecs)
            ps = PodSearch(st)
            assert ps.global_n % ps.mesh.shape["dp"] == 0
            q = rng.normal(size=16).astype(np.float32)
            hits = ps.search(q, k=5)
            want_s, want_i = _dense_topk(np.array(st.vectors), q, 5)
            assert [h["slot"] for h in hits] == list(want_i)
            assert all(h["slot"] < 100 for h in hits)
        finally:
            st.close()
            Store.unlink(name)

    def test_mask_prefilters_rows(self, store):
        dim = store.vec_dim
        rng = np.random.default_rng(5)
        vecs = rng.normal(size=(16, dim)).astype(np.float32)
        _fill(store, vecs)
        ps = PodSearch(store)
        q = rng.normal(size=dim).astype(np.float32)
        top = ps.search(q, k=1)[0]
        mask = np.ones(store.nslots, np.float32)
        mask[top["slot"]] = 0.0
        second = ps.search(q, k=1, mask=mask)[0]
        assert second["slot"] != top["slot"]
        assert second["similarity"] <= top["similarity"]

    def test_incremental_staging(self, store):
        dim = store.vec_dim
        _fill(store, np.ones((8, dim), np.float32))
        ps = PodSearch(store)
        q = np.ones(dim, np.float32)
        ps.search(q, k=2)
        assert ps.full_stages == 1 and ps.rows_staged == 0
        ps.search(q, k=2)                     # no writes: no transfer
        assert ps.full_stages == 1 and ps.rows_staged == 0
        store.vec_set("doc/3", np.arange(dim, dtype=np.float32))
        ps.search(q, k=2)
        assert ps.full_stages == 1 and ps.rows_staged == 1

    def test_refresh_sees_new_writes(self, store):
        dim = store.vec_dim
        _fill(store, np.ones((4, dim), np.float32))
        ps = PodSearch(store)
        target = np.zeros(dim, np.float32)
        target[1] = 1.0
        ps.search(target, k=1)
        store.set("late", "late doc")
        store.vec_set("late", target)
        hits = ps.search(target, k=1)
        assert hits[0]["key"] == "late"
        assert hits[0]["similarity"] == pytest.approx(1.0, abs=1e-5)


class TestShardedTopkEdges:
    """sharded_topk edge cases straight on the mesh primitive (no
    store): k_local clamping when a shard's valid rows < k_local, and
    global index translation after the ICI merge — exercised on the
    jnp fallback AND the fused kernel in interpret mode (PR 3), which
    must agree."""

    def _mesh(self):
        from libsplinter_tpu.parallel.mesh import make_mesh
        return make_mesh()

    def _ref(self, vecs, q):
        norms = np.linalg.norm(vecs, axis=1) * np.linalg.norm(q)
        with np.errstate(invalid="ignore"):
            return np.where(norms > 0,
                            vecs @ q / np.maximum(norms, 1e-12),
                            -np.inf)

    @pytest.mark.parametrize("interpret", [False, True])
    def test_k_local_exceeds_shard_valid_rows(self, interpret):
        """3 live rows spread over an 8-shard mesh, k=10: every shard
        clamps k_local to its tile, shards with zero live rows
        contribute only filler, and the merge returns exactly the 3
        real candidates above the score floor."""
        from libsplinter_tpu.parallel.sharded_search import (
            shard_vectors, sharded_topk)
        mesh = self._mesh()
        rng = np.random.default_rng(21)
        vecs = np.zeros((64, 16), np.float32)
        live = [2, 33, 61]                     # shards 0, 4, 7
        vecs[live] = rng.normal(size=(3, 16)).astype(np.float32)
        q = rng.normal(size=16).astype(np.float32)
        s, i = sharded_topk(mesh, shard_vectors(mesh, vecs), q, 10,
                            use_pallas=False, interpret=interpret)
        keep = s > -1e29
        assert keep.sum() == 3
        assert set(i[keep].tolist()) == set(live)
        ref = self._ref(vecs, q)
        np.testing.assert_allclose(np.sort(s[keep]),
                                   np.sort(ref[live]), rtol=1e-5)

    @pytest.mark.parametrize("interpret", [False, True])
    def test_global_index_translation(self, interpret):
        """Winners planted on known shards come back with GLOBAL row
        ids (shard * local_n + local row), in rank order."""
        from libsplinter_tpu.parallel.sharded_search import (
            shard_vectors, sharded_topk)
        mesh = self._mesh()
        m = mesh.shape["dp"]
        local_n = 8
        n, d = m * local_n, 16
        rng = np.random.default_rng(22)
        vecs = rng.normal(size=(n, d)).astype(np.float32)
        q = rng.normal(size=d).astype(np.float32)
        # plant exact hits at the last row of shard 1 and the first
        # row of the last shard — translation errors (off-by-shard,
        # local-vs-global) land exactly on these boundaries
        g1 = 1 * local_n + (local_n - 1)
        g2 = (m - 1) * local_n + 0
        vecs[g1] = q * 2.0
        vecs[g2] = q * 0.5                     # colinear: cosine 1.0 too
        s, i = sharded_topk(mesh, shard_vectors(mesh, vecs), q, 4,
                            use_pallas=False, interpret=interpret)
        assert {int(i[0]), int(i[1])} == {g1, g2}
        np.testing.assert_allclose(s[:2], 1.0, atol=1e-5)
        ref = self._ref(vecs, q)
        order = np.argsort(-ref)[:4]
        assert set(i.tolist()) == set(order.tolist())

    def test_fused_and_jnp_paths_agree(self):
        from libsplinter_tpu.parallel.sharded_search import (
            shard_vectors, sharded_topk)
        mesh = self._mesh()
        rng = np.random.default_rng(23)
        vecs = rng.normal(size=(64, 16)).astype(np.float32)
        vecs[10:20] = 0.0                      # dead rows on one shard
        q = rng.normal(size=16).astype(np.float32)
        arr = shard_vectors(mesh, vecs)
        s_j, i_j = sharded_topk(mesh, arr, q, 5, use_pallas=False)
        s_f, i_f = sharded_topk(mesh, arr, q, 5, use_pallas=False,
                                interpret=True)
        np.testing.assert_allclose(s_f, s_j, rtol=1e-5)
        np.testing.assert_array_equal(i_f, i_j)


WORKER = r"""
import json, os, re, sys
# 2 devices per host -> 4 global; older jax lacks the config option and
# reads the XLA flag instead (must land before backend init).  REPLACE
# any inherited count (pytest's conftest exports =8) — merely skipping
# when present would hand each worker 8 devices
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=2").strip()
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass
import jax.distributed
pid = int(sys.argv[1]); coord = sys.argv[2]; out_path = sys.argv[3]
jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=pid)
sys.path.insert(0, os.environ["SPTPU_ROOT"])
from libsplinter_tpu import Store
from libsplinter_tpu.parallel import PodSearch
from libsplinter_tpu.parallel.mesh import make_mesh

dim, nslots = 16, 32
rng = np.random.default_rng(100 + pid)        # per-host distinct lanes
name = os.environ["SPTPU_POD_STORE"] + str(pid)
Store.unlink(name)
st = Store.create(name, nslots=nslots, max_val=128, vec_dim=dim)
vecs = rng.normal(size=(20, dim)).astype(np.float32)
for i in range(20):
    st.set(f"h{pid}/doc{i}", f"host {pid} text {i}")
    st.vec_set(f"h{pid}/doc{i}", vecs[i])

ps = PodSearch(st)
q = np.arange(dim, dtype=np.float32)          # same query everywhere
hits = ps.search(q, k=6)

# incremental multi-process restage (VERDICT r2 #2): one write on host 0
# must cost an O(changed) collective scatter, never a full restage
if pid == 0:
    st.vec_set("h0/doc5", q)                  # exact match for the query
hits2 = ps.search(q, k=6)
staged_after_write = ps.rows_staged
hits3 = ps.search(q, k=6)                     # no writes: no transfer

# mismatched per-host geometry must raise, not misattribute results
bad_name = name + "-bad"
Store.unlink(bad_name)
bad = Store.create(bad_name, nslots=32 if pid == 0 else 48,
                   max_val=128, vec_dim=dim)
try:
    PodSearch(bad)
    geometry_guard = "no-error"
except ValueError:
    geometry_guard = "raised"
bad.close()
Store.unlink(bad_name)

json.dump({"hits": hits, "hits2": hits2, "hits3": hits3,
           "full_stages": ps.full_stages,
           "rows_staged_after_write": staged_after_write,
           "rows_staged_final": ps.rows_staged,
           "geometry_guard": geometry_guard},
          open(out_path, "w"))
st.close()
Store.unlink(name)
"""


@pytest.mark.slow
def test_two_process_pod_search(tmp_path):
    port = 12000 + (os.getpid() % 2000)
    # make sure the port is free-ish
    with socket.socket() as s:
        try:
            s.bind(("127.0.0.1", port))
        except OSError:
            port += 1777
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ, SPTPU_ROOT=ROOT,
               SPTPU_POD_STORE=f"/spt-pod-{uuid.uuid4().hex[:6]}-")
    env.pop("JAX_PLATFORMS", None)
    outs = [tmp_path / "out0.json", tmp_path / "out1.json"]
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), coord, str(outs[i])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    for p in procs:
        try:
            _, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("pod worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]

    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    h0, h1 = r0["hits"], r1["hits"]
    assert h0 == h1, "workers disagree on the global result"

    # incremental restage: the post-write refresh was a collective
    # O(changed) scatter (1 row on host 0, 0 rows on host 1) — the
    # initial full stage stays the ONLY full stage
    for r, expect_rows in ((r0, 1), (r1, 0)):
        assert r["full_stages"] == 1, r
        assert r["rows_staged_after_write"] == expect_rows, r
        assert r["rows_staged_final"] == expect_rows, r  # idle refresh free
    assert r0["hits2"] == r1["hits2"]
    assert r0["hits3"] == r0["hits2"]
    # the written row won the search on both workers
    assert r0["hits2"][0]["key"] == "h0/doc5"
    assert r0["hits2"][0]["host"] == 0
    assert r0["hits2"][0]["similarity"] == pytest.approx(1.0, abs=1e-5)
    # ADVICE r2 medium: differing nslots across workers is an error
    assert r0["geometry_guard"] == "raised"
    assert r1["geometry_guard"] == "raised"

    # dense reference over the concatenated per-host lanes
    dim, nslots = 16, 32
    lanes = []
    for pid in range(2):
        rng = np.random.default_rng(100 + pid)
        vecs = rng.normal(size=(20, dim)).astype(np.float32)
        # rebuild the store layout host-side to learn slot indices
        name = f"/spt-pod-ref-{pid}"
        Store.unlink(name)
        st = Store.create(name, nslots=nslots, max_val=128, vec_dim=dim)
        for i in range(20):
            st.set(f"h{pid}/doc{i}", f"host {pid} text {i}")
            st.vec_set(f"h{pid}/doc{i}", vecs[i])
        lanes.append(np.array(st.vectors))
        st.close()
        Store.unlink(name)
    lane = np.concatenate(lanes)
    q = np.arange(dim, dtype=np.float32)
    norms = np.linalg.norm(lane, axis=1) * np.linalg.norm(q)
    scores = np.where(norms > 0, lane @ q / np.maximum(norms, 1e-12),
                      -np.inf)
    order = np.argsort(-scores)[:6]
    got_global = [h["host"] * nslots + h["slot"] for h in h0]
    assert got_global == list(order)
    np.testing.assert_allclose([h["similarity"] for h in h0],
                               scores[order], rtol=1e-4)
    # keys resolved across hosts (worker 0 sees worker 1's keys)
    hosts_seen = {h["host"] for h in h0}
    for h in h0:
        assert h["key"].startswith(f"h{h['host']}/")
    assert hosts_seen == {0, 1}, f"expected hits from both hosts: {h0}"
