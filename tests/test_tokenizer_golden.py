"""Golden cross-validation of the from-scratch tokenizers against the
HuggingFace `tokenizers` library (an independent Rust implementation of
the same algorithms llama.cpp mirrors).

Real checkpoints are unreachable in this offline image, so realistic
vocabularies are TRAINED here with HF trainers on a fixed corpus, then
both implementations must produce identical token ids on held-out text
(VERDICT r1 item 4: tokenizer parity evidence).  Training is
deterministic for a fixed corpus, so these are stable goldens.
"""
from __future__ import annotations

import json

import pytest

tokenizers = pytest.importorskip("tokenizers")

from tokenizers import (Tokenizer, models, normalizers,  # noqa: E402
                        pre_tokenizers, trainers)

from libsplinter_tpu.models.gguf import (ByteBpeTokenizer,  # noqa: E402
                                         UnigramTokenizer)
from libsplinter_tpu.models.tokenizer import \
    WordPieceTokenizer  # noqa: E402

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "seqlock arenas stage vectors to TPU HBM lanes",
    "hello world, hello tokenizer cross validation!",
    "writers CAS the epoch odd, publish, then release it even",
    "cosine similarity over a million vectors in pallas",
] * 40

HELD_OUT = [
    "the quick liquor jugs jump!",
    "hello TPU world",
    "a writer publishes vectors",
    "dog-gone lazy, isn't it?",
    "boxy foxes pack jugs",
]


@pytest.fixture(scope="module")
def hf_bpe():
    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tr = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(CORPUS, tr)
    return tok


def test_byte_bpe_matches_hf_rust_bpe(hf_bpe):
    state = json.loads(hf_bpe.to_str())
    vocab = state["model"]["vocab"]                 # piece -> id
    tokens = [p for p, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    merges = [f"{a} {b}" for a, b in state["model"]["merges"]]
    mine = ByteBpeTokenizer(tokens, merges)
    for text in HELD_OUT:
        want = hf_bpe.encode(text, add_special_tokens=False).ids
        got = mine.encode(text, add_bos=False)
        assert got == want, (text, got, want)
        assert mine.decode(got) == text


def test_byte_bpe_decode_inverts_unicode(hf_bpe):
    state = json.loads(hf_bpe.to_str())
    vocab = state["model"]["vocab"]
    tokens = [p for p, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    merges = [f"{a} {b}" for a, b in state["model"]["merges"]]
    mine = ByteBpeTokenizer(tokens, merges)
    for text in ["héllo wörld", "naïve café", "“smart quotes”"]:
        assert mine.decode(mine.encode(text, add_bos=False)) == text


@pytest.fixture(scope="module")
def hf_unigram():
    tok = Tokenizer(models.Unigram())
    tok.normalizer = normalizers.Sequence([
        normalizers.Replace(" ", "▁"),
        normalizers.Prepend("▁"),
    ])
    tr = trainers.UnigramTrainer(vocab_size=200,
                                 special_tokens=["<unk>"],
                                 unk_token="<unk>")
    tok.train_from_iterator(CORPUS, tr)
    return tok


def test_unigram_viterbi_matches_hf(hf_unigram):
    state = json.loads(hf_unigram.to_str())
    vocab = state["model"]["vocab"]                 # [[piece, score]...]
    tokens = [p for p, _ in vocab]
    scores = [s for _, s in vocab]
    mine = UnigramTokenizer(tokens, scores, bos_token_id=-1,
                            eos_token_id=-1, unknown_token_id=0)
    for text in HELD_OUT:
        want = hf_unigram.encode(text, add_special_tokens=False).ids
        got = mine.encode(text, add_bos=False)
        assert got == want, (
            text,
            [tokens[i] for i in got],
            [tokens[i] for i in want])


def test_wordpiece_matches_hf():
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "quick", "brown", "fox", "jump", "##s", "##ed",
             "over", "lazy", "dog", "hello", "world", "##ly", "li",
             "##quo", "##r", ",", "!", "'", "t", "isn", "##n"]
    hf = Tokenizer(models.WordPiece(
        vocab={t: i for i, t in enumerate(vocab)}, unk_token="[UNK]",
        max_input_chars_per_word=100))
    hf.normalizer = normalizers.BertNormalizer(lowercase=True)
    hf.pre_tokenizer = pre_tokenizers.BertPreTokenizer()
    mine = WordPieceTokenizer.from_vocab_list(vocab)
    for text in ["the quick brown fox jumps!", "Hello worldly dog,",
                 "liquor", "unknownword here"]:
        want = hf.encode(text, add_special_tokens=False).ids
        got = mine.encode(text)[1:-1]               # strip [CLS]/[SEP]
        assert got == want, (text, got, want)
