"""WASM scripting host tests.

Modules are hand-assembled binary wasm (no wat toolchain in the image) via
the tiny builder below, then run through the microwasm interpreter — pure
compute first, then store-backed host imports mirroring the reference's
splinter.get/set wasm surface (splinter_cli_cmd_wasm.c:85-143).
"""
from __future__ import annotations

import os
import struct

import pytest

from libsplinter_tpu.scripting.microwasm import (
    Trap, WasmError, instantiate,
)

I32, I64, F32, F64 = 0x7F, 0x7E, 0x7D, 0x7C


# ------------------------------------------------------- binary wasm builder

def uleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def sleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        done = (v == 0 and not b & 0x40) or (v == -1 and b & 0x40)
        out.append(b | (0 if done else 0x80))
        if done:
            return bytes(out)


def vec(items: list[bytes]) -> bytes:
    return uleb(len(items)) + b"".join(items)


def section(sid: int, payload: bytes) -> bytes:
    return bytes([sid]) + uleb(len(payload)) + payload


def functype(params: list[int], results: list[int]) -> bytes:
    return (b"\x60" + vec([bytes([p]) for p in params]) +
            vec([bytes([r]) for r in results]))


def name(s: str) -> bytes:
    b = s.encode()
    return uleb(len(b)) + b


def code_entry(local_groups: list[tuple[int, int]], body: bytes) -> bytes:
    locals_ = vec([uleb(n) + bytes([t]) for n, t in local_groups])
    payload = locals_ + body
    return uleb(len(payload)) + payload


def module(sections: list[bytes]) -> bytes:
    return b"\x00asm\x01\x00\x00\x00" + b"".join(sections)


def simple_module(params, results, body, locals_=()):
    """One exported function 'run' with the given raw body bytes."""
    return module([
        section(1, vec([functype(params, results)])),
        section(3, vec([uleb(0)])),
        section(7, vec([name("run") + b"\x00" + uleb(0)])),
        section(10, vec([code_entry(list(locals_), body)])),
    ])


# opcodes used below
END = b"\x0b"


def i32c(v):
    return b"\x41" + sleb(v)


def i64c(v):
    return b"\x42" + sleb(v)


LOCAL_GET = lambda i: b"\x20" + uleb(i)      # noqa: E731
LOCAL_SET = lambda i: b"\x21" + uleb(i)      # noqa: E731
CALL = lambda i: b"\x10" + uleb(i)           # noqa: E731


class TestInterpreterCore:
    def test_add(self):
        inst = instantiate(simple_module(
            [I32, I32], [I32],
            LOCAL_GET(0) + LOCAL_GET(1) + b"\x6a" + END))
        assert inst.invoke("run", [2, 40]) == [42]

    def test_loop_sum(self):
        # sum 1..n with loop + br_if: locals i(1), acc(2)
        body = (
            b"\x02\x40"                              # block void
            b"\x03\x40" +                            # loop void
            LOCAL_GET(1) + LOCAL_GET(0) + b"\x4a" +  # i > n ?
            b"\x0d\x01" +                            # br_if 1 (exit block)
            LOCAL_GET(2) + LOCAL_GET(1) + b"\x6a" + LOCAL_SET(2) +
            LOCAL_GET(1) + i32c(1) + b"\x6a" + LOCAL_SET(1) +
            b"\x0c\x00" +                            # br 0 (continue loop)
            END + END +
            LOCAL_GET(2) + END)
        m = module([
            section(1, vec([functype([I32], [I32])])),
            section(3, vec([uleb(0)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry([(2, I32)],
                                        LOCAL_GET(0) + b"\x1a" +  # warm drop
                                        i32c(1) + LOCAL_SET(1) +
                                        i32c(0) + LOCAL_SET(2) + body)])),
        ])
        inst = instantiate(m)
        assert inst.invoke("run", [10]) == [55]
        assert inst.invoke("run", [100]) == [5050]

    def test_if_else(self):
        body = (LOCAL_GET(0) + i32c(0) + b"\x48" +   # n < 0 (signed)
                b"\x04\x7f" +                        # if (result i32)
                i32c(-1) + b"\x05" + i32c(1) + END + END)
        inst = instantiate(simple_module([I32], [I32], body))
        assert inst.invoke("run", [-5]) == [4294967295]  # -1 as u32
        assert inst.invoke("run", [5]) == [1]

    def test_recursion_factorial(self):
        # fact(n) = n<2 ? 1 : n*fact(n-1)
        fact_body = (
            LOCAL_GET(0) + i32c(2) + b"\x48" +       # n < 2
            b"\x04\x7f" + i32c(1) +                  # then 1
            b"\x05" +                                # else
            LOCAL_GET(0) + LOCAL_GET(0) + i32c(1) + b"\x6b" +
            CALL(0) + b"\x6c" +
            END + END)
        m = module([
            section(1, vec([functype([I32], [I32])])),
            section(3, vec([uleb(0)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry([], fact_body)])),
        ])
        assert instantiate(m).invoke("run", [10]) == [3628800]

    def test_i64_and_div_trap(self):
        inst = instantiate(simple_module(
            [I64, I64], [I64],
            LOCAL_GET(0) + LOCAL_GET(1) + b"\x7e" + END))
        assert inst.invoke("run", [1 << 40, 4]) == [1 << 42]
        div = instantiate(simple_module(
            [I32, I32], [I32],
            LOCAL_GET(0) + LOCAL_GET(1) + b"\x6d" + END))
        with pytest.raises(Trap, match="divide by zero"):
            div.invoke("run", [1, 0])

    def test_f64_math(self):
        inst = instantiate(simple_module(
            [F64], [F64], LOCAL_GET(0) + b"\x9f" + END))  # f64.sqrt
        assert inst.invoke("run", [81.0]) == [9.0]

    def test_memory_store_load(self):
        # store arg at [16], load it back
        body = (i32c(16) + LOCAL_GET(0) + b"\x36\x02\x00" +  # i32.store
                i32c(16) + b"\x28\x02\x00" + END)            # i32.load
        m = module([
            section(1, vec([functype([I32], [I32])])),
            section(3, vec([uleb(0)])),
            section(5, vec([b"\x00" + uleb(1)])),            # 1 page
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry([], body)])),
        ])
        assert instantiate(m).invoke("run", [0xDEAD]) == [0xDEAD]

    def test_memory_oob_traps(self):
        body = (i32c(70000) + i32c(1) + b"\x36\x02\x00" + i32c(0) + END)
        m = module([
            section(1, vec([functype([], [I32])])),
            section(3, vec([uleb(0)])),
            section(5, vec([b"\x00" + uleb(1)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry([], body)])),
        ])
        with pytest.raises(Trap, match="out-of-bounds"):
            instantiate(m).invoke("run", [])

    def test_data_segment_and_export(self):
        m = module([
            section(1, vec([functype([], [I32])])),
            section(3, vec([uleb(0)])),
            section(5, vec([b"\x00" + uleb(1)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry(
                [], i32c(8) + b"\x2d\x00\x00" + END)])),     # load8_u @8
            section(11, vec([b"\x00" + i32c(8) + END +
                             uleb(1) + b"\x2a"])),           # byte 42 @8
        ])
        assert instantiate(m).invoke("run", []) == [42]

    def test_unreachable_and_unsupported(self):
        m = simple_module([], [], b"\x00" + END)
        with pytest.raises(Trap, match="unreachable"):
            instantiate(m).invoke("run", [])
        with pytest.raises(WasmError, match="magic"):
            instantiate(b"\x00asm\x02\x00\x00\x00")

    def test_runaway_guard(self):
        body = b"\x03\x40" + b"\x0c\x00" + END + END  # loop { br 0 }
        m = simple_module([], [], body)
        inst = instantiate(m)
        inst.MAX_STEPS = 10_000
        with pytest.raises(Trap, match="budget"):
            inst.invoke("run", [])


# --------------------------------------------------------- store host tests

def host_module() -> bytes:
    """imports splinter.set/get + env.print; data: key@0 "wkey" (4), val@8
    "hello wasm" (10); run(): set(key, val); n = get(key -> @64 cap 32);
    print(@64, n); return n."""
    t_set = functype([I32, I32, I32, I32], [I32])
    t_get = functype([I32, I32, I32, I32], [I32])
    t_print = functype([I32, I32], [])
    t_run = functype([], [I32])
    run_body = (
        i32c(0) + i32c(4) + i32c(8) + i32c(10) + CALL(0) + b"\x1a" +
        i32c(0) + i32c(4) + i32c(64) + i32c(32) + CALL(1) +
        LOCAL_SET(0) +
        i32c(64) + LOCAL_GET(0) + CALL(2) +
        LOCAL_GET(0) + END)
    return module([
        section(1, vec([t_set, t_get, t_print, t_run])),
        section(2, vec([
            name("splinter") + name("set") + b"\x00" + uleb(0),
            name("splinter") + name("get") + b"\x00" + uleb(1),
            name("env") + name("print") + b"\x00" + uleb(2),
        ])),
        section(3, vec([uleb(3)])),                   # run : type 3
        section(5, vec([b"\x00" + uleb(1)])),
        section(7, vec([name("run") + b"\x00" + uleb(3)])),
        section(10, vec([code_entry([(1, I32)], run_body)])),
        section(11, vec([
            b"\x00" + i32c(0) + END + uleb(4) + b"wkey",
            b"\x00" + i32c(8) + END + uleb(10) + b"hello wasm",
        ])),
    ])


class TestStoreHost:
    @pytest.fixture
    def store(self):
        from libsplinter_tpu.store import Store
        nm = f"wasm-host-{os.getpid()}"
        st = Store.create(nm, nslots=64, max_val=256, vec_dim=4)
        yield st
        st.close()
        Store.unlink(nm)

    def test_set_get_print_roundtrip(self, store):
        from libsplinter_tpu.scripting.wasm_host import make_host_imports
        printed = []
        inst = instantiate(host_module(),
                           make_host_imports(store, out=printed.append))
        assert inst.invoke("run", []) == [10]
        assert store.get("wkey") == b"hello wasm"
        assert printed == ["hello wasm"]

    def test_get_missing_returns_negative_errno(self, store):
        from libsplinter_tpu.scripting.wasm_host import make_host_imports
        # run() gets before any set: patch module to call get only
        t_get = functype([I32, I32, I32, I32], [I32])
        t_run = functype([], [I32])
        body = i32c(0) + i32c(4) + i32c(64) + i32c(32) + CALL(0) + END
        m = module([
            section(1, vec([t_get, t_run])),
            section(2, vec([name("splinter") + name("get") +
                            b"\x00" + uleb(0)])),
            section(3, vec([uleb(1)])),
            section(5, vec([b"\x00" + uleb(1)])),
            section(7, vec([name("run") + b"\x00" + uleb(1)])),
            section(10, vec([code_entry([], body)])),
            section(11, vec([b"\x00" + i32c(0) + END + uleb(4) + b"nope"])),
        ])
        inst = instantiate(m, make_host_imports(store))
        rc = inst.invoke("run", [])[0]
        assert rc == (-2 & 0xFFFFFFFF) or rc == -2   # -ENOENT

    def test_cli_wasm_command(self, store, tmp_path, capsys):
        from libsplinter_tpu.cli.main import Session, dispatch
        mod = tmp_path / "m.wasm"
        mod.write_bytes(host_module())
        ses = Session.__new__(Session)
        ses.store_name = store.name
        ses.ns_prefix = ""
        ses.persistent = False
        ses._store = store
        ses.labels = {}
        dispatch(ses, ["wasm", str(mod), "run"])
        out = capsys.readouterr().out
        assert "hello wasm" in out and "10" in out
        assert store.get("wkey") == b"hello wasm"


# ------------------------------------------------------------ SIMD (v128)

V128 = 0x7B


def fd(sub: int, *extra: bytes) -> bytes:
    return b"\xfd" + uleb(sub) + b"".join(extra)


def v128c(raw16: bytes) -> bytes:
    assert len(raw16) == 16
    return fd(12, raw16)


def memory_module(params, results, body):
    return module([
        section(1, vec([functype(params, results)])),
        section(3, vec([uleb(0)])),
        section(5, vec([b"\x00" + uleb(1)])),               # 1 page
        section(7, vec([name("run") + b"\x00" + uleb(0)])),
        section(10, vec([code_entry([], body)])),
    ])


class TestSimd:
    def test_i32x4_add_and_extract(self):
        a = struct.pack("<4i", 1, 2, 3, 4)
        b = struct.pack("<4i", 10, 20, 30, -40)
        body = v128c(a) + v128c(b) + fd(174) + fd(27, b"\x03") + END
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [(4 + -40) & 0xFFFFFFFF]

    def test_splat_mul_f32x4(self):
        body = (b"\x43" + struct.pack("<f", 1.5) + fd(19) +   # splat 1.5
                b"\x43" + struct.pack("<f", 2.0) + fd(19) +   # splat 2.0
                fd(230) +                                     # f32x4.mul
                fd(31, b"\x02") + END)                        # extract lane
        inst = instantiate(simple_module([], [F32], body))
        assert inst.invoke("run", []) == [3.0]

    def test_load_store_roundtrip(self):
        payload = bytes(range(16))
        body = (i32c(0) + v128c(payload) + fd(11, b"\x00", b"\x00") +
                i32c(0) + fd(0, b"\x00", b"\x00") +
                fd(21, b"\x05") + END)          # i8x16.extract_lane_s 5
        inst = instantiate(memory_module([], [I32], body))
        assert inst.invoke("run", []) == [5]

    def test_shuffle_reverses(self):
        a = bytes(range(16))
        ctl = bytes(range(15, -1, -1))
        body = (v128c(a) + v128c(b"\xff" * 16) + fd(13, ctl) +
                fd(22, b"\x00") + END)          # extract_lane_u 0
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [15]

    def test_swizzle_out_of_range_zeroes(self):
        a = bytes(range(16, 32))
        idx = bytes([0, 31, 2, 200] + [0] * 12)
        body = (v128c(a) + v128c(idx) + fd(14) +
                fd(22, b"\x03") + END)
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [0]    # index 200 -> 0

    def test_saturating_i8_add(self):
        a = struct.pack("<16b", *([127] * 16))
        b = struct.pack("<16b", *([1] * 16))
        body = (v128c(a) + v128c(b) + fd(111) +  # i8x16.add_sat_s
                fd(21, b"\x00") + END)
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [127]  # clamped, not wrapped

    def test_compare_bitmask_alltrue(self):
        a = struct.pack("<4i", 5, -1, 7, 0)
        b = struct.pack("<4i", 4, 0, 9, 1)
        # gt_s -> lanes (T, F, F, F); bitmask -> 0b0001
        body = v128c(a) + v128c(b) + fd(59) + fd(164) + END
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [0b0001]
        # all_true over a vector with one zero lane
        body2 = v128c(a) + fd(163) + END
        assert instantiate(
            simple_module([], [I32], body2)).invoke("run", []) == [0]
        body3 = v128c(a) + fd(83) + END         # any_true
        assert instantiate(
            simple_module([], [I32], body3)).invoke("run", []) == [1]

    def test_shifts(self):
        a = struct.pack("<4i", -8, 8, 16, 1)
        body = (v128c(a) + i32c(2) + fd(172) +  # i32x4.shr_s by 2
                fd(27, b"\x00") + END)
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [(-2) & 0xFFFFFFFF]

    def test_narrow_and_extend(self):
        a = struct.pack("<8h", 300, -300, 5, 6, 7, 8, 9, 10)
        body = (v128c(a) + v128c(a) + fd(101) +  # narrow_i16x8_s
                fd(21, b"\x00") + END)           # 300 clamps to 127
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [127]
        body2 = (v128c(a) + fd(135) +            # extend_low_i8x16_s
                 fd(24, b"\x00") + END)          # lane0 of i16x8
        got = instantiate(
            simple_module([], [I32], body2)).invoke("run", [])
        assert got == [struct.unpack("<16b", a)[0] & 0xFFFFFFFF]

    def test_trunc_sat_nan_is_zero(self):
        a = struct.pack("<4f", float("nan"), 1.9, -2.9, 3e9)
        body = (v128c(a) + fd(248) +             # i32x4.trunc_sat_f32x4_s
                fd(27, b"\x00") + END)
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [0]
        body2 = v128c(a) + fd(248) + fd(27, b"\x03") + END
        assert instantiate(simple_module([], [I32], body2)).invoke(
            "run", []) == [2**31 - 1]            # 3e9 saturates

    def test_v128_local_defaults_zero(self):
        body = (LOCAL_GET(0) + fd(83) + END)     # any_true(zero) == 0
        inst = instantiate(simple_module([], [I32], body,
                                         locals_=[(1, V128)]))
        assert inst.invoke("run", []) == [0]

    def test_dot_product(self):
        a = struct.pack("<8h", 1, 2, 3, 4, 5, 6, 7, 8)
        b = struct.pack("<8h", 1, 1, 1, 1, 1, 1, 1, 1)
        body = v128c(a) + v128c(b) + fd(186) + fd(27, b"\x00") + END
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [3]     # 1*1 + 2*1

    def test_bitselect(self):
        a = b"\xaa" * 16
        b = b"\x55" * 16
        c = b"\xf0" * 16
        body = (v128c(a) + v128c(b) + v128c(c) + fd(82) +
                fd(22, b"\x00") + END)
        inst = instantiate(simple_module([], [I32], body))
        assert inst.invoke("run", []) == [(0xAA & 0xF0) | (0x55 & 0x0F)]

    def test_unsupported_simd_tail_raises(self):
        body = v128c(b"\x00" * 16) + v128c(b"\x00" * 16) + fd(156) + END
        with pytest.raises(WasmError, match="SIMD"):
            instantiate(simple_module([], [I32], body))

    def test_lane_immediate_out_of_range_rejected(self):
        body = v128c(b"\x00" * 16) + fd(27, b"\x09") + END
        with pytest.raises(WasmError, match="lane 9 out of range"):
            instantiate(simple_module([], [I32], body))

    def test_shuffle_control_out_of_range_rejected(self):
        ctl = bytes([40] + [0] * 15)
        body = (v128c(b"\x00" * 16) + v128c(b"\x00" * 16) +
                fd(13, ctl) + fd(22, b"\x00") + END)
        with pytest.raises(WasmError, match="shuffle lane"):
            instantiate(simple_module([], [I32], body))


def f64c(v):
    return b"\x44" + struct.pack("<d", v)


def FC(sub, imm=b""):
    return b"\xfc" + uleb(sub) + imm


class TestBulkMemory:
    """Bulk-memory proposal (memory.copy/fill/init, data.drop, passive
    segments + DataCount section) — the encodings modern
    `clang --target=wasm32` emits by default; the reference gets them
    from WasmEdge (splinter_cli_cmd_wasm.c:85-143)."""

    def bulk_module(self, body, *, passive=b"hello, bulk!", n_funcs=1):
        return module([
            section(1, vec([functype([], [])])),
            section(3, vec([uleb(0)])),
            section(5, vec([b"\x00" + uleb(1)])),          # 1 page
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(12, uleb(1)),                          # DataCount
            section(10, vec([code_entry([], body)])),
            section(11, vec([b"\x01" + uleb(len(passive)) + passive])),
        ])

    def test_init_copy_fill_roundtrip(self):
        body = (
            # memory.init: dst=16 src=0 n=12 from passive segment 0
            i32c(16) + i32c(0) + i32c(12) + FC(8, uleb(0) + b"\x00") +
            # memory.copy: dst=100 src=16 n=12
            i32c(100) + i32c(16) + i32c(12) + FC(10, b"\x00\x00") +
            # memory.fill: dst=200 val=0x2A n=4
            i32c(200) + i32c(0x2A) + i32c(4) + FC(11, b"\x00") +
            END)
        inst = instantiate(self.bulk_module(body))
        inst.invoke("run", [])
        assert inst.mem_read(16, 12) == b"hello, bulk!"
        assert inst.mem_read(100, 12) == b"hello, bulk!"
        assert inst.mem_read(200, 4) == b"\x2a" * 4
        assert inst.mem_read(204, 2) == b"\x00\x00"

    def test_copy_overlapping_is_memmove(self):
        m = module([
            section(1, vec([functype([], [])])),
            section(3, vec([uleb(0)])),
            section(5, vec([b"\x00" + uleb(1)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry(
                [], i32c(2) + i32c(0) + i32c(6) + FC(10, b"\x00\x00")
                + END)])),
            section(11, vec([b"\x00" + i32c(0) + END +
                             uleb(8) + b"abcdefgh"])),
        ])
        inst = instantiate(m)
        inst.invoke("run", [])
        assert inst.mem_read(0, 8) == b"ababcdef"

    def test_data_drop_then_init_traps(self):
        drop_then_init = (
            FC(9, uleb(0)) +                              # data.drop 0
            i32c(0) + i32c(0) + i32c(1) +                 # n=1 must trap
            FC(8, uleb(0) + b"\x00") + END)
        inst = instantiate(self.bulk_module(drop_then_init))
        with pytest.raises(Trap, match="memory.init"):
            inst.invoke("run", [])

    def test_data_drop_then_zero_init_ok(self):
        body = (FC(9, uleb(0)) +
                i32c(0) + i32c(0) + i32c(0) +             # n=0 is fine
                FC(8, uleb(0) + b"\x00") + END)
        inst = instantiate(self.bulk_module(body))
        inst.invoke("run", [])

    def test_init_source_oob_traps(self):
        body = (i32c(0) + i32c(8) + i32c(8) +             # 8+8 > len(seg)
                FC(8, uleb(0) + b"\x00") + END)
        inst = instantiate(self.bulk_module(body))
        with pytest.raises(Trap, match="memory.init"):
            inst.invoke("run", [])

    def test_fill_oob_traps(self):
        body = (i32c(65530) + i32c(1) + i32c(100) +
                FC(11, b"\x00") + END)
        inst = instantiate(self.bulk_module(body))
        with pytest.raises(Trap, match="memory.fill"):
            inst.invoke("run", [])

class TestTables:
    """Funcref table tier: the elem-segment flag matrix, table.* bulk
    ops, and the ref opcodes — matching what the reference gets from
    WasmEdge's reference-types/bulk-memory support
    (splinter_cli_cmd_wasm.c:85-143).  Funcs 0..2 return 10..12; null
    refs are -1 in the unityped interpreter."""

    CALL_IND = b"\x11" + uleb(0) + uleb(0)    # call_indirect type0 tbl0

    def table_module(self, run_body, *, elem: bytes = b"",
                     table=(8, None)):
        tmin, tmax = table
        tbl = b"\x70" + (b"\x00" + uleb(tmin) if tmax is None
                         else b"\x01" + uleb(tmin) + uleb(tmax))
        consts = [code_entry([], i32c(10 + i) + END) for i in range(3)]
        secs = [
            section(1, vec([functype([], [I32])])),
            section(3, vec([uleb(0)] * 4)),
            section(4, vec([tbl])),
            section(7, vec([name("run") + b"\x00" + uleb(3)])),
        ]
        if elem:
            secs.append(section(9, elem))
        secs.append(section(10, vec(consts + [code_entry([], run_body)])))
        return module(secs)

    # elem segment encodings by flag
    @staticmethod
    def elem_active(off, funcs):
        return uleb(0) + i32c(off) + END + vec([uleb(f) for f in funcs])

    @staticmethod
    def elem_passive(funcs):
        return uleb(1) + b"\x00" + vec([uleb(f) for f in funcs])

    @staticmethod
    def elem_declared(funcs):
        return uleb(3) + b"\x00" + vec([uleb(f) for f in funcs])

    @staticmethod
    def elem_passive_exprs(entries):
        """entries: funcidx or None (ref.null)."""
        return uleb(5) + b"\x70" + vec(
            [(b"\xd0\x70" if f is None else b"\xd2" + uleb(f)) + END
             for f in entries])

    def test_active_elem_call_indirect(self):
        m = self.table_module(i32c(1) + self.CALL_IND + END,
                              elem=vec([self.elem_active(0, [0, 1, 2])]))
        assert instantiate(m).invoke("run", []) == [11]

    def test_table_init_from_passive(self):
        body = (i32c(0) + i32c(0) + i32c(3)
                + FC(12, uleb(0) + uleb(0))          # table.init seg0
                + i32c(2) + self.CALL_IND + END)
        m = self.table_module(body,
                              elem=vec([self.elem_passive([0, 1, 2])]))
        assert instantiate(m).invoke("run", []) == [12]

    def test_elem_drop_then_init_traps(self):
        body = (FC(13, uleb(0))                      # elem.drop 0
                + i32c(0) + i32c(0) + i32c(1)
                + FC(12, uleb(0) + uleb(0)) + END)
        m = self.table_module(body,
                              elem=vec([self.elem_passive([0])]))
        with pytest.raises(Trap, match="table.init"):
            instantiate(m).invoke("run", [])

    def test_elem_drop_then_zero_init_ok(self):
        body = (FC(13, uleb(0))
                + i32c(0) + i32c(0) + i32c(0)        # n=0 is fine
                + FC(12, uleb(0) + uleb(0)) + END)
        m = self.table_module(body,
                              elem=vec([self.elem_passive([0])]))
        instantiate(m).invoke("run", [])

    def test_table_copy_is_memmove(self):
        # table [f0,f1,f2,...] --copy d=1 s=0 n=2--> [f0,f0,f1,...]
        body = (i32c(1) + i32c(0) + i32c(2)
                + FC(14, uleb(0) + uleb(0))          # table.copy
                + i32c(2) + self.CALL_IND + END)
        m = self.table_module(body,
                              elem=vec([self.elem_active(0, [0, 1, 2])]))
        assert instantiate(m).invoke("run", []) == [11]

    def test_grow_size_and_max(self):
        # size(8) + grow(null, 4) -> 8; size -> 12; grow past max -> -1
        body = (FC(16, uleb(0))                      # table.size: 8
                + b"\xd0\x70" + i32c(4) + FC(15, uleb(0))   # grow: 8
                + b"\x6a"                            # 8 + 8 = 16
                + FC(16, uleb(0)) + b"\x6a"          # +12 = 28
                + b"\xd0\x70" + i32c(100) + FC(15, uleb(0)) # -> -1
                + b"\x6a" + END)                     # 28 + -1 = 27
        m = self.table_module(body, table=(8, 12))
        assert instantiate(m).invoke("run", []) == [27]

    def test_get_set_and_refs(self):
        # table.set 5 = ref.func 2; call 5 -> 12; ref.is_null(get 0) -> 1
        body = (i32c(5) + b"\xd2" + uleb(2) + b"\x26" + uleb(0)
                + i32c(5) + self.CALL_IND
                + i32c(0) + b"\x25" + uleb(0) + b"\xd1"
                + b"\x6a" + END)                     # 12 + 1
        m = self.table_module(body)
        assert instantiate(m).invoke("run", []) == [13]

    def test_table_fill_then_call(self):
        body = (i32c(2) + b"\xd2" + uleb(0) + i32c(3)
                + FC(17, uleb(0))                    # fill [2,5) = f0
                + i32c(4) + self.CALL_IND + END)
        m = self.table_module(body)
        assert instantiate(m).invoke("run", []) == [10]

    def test_expr_elems_and_null_trap(self):
        init = (i32c(0) + i32c(0) + i32c(2)
                + FC(12, uleb(0) + uleb(0)))
        m_ok = self.table_module(
            init + i32c(0) + self.CALL_IND + END,
            elem=vec([self.elem_passive_exprs([2, None])]))
        assert instantiate(m_ok).invoke("run", []) == [12]
        m_null = self.table_module(
            init + i32c(1) + self.CALL_IND + END,
            elem=vec([self.elem_passive_exprs([2, None])]))
        with pytest.raises(Trap, match="undefined table element"):
            instantiate(m_null).invoke("run", [])

    def test_declared_segment_starts_dropped(self):
        body = (i32c(0) + i32c(0) + i32c(1)
                + FC(12, uleb(0) + uleb(0)) + END)
        m = self.table_module(body,
                              elem=vec([self.elem_declared([1])]))
        with pytest.raises(Trap, match="table.init"):
            instantiate(m).invoke("run", [])

    def test_grow_unbounded_table_is_capped(self):
        # no-max table: a huge grow must answer -1, not allocate
        body = (b"\xd0\x70" + i32c(0x10000000) + FC(15, uleb(0)) + END)
        m = self.table_module(body)
        assert instantiate(m).invoke("run", []) == [(1 << 32) - 1]

    def test_call_null_slot_traps(self):
        m = self.table_module(i32c(7) + self.CALL_IND + END)
        with pytest.raises(Trap, match="undefined table element"):
            instantiate(m).invoke("run", [])

    def test_active_elem_oob_is_error(self):
        m = self.table_module(i32c(0) + self.CALL_IND + END,
                              elem=vec([self.elem_active(7, [0, 1])]),
                              table=(8, None))
        with pytest.raises(WasmError, match="elem segment"):
            instantiate(m)


class TestTruncSat:
    def run1(self, body, params=(), args=()):
        inst = instantiate(simple_module(list(params), [I32], body))
        return inst.invoke("run", list(args))[0]

    def test_i32_trunc_sat_f64_s(self):
        assert self.run1(f64c(3.9) + FC(2) + END) == 3
        assert self.run1(f64c(-3.9) + FC(2) + END) == (1 << 32) - 3
        assert self.run1(f64c(float("nan")) + FC(2) + END) == 0
        assert self.run1(f64c(1e20) + FC(2) + END) == 0x7FFFFFFF
        assert self.run1(f64c(-1e20) + FC(2) + END) == 0x80000000

    def test_i32_trunc_sat_f64_u(self):
        assert self.run1(f64c(3.9) + FC(3) + END) == 3
        assert self.run1(f64c(-3.9) + FC(3) + END) == 0
        assert self.run1(f64c(1e20) + FC(3) + END) == 0xFFFFFFFF

    def test_i64_trunc_sat_f64(self):
        body64 = f64c(-1e300) + b"\xfc\x06" + END   # i64.trunc_sat_f64_s
        inst = instantiate(simple_module([], [0x7E], body64))
        assert inst.invoke("run", []) == [1 << 63]   # saturated at min


class TestMultiValue:
    """wasm multi-value: multi-result functions, type-index block
    signatures (params enter on the stack), and branches to a loop
    carrying its params back to the top."""

    def test_two_result_function(self):
        wasm = simple_module([], [0x7F, 0x7F], i32c(1) + i32c(2) + END)
        inst = instantiate(wasm, {})
        assert inst.invoke("run", []) == [1, 2]

    def test_multi_result_call_site(self):
        # f0: () -> (i32, i32); run: () -> i32 calls f0 and adds
        wasm = module([
            section(1, vec([functype([], [0x7F, 0x7F]),
                            functype([], [0x7F])])),
            section(3, vec([uleb(0), uleb(1)])),
            section(7, vec([name("run") + b"\x00" + uleb(1)])),
            section(10, vec([
                code_entry([], i32c(20) + i32c(22) + END),
                code_entry([], CALL(0) + b"\x6a" + END),   # i32.add
            ])),
        ])
        inst = instantiate(wasm, {})
        assert inst.invoke("run", []) == [42]

    def test_block_with_params_via_type_index(self):
        # type1: (i32, i32) -> (i32); block consumes the two pushed
        # operands as params and yields their sum
        wasm = module([
            section(1, vec([functype([], [0x7F]),
                            functype([0x7F, 0x7F], [0x7F])])),
            section(3, vec([uleb(0)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry(
                [],
                i32c(3) + i32c(4)
                + b"\x02" + uleb(1)          # block (type 1)
                + b"\x6a"                    # i32.add
                + END                        # end block
                + END)])),
        ])
        inst = instantiate(wasm, {})
        assert inst.invoke("run", []) == [7]

    def test_loop_params_carried_by_branch(self):
        # fib via a (i32,i32)->(i32,i32) loop: state (a, b) lives ON
        # THE STACK; br 0 carries both values back to the loop top,
        # br 2 exits through the enclosing block with both results.
        #   locals: 0 = n (param), 1..2 = scratch
        body = (
            i32c(0) + i32c(1)                 # a=0 b=1
            + b"\x02" + uleb(2)               # block (type 2: ()->(i32,i32))
            + b"\x03" + uleb(1)               # loop  (type 1: (i32,i32)->same)
            + LOCAL_SET(2) + LOCAL_SET(1)     # b->l2, a->l1
            + LOCAL_GET(0) + b"\x45"          # i32.eqz
            + b"\x04\x40"                     # if (empty)
            + LOCAL_GET(1) + LOCAL_GET(2)
            + b"\x0c" + uleb(2)               # br 2 -> block, carries (a,b)
            + END                             # end if
            + LOCAL_GET(0) + i32c(1) + b"\x6b" + LOCAL_SET(0)  # n--
            + LOCAL_GET(2)                    # b
            + LOCAL_GET(1) + LOCAL_GET(2) + b"\x6a"            # a+b
            + b"\x0c" + uleb(0)               # br 0 -> loop top with (b,a+b)
            + END                             # end loop
            + END                             # end block
            + b"\x1a"                         # drop b: leave a = fib(n)
            + END)
        wasm = module([
            section(1, vec([functype([0x7F], [0x7F]),
                            functype([0x7F, 0x7F], [0x7F, 0x7F]),
                            functype([], [0x7F, 0x7F])])),
            section(3, vec([uleb(0)])),
            section(7, vec([name("run") + b"\x00" + uleb(0)])),
            section(10, vec([code_entry([(2, 0x7F)], body)])),
        ])
        inst = instantiate(wasm, {})
        assert inst.invoke("run", [10]) == [55]
        assert inst.invoke("run", [0]) == [0]
        assert inst.invoke("run", [1]) == [1]

    def test_bad_blocktype_index_rejected(self):
        wasm = simple_module([], [], b"\x02" + uleb(9) + END + END)
        with pytest.raises(WasmError, match="out of range"):
            instantiate(wasm, {})
