#!/bin/sh
# CLI workflow regression — the reference's splinterctl_tests.sh analog
# (SURVEY.md §4: "shell script exercising init/set/get/head/list/type/
# unset/config/export/bump/append/uuid as workflow UX tests, explicitly
# not re-testing the library").  Exercises the one-shot CLI the way an
# operator would.  Exit 0 = pass.
set -eu

REPO=$(cd "$(dirname "$0")/.." && pwd)
STORE="/spt-clireg-$$"
PYTHON="${PYTHON:-python3}"
CLI="$PYTHON -m libsplinter_tpu.cli --store $STORE"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS=cpu
FAILED=0
N=0

check() {  # check NAME EXPECTED ACTUAL
    N=$((N + 1))
    if [ "$2" = "$3" ]; then
        echo "ok $N - $1"
    else
        echo "not ok $N - $1: expected [$2] got [$3]"
        FAILED=1
    fi
}

fail() { N=$((N + 1)); echo "not ok $N - $1"; FAILED=1; }
pass() { N=$((N + 1)); echo "ok $N - $1"; }

cleanup() { rm -f "/dev/shm$STORE"; }
trap cleanup EXIT

# --- init / set / get ---------------------------------------------------
$CLI init 64 512 8 >/dev/null
check "set+get round trip" "hello world" "$($CLI set greet hello world && $CLI get greet)"

# --- append -------------------------------------------------------------
$CLI append greet ", again" >/dev/null
check "append grows value" "hello world, again" "$($CLI get greet)"

# --- type / math --------------------------------------------------------
$CLI set counter 41 >/dev/null
$CLI type counter BIGUINT >/dev/null
check "type readback" "BIGUINT" "$($CLI type counter)"
check "math inc" "42" "$($CLI math counter inc)"
check "math add" "52" "$($CLI math counter add 10)"

# --- list ---------------------------------------------------------------
check "list shows both keys" "counter
greet" "$($CLI list | sort)"

# --- head ---------------------------------------------------------------
$CLI head greet | grep -q "^key " && pass "head dumps metadata" || fail "head output"

# --- label / bump -------------------------------------------------------
$CLI label greet +0x40 >/dev/null
check "label readback" "0x0000000000000040" "$($CLI label greet)"
$CLI bump greet >/dev/null && pass "bump" || fail "bump"

# --- export -------------------------------------------------------------
$CLI type greet VARTEXT >/dev/null
EXPORT=$($CLI export)
echo "$EXPORT" | grep -q '"key": "greet"' && pass "export contains greet" || fail "export contains greet"
echo "$EXPORT" | grep -q '"value": "hello world, again"' && pass "export inlines VARTEXT value" || fail "export inlines VARTEXT value"
check "export count" "2" "$(echo "$EXPORT" | python -c 'import json,sys; print(json.load(sys.stdin)["count"])')"

# --- uuid ---------------------------------------------------------------
$CLI uuid ukey >/dev/null
check "uuid length" "36" "$($CLI get ukey | tr -d '\n' | wc -c | tr -d ' ')"

# --- config -------------------------------------------------------------
$CLI config user 0x3 >/dev/null
$CLI config | grep -q "user flags   0x3" && pass "config user flags" || fail "config dump"
$CLI config mop 2 >/dev/null
$CLI config | grep -q "mop          2" && pass "config mop" || fail "config mop"

# --- orders (tandem) ----------------------------------------------------
$CLI set doc part0 >/dev/null
$CLI set doc.1 part1 >/dev/null
$CLI set doc.2 part2 >/dev/null
check "orders count" "doc: 3 orders" "$($CLI orders doc 2>/dev/null | head -1)"

# --- unset --------------------------------------------------------------
$CLI unset greet >/dev/null
if $CLI get greet >/dev/null 2>&1; then fail "unset removed key"; else pass "unset removed key"; fi

# --- watch: continuous loop + Ctrl-] abort (interactive, r2 #6) ---------
$CLI set wkey v0 >/dev/null
WATCH_OUT=$(mktemp)
# drive the interactive loop through a pipe: two writes must stream as
# size:value lines, then the Ctrl-] byte (0x1d) must end the loop
{
    sleep 0.4; $CLI set wkey alpha >/dev/null
    sleep 0.4; $CLI set wkey bravoo >/dev/null
    sleep 0.4; printf '\035'
} | $CLI watch wkey >"$WATCH_OUT" 2>/dev/null &
WATCH_PID=$!
if wait $WATCH_PID; then
    grep -q "^5:alpha$" "$WATCH_OUT" && pass "watch streams first change" \
        || fail "watch missed first change: $(cat "$WATCH_OUT")"
    grep -q "^6:bravoo$" "$WATCH_OUT" && pass "watch streams second change" \
        || fail "watch missed second change: $(cat "$WATCH_OUT")"
else
    fail "watch did not exit 0 on Ctrl-]"
fi
rm -f "$WATCH_OUT"

# --- watch: oneshot timeout --------------------------------------------
check "watch oneshot timeout" "timeout" "$($CLI watch wkey 60)"

# --- one-shot error discipline -----------------------------------------
if $CLI get nonexistent >/dev/null 2>&1; then
    fail "missing key must exit nonzero"
else
    pass "missing key exits nonzero"
fi

echo "cli regression: $N checks, FAILED=$FAILED"
exit $FAILED
