"""Resident device loop + K-deep dispatch overlap (engine/resident.py;
`make dispatch-check` runs this file + the depth-amortization smoke).

The PR-7 contract: the hot lanes stop paying one runtime dispatch per
drain, and BOTH mechanisms are byte-exact against the per-call paths —
  - embed vectors: resident ring vs per-call encode (fixed seed);
  - search results: K-deep select/commit vs fetch-in-dispatch-order;
  - decode tokens: K-deep chunk window vs the sync chunk cadence;
  - staged-lane refreshes: ring scatter vs per-chunk scatter —
plus compile-count pinning (ring occupancy is an OPERAND: no drain
geometry may recompile the resident program), the heartbeat gauges
(`ring_occupancy`, `inflight_depth`, `resident_iterations`), and the
SPTPU_FAULT sites for a ring stalled or crashed mid-dispatch.
"""
from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

import libsplinter_tpu as sp
from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.resident import (CallbackWindow,
                                             InflightWindow, RingResult,
                                             pending_ready)
from libsplinter_tpu.models import default_tokenizer
from libsplinter_tpu.models.encoder import EmbeddingModel, EncoderConfig


class FakeFuture:
    def __init__(self, tag, *, ready):
        self.tag = tag
        self.ready = ready

    def is_ready(self):
        return self.ready


# --------------------------------------------------- InflightWindow

class TestInflightWindow:
    def test_pending_ready_contract(self):
        assert pending_ready(None)
        assert pending_ready(np.zeros(3))
        assert pending_ready(b"host bytes")
        assert pending_ready((np.zeros(2), None))
        assert pending_ready(FakeFuture(0, ready=True))
        assert not pending_ready(FakeFuture(0, ready=False))
        assert not pending_ready((FakeFuture(0, ready=True),
                                  FakeFuture(1, ready=False)))

    def test_completion_order_beats_dispatch_order(self):
        done = []
        win = CallbackWindow(4, lambda p, pend, ready: done.append(p))
        slow = FakeFuture(1, ready=False)
        fast = FakeFuture(2, ready=True)
        win.push(1, slow)
        win.push(2, fast)              # finished first: resolves first
        assert done == [2]
        slow.ready = True
        assert win.drain_ready() == 1
        assert done == [2, 1]
        assert win.ready_resolves == 2
        assert win.blocking_resolves == 0

    def test_depth_bound_forces_oldest(self):
        done = []
        win = CallbackWindow(1, lambda p, pend, ready: done.append(
            (p, ready)))
        a, b, c = (FakeFuture(i, ready=False) for i in range(3))
        win.push("a", a)
        assert done == []              # within depth: nothing forced
        win.push("b", b)               # depth exceeded: oldest forced
        assert done == [("a", False)]
        win.push("c", c)
        assert done == [("a", False), ("b", False)]
        win.flush()
        assert [p for p, _ in done] == ["a", "b", "c"]
        assert win.inflight_peak == 2
        assert win.blocking_resolves == 3

    def test_flush_takes_ready_first(self):
        done = []
        win = CallbackWindow(4, lambda p, pend, ready: done.append(p))
        win.push_entry(("a", FakeFuture(0, ready=False)))
        win.push_entry(("b", FakeFuture(1, ready=True)))
        win.flush()
        assert done == ["b", "a"]

    def test_base_class_is_abstract(self):
        win = InflightWindow(2)
        with pytest.raises(NotImplementedError):
            win.push_entry(("x", None))


# ------------------------------------------------ resident ring (model)

@pytest.fixture(scope="module")
def ring_model():
    cfg = EncoderConfig.tiny(out_dim=32)
    return EmbeddingModel(cfg, buckets=(16, 32))


class TestEncoderRing:
    def test_ring_matches_per_call_byte_exact(self, ring_model):
        m = ring_model
        rng = np.random.default_rng(3)
        depth, cap, b = 4, 8, 16
        ids = rng.integers(0, m.cfg.vocab_size,
                           (depth, cap, b)).astype(np.int32)
        lens = rng.integers(1, b + 1, (depth, cap)).astype(np.int32)
        per = [m.encode_ids_async(ids[i], lens[i]).materialize()
               for i in range(depth)]
        ring = m.encode_ring_async(ids, lens, depth)
        for i in range(depth):
            got = ring.slot(i, cap).materialize()
            np.testing.assert_array_equal(got, per[i])

    def test_occupancy_is_an_operand_not_a_shape(self, ring_model):
        """Every occupancy 1..depth reuses ONE compiled program — a
        drain's ring fill level must never jit on the wake path."""
        m = ring_model
        depth, cap, b = 4, 8, 16
        ids = np.ones((depth, cap, b), np.int32)
        lens = np.full((depth, cap), b, np.int32)
        m.encode_ring_async(ids, lens, depth).materialize_host()
        c0 = m.compile_count()
        for occ in (1, 2, 3, 4):
            m.encode_ring_async(ids, lens, occ).materialize_host()
        assert m.compile_count() == c0

    def test_out_buffer_pool_recycles(self, ring_model):
        m = ring_model
        depth, cap, b = 4, 8, 16
        ids = np.ones((depth, cap, b), np.int32)
        lens = np.full((depth, cap), b, np.int32)
        r1 = m.encode_ring_async(ids, lens, 2)
        pool = m._ring_pool[(depth, cap)]
        held = len(pool)
        r1.materialize_host()          # host copy landed: buffer back
        assert len(pool) == held + 1
        r2 = m.encode_ring_async(ids, lens, 2)   # consumes (donates) it
        assert len(pool) == held
        r2.materialize_host()

    def test_ring_slot_wire_upcast_matches_per_call(self):
        """int8-wire rings must convert slot views exactly like
        PendingEmbeddings (the shared _wire_to_f32)."""
        cfg = EncoderConfig.tiny(out_dim=32)
        m8 = EmbeddingModel(cfg, buckets=(16,), fetch_dtype="int8")
        rng = np.random.default_rng(5)
        ids = rng.integers(0, cfg.vocab_size, (2, 4, 16)).astype(np.int32)
        lens = rng.integers(1, 17, (2, 4)).astype(np.int32)
        per = [m8.encode_ids_async(ids[i], lens[i]).materialize()
               for i in range(2)]
        ring = m8.encode_ring_async(ids, lens, 2)
        for i in range(2):
            np.testing.assert_array_equal(
                ring.slot(i, 4).materialize(), per[i])

    def test_failed_fetch_caches_error_and_skips_pool(self):
        """A ring whose device fetch fails must poison NEITHER the
        sibling slots' error reporting (the real error re-raises, no
        None deref) NOR the donation pool (the buffer is dropped)."""
        class BoomArray:
            def is_ready(self):
                return True

            def __array__(self, *a, **kw):
                raise RuntimeError("device fell over")

        pool: list = []
        ring = RingResult(BoomArray(), 2, release=pool.append)
        with pytest.raises(RuntimeError, match="device fell over"):
            ring.slot(0, 1).materialize()
        with pytest.raises(RuntimeError, match="device fell over"):
            ring.slot(1, 1).materialize()     # cached, not a None deref
        assert ring.is_ready()                # forcing will not block
        assert pool == []                     # poisoned buffer dropped

        fell_back = []
        ring2 = RingResult(BoomArray(), 2, release=pool.append,
                           retry=lambda i, n: fell_back.append(i)
                           or np.zeros((n, 4), np.float32))
        out = ring2.slot(1, 3).materialize()
        assert out.shape == (3, 4)
        assert fell_back == [1]               # per-slot fallback armed

    def test_n_valid_bounds_checked(self, ring_model):
        ids = np.ones((2, 4, 16), np.int32)
        lens = np.full((2, 4), 16, np.int32)
        with pytest.raises(ValueError):
            ring_model.encode_ring_async(ids, lens, 0)
        with pytest.raises(ValueError):
            ring_model.encode_ring_async(ids, lens, 3)


# -------------------------------------------------- embedder lane

def _arm_embed(store, n, word="text"):
    for i in range(n):
        store.set(f"k{i}", f"{word} number {i} " * (1 + i % 4))
        store.set_type(f"k{i}", sp.T_VARTEXT)
        store.label_or(f"k{i}", P.LBL_EMBED_REQ)
        store.bump(f"k{i}")


def _embed_run(tmp_path, tag, n=30, **emb_kw):
    from libsplinter_tpu.engine.embedder import Embedder

    name = f"/spt-res-{tag}-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=1024, vec_dim=32)
    try:
        cfg = EncoderConfig.tiny(out_dim=32)
        model = EmbeddingModel(cfg, buckets=(16, 32))
        emb = Embedder(st, model=model,
                       tokenizer=default_tokenizer(cfg.vocab_size),
                       max_ctx=128, **emb_kw)
        emb.attach()
        _arm_embed(st, n)
        served = emb.run_once()
        vecs = np.stack([st.vec_get(f"k{i}") for i in range(n)])
        return served, vecs, emb
    finally:
        st.close()
        Store.unlink(name)


class TestEmbedderRing:
    def test_ring_vectors_byte_identical_to_per_call(self, tmp_path):
        """THE parity bar: resident-ring drains commit byte-identical
        vectors to per-call drains at a fixed weight seed."""
        n0, v0, e0 = _embed_run(tmp_path, "percall", batch_cap=4,
                                ring_depth=0)
        n1, v1, e1 = _embed_run(tmp_path, "ring", batch_cap=4,
                                ring_depth=4)
        assert n0 == n1 == 30
        assert e0.stats.ring_dispatches == 0
        assert e1.stats.ring_dispatches >= 1
        assert e1.stats.resident_iterations >= 2
        assert e1.stats.ring_occupancy_peak >= 2
        np.testing.assert_array_equal(v0, v1)

    def test_ring_disengages_below_two_full_batches(self, tmp_path):
        """Tiny drains (the latency-probe lane) must never pay ring
        assembly: one batch -> the per-call path."""
        n, _, emb = _embed_run(tmp_path, "small", n=3, batch_cap=4,
                               ring_depth=4)
        assert n == 3
        assert emb.stats.ring_dispatches == 0

    def test_warmup_ring_pins_compile_count(self, tmp_path):
        """After warmup_ring, drains at ANY ring occupancy (different
        drain sizes across join/finish cycles) never recompile."""
        from libsplinter_tpu.engine.embedder import Embedder

        name = f"/spt-res-warm-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=256, max_val=1024, vec_dim=32)
        try:
            cfg = EncoderConfig.tiny(out_dim=32)
            model = EmbeddingModel(cfg, buckets=(16, 32))
            emb = Embedder(st, model=model,
                           tokenizer=default_tokenizer(cfg.vocab_size),
                           max_ctx=128, batch_cap=4, ring_depth=4)
            emb.attach()
            model.warmup(batch_sizes=(1, 2, 4))
            model.warmup_ring(emb.ring_depth, emb.batch_cap)
            c0 = model.compile_count()
            assert c0 > 0
            for n in (9, 17, 30):      # different ring occupancies
                _arm_embed(st, n)
                assert emb.run_once() == n
                # finish cycle: re-arm the same keys next round
            assert model.compile_count() == c0, \
                "resident program recompiled across drain cycles"
            assert emb.stats.ring_dispatches >= 2
        finally:
            st.close()
            Store.unlink(name)

    def test_heartbeat_carries_ring_gauges(self, store):
        from libsplinter_tpu.engine.embedder import Embedder

        emb = Embedder(store, encoder_fn=lambda ts: np.zeros(
            (len(ts), store.vec_dim), np.float32), max_ctx=64,
            ring_depth=4, inflight_depth=3)
        emb.attach()
        emb.publish_stats()
        snap = json.loads(store.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        disp = snap["dispatch"]
        for field in ("ring_dispatches", "resident_iterations",
                      "ring_occupancy", "ring_occupancy_peak",
                      "ring_faults", "ring_depth", "inflight_depth"):
            assert field in disp, field
        assert disp["ring_depth"] == 4
        assert disp["inflight_depth"] == 3


# -------------------------------------------------- searcher lane

def _search_round(store, sr, keys, qs):
    for key, q in zip(keys, qs):
        store.set(key, json.dumps({"k": 5}))
        store.vec_set(key, q)
        store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
        store.bump(key)
    served = sr.run_once()
    out = {}
    for key in keys:
        out[key] = json.loads(store.get(
            P.search_result_key(store.find_index(key))).rstrip(b"\0"))
    return served, out


class TestSearcherOverlap:
    def _fill(self, store, n=64, seed=11):
        rng = np.random.default_rng(seed)
        vecs = rng.normal(size=(n, store.vec_dim)).astype(np.float32)
        for i in range(n):
            store.set(f"doc/{i}", f"text {i}")
            store.vec_set(f"doc/{i}", vecs[i])
        return rng

    def test_overlap_results_identical_to_in_order(self, store):
        """Search results must not depend on inflight_depth — the
        window only reorders HOST work, never device math."""
        from libsplinter_tpu.engine.searcher import Searcher

        rng = self._fill(store)
        qs = rng.normal(size=(24, store.vec_dim)).astype(np.float32)
        keys = [f"__sqtmp_{1000 + i}" for i in range(24)]
        results = {}
        for depth in (1, 4):
            sr = Searcher(store, inflight_depth=depth)
            sr.attach()
            served, out = _search_round(store, sr, keys, qs)
            assert served == 24
            results[depth] = out
            if depth > 1:
                assert sr.stats.inflight_peak >= 1
            for key in keys:
                store.unset(P.search_result_key(store.find_index(key)))
        # strip per-commit wall timestamps + the round's slot epochs
        # (each round rewrites the request slots) before comparing
        for out in results.values():
            for rec in out.values():
                rec.pop("ts", None)
                rec.pop("e", None)
        assert results[1] == results[4]

    def test_window_bounds_inflight(self, store):
        """Many QB chunks in one drain: the window never holds more
        than inflight_depth un-awaited batch dispatches."""
        from libsplinter_tpu.engine.searcher import Searcher

        rng = self._fill(store)
        # 3 bloom groups x 1 chunk each -> 3 dispatches in one drain
        sr = Searcher(store, inflight_depth=2)
        sr.attach()
        keys, qs = [], []
        for g, bloom in enumerate((0, P.LBL_CHUNK, P.LBL_META)):
            for i in range(4):
                key = f"__sqtmp_{2000 + g * 8 + i}"
                store.set(key, json.dumps({"k": 3, "bloom": bloom}))
                store.vec_set(key, rng.normal(
                    size=store.vec_dim).astype(np.float32))
                store.label_or(key, P.LBL_SEARCH_REQ)
                store.bump(key)
                keys.append(key)
        for i in range(8):             # give the bloom groups members
            store.label_or(f"doc/{i}", P.LBL_CHUNK)
            store.label_or(f"doc/{i + 8}", P.LBL_META)
        served = sr.run_once()
        assert served == len(keys)
        assert sr.stats.dispatches >= 3
        # peak counts the moment AFTER a push, before the overflow
        # resolve — depth+1 max (CommitPipeline's pinned semantics)
        assert 1 <= sr.stats.inflight_peak <= 3
        assert (sr.stats.ready_selects
                + sr.stats.blocking_selects) == sr.stats.dispatches

    def test_heartbeat_carries_inflight_gauge(self, store):
        from libsplinter_tpu.engine.searcher import Searcher

        sr = Searcher(store, inflight_depth=3)
        sr.attach()
        sr.publish_stats()
        snap = json.loads(store.get(P.KEY_SEARCH_STATS).rstrip(b"\0"))
        assert snap["inflight_depth"] == 3
        assert "inflight_peak" in snap
        # the staged-lane ring counters ride the lane section
        assert "ring_dispatches" in snap["lane"]


# -------------------------------------------------- completer lane

class TestCompleterOverlap:
    def _serve(self, tmp_path, tag, depth, n_req=3):
        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        name = f"/spt-res-dec-{tag}-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
        try:
            model = CompletionModel(
                DecoderConfig.tiny(dtype=jnp.float32), buckets=(32,),
                temp=0.0, seed=1)
            comp = Completer(st, model=model, max_new_tokens=10,
                             flush_tokens=4, template="none",
                             batch_cap=4, page_size=16,
                             inflight_depth=depth)
            comp.attach()
            for i in range(n_req):
                st.set(f"q/{i}", f"say {i} things")
                st.label_or(f"q/{i}", P.LBL_INFER_REQ)
                st.bump(f"q/{i}")
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
                daemon=True)
            th.start()
            deadline = time.time() + 50
            keys = [f"q/{i}" for i in range(n_req)]
            while time.time() < deadline:
                if all(st.labels(k) & P.LBL_READY for k in keys):
                    break
                time.sleep(0.05)
            comp.stop()
            th.join(timeout=10)
            assert all(st.labels(k) & P.LBL_READY for k in keys), \
                comp.stats
            out = b"|".join(st.get(k).rstrip(b"\0") for k in keys)
            assert comp._paged_cache.used_pages == 0, "pages leaked"
            return out, comp
        finally:
            st.close()
            Store.unlink(name)

    def test_k_deep_decode_byte_identical_to_sync(self, tmp_path):
        """THE decode parity bar: greedy completions through the
        K-deep chunk window == the collect-every-chunk cadence."""
        sync_out, sync_comp = self._serve(tmp_path, "sync", depth=1)
        deep_out, deep_comp = self._serve(tmp_path, "deep", depth=3)
        assert sync_out == deep_out
        assert deep_comp.stats.inflight_peak >= 2
        assert sync_comp.stats.inflight_peak <= 1

    def test_heartbeat_carries_inflight_gauge(self, store):
        from libsplinter_tpu.engine.completer import Completer

        comp = Completer(store, generate_fn=lambda p: iter([b"x"]),
                         template="none", inflight_depth=4)
        comp.attach()
        comp.publish_stats()
        snap = json.loads(store.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert snap["inflight_depth"] == 4
        assert "inflight_peak" in snap


# -------------------------------------------------- metrics surface

@pytest.mark.obs
def test_metrics_exposition_renders_overlap_gauges(tmp_path):
    """The ISSUE-7 obs satellite: `spt metrics` renders the ring /
    in-flight gauges as sptpu_<lane>_* so saturation of the overlap
    window is scrapeable in production."""
    import contextlib
    import io
    import os
    import uuid

    from libsplinter_tpu.engine.embedder import Embedder
    from libsplinter_tpu.engine.searcher import Searcher

    name = f"/spt-res-prom-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=4096, vec_dim=32)
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 32), np.float32), max_ctx=64, ring_depth=8)
        emb.attach()
        emb.publish_stats()
        sr = Searcher(st, inflight_depth=2)
        sr.attach()
        sr.publish_stats()

        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(name)
        try:
            fn, _, _ = COMMANDS["metrics"]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                fn(ses, [])
            out = buf.getvalue()
            for needle in ("sptpu_embedder_ring_depth 8",
                           "sptpu_embedder_ring_dispatches",
                           "sptpu_embedder_resident_iterations",
                           "sptpu_embedder_ring_occupancy",
                           "sptpu_embedder_inflight_depth",
                           "sptpu_searcher_inflight_depth 2",
                           "sptpu_searcher_inflight_peak",
                           "sptpu_searcher_lane_ring_dispatches"):
                assert needle in out, f"{needle} missing:\n{out[:2000]}"
        finally:
            ses.close()
    finally:
        st.close()
        Store.unlink(name)


# -------------------------------------------------- staged-lane ring

class TestStagedLaneRing:
    def test_ring_scatter_refresh_exact(self, store):
        """A refresh whose plan repeats buckets goes through the ring
        scatter and must land the exact same lane as per-chunk."""
        from libsplinter_tpu.ops.staged_lane import StagedLane

        rng = np.random.default_rng(9)
        n = 200
        v0 = rng.normal(size=(n, store.vec_dim)).astype(np.float32)
        for i in range(n):
            store.set(f"d/{i}", "x")
            store.vec_set(f"d/{i}", v0[i])
        idxs = np.array([store.find_index(f"d/{i}") for i in range(n)])

        lane = StagedLane(store)
        lane.refresh()
        v1 = v0 + 1.0
        for i in range(n):
            store.vec_set(f"d/{i}", v1[i])
        arr = np.asarray(lane.refresh())
        # 200 dirty -> plan [64, 64, 64, 64(tail)]: same-bucket chunks
        # coalesce into ring dispatches
        assert lane.ring_dispatches >= 1
        assert lane.ring_chunks >= 2
        for i in range(n):
            np.testing.assert_array_equal(arr[idxs[i]], v1[i])
        norms = np.asarray(lane.norms)[idxs]
        np.testing.assert_allclose(norms, np.linalg.norm(v1, axis=1),
                                   rtol=1e-6)

    def test_buffered_chunks_lost_mid_refresh_stay_dirty(
            self, store, monkeypatch):
        """A refresh that dies with chunks still buffered (or whose
        scatter raises) must NOT have marked those rows staged — the
        next refresh re-stages them instead of serving stale rows
        forever."""
        from libsplinter_tpu.ops import staged_lane as sl_mod
        from libsplinter_tpu.ops.staged_lane import StagedLane

        rng = np.random.default_rng(13)
        n = 200
        v0 = rng.normal(size=(n, store.vec_dim)).astype(np.float32)
        for i in range(n):
            store.set(f"d/{i}", "x")
            store.vec_set(f"d/{i}", v0[i])
        lane = StagedLane(store)
        lane.refresh()
        v1 = v0 + 1.0
        for i in range(n):
            store.vec_set(f"d/{i}", v1[i])

        import libsplinter_tpu.ops.similarity as sim
        real = sim.scatter_rows_with_norms_ring
        calls = {"n": 0}

        def boom(*a, **kw):
            calls["n"] += 1
            raise RuntimeError("scatter died")

        monkeypatch.setattr(sim, "scatter_rows_with_norms_ring", boom)
        with pytest.raises(RuntimeError):
            lane.refresh()
        assert calls["n"] == 1
        monkeypatch.setattr(sim, "scatter_rows_with_norms_ring", real)
        arr = np.asarray(lane.refresh())      # everything re-staged
        idxs = np.array([store.find_index(f"d/{i}") for i in range(n)])
        for i in range(n):
            np.testing.assert_array_equal(arr[idxs[i]], v1[i])

    def test_ring_disabled_matches(self, store):
        from libsplinter_tpu.ops.staged_lane import StagedLane

        rng = np.random.default_rng(10)
        n = 200
        for i in range(n):
            store.set(f"d/{i}", "x")
            store.vec_set(
                f"d/{i}",
                rng.normal(size=store.vec_dim).astype(np.float32))
        lane = StagedLane(store)
        lane.ring_depth = 1
        lane.refresh()
        v1 = rng.normal(size=(n, store.vec_dim)).astype(np.float32)
        for i in range(n):
            store.vec_set(f"d/{i}", v1[i])
        arr = np.asarray(lane.refresh())
        assert lane.ring_dispatches == 0
        idxs = np.array([store.find_index(f"d/{i}") for i in range(n)])
        for i in range(0, n, 17):
            np.testing.assert_array_equal(arr[idxs[i]], v1[i])


# -------------------------------------------------- fault sites

class TestRingFaults:
    def test_ring_dispatch_raise_degrades_to_per_call(self, tmp_path):
        """An injected failure at resident.ring_dispatch costs only
        the ring: its chunks fall back to the per-call programs and
        every request still embeds, byte-identically."""
        from libsplinter_tpu.utils import faults

        n0, v0, _ = _embed_run(tmp_path, "flt-ref", batch_cap=4,
                               ring_depth=0)
        faults.arm("resident.ring_dispatch:raise@1")
        try:
            n, vecs, emb = _embed_run(tmp_path, "flt", batch_cap=4,
                                      ring_depth=4)
        finally:
            faults.disarm()
        assert n == n0 == 30
        assert emb.stats.ring_faults >= 1
        assert emb.stats.drain_faults == 0
        np.testing.assert_array_equal(vecs, v0)

    def test_ring_collect_raise_falls_back_per_slot(self, tmp_path):
        """A collect-time failure (where async dispatch surfaces
        device errors) re-encodes the affected slot on the per-call
        programs: no batch fails, no cap degrades, vectors stay
        byte-identical."""
        from libsplinter_tpu.utils import faults

        n0, v0, _ = _embed_run(tmp_path, "col-ref", batch_cap=4,
                               ring_depth=0)
        faults.arm("resident.ring_collect:raise@1")
        try:
            n, vecs, emb = _embed_run(tmp_path, "col", batch_cap=4,
                                      ring_depth=4)
        finally:
            faults.disarm()
        assert n == n0 == 30
        assert emb.stats.ring_faults >= 1
        assert emb.stats.batch_faults == 0    # no cap degradation
        np.testing.assert_array_equal(vecs, v0)

    def test_ring_collect_stall_absorbed(self, tmp_path):
        """A stall mid-collect (device hiccup) slows the drain but
        loses nothing."""
        from libsplinter_tpu.utils import faults

        faults.arm("resident.ring_collect:stall50@1")
        try:
            n, vecs, emb = _embed_run(tmp_path, "stall", batch_cap=4,
                                      ring_depth=4)
        finally:
            faults.disarm()
        assert n == 30
        assert emb.stats.ring_dispatches >= 1

    @pytest.mark.chaos
    def test_ring_dispatch_crash_recovers(self, tmp_path):
        """Chaos: a child daemon crashed INSIDE a resident-ring drain
        (os._exit mid-dispatch) strands nothing — a restarted daemon
        converges every request."""
        import os
        import subprocess
        import sys

        from libsplinter_tpu.utils.faults import CRASH_EXIT_CODE

        name = f"/spt-res-crash-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=256, max_val=1024, vec_dim=32)
        try:
            _arm_embed(st, 20)
            child = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "chaos_child.py")
            env = dict(os.environ)
            env["SPTPU_FAULT"] = "resident.ring_dispatch:crash@1"
            env["JAX_PLATFORMS"] = "cpu"
            out = subprocess.run(
                [sys.executable, child, "embedder_ring", name],
                env=env, capture_output=True, text=True, timeout=300)
            assert out.returncode == CRASH_EXIT_CODE, out.stderr[-800:]

            from libsplinter_tpu.engine.embedder import Embedder
            cfg = EncoderConfig.tiny(out_dim=32)
            model = EmbeddingModel(cfg, buckets=(16, 32))
            emb = Embedder(st, model=model,
                           tokenizer=default_tokenizer(cfg.vocab_size),
                           max_ctx=128, batch_cap=4, ring_depth=4)
            emb.attach()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                emb.run_once()
                if not st.enumerate_indices(P.LBL_EMBED_REQ):
                    break
            assert not st.enumerate_indices(P.LBL_EMBED_REQ)
            for i in range(20):
                assert np.abs(st.vec_get(f"k{i}")).max() > 0, i
            assert emb.stats.ring_dispatches >= 1
        finally:
            st.close()
            Store.unlink(name)

    @pytest.mark.chaos
    @pytest.mark.slow
    def test_supervisor_restarts_lane_wedged_in_ring(self, tmp_path,
                                                     monkeypatch):
        """PR-4 supervisor acceptance for PR 7: an embedder lane
        WEDGED inside a resident program (45 s stall at the ring
        collect — a hung device, not a crash) goes heartbeat-stale,
        the supervisor SIGKILLs + restarts it (fault stripped from
        generation 2), and every pending request still embeds — no
        stranded rows."""
        import os
        import uuid

        from libsplinter_tpu.engine.supervisor import Supervisor

        name = f"/spt-res-sup-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        Store.unlink(name)
        st = Store.create(name, nslots=128, max_val=2048, vec_dim=16)
        try:
            monkeypatch.setenv("SPTPU_FAULT",
                               "resident.ring_collect:stall45000@1")
            monkeypatch.setenv("SPTPU_FORCE_CPU", "1")
            sup = Supervisor(
                name, lanes=("embedder",), store=st,
                lane_args={"embedder": ["--batch-cap", "2",
                                        "--ring-depth", "2",
                                        "--max-ctx", "64"]},
                backoff_base_ms=100, backoff_max_ms=2000,
                breaker_threshold=8, breaker_window_s=300,
                heartbeat_timeout_s=20, startup_grace_s=300,
                healthy_after_s=5)
            t = threading.Thread(target=sup.run,
                                 kwargs={"poll_interval_s": 0.2,
                                         "stop_after": 600.0})
            t.start()
            try:
                # wait for the lane's FIRST heartbeat so the hang
                # detector has a baseline, then submit the work the
                # armed stall will wedge
                deadline = time.monotonic() + 400
                while time.monotonic() < deadline:
                    if P.heartbeat_live(st, P.KEY_EMBED_STATS,
                                        max_age_s=30):
                        break
                    time.sleep(0.5)
                assert P.heartbeat_live(st, P.KEY_EMBED_STATS,
                                        max_age_s=30), "lane never up"
                _arm_embed(st, 8)
                deadline = time.monotonic() + 400
                while time.monotonic() < deadline:
                    if not st.enumerate_indices(P.LBL_EMBED_REQ):
                        break
                    time.sleep(0.5)
                assert not st.enumerate_indices(P.LBL_EMBED_REQ), \
                    sup.lanes["embedder"].snapshot()
                for i in range(8):
                    assert np.abs(st.vec_get(f"k{i}")).max() > 0, i
                ln = sup.lanes["embedder"]
                assert ln.restarts >= 1, \
                    "wedged lane was never restarted"
            finally:
                sup.stop()
                t.join()
                sup.shutdown()
        finally:
            st.close()
            Store.unlink(name)
