"""Pod-sharded paged decode (PR 8): the continuous-batching lane
tensor-parallel over the virtual 8-device CPU mesh.

The per-layer block pools shard on their KV-HEAD axis over `tp`
(parallel/serve.ShardedCompletionModel._pool_sharding), the ragged
paged-attention and flash-prefill kernels run under shard_map
(ops/paged_attention, ops/flash_attention), and the host-side page
scheduler is byte-identical to the single-chip pool — so sharded paged
serving must be TOKEN-EXACT with the single-chip paged path (and with
serial decode) at a fixed weight seed, including a mid-flight joiner
and pool-exhaustion backpressure.  `make pod-check` runs this file's
fast tier; the full sweep collects all of it.
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig
from libsplinter_tpu.parallel import ShardedCompletionModel, make_mesh
from libsplinter_tpu.utils import faults

CFG = DecoderConfig.tiny(dtype=jnp.float32)      # heads=4, kv_heads=2


@pytest.fixture(scope="module")
def pair():
    """(single-chip model, tp=2-sharded model) over the SAME params."""
    base = CompletionModel(CFG, buckets=(16, 32), temp=0.0, seed=1)
    mesh = make_mesh(dp=4, tp=2)
    tp = ShardedCompletionModel(CFG, mesh, params=base.params,
                                buckets=(16, 32), temp=0.0, seed=1)
    return base, tp


# ------------------------------------------------------- placement

def test_paged_supported_and_pool_sharded(pair):
    _, tp = pair
    assert tp.paged_supported is True
    cache = tp.init_paged(2, page=16)
    sh = cache.k_pools[0].sharding
    assert len(sh.device_set) == 8
    assert tuple(sh.spec) == (None, "tp", None, None)
    # distinct per-layer buffers (the programs donate the pools)
    assert cache.k_pools[0] is not cache.k_pools[1]


def test_meshless_custom_module_demotes_paged():
    """A custom module built WITHOUT the mesh cannot run the
    shard_map'd kernels — the instance (and only the instance) turns
    the paged lane off and dense serving still works."""
    from libsplinter_tpu.models.decoder import Decoder

    mesh = make_mesh(dp=4, tp=2)
    tp = ShardedCompletionModel(CFG, mesh, module=Decoder(CFG),
                                buckets=(16,), temp=0.0)
    assert tp.paged_supported is False
    assert ShardedCompletionModel.paged_supported is True


# ------------------------------------------- shard_map'd kernels

def test_paged_kernel_sharded_interpret_parity():
    """The Pallas ragged kernel under shard_map (interpret mode, the
    CPU stand-in for the Mosaic build) == the dense gathered-page
    reference, ragged lengths crossing page boundaries included."""
    from libsplinter_tpu.ops.paged_attention import (_paged_ref,
                                                     paged_attention)

    mesh = make_mesh(dp=4, tp=2)
    rng = np.random.default_rng(0)
    B, H, KH, D, page, nb, npg = 4, 4, 2, 8, 16, 9, 3
    q = rng.normal(size=(B, H, D)).astype(np.float32)
    kp = rng.normal(size=(nb, KH, page, D)).astype(np.float32)
    vp = rng.normal(size=(nb, KH, page, D)).astype(np.float32)
    tables = rng.integers(1, nb, size=(B, npg)).astype(np.int32)
    lengths = np.array([5, 17, 33, 48], np.int32)

    ref = np.asarray(_paged_ref(jnp.asarray(q), jnp.asarray(kp),
                                jnp.asarray(vp), jnp.asarray(tables),
                                jnp.asarray(lengths)))
    out = np.asarray(paged_attention(q, kp, vp, tables, lengths,
                                     interpret=True, mesh=mesh))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # the jnp per-shard fallback (serving path on CPU) agrees too
    out2 = np.asarray(paged_attention(q, kp, vp, tables, lengths,
                                      mesh=mesh))
    np.testing.assert_allclose(out2, ref, rtol=2e-5, atol=2e-5)


def test_flash_kernel_sharded_interpret_parity():
    """The causal flash-prefill kernel under shard_map (the
    flash_min_seq demotion lift): sharded interpret run == the shared
    jnp reference with GQA heads repeated."""
    from libsplinter_tpu.ops.flash_attention import (_causal_jnp,
                                                     causal_flash_attention)

    mesh = make_mesh(dp=4, tp=2)
    rng = np.random.default_rng(1)
    B, S, H, KH, D, T = 4, 8, 4, 2, 8, 16
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    kk = rng.normal(size=(B, T, KH, D)).astype(np.float32)
    vv = rng.normal(size=(B, T, KH, D)).astype(np.float32)
    start = np.array([0, 1, 2, 0], np.int32)
    rep = H // KH
    ref = np.asarray(_causal_jnp(
        jnp.asarray(q), jnp.repeat(jnp.asarray(kk), rep, 2),
        jnp.repeat(jnp.asarray(vv), rep, 2), jnp.int32(4),
        jnp.asarray(start)))
    out = np.asarray(causal_flash_attention(
        q, kk, vv, jnp.int32(4), start, block_q=4, interpret=True,
        mesh=mesh))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# ------------------------------------------------- token exactness

def _paged_greedy(m, prompt, n, batch=2, page=16):
    """Greedy tokens through the paged surface: prefill one row, then
    chunked paged decode; returns the token list."""
    cache = m.init_paged(batch, page=page)
    lg = m.paged_prefill_row(cache, prompt, 0)
    t0 = int(np.argmax(lg))
    toks = np.zeros((batch,), np.int32)
    toks[0] = t0
    blk = m.paged_decode_chunk(cache, toks, n)
    out = [t0] + [int(x) for x in blk[0]]
    cache.reset()
    return out


def test_sharded_paged_token_exact_vs_single_vs_serial(pair):
    """THE acceptance bar: sharded-paged == single-chip-paged ==
    serial greedy tokens at the fixed weight seed on the 8-device
    CPU mesh."""
    base, tp = pair
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    serial = list(base.generate_tokens(prompt, 9, chunk=8))
    base.reset()
    single = _paged_greedy(base, prompt, 8)
    sharded = _paged_greedy(tp, prompt, 8)
    assert single == sharded, (single, sharded)
    assert serial == sharded, (serial, sharded)


def test_midflight_joiner_token_exact(pair):
    """A row joining while its neighbour is mid-decode: both models
    must produce identical tokens for BOTH rows (the joiner's commit
    scatter lands in a kv-head-sharded pool)."""
    base, tp = pair

    def run(m):
        cache = m.init_paged(2, page=16)
        lg = m.paged_prefill_row(cache,
                                 np.array([3, 1, 4, 1, 5], np.int32), 0)
        t0 = int(np.argmax(lg))
        blk = m.paged_decode_chunk(cache, np.array([t0, 0], np.int32), 4)
        lg2 = m.paged_prefill_row(cache, np.array([2, 7, 1], np.int32),
                                  1)                 # joins mid-decode
        t1 = int(np.argmax(lg2))
        blk2 = m.paged_decode_chunk(
            cache, np.array([int(blk[0, -1]), t1], np.int32), 4)
        out = ([t0] + [int(x) for x in blk[0]] + [int(x) for x in blk2[0]],
               [t1] + [int(x) for x in blk2[1]])
        cache.reset()
        return out

    assert run(base) == run(tp)


def test_kdeep_async_carry_token_exact(pair):
    """The PR-7 K-deep chunk chain (device-side token carry) over the
    sharded pools: chained async chunks == the single-chip chain."""
    base, tp = pair

    def run(m):
        cache = m.init_paged(2, page=16)
        lg = m.paged_prefill_row(cache,
                                 np.array([5, 2, 9], np.int32), 0)
        toks = np.array([int(np.argmax(lg)), -1], np.int32)
        p1 = m.paged_decode_chunk_async(cache, toks, 4)
        p2 = m.paged_decode_chunk_async(
            cache, np.full((2,), -1, np.int32), 4, carry=p1.last)
        out = np.concatenate([p1.block(), p2.block()], axis=1)
        cache.reset()
        return out[0].tolist()

    assert run(base) == run(tp)


def test_warmup_pins_compile_count(pair):
    """A join/finish/join cycle after warmup_paged must not compile:
    the out_shardings pin keeps the jit signature stable across the
    fresh-pool -> commit-out -> chunk-out program chain."""
    _, tp = pair
    cache = tp.init_paged(4, page=16)
    tp.warmup_paged(cache, chunk=4, max_prompt=30)
    c0 = tp.compile_count()
    lg = tp.paged_prefill_row(cache, np.ones((7,), np.int32), 0)
    tp.sample(lg)
    tp.paged_decode_chunk(cache, np.array([1, 0, 0, 0], np.int32), 4)
    cache.free_row(0)
    tp.paged_prefill_row(cache, np.ones((20,), np.int32), 1)
    tp.paged_decode_chunk(cache, np.array([0, 1, 0, 0], np.int32), 4)
    assert tp.compile_count() == c0
    cache.reset()


# ------------------------------------------------- pool pressure

def test_pool_exhaustion_backpressure_sharded(pair):
    """All-or-nothing alloc on the sharded pool: a row the pool
    cannot cover allocates NOTHING (backpressure), prefill into an
    exhausted pool raises, and freeing the hog admits the waiter."""
    _, tp = pair
    # one full window of pages: the second row cannot fit
    cache = tp.init_paged(2, page=16, pool_pages=8)
    assert cache.ensure(0, CFG.max_len)
    assert cache.free_pages == 0
    assert not cache.ensure(1, 16)               # nothing allocated
    assert cache.tables[1].max() == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        tp.paged_prefill_row(cache, np.ones((8,), np.int32), 1)
    cache.free_row(0)
    assert cache.free_pages == 8
    lg = tp.paged_prefill_row(cache, np.ones((8,), np.int32), 1)
    assert lg.shape[-1] == CFG.vocab_size
    cache.reset()


# ------------------------------------------- the continuous lane

def _submit(st, key, prompt):
    st.set(key, prompt)
    st.label_or(key, P.LBL_INFER_REQ)
    st.bump(key)


def _await_ready(st, keys, timeout=75):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(st.labels(k) & P.LBL_READY for k in keys):
            return True
        time.sleep(0.05)
    return False


def _run_bg(comp, stop_after=90.0):
    th = threading.Thread(
        target=comp.run_continuous,
        kwargs=dict(idle_timeout_ms=20, stop_after=stop_after),
        daemon=True)
    th.start()
    time.sleep(0.2)
    return th


def test_continuous_sharded_byte_identical_vs_single(pair, tmp_path):
    """run_continuous through the sharded model == the single-chip
    model, byte for byte, with the daemon surface (labels, streaming
    appends, heartbeat) driving both unchanged."""
    base, tp = pair
    out = {}
    for tag, model in (("single", base), ("sharded", tp)):
        name = f"/spt-shpg-{tag}-{tmp_path.name[-8:]}"
        Store.unlink(name)
        st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
        try:
            comp = Completer(st, model=model, max_new_tokens=10,
                             flush_tokens=4, template="none",
                             batch_cap=4, page_size=16)
            comp.attach()
            for i in range(3):
                _submit(st, f"q/{i}", f"say {i} things")
            th = _run_bg(comp)
            assert _await_ready(st, [f"q/{i}" for i in range(3)]), \
                comp.stats
            comp.stop()
            th.join(timeout=5)
            out[tag] = b"|".join(
                st.get(f"q/{i}").rstrip(b"\0") for i in range(3))
            assert comp._paged_cache.used_pages == 0, "pages leaked"
        finally:
            st.close()
            Store.unlink(name)
    assert out["single"] == out["sharded"]


def test_heartbeat_and_metrics_shard_labels(pair, tmp_path):
    """Satellite: the sharded completer heartbeat carries the tp axis
    size and per-shard pool occupancy, and `spt metrics` renders
    sptpu_completer_pages_{free,used} with a shard label."""
    _, tp = pair
    name = f"/spt-shpm-{tmp_path.name[-8:]}"
    Store.unlink(name)
    st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
    try:
        comp = Completer(st, model=tp, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        comp._ensure_paged_cache()
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert snap["tp"] == 2
        # one key per tp position, MEASURED from the placed buffers
        # (a broken placement would collapse the key set)
        assert set(snap["pages_shard"]) == {"0", "1"}
        cache = comp._paged_cache
        expect_mb = round(
            cache.k_pools[0].nbytes / 2 * 2 * CFG.layers / 1e6, 3)
        for occ in snap["pages_shard"].values():
            assert occ["used"] == 0
            assert occ["free"] == cache.free_pages
            # each tp shard holds half the kv heads of every pool
            assert occ["shard_mb"] == pytest.approx(expect_mb,
                                                    rel=0.01)

        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(name)
        try:
            fn, _, _ = COMMANDS["metrics"]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                fn(ses, [])
            out = buf.getvalue()
            assert "sptpu_completer_tp 2" in out
            assert 'sptpu_completer_pages_free{daemon="completer",' \
                   'shard="0"}' in out
            assert 'shard="1"' in out
        finally:
            ses.close()
    finally:
        st.close()
        Store.unlink(name)


def test_sharded_dispatch_fault_contained(pair, tmp_path):
    """Satellite: a raise at completer.sharded_dispatch aborts the
    live batch (rows finalize with what they streamed, the pool is
    rebuilt) and the lane keeps serving — the next request completes
    normally."""
    _, tp = pair
    name = f"/spt-shpf-{tmp_path.name[-8:]}"
    Store.unlink(name)
    st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
    try:
        faults.arm("completer.sharded_dispatch:raise@1")
        comp = Completer(st, model=tp, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        _submit(st, "first", b"hello pod")
        th = _run_bg(comp, stop_after=120.0)
        assert _await_ready(st, ["first"], timeout=60), comp.stats
        stats = faults.stats()["completer.sharded_dispatch"]
        assert stats["fired"] == 1
        # the lane survived the abort: a fresh request serves fully
        _submit(st, "second", b"still alive?")
        assert _await_ready(st, ["second"], timeout=60), comp.stats
        comp.stop()
        th.join(timeout=5)
        assert comp._paged_cache.used_pages == 0, "pages leaked"
        assert len(st.get("second").rstrip(b"\0")) > len(b"still alive?")
    finally:
        faults.disarm()
        st.close()
        Store.unlink(name)
