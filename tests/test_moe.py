"""MoE decoder family (models/moe.py): routing semantics, KV-cache
decode consistency, and expert-parallel serving parity on the virtual
mesh."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import CompletionModel, init_cache
from libsplinter_tpu.models.moe import (MoeDecoder, MoeDecoderConfig,
                                        MoeMlp, moe_completion_model)
from libsplinter_tpu.parallel import make_mesh

CFG = MoeDecoderConfig.tiny(dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return moe_completion_model(CFG, buckets=(16,), temp=0.0)


def test_top1_routing_selects_single_expert():
    """With top_k=1 the output must equal the argmax expert's FFN alone."""
    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32, top_k=1)
    mlp = MoeMlp(cfg)
    x = np.random.default_rng(0).normal(size=(1, 3, cfg.hidden)) \
        .astype(np.float32)
    params = mlp.init(jax.random.PRNGKey(0), x)
    out = mlp.apply(params, x)

    p = params["params"]
    logits = x @ np.asarray(p["router"]["kernel"])
    e_star = np.argmax(logits, -1)              # (1, 3)
    wg = np.asarray(p["gate_experts"])
    wu = np.asarray(p["up_experts"])
    wd = np.asarray(p["down_experts"])
    for s in range(3):
        e = int(e_star[0, s])
        h = x[0, s] @ wg[e]
        u = x[0, s] @ wu[e]
        want = (h / (1 + np.exp(-h)) * u) @ wd[e]   # silu(h)*u @ down
        np.testing.assert_allclose(np.asarray(out)[0, s], want,
                                   rtol=1e-5, atol=1e-5)


def test_gates_renormalize_over_topk():
    """top_k=2 output must equal w1*FFN(e1) + w2*FFN(e2) with the two
    selected routing probs renormalized to sum to 1 (Mixtral
    convention) — not the raw softmax masses."""
    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32, top_k=2)
    mlp = MoeMlp(cfg)
    x = np.random.default_rng(3).normal(size=(1, 2, cfg.hidden)) \
        .astype(np.float32)
    params = mlp.init(jax.random.PRNGKey(1), x)
    out = np.asarray(mlp.apply(params, x))

    p = params["params"]
    logits = x @ np.asarray(p["router"]["kernel"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    wg = np.asarray(p["gate_experts"])
    wu = np.asarray(p["up_experts"])
    wd = np.asarray(p["down_experts"])

    def ffn(vec, e):
        h = vec @ wg[e]
        u = vec @ wu[e]
        return (h / (1 + np.exp(-h)) * u) @ wd[e]

    for s in range(2):
        top2 = np.argsort(-probs[0, s])[:2]
        w = probs[0, s, top2]
        w = w / w.sum()                     # the renormalization
        want = w[0] * ffn(x[0, s], top2[0]) + w[1] * ffn(x[0, s], top2[1])
        np.testing.assert_allclose(out[0, s], want, rtol=1e-5,
                                   atol=1e-5)


def test_n_experts_must_divide_ep():
    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32, n_experts=3)
    mesh = make_mesh(dp=2, tp=2, ep=2)
    with pytest.raises(ValueError, match="n_experts=3 must divide"):
        moe_completion_model(cfg, mesh)


def test_prefill_then_decode_matches_full_forward(model):
    """KV-cache decode == one full forward on the same ids (the
    Decoder family's core invariant holds for the MoE family too)."""
    ids = np.array([5, 9, 2, 7, 1, 3], np.int32)
    module = model.module
    cache = init_cache(CFG, 1)
    full_logits, _ = module.apply(model.params, ids[None, :], cache,
                                  jnp.int32(0))

    logits = model.prefill(ids[:4])
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full_logits[0, 3]),
                               rtol=1e-4, atol=1e-4)
    l4 = model.decode_one(int(ids[4]))
    np.testing.assert_allclose(np.asarray(l4),
                               np.asarray(full_logits[0, 4]),
                               rtol=1e-4, atol=1e-4)
    model.reset()


def test_generate_runs(model):
    toks = list(model.generate_tokens(np.ones(4, np.int32), 8, chunk=4))
    model.reset()
    assert len(toks) == 8
    assert all(0 <= t < CFG.vocab_size for t in toks)


def test_expert_parallel_generation_identical(model):
    """ep x tp sharded MoE decode must produce exactly the single-device
    tokens (GSPMD's ep psum is the identity on the math)."""
    mesh = make_mesh(dp=2, tp=2, sp=1, ep=2)
    served = moe_completion_model(CFG, mesh, params=model.params,
                                  buckets=(16,), temp=0.0)
    # expert tensors actually sharded on ep
    wg = served.params["params"]["layer_0"]["moe"]["gate_experts"]
    assert tuple(wg.sharding.spec) == ("ep", None, None)
    prompt = np.array([2, 7, 1, 8], np.int32)
    want = list(model.generate_tokens(prompt, 10, chunk=5))
    model.reset()
    got = list(served.generate_tokens(prompt, 10, chunk=5))
    served.reset()
    assert got == want
