"""Daemon-level tests of the completion engine (splainference analog):
label trifecta, streaming append, system-prompt key, chat template,
truncation, and the real JAX decoder end-to-end on a tiny config."""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import (OOM_MARKER, Completer,
                                              render_prompt)


def fake_generate(prompt):
    """Deterministic 'decoder': streams a fixed reply word by word."""
    for w in ["the", " answer", " is", " 42", "\n"]:
        yield w.encode()


def _request(store, key, prompt):
    store.set(key, prompt)
    store.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
    store.bump(key)


@pytest.fixture
def completer(store):
    c = Completer(store, generate_fn=fake_generate)
    c.attach()
    return c


def test_completion_round_trip(store, completer):
    _request(store, "q1", "what is the answer?")
    n = completer.run_once()
    assert n == 1
    out = store.get_str("q1")
    # slot = rendered prompt + streamed reply
    assert out.startswith("<|im_start|>user\nwhat is the answer?")
    assert out.endswith("the answer is 42\n")
    labels = store.labels("q1")
    assert labels & P.LBL_READY
    assert not labels & (P.LBL_INFER_REQ | P.LBL_SERVICING | P.LBL_WAITING)


def test_system_prompt_fetched_fresh(store, completer):
    store.set(P.KEY_SYSTEM_PROMPT, "be terse")
    _request(store, "q1", "hi")
    completer.run_once()
    assert "<|im_start|>system\nbe terse<|im_end|>" in store.get_str("q1")
    # change it; the next request must see the NEW system prompt
    store.set(P.KEY_SYSTEM_PROMPT, "be verbose")
    _request(store, "q2", "hi again")
    completer.run_once()
    assert "be verbose" in store.get_str("q2")
    assert "be terse" not in store.get_str("q2")


def test_bare_template_fallback(store):
    c = Completer(store, generate_fn=fake_generate, template="none")
    c.attach()
    store.set(P.KEY_SYSTEM_PROMPT, "sys")
    _request(store, "q", "user text")
    c.run_once()
    assert store.get_str("q").startswith("sys\n\nuser text")
    assert render_prompt("u", None, "none") == "u"


def test_streaming_appends_visible_mid_generation(store):
    """Readers polling the key must see val_len grow during generation
    (the reference's streaming contract, splainference.cpp:306-365)."""
    lengths = []

    def slow_generate(prompt):
        for w in ["alpha ", "beta ", "gamma "]:
            yield w.encode()
            lengths.append(store.value_len("q"))

    c = Completer(store, generate_fn=slow_generate)
    c.attach()
    _request(store, "q", "p")
    c.run_once()
    # each word ends with a boundary => flushed before the next yield
    assert lengths == sorted(lengths)
    assert lengths[1] > lengths[0]


def test_truncation_at_max_val(store):
    def endless(prompt):
        while True:
            yield b"xxxxxxxx "

    c = Completer(store, generate_fn=endless, max_new_tokens=10 ** 6)
    c.attach()
    _request(store, "q", "p")
    c.run_once()
    out = store.get("q")
    assert len(out) <= store.max_val
    assert OOM_MARKER.rstrip(b"\0") in out or len(out) >= store.max_val - 1
    assert c.stats.truncated == 1
    assert store.labels("q") & P.LBL_READY      # still completes the protocol


def test_generation_failure_releases_labels(store):
    def broken(prompt):
        yield b"partial "
        raise RuntimeError("model fell over")

    c = Completer(store, generate_fn=broken)
    c.attach()
    _request(store, "q", "p")
    c.run_once()
    labels = store.labels("q")
    assert labels & P.LBL_READY                 # never wedged in SERVICING
    assert not labels & P.LBL_SERVICING
    assert "[completer]" in store.get_str(P.KEY_DEBUG)


def test_signal_driven_run_loop(store):
    c = Completer(store, generate_fn=fake_generate)
    c.attach()
    t = threading.Thread(target=c.run, kwargs={"stop_after": 5.0})
    t.start()
    try:
        time.sleep(0.1)
        _request(store, "live", "ping")
        deadline = time.time() + 4.0
        while time.time() < deadline:
            if store.labels("live") & P.LBL_READY:
                break
            time.sleep(0.01)
        assert store.labels("live") & P.LBL_READY
    finally:
        c.stop()
        t.join()


def test_real_decoder_end_to_end(store):
    """Tiny real JAX decoder through the full protocol — prompt in,
    sampled bytes streamed back, READY label out."""
    from libsplinter_tpu.models import (ByteTokenizer, CompletionModel,
                                        DecoderConfig)

    cfg = DecoderConfig.tiny(vocab_size=300, dtype=jnp.float32)
    model = CompletionModel(cfg, buckets=(16, 32, 64), temp=1.0)
    c = Completer(store, model=model, tokenizer=ByteTokenizer(),
                  max_new_tokens=8, template="none")
    c.attach()
    _request(store, "q", "ab")
    assert c.run_once() == 1
    assert store.labels("q") & P.LBL_READY
    out = store.get("q")
    assert out.startswith(b"ab")
    assert c.stats.tokens > 0 or out == b"ab"   # eos-first is legal


# --------------------------------------- ADVICE r1: template resolution

def test_render_prompt_unknown_template_raises():
    with pytest.raises(ValueError, match="unknown chat template"):
        render_prompt("u", None, "auto")
    with pytest.raises(ValueError, match="unknown chat template"):
        render_prompt("u", None, "alpaca")


def test_completer_rejects_unresolved_auto(store):
    with pytest.raises(ValueError, match="unknown chat template"):
        Completer(store, generate_fn=fake_generate, template="auto")


def test_detect_template_fingerprints():
    from libsplinter_tpu.engine.completer import detect_template
    assert detect_template("{%...<|im_start|>...%}") == "chatml"
    assert detect_template("...<|start_header_id|>...") == "llama3"
    assert detect_template("...[INST]...") == "llama2"
    assert detect_template("{{ weird custom }}") == "none"
    assert detect_template(None) == "none"


def test_main_auto_resolves_from_gguf_metadata(tmp_path, store):
    """--template auto must fingerprint tokenizer.chat_template from the
    GGUF (the round-1 bug: auto fell through to chatml for every model)."""
    import jax as _jax
    import numpy as _np

    from libsplinter_tpu.models.decoder import (Decoder, DecoderConfig,
                                                init_cache)
    from tests.test_gguf import (_decoder_gguf_from_params, kv_f32_array,
                                 kv_str, kv_str_array, kv_u32, write_gguf)

    cfg = DecoderConfig.tiny(vocab_size=300)
    params = Decoder(cfg).init(_jax.random.PRNGKey(0),
                               _np.zeros((1, 4), _np.int32),
                               init_cache(cfg, 1), _np.int32(0))
    path = tmp_path / "auto.gguf"
    _decoder_gguf_from_params(path, params, cfg)

    # re-write with chat-template metadata attached
    import tests.test_gguf as tg
    p = _jax.tree.map(lambda x: _np.asarray(x, _np.float32),
                      params["params"])
    t = {"token_embd.weight": (p["tok_emb"]["embedding"], tg.GGML_F32),
         "output_norm.weight": (p["ln_out"]["scale"], tg.GGML_F32),
         "output.weight": (p["lm_head"]["kernel"].T.copy(), tg.GGML_F32)}
    for i in range(cfg.layers):
        lp = p[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_norm.weight"] = (lp["ln_attn"]["scale"], tg.GGML_F32)
        t[f"{b}.ffn_norm.weight"] = (lp["ln_mlp"]["scale"], tg.GGML_F32)
        for src, dst in (("q", "attn_q"), ("k", "attn_k"),
                         ("v", "attn_v"), ("out", "attn_output")):
            t[f"{b}.{dst}.weight"] = (
                lp["attn"][src]["kernel"].T.copy(), tg.GGML_F32)
        for name in ("gate", "up", "down"):
            t[f"{b}.ffn_{name}.weight"] = (lp[name]["kernel"].T.copy(),
                                           tg.GGML_F32)
    tokens = [f"tok{i}" for i in range(300)]
    meta = [kv_str("general.architecture", "llama"),
            kv_u32("llama.block_count", cfg.layers),
            kv_u32("llama.embedding_length", cfg.hidden),
            kv_u32("llama.attention.head_count", cfg.heads),
            kv_u32("llama.attention.head_count_kv", cfg.kv_heads),
            kv_u32("llama.feed_forward_length", cfg.mlp_dim),
            kv_u32("llama.context_length", cfg.max_len),
            kv_str("tokenizer.ggml.model", "llama"),
            kv_str_array("tokenizer.ggml.tokens", tokens),
            kv_f32_array("tokenizer.ggml.scores", [0.0] * 300),
            kv_str("tokenizer.chat_template",
                   "{% ... <|start_header_id|> ... %}")]
    write_gguf(path, t, meta)

    import libsplinter_tpu.engine.completer as completer_mod
    captured = {}
    real_completer = completer_mod.Completer

    class Capture(real_completer):
        def __init__(self, *a, **kw):
            captured["template"] = kw.get("template")
            super().__init__(*a, **kw)

    completer_mod.Completer = Capture
    try:
        completer_mod.main(["--store", store.name, "--oneshot",
                            "--weights", str(path)])
    finally:
        completer_mod.Completer = real_completer
    assert captured["template"] == "llama3"


def test_tp_sharded_model_serves_daemon(store):
    """The completion daemon drives a tensor-parallel decoder unchanged
    (parallel.serve: constructor swap) — a labeled request is serviced
    end to end with the model sharded over the virtual mesh."""
    from libsplinter_tpu.models.decoder import DecoderConfig
    from libsplinter_tpu.parallel import ShardedCompletionModel, make_mesh

    cfg = DecoderConfig.tiny(dtype=jnp.float32, vocab_size=512)
    mesh = make_mesh(dp=4, tp=2, sp=1)
    model = ShardedCompletionModel(cfg, mesh, buckets=(16,), temp=0.0)
    c = Completer(store, model=model, max_new_tokens=8, template="none")
    c.attach()
    _request(store, "q", "hi")
    assert c.run_once() == 1
    out = store.get("q")
    assert len(out.rstrip(b"\0")) > 0
    labels = store.labels("q")
    assert labels & P.LBL_READY
    assert not labels & (P.LBL_INFER_REQ | P.LBL_SERVICING)
