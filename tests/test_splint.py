"""splint (libsplinter_tpu/analysis/): registry extraction against
the live protocol.py, per-rule positive/negative fixtures, suppression
+ baseline semantics, the live-tree gate, and the meta-test keeping
the rule catalog and the docs rule table in sync.

The analysis package is loaded STANDALONE (by path, stdlib-only) —
this tier must run without jax or the built native lib, exactly like
`make lint-check` promises.
"""
from __future__ import annotations

import importlib.util
import os
import re
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_splint():
    spec = importlib.util.spec_from_file_location(
        "_splint_load", os.path.join(
            ROOT, "libsplinter_tpu", "analysis", "_load.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load()


@pytest.fixture(scope="module")
def splint():
    return _load_splint()


@pytest.fixture(scope="module")
def R(splint):
    return sys.modules[splint.__name__ + ".registry"]


@pytest.fixture(scope="module")
def core(splint):
    return sys.modules[splint.__name__ + ".core"]


@pytest.fixture(scope="module")
def runner(splint):
    return sys.modules[splint.__name__ + ".runner"]


# ------------------------------------------------------------ fixtures

PROTO_OK = """\
LBL_A = 0x1                    # label a
LBL_B = 0x40                   # label b
LBL_HIGH = 0x1 << 57           # high label
TENANT_SHIFT = 48
TENANT_BITS = 4
TENANT_MASK = ((1 << TENANT_BITS) - 1) << TENANT_SHIFT
BIT_A = 0
BIT_B = 6
PIPELINE_STAGES = ("drain", "commit")
SEARCH_STAGES = ("wake", "drain", "score", "select", "commit")
KEY_EMBED_STATS = "__embedder_stats"
SEARCH_RESULT_PREFIX = "__sr_"
"""

PROTO_RELPATH = "libsplinter_tpu/engine/protocol.py"


def make_ctx(splint, R, core, files=None, proto=PROTO_OK, docs=None,
             tests_text="", fault_docs=None):
    files = files or {}
    reg = R.extract_registry(source=proto)
    return core.Context(
        registry=reg,
        files={rel: core.SourceFile(rel, text)
               for rel, text in files.items()},
        fault_sites=R.fault_sites(sources=files),
        fault_site_docs=(R.FAULT_SITE_DOCS if fault_docs is None
                         else fault_docs),
        docs=docs or {},
        tests_text=tests_text,
        protocol_relpath=PROTO_RELPATH)


def run_rule(splint, R, core, runner, rule_id, **kw):
    ctx = make_ctx(splint, R, core, **kw)
    return [f for f in runner.run_rules(ctx, [rule_id])]


# --------------------------------------- registry vs live protocol.py

def test_registry_extracts_live_protocol(splint):
    reg = splint.extract_registry()
    assert reg.labels["LBL_EMBED_REQ"].mask == 0x1
    assert reg.labels["LBL_READY"].mask == 1 << 62
    assert reg.labels["LBL_SEARCH_REQ"].bits == (57,)
    assert reg.fields["TENANT_MASK"].bits == tuple(range(48, 52))
    assert reg.stages["PIPELINE_STAGES"] == (
        "drain", "tokenize", "dispatch", "device_wait", "commit")
    assert reg.stages["CONT_INFER_STAGES"] == (
        "join", "sample", "decode", "collect", "flush", "prefix_hit",
        "handoff", "adopt")
    assert reg.keys["KEY_SEARCH_STATS"] == "__searcher_stats"
    assert reg.prefixes["SEARCH_RESULT_PREFIX"] == "__sr_"
    assert reg.prefixes["DEADLINE_STAMP_PREFIX"] == "__dl_"
    assert reg.bit_indices["BIT_INFER_REQ"] == 60
    # the label comment rides into the registry (doc-table source)
    assert "wakes the embedding daemon" in \
        reg.labels["LBL_EMBED_REQ"].comment


def test_live_fault_sites_discovered(splint):
    sites = {s.site for s in splint.fault_sites(ROOT)}
    assert {"searcher.gather", "embedder.encode", "completer.render",
            "completer.kv_quant_commit", "resident.ring_collect",
            "supervisor.poll", "store.set", "store.vec_commit"} <= sites
    assert sites <= set(splint.FAULT_SITE_DOCS)


# ----------------------------------------------------- the live gate

def test_live_tree_is_clean(runner):
    """THE acceptance gate: splint exits 0 on the tree at HEAD.  Any
    new finding must be fixed, suppressed with a reason, or (outside
    the engine layer) baselined — see docs/operations.md."""
    rep = runner.scan(ROOT)
    assert rep.clean, "\n" + rep.render()
    # the shipped suppressions: the two documented intentional host
    # syncs plus the SPL205 inner-kernel / cold-path registrations;
    # anything more deserves a fresh look at this list
    reasons = {f.file for f, _ in rep.suppressed}
    assert reasons == {"libsplinter_tpu/engine/completer.py",
                       "libsplinter_tpu/engine/embedder.py",
                       "libsplinter_tpu/models/decoder.py",
                       "libsplinter_tpu/ops/flash_attention.py",
                       "libsplinter_tpu/ops/paged_attention.py",
                       "libsplinter_tpu/ops/similarity.py"}


def test_baseline_has_no_engine_entries(core):
    """The committed baseline must be empty of engine-layer findings
    (and in fact ships empty): hot-path hazards are fixed or
    justified inline, never backlogged."""
    path = os.path.join(ROOT, core.BASELINE_RELPATH)
    entries = core.load_baseline(path)
    assert not {e for e in entries
                if "libsplinter_tpu/engine/" in e}
    assert entries == set()            # ships empty — keep it so


# ------------------------------------------- SPL101/SPL108: registry

def test_label_collision_detected(splint, R, core, runner):
    bad = PROTO_OK + "LBL_EVIL = 0x40        # collides with LBL_B\n"
    fs = run_rule(splint, R, core, runner, "SPL101", proto=bad)
    assert len(fs) == 1 and fs[0].rule == "SPL101"
    assert "LBL_EVIL" in fs[0].message and "bit 6" in fs[0].message


def test_label_field_collision_detected(splint, R, core, runner):
    bad = PROTO_OK + "LBL_EVIL = 0x1 << 50   # inside TENANT_MASK\n"
    fs = run_rule(splint, R, core, runner, "SPL101", proto=bad)
    assert len(fs) == 1 and "TENANT_MASK" in fs[0].message


def test_live_protocol_has_no_collisions(splint, R, core, runner):
    with open(os.path.join(ROOT, PROTO_RELPATH)) as f:
        live = f.read()
    assert run_rule(splint, R, core, runner, "SPL101",
                    proto=live) == []
    assert run_rule(splint, R, core, runner, "SPL108",
                    proto=live) == []


def test_bit_index_mismatch_detected(splint, R, core, runner):
    bad = PROTO_OK.replace("BIT_B = 6", "BIT_B = 7")
    fs = run_rule(splint, R, core, runner, "SPL108", proto=bad)
    assert len(fs) == 1 and "BIT_B=7" in fs[0].message


# ------------------------------------------- SPL102: raw bit literals

def test_raw_high_shift_flagged(splint, R, core, runner):
    src = "MASK = 1 << 57\n"
    fs = run_rule(splint, R, core, runner, "SPL102",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1 and "bit 57" in fs[0].message


def test_raw_literal_in_label_api_flagged(splint, R, core, runner):
    src = "def f(store, key):\n    store.label_or(key, 0x40)\n"
    fs = run_rule(splint, R, core, runner, "SPL102",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1 and "label_or" in fs[0].message


def test_raw_literal_in_label_bitop_flagged(splint, R, core, runner):
    src = "def f(labels):\n    return labels & 0x40\n"
    fs = run_rule(splint, R, core, runner, "SPL102",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1


def test_innocent_literals_not_flagged(splint, R, core, runner):
    # 0x40 == 64 as a size, a non-label bitop, protocol.py itself
    src = ("def f(v, store):\n"
           "    buf = bytearray(0x40)\n"
           "    store.set('k', 'x' * 64)\n"
           "    return v & 0x3F\n")
    assert run_rule(splint, R, core, runner, "SPL102", files={
        "libsplinter_tpu/engine/foo.py": src,
        PROTO_RELPATH: "LBL_B = 0x40\nX = LBL_B & 0x40\n"}) == []


# --------------------------------------- SPL103/SPL104: fault sites

def test_undocumented_fault_site_flagged(splint, R, core, runner):
    src = "def f():\n    fault('new.site')\n"
    fs = run_rule(splint, R, core, runner, "SPL103",
                  files={"libsplinter_tpu/engine/foo.py": src},
                  tests_text="new.site")
    assert len(fs) == 1 and "FAULT_SITE_DOCS" in fs[0].message


def test_documented_site_missing_from_ops_doc(splint, R, core, runner):
    src = "def f():\n    fault('new.site')\n"
    fs = run_rule(splint, R, core, runner, "SPL103",
                  files={"libsplinter_tpu/engine/foo.py": src},
                  fault_docs={"new.site": "somewhere"},
                  docs={"operations": "no table here"})
    assert len(fs) == 1 and "operations.md" in fs[0].message
    fs = run_rule(splint, R, core, runner, "SPL103",
                  files={"libsplinter_tpu/engine/foo.py": src},
                  fault_docs={"new.site": "somewhere"},
                  docs={"operations": "| `new.site` | somewhere |"})
    assert fs == []


def test_chaos_unreached_site_flagged(splint, R, core, runner):
    src = "def f():\n    fault('lonely.site')\n"
    fs = run_rule(splint, R, core, runner, "SPL104",
                  files={"libsplinter_tpu/engine/foo.py": src},
                  tests_text="tests mention other.site only")
    assert len(fs) == 1 and "lonely.site" in fs[0].message
    assert run_rule(splint, R, core, runner, "SPL104",
                    files={"libsplinter_tpu/engine/foo.py": src},
                    tests_text="SPTPU_FAULT=lonely.site:crash@1") == []


# ----------------------------------------- SPL105: metrics/heartbeat

METRICS_RELPATH = "libsplinter_tpu/cli/metrics.py"


def test_hardcoded_heartbeat_key_flagged(splint, R, core, runner):
    src = ("from ..engine import protocol as P\n"
           "KEYS = [P.KEY_EMBED_STATS]\n"
           "BAD = '__embedder_stats'\n")
    fs = run_rule(splint, R, core, runner, "SPL105",
                  files={METRICS_RELPATH: src})
    assert len(fs) == 1 and "hardcoded" in fs[0].message


def test_unrendered_heartbeat_key_flagged(splint, R, core, runner):
    proto = PROTO_OK + 'KEY_NEWLANE_STATS = "__newlane_stats"\n'
    src = "from ..engine import protocol as P\nK = P.KEY_EMBED_STATS\n"
    fs = run_rule(splint, R, core, runner, "SPL105", proto=proto,
                  files={METRICS_RELPATH: src})
    assert len(fs) == 1 and "KEY_NEWLANE_STATS" in fs[0].message


def test_unknown_store_key_flagged(splint, R, core, runner):
    src = "K = '__mystery_key'\n"
    fs = run_rule(splint, R, core, runner, "SPL105",
                  files={METRICS_RELPATH: src})
    assert len(fs) == 2     # hardcoded-unknown + unrendered KEY_EMBED
    assert any("not a registered" in f.message for f in fs)


# ------------------------------------------- SPL106: doc-table drift

def test_doc_table_drift_flagged(splint, R, core, runner):
    fs = run_rule(splint, R, core, runner, "SPL106",
                  docs={"operations": "stale", "bloom-labels": "stale"})
    assert {f.rule for f in fs} == {"SPL106"} and len(fs) == 2


def test_doc_tables_in_sync_pass(splint, R, core, runner):
    reg = R.extract_registry(source=PROTO_OK)
    files = {"libsplinter_tpu/engine/foo.py":
             "def f():\n    fault('searcher.gather')\n"}
    ctx = make_ctx(splint, R, core, files=files, docs={})
    ctx.docs = {"bloom-labels": R.render_label_table(reg),
                "operations": R.render_fault_table(ctx.fault_sites)}
    assert runner.run_rules(ctx, ["SPL106"]) == []


# ------------------------------------------- SPL107: stage names

def test_stage_typo_flagged(splint, R, core, runner):
    src = ("def f(tracer):\n"
           "    tracer.record('search.scoree', 1.0)\n"
           "    tracer.record('search.score', 1.0)\n"
           "    tracer.record('search.e2e', 1.0)\n")
    fs = run_rule(splint, R, core, runner, "SPL107",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1 and "scoree" in fs[0].message


def test_span_helper_stage_checked(splint, R, core, runner):
    src = ("def f(span, r):\n"
           "    span(r, 'wake', 1.0)\n"
           "    span(r, 'jion', 1.0)\n")
    fs = run_rule(splint, R, core, runner, "SPL107",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1 and "jion" in fs[0].message


# ------------------------------------------- SPL201: host syncs

DRAIN_BAD = """\
import jax
import numpy as np

class D:
    def run_continuous(self):
        pend = self.dispatch()
        toks = jax.device_get(pend)
        t = int(self.m.sample(toks))
        return toks, t

    def _dispatch_ring(self):
        vecs = np.asarray(self.encoder_fn(['x']), np.float32)
        pend2 = self.dispatch()
        pend2.block_until_ready()
        return vecs

    def helper(self):
        return jax.device_get(self.x)    # not a drain fn: allowed

    def _service(self):
        n = int(self.count)              # Name arg: no fetch
        lens = np.asarray(self.lens)     # Name arg: no fetch
        return n, lens
"""


def test_host_sync_in_drain_flagged(splint, R, core, runner):
    fs = run_rule(splint, R, core, runner, "SPL201",
                  files={"libsplinter_tpu/engine/foo.py": DRAIN_BAD})
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 4, msgs
    assert any("device_get" in m for m in msgs)
    assert any("block_until_ready" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any("int(" in m for m in msgs)
    # exactly the four hazard lines — helper()'s device_get (not a
    # drain fn) and _service's Name-arg coercions stay clean
    assert sorted(f.line for f in fs) == [7, 8, 12, 14]


def test_acceptance_seeded_device_get_fails_gate(splint, R, core,
                                                 runner):
    """The ISSUE's acceptance drill: seed a device_get into a
    run_continuous body and the gate must fail with a file:line ·
    RULE_ID report."""
    src = ("import jax\n"
           "def run_continuous(self):\n"
           "    return jax.device_get(self.pend)\n")
    ctx = make_ctx(splint, R, core,
                   files={"libsplinter_tpu/engine/evil.py": src})
    rep = runner.scan(ctx=ctx, use_baseline=False,
                      rule_ids=["SPL201"])
    assert not rep.clean
    line = rep.render().splitlines()[0]
    assert re.match(r"libsplinter_tpu/engine/evil\.py:3 · SPL201 · ",
                    line)


# ----------------------------------- suppression + baseline semantics

def test_suppression_with_reason_suppresses(splint, R, core, runner):
    src = ("import jax\n"
           "def run_continuous(self):\n"
           "    # splint: ignore[SPL201] reason=measured: the fetch "
           "overlaps the next dispatch\n"
           "    return jax.device_get(self.pend)\n")
    ctx = make_ctx(splint, R, core,
                   files={"libsplinter_tpu/engine/foo.py": src})
    rep = runner.scan(ctx=ctx, use_baseline=False,
                      rule_ids=["SPL201", "SPL001"])
    assert [f.rule for f in rep.findings] == []
    assert len(rep.suppressed) == 1
    assert "overlaps" in rep.suppressed[0][1].reason


def test_suppression_without_reason_is_a_finding(splint, R, core,
                                                 runner):
    src = ("import jax\n"
           "def run_continuous(self):\n"
           "    return jax.device_get(self.pend)  "
           "# splint: ignore[SPL201]\n")
    ctx = make_ctx(splint, R, core,
                   files={"libsplinter_tpu/engine/foo.py": src})
    rep = runner.scan(ctx=ctx, use_baseline=False,
                      rule_ids=["SPL201", "SPL001"])
    # the SPL201 is suppressed, but the naked suppression is SPL001
    assert [f.rule for f in rep.findings] == ["SPL001"]


def test_suppression_unknown_rule_is_a_finding(splint, R, core,
                                               runner):
    src = "x = 1  # splint: ignore[SPL999] reason=no such rule\n"
    ctx = make_ctx(splint, R, core,
                   files={"libsplinter_tpu/engine/foo.py": src})
    rep = runner.scan(ctx=ctx, use_baseline=False,
                      rule_ids=["SPL001"])
    assert [f.rule for f in rep.findings] == ["SPL001"]


def test_baseline_hides_only_matching_findings(splint, R, core,
                                               runner, tmp_path):
    src = ("import jax\n"
           "def run_continuous(self):\n"
           "    return jax.device_get(self.pend)\n")
    ctx = make_ctx(splint, R, core,
                   files={"libsplinter_tpu/engine/foo.py": src})
    rep = runner.scan(ctx=ctx, use_baseline=False,
                      rule_ids=["SPL201"])
    assert len(rep.findings) == 1
    base = tmp_path / "base.txt"
    base.write_text(rep.findings[0].fingerprint() + "\n")
    rep2 = runner.scan(ctx=make_ctx(
        splint, R, core,
        files={"libsplinter_tpu/engine/foo.py": src}),
        baseline_path=str(base), rule_ids=["SPL201"])
    assert rep2.clean and len(rep2.baselined) == 1
    # a DIFFERENT finding (another hazard class, so another
    # fingerprint) is not baselined
    src2 = src.replace("jax.device_get(self.pend)",
                       "self.pend.block_until_ready()")
    rep3 = runner.scan(ctx=make_ctx(
        splint, R, core,
        files={"libsplinter_tpu/engine/foo.py": src2}),
        baseline_path=str(base), rule_ids=["SPL201"])
    assert not rep3.clean


def test_write_baseline_refuses_engine_findings(runner, tmp_path):
    """The no-engine-entries policy lives in the MECHANISM: an
    engine-layer finding refuses to baseline (nothing written), so
    the documented workflow cannot mask a hot-path hazard."""
    pkg = tmp_path / "libsplinter_tpu" / "engine"
    pkg.mkdir(parents=True)
    (pkg / "protocol.py").write_text(PROTO_OK)
    (pkg / "evil.py").write_text(
        "import jax\ndef run_continuous(s):\n"
        "    return jax.device_get(s.p)\n")
    with pytest.raises(ValueError, match="engine-layer"):
        runner.update_baseline(str(tmp_path))
    base = tmp_path / "libsplinter_tpu" / "analysis" / \
        "splint_baseline.txt"
    assert not base.exists()
    # the same hazard outside the engine layer baselines fine
    ops = tmp_path / "libsplinter_tpu" / "ops"
    ops.mkdir()
    (pkg / "evil.py").rename(ops / "evil.py")
    base.parent.mkdir()
    runner.update_baseline(str(tmp_path))
    assert "SPL201" in base.read_text()


def test_write_baseline_roundtrip(splint, R, core, tmp_path):
    f = core.Finding("libsplinter_tpu/ops/x.py", 3, "SPL102", "msg")
    path = tmp_path / "b.txt"
    core.write_baseline(str(path), [f])
    assert core.load_baseline(str(path)) == {f.fingerprint()}


# ------------------------------------------- SPL202/203/204 fixtures

def test_donated_buffer_reuse_flagged(splint, R, core, runner):
    src = ("import jax\n"
           "def build():\n"
           "    fn = jax.jit(step, donate_argnums=(0,))\n"
           "    pool = make_pool()\n"
           "    out = fn(pool, x)\n"
           "    return pool.shape\n")          # reuse after donation
    fs = run_rule(splint, R, core, runner, "SPL202",
                  files={"libsplinter_tpu/models/foo.py": src})
    assert len(fs) == 1 and "'pool'" in fs[0].message


def test_donated_rebind_is_clean(splint, R, core, runner):
    src = ("import jax\n"
           "def build():\n"
           "    fn = jax.jit(step, donate_argnums=(0,))\n"
           "    pool = make_pool()\n"
           "    pool = fn(pool, x)\n"         # rebound on the line
           "    return pool.shape\n")
    assert run_rule(splint, R, core, runner, "SPL202", files={
        "libsplinter_tpu/models/foo.py": src}) == []


def test_donating_call_spanning_lines_is_clean(splint, R, core,
                                               runner):
    """The donated argument's own load inside a WRAPPED donating call
    is pre-donation — it must not flag (this codebase wraps at ~72
    chars, so multi-line calls are the norm)."""
    src = ("import jax\n"
           "def build():\n"
           "    fn = jax.jit(step, donate_argnums=(0,))\n"
           "    pool = make_pool()\n"
           "    out = fn(\n"
           "        pool, x)\n"
           "    return out\n")
    assert run_rule(splint, R, core, runner, "SPL202", files={
        "libsplinter_tpu/models/foo.py": src}) == []
    # ...while a post-call read of the wrapped call's donated arg
    # still flags
    bad = src.replace("return out", "return pool.shape")
    fs = run_rule(splint, R, core, runner, "SPL202", files={
        "libsplinter_tpu/models/foo.py": bad})
    assert len(fs) == 1 and "'pool'" in fs[0].message


def test_unknown_rule_selection_fails_loudly(splint, R, core, runner):
    """`--rules SPL999` must error, never run zero rules and report a
    clean tree (the fault-spec-typo lesson)."""
    ctx = make_ctx(splint, R, core)
    with pytest.raises(ValueError, match="SPL999"):
        runner.run_rules(ctx, ["SPL999"])
    with pytest.raises(ValueError, match="SPL999"):
        runner.scan(ctx=ctx, rule_ids=["SPL101", "SPL999"])


def test_pool_jit_without_out_shardings_flagged(splint, R, core,
                                                runner):
    src = ("import jax\n"
           "def make(cache):\n"
           "    pools = cache.k_pools\n"
           "    fn = jax.jit(run, donate_argnums=(0,))\n"
           "    return fn(pools)\n")
    fs = run_rule(splint, R, core, runner, "SPL203",
                  files={"libsplinter_tpu/models/foo.py": src})
    assert len(fs) == 1 and "out_shardings" in fs[0].message


def test_pool_jit_with_pin_or_kw_idiom_clean(splint, R, core, runner):
    direct = ("import jax\n"
              "def make(cache, sh):\n"
              "    pools = cache.k_pools\n"
              "    fn = jax.jit(run, out_shardings=sh)\n"
              "    return fn(pools)\n")
    kw_idiom = ("import jax\n"
                "def make(self, cache):\n"
                "    pools = cache.k_pools\n"
                "    out_sh = self._paged_pool_out_shardings(1, 0)\n"
                "    kw = {} if out_sh is None else "
                "{'out_shardings': out_sh}\n"
                "    fn = jax.jit(run, **kw)\n"
                "    return fn(pools)\n")
    for src in (direct, kw_idiom):
        assert run_rule(splint, R, core, runner, "SPL203", files={
            "libsplinter_tpu/models/foo.py": src}) == []


def test_unregistered_jit_program_flagged(splint, R, core, runner):
    src = ("import jax\n"
           "def _chunk_fn(n):\n"
           "    def run(x):\n"
           "        return x + n\n"
           "    return jax.jit(run, donate_argnums=(0,))\n")
    fs = run_rule(splint, R, core, runner, "SPL205",
                  files={"libsplinter_tpu/models/foo.py": src})
    assert len(fs) == 1 and "DEVTIME.register" in fs[0].message \
        and "_chunk_fn" in fs[0].message
    # the same factory returning through DEVTIME.register is clean
    ok = src.replace(
        "return jax.jit(run, donate_argnums=(0,))",
        "return DEVTIME.register('completer.chunk',\n"
        "        jax.jit(run, donate_argnums=(0,)))")
    assert run_rule(splint, R, core, runner, "SPL205", files={
        "libsplinter_tpu/models/foo.py": ok}) == []


def test_spl205_scope_and_module_level_semantics(splint, R, core,
                                                 runner):
    # a partial(jax.jit, ...) decorator on a module-level function is
    # a jit program too — flagged when no scope registers it
    deco = ("import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def _kernel(x, n):\n"
            "    return x * n\n")
    fs = run_rule(splint, R, core, runner, "SPL205",
                  files={"libsplinter_tpu/ops/foo.py": deco})
    assert len(fs) == 1 and fs[0].line == 3
    # a module-level jit assignment registered in the same statement
    # is clean; unregistered flags
    mod = ("import jax\n"
           "prog = DEVTIME.register('searcher.topk', jax.jit(run))\n"
           "bare = jax.jit(other)\n")
    fs = run_rule(splint, R, core, runner, "SPL205",
                  files={"libsplinter_tpu/ops/foo.py": mod})
    assert len(fs) == 1 and fs[0].line == 3
    # module-level pallas_call is a program of its own; inside a
    # function it is an internal of the enclosing jit program
    pal = ("import jax\n"
           "grid_fn = pl.pallas_call(kern, grid=(4,))\n"
           "def scores(x):\n"
           "    return pl.pallas_call(kern, grid=(4,))(x)\n")
    fs = run_rule(splint, R, core, runner, "SPL205",
                  files={"libsplinter_tpu/ops/foo.py": pal})
    assert len(fs) == 1 and fs[0].line == 2 \
        and "pallas_call" in fs[0].message
    # engine/ and parallel/ trees are out of scope — programs there
    # are built by the models/ops factories this rule already covers
    assert run_rule(splint, R, core, runner, "SPL205", files={
        "libsplinter_tpu/engine/foo.py": deco,
        "libsplinter_tpu/parallel/foo.py": deco}) == []


def test_global_rng_in_fault_path_flagged(splint, R, core, runner):
    src = ("import random\n"
           "def step():\n"
           "    fault('x.y')\n"
           "    if random.random() < 0.5:\n"
           "        return 1\n")
    fs = run_rule(splint, R, core, runner, "SPL204",
                  files={"libsplinter_tpu/engine/foo.py": src})
    assert len(fs) == 1 and "random.random" in fs[0].message
    # a seeded instance draw is fine
    ok = src.replace("random.random()", "rng.random()")
    assert run_rule(splint, R, core, runner, "SPL204", files={
        "libsplinter_tpu/engine/foo.py": ok}) == []


# ----------------------------------------------- meta + report shape

def test_rule_catalog_matches_docs_table(core):
    """The docs/operations.md rule table is generated from the rule
    registry — ids must match EXACTLY (a rule that runs undocumented
    or a documented rule that doesn't run both fail)."""
    with open(os.path.join(ROOT, "docs", "operations.md")) as f:
        ops = f.read()
    begin = ops.index("splint:rule-catalog:begin")
    end = ops.index("splint:rule-catalog:end")
    table = ops[begin:end]
    doc_ids = set(re.findall(r"\| `(SPL\d+)` \|", table))
    assert doc_ids == set(core.RULES)


def test_rule_table_render_matches_committed(core):
    with open(os.path.join(ROOT, "docs", "operations.md")) as f:
        ops = f.read()
    assert core.render_rule_table() in ops, \
        "docs rule table stale — run scripts/gen_api_docs.py"


def test_report_line_format(core):
    f = core.Finding("a/b.py", 7, "SPL101", "boom")
    assert f.render() == "a/b.py:7 · SPL101 · boom"
    assert f.fingerprint() == "SPL101 · a/b.py · boom"


def test_every_rule_has_fixture_coverage():
    """Each cataloged rule id must appear in this test file beyond
    the catalog itself — a rule without a fixture is unverified."""
    splint = _load_splint()
    with open(os.path.abspath(__file__)) as f:
        me = f.read()
    for rid in splint.RULES:
        assert me.count(rid) >= 1, f"no fixture exercises {rid}"
