"""The obs subsystem: log-bucketed histograms, flight recorder, and
Prometheus exposition — plus their threading through the daemons
(trace-id stamps, heartbeat quantiles, slow log, `spt metrics` /
`spt trace tail`).

Grouped under `pytest -m obs` (the `make obs-check` tier)."""
from __future__ import annotations

import json

import numpy as np
import pytest

from libsplinter_tpu import Store, T_VARTEXT
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.obs.hist import (
    LogHistogram, bucket_index, bucket_upper_ms,
)
from libsplinter_tpu.obs.prom import PromWriter
from libsplinter_tpu.obs.recorder import FlightRecorder
from libsplinter_tpu.utils.trace import Tracer

pytestmark = pytest.mark.obs


# ---------------------------------------------------------------- histogram

class TestLogHistogram:
    def test_quantiles_within_bucket_resolution(self):
        h = LogHistogram()
        rng = np.random.default_rng(0)
        samples = rng.lognormal(mean=1.0, sigma=1.2, size=5000)
        for s in samples:
            h.record(float(s))
        for q in (0.5, 0.9, 0.99):
            exact = float(np.quantile(samples, q))
            got = h.quantile(q)
            # log-bucket resolution: ~19% relative at 4 buckets/octave
            assert abs(got - exact) / exact < 0.25, (q, got, exact)
        assert h.n == 5000
        assert h.max_ms == pytest.approx(float(samples.max()))

    def test_quantiles_clamped_to_observed_range(self):
        h = LogHistogram()
        h.record(3.0)
        assert h.quantile(0.5) == 3.0
        assert h.quantile(0.99) == 3.0

    def test_bucket_edges_monotonic_and_owning(self):
        prev = 0.0
        for ms in (0.0005, 0.001, 0.01, 1.0, 50.0, 7000.0, 1e8):
            i = bucket_index(ms)
            assert ms <= bucket_upper_ms(i)
            assert bucket_upper_ms(i) >= prev
            prev = bucket_upper_ms(i)
        assert bucket_index(0.0) == 0

    def test_merge_equals_union(self):
        a, b, u = LogHistogram(), LogHistogram(), LogHistogram()
        for v in (0.1, 0.5, 2.0, 2.1):
            a.record(v)
            u.record(v)
        for v in (10.0, 80.0):
            b.record(v)
            u.record(v)
        a.merge(b)
        assert a.counts == u.counts
        assert a.n == u.n and a.max_ms == u.max_ms
        assert a.quantile(0.5) == u.quantile(0.5)

    def test_state_roundtrip_merges_cross_process(self):
        h = LogHistogram()
        for v in (0.2, 5.0, 5.0, 300.0):
            h.record(v)
        h2 = LogHistogram.from_state(
            json.loads(json.dumps(h.state())))
        assert h2.counts == h.counts
        assert h2.quantile(0.9) == h.quantile(0.9)
        # version mismatch -> empty, never silently wrong edges
        bad = h.state()
        bad["v"] = 999
        assert LogHistogram.from_state(bad).n == 0

    def test_snapshot_shape(self):
        h = LogHistogram()
        h.record(1.5)
        snap = h.snapshot()
        for k in ("n", "total_ms", "max_ms", "p50_ms", "p90_ms",
                  "p95_ms", "p99_ms"):
            assert k in snap, k
        assert LogHistogram().snapshot() == {
            "n": 0, "total_ms": 0.0, "max_ms": 0.0}


# ------------------------------------------------------------ flight recorder

class TestFlightRecorder:
    def test_ring_bounds_and_tail_order(self):
        r = FlightRecorder(capacity=4, slow_ms=1e9)
        for i in range(10):
            r.record(i, f"k{i}", 1.0, [["drain", 1.0]])
        assert len(r) == 4
        assert [rec["id"] for rec in r.tail()] == [6, 7, 8, 9]
        assert [rec["id"] for rec in r.tail(2)] == [8, 9]
        assert r.recorded == 10
        assert r.dropped == 6

    def test_explicit_slow_threshold_promotes(self):
        r = FlightRecorder(capacity=8, slow_ms=5.0)
        r.record(1, "fast", 2.0, [])
        r.record(2, "slow", 50.0, [])
        slow = r.slow_log()
        assert [s["id"] for s in slow] == [2]
        assert slow[0]["slow_threshold_ms"] == 5.0
        assert r.slow_promoted == 1

    def test_auto_threshold_arms_at_5x_live_p50(self):
        r = FlightRecorder(capacity=64)
        r.slow_ms = None               # force auto mode (ignore env)
        assert r.slow_threshold_ms() is None    # unarmed: no samples
        for _ in range(30):
            r.record(1, "k", 2.0, [])
        thr = r.slow_threshold_ms()
        assert thr == pytest.approx(5 * r.e2e.quantile(0.5))
        r.record(2, "outlier", thr * 3, [])
        assert [s["id"] for s in r.slow_log()] == [2]

    def test_slow_log_survives_ring_wrap(self):
        r = FlightRecorder(capacity=2, slow_ms=5.0)
        r.record(1, "slow", 99.0, [])
        for i in range(10, 20):
            r.record(i, "fast", 1.0, [])
        assert 1 not in [rec["id"] for rec in r.tail()]
        assert [s["id"] for s in r.slow_log()] == [1]


# ---------------------------------------------------------------- exposition

class TestPromExposition:
    def test_histogram_cumulative_buckets(self):
        h = LogHistogram()
        for v in (0.5, 0.5, 100.0):
            h.record(v)
        out = PromWriter()
        out.histogram("x_ms", h, {"span": "s"})
        text = out.render()
        assert "# TYPE x_ms histogram" in text
        lines = [ln for ln in text.splitlines() if "_bucket" in ln]
        counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
        assert counts == sorted(counts)          # cumulative
        assert counts[-1] == 3
        assert 'x_ms_count{span="s"} 3' in text
        assert '+Inf' in lines[-1]

    def test_summary_from_heartbeat_quantiles(self):
        snap = {"n": 7, "total_ms": 14.0, "p50_ms": 1.0,
                "p90_ms": 2.0, "p95_ms": 2.5, "p99_ms": 3.0,
                "max_ms": 3.3}
        w = PromWriter()
        w.summary("stage_ms", snap, {"stage": "commit"})
        text = w.render()
        assert '{stage="commit",quantile="0.5"} 1.0' in text
        assert '{stage="commit",quantile="0.99"} 3.0' in text
        assert 'stage_ms_count{stage="commit"} 7' in text

    def test_families_grouped_across_interleaved_emits(self):
        """Exposition format: every line of one metric family must be
        contiguous under a single TYPE header even when callers
        interleave families (per-daemon loops over shared names)."""
        w = PromWriter()
        w.metric("age_s", 1.0, {"daemon": "embedder"})
        w.summary("stage_ms", {"n": 1, "total_ms": 1.0, "p50_ms": 1.0},
                  {"daemon": "embedder"})
        w.metric("age_s", 2.0, {"daemon": "completer"})
        w.summary("stage_ms", {"n": 2, "total_ms": 2.0, "p50_ms": 1.0},
                  {"daemon": "completer"})
        lines = w.render().splitlines()
        fams = []
        for ln in lines:
            if ln.startswith("# TYPE"):
                fams.append(ln.split()[2])
        assert fams == ["age_s", "stage_ms"]      # one header each
        # no family's sample appears after another family started
        owner = [("age_s" if ln.startswith("age_s") else "stage_ms")
                 for ln in lines if not ln.startswith("#")]
        assert owner == sorted(owner, key=["age_s",
                                           "stage_ms"].index)

    def test_scalars_skip_non_numeric(self):
        w = PromWriter()
        w.scalars("lane", {"rows": 5, "note": "text",
                           "truncated": True})
        text = w.render()
        assert "lane_rows 5" in text
        assert "note" not in text and "truncated" not in text

    def test_tracer_render_prom(self):
        t = Tracer(enabled=True)
        with t.span("embed.commit"):
            pass
        text = t.render_prom(counters={"staged_lane": {
            "scatter_chunks": 3, "rows_padded": 128}})
        assert 'sptpu_span_ms_bucket{span="embed.commit"' in text
        assert "sptpu_staged_lane_scatter_chunks 3" in text
        assert "sptpu_staged_lane_rows_padded 128" in text

    def test_staged_lane_counters_shape(self):
        from libsplinter_tpu.ops.staged_lane import StagedLane

        lane = StagedLane.__new__(StagedLane)   # no device needed
        lane.full_uploads = 1
        lane.refreshes = 4
        lane.rows_staged = 100
        lane.rows_padded = 128
        lane.scatter_chunks = 2
        lane.ring_dispatches = 1
        lane.ring_chunks = 2
        lane.chunk_hist = {64: 2}
        c = lane.counters()
        assert c["chunks_bucket_64"] == 2
        assert c["ring_dispatches"] == 1
        assert all(isinstance(v, (int, float)) for v in c.values())


# --------------------------------------------------------- tracer quantiles

class TestTracerQuantiles:
    def test_prefix_filter_strips_names(self):
        t = Tracer(enabled=True)
        t.record("embed.drain", 1.0)
        t.record("embed.commit", 2.0)
        t.record("infer.render", 3.0)
        q = t.quantiles("embed.")
        assert set(q) == {"drain", "commit"}
        assert set(t.quantiles()) == {"embed.drain", "embed.commit",
                                      "infer.render"}

    def test_snapshot_keeps_legacy_keys(self):
        t = Tracer(enabled=True)
        with t.span("w"):
            pass
        s = t.snapshot()["w"]
        assert s["n"] == 1
        assert "total_ms" in s and "max_ms" in s and "p50_ms" in s


# ------------------------------------------------------- daemon integration

def _mkstore(tag, nslots=128, max_val=4096):
    name = f"/spt-obs-{tag}"
    Store.unlink(name)
    return name, Store.create(name, nslots=nslots, max_val=max_val,
                              vec_dim=8)


@pytest.fixture
def traced(monkeypatch):
    from libsplinter_tpu.utils.trace import tracer

    monkeypatch.setattr(tracer, "enabled", True)
    tracer.reset()
    yield tracer
    tracer.reset()


def test_embedder_flight_record_reconstructs_request(tmp_path, traced):
    """A client-stamped embed request yields one recorder entry whose
    event sequence is exactly PIPELINE_STAGES, the stamp is consumed,
    and the ring rides KEY_EMBED_TRACE after a heartbeat."""
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"fr-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("req", "trace me")
        st.set_type("req", T_VARTEXT)
        st.label_or("req", P.LBL_EMBED_REQ)
        st.bump("req")
        tid = P.stamp_trace(st, "req")
        assert tid is not None and (tid >> 24) > 0
        assert emb.run_once() == 1

        assert emb.recorder.recorded == 1
        rec = emb.recorder.tail(1)[0]
        assert rec["id"] == tid
        assert rec["key"] == "req"
        assert [e[0] for e in rec["events"]] == list(P.PIPELINE_STAGES)
        assert all(e[1] >= 0.0 for e in rec["events"])
        assert rec["wall_ms"] > 0
        # the stamp was consumed: a second drain records nothing new
        idx = st.find_index("req")
        with pytest.raises(KeyError):
            st.get(P.trace_stamp_key(idx))

        emb.publish_stats()
        ring = json.loads(st.get(P.KEY_EMBED_TRACE).rstrip(b"\0"))
        assert ring["trace"][0]["id"] == tid
        hb = json.loads(st.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        assert "quantiles" in hb and "recorder" in hb
        assert hb["recorder"]["recorded"] == 1
    finally:
        st.close()
        Store.unlink(name)


def test_embedder_slow_log_promotion(tmp_path, traced):
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"slow-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.recorder.slow_ms = 1e-4      # everything is "slow"
        emb.attach()
        st.set("s", "slow one")
        st.set_type("s", T_VARTEXT)
        st.label_or("s", P.LBL_EMBED_REQ)
        st.bump("s")
        P.stamp_trace(st, "s")
        emb.run_once()
        assert emb.recorder.slow_promoted == 1
        emb.publish_stats()
        hb = json.loads(st.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        assert hb["slow_log"][0]["key"] == "s"
        assert hb["slow_log"][0]["slow_threshold_ms"] == 1e-4
    finally:
        st.close()
        Store.unlink(name)


def test_untraced_requests_cost_no_records(tmp_path):
    """Tracing disabled: no stamps read, no records, stage acc off."""
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"off-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("k", "plain")
        st.set_type("k", T_VARTEXT)
        st.label_or("k", P.LBL_EMBED_REQ)
        st.bump("k")
        assert emb.run_once() == 1
        assert emb.recorder.recorded == 0
        assert emb._stage_acc is None
    finally:
        st.close()
        Store.unlink(name)


def test_stale_stamp_never_attributed_to_next_request(tmp_path,
                                                      traced):
    """A stamp that lands AFTER its request was serviced (the client
    lost the race) must not corrupt the NEXT request's flight record:
    the embedded epoch marks it stale and the daemon consumes it."""
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"stale-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("r", "first request")
        st.set_type("r", T_VARTEXT)
        st.label_or("r", P.LBL_EMBED_REQ)
        st.bump("r")
        assert emb.run_once() == 1    # serviced BEFORE any stamp
        stale_tid = P.stamp_trace(st, "r")   # client lost the race

        # next request on the same key, NOT stamped by anyone
        st.set("r", "second request")
        st.label_or("r", P.LBL_EMBED_REQ)
        st.bump("r")
        assert emb.run_once() == 1
        assert emb.recorder.recorded == 0, emb.recorder.tail()
        assert stale_tid not in [rec["id"] for rec in
                                 emb.recorder.tail()]
        # the stale stamp AND its discovery label were consumed, not
        # left to rot (a phantom LBL_TRACED would cost a dead lookup
        # on every future drain of this row)
        idx = st.find_index("r")
        with pytest.raises(KeyError):
            st.get(P.trace_stamp_key(idx))
        assert not st.labels("r") & P.LBL_TRACED
    finally:
        st.close()
        Store.unlink(name)


def test_completer_batched_drain_consumes_stamp(tmp_path, traced):
    """process_batch claims stamped requests through _prepare, which
    consumes the stamp — a later serial request on the same key must
    not inherit it as a phantom flight record."""
    import jax.numpy as jnp

    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)

    name, st = _mkstore(f"bstamp-{tmp_path.name}")
    try:
        model = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                                buckets=(32,), temp=0.0, seed=1)
        comp = Completer(st, model=model, max_new_tokens=4,
                         flush_tokens=2, template="none", batch_cap=4)
        comp.attach()
        st.set("b", "batched prompt")
        st.label_or("b", P.LBL_INFER_REQ)
        P.stamp_trace(st, "b")
        st.bump("b")
        assert comp.run_once() == 1   # batched path: stamp consumed
        idx = st.find_index("b")
        with pytest.raises(KeyError):
            st.get(P.trace_stamp_key(idx))
        assert not st.labels("b") & P.LBL_TRACED
        assert comp.recorder.recorded == 0   # aggregated via spans only
    finally:
        st.close()
        Store.unlink(name)


def test_completer_flight_record_serial_path(tmp_path, traced):
    from libsplinter_tpu.engine.completer import Completer

    name, st = _mkstore(f"comp-{tmp_path.name}")
    try:
        comp = Completer(st, generate_fn=lambda p: iter([b"ok "]),
                         template="none")
        comp.attach()
        st.set("q", "hi")
        st.label_or("q", P.LBL_INFER_REQ)
        st.bump("q")
        tid = P.stamp_trace(st, "q")
        assert comp.run_once() == 1
        rec = comp.recorder.tail(1)[0]
        assert rec["id"] == tid
        assert [e[0] for e in rec["events"]] == list(P.INFER_STAGES)
        comp.publish_stats()
        hb = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert set(P.INFER_STAGES) <= set(hb["quantiles"])
        ring = json.loads(st.get(P.KEY_COMPLETE_TRACE).rstrip(b"\0"))
        assert ring["trace"][0]["id"] == tid
    finally:
        st.close()
        Store.unlink(name)


def test_orphan_stamp_shed_without_followup_request(tmp_path,
                                                    traced):
    """A stamp that lands AFTER its request was serviced, with no
    second request ever arriving on the key, must still be retired:
    the stamp slot's own write surfaces through the dirty mask and
    the daemon's discard path sheds it (no leaked __tr_<idx> slot,
    no permanent LBL_TRACED)."""
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"orph-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("o", "serviced before stamp")
        st.set_type("o", T_VARTEXT)
        st.label_or("o", P.LBL_EMBED_REQ)
        st.bump("o")
        assert emb.run_once() == 1
        P.stamp_trace(st, "o")        # too late: request already done
        emb.run_once()                # stamp slot in the dirty mask
        idx = st.find_index("o")
        with pytest.raises(KeyError):
            st.get(P.trace_stamp_key(idx))
        assert not st.labels("o") & P.LBL_TRACED
        assert emb.recorder.recorded == 0
    finally:
        st.close()
        Store.unlink(name)


def test_orphan_shed_leaves_pending_infer_stamp(tmp_path, traced):
    """The embedder's orphan shed must NOT retire a stamp whose
    request is still pending for the OTHER daemon (LBL_INFER_REQ)."""
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"xd-{tmp_path.name}")
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("q", "a completion request")
        st.label_or("q", P.LBL_INFER_REQ)
        P.stamp_trace(st, "q")
        st.bump("q")
        emb.run_once()                # embedder drains the dirty bits
        idx = st.find_index("q")
        assert st.get(P.trace_stamp_key(idx))   # stamp survives
        assert st.labels("q") & P.LBL_TRACED
    finally:
        st.close()
        Store.unlink(name)


def test_trace_ring_publish_shrinks_to_fit(tmp_path):
    """An oversized flight-recorder ring publishes a SHORTER tail
    (halving until it fits max_val), never an empty key: `spt trace
    tail` must keep working exactly when there is the most data."""
    name = f"/spt-obs-ring-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=1024, vec_dim=8)
    try:
        r = FlightRecorder(capacity=64, slow_ms=1e9)
        for i in range(40):
            r.record((7 << 24) | i, f"key/{i}", 12.345,
                     [[s, 1.234] for s in P.PIPELINE_STAGES])
        P.publish_trace_ring(st, "__ring", r)
        snap = json.loads(st.get("__ring").rstrip(b"\0"))
        got = snap["trace"]
        assert 1 <= len(got) < 32
        assert got[-1]["id"] == (7 << 24) | 39   # newest survive
    finally:
        st.close()
        Store.unlink(name)


# ------------------------------------------------------------------- CLI

def test_cli_metrics_and_trace_tail(tmp_path, traced, monkeypatch,
                                    capsys):
    from libsplinter_tpu.cli.main import main
    from libsplinter_tpu.engine.embedder import Embedder

    name, st = _mkstore(f"cli-{tmp_path.name}")
    monkeypatch.setenv("SPTPU_DEFAULT_STORE", name)
    monkeypatch.delenv("SPTPU_NS_PREFIX", raising=False)
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("k", "metric me")
        st.set_type("k", T_VARTEXT)
        st.label_or("k", P.LBL_EMBED_REQ)
        st.bump("k")
        tid = P.stamp_trace(st, "k")
        emb.run_once()
        emb.publish_stats()

        assert main(["metrics"]) == 0
        out = capsys.readouterr().out
        assert "# TYPE sptpu_store_parse_failures counter" in out
        assert "sptpu_embedder_embedded 1" in out
        assert 'sptpu_stage_ms{daemon="embedder",stage="commit"' in out
        assert "sptpu_heartbeat_age_seconds" in out

        assert main(["trace", "tail", "4"]) == 0
        out = capsys.readouterr().out
        assert f"id={tid:#x}" in out
        assert "drain=" in out and "commit=" in out

        # empty-store UX: no recorder ring is a message, not an error
        st2_name, st2 = _mkstore(f"cli2-{tmp_path.name}")
        st2.close()
        monkeypatch.setenv("SPTPU_DEFAULT_STORE", st2_name)
        assert main(["trace", "tail"]) == 0
        assert "no traced requests" in capsys.readouterr().out
        Store.unlink(st2_name)
    finally:
        st.close()
        Store.unlink(name)
