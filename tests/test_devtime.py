"""Device-time & compile attribution tier (`make compile-check`): the
named-program registry (obs/devtime.py) — compile-event ledgering
with warmup/runtime cause split, dispatch marks and the warmup
exclusion, the `__compile_<i>` store ring and its cross-restart
generation visibility, span schema v3 (device_ms / dispatch_queue),
tail-based span retention, the Perfetto export's device + compile
tracks, replica-suffixed devtime heartbeat discovery (SPL105
discipline), and the seeded-recompile drill that proves the gate
script can actually fail."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.obs import spans as S
from libsplinter_tpu.obs.devtime import (DevtimeRegistry, close_mark,
                                         collect_compile_events)

GATE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "compile_gate_check.py")


class FakeJit:
    """A callable with the jit private cache API: `grow` scripts when
    a call 'compiles' (cache size bump)."""

    def __init__(self, result=None):
        self.cache = 0
        self.grow_next = False
        self.result = result if result is not None \
            else np.zeros((2,), np.float32)
        self.calls = 0

    def _cache_size(self):
        return self.cache

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.grow_next:
            self.cache += 1
            self.grow_next = False
        return self.result


@pytest.fixture
def reg():
    return DevtimeRegistry()


# --------------------------------------------- ledger + cause split

class TestCompileLedger:
    def test_warmup_vs_runtime_cause(self, reg):
        fn = FakeJit()
        w = reg.register("completer.chunk", fn)
        with reg.warmup_phase():
            fn.grow_next = True
            w(np.ones((4, 8), np.int32))
        assert reg.compile_events() == 0          # warmup is free
        fn.grow_next = True
        w(np.ones((4, 16), np.int32))
        assert reg.compile_events() == 1
        assert reg.compile_events("completer") == 1
        assert reg.compile_events("embedder") == 0
        evs = reg.pending_events()
        assert [e["cause"] for e in evs] == ["warmup", "runtime"]
        rt = evs[1]
        assert rt["program"] == "completer.chunk"
        assert rt["lane"] == "completer"
        assert "int32[4, 16]" in rt["shapes_key"]
        assert rt["duration_ms"] >= 0
        assert rt["generation"] == reg.generation

    def test_no_growth_no_event(self, reg):
        fn = FakeJit()
        w = reg.register("searcher.topk", fn)
        for _ in range(5):
            w(np.ones((8,), np.float32))
        assert reg.pending_events() == []
        assert reg.compile_events() == 0

    def test_non_jit_callable_never_ledgers(self, reg):
        calls = []
        w = reg.register("embedder.encode",
                         lambda x: calls.append(x) or
                         np.zeros((1,), np.float32))
        w("text")
        assert calls == ["text"] and reg.pending_events() == []

    def test_reregister_same_name_reuses_program(self, reg):
        a, b = FakeJit(), FakeJit()
        reg.register("completer.trunk", a)
        reg.register("completer.trunk", b)  # lru_cache factory rerun
        assert list(reg._progs) == ["completer.trunk"]

    def test_kill_switch_returns_fn_untouched(self, monkeypatch):
        monkeypatch.setenv("SPTPU_DEVTIME", "0")
        off = DevtimeRegistry()
        fn = FakeJit()
        assert off.register("completer.chunk", fn) is fn
        assert fn.__wrapped__ is fn        # unwrap stays unconditional


# ------------------------------------------ marks + warmup exclusion

class TestDispatchMarks:
    def test_warmup_opens_no_device_window(self, reg):
        fn = FakeJit(result=object())      # async-ish: not ndarray
        w = reg.register("completer.chunk", fn)
        with reg.warmup_phase():
            w()
        assert reg.take_mark("completer.chunk") is None
        assert reg.take_lane_ms("completer") == 0.0

    def test_async_result_leaves_mark_for_collect_point(self, reg):
        fn = FakeJit(result=object())
        w = reg.register("completer.paged_chunk", fn)
        w()
        mark = reg.take_mark("completer.paged_chunk")
        assert mark is not None
        assert reg.take_mark("completer.paged_chunk") is None  # popped
        time.sleep(0.002)
        ms = mark.close()
        assert ms >= 2.0
        assert mark.close() == 0.0                 # idempotent
        assert reg.take_lane_ms("completer") >= 2.0
        assert reg.take_lane_ms("completer") == 0.0  # popped
        close_mark(None)                           # None-safe helper

    def test_sync_ndarray_result_closes_inline(self, reg):
        w = reg.register("searcher.topk",
                         FakeJit(result=np.zeros((4,), np.float32)))
        w()
        assert reg.take_mark("searcher.topk") is None
        assert reg.take_lane_ms("searcher") > 0.0

    def test_heartbeat_section_and_share(self, reg):
        fn = FakeJit(result=np.zeros((2,), np.float32))
        w = reg.register("completer.chunk", fn)
        fn.grow_next = True
        w()
        w()
        sec = reg.heartbeat_section("completer")
        assert sec["chunk"]["n"] == 2
        assert sec["chunk"]["compiles"] == 1
        assert sec["chunk"]["runtime_compiles"] == 1
        assert sec["chunk"]["p99_ms"] >= sec["chunk"]["p50_ms"] >= 0
        assert reg.heartbeat_section("embedder") == {}
        assert 0.0 <= reg.device_ms_share() <= 1.0


# --------------------------------------------------- the store ring

class TestCompileRing:
    def _seed(self, reg, name, shapes=((4,),)):
        fn = FakeJit()
        w = reg.register(name, fn)
        for shp in shapes:
            fn.grow_next = True
            w(np.ones(shp, np.int32))

    def test_flush_and_collect(self, reg, store):
        self._seed(reg, "completer.chunk", ((4,), (8,)))
        assert reg.flush(store) == 2
        assert reg.pending_events() == []          # drained
        assert reg.flush(store) == 0
        evs = collect_compile_events(store)
        assert len(evs) == 2
        assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
        assert {e["program"] for e in evs} == {"completer.chunk"}
        assert store.get_uint(P.KEY_COMPILE_HEAD) == 2

    def test_ring_bounded_oldest_overwritten(self, reg, store):
        n = S.span_ring_size(store)
        for i in range(n + 3):
            self._seed(reg, "completer.chunk", ((i + 1,),))
        reg.flush(store)
        evs = collect_compile_events(store)
        assert len(evs) == n                      # bounded ring
        assert int(store.get_uint(P.KEY_COMPILE_HEAD)) == n + 3

    def test_generation_bump_survives_restart(self, reg, store):
        """The crash/restart drill: generation 0's events stay in the
        ring; the restarted process (fresh registry state, generation
        synced from the lane's bumped supervision counter) lands its
        under the new generation — the ring tells the two lives
        apart."""
        self._seed(reg, "completer.chunk")
        reg.flush(store)
        # supervised restart: attach() syncs the registry generation
        # from bump_generation, and the re-exec resets in-process state
        reg.reset()
        g = P.bump_generation(store, P.KEY_COMPLETE_STATS)
        reg.generation = max(reg.generation, g)
        assert reg.generation >= 1
        self._seed(reg, "completer.chunk")        # factory re-runs
        reg.flush(store)
        gens = [e["generation"] for e in
                collect_compile_events(store)]
        assert len(gens) == 2 and gens[0] == 0 and gens[1] >= 1

    def test_flush_full_store_degrades_quietly(self, reg):
        self._seed(reg, "completer.chunk")
        class Dead:
            def __contains__(self, k):
                raise OSError("full")
        assert reg.flush(Dead()) == 0             # never raises
        assert reg.compile_events() == 1          # counters keep truth


# ------------------------------------- span schema v3 + tail spans

class TestSpanV3:
    def test_device_ms_split(self, store):
        w = S.SpanWriter(store, "completer", eager=True)
        store.set("req", "x")
        tid = P.stamp_trace(store, "req")
        idx = store.find_index("req")
        pend = w.begin(idx, store.epoch_at(idx))
        time.sleep(0.005)
        assert w.commit(pend, device_ms=2.0)
        rec = S.collect_spans(store, tid)[0]
        assert rec["device_ms"] == 2.0
        assert rec["dispatch_queue"] == pytest.approx(
            rec["service_ms"] - 2.0, abs=0.01)
        assert rec["dispatch_queue"] >= 0

    def test_no_device_window_no_v3_fields(self, store):
        w = S.SpanWriter(store, "completer", eager=True)
        store.set("req", "x")
        tid = P.stamp_trace(store, "req")
        idx = store.find_index("req")
        assert w.commit(w.begin(idx, store.epoch_at(idx)),
                        device_ms=0.0)
        rec = S.collect_spans(store, tid)[0]
        assert "device_ms" not in rec
        assert "dispatch_queue" not in rec

    def test_tail_span_resolves_by_trace_id(self, store):
        w = S.SpanWriter(store, "completer", eager=True)
        tid = w.tail_span("slow/key", 120.0,
                          stages={"decode": 100.0, "flush": 20.0},
                          extra={"tokens": 7}, device_ms=80.0)
        assert tid is not None
        recs = S.collect_spans(store, tid)
        assert len(recs) == 1
        rec = recs[0]
        assert rec["tail"] is True
        assert rec["key"] == "slow/key"
        assert rec["service_ms"] == pytest.approx(120.0, abs=15.0)
        assert rec["stages"] == {"decode": 100.0, "flush": 20.0}
        assert rec["tokens"] == 7 and rec["device_ms"] == 80.0
        # the tree renders standalone (slow-log `spt trace show` path)
        tree = S.assemble_tree(recs)
        assert tree["tid"] == tid
        assert tree["root"]["span"]["lane"] == "completer"

    def test_chrome_trace_device_and_compile_tracks(self):
        now = time.time()
        spans = [{"tid": 7, "span": 7, "parent": 0,
                  "lane": "completer", "key": "k", "status": "ok",
                  "t_queue": now - 0.02, "t_admit": now - 0.01,
                  "queue_ms": 10.0, "service_ms": 10.0,
                  "device_ms": 6.0, "dispatch_queue": 4.0}]
        compiles = [{"program": "completer.chunk",
                     "lane": "completer", "shapes_key": "(int32[4])",
                     "duration_ms": 12.5, "generation": 1,
                     "cause": "runtime", "ts": now}]
        doc = S.to_chrome_trace(spans, compile_events=compiles)
        evs = doc["traceEvents"]
        host = [e for e in evs if e.get("cat") == "span"]
        dev = [e for e in evs if e.get("cat") == "device"]
        comp = [e for e in evs if e.get("cat") == "compile"]
        assert len(host) == len(dev) == len(comp) == 1
        # three DISTINCT tracks: host lane, device lane, compile
        assert len({host[0]["pid"], dev[0]["pid"], comp[0]["pid"]}) \
            == 3
        assert comp[0]["ph"] == "i"
        assert comp[0]["args"]["shapes_key"] == "(int32[4])"
        # the device slice sits at the TAIL of the service window
        assert dev[0]["ts"] == pytest.approx(
            host[0]["ts"] + 4.0 * 1e3, abs=1.0)
        names = {e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert {"lane:completer", "device:completer",
                "compiles"} <= names
        assert doc["otherData"]["compile_events"] == 1


# ------------------------------- replica-suffixed devtime discovery

class TestReplicaDevtimeKeys:
    def test_devtime_sections_discovered_per_replica(self, store):
        """SPL105 discipline: a reader that hardcodes the base
        heartbeat key misses replica N's devtime/compile counters —
        discovery must go through replica_heartbeat_keys."""
        base = P.KEY_COMPLETE_STATS
        for r in (0, 1):
            snap = {"pid": os.getpid(), "ts": time.time(),
                    "replica": r,
                    "compile_events": r,       # distinct per replica
                    "devtime": {"chunk": {"n": 5 + r, "compiles": 1,
                                          "runtime_compiles": r}}}
            key = P.replica_stats_key(base, r)
            store.set(key, json.dumps(snap))
            # heartbeats are debug-labeled: the bloom prefilter IS
            # the discovery path (replica_heartbeat_map enumerates
            # LBL_DEBUG, never walks per-base key guesses)
            store.label_or(key, P.LBL_DEBUG)
        found = {}
        for r, key in P.replica_heartbeat_keys(store, base):
            snap = json.loads(store.get(key).rstrip(b"\0"))
            found[r] = (snap["compile_events"],
                        snap["devtime"]["chunk"]["n"])
        assert found == {0: (0, 5), 1: (1, 6)}


# ------------------------------------------- the gate's own drills

@pytest.mark.slow
class TestGateScript:
    def _run(self, *args):
        env = dict(os.environ)
        env.pop("SPTPU_SEED_RECOMPILE", None)
        env.pop("SPTPU_DEVTIME", None)
        return subprocess.run(
            [sys.executable, GATE, *args], env=env,
            capture_output=True, text=True, timeout=900)

    def test_clean_gate_passes(self):
        p = self._run()
        assert p.returncode == 0, p.stderr
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["value"] == 0 and rec["warmup_events"] > 0

    def test_seeded_recompile_is_caught_by_name(self):
        p = self._run("--seed-recompile")
        assert p.returncode == 0, p.stderr
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["value"] > 0 and rec["ok"]
        progs = {g["program"] for g in rec["guilty"]}
        assert any(pr.startswith("completer.") for pr in progs)
        assert all(g["shapes_key"] for g in rec["guilty"])
