"""int4-PACKED paged KV pools + sharded speculative decode + int8
per-output-channel weight residency (PR 20 — the quantization tier).

Numeric tolerance contract: per-page symmetric int4 puts every stored
element within d/2 of its float value, d = page-absmax/7 — 16x coarser
than int8's grid (<= 7.2% of the page's max magnitude vs 0.4%).  The
in-register nibble-unpack dequant is EXACT against the f32 kernel over
host-dequantized pools (DEQ_TOL), so all int4 error is quantization
error.  Token-level greedy agreement is pinned LOOSER than int8's 75%:
>= 50% over 13 tokens on the tiny random model, first token exact
(prefill logits come from the dense f32 scratch pass and only commit
through the pool afterwards — byte-identical across kv dtypes).

Spec-paged x tensor-parallel (tentpole b): under a tp=2 CPU mesh the
fused propose-verify-accept step is BYTE-EXACT to the target's own
greedy sequence over f32 pools (the structural spec contract — now
holding with both pools kv-head-sharded and out_shardings pinned), and
int8 pools stay byte-exact at this pinned seed.  int4 spec carries a
documented agreement tolerance instead: a REJECTED draft's ingest can
raise a page's monotone scale before the host rewind, re-rounding
accepted history on the 16x-coarser grid — plain decode never sees
that scale (same mechanism test_quant_kv documents for int8, where the
fine grid happens not to flip an argmax here).

Weight quant (tentpole c): ChannelQuantDense round-trips its own grid
losslessly, per-element error <= d/2 (d = column-absmax/127), prefill
argmax preserved on the tiny model, greedy agreement >= 25% over 16
tokens (random weights leave near-zero logit gaps, so token flips are
expected and harmless; real checkpoints have real margins).

`make quant-check` runs this file plus scripts/quant_pool_bytes_check
(int4 == 1/4 bf16 == 1/8 f32 from placed buffers).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import (CompletionModel,
                                            DecoderConfig, PagedKVCache,
                                            _quant_append)
from libsplinter_tpu.models.speculative import (SpeculativeCompletionModel,
                                                self_draft_model)
from libsplinter_tpu.ops.paged_attention import (INT4_QMAX,
                                                 dequantize_pool,
                                                 pack_int4,
                                                 paged_attention,
                                                 unpack_int4)

ATOL = 0.35          # int4-vs-f32 attention output bound (unit-scale;
                     # 16x int8's grid — measured headroom ~2x)
DEQ_TOL = 2e-5       # in-register nibble dequant vs host dequant


def _build_paged(rng, lengths, *, KH, D, page, P, shuffle=True):
    B = len(lengths)
    n_blocks = 1 + sum(-(-int(l) // page) or 1 for l in lengths)
    kp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    vp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    tables = np.zeros((B, P), np.int32)
    ids = list(range(1, n_blocks))
    if shuffle:
        rng.shuffle(ids)
    for b in range(B):
        for p in range(-(-int(lengths[b]) // page)):
            tables[b, p] = ids.pop()
    return kp, vp, tables


def _quantize4(pool):
    """Per-(page, kv head) symmetric int4 codes + PACKED bytes."""
    d = np.abs(pool).max(axis=(2, 3)) / INT4_QMAX
    d = np.where(d == 0, 1.0, d)
    q = np.clip(np.round(pool / d[:, :, None, None]), -INT4_QMAX,
                INT4_QMAX).astype(np.int32)
    packed = np.asarray(pack_int4(jnp.asarray(q)))
    return packed, d.astype(np.float32)


# --------------------------------------------------- pack primitives


def test_pack_unpack_roundtrip_exact():
    """pack_int4/unpack_int4 are exact inverses over the full signed
    code range [-8, 7] (offset-8 storage: garbage tails decode to -8,
    inside the representable grid, never wrapping)."""
    rng = np.random.RandomState(0)
    codes = rng.randint(-8, 8, size=(3, 2, 8, 16)).astype(np.int32)
    packed = np.asarray(pack_int4(jnp.asarray(codes)))
    assert packed.dtype == np.uint8
    assert packed.shape == (3, 2, 8, 8)          # D/2 last axis
    back = np.asarray(unpack_int4(jnp.asarray(packed)))
    np.testing.assert_array_equal(back, codes.astype(np.float32))


def test_split_half_nibble_layout():
    """The packed layout is SPLIT-HALF, not interleaved: byte j holds
    element j (low nibble) and element j + D/2 (high nibble) — the
    unpack is one lane-dim concatenate, the TPU-friendly shape."""
    codes = np.zeros((1, 1, 1, 4), np.int32)
    codes[0, 0, 0] = [1, 2, 3, 4]
    packed = np.asarray(pack_int4(jnp.asarray(codes)))[0, 0, 0]
    # low nibbles: elements 0,1 (+8 bias); high nibbles: elements 2,3
    assert [int(b & 0xF) - 8 for b in packed] == [1, 2]
    assert [int(b >> 4) - 8 for b in packed] == [3, 4]


# ------------------------------------------------------------ kernel


@pytest.mark.parametrize("lengths,page,P", [
    ([1, 8, 7, 19], 8, 4),
])
def test_int4_kernel_parity_ragged(lengths, page, P):
    """Packed int4 kernel within ATOL of the f32 kernel across the
    ragged length classes — and the in-register nibble dequant is
    EXACT vs host-unpacked pools (kernel error separated from
    quantization error, like the int8 bar)."""
    rng = np.random.RandomState(7)
    KH, H, D = 2, 4, 16
    kp, vp, tables = _build_paged(rng, lengths, KH=KH, D=D,
                                  page=page, P=P)
    kq, ks = _quantize4(kp)
    vq, vs = _quantize4(vp)
    q = rng.randn(len(lengths), H, D).astype(np.float32)
    args = (jnp.asarray(tables), jnp.asarray(lengths, np.int32))
    ref = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), *args,
        interpret=True))
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq), *args,
        k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs),
        interpret=True))
    assert np.abs(out - ref).max() < ATOL
    deq = np.asarray(paged_attention(
        jnp.asarray(q),
        dequantize_pool(jnp.asarray(kq), jnp.asarray(ks)),
        dequantize_pool(jnp.asarray(vq), jnp.asarray(vs)),
        *args, interpret=True))
    np.testing.assert_allclose(out, deq, rtol=DEQ_TOL, atol=DEQ_TOL)


def test_int4_kernel_gqa_dead_rows_multiquery():
    """GQA grouping (rep=3), a dead row, AND the multi-query verify
    stack over one packed pool: token t of the stacked dispatch
    equals a single-token call at lengths + t."""
    rng = np.random.RandomState(11)
    lengths = np.array([9, 0, 4], np.int32)
    KH, H, D, page, P, S = 2, 6, 8, 4, 4, 3
    kp, vp, tables = _build_paged(rng, lengths, KH=KH, D=D,
                                  page=page, P=P)
    kq, ks = _quantize4(kp)
    vq, vs = _quantize4(vp)
    kw = dict(k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs),
              interpret=True)
    q = rng.randn(3, H, D).astype(np.float32)
    args = (jnp.asarray(tables), jnp.asarray(lengths))
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq), *args, **kw))
    assert np.isfinite(out).all()
    assert np.abs(out[1]).max() == 0.0           # dead row: zeros
    qm = rng.randn(3, S, H, D).astype(np.float32)
    stack = np.asarray(paged_attention(
        jnp.asarray(qm), jnp.asarray(kq), jnp.asarray(vq), *args,
        **kw))
    for t in range(S):
        single = np.asarray(paged_attention(
            jnp.asarray(qm[:, t]), jnp.asarray(kq), jnp.asarray(vq),
            jnp.asarray(tables), jnp.asarray(lengths + t), **kw))
        np.testing.assert_allclose(stack[:, t], single, rtol=1e-5,
                                   atol=1e-5)


# ----------------------------------------------------- pool numerics


def test_int4_append_rescale_unit():
    """_quant_append over a PACKED pool: every live element stays
    within one full step of the final page scale even when growing
    magnitudes force a rescale on every append (same bound shape as
    the int8 unit test, at the int4 grid)."""
    rng = np.random.RandomState(0)
    page, KH, D = 8, 2, 4
    pool = jnp.zeros((3, KH, page, D // 2), jnp.uint8)
    scales = jnp.zeros((3, KH), jnp.float32)
    toks = [rng.randn(1, KH, D).astype(np.float32) * (1 + 0.5 * i)
            for i in range(page)]
    bids = jnp.asarray([1], jnp.int32)
    for i, x in enumerate(toks):
        pool, scales = _quant_append(pool, scales, bids,
                                     jnp.asarray([i], np.int32),
                                     jnp.asarray(x))
    assert pool.dtype == jnp.uint8               # stayed packed
    deq = np.asarray(dequantize_pool(pool, scales))[1]
    want = np.concatenate(toks, 0).transpose(1, 0, 2)
    step = np.asarray(scales)[1][:, None, None]
    assert (np.abs(deq - want) <= step + 1e-7).all()
    assert (np.asarray(scales)[1]
            >= np.abs(want).max((1, 2)) / INT4_QMAX - 1e-7).all()


def test_int4_append_offset0_resets_stale_scale():
    """Pool reuse at the packed layout: offset-0 writes treat the
    page as fresh, so a tiny token after a huge previous owner
    quantizes at its own scale (not rounded to zero forever)."""
    rng = np.random.RandomState(1)
    page, KH, D = 8, 2, 4
    pool = jnp.zeros((2, KH, page, D // 2), jnp.uint8)
    scales = jnp.zeros((2, KH), jnp.float32)
    bids = jnp.asarray([1], jnp.int32)
    big = rng.randn(1, KH, D).astype(np.float32) * 100.0
    pool, scales = _quant_append(pool, scales, bids,
                                 jnp.asarray([0], np.int32),
                                 jnp.asarray(big))
    assert np.asarray(scales)[1].min() > 0.1
    small = rng.randn(1, KH, D).astype(np.float32) * 0.01
    pool, scales = _quant_append(pool, scales, bids,
                                 jnp.asarray([0], np.int32),
                                 jnp.asarray(small))
    deq = np.asarray(dequantize_pool(pool, scales))[1][:, 0]
    d_own = np.abs(small[0]).max(-1, keepdims=True) / INT4_QMAX
    assert (np.abs(deq - small[0]) <= d_own / 2 + 1e-9).all()


@pytest.fixture(scope="module")
def model():
    return CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(16, 32), temp=0.0, seed=1)


def test_int4_commit_roundtrip_error_budget(model):
    """paged_prefill_row through the PACKING commit program:
    dequantized pages reproduce the f32 pool's pages within d/2 per
    element, d = that page's absmax/7."""
    m = model
    prompt = np.arange(1, 14, dtype=np.int32)
    cf = m.init_paged(2, page=16, kv_dtype="f32")
    ci = m.init_paged(2, page=16, kv_dtype="int4")
    assert ci.packed and ci.quantized
    assert ci.k_pools[0].dtype == jnp.uint8
    assert int(ci.k_pools[0].shape[3]) == m.cfg.head_dim // 2
    m.paged_prefill_row(cf, prompt, 0)
    m.paged_prefill_row(ci, prompt, 0)
    P = len(prompt)
    for layer in range(m.cfg.layers):
        for pools_f, pools_q, scales in (
                (cf.k_pools, ci.k_pools, ci.k_scales),
                (cf.v_pools, ci.v_pools, ci.v_scales)):
            bid = int(cf.tables[0, 0])
            bid_q = int(ci.tables[0, 0])
            f = np.asarray(pools_f[layer])[bid][:, :P]
            deq = np.asarray(dequantize_pool(
                pools_q[layer], scales[layer]))[bid_q][:, :P]
            d = np.asarray(scales[layer])[bid_q][:, None, None]
            assert (np.abs(deq - f) <= d / 2 + 1e-7).all(), layer
    cf.reset()
    ci.reset()


def test_int4_paged_decode_token_agreement(model):
    """Greedy chunked paged decode over the packed pool: first token
    exact (dense scratch prefill is dtype-independent), a >= 4-token
    exact prefix, and >= 30% agreement with f32 over 13 tokens (the
    documented int4 bar — the 16x-coarser grid flips argmaxes the
    int8 grid does not, and once one token flips on a random tiny
    model the tails diverge; measured 0.38 at this seed)."""
    m = model
    A = np.arange(1, 8, dtype=np.int32)
    outs = {}
    for kvd in ("f32", "int4"):
        cache = m.init_paged(2, page=16, kv_dtype=kvd)
        lg = m.paged_prefill_row(cache, A, 0)
        out = [int(np.argmax(lg))]
        toks = np.array([out[0], 0], np.int32)
        for _ in range(4):
            blk = m.paged_decode_chunk(cache, toks, 3)
            out += [int(x) for x in blk[0]]
            toks = blk[:, -1].astype(np.int32)
        outs[kvd] = out
        cache.reset()
    agree = np.mean([a == b for a, b in zip(outs["f32"],
                                            outs["int4"])])
    assert outs["f32"][0] == outs["int4"][0]
    prefix = 0
    for a, b in zip(outs["f32"], outs["int4"]):
        if a != b:
            break
        prefix += 1
    assert prefix >= 4, (prefix, outs)
    assert agree >= 0.3, (agree, outs)


def test_int4_warmup_pins_compile_count(model):
    """The packed program set (prefill scratch + packing commit +
    packed-pool chunk) warms like int8: join/finish/join after
    warmup_paged compiles NOTHING new."""
    m = model
    cache = m.init_paged(2, page=16, kv_dtype="int4")
    m.warmup_paged(cache, chunk=4)
    base = m.compile_count()
    assert base > 0
    for prompt in (np.array([1, 2, 3], np.int32),
                   np.arange(1, 12, dtype=np.int32)):
        lg = m.paged_prefill_row(cache, prompt, 0)
        toks = np.array([int(np.argmax(lg)), 0], np.int32)
        m.paged_decode_chunk(cache, toks, 4)
        m.paged_prefill_row(cache, np.array([7, 7], np.int32), 1)
        m.paged_decode_chunk(cache, toks, 4)
        cache.free_row(0)
        cache.free_row(1)
    assert m.compile_count() == base, \
        "packed paged steady state recompiled on join/finish/join"


def test_pool_bytes_quarter(model):
    """device_mb MEASURED from placed buffers: int4 == 1/4 bf16 ==
    1/8 f32 == 1/2 int8 for the same page count (within 10%), and
    kv_bytes_per_token halves vs int8 exactly."""
    m = model
    mb = {}
    caches = {}
    for kvd in ("f32", "bf16", "int8", "int4"):
        c = m.init_paged(2, page=16, pool_pages=16, kv_dtype=kvd)
        mb[kvd] = c.device_mb()
        caches[kvd] = c
    assert abs(mb["int4"] / mb["bf16"] - 0.25) < 0.1, mb
    assert abs(mb["int4"] / mb["f32"] - 0.125) < 0.1, mb
    assert abs(mb["int4"] / mb["int8"] - 0.5) < 0.1, mb
    assert caches["int4"].kv_bytes_per_token() * 2 == \
        caches["int8"].kv_bytes_per_token()
    # the headline capacity claim: batch 256 of int4 pages fits the
    # HBM envelope batch 64 of bf16 pages occupies (4x pages/byte).
    # The tiny fixture overstates the per-page f32 scale overhead
    # (16 scale bytes vs 256 packed page bytes = 6%; at production
    # head_dim=128/page=128 it is 0.05%) — hence the 10% allowance.
    assert 4 * mb["int4"] <= mb["bf16"] * 1.10


def test_int4_requires_even_head_dim():
    cfg = dataclasses.replace(DecoderConfig.tiny(dtype=jnp.float32),
                              hidden=28)      # heads=4 -> head_dim 7
    with pytest.raises(ValueError, match="must be even"):
        PagedKVCache(cfg, 2, page=16, kv_dtype="int4")


# ------------------------------------------------ packed wire + tier


def test_int4_wire_roundtrip_and_bytes_halve(model):
    """The handoff/tier wire carries PACKED bytes verbatim: export →
    adopt into a second pool reproduces pool pages and scales
    byte-for-byte, and page_wire_bytes is half the int8 wire."""
    m = model
    prompt = np.arange(1, 20, dtype=np.int32)
    src = m.init_paged(2, page=16, kv_dtype="int4")
    i8 = m.init_paged(2, page=16, kv_dtype="int8")
    assert m.page_wire_bytes(src) * 2 == m.page_wire_bytes(i8)
    assert m._page_wire_dtype(src) == np.dtype("uint8")
    m.paged_prefill_row(src, prompt, 0)
    pages, scales = m.export_row_pages(src, 0)
    dst = m.init_paged(2, page=16, kv_dtype="int4")
    assert m.paged_adopt_row(dst, 1, len(prompt), pages, scales)
    for layer in range(m.cfg.layers):
        sb = int(src.tables[0, 0])
        db = int(dst.tables[1, 0])
        np.testing.assert_array_equal(
            np.asarray(src.k_pools[layer][sb]),
            np.asarray(dst.k_pools[layer][db]))
        np.testing.assert_array_equal(
            np.asarray(src.v_scales[layer][sb]),
            np.asarray(dst.v_scales[layer][db]))
    # byte-exact continuation: same next tokens from either pool
    toks = np.array([int(prompt[-1]), int(prompt[-1])], np.int32)
    src.lengths[0] = len(prompt) - 1
    dst.lengths[1] = len(prompt) - 1
    a = np.asarray(m.paged_decode_chunk(src, toks, 4))[0]
    b = np.asarray(m.paged_decode_chunk(dst, toks, 4))[1]
    np.testing.assert_array_equal(a, b)


# ------------------------------------------- sharded int4 (tp mesh)


@pytest.mark.slow
def test_sharded_int4_paged_token_exact(model):
    """Packed pools + tensor parallelism: the tp=2-sharded int4 path
    (packing narrows only the UNSHARDED last axis, so kv_pool_sharding
    applies unchanged) is token-exact with single-chip int4."""
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)

    base = model
    mesh = make_mesh(dp=4, tp=2)
    tp = ShardedCompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), mesh,
        params=base.params, buckets=(16, 32), temp=0.0, seed=1)
    A = np.arange(1, 8, dtype=np.int32)

    def run(m):
        cache = m.init_paged(2, page=16, kv_dtype="int4")
        if m is tp:
            assert cache.packed
            assert tuple(cache.k_pools[0].sharding.spec) \
                == (None, "tp", None, None)
            assert tuple(cache.k_scales[0].sharding.spec) \
                == (None, "tp")
        lg = m.paged_prefill_row(cache, A, 0)
        out = [int(np.argmax(lg))]
        toks = np.array([out[0], 0], np.int32)
        for _ in range(3):
            blk = m.paged_decode_chunk(cache, toks, 3)
            out += [int(x) for x in blk[0]]
            toks = blk[:, -1].astype(np.int32)
        cache.reset()
        return out

    assert run(base) == run(tp)


# ------------------------------- spec-paged under tensor parallelism


def _greedy_paged(m, prompt, *, chunk=4, n_chunks=3, batch=4):
    cache = m.init_paged(batch, page=8)
    lg = m.paged_prefill_row(cache, prompt, 0)
    out = [int(np.argmax(np.asarray(lg)))]
    for _ in range(n_chunks):
        t = np.full((batch,), -1, np.int32)
        t[0] = out[-1]
        blk = np.asarray(m.paged_decode_chunk(cache, t, chunk))
        out += [int(x) for x in blk[0]]
    return out, cache


@pytest.mark.slow
@pytest.mark.parametrize("kvd", ["f32", "int8", "int4"])
def test_spec_paged_tp2_greedy(model, kvd):
    """Tentpole (b): spec-paged decode under a tp=2 CPU mesh — the
    demotion guard is gone, both halves' pools shard on kv heads, and
    greedy output is BYTE-EXACT to target-greedy over f32 pools (the
    structural spec contract) and over int8 at this pinned seed.
    int4 pins first-token exactness + >= 4-token common prefix + the
    packed/sharded invariants instead: a rejected draft's ingest can
    raise the monotone page scale pre-rewind, and re-rounding on the
    16x-coarser grid flips argmaxes (the documented int4 spec
    tolerance; same mechanism as test_quant_kv's int8 note)."""
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)

    prompt = np.arange(2, 14, dtype=np.int32)
    base = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(16, 32), temp=0.0, seed=1,
                           kv_dtype=kvd)
    want, _ = _greedy_paged(base, prompt)

    mesh = make_mesh(dp=4, tp=2)
    tgt = ShardedCompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), mesh=mesh,
        buckets=(16, 32), temp=0.0, seed=1, kv_dtype=kvd)
    draft = self_draft_model(tgt, 1)
    assert getattr(draft, "mesh", None) is not None, \
        "self-draft of a sharded target must shard on the same mesh"
    spec = SpeculativeCompletionModel(tgt, draft, gamma=2)
    assert spec.paged_supported, "tp demotion guard resurrected"
    got, cache = _greedy_paged(spec, prompt)
    assert cache.packed == (kvd == "int4")
    assert tuple(cache.target.k_pools[0].sharding.spec) \
        == (None, "tp", None, None)
    assert tuple(cache.draft.k_pools[0].sharding.spec) \
        == (None, "tp", None, None)
    if kvd in ("f32", "int8"):
        assert got == want, kvd
    else:
        assert got[0] == want[0]
        prefix = 0
        for a, b in zip(got, want):
            if a != b:
                break
            prefix += 1
        assert prefix >= 4, (prefix, got, want)


@pytest.mark.slow
def test_spec_paged_tp2_no_post_warmup_recompiles(model):
    """The SPL203/compile-gate criterion for the sharded spec lane:
    warmup_paged drills the fused step with out_shardings pinned for
    BOTH halves' pools; join/finish/join cycles afterwards compile
    nothing (a GSPMD-chosen output placement would recompile the
    first serve-time step)."""
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)

    mesh = make_mesh(dp=4, tp=2)
    tgt = ShardedCompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), mesh=mesh,
        buckets=(16, 32), temp=0.0, seed=1, kv_dtype="int4")
    spec = SpeculativeCompletionModel(tgt, self_draft_model(tgt, 1),
                                      gamma=2)
    cache = spec.init_paged(2, page=16)
    spec.warmup_paged(cache, chunk=4)
    base = spec.compile_count()
    assert base > 0
    for prompt in (np.array([1, 2, 3], np.int32),
                   np.arange(1, 12, dtype=np.int32)):
        lg = spec.paged_prefill_row(cache, prompt, 0)
        spec.paged_decode_chunk(
            cache, np.array([int(np.argmax(lg)), -1], np.int64), 4)
        spec.paged_prefill_row(cache, np.array([7, 7], np.int32), 1)
        spec.paged_decode_chunk(cache, np.array([-1, 5], np.int64), 4)
        cache.free_row(0)
        cache.free_row(1)
    assert spec.compile_count() == base, \
        "sharded spec-paged steady state recompiled"


# ------------------------------------- int8 per-channel weight path


def test_channel_quant_roundtrip_bounds():
    """quantize_channel_kernel: requantizing its own dequantized grid
    is LOSSLESS (symmetric scaling maps the column max to ±127
    exactly), and per-element roundoff vs the float source is
    <= d/2, d = column-absmax/127."""
    from libsplinter_tpu.models.quant import (dequantize_channel_kernel,
                                              quantize_channel_kernel)
    rng = np.random.RandomState(0)
    w = rng.randn(32, 48).astype(np.float32)
    qk = quantize_channel_kernel(w)
    assert qk["wq"].dtype == np.int8 and qk["wq"].shape == (32, 48)
    assert qk["wscale"].shape == (48,)
    deq = dequantize_channel_kernel(qk)
    d = np.abs(w).max(axis=0) / 127.0
    assert (np.abs(deq - w) <= d[None, :] / 2 + 1e-7).all()
    again = quantize_channel_kernel(deq)
    np.testing.assert_array_equal(again["wq"], qk["wq"])
    np.testing.assert_allclose(again["wscale"], qk["wscale"],
                               rtol=1e-6)


def test_weights_int8_decode_tolerance(model):
    """cfg.weights_int8 converts every attention/MLP kernel to
    {wq, wscale} (per-output-channel; matmul-first, dequant on the
    f32 output) and the pinned tolerance holds: prefill argmax
    preserved with logits within 0.08, greedy agreement >= 25% over
    16 tokens on the tiny random model (near-zero logit margins —
    real checkpoints only widen them)."""
    qcfg = dataclasses.replace(model.cfg, weights_int8=True)
    qm = CompletionModel(qcfg, buckets=(16, 32), temp=0.0, seed=1,
                         params=model.params)
    leaves = qm.params["params"]["layer_0"]["attn"]["q"]
    assert set(leaves) == {"wq", "wscale"}
    assert leaves["wq"].dtype == jnp.int8
    prompt = np.arange(1, 10, dtype=np.int32)
    ca = model.init_paged(2, page=16)
    cb = qm.init_paged(2, page=16)
    la = np.asarray(model.paged_prefill_row(ca, prompt, 0))
    lb = np.asarray(qm.paged_prefill_row(cb, prompt, 0))
    assert int(np.argmax(la)) == int(np.argmax(lb))
    assert np.abs(la - lb).max() < 0.08
    ca.reset()
    cb.reset()
    a = [int(x) for x in model.generate_tokens(prompt, 16, chunk=4)]
    model.reset()
    b = [int(x) for x in qm.generate_tokens(prompt, 16, chunk=4)]
    qm.reset()
    agree = np.mean([x == y for x, y in zip(a, b)])
    assert a[0] == b[0]
    assert agree >= 0.25, (agree, a, b)


def test_weights_int8_excludes_q8_blocks():
    """The two int8 residencies claim the same projections — asking
    for both is a config error, caught at model build AND at the
    daemon CLI (`--quantized --weights-int8` exits typed; the
    completer.weight_quant fault site fires before quantization when
    armed, e.g. SPTPU_FAULT=completer.weight_quant:crash@1)."""
    cfg = dataclasses.replace(DecoderConfig.tiny(dtype=jnp.float32),
                              quantized=True, weights_int8=True)
    with pytest.raises(ValueError, match="pick one"):
        CompletionModel(cfg, buckets=(16,))


def test_weights_int8_fault_site_fires():
    """completer.weight_quant chaos coverage (SPL104): arming the
    site makes the daemon's `--weights int8` boot path raise BEFORE
    any program compiles — the supervisor-restart claim is that a
    crash here leaves nothing half-converted (the quantized tree is
    rebuilt from the float checkpoint on respawn)."""
    from libsplinter_tpu.utils import faults
    from libsplinter_tpu.utils.faults import FaultInjected, fault
    faults.arm("completer.weight_quant:raise@1")
    try:
        with pytest.raises(FaultInjected):
            fault("completer.weight_quant")
    finally:
        faults.disarm()


def test_weights_int8_encoder_optin():
    """EncoderConfig.weights_int8 shares the ChannelQuantDense
    residency: a float checkpoint converts in place (biases ride
    along float), embeddings stay cosine ~1 with the float encoder
    (pinned >= 0.999 — one scale per output column on bert-size
    columns is far finer than the unit-vector output cares about),
    and the encoder param_pspec routes wq/wscale like the kernels
    they replaced."""
    from jax.sharding import PartitionSpec as P
    from libsplinter_tpu.models.encoder import (EmbeddingModel,
                                                EncoderConfig)
    from libsplinter_tpu.parallel.mesh import param_pspec

    cfg = EncoderConfig.tiny(dtype=jnp.float32)
    base = EmbeddingModel(cfg, seed=3, buckets=(16,))
    qm = EmbeddingModel(dataclasses.replace(cfg, weights_int8=True),
                        seed=3, buckets=(16,), params=base.params)
    mod = qm.params["params"]["layer_0"]["attn"]["qkv"]
    assert {"wq", "wscale", "bias"} <= set(mod)
    assert mod["wq"].dtype == jnp.int8
    ids = np.zeros((1, 16), np.int32)
    ids[0, :12] = np.arange(1, 13)
    va = np.asarray(base.encode_ids(ids, np.array([12])))
    vb = np.asarray(qm.encode_ids(ids, np.array([12])))
    cos = float((va * vb).sum()
                / (np.linalg.norm(va) * np.linalg.norm(vb)))
    assert cos >= 0.999, cos

    class _K:
        def __init__(self, k):
            self.key = k

    def spec(path_keys, leaf):
        return param_pspec(tuple(_K(k) for k in path_keys), leaf)

    wq = np.zeros((8, 16), np.int8)
    ws = np.zeros((16,), np.float32)
    attn = ("params", "layer_0", "attn")
    assert spec(attn + ("qkv", "wq"), wq) == P(None, "tp")
    assert spec(attn + ("qkv", "wscale"), ws) == P("tp")
    assert spec(attn + ("out", "wq"), wq) == P("tp", None)
    assert spec(attn + ("out", "wscale"), ws) == P()


def test_weights_int8_sharded_pspec():
    """decoder_param_pspec routes the channel-quant leaves: wq shards
    like the kernel it replaced (column-parallel out-dim for q/k/v/
    gate/up, row-parallel in-dim for out/down); wscale shards WITH
    the output columns on column-parallel layers and replicates on
    row-parallel ones (scaling partial sums before the psum is exact
    — the multiply distributes over the sum)."""
    from jax.sharding import PartitionSpec as P
    from libsplinter_tpu.parallel.serve import decoder_param_pspec

    class _K:
        def __init__(self, k):
            self.key = k

    def spec(path_keys, leaf):
        return decoder_param_pspec(tuple(_K(k) for k in path_keys),
                                   leaf)

    wq = np.zeros((8, 16), np.int8)
    ws = np.zeros((16,), np.float32)
    base = ("params", "layer_0", "attn")
    assert spec(base + ("q", "wq"), wq) == P(None, "tp")
    assert spec(base + ("q", "wscale"), ws) == P("tp")
    assert spec(base + ("out", "wq"), wq) == P("tp", None)
    assert spec(base + ("out", "wscale"), ws) == P()
    mlp = ("params", "layer_0", "mlp")
    assert spec(mlp + ("up", "wq"), wq) == P(None, "tp")
    assert spec(mlp + ("down", "wq"), wq) == P("tp", None)
    assert spec(mlp + ("down", "wscale"), ws) == P()
