"""Ring attention / sequence parallelism vs the dense oracle.

The reference rejects long inputs (splinference.cpp:226-233) — long
context is a net-new first-class capability here, so correctness is
pinned to a single-device dense attention reference on the virtual
8-device CPU mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from libsplinter_tpu.parallel.mesh import shard_map

from libsplinter_tpu.models import Encoder, EncoderConfig
from libsplinter_tpu.parallel import (dense_reference, make_mesh,
                                      make_ring_train_step, make_train_step,
                                      ring_attention_sharded)


@pytest.fixture(scope="module")
def qkvm():
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 32, 4, 16
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
               for _ in range(3))
    mask = jnp.asarray(rng.random((B, S)) > 0.2)
    return q, k, v, mask


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(qkvm, causal):
    q, k, v, mask = qkvm
    mesh = make_mesh(dp=2, tp=1, sp=4)
    ref = dense_reference(q, k, v, mask, causal=causal)
    out = ring_attention_sharded(mesh, q, k, v, mask, causal=causal)
    assert float(jnp.abs(out - ref).max()) < 1e-5


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradient_matches_dense(qkvm, causal):
    """d/dq AND d/dk, d/dv — the k/v cotangents flow back through the
    ppermute transpose (inverse ring rotation), the novel backward path."""
    q, k, v, mask = qkvm
    mesh = make_mesh(dp=2, tp=1, sp=4)

    def loss_ring(q, k, v):
        return (ring_attention_sharded(mesh, q, k, v, mask,
                                       causal=causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (dense_reference(q, k, v, mask, causal=causal) ** 2).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        assert float(jnp.abs(a - b).max()) < 1e-4, f"d/d{name} mismatch"


def test_sp8_full_ring(qkvm):
    """All 8 devices on the ring (sp=8, no dp)."""
    q, k, v, mask = qkvm
    mesh = make_mesh(dp=1, tp=1, sp=8)
    ref = dense_reference(q, k, v, mask)
    out = ring_attention_sharded(mesh, q, k, v, mask)
    assert float(jnp.abs(out - ref).max()) < 1e-5


@pytest.fixture(scope="module")
def enc_setup():
    rng = np.random.default_rng(1)
    cfg = EncoderConfig.tiny(out_dim=16, dtype=jnp.float32)
    B, S = 4, 32
    ids = rng.integers(0, cfg.vocab_size, size=(B, S)).astype(np.int32)
    lens = rng.integers(S // 2, S + 1, size=(B,))
    mask = np.arange(S)[None] < lens[:, None]
    return cfg, ids, mask


@pytest.mark.parametrize("variant", ["nomic", "bert"])
def test_sequence_parallel_encoder_matches_dense(enc_setup, variant):
    """The encoder run sequence-sharded over sp (ring attention, global
    rotary/absolute positions, psum'd mean pool) reproduces the dense
    single-device embeddings."""
    cfg, ids, mask = enc_setup
    cfg = dataclasses.replace(cfg, variant=variant)
    dense = Encoder(cfg)
    params = dense.init(jax.random.PRNGKey(0), ids, mask)
    ref = dense.apply(params, ids, mask)

    mesh = make_mesh(dp=2, tp=1, sp=4)
    ring = Encoder(dataclasses.replace(cfg, ring_axis="sp"))
    fn = shard_map(lambda p, i, m: ring.apply(p, i, m), mesh=mesh,
                   in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
                   out_specs=P("dp"), check_vma=False)
    out = fn(params, jnp.asarray(ids), jnp.asarray(mask))
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_ring_train_step_matches_dense(enc_setup):
    """One SGD step of the sequence-parallel trainer == one step of the
    single-device trainer (validates the psum/N gradient argument)."""
    cfg, ids, mask = enc_setup
    mesh = make_mesh(dp=2, tp=1, sp=4)
    opt = optax.sgd(0.1)
    init_d, step_d = make_train_step(cfg, optimizer=opt)
    init_r, step_r = make_ring_train_step(
        dataclasses.replace(cfg, ring_axis="sp"), mesh, optimizer=opt)

    batch = {"ids_a": jnp.asarray(ids), "mask_a": jnp.asarray(mask),
             "ids_b": jnp.asarray((ids + 7) % cfg.vocab_size),
             "mask_b": jnp.asarray(mask)}
    sd = init_d(jax.random.PRNGKey(0), ids[:1], mask[:1])
    sr = init_r(jax.random.PRNGKey(0), ids[:1], mask[:1])
    sd2, ld = step_d(sd, batch)
    sr2, lr = step_r(sr, batch)
    assert abs(float(ld) - float(lr)) < 1e-5
    deltas = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()), sd2.params, sr2.params)
    assert max(jax.tree_util.tree_leaves(deltas)) < 1e-5
    assert int(sr2.step) == 1


def test_ring_train_step_rejects_missing_axis(enc_setup):
    cfg, ids, mask = enc_setup
    mesh = make_mesh(dp=8, tp=1, sp=1)
    with pytest.raises(ValueError):
        make_ring_train_step(cfg, mesh)  # no ring_axis set
