"""Paged KV pool + ragged paged attention (ops/paged_attention.py,
models/decoder.PagedKVCache): interpret-mode kernel parity vs the
dense causal reference across ragged length patterns, pool alloc/free
leak checks, and model-level paged decode token-exactness vs serial.
`make decode-check` runs this file + tests/test_paged_continuous.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import (CompletionModel,
                                            DecoderConfig, PagedKVCache)
from libsplinter_tpu.ops.flash_attention import _causal_jnp
from libsplinter_tpu.ops.paged_attention import _paged_ref, paged_attention


def _build_paged(rng, lengths, *, KH, D, page, P, shuffle=True):
    """Random pools + tables for the given ragged lengths.  Returns
    (k_pool, v_pool, tables, dense_k, dense_v) where dense_* is the
    contiguous (B, T, KH, D) view of each row's tokens."""
    B = len(lengths)
    n_blocks = 1 + sum(-(-int(l) // page) or 1 for l in lengths)
    kp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    vp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    tables = np.zeros((B, P), np.int32)
    ids = list(range(1, n_blocks))
    if shuffle:
        rng.shuffle(ids)
    T = P * page
    dense_k = np.zeros((B, T, KH, D), np.float32)
    dense_v = np.zeros((B, T, KH, D), np.float32)
    for b in range(B):
        for p in range(-(-int(lengths[b]) // page)):
            bid = ids.pop()
            tables[b, p] = bid
            dense_k[b, p * page:(p + 1) * page] = kp[bid].transpose(1, 0, 2)
            dense_v[b, p * page:(p + 1) * page] = vp[bid].transpose(1, 0, 2)
    return kp, vp, tables, dense_k, dense_v


def _dense_rows(q, dense_k, dense_v, lengths):
    """Per-row dense causal reference: row b's single query at
    position lengths[b]-1 over its own keys (the math the paged
    kernel must reproduce)."""
    B, H, D = q.shape
    KH = dense_k.shape[2]
    rep = H // KH
    outs = []
    for b in range(B):
        L = int(lengths[b])
        kk = np.repeat(dense_k[b:b + 1, :L], rep, axis=2)
        vv = np.repeat(dense_v[b:b + 1, :L], rep, axis=2)
        ref = _causal_jnp(jnp.asarray(q[b:b + 1].reshape(1, 1, H, D)),
                          jnp.asarray(kk), jnp.asarray(vv),
                          jnp.int32(L - 1), jnp.zeros((1,), jnp.int32))
        outs.append(np.asarray(ref)[0, 0])
    return np.stack(outs)


# length patterns the tentpole calls out — the fast tier runs the one
# batch that exercises every class at once (single-token row, exact
# page boundary, len % page != 0, multi-page straggler); the wider
# grid rides the slow tier so tier-1 stays inside its 870 s budget
RAGGED = [
    ([1, 8, 7, 19], 8, 4),            # the canonical mixed batch
]
RAGGED_HEAVY = [
    ([8, 16, 24, 32], 8, 4),          # every row ON a page boundary
    ([1, 1, 1, 1], 4, 2),             # all single-token
    ([5, 13, 29, 31], 8, 4),          # nothing aligned
]


@pytest.mark.parametrize("lengths,page,P", RAGGED)
def test_kernel_matches_dense_reference(lengths, page, P):
    """Interpret-mode kernel == per-row dense causal attention to fp
    tolerance, with shuffled (non-contiguous) block assignments."""
    rng = np.random.RandomState(7)
    KH, H, D = 2, 4, 16
    kp, vp, tables, dk, dv = _build_paged(rng, lengths, KH=KH, D=D,
                                          page=page, P=P)
    q = rng.randn(len(lengths), H, D).astype(np.float32)
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths, np.int32),
        interpret=True))
    ref = _dense_rows(q, dk, dv, lengths)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("lengths,page,P", RAGGED)
def test_kernel_matches_jnp_gather_reference(lengths, page, P):
    """Kernel == the jnp gathered-page reference (_paged_ref, the
    non-TPU serving path) on the same pools/tables."""
    rng = np.random.RandomState(3)
    KH, H, D = 2, 6, 8                # rep = 3 (odd GQA grouping)
    kp, vp, tables, _, _ = _build_paged(rng, lengths, KH=KH, D=D,
                                        page=page, P=P)
    q = rng.randn(len(lengths), H, D).astype(np.float32)
    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(tables), jnp.asarray(lengths, np.int32))
    out = np.asarray(paged_attention(*args, interpret=True))
    ref = np.asarray(_paged_ref(*args))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_kernel_no_gqa_and_dead_rows():
    """rep == 1 (heads == kv_heads) lowers too, and a lengths == 0
    row (a dead batch slot) returns finite output — zeros from the
    kernel, don't-care by contract."""
    rng = np.random.RandomState(11)
    lengths = [9, 0, 4]
    KH = H = 4
    D, page, P = 8, 4, 4
    kp, vp, tables, dk, dv = _build_paged(rng, lengths, KH=KH, D=D,
                                          page=page, P=P)
    q = rng.randn(3, H, D).astype(np.float32)
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths, np.int32),
        interpret=True))
    assert np.isfinite(out).all()
    assert np.abs(out[1]).max() == 0.0          # dead row: zeros
    ref = _dense_rows(q[[0, 2]], dk[[0, 2]], dv[[0, 2]],
                      [lengths[0], lengths[2]])
    np.testing.assert_allclose(out[[0, 2]], ref, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("lengths,page,P", RAGGED_HEAVY)
def test_kernel_parity_ragged_heavy(lengths, page, P):
    """The rest of the ragged grid (boundary-only, all-single-token,
    unaligned batches) against both references."""
    rng = np.random.RandomState(5)
    KH, H, D = 2, 4, 16
    kp, vp, tables, dk, dv = _build_paged(rng, lengths, KH=KH, D=D,
                                          page=page, P=P)
    q = rng.randn(len(lengths), H, D).astype(np.float32)
    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(tables), jnp.asarray(lengths, np.int32))
    out = np.asarray(paged_attention(*args, interpret=True))
    np.testing.assert_allclose(out, _dense_rows(q, dk, dv, lengths),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(out, np.asarray(_paged_ref(*args)),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_kernel_parity_heavy_matrix():
    """Wider sweep: many (lengths, page, KH/H) geometries including
    bf16 pools — the slow tier's exhaustive arm."""
    rng = np.random.RandomState(42)
    for page, P in ((4, 8), (8, 4), (16, 3)):
        for KH, H in ((1, 4), (2, 8), (4, 4)):
            lengths = [int(rng.randint(1, page * P + 1))
                       for _ in range(5)]
            kp, vp, tables, dk, dv = _build_paged(
                rng, lengths, KH=KH, D=16, page=page, P=P)
            q = rng.randn(5, H, 16).astype(np.float32)
            out = np.asarray(paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(tables), jnp.asarray(lengths, np.int32),
                interpret=True))
            ref = _dense_rows(q, dk, dv, lengths)
            np.testing.assert_allclose(out, ref, rtol=3e-5, atol=3e-5)


# ------------------------------------------------------------- pool


def test_pool_alloc_free_no_leak():
    """Every finished row returns ALL its pages: used_pages comes back
    to zero and the free list is duplicate-free."""
    cfg = DecoderConfig.tiny(max_len=128)
    cache = PagedKVCache(cfg, 4, page=16, pool_pages=20)
    assert cache.free_pages == 20 and cache.used_pages == 0
    assert cache.ensure(0, 40)        # 3 pages
    assert cache.ensure(1, 16)        # 1 page (boundary)
    assert cache.ensure(2, 17)        # 2 pages
    assert cache.used_pages == 6
    assert cache.ensure(0, 48)        # grow in place: same 3 pages
    assert cache.used_pages == 6
    assert cache.ensure(0, 49)        # +1
    assert cache.used_pages == 7
    for r in range(4):
        cache.free_row(r)
    assert cache.used_pages == 0
    assert cache.free_pages == 20
    assert sorted(cache._free) == list(range(1, 21))
    assert (cache.tables == 0).all()
    assert (cache.lengths == 0).all()


def test_pool_exhaustion_backpressures_not_partial():
    """ensure() past the pool is an all-or-nothing refusal — nothing
    allocated, nothing leaked — and frees make it succeed again."""
    cfg = DecoderConfig.tiny(max_len=128)
    cache = PagedKVCache(cfg, 2, page=16, pool_pages=8)
    assert cache.ensure(0, 96)        # 6 of 8 pages
    assert not cache.ensure(1, 48)    # needs 3, only 2 free
    assert cache.used_pages == 6      # refusal allocated nothing
    assert len(cache._owned[1]) == 0
    cache.free_row(0)
    assert cache.ensure(1, 48)
    assert cache.used_pages == 3


def test_pool_window_cap_and_trash_block():
    """pages_needed caps at the window (a worst-case reservation can
    always fit an empty pool) and block 0 is never handed out."""
    cfg = DecoderConfig.tiny(max_len=128)
    cache = PagedKVCache(cfg, 2, page=16, pool_pages=8)
    assert cache.pages_needed(10_000) == cache.pages_per_row == 8
    assert cache.ensure(0, 10_000)    # exactly the whole pool
    assert 0 not in cache._owned[0]
    with pytest.raises(ValueError):
        PagedKVCache(cfg, 2, page=16, pool_pages=4)   # < one window


# ------------------------------------------- model-level paged decode


@pytest.fixture(scope="module")
def model():
    # f32 on CPU so greedy argmax comparisons are tie-stable (the
    # suite's convention for token-exactness tests)
    return CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(16, 32), temp=0.0)


@pytest.mark.slow
def test_paged_decode_token_exact_vs_serial(model):
    """Paged prefill + chunked paged decode reproduce the serial
    dense path token for token (greedy), including a row that joins
    mid-flight with shuffled page ownership.  Slow tier: the fast
    sweep keeps the daemon-level token-exactness bar
    (test_paged_continuous.test_paged_continuous_token_exact_vs_dense)
    inside the tier-1 870 s budget."""
    m = model
    A = np.arange(1, 8, dtype=np.int32)
    Bp = np.array([9, 2, 6], np.int32)
    sa = [int(x) for x in m.generate_tokens(A, 16, chunk=4)]
    m.reset()
    sb = [int(x) for x in m.generate_tokens(Bp, 10, chunk=4)]
    m.reset()

    cache = m.init_paged(2, page=16)
    logits = m.paged_prefill_row(cache, A, 0)
    out_a = [int(np.argmax(logits))]
    blk = m.paged_decode_chunk(cache, np.array([out_a[0], 0], np.int32), 6)
    out_a += [int(x) for x in blk[0]]
    jl = m.paged_prefill_row(cache, Bp, 1)     # join mid-decode
    out_b = [int(np.argmax(jl))]
    toks = np.array([int(blk[0][-1]), out_b[0]], np.int32)
    for _ in range(3):
        blk = m.paged_decode_chunk(cache, toks, 3)
        out_a += [int(x) for x in blk[0]]
        out_b += [int(x) for x in blk[1]]
        toks = blk[:, -1].astype(np.int32)
    assert out_a[:16] == sa[:16]
    assert out_b[:10] == sb[:10]
    cache.free_row(0)
    cache.free_row(1)
    assert cache.used_pages == 0


@pytest.mark.slow
def test_paged_join_not_bounded_by_neighbour(model):
    """The dense shared window forbade a joiner whose prompt exceeds
    join_budget(); paged rows have independent windows — a 20-token
    joiner lands with FULL context while a 3-token row decodes, and
    still matches its serial tokens.  Slow tier: `make decode-check`
    (whole-file, no slow filter) keeps the daemon-level regression
    (test_paged_joiner_exceeding_dense_window_untruncated)."""
    m = model
    short = np.array([5, 3, 2], np.int32)
    longp = (np.arange(1, 21, dtype=np.int32) % 900) + 1
    sl = [int(x) for x in m.generate_tokens(longp, 8, chunk=4)]
    m.reset()

    cache = m.init_paged(2, page=16)
    lg = m.paged_prefill_row(cache, short, 0)
    t0 = int(np.argmax(lg))
    blk = m.paged_decode_chunk(cache, np.array([t0, 0], np.int32), 4)
    # dense equivalent: pos=16, join_budget=16 < 20 -> deferred.
    # paged: admitted at once, full prompt, own positions 0..19
    jl = m.paged_prefill_row(cache, longp, 1)
    out_b = [int(np.argmax(jl))]
    toks = np.array([int(blk[0][-1]), out_b[0]], np.int32)
    for _ in range(2):
        blk = m.paged_decode_chunk(cache, toks, 4)
        out_b += [int(x) for x in blk[1]]
        toks = blk[:, -1].astype(np.int32)
    assert out_b[:8] == sl[:8]
    cache.free_row(0)
    cache.free_row(1)


def test_paged_warmup_pins_compile_count(model):
    """After warmup_paged, a join/finish/join cycle (varying prompt
    lengths and batch occupancy) compiles NOTHING new — the
    recompile-on-occupancy-change regression paged decode must not
    reintroduce."""
    m = model
    cache = m.init_paged(2, page=16)
    m.warmup_paged(cache, chunk=4)
    base = m.compile_count()
    assert base > 0
    for prompt in (np.array([1, 2, 3], np.int32),
                   np.arange(1, 12, dtype=np.int32)):
        lg = m.paged_prefill_row(cache, prompt, 0)
        toks = np.array([int(np.argmax(lg)), 0], np.int32)
        m.paged_decode_chunk(cache, toks, 4)
        # second row joins, then both finish
        m.paged_prefill_row(cache, np.array([7, 7], np.int32), 1)
        m.paged_decode_chunk(cache, toks, 4)
        cache.free_row(0)
        cache.free_row(1)
    assert m.compile_count() == base, \
        "paged steady state recompiled on a join/finish/join cycle"


def test_paged_pool_exhaustion_raises_for_unreserved(model):
    """Model-level contract: a decode chunk that must grow a row past
    the pool raises (the daemon's admission reservation makes this
    unreachable in serving)."""
    m = model
    cfg = m.cfg
    cache = m.init_paged(2, page=16, pool_pages=cfg.max_len // 16)
    m.paged_prefill_row(cache, np.arange(1, 15, dtype=np.int32), 0)
    # eat the rest of the pool with row 1
    assert cache.ensure(1, cfg.max_len - 16)
    cache.lengths[1] = 15              # parked at its page boundary
    with pytest.raises(RuntimeError, match="pool exhausted"):
        m.paged_decode_chunk(cache, np.array([1, 1], np.int32), 8)
