"""Binding-surface smoke tests (reference parity: Deno/Bun FFI test suite
bindings/ts/splinter_test.ts + the Rust -sys crates built by cc in build.rs).

Neither a JS runtime nor rustc is guaranteed in the build image, so:
  - the vendored-source sync check always runs (a stale csrc/ is the classic
    -sys crate failure mode);
  - the TS symbol table is cross-checked against the C header so the FFI
    declarations cannot drift silently;
  - the real runtime suites execute only when deno / bun / cargo exist.
"""
from __future__ import annotations

import filecmp
import re
import shutil
import subprocess
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
CSRC = ROOT / "bindings" / "rust" / "libsptpu-sys" / "csrc"
TS = ROOT / "bindings" / "ts" / "sptpu.ts"
HDR = ROOT / "native" / "include" / "sptpu.h"


def test_rust_vendor_in_sync():
    pairs = [
        (ROOT / "native" / "src" / "store.c", CSRC / "store.c"),
        (ROOT / "native" / "src" / "coord.c", CSRC / "coord.c"),
        (ROOT / "native" / "src" / "wptok.c", CSRC / "wptok.c"),
        (ROOT / "native" / "src" / "internal.h", CSRC / "internal.h"),
        (HDR, CSRC / "sptpu.h"),
    ]
    for src, dst in pairs:
        assert dst.exists(), f"{dst} missing — run scripts/sync_rust_vendor.sh"
        assert filecmp.cmp(src, dst, shallow=False), (
            f"{dst} is stale — run scripts/sync_rust_vendor.sh"
        )


def test_rust_decls_exist_in_header():
    lib_rs = (ROOT / "bindings" / "rust" / "libsptpu-sys" / "src" /
              "lib.rs").read_text()
    header = HDR.read_text()
    declared = set(re.findall(r"pub fn (spt_\w+)", lib_rs))
    assert len(declared) > 60
    for fn in sorted(declared):
        assert re.search(rf"\b{fn}\s*\(", header), (
            f"lib.rs declares {fn} which is not in sptpu.h"
        )


def test_ts_symbols_exist_in_header():
    ts = TS.read_text()
    header = HDR.read_text()
    declared = set(re.findall(r"^  (spt_\w+):", ts, re.M))
    assert len(declared) > 35
    for fn in sorted(declared):
        assert re.search(rf"\b{fn}\s*\(", header), (
            f"sptpu.ts binds {fn} which is not in sptpu.h"
        )


@pytest.mark.skipif(shutil.which("deno") is None, reason="deno not installed")
def test_ts_suite_under_deno():
    subprocess.run(
        ["deno", "test", "--allow-ffi", "--allow-env",
         str(ROOT / "bindings" / "ts" / "sptpu_test.ts")],
        check=True, timeout=120,
    )


@pytest.mark.skipif(shutil.which("bun") is None, reason="bun not installed")
def test_ts_suite_under_bun():
    subprocess.run(
        ["bun", str(ROOT / "bindings" / "ts" / "sptpu_test.ts")],
        check=True, timeout=120,
    )


@pytest.mark.skipif(shutil.which("cargo") is None, reason="cargo not installed")
def test_rust_suite_under_cargo():
    subprocess.run(
        ["cargo", "test", "--quiet"],
        cwd=ROOT / "bindings" / "rust" / "libsptpu-sys",
        check=True, timeout=600,
    )
