"""Lua scripting host tests.

Interpreter-level coverage of the microlua subset, then store-backed host
coverage mirroring the reference's smoke script (test.lua: require, arg
table, get-or-default, set, math/inc — plus tandem, labels, embeddings
through the host API of splinter_cli_cmd_lua.c:365-386).
"""
from __future__ import annotations

import os

import pytest

from libsplinter_tpu.scripting.microlua import (
    LuaError, LuaRuntime, LuaTable,
)


def run_lua(src, **kw):
    lines = []
    rt = LuaRuntime(output=lines.append)
    result = rt.run(src, **kw)
    return lines, result


class TestInterpreter:
    def test_arith_and_print(self):
        out, _ = run_lua("print(1 + 2 * 3, 10 / 4, 7 // 2, 2^10, 7 % 3)")
        assert out == ["7\t2.5\t3\t1024.0\t1"]

    def test_int_float_semantics(self):
        out, _ = run_lua("print(1 == 1.0, 3 / 1, 4 // 1)")
        assert out == ["true\t3.0\t4"]

    def test_strings_concat_len(self):
        out, _ = run_lua('local s = "ab" .. "cd" .. 12 print(s, #s)')
        assert out == ["abcd12\t6"]

    def test_locals_and_scoping(self):
        src = """
        local x = 1
        do local x = 2 end
        print(x)
        """
        assert run_lua(src)[0] == ["1"]

    def test_if_elseif_else(self):
        src = """
        local function grade(n)
          if n > 89 then return "A" elseif n > 79 then return "B"
          else return "C" end
        end
        print(grade(95), grade(85), grade(10))
        """
        assert run_lua(src)[0] == ["A\tB\tC"]

    def test_while_repeat_break(self):
        src = """
        local i, total = 0, 0
        while true do
          i = i + 1
          if i > 10 then break end
          total = total + i
        end
        local j = 0
        repeat j = j + 1 until j >= 3
        print(total, j)
        """
        assert run_lua(src)[0] == ["55\t3"]

    def test_numeric_for_with_step(self):
        src = """
        local acc = {}
        for i = 10, 1, -3 do table.insert(acc, i) end
        print(table.concat(acc, ","))
        """
        assert run_lua(src)[0] == ["10,7,4,1"]

    def test_generic_for_ipairs_pairs(self):
        src = """
        local t = {"a", "b", "c", x = 1}
        local items = {}
        for i, v in ipairs(t) do items[#items + 1] = i .. v end
        local count = 0
        for k, v in pairs(t) do count = count + 1 end
        print(table.concat(items, " "), count)
        """
        assert run_lua(src)[0] == ["1a 2b 3c\t4"]

    def test_functions_closures_recursion(self):
        src = """
        local function counter()
          local n = 0
          return function() n = n + 1 return n end
        end
        local c = counter()
        c() c()
        local function fib(n)
          if n < 2 then return n end
          return fib(n - 1) + fib(n - 2)
        end
        print(c(), fib(10))
        """
        assert run_lua(src)[0] == ["3\t55"]

    def test_multiple_returns_and_adjustment(self):
        src = """
        local function two() return 1, 2 end
        local a, b = two()
        local c, d = two(), 10      -- first call truncated to one value
        local t = {two(), two()}    -- last call expands
        print(a, b, c, d, #t)
        """
        assert run_lua(src)[0] == ["1\t2\t1\t10\t3"]

    def test_varargs(self):
        src = """
        local function pack(...) return select("#", ...), ... end
        print(pack("x", "y"))
        """
        assert run_lua(src)[0] == ["2\tx\ty"]

    def test_method_calls(self):
        src = """
        local obj = { n = 5 }
        function obj:bump(k) self.n = self.n + k return self.n end
        print(obj:bump(3))
        """
        assert run_lua(src)[0] == ["8"]

    def test_table_length_border(self):
        src = """
        local t = {1, 2, 3}
        t[5] = 9            -- hole at 4: border stays 3
        print(#t)
        t[4] = 8
        print(#t)
        """
        assert run_lua(src)[0] == ["3", "5"]

    def test_string_library(self):
        src = """
        print(string.format("%s=%d (%.2f) %x", "k", 42, 1.5, 255))
        print(("hello"):upper(), string.sub("hello", 2, 4))
        print(string.rep("ab", 3), string.find("hello world", "wor"))
        local s, n = string.gsub("a-b-c", "-", "+")
        print(s, n)
        """
        out, _ = run_lua(src)
        assert out == [
            "k=42 (1.50) ff",
            "HELLO\tell",
            "ababab\t7\t9",
            "a+b+c\t2",
        ]

    def test_andor_idioms(self):
        out, _ = run_lua(
            'local x = nil print(x or "dflt", x and 1, 0 or "zerotruthy")')
        assert out == ["dflt\tnil\t0"]

    def test_comparison_and_equality(self):
        out, _ = run_lua('print("a" < "b", 2 >= 2, "1" == 1, nil == false)')
        assert out == ["true\ttrue\tfalse\tfalse"]

    def test_pcall_and_error(self):
        src = """
        local ok, err = pcall(function() error("boom") end)
        print(ok, err)
        print(pcall(function() return 1 + nil end))
        """
        out, _ = run_lua(src)
        assert out[0] == "false\tboom"
        assert out[1].startswith("false")

    def test_arg_table(self):
        src = """
        print(arg[0], #arg)
        for i = 1, #arg do print(arg[i]) end
        """
        out, _ = run_lua(src, script_args=["mykey", "42"],
                         chunk_name="test.lua")
        assert out == ["test.lua\t2", "mykey", "42"]

    def test_comments_and_long_strings(self):
        src = """
        -- a line comment
        --[[ a block
             comment ]]
        local s = [[line one]]
        print(s)
        """
        assert run_lua(src)[0] == ["line one"]

    def test_runaway_loop_guard(self):
        rt = LuaRuntime(output=lambda s: None, max_steps=10_000)
        with pytest.raises(LuaError, match="exceeded"):
            rt.run("while true do end")

    def test_parse_errors_carry_line(self):
        with pytest.raises(LuaError, match="line 2"):
            run_lua("local x = 1\nlocal = 3")

    def test_require_unknown_module(self):
        with pytest.raises(LuaError, match="not found"):
            run_lua('require("nope")')

    def test_tostring_tonumber(self):
        out, _ = run_lua(
            'print(tostring(nil), tonumber("0x10"), tonumber("3.5"),'
            ' tonumber("zz"))')
        assert out == ["nil\t16\t3.5\tnil"]


class TestMetatables:
    """Metatable semantics (reference: liblua 5.4 via
    splinter_cli_cmd_lua.c:365-386) — the OO-style store-script
    surface: class tables behind __index, operator overloads,
    defaulting proxies, protected metatables."""

    def test_class_pattern_with_methods(self):
        src = """
        local Account = {}
        Account.__index = Account
        function Account.new(owner, balance)
          return setmetatable({owner = owner, balance = balance or 0},
                              Account)
        end
        function Account:deposit(n) self.balance = self.balance + n end
        function Account:get() return self.balance end
        local a = Account.new("ada", 10)
        a:deposit(32)
        print(a:get(), a.owner)
        """
        assert run_lua(src)[0] == ["42\tada"]

    def test_inheritance_chain(self):
        src = """
        local Base = {}
        Base.__index = Base
        function Base:kind() return "base" end
        function Base:greet() return "hello from " .. self:kind() end
        local Derived = setmetatable({}, {__index = Base})
        Derived.__index = Derived
        function Derived:kind() return "derived" end
        local d = setmetatable({}, Derived)
        print(d:greet())
        local b = setmetatable({}, Base)
        print(b:greet())
        """
        assert run_lua(src)[0] == ["hello from derived",
                                   "hello from base"]

    def test_index_function_handler(self):
        src = """
        local t = setmetatable({}, {__index = function(t, k)
          return "<" .. k .. ">"
        end})
        t.real = 1
        print(t.real, t.missing)
        """
        assert run_lua(src)[0] == ["1\t<missing>"]

    def test_newindex_function_and_rawset(self):
        src = """
        local log = {}
        local t = setmetatable({}, {__newindex = function(t, k, v)
          table.insert(log, k .. "=" .. tostring(v))
          rawset(t, k, v)
        end})
        t.a = 1
        t.a = 2       -- raw hit now: __newindex must NOT fire again
        print(table.concat(log, ","), t.a)
        """
        assert run_lua(src)[0] == ["a=1\t2"]

    def test_newindex_table_handler_redirects(self):
        src = """
        local backing = {}
        local t = setmetatable({}, {__newindex = backing})
        t.x = 7
        print(rawget(t, "x"), backing.x)
        """
        assert run_lua(src)[0] == ["nil\t7"]

    def test_arith_metamethods_vector(self):
        src = """
        local V = {}
        V.__index = V
        V.__add = function(a, b) return V.new(a.x + b.x, a.y + b.y) end
        V.__sub = function(a, b) return V.new(a.x - b.x, a.y - b.y) end
        V.__mul = function(a, k) return V.new(a.x * k, a.y * k) end
        V.__unm = function(a) return V.new(-a.x, -a.y) end
        V.__eq = function(a, b) return a.x == b.x and a.y == b.y end
        V.__tostring = function(a)
          return "(" .. a.x .. "," .. a.y .. ")"
        end
        function V.new(x, y) return setmetatable({x = x, y = y}, V) end
        local a, b = V.new(1, 2), V.new(3, 4)
        print(tostring(a + b), tostring(b - a), tostring(a * 10),
              tostring(-a))
        print(a + b == V.new(4, 6), a == b)
        """
        assert run_lua(src)[0] == ["(4,6)\t(2,2)\t(10,20)\t(-1,-2)",
                                   "true\tfalse"]

    def test_comparison_and_len_and_concat(self):
        src = """
        local M = {}
        M.__lt = function(a, b) return a.v < b.v end
        M.__le = function(a, b) return a.v <= b.v end
        M.__len = function(a) return a.v end
        M.__concat = function(a, b)
          local av = type(a) == "table" and a.v or a
          local bv = type(b) == "table" and b.v or b
          return av .. "|" .. bv
        end
        local function box(v) return setmetatable({v = v}, M) end
        local s, t = box(3), box(5)
        print(s < t, t < s, s <= s, t > s, #t)
        print(s .. t, "x" .. t)
        """
        assert run_lua(src)[0] == ["true\tfalse\ttrue\ttrue\t5",
                                   "3|5\tx|5"]

    def test_call_metamethod(self):
        src = """
        local counter = setmetatable({n = 0}, {__call = function(self, k)
          self.n = self.n + (k or 1)
          return self.n
        end})
        counter(5)
        print(counter(), counter.n)
        """
        assert run_lua(src)[0] == ["6\t6"]

    def test_protected_metatable(self):
        src = """
        local t = setmetatable({}, {__metatable = "locked"})
        print(getmetatable(t))
        local ok, err = pcall(function() setmetatable(t, {}) end)
        print(ok, err)
        """
        out, _ = run_lua(src)
        assert out[0] == "locked"
        assert out[1].startswith("false\t")
        assert "protected metatable" in out[1]

    def test_rawequal_rawlen_bypass(self):
        src = """
        local M = {__eq = function() return true end,
                   __len = function() return 99 end}
        local a = setmetatable({1, 2}, M)
        local b = setmetatable({1, 2}, M)
        print(a == b, rawequal(a, b), #a, rawlen(a))
        """
        assert run_lua(src)[0] == ["true\tfalse\t99\t2"]

    def test_default_value_proxy_store_script(self):
        """The canonical store-script idiom: a config table whose reads
        fall back to defaults and whose writes are validated."""
        src = """
        local defaults = {ttl = 60, shards = 8}
        local cfg = setmetatable({}, {
          __index = defaults,
          __newindex = function(t, k, v)
            if defaults[k] == nil then
              error("unknown config key: " .. k)
            end
            rawset(t, k, v)
          end,
        })
        cfg.ttl = 120
        print(cfg.ttl, cfg.shards)
        local ok, err = pcall(function() cfg.bogus = 1 end)
        print(ok, err)
        """
        out, _ = run_lua(src)
        assert out[0] == "120\t8"
        assert out[1].startswith("false\t") and "unknown config key" in out[1]

    def test_getmetatable_plain(self):
        out, _ = run_lua("""
        local mt = {}
        local t = setmetatable({}, mt)
        print(getmetatable(t) == mt, getmetatable({}), getmetatable(1))
        """)
        assert out == ["true\tnil\tnil"]


class TestStoreHost:
    @pytest.fixture
    def store(self):
        from libsplinter_tpu.store import Store
        name = f"lua-host-{os.getpid()}"
        st = Store.create(name, nslots=128, max_val=512, vec_dim=8)
        yield st
        st.close()
        Store.unlink(name)

    def run_host(self, store, src, args=None):
        from libsplinter_tpu.scripting.lua_host import make_runtime
        lines = []
        rt = make_runtime(store, output=lines.append)
        rt.run(src, script_args=args or [])
        return lines

    def test_reference_smoke_script_shape(self, store):
        # the reference's test.lua flow: require, get-or-default, set, math
        src = """
        local bus = require("splinter")
        local test = bus.get("test_key") or 0
        print("Test result:" .. test)
        bus.set("test_multi", "1, 2, 3, 4, 5")
        bus.set("test_integer", 1)
        bus.math("test_integer", "inc", 0)
        print(bus.get("test_integer"))
        """
        out = self.run_host(store, src)
        assert out == ["Test result:0", "2"]
        assert store.get("test_multi") == b"1, 2, 3, 4, 5"
        assert store.get_uint("test_integer") == 2

    def test_labels_read_and_mask_test(self, store):
        # the bitwise-tier idiom: set label bits, read the mask back,
        # test + clear bits in-script
        src = """
        local bus = require("splinter")
        bus.set("job", "pending")
        local EMBED, DONE = 1 << 0, 1 << 5
        bus.label("job", EMBED | DONE)
        local m = bus.labels("job")
        print(m, (m & EMBED) ~= 0, m & ~EMBED)
        bus.label("job", EMBED, true)
        print(bus.labels("job"), bus.labels("missing"))
        bus.label("job", 1 << 63)
        print(bus.labels("job") & (1 << 63) ~= 0,
              bus.labels("job") < 0)
        """
        out = self.run_host(store, src)
        # bit 63 reads back in the interpreter's signed-i64 convention
        assert out == ["33\ttrue\t32", "32\tnil", "true\ttrue"]

    def test_tandem_roundtrip(self, store):
        src = """
        local bus = require("splinter")
        bus.set_tandem("doc", 1, "chunk one")
        bus.set_tandem("doc", 2, "chunk two")
        print(bus.get_tandem("doc", 2))
        """
        assert self.run_host(store, src) == ["chunk two"]

    def test_labels_and_bump_signaccording(self, store):
        src = """
        local bus = require("splinter")
        bus.set("task", "payload")
        bus.watch("task", 5)
        local before = bus.signal_count(5)
        bus.label("task", 64)
        bus.bump("task")
        print(bus.signal_count(5) - before)
        """
        out = self.run_host(store, src)
        assert out == ["1"]  # label set is metadata-only; only bump pulses

    def test_embedding_roundtrip(self, store):
        src = """
        local bus = require("splinter")
        bus.set("vec_key", "has a vector")
        bus.set_embedding("vec_key", {0.5, 1.0, 0, 0, 0, 0, 0, 0.25})
        local v = bus.get_embedding("vec_key")
        print(#v, v[1], v[8])
        """
        out = self.run_host(store, src)
        assert out == ["8\t0.5\t0.25"]

    def test_unset_and_epoch(self, store):
        src = """
        local bus = require("splinter")
        bus.set("gone", "x")
        local e1 = bus.epoch("gone")
        bus.set("gone", "y")
        print(bus.epoch("gone") - e1)
        bus.unset("gone")
        print(bus.get("gone"))
        """
        assert self.run_host(store, src) == ["2", "nil"]

    def test_cli_lua_command(self, store, tmp_path, capsys):
        from libsplinter_tpu.cli.main import Session, dispatch
        script = tmp_path / "s.lua"
        script.write_text(
            'local bus = require("splinter")\n'
            'bus.set(arg[1], "from cli lua")\n'
            'print("wrote " .. arg[1])\n')
        ses = Session.__new__(Session)
        ses.store_name = store.name
        ses.ns_prefix = ""
        ses.persistent = False
        ses._store = store
        ses.labels = {}
        dispatch(ses, ["lua", str(script), "cli_key"])
        assert capsys.readouterr().out.strip() == "wrote cli_key"
        assert store.get("cli_key") == b"from cli lua"


class TestRecursionSafety:
    def test_recursive_metamethod_is_lua_error(self):
        src = """
        local M = {}
        M.__add = function(a, b) return a + b end
        local x = setmetatable({}, M)
        local ok, err = pcall(function() return x + x end)
        print(ok, err)
        """
        out, _ = run_lua(src)
        assert out[0].startswith("false\t")
        assert "stack overflow" in out[0]

    def test_recursive_method_is_lua_error(self):
        src = """
        local A = {}
        A.__index = A
        function A:m() return self:m() end
        local a = setmetatable({}, A)
        local ok, err = pcall(function() return a:m() end)
        print(ok, err)
        """
        out, _ = run_lua(src)
        assert out[0].startswith("false\t") and "stack overflow" in out[0]

    def test_uncaught_overflow_is_lua_error_not_python(self):
        with pytest.raises(LuaError, match="stack overflow"):
            run_lua("local function f() return f() end f()")


class TestBitwise:
    """Lua 5.4 bitwise tier (§3.4.2-3.4.3): 64-bit two's-complement
    wrap, logical shifts with signed out-of-range counts, string/float
    integer-representation coercion, and the six metamethods — the one
    operator family real store scripts (bloom label masks) lean on."""

    def test_and_or_xor_not(self):
        out, _ = run_lua("print(0xF0 & 0x3C, 0xF0 | 0x0F, "
                         "0xFF ~ 0x0F, ~0)")
        assert out == ["48\t255\t240\t-1"]

    def test_shifts_logical_and_signed_counts(self):
        out, _ = run_lua(
            "print(1 << 4, 0x100 >> 4, -1 >> 56, 1 << 64, "
            "16 >> -2, -1 >> 0)")
        # -1 >> 56 is LOGICAL: 0xFF; shift >= 64 -> 0; negative count
        # reverses direction
        assert out == ["16\t16\t255\t0\t64\t-1"]

    def test_wrap_to_64_bits(self):
        # bitwise results wrap to 64-bit two's complement (plain
        # integer arithmetic deliberately stays python-bigint here)
        out, _ = run_lua("print(1 << 63, -1 >> 1, ~(1 << 63))")
        assert out == [f"{-(1 << 63)}\t{(1 << 63) - 1}\t{(1 << 63) - 1}"]

    def test_precedence_between_or_and_concat(self):
        # 5.4 §3.4.8: | is looser than .. and tighter than
        # comparisons — a < b | c parses as a < (b | c), and
        # tostring(1 | 2) .. "" concats the already-computed 3
        out, _ = run_lua("print(1 < 2 | 4, tostring(1 | 2) .. '')")
        assert out == ["true\t3"]

    def test_float_coercion_and_5_4_errors(self):
        out, _ = run_lua("print(3.0 & 7)")
        assert out == ["3"]
        with pytest.raises(LuaError, match="no integer representation"):
            run_lua("return 3.5 & 1")
        # out-of-i64-range float: error, not a silent wrap
        with pytest.raises(LuaError, match="no integer representation"):
            run_lua("return 2^63 & 1")
        # 5.4 does NOT coerce strings for bitwise (unlike arithmetic)
        with pytest.raises(LuaError, match="bitwise"):
            run_lua("return '12' & 0xFF")
        with pytest.raises(LuaError, match="bitwise"):
            run_lua("return {} & 1")
        # inf/nan must be a CATCHABLE lua error, never a raw Python
        # OverflowError escaping the sandbox
        for bad in ("math.huge & 1", "(1/0) & 1", "(0/0) | 2"):
            with pytest.raises(LuaError,
                               match="no integer representation"):
                run_lua(f"return {bad}")
        out, _ = run_lua(
            "print(pcall(function() return math.huge & 1 end))")
        assert out[0].startswith("false\t")

    def test_label_mask_pattern(self):
        # the store-script idiom this exists for: build, test, clear
        # label bits
        out, _ = run_lua("""
            local EMBED, WAIT = 1 << 0, 1 << 3
            local mask = EMBED | WAIT
            print(mask, mask & EMBED ~= 0, mask & ~EMBED)
        """)
        assert out == ["9\ttrue\t8"]

    def test_bitwise_metamethods(self):
        out, _ = run_lua("""
            local mt = {
                __band = function(a, b) return "band" end,
                __bor  = function(a, b) return "bor" end,
                __bxor = function(a, b) return "bxor" end,
                __shl  = function(a, b) return "shl" end,
                __shr  = function(a, b) return "shr" end,
                __bnot = function(a) return "bnot" end,
            }
            local t = setmetatable({}, mt)
            print(t & 1, 1 | t, t ~ t, t << 2, t >> 2, ~t)
        """)
        assert out == ["band\tbor\tbxor\tshl\tshr\tbnot"]

    def test_unary_bnot_binds_tighter_than_binary(self):
        out, _ = run_lua("print(~1 & 0xFF, 2 ~ ~0)")
        assert out == ["254\t-3"]


class TestGoto:
    """goto / ::label:: — lua 5.4 block-granular control transfer."""

    def test_continue_idiom(self):
        out, _ = run_lua("""
            local s = 0
            for i = 1, 10 do
              if i % 2 == 0 then goto continue end
              s = s + i
              ::continue::
            end
            print(s)
        """)
        assert out == ["25"]

    def test_backward_goto_loops(self):
        out, _ = run_lua("""
            local i = 0
            ::top::
            i = i + 1
            if i < 5 then goto top end
            print(i)
        """)
        assert out == ["5"]

    def test_goto_out_of_nested_blocks(self):
        out, _ = run_lua("""
            local n = 0
            do
              do
                n = 1
                goto done
              end
            end
            n = 99            -- skipped
            ::done::
            print(n)
        """)
        assert out == ["1"]

    def test_goto_out_of_loop(self):
        out, _ = run_lua("""
            for i = 1, 100 do
              if i == 3 then goto out end
            end
            ::out::
            print("escaped")
        """)
        assert out == ["escaped"]

    def test_invisible_label_is_catchable_error(self):
        out, _ = run_lua("""
            local ok, err = pcall(function() goto nowhere end)
            print(ok, err)
        """)
        assert out[0].startswith("false\t")
        assert "nowhere" in out[0]

    def test_runaway_backward_goto_hits_step_budget(self):
        lines = []
        rt = LuaRuntime(output=lines.append, max_steps=10_000)
        with pytest.raises(LuaError, match="exceeded"):
            rt.run("::spin:: goto spin")


class TestCoroutines:
    """coroutine.* — one daemon thread per coroutine, strict handoff."""

    def test_producer_consumer_round_trip(self):
        out, _ = run_lua("""
            local co = coroutine.create(function(a, b)
              local c = coroutine.yield(a + b)
              local d, e = coroutine.yield(c * 2)
              return d + e, "done"
            end)
            print(coroutine.status(co))
            print(coroutine.resume(co, 1, 2))
            print(coroutine.resume(co, 10))
            print(coroutine.resume(co, 3, 4))
            print(coroutine.status(co))
            print(coroutine.resume(co))
        """)
        assert out == [
            "suspended",
            "true\t3",
            "true\t20",
            "true\t7\tdone",
            "dead",
            "false\tcannot resume dead coroutine",
        ]

    def test_wrap_generator_idiom(self):
        out, _ = run_lua("""
            local gen = coroutine.wrap(function()
              for i = 1, 3 do coroutine.yield(i * i) end
            end)
            print(gen(), gen(), gen())
        """)
        assert out == ["1\t4\t9"]

    def test_wrap_in_generic_for(self):
        out, _ = run_lua("""
            local function range2(n)
              return coroutine.wrap(function()
                for i = 1, n do coroutine.yield(i) end
              end)
            end
            local s = 0
            for i in range2(4) do s = s + i end
            print(s)
        """)
        assert out == ["10"]

    def test_error_in_body_returns_false(self):
        out, _ = run_lua("""
            local co = coroutine.create(function() error("boom") end)
            print(coroutine.resume(co))
            print(coroutine.status(co))
        """)
        assert out[0].startswith("false\t")
        assert "boom" in out[0]
        assert out[1] == "dead"

    def test_yield_crosses_pcall(self):
        # thread-per-coroutine keeps the python stack alive across the
        # suspension, so yield inside pcall works (liblua's unyieldable
        # C-boundary restriction does not apply here)
        out, _ = run_lua("""
            local co = coroutine.create(function()
              local ok = pcall(function() coroutine.yield("mid") end)
              return ok
            end)
            print(coroutine.resume(co))
            print(coroutine.resume(co))
        """)
        assert out == ["true\tmid", "true\ttrue"]

    def test_yield_outside_coroutine_is_error(self):
        out, _ = run_lua("print(pcall(coroutine.yield))")
        assert out[0].startswith("false\t")
        assert "outside" in out[0]

    def test_introspection_and_close(self):
        out, _ = run_lua("""
            print(coroutine.isyieldable())
            local co, main = coroutine.running()
            print(co, main)
            local c2 = coroutine.create(function() coroutine.yield() end)
            coroutine.resume(c2)
            print(coroutine.close(c2))
            print(coroutine.status(c2))
            print(type(c2))
        """)
        # lua 5.4: running() on the main thread returns the MAIN THREAD
        # VALUE (a thread) plus true — not nil
        assert out[0] == "false"
        assert out[1].startswith("thread: 0x")
        assert out[1].endswith("\ttrue")
        assert out[2:] == ["true", "dead", "thread"]

    def test_running_main_is_usable_thread_value(self):
        # the main-thread value round-trips through type/status like
        # any other thread
        out, _ = run_lua("""
            local main = coroutine.running()
            print(type(main), coroutine.status(main))
            local co = coroutine.create(function()
              local inner, is_main = coroutine.running()
              print(type(inner), is_main)
            end)
            coroutine.resume(co)
        """)
        assert out == ["thread\trunning", "thread\tfalse"]

    def test_tostring_thread_values(self):
        # thread values print as `thread: 0x...` (never the host
        # object repr), via print AND tostring, for live and dead
        out, _ = run_lua("""
            local co = coroutine.create(function() end)
            print(co)
            print(tostring(co))
            coroutine.resume(co)
            print(tostring(co))
        """)
        assert len(out) == 3
        for line in out:
            assert line.startswith("thread: 0x"), line
        assert "object at" not in "".join(out)   # the old repr leak

    def test_close_reports_unreclaimable_thread(self, monkeypatch):
        # a host frame that swallows the close unwind leaves the body
        # thread alive: close() must report failure (false + message),
        # not silently leak the slot accounting
        from libsplinter_tpu.scripting.microlua import LuaCoroutine

        monkeypatch.setattr(LuaCoroutine, "CLOSE_JOIN_TIMEOUT_S", 0.2)
        import threading
        release = threading.Event()

        def swallow(y):
            try:
                y()                    # parks in coroutine.yield
            except BaseException:
                release.wait(30.0)     # close signal swallowed

        lines = []
        rt = LuaRuntime(output=lines.append)
        rt.globals["swallow"] = swallow
        out = rt.run("""
            local co = coroutine.create(function()
              swallow(coroutine.yield)
            end)
            coroutine.resume(co)
            return coroutine.close(co)
        """)
        try:
            assert out[0] is False
            assert "did not exit" in out[1]
            assert rt._co_live == 1    # honest accounting: still live
        finally:
            release.set()              # let the parked thread finish

    def test_nested_resume_marks_outer_normal(self):
        out, _ = run_lua("""
            local inner = coroutine.create(function()
              coroutine.yield("i1")
            end)
            local outer = coroutine.create(function()
              local _, v = coroutine.resume(inner)
              coroutine.yield("o:" .. v)
            end)
            print(coroutine.resume(outer))
            print(coroutine.status(inner))
        """)
        assert out == ["true\to:i1", "suspended"]

    def test_self_resume_rejected(self):
        out, _ = run_lua("""
            local co
            co = coroutine.create(function()
              print(coroutine.resume(co))
            end)
            coroutine.resume(co)
        """)
        assert out == ["false\tcannot resume non-suspended coroutine"]

    def test_step_budget_shared_with_coroutine(self):
        lines = []
        rt = LuaRuntime(output=lines.append, max_steps=10_000)
        out = rt.run("""
            local co = coroutine.create(function()
              while true do end
            end)
            return coroutine.resume(co)
        """)
        assert out[0] is False
        assert "exceeded" in out[1]

    def test_break_outside_loop_is_catchable(self):
        out, _ = run_lua("print(pcall(function() break end))")
        assert out[0].startswith("false\t")
        assert "break" in out[0]

    def test_close_reclaims_parked_thread(self):
        import time

        lines = []
        rt = LuaRuntime(output=lines.append)
        rt.run("""
            local co = coroutine.create(function() coroutine.yield() end)
            coroutine.resume(co)
            coroutine.close(co)
        """)
        for _ in range(100):           # parked body unwinds async
            if rt._co_live == 0:
                break
            time.sleep(0.01)
        assert rt._co_live == 0

    def test_live_thread_cap_is_catchable(self):
        lines = []
        rt = LuaRuntime(output=lines.append, max_coroutines=4)
        out = rt.run("""
            held = {}              -- global: the follow-up run closes it
            local ok, err
            for i = 1, 8 do
              local co = coroutine.create(function()
                coroutine.yield()
              end)
              ok, err = pcall(coroutine.resume, co)
              if not ok then break end
              held[i] = co
            end
            return ok, err
        """)
        assert out[0] is False
        assert "too many live coroutines" in out[1]
        # closing a parked coroutine releases its slot synchronously
        out2 = rt.run("""
            coroutine.close(held[1])
            local co = coroutine.create(function() return 1 end)
            return coroutine.resume(co)
        """)
        assert out2[0] is True and out2[1] == 1


class TestGotoScopeRule:
    def test_forward_goto_into_local_scope_rejected(self):
        out, _ = run_lua("""
            print(pcall(function()
              goto skip
              local x = 5
              ::skip::
              return x
            end))
        """)
        assert out[0].startswith("false\t")
        assert "scope of a local" in out[0]

    def test_continue_carveout_with_locals_allowed(self):
        # label at end of block: jumping over a local is legal (the
        # lua 5.4 ::continue:: carve-out)
        out, _ = run_lua("""
            local s = 0
            for i = 1, 4 do
              if i % 2 == 0 then goto continue end
              local double = i * 2
              s = s + double
              ::continue::
            end
            print(s)
        """)
        assert out == ["8"]

    def test_backward_goto_exits_local_scope(self):
        # lua 5.4: a backward jump leaves the scope of locals declared
        # after the label, so the outer binding is visible again
        out, _ = run_lua("""
            local v = "g"
            do
              local first = true
              ::top::
              print(v)
              local v = "inner"
              if first then
                first = false
                goto top
              end
            end
        """)
        assert out == ["g", "g"]

    def test_duplicate_label_is_parse_error(self):
        with pytest.raises(LuaError, match="already defined"):
            run_lua("::a:: print(1) ::a:: print(2)")

    def test_runtime_close_unwinds_suspended(self):
        lines = []
        rt = LuaRuntime(output=lines.append)
        rt.run("""
            gen = coroutine.create(function()
              coroutine.yield(1)
              coroutine.yield(2)
            end)
            coroutine.resume(gen)
        """)
        assert rt._co_live == 1
        rt.close()
        assert rt._co_live == 0

    def test_runtime_context_manager(self):
        lines = []
        with LuaRuntime(output=lines.append) as rt:
            rt.run("""
                local co = coroutine.create(function()
                  coroutine.yield()
                end)
                coroutine.resume(co)
            """)
        assert rt._co_live == 0


class TestErrorValues:
    """error() objects are VALUES (Lua 5.4 §2.3): a table thrown by
    error() must come back VERBATIM from pcall — including across a
    coroutine.wrap boundary, where the re-raise used to coerce it to
    a string (the last open ADVICE item)."""

    def test_pcall_returns_table_error_value(self):
        out, _ = run_lua("""
            local ok, err = pcall(function()
              error({code = 42, msg = "structured"})
            end)
            print(ok, type(err), err.code, err.msg)
        """)
        assert out == ["false\ttable\t42\tstructured"]

    def test_pcall_returns_number_error_value(self):
        out, _ = run_lua("print(pcall(function() error(777) end))")
        assert out == ["false\t777"]

    def test_coroutine_resume_propagates_error_value(self):
        out, _ = run_lua("""
            local co = coroutine.create(function()
              error({tag = "t"})
            end)
            local ok, err = coroutine.resume(co)
            print(ok, type(err), err.tag)
        """)
        assert out == ["false\ttable\tt"]

    def test_wrap_rethrows_original_value_through_pcall(self):
        out, _ = run_lua("""
            local f = coroutine.wrap(function()
              coroutine.yield(1)
              error({why = "wrapped"})
            end)
            print(f())
            local ok, err = pcall(f)
            print(ok, type(err), err.why)
        """)
        assert out == ["1", "false\ttable\twrapped"]

    def test_assert_message_value_verbatim(self):
        out, _ = run_lua("""
            local ok, err = pcall(function() assert(false, {m = 1}) end)
            print(ok, type(err), err.m)
        """)
        assert out == ["false\ttable\t1"]

    def test_uncaught_error_carries_value_to_host(self):
        with pytest.raises(LuaError) as ei:
            run_lua('error({boom = true})')
        assert isinstance(ei.value.value, LuaTable)
        assert ei.value.value.get("boom") is True
