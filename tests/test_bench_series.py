"""The unified bench series runner (bench_series.py) is the round's
measurement spine: one tunnel claim must yield the whole evidence set,
with per-phase fencing so one bad phase can't erase the rest.  These
tests drive the orchestration logic with stub phases (fast) and one
real phase (kernels, tiny shapes, interpret mode) end to end."""
from __future__ import annotations

import json
import os
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench_series  # noqa: E402


@pytest.fixture
def ledger(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setattr(bench_series, "RESULTS_LOG", str(path))
    return path


def read_ledger(path):
    return [json.loads(ln) for ln in path.read_text().splitlines()]


def test_phase_fencing_and_status(ledger, monkeypatch):
    """A failing phase logs + moves on; later phases still record."""
    calls = []

    def ok_phase(ctx):
        calls.append("ok")
        return ctx.record({"metric": "m_ok", "value": 1.0,
                           "unit": "u", "vs_baseline": 0.0})

    def bad_phase(ctx):
        calls.append("bad")
        raise RuntimeError("synthetic failure")

    monkeypatch.setitem(bench_series.PHASE_FNS, "embed", bad_phase)
    monkeypatch.setitem(bench_series.PHASE_FNS, "profile", ok_phase)
    ctx = bench_series.run_series(phases=("embed", "profile"))
    assert calls == ["bad", "ok"]
    assert ctx.phase_status == {"embed": "failed", "profile": "ok"}
    assert ctx.headline is None
    recs = read_ledger(ledger)
    assert len(recs) == 1 and recs[0]["metric"] == "m_ok"
    assert "ts" in recs[0]


def test_deadline_skips_nonembed_phases(ledger, monkeypatch):
    """Past the window, non-embed phases skip; embed always runs."""
    ran = []
    monkeypatch.setitem(
        bench_series.PHASE_FNS, "embed",
        lambda ctx: ran.append("embed") or ctx.record(
            {"metric": "e", "value": 1.0, "unit": "u",
             "vs_baseline": 0.0}))
    monkeypatch.setitem(
        bench_series.PHASE_FNS, "kernels",
        lambda ctx: ran.append("kernels"))
    ctx = bench_series.run_series(
        phases=("embed", "kernels"),
        deadline_epoch=time.time() + 5)   # < every non-embed floor
    assert ran == ["embed"]
    assert ctx.phase_status == {"embed": "ok", "kernels": "skipped"}


def test_headline_recovery_file(ledger, monkeypatch, tmp_path):
    """The REAL phase_embed writes its record to SPTPU_BENCH_RESULTFILE
    (the recovery contract bench.py's parent depends on when a later
    phase hangs) — driven end to end at tiny sizes."""
    result = tmp_path / "result.json"
    monkeypatch.setenv("SPTPU_BENCH_RESULTFILE", str(result))
    monkeypatch.setenv("SPTPU_BENCH_STORE", f"/spt-series-test-{os.getpid()}")
    monkeypatch.setenv("BENCH_TEXTS", "8")
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_BUCKETS", "32")
    monkeypatch.setenv("BENCH_P50_PROBES", "2")
    ctx = bench_series.SeriesCtx(time.time() + 3600)
    import jax
    ctx.backend = jax.default_backend()
    ctx.n_devices = len(jax.devices())
    rec = bench_series.phase_embed(ctx)
    assert rec["metric"] == "embeddings_per_sec_per_chip"
    assert rec["value"] > 0
    saved = json.loads(result.read_text())
    assert saved["value"] == rec["value"] and "ts" not in saved
    # the ledger got the same record (with a timestamp)
    led = read_ledger(ledger)
    assert led[0]["metric"] == "embeddings_per_sec_per_chip"
    assert led[0]["detail"]["p50_samples"] == 2


def test_series_complete_requires_all_phases(ledger, monkeypatch, capsys):
    """ADVICE r4 (medium): series_complete means ALL_PHASES ran ok — a
    phase-restricted run must report false even when everything it was
    asked to run succeeded."""
    def embed_phase(ctx):
        ctx.headline = ctx.record(
            {"metric": "embeddings_per_sec_per_chip", "value": 5.0,
             "unit": "u", "vs_baseline": 0.1})

    monkeypatch.setitem(bench_series.PHASE_FNS, "embed", embed_phase)
    monkeypatch.setenv("BENCH_PHASES", "embed")
    assert bench_series.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["series_complete"] is False

    for name in bench_series.ALL_PHASES:
        if name != "embed":
            monkeypatch.setitem(
                bench_series.PHASE_FNS, name, lambda ctx: None)
    monkeypatch.setenv("BENCH_PHASES", ",".join(bench_series.ALL_PHASES))
    assert bench_series.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["series_complete"] is True


def test_store_ops_phase_real(ledger, monkeypatch):
    """The store_ops phase end to end at a short duration: runs the
    native stress harnesses in --json mode, asserts integrity, and
    ledgers the reference-contract comparison (VERDICT r4 #5)."""
    import subprocess

    build = os.path.join(ROOT, "native", "build")
    if not os.path.exists(os.path.join(build, "spt_stress")):
        subprocess.run(["make", "tests"],
                       cwd=os.path.join(ROOT, "native"), check=True)
    monkeypatch.setenv("STORE_OPS_MS", "300")
    ctx = bench_series.SeriesCtx(time.time() + 3600)
    rec = bench_series.phase_store_ops(ctx)
    assert rec["value"] > 0
    d = rec["detail"]
    assert d["mrsw_raw"]["corrupt"] == 0
    assert d["mrmw"]["corrupt"] == 0
    assert d["mrmw"]["writers"] == 32
    assert d["write_cpo"] > 0
    assert d["reference"]["write_cpo"] == 937.0
    led = read_ledger(ledger)
    assert led[0]["metric"] == "store_ops_per_sec"


def test_kernels_phase_real(ledger, monkeypatch):
    """The kernels phase end to end at tiny sizes: every kernel runs
    (interpret mode off-TPU), numerics checked vs the jnp oracle, and
    the record carries ok flags."""
    monkeypatch.setenv("KERNELS_SEQ", "64")
    monkeypatch.setenv("KERNELS_ROWS", "1024")
    monkeypatch.setenv("KERNELS_REPS", "2")
    ctx = bench_series.SeriesCtx(time.time() + 3600)
    import jax
    ctx.backend = jax.default_backend()
    rec = bench_series.phase_kernels(ctx)
    assert rec["value"] == 1.0, rec          # every ok flag true
    d = rec["detail"]
    assert d["flash_fwd"]["ok"] and d["flash_bwd"]["ok"]
    assert d["causal_prefill_gqa"]["ok"] and d["cosine_topk"]["ok"]
    assert read_ledger(ledger)[0]["metric"] == "kernels_smoke"


@pytest.mark.slow
def test_multichip_phase_real(ledger, monkeypatch):
    """The pod-sharded paged arm end to end on the virtual 8-device
    CPU mesh (tiny geometry): batch {32, 64} rows ledger with the
    LOUD cpu_mesh_smoke label and the r05 single-chip reference."""
    monkeypatch.setenv("BENCH_CPU", "1")
    monkeypatch.setenv("MULTICHIP_TOKENS", "8")
    ctx = bench_series.SeriesCtx(time.time() + 3600)
    import jax
    ctx.backend = jax.default_backend()
    ctx.n_devices = len(jax.devices())
    rec = bench_series.phase_multichip(ctx)
    d = rec["detail"]
    assert d["n_devices"] == 8 and d["tp"] >= 2
    assert d["cpu_mesh_smoke"] is True       # never a perf claim here
    assert set(d["tokens_per_sec_by_batch"]) == {"32", "64"}
    assert all(v > 0 for v in d["tokens_per_sec_by_batch"].values())
    assert d["r05_single_chip_dense_batch8"] == 612.3
    assert read_ledger(ledger)[0]["metric"] == \
        "multichip_paged_tokens_per_sec"


def test_multichip_phase_single_device_skips(ledger, monkeypatch):
    """A single-chip claim cannot shard: the phase ledgers an explicit
    skip row (series_complete stays true) instead of failing."""
    ctx = bench_series.SeriesCtx(time.time() + 3600)
    ctx.backend = "cpu"
    ctx.n_devices = 1
    rec = bench_series.phase_multichip(ctx)
    assert "skipped" in rec["detail"]
    assert read_ledger(ledger)[0]["value"] == 0.0
