"""Real-export parity pack (VERDICT r4 #6).

The model path had only ever loaded GGUF files produced by this repo's
own writer — a mirrored misunderstanding of the format or of llama.cpp's
tensor-name conventions would pass every test.  This suite closes that
hole offline (the image has no network and no real checkpoint):

  - tests/fixtures/llamacpp_export_manifest.json FREEZES the metadata
    keys + tensor names/shapes the public llama.cpp converters emit for
    the llama / bert / nomic-bert families (sha256-pinned below so it
    can't drift silently);
  - a minimal GGUF v3 writer implemented HERE, straight from the GGUF
    spec (magic/version/kv types/ggml-reversed dims/32-byte alignment)
    and deliberately NOT importing models/gguf_writer.py, materialises
    the manifest with seeded random weights;
  - models/gguf.py must then derive the right config from the metadata,
    consume EVERY non-derived tensor (a converter-emitted tensor the
    loader silently ignores is a parity bug), produce correctly-shaped
    trees, run a forward pass, and build working tokenizers from the
    tokenizer.ggml.* metadata alone.

Reference behavior being mirrored: the reference loads real Nomic GGUF
and chat-model files end to end (splinference.cpp:423-447,
splainference.cpp:414-448).
"""
from __future__ import annotations

import hashlib
import json
import os
import struct

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(ROOT, "tests", "fixtures",
                        "llamacpp_export_manifest.json")

# sha256 of the frozen manifest — update ONLY when deliberately
# extending the parity surface, never to make a loader change pass
MANIFEST_SHA256 = \
    "863cb6749640832739077de647733e93f33c390e7f575df1b6c38623f5e3460c"


# --------------------------------------------------------------------------
# independent GGUF v3 writer (from the spec; no repo writer imported)
# --------------------------------------------------------------------------

_GGUF_MAGIC = b"GGUF"
_GGUF_VERSION = 3
_ALIGN = 32
# value types per the spec
_T_U32, _T_F32, _T_STR, _T_ARR, _T_U64, _T_F64 = 4, 6, 8, 9, 10, 12
_T_I32 = 5


def _s(b: bytes) -> bytes:
    return struct.pack("<Q", len(b)) + b


def _kv(key: str, vtype: int, payload: bytes) -> bytes:
    return _s(key.encode()) + struct.pack("<I", vtype) + payload


def _kv_auto(key: str, val) -> bytes:
    if isinstance(val, bool):
        raise TypeError("bool kv not needed here")
    if isinstance(val, int):
        return _kv(key, _T_U32, struct.pack("<I", val))
    if isinstance(val, float):
        return _kv(key, _T_F32, struct.pack("<f", val))
    if isinstance(val, str):
        return _kv(key, _T_STR, _s(val.encode()))
    if isinstance(val, list) and val and isinstance(val[0], str):
        body = b"".join(_s(x.encode()) for x in val)
        return _kv(key, _T_ARR,
                   struct.pack("<IQ", _T_STR, len(val)) + body)
    if isinstance(val, list) and val and isinstance(val[0], float):
        return _kv(key, _T_ARR,
                   struct.pack("<IQ", _T_F32, len(val)) +
                   struct.pack(f"<{len(val)}f", *val))
    if isinstance(val, list):
        return _kv(key, _T_ARR,
                   struct.pack("<IQ", _T_I32, len(val)) +
                   struct.pack(f"<{len(val)}i", *val))
    raise TypeError(f"unsupported kv {key}={val!r}")


def write_spec_gguf(path: str, metadata: dict, tensors: dict) -> None:
    """tensors: name -> np.float32 array (numpy-order shape).  Dims are
    written REVERSED (ggml ne order: ne[0] = fastest-varying), F32,
    offsets aligned to 32 inside the tensor-data region."""
    infos = []
    blobs = []
    off = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        dims = arr.shape[::-1]
        info = (_s(name.encode()) +
                struct.pack("<I", len(dims)) +
                struct.pack(f"<{len(dims)}Q", *dims) +
                struct.pack("<I", 0) +             # GGML_TYPE_F32
                struct.pack("<Q", off))
        infos.append(info)
        raw = arr.tobytes()
        pad = (-len(raw)) % _ALIGN
        blobs.append(raw + b"\0" * pad)
        off += len(raw) + pad
    kvs = [_kv_auto(k, v) for k, v in metadata.items()]
    head = (_GGUF_MAGIC + struct.pack("<I", _GGUF_VERSION) +
            struct.pack("<Q", len(tensors)) +
            struct.pack("<Q", len(kvs)))
    body = head + b"".join(kvs) + b"".join(infos)
    pad = (-len(body)) % _ALIGN
    with open(path, "wb") as f:
        f.write(body + b"\0" * pad + b"".join(blobs))


# --------------------------------------------------------------------------
# fixtures
# --------------------------------------------------------------------------

def _manifest() -> dict:
    with open(MANIFEST) as f:
        return json.load(f)


def _seeded_tensors(spec: dict) -> dict:
    rng = np.random.default_rng(7)
    return {name: rng.standard_normal(shape).astype(np.float32) * 0.05
            for name, shape in spec["tensors"].items()}


def _materialise(tmp_path, model_key: str) -> tuple[str, dict, dict]:
    spec = _manifest()["models"][model_key]
    md = dict(spec["metadata"])
    if "spm_tokens" in spec:
        md["tokenizer.ggml.tokens"] = spec["spm_tokens"]
        md["tokenizer.ggml.scores"] = [
            0.0 if i < 3 else -float(i) for i in
            range(len(spec["spm_tokens"]))]
        md["tokenizer.ggml.token_type"] = spec["spm_token_types"]
    if "wordpiece_tokens" in spec:
        md["tokenizer.ggml.tokens"] = spec["wordpiece_tokens"]
    tensors = _seeded_tensors(spec)
    path = str(tmp_path / f"{model_key}.gguf")
    write_spec_gguf(path, md, tensors)
    return path, spec, tensors


class _Recorder:
    """Wrap GgufFile.tensor to record which names a loader consumes."""

    def __init__(self, monkeypatch):
        from libsplinter_tpu.models.gguf import GgufFile
        self.read: set[str] = set()
        orig = GgufFile.tensor

        def spy(gf, name):
            self.read.add(name)
            return orig(gf, name)

        monkeypatch.setattr(GgufFile, "tensor", spy)


# --------------------------------------------------------------------------
# the manifest itself
# --------------------------------------------------------------------------

def test_manifest_is_frozen():
    with open(MANIFEST, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    assert digest == MANIFEST_SHA256, (
        f"llamacpp_export_manifest.json changed (sha256 {digest}); if "
        f"the parity surface was deliberately extended, update the pin")


# --------------------------------------------------------------------------
# llama decoder family
# --------------------------------------------------------------------------

def test_llama_decoder_config_and_full_consumption(tmp_path, monkeypatch):
    from libsplinter_tpu.models.gguf import (
        decoder_config_from_gguf, load_decoder_params,
    )
    path, spec, tensors = _materialise(tmp_path, "llama_decoder")
    cfg = decoder_config_from_gguf(path)
    assert cfg.hidden == 64 and cfg.layers == 2
    assert cfg.heads == 4 and cfg.kv_heads == 2
    assert cfg.mlp_dim == 128 and cfg.max_len == 128
    assert cfg.vocab_size == len(spec["spm_tokens"])
    assert cfg.rope_base == 10000.0
    assert abs(cfg.rms_eps - 1e-5) < 1e-12

    rec = _Recorder(monkeypatch)
    params = load_decoder_params(path, cfg)
    unread = (set(spec["tensors"]) - rec.read
              - set(spec["derived_tensors"]))
    assert not unread, (
        f"converter-emitted tensors the loader never consumed: "
        f"{sorted(unread)}")
    # spot-check mapping + transposition (ggml numpy view is (out, in);
    # flax kernels are (in, out))
    p = params["params"]
    np.testing.assert_allclose(
        np.asarray(p["layer_0"]["attn"]["q"]["kernel"]),
        tensors["blk.0.attn_q.weight"].T, rtol=1e-5)
    assert p["layer_1"]["down"]["kernel"].shape == (128, 64)
    assert p["lm_head"]["kernel"].shape == (64, 32)


def test_llama_decoder_forward_runs(tmp_path):
    import jax.numpy as jnp

    from libsplinter_tpu.models.decoder import Decoder, init_cache
    from libsplinter_tpu.models.gguf import (
        decoder_config_from_gguf, load_decoder_params,
    )
    path, _, _ = _materialise(tmp_path, "llama_decoder")
    cfg = decoder_config_from_gguf(path)
    params = load_decoder_params(path, cfg)
    model = Decoder(cfg)
    cache = init_cache(cfg, 1)
    ids = np.array([[1, 4, 5, 8]], np.int32)
    logits, _ = model.apply(params, jnp.asarray(ids), cache,
                            jnp.int32(0))
    assert logits.shape[0] == 1 and logits.shape[-1] == cfg.vocab_size
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_spm_tokenizer_from_metadata(tmp_path):
    from libsplinter_tpu.models.gguf import load_tokenizer
    path, spec, _ = _materialise(tmp_path, "llama_decoder")
    tok = load_tokenizer(path)
    toks = spec["spm_tokens"]
    ids = tok.encode("the quick fox")
    assert ids, "empty encoding"
    assert ids[0] == 1, "llama.cpp semantics: BOS (<s>) leads"
    text = "".join(toks[i] for i in ids[1:] if i < len(toks))
    assert text.replace("▁", " ").strip() == "the quick fox"
    # control tokens parse atomically (llama.cpp parse_special):
    # id 1 appears TWICE — the leading BOS plus the literal "<s>"
    # (character-piece tokenization of "<s>" would leave count at 1)
    ids2 = list(tok.encode("<s>the"))
    assert ids2.count(1) == 2, ids2


# --------------------------------------------------------------------------
# bert / nomic-bert encoder families
# --------------------------------------------------------------------------

@pytest.mark.parametrize("key,variant", [
    ("bert_encoder", "bert"),
    ("nomic_bert_encoder", "nomic"),
])
def test_encoder_config_and_full_consumption(tmp_path, monkeypatch,
                                             key, variant):
    from libsplinter_tpu.models.gguf import (
        encoder_config_from_gguf, load_encoder_params,
    )
    path, spec, tensors = _materialise(tmp_path, key)
    cfg = encoder_config_from_gguf(path)
    assert cfg.variant == variant
    assert cfg.hidden == 32 and cfg.layers == 1 and cfg.heads == 2
    assert cfg.mlp_dim == 64
    assert cfg.vocab_size == len(spec["wordpiece_tokens"])
    assert abs(cfg.layer_norm_eps - 1e-12) < 1e-20

    rec = _Recorder(monkeypatch)
    params = load_encoder_params(path, cfg)
    unread = (set(spec["tensors"]) - rec.read
              - set(spec["derived_tensors"]))
    assert not unread, (
        f"converter-emitted tensors the loader never consumed: "
        f"{sorted(unread)}")
    # token_types row 0 must be folded into the embedding table
    folded = (tensors["token_embd.weight"]
              + tensors["token_types.weight"][0][None, :])
    np.testing.assert_allclose(
        np.asarray(params["params"]["tok_emb"]["embedding"]), folded,
        rtol=1e-5)


@pytest.mark.parametrize("key", ["bert_encoder", "nomic_bert_encoder"])
def test_encoder_forward_runs(tmp_path, key):
    from libsplinter_tpu.models.encoder import Encoder
    from libsplinter_tpu.models.gguf import (
        encoder_config_from_gguf, load_encoder_params,
    )
    path, _, _ = _materialise(tmp_path, key)
    cfg = encoder_config_from_gguf(path)
    params = load_encoder_params(path, cfg)
    model = Encoder(cfg)
    ids = np.array([[2, 5, 14, 3]], np.int32)   # [CLS] store ##s [SEP]
    mask = np.ones_like(ids)
    out = np.asarray(model.apply(params, ids, mask))
    assert out.shape[0] == 1 and out.shape[-1] == cfg.hidden
    assert np.isfinite(out).all()
    # pooled embeddings come back L2-normalised (reference forces mean
    # pooling + normalise, splinference.cpp:435)
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0,
                               rtol=1e-4)


def test_bert_wordpiece_tokenizer_from_metadata(tmp_path):
    from libsplinter_tpu.models.gguf import load_tokenizer
    path, spec, _ = _materialise(tmp_path, "bert_encoder")
    tok = load_tokenizer(path)
    toks = spec["wordpiece_tokens"]
    # greedy longest-match + ## continuation, ids ARE vocab positions
    ids = tok.encode("stores the")
    want = [toks.index("[CLS]"), toks.index("store"), toks.index("##s"),
            toks.index("the"), toks.index("[SEP]")]
    assert list(ids) == want, (ids, want)
    # unknown word falls back to [UNK]
    ids2 = tok.encode("zzz")
    assert toks.index("[UNK]") in list(ids2)
