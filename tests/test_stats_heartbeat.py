"""Daemon stats heartbeats: structured JSON snapshots in debug-labeled
store keys (__embedder_stats / __completer_stats) — the observability
counterpart of the reference's append-only __debug channel
(/root/reference/splainference.cpp:94-100), consumable by the sidecar's
group-63 debug watch."""
from __future__ import annotations

import json

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder


def _mkstore(tag):
    name = f"/spt-stats-{tag}"
    Store.unlink(name)
    return name, Store.create(name, nslots=64, max_val=1024, vec_dim=8)


def test_embedder_stats_heartbeat(tmp_path):
    name, st = _mkstore(tmp_path.name)
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("k", "text")
        st.set_type("k", 0x80)        # T_VARTEXT
        st.label_or("k", P.LBL_EMBED_REQ)
        emb.run_once()
        emb.publish_stats()
        snap = json.loads(st.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        assert snap["embedded"] == 1
        assert snap["pending"] == 0
        assert "ts" in snap
        assert st.labels(P.KEY_EMBED_STATS) & P.LBL_DEBUG
    finally:
        st.close()
        Store.unlink(name)


def test_heartbeat_degrades_on_overflow(tmp_path):
    """A snapshot too big for max_val must degrade to the scalar
    counters (truncated flag set), not silently vanish — enabling
    tracing must never remove the heartbeat."""
    name, st = _mkstore(f"ovf-{tmp_path.name}")
    try:
        big = {"completions": 7, "spans": {f"s{i}": {"n": i,
               "total_ms": 1.0, "max_ms": 1.0} for i in range(200)}}
        P.publish_heartbeat(st, "__hb", big)
        snap = json.loads(st.get("__hb").rstrip(b"\0"))
        assert snap["completions"] == 7
        assert snap.get("truncated") is True
        assert "spans" not in snap
    finally:
        st.close()
        Store.unlink(name)


class _SetSpy:
    """Store facade recording every publish attempt's section set —
    the degradation ORDER is observable, not just the survivors."""

    def __init__(self, st):
        self._st = st
        self.attempts: list[list[str]] = []

    def set(self, key, val):
        self.attempts.append(sorted(json.loads(val).keys()))
        self._st.set(key, val)

    def label_or(self, key, mask):
        self._st.label_or(key, mask)


def _traced_payload():
    """A realistic SPTPU_TRACE=1 embedder heartbeat: scalar counters +
    a slow log (largest), a quantiles section (medium), and recorder
    accounting (small)."""
    slow = [{"id": (1 << 24) | i, "key": f"bench/{i}",
             "wall_ms": 123.456, "ts": 1e9,
             "slow_threshold_ms": 10.0,
             "events": [[s, 1.234] for s in P.PIPELINE_STAGES]}
            for i in range(12)]
    quantiles = {s: {"n": 30, "total_ms": 99.9, "max_ms": 9.9,
                     "p50_ms": 1.11, "p90_ms": 2.22, "p95_ms": 2.88,
                     "p99_ms": 3.33} for s in P.PIPELINE_STAGES}
    return {"wakes": 9, "embedded": 8, "pending": 0,
            "overlap_ratio": 0.5,
            "recorder": {"recorded": 12, "dropped": 0,
                         "slow_promoted": 12},
            "quantiles": quantiles, "slow_log": slow}


@pytest.mark.obs
def test_heartbeat_drop_order_slow_log_then_quantiles(tmp_path):
    """Section-by-section degradation drops the LARGEST section first:
    for the traced heartbeat that is the slow log, then quantiles —
    and the scalar core counters always land last-resort."""
    # max_val sized so BOTH optional sections must go (core counters
    # + recorder accounting still fit)
    name = f"/spt-stats-order-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=320, vec_dim=8)
    try:
        spy = _SetSpy(st)
        P.publish_heartbeat(spy, "__hb", _traced_payload())
        # attempt 0 carried everything; slow_log (largest) went first;
        # quantiles only after it; core counters never dropped
        assert "slow_log" in spy.attempts[0]
        assert "quantiles" in spy.attempts[0]
        dropped_slow = next(i for i, a in enumerate(spy.attempts)
                            if "slow_log" not in a)
        dropped_q = next(i for i, a in enumerate(spy.attempts)
                         if "quantiles" not in a)
        assert dropped_slow < dropped_q, spy.attempts
        assert all("embedded" in a and "wakes" in a
                   for a in spy.attempts)
        snap = json.loads(st.get("__hb").rstrip(b"\0"))
        assert snap.get("truncated") is True
        assert "slow_log" not in snap
        assert snap["embedded"] == 8
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.obs
def test_heartbeat_quantiles_survive_slow_log_drop(tmp_path):
    """With room for everything but the slow log, quantiles stay: the
    bench's stage table degrades LAST among the optional sections."""
    name = f"/spt-stats-q-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        P.publish_heartbeat(st, "__hb", _traced_payload())
        snap = json.loads(st.get("__hb").rstrip(b"\0"))
        assert snap.get("truncated") is True
        assert "slow_log" not in snap
        assert set(P.PIPELINE_STAGES) <= set(snap["quantiles"])
        assert snap["embedded"] == 8
    finally:
        st.close()
        Store.unlink(name)


def test_completer_stats_heartbeat(tmp_path):
    name, st = _mkstore(tmp_path.name)
    try:
        comp = Completer(st, generate_fn=lambda p: iter([b"ok "]),
                         template="none")
        comp.attach()
        st.set("q", "hi")
        st.label_or("q", P.LBL_INFER_REQ)
        comp.run_once()
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert snap["completions"] == 1
        assert snap["vanished"] == 0
        assert st.labels(P.KEY_COMPLETE_STATS) & P.LBL_DEBUG
    finally:
        st.close()
        Store.unlink(name)
