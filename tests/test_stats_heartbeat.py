"""Daemon stats heartbeats: structured JSON snapshots in debug-labeled
store keys (__embedder_stats / __completer_stats) — the observability
counterpart of the reference's append-only __debug channel
(/root/reference/splainference.cpp:94-100), consumable by the sidecar's
group-63 debug watch."""
from __future__ import annotations

import json

import numpy as np

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder


def _mkstore(tag):
    name = f"/spt-stats-{tag}"
    Store.unlink(name)
    return name, Store.create(name, nslots=64, max_val=1024, vec_dim=8)


def test_embedder_stats_heartbeat(tmp_path):
    name, st = _mkstore(tmp_path.name)
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("k", "text")
        st.set_type("k", 0x80)        # T_VARTEXT
        st.label_or("k", P.LBL_EMBED_REQ)
        emb.run_once()
        emb.publish_stats()
        snap = json.loads(st.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        assert snap["embedded"] == 1
        assert snap["pending"] == 0
        assert "ts" in snap
        assert st.labels(P.KEY_EMBED_STATS) & P.LBL_DEBUG
    finally:
        st.close()
        Store.unlink(name)


def test_heartbeat_degrades_on_overflow(tmp_path):
    """A snapshot too big for max_val must degrade to the scalar
    counters (truncated flag set), not silently vanish — enabling
    tracing must never remove the heartbeat."""
    name, st = _mkstore(f"ovf-{tmp_path.name}")
    try:
        big = {"completions": 7, "spans": {f"s{i}": {"n": i,
               "total_ms": 1.0, "max_ms": 1.0} for i in range(200)}}
        P.publish_heartbeat(st, "__hb", big)
        snap = json.loads(st.get("__hb").rstrip(b"\0"))
        assert snap["completions"] == 7
        assert snap.get("truncated") is True
        assert "spans" not in snap
    finally:
        st.close()
        Store.unlink(name)


def test_completer_stats_heartbeat(tmp_path):
    name, st = _mkstore(tmp_path.name)
    try:
        comp = Completer(st, generate_fn=lambda p: iter([b"ok "]),
                         template="none")
        comp.attach()
        st.set("q", "hi")
        st.label_or("q", P.LBL_INFER_REQ)
        comp.run_once()
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert snap["completions"] == 1
        assert snap["vanished"] == 0
        assert st.labels(P.KEY_COMPLETE_STATS) & P.LBL_DEBUG
    finally:
        st.close()
        Store.unlink(name)
