"""Supervisor (engine/supervisor.py): crash detection, jittered
exponential backoff, circuit breaker + half-open probe, hung-heartbeat
kill, the chaos-drill fault-env contract, and the obs surface
(supervisor heartbeat, `spt metrics`, protocol.lane_down /
daemon_live veto).  Dummy children (no jax) keep this tier fast."""
from __future__ import annotations

import contextlib
import io
import json
import os
import subprocess
import sys
import time
import uuid

import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.supervisor import Supervisor

pytestmark = pytest.mark.chaos


@pytest.fixture
def sstore():
    name = f"/spt-sup-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=128, max_val=2048, vec_dim=8)
    yield st
    st.close()
    Store.unlink(name)


def _crasher(code=7):
    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, "-c", f"import sys; sys.exit({code})"])
    return spawn


def _sleeper():
    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(600)"])
    return spawn


def _drain(sup, rounds, dt=0.02):
    for _ in range(rounds):
        sup.poll_once()
        time.sleep(dt)


def _poll_until(sup, cond, *, timeout=15.0, dt=0.02,
                between=None) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.poll_once()
        if between is not None:
            between()
        if cond():
            return True
        time.sleep(dt)
    return False


def test_crash_restarts_with_growing_backoff(sstore):
    sup = Supervisor(sstore.name, lanes=("searcher",),
                     spawn_fn=_crasher(), store=sstore,
                     backoff_base_ms=40, backoff_max_ms=10_000,
                     breaker_threshold=100, breaker_window_s=60)
    try:
        backoffs = []
        # time-based deadline, not an iteration budget: each crash
        # cycle pays a real interpreter spawn plus jittered backoff,
        # so a fixed poll count is flaky on a slow box
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(backoffs) < 4:
            sup.poll_once()
            ln = sup.lanes["searcher"]
            if ln.state == "backoff" and (not backoffs
                                          or ln.backoff_ms != backoffs[-1]):
                backoffs.append(ln.backoff_ms)
            time.sleep(0.02)
        ln = sup.lanes["searcher"]
        assert ln.restarts >= 2
        assert ln.last_exit == 7
        assert len(backoffs) >= 4
        # exponential growth through the jitter: crash k's backoff is
        # base*2^(k-1)*U(0.5,1.5), so backoff[k+2] > backoff[k] always
        for a, b in zip(backoffs, backoffs[2:]):
            assert b > a
    finally:
        sup.shutdown()


def test_breaker_opens_and_marks_lane_down(sstore):
    sup = Supervisor(sstore.name, lanes=("searcher",),
                     spawn_fn=_crasher(), store=sstore,
                     backoff_base_ms=5, breaker_threshold=3,
                     breaker_window_s=30, breaker_cooldown_s=600)
    try:
        ln = sup.lanes["searcher"]
        assert _poll_until(sup, lambda: ln.state == "down")
        assert ln.breaker_opens == 1
        # the down marker is what CLI clients consult: lane_down True,
        # and daemon_live refuses dispatch even with a fresh searcher
        # heartbeat on the store
        assert P.lane_down(sstore, "searcher")
        P.publish_heartbeat(sstore, P.KEY_SEARCH_STATS, {"served": 0})
        from libsplinter_tpu.engine.searcher import daemon_live
        assert not daemon_live(sstore)
        assert not P.lane_down(sstore, "embedder")   # only the broken lane
    finally:
        sup.shutdown()


def test_breaker_half_open_probe_closes_on_health(sstore):
    """After the cooldown the breaker spawns ONE probe child; a probe
    that stays healthy past healthy_after_s closes the breaker."""
    calls = {"n": 0}

    def spawn(lane):
        calls["n"] += 1
        if calls["n"] <= 3:           # first three children crash
            return subprocess.Popen(
                [sys.executable, "-c", "import sys; sys.exit(9)"])
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(600)"])

    sup = Supervisor(sstore.name, lanes=("searcher",), spawn_fn=spawn,
                     store=sstore, backoff_base_ms=5,
                     breaker_threshold=3, breaker_window_s=30,
                     breaker_cooldown_s=0.2, healthy_after_s=0.1,
                     startup_grace_s=600)
    try:
        ln = sup.lanes["searcher"]
        assert _poll_until(sup, lambda: ln.breaker_opens == 1)
        # the probe child publishes nothing itself; a fresh heartbeat
        # is what _watch_live needs to call it healthy
        assert _poll_until(
            sup,
            lambda: (ln.state == "running" and not ln.half_open
                     and ln.consecutive == 0),
            between=lambda: P.publish_heartbeat(
                sstore, P.KEY_SEARCH_STATS, {}),
            dt=0.05)
        assert ln.state == "running"
        assert not ln.half_open
        assert ln.consecutive == 0
        assert not P.lane_down(sstore, "searcher")
    finally:
        sup.shutdown()


def test_hung_heartbeat_gets_killed_and_restarted(sstore):
    """A live pid with a stale heartbeat is a hung daemon: SIGKILL +
    restart (the crash-only remedy), counted as hung_kills."""
    sup = Supervisor(sstore.name, lanes=("embedder",),
                     spawn_fn=_sleeper(), store=sstore,
                     backoff_base_ms=5, breaker_threshold=50,
                     heartbeat_timeout_s=0.2, startup_grace_s=0.2)
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sup.poll_once()
            if sup.lanes["embedder"].hung_kills >= 1:
                break
            time.sleep(0.05)
        ln = sup.lanes["embedder"]
        assert ln.hung_kills >= 1
        assert ln.last_exit == -9     # SIGKILL, not a polite exit
    finally:
        sup.shutdown()


def test_fault_env_stripped_from_respawns(sstore, monkeypatch):
    """The chaos-drill contract: SPTPU_FAULT reaches generation 1 only
    (a drill proves the RESTART recovers; an inherited crash@1 would
    re-fire forever) unless keep_faults opts back in."""
    monkeypatch.setenv("SPTPU_FAULT", "searcher.gather:crash@1")
    sup = Supervisor(sstore.name, lanes=("searcher",),
                     spawn_fn=_crasher(), store=sstore)
    ln = sup.lanes["searcher"]
    ln.generation = 1
    assert "SPTPU_FAULT" in sup._child_env(ln)
    ln.generation = 2
    assert "SPTPU_FAULT" not in sup._child_env(ln)
    keep = Supervisor(sstore.name, lanes=("searcher",),
                      spawn_fn=_crasher(), store=sstore,
                      keep_faults=True)
    keep.lanes["searcher"].generation = 2
    assert "SPTPU_FAULT" in keep._child_env(keep.lanes["searcher"])


def test_supervisor_heartbeat_and_metrics_exposition(sstore):
    """Restart/backoff/breaker counters publish through the existing
    obs surface: __supervisor_stats JSON and `spt metrics`
    Prometheus lines."""
    sup = Supervisor(sstore.name, lanes=("searcher", "embedder"),
                     spawn_fn=_crasher(), store=sstore,
                     backoff_base_ms=5, breaker_threshold=3,
                     breaker_window_s=30, breaker_cooldown_s=600)
    try:
        assert _poll_until(
            sup, lambda: all(ln.state == "down"
                             for ln in sup.lanes.values()))
        snap = json.loads(
            sstore.get(P.KEY_SUPERVISOR_STATS).rstrip(b"\0"))
        assert snap["pid"] == os.getpid()
        for lane in ("searcher", "embedder"):
            sec = snap["lanes"][lane]
            assert sec["state"] == "down"
            assert sec["restarts"] >= 2
            assert sec["breaker_opens"] == 1

        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(sstore.name)
        try:
            fn, _, _ = COMMANDS["metrics"]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                fn(ses, [])
            out = buf.getvalue()
        finally:
            ses.close()
        assert 'sptpu_supervisor_lane_down{lane="searcher"} 1' in out
        assert 'sptpu_supervisor_lane_breaker_opens{lane="searcher"} 1' \
            in out
        assert 'sptpu_supervisor_lane_restarts{lane="embedder"}' in out
        assert "sptpu_supervisor_polls" in out
    finally:
        sup.shutdown()


def test_poll_fault_site_live_and_survivable(sstore):
    """`supervisor.poll` chaos reachability (splint SPL104): the
    supervision-step fault site raises out of poll_once on its hit
    window — run()'s step firewall is the production containment —
    and the step after the window supervises normally."""
    from libsplinter_tpu.utils import faults

    sup = Supervisor(sstore.name, lanes=("searcher",),
                     spawn_fn=_sleeper(), store=sstore)
    faults.arm("supervisor.poll:raise@1")
    try:
        assert faults.registered_sites() == ("supervisor.poll",)
        with pytest.raises(faults.FaultInjected):
            sup.poll_once()
        sup.poll_once()                  # window passed: step runs
        assert sup.polls == 1
        assert sup.lanes["searcher"].proc is not None
    finally:
        faults.disarm()
        sup.shutdown()


def test_reclaim_closed_respects_handoff_sides(sstore):
    """The disaggregated lanes' straggler reclaim: a dead PREFILL
    replica's sweep must not clear_handoff + re-queue rows a live
    decode replica has adopted (SERVICING|DECODE_READY), and a dead
    DECODE replica's sweep must not re-queue SERVICING-only rows a
    live prefill replica is servicing — both stripe maps cover the
    same slot space."""
    st = sstore
    sup = Supervisor(st.name, lanes=("prefill", "decode"),
                     spawn_fn=_sleeper(), store=st)
    all_stripes = tuple(range(P.DEFAULT_STRIPE_WIDTH))
    st.set("adopted", "prompt bytes")
    st.label_or("adopted", P.LBL_SERVICING | P.LBL_DECODE_READY)
    aidx = st.find_index("adopted")
    assert P.write_handoff_record(st, aidx, {
        "len": 3, "ids": [1, 2, 3], "carry": 5, "n_tok": 1,
        "remaining": 7, "disp_left": 7,
        "plen": st.value_len("adopted"), "t0": 0, "tenant": 0,
        "deadline": None, "wire_pages": 0, "quant": False})
    st.set("claim", "prompt bytes")
    st.label_or("claim", P.LBL_SERVICING)

    # dead prefill replica: its own SERVICING-only row re-queues,
    # the decode-owned row (and its record) is untouchable
    assert sup._reclaim_closed("prefill", all_stripes) == 1
    labels = st.labels("adopted")
    assert labels & P.LBL_SERVICING and labels & P.LBL_DECODE_READY
    assert P.read_handoff_record(st, aidx) is not None
    labels = st.labels("claim")
    assert labels & P.LBL_WAITING and not labels & P.LBL_SERVICING

    # dead decode replica: the adopted row rolls back to bare
    # DECODE_READY, the prefill claim is untouchable
    st.label_clear("claim", P.LBL_WAITING | P.LBL_INFER_REQ)
    st.label_or("claim", P.LBL_SERVICING)
    assert sup._reclaim_closed("decode", all_stripes) == 1
    labels = st.labels("claim")
    assert labels & P.LBL_SERVICING and not labels & P.LBL_WAITING
    labels = st.labels("adopted")
    assert labels & P.LBL_DECODE_READY
    assert not labels & P.LBL_SERVICING
    assert P.read_handoff_record(st, aidx) is not None


def test_unknown_lane_rejected(sstore):
    with pytest.raises(ValueError):
        Supervisor(sstore.name, lanes=("warp-drive",), store=sstore)


def test_shutdown_terminates_children(sstore):
    sup = Supervisor(sstore.name, lanes=("completer",),
                     spawn_fn=_sleeper(), store=sstore)
    sup.poll_once()
    pid = sup.lanes["completer"].pid
    assert pid and P.pid_alive(pid)
    sup.shutdown()
    deadline = time.monotonic() + 5
    while P.pid_alive(pid) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not P.pid_alive(pid)
    assert sup.lanes["completer"].state == "init"
