"""Fused streaming top-k parity vs the score-matrix + lax.top_k
reference path.

The Pallas kernel runs in INTERPRET mode so the CPU tier-1 suite
covers the actual kernel body (block accumulator, in-VMEM select,
tie-break, filler contract), not a shadow implementation.  Reference
ranking = stable argsort over cosine_scores on the same backend —
identical tie-break semantics to lax.top_k (smallest index first).
`make search-check` runs this file.
"""
import numpy as np
import pytest

from libsplinter_tpu.ops.similarity import (FUSED_K_MAX, NEG_INF,
                                            cosine_scores, cosine_topk,
                                            cosine_topk_batch,
                                            topk_program)

BLOCK = 64          # small tile: several grid steps per tiny lane


def _ref_topk(vectors, queries, mask, k, mxu_bf16=False):
    """(Q, k) reference scores + indices: the unfused path's math with
    lax.top_k's stable smallest-index tie-break."""
    if mxu_bf16:
        import jax.numpy as jnp
        from libsplinter_tpu.ops.similarity import _cosine_scores_pallas
        n, d = vectors.shape
        npad = -(-n // BLOCK) * BLOCK
        dpad = -(-d // 128) * 128
        q = queries.shape[0]
        qpad = max(8, -(-q // 8) * 8)
        v = np.zeros((npad, dpad), np.float32)
        v[:n, :d] = vectors
        qs = np.zeros((qpad, dpad), np.float32)
        qs[:q, :d] = queries
        m = np.zeros((npad, 1), np.float32)
        m[:n, 0] = np.ones(n) if mask is None else mask
        scores = np.asarray(_cosine_scores_pallas(
            jnp.asarray(v), jnp.asarray(qs), jnp.asarray(m),
            block_n=BLOCK, interpret=True, mxu_bf16=True))[:n, :q]
    else:
        scores = np.asarray(cosine_scores(vectors, queries, mask,
                                          use_pallas=False))
    out_s = np.empty((queries.shape[0], k), np.float32)
    out_i = np.empty((queries.shape[0], k), np.int64)
    for c in range(queries.shape[0]):
        order = np.argsort(-scores[:, c], kind="stable")[:k]
        out_s[c] = scores[order, c]
        out_i[c] = order
    return out_s, out_i


def _assert_parity(vectors, queries, mask, k, mxu_bf16=False):
    """Fused results must be rank-identical to the reference wherever
    real candidates exist, and carry the (NEG_INF, -1) filler beyond
    them."""
    got_s, got_i = cosine_topk_batch(
        vectors, queries, min(k, len(vectors)), mask, fused=True,
        interpret=True, use_pallas=True, block_n=BLOCK,
        mxu_bf16=mxu_bf16)
    ref_s, ref_i = _ref_topk(vectors, queries, mask,
                             min(k, len(vectors)), mxu_bf16)
    for c in range(queries.shape[0]):
        valid = ref_s[c] > -1e29
        np.testing.assert_allclose(got_s[c][valid], ref_s[c][valid],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(got_i[c][valid], ref_i[c][valid])
        filler = ~valid
        assert (got_s[c][filler] <= -1e29).all()
        assert (got_i[c][filler] == -1).all()


def _lane(rng, n, d, kind):
    """Candidate value distributions per dtype family.  bf16/int8 data
    is quantized-then-dequantized f32 — dense with exact-tie mass, the
    regime where a sloppy selector's tie-break diverges first."""
    x = rng.normal(size=(n, d)).astype(np.float32)
    if kind == "bf16":
        import jax.numpy as jnp
        return np.asarray(jnp.asarray(x, jnp.bfloat16), np.float32)
    if kind == "int8":
        scale = np.abs(x).max() / 127.0
        return (np.round(x / scale) * scale).astype(np.float32)
    return x


@pytest.mark.parametrize("kind", ["f32", "bf16", "int8"])
@pytest.mark.parametrize("n", [64, 200, 333])   # 333: N % block != 0
@pytest.mark.parametrize("k", [1, 7, 20])
def test_parity_dtypes_and_shapes(kind, n, k):
    rng = np.random.default_rng(hash((kind, n, k)) % 2**31)
    vectors = _lane(rng, n, 48, kind)
    queries = _lane(rng, 4, 48, kind)
    _assert_parity(vectors, queries, None, k)


@pytest.mark.parametrize("pattern", ["random", "prefix", "all_off",
                                     "zeros_and_mask"])
def test_mask_patterns(pattern):
    rng = np.random.default_rng(5)
    vectors = rng.normal(size=(150, 32)).astype(np.float32)
    queries = rng.normal(size=(3, 32)).astype(np.float32)
    mask = np.ones(150, np.float32)
    if pattern == "random":
        mask = (rng.random(150) > 0.5).astype(np.float32)
    elif pattern == "prefix":
        mask[:97] = 0.0
    elif pattern == "all_off":
        mask[:] = 0.0
    elif pattern == "zeros_and_mask":
        vectors[10:40] = 0.0          # un-embedded slots
        mask[60:80] = 0.0             # bloom-filtered rows
    _assert_parity(vectors, queries, mask, 12)


def test_exact_ties_index_stable():
    """Duplicated / colinear rows score EXACTLY equal; the fused
    selector must return the same (smallest-first) winners as
    lax.top_k."""
    rng = np.random.default_rng(11)
    vectors = (rng.integers(-3, 4, size=(130, 24)).astype(np.float32)
               / 3.0)
    vectors[77] = vectors[5]
    vectors[99] = vectors[5] * 2.5    # colinear: same cosine
    vectors[128] = vectors[5]
    queries = vectors[[5, 40]]
    _assert_parity(vectors, queries, None, 10)


def test_k_exceeds_valid_rows():
    rng = np.random.default_rng(3)
    vectors = np.zeros((96, 16), np.float32)
    vectors[[4, 50, 91]] = rng.normal(size=(3, 16)).astype(np.float32)
    q = rng.normal(size=16).astype(np.float32)
    s, i = cosine_topk(vectors, q, 10, fused=True, interpret=True,
                       use_pallas=True, block_n=32)
    assert (s[3:] <= -1e29).all() and (i[3:] == -1).all()
    assert set(i[:3].tolist()) == {4, 50, 91}


def test_bf16_fused_matches_bf16_reference():
    rng = np.random.default_rng(17)
    vectors = rng.standard_normal((256, 128)).astype(np.float32)
    vectors /= np.linalg.norm(vectors, axis=1, keepdims=True)
    queries = rng.standard_normal((8, 128)).astype(np.float32)
    _assert_parity(vectors, queries, None, 10, mxu_bf16=True)


def test_single_query_contract():
    rng = np.random.default_rng(23)
    vectors = rng.normal(size=(100, 40)).astype(np.float32)
    q = rng.normal(size=40).astype(np.float32)
    s, i = cosine_topk(vectors, q, 6, fused=True, interpret=True,
                       use_pallas=True, block_n=BLOCK)
    ref_s, ref_i = _ref_topk(vectors, q[None, :], None, 6)
    np.testing.assert_allclose(s, ref_s[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(i, ref_i[0])
    assert s.shape == (6,) and i.shape == (6,)


def test_program_selection():
    """fused=None auto-selects the streaming kernel up to FUSED_K_MAX
    and falls back to the score-matrix path beyond it."""
    fused = topk_program(8, fused=None, interpret=True,
                         use_pallas=True)
    legacy = topk_program(FUSED_K_MAX + 1, fused=None, interpret=True,
                          use_pallas=True)
    rng = np.random.default_rng(0)
    v = rng.normal(size=(FUSED_K_MAX + 50, 16)).astype(np.float32)
    q = rng.normal(size=(1, 16)).astype(np.float32)
    sf, _ = fused(v, q, None, None)
    sl, _ = legacy(v, q, None, None)
    assert np.asarray(sf).shape == (1, 8)
    assert np.asarray(sl).shape == (1, FUSED_K_MAX + 1)


def test_fused_output_is_o_of_kq():
    """Acceptance: the fused program's outputs are O(k*Q) shaped —
    nothing N-sized leaves the kernel."""
    import jax
    fn = topk_program(5, fused=True, interpret=True, use_pallas=True)
    rng = np.random.default_rng(1)
    v = rng.normal(size=(512, 32)).astype(np.float32)
    q = rng.normal(size=(3, 32)).astype(np.float32)
    shapes = [np.asarray(x).shape
              for x in jax.tree_util.tree_leaves(fn(v, q, None, None))]
    assert shapes == [(3, 5), (3, 5)]
    # and the jaxpr-level output of the pallas_call itself is k*Q
    # padded, never (N, Q): the kernel's out_shape is (k_pad, q_pad)
    from libsplinter_tpu.ops.similarity import _fused_topk_fn
    closed = jax.make_jaxpr(_fused_topk_fn(5, 128, False, True))(
        v, q, np.ones(512, np.float32), None)

    def _pallas_eqns(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                yield eqn
            for val in eqn.params.values():
                sub = getattr(val, "jaxpr", None)
                if sub is not None:
                    yield from _pallas_eqns(sub)

    eqns = list(_pallas_eqns(closed.jaxpr))
    assert eqns, "fused path must lower through pallas_call"
    for eqn in eqns:
        for var in eqn.outvars:
            assert var.aval.shape[0] == 8      # k=5 padded to 8, not N
