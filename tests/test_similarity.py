"""Similarity ops: correctness vs numpy reference, masking, zero-vector
exclusion, pallas-interpret parity with the jnp path."""
import numpy as np
import pytest

from libsplinter_tpu.ops import (cosine_scores, cosine_topk,
                                 cosine_topk_batch, euclidean_distances)
from libsplinter_tpu.ops.similarity import NEG_INF


def _np_cosine(vectors, query):
    vn = np.linalg.norm(vectors, axis=-1)
    qn = np.linalg.norm(query)
    with np.errstate(divide="ignore", invalid="ignore"):
        return (vectors @ query) / np.maximum(vn * qn, 1e-12)


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    vectors = rng.normal(size=(200, 64)).astype(np.float32)
    query = rng.normal(size=64).astype(np.float32)
    return vectors, query


def test_scores_match_numpy(data):
    vectors, query = data
    got = np.asarray(cosine_scores(vectors, query))[:, 0]
    np.testing.assert_allclose(got, _np_cosine(vectors, query),
                               rtol=1e-4, atol=1e-5)


def test_topk_order(data):
    vectors, query = data
    scores, idx = cosine_topk(vectors, query, k=10)
    ref = _np_cosine(vectors, query)
    np.testing.assert_array_equal(idx, np.argsort(-ref)[:10])
    assert (np.diff(scores) <= 1e-7).all()


def test_exact_match_wins():
    rng = np.random.default_rng(0)
    vectors = rng.normal(size=(50, 32)).astype(np.float32)
    query = vectors[17] * 3.0  # same direction, different magnitude
    scores, idx = cosine_topk(vectors, query, k=1)
    assert idx[0] == 17
    assert scores[0] == pytest.approx(1.0, abs=1e-5)


def test_mask_excludes(data):
    vectors, query = data
    mask = np.ones(200, np.float32)
    ref = _np_cosine(vectors, query)
    best = int(np.argmax(ref))
    mask[best] = 0.0
    _, idx = cosine_topk(vectors, query, k=1, mask=mask)
    assert idx[0] != best
    assert idx[0] == np.argsort(-np.where(mask > 0, ref, -np.inf))[0]


def test_zero_vectors_excluded(data):
    vectors, query = data
    vectors = vectors.copy()
    vectors[5] = 0.0  # un-embedded slot
    scores = np.asarray(cosine_scores(vectors, query))[:, 0]
    assert scores[5] == NEG_INF


def test_batch_queries(data):
    vectors, _ = data
    rng = np.random.default_rng(7)
    queries = rng.normal(size=(3, 64)).astype(np.float32)
    scores, idx = cosine_topk_batch(vectors, queries, k=5)
    assert scores.shape == (3, 5) and idx.shape == (3, 5)
    for qi in range(3):
        ref = _np_cosine(vectors, queries[qi])
        np.testing.assert_array_equal(idx[qi], np.argsort(-ref)[:5])


def test_euclidean(data):
    vectors, query = data
    got = np.asarray(euclidean_distances(vectors, query))[:, 0]
    ref = np.linalg.norm(vectors - query, axis=-1)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_pallas_interpret_matches_jnp(data):
    """Run the actual pallas kernel in interpreter mode on CPU and compare
    with the jnp path."""
    from libsplinter_tpu.ops.similarity import (_cosine_scores_pallas,
                                                _pad_to)
    import jax.numpy as jnp
    vectors, query = data
    # pad to kernel-friendly shapes
    v = np.zeros((256, 128), np.float32); v[:200, :64] = vectors
    q = np.zeros((8, 128), np.float32); q[0, :64] = query
    mask = np.zeros((256, 1), np.float32); mask[:200] = 1.0
    out = _cosine_scores_pallas(jnp.asarray(v), jnp.asarray(q),
                                jnp.asarray(mask), block_n=128,
                                interpret=True, mxu_bf16=False)
    got = np.asarray(out)[:200, 0]
    np.testing.assert_allclose(got, _np_cosine(vectors, query),
                               rtol=1e-4, atol=1e-5)


def test_k_larger_than_n():
    rng = np.random.default_rng(3)
    vectors = rng.normal(size=(4, 16)).astype(np.float32)
    query = rng.normal(size=16).astype(np.float32)
    scores, idx = cosine_topk(vectors, query, k=50)
    assert len(idx) == 4


def test_bf16_kernel_ranking_matches_f32():
    """bf16 MXU inputs must not change top-k ordering on realistic
    (unit-norm-ish) embedding data; exercised through the pallas kernel
    in interpret mode so the bf16 cast path itself runs on CPU."""
    import jax.numpy as jnp
    from libsplinter_tpu.ops.similarity import _cosine_scores_pallas
    rng = np.random.default_rng(11)
    vecs = rng.standard_normal((256, 128)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    qs = rng.standard_normal((8, 128)).astype(np.float32)
    mask = np.ones((256, 1), np.float32)
    exact = _cosine_scores_pallas(jnp.asarray(vecs), jnp.asarray(qs),
                                  jnp.asarray(mask), block_n=128,
                                  interpret=True, mxu_bf16=False)
    fast = _cosine_scores_pallas(jnp.asarray(vecs), jnp.asarray(qs),
                                 jnp.asarray(mask), block_n=128,
                                 interpret=True, mxu_bf16=True)
    np.testing.assert_allclose(np.asarray(fast), np.asarray(exact),
                               atol=2e-2)
    for col in range(8):
        top_exact = np.argsort(-np.asarray(exact)[:, col])[:10]
        top_fast = np.argsort(-np.asarray(fast)[:, col])[:10]
        # top-10 sets agree (ordering within epsilon ties may differ)
        assert len(set(top_exact) & set(top_fast)) >= 9
