"""The commit pipeline: wake->commit must never park on a device
round-trip it could overlap (BENCH_r05: 62.2 of the 67.2 ms p50
set->vector was a synchronous device wait inside the old fused commit).

Three tiers:
  - CommitPipeline unit tests with hand-rolled futures (completion-order
    resolution, back-pressure, blocking accounting);
  - Embedder integration with the stub encoder (probe lane routing,
    pipeline counters on real drains, heartbeat surface);
  - a slow-marked CPU micro-bench running the event-driven daemon loop
    and asserting the wake handler performed ZERO blocking device
    fetches across a multi-wave load (the regression guard that needs
    no TPU hardware).
"""
from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

import libsplinter_tpu as sp
from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.embedder import (
    CommitPipeline, Embedder, EmbedderStats,
)


def fake_encoder(texts):
    out = np.zeros((len(texts), 32), np.float32)
    for i, t in enumerate(texts):
        out[i, 0] = len(t)
        out[i, 2] = 1.0
    return out


def _request(store, key, text):
    store.set(key, text)
    store.set_type(key, sp.T_VARTEXT)
    store.label_or(key, P.LBL_EMBED_REQ)
    store.bump(key)


class FakePending:
    """A controllable encode future: flips ready on command."""

    def __init__(self, tag, *, ready):
        self.tag = tag
        self.ready = ready
        self.n = 1

    def is_ready(self):
        return self.ready

    def materialize(self):
        return np.full((1, 4), float(self.tag), np.float32)


class TestCommitPipeline:
    def _pipe(self, depth=4):
        committed = []
        stats = EmbedderStats()

        def commit(rows, epochs, vecs):
            committed.append(rows)
            return len(rows)

        return CommitPipeline(commit, stats, depth), committed, stats

    def test_completion_order_beats_dispatch_order(self):
        pipe, committed, stats = self._pipe()
        slow = FakePending(1, ready=False)
        fast = FakePending(2, ready=True)
        pipe.push([1], [2], slow)
        pipe.push([2], [2], fast)     # finished first: commits first
        assert committed == [[2]]
        slow.ready = True
        assert pipe.drain_ready() == 1
        assert committed == [[2], [1]]
        assert stats.ready_commits == 2
        assert stats.blocking_waits == 0
        assert stats.futures_resolved == 2

    def test_backpressure_blocks_only_past_depth(self):
        pipe, committed, stats = self._pipe(depth=1)
        a = FakePending(1, ready=False)
        b = FakePending(2, ready=False)
        c = FakePending(3, ready=False)
        pipe.push([1], [2], a)
        assert committed == []        # within depth: nothing forced
        pipe.push([2], [2], b)        # depth exceeded: oldest forced
        assert committed == [[1]]
        assert stats.blocking_waits == 1
        pipe.push([3], [2], c)
        assert committed == [[1], [2]]
        pipe.flush()
        assert committed == [[1], [2], [3]]
        assert stats.futures_resolved == 3
        assert stats.inflight_peak == 2

    def test_flush_takes_ready_futures_first(self):
        pipe, committed, _ = self._pipe()
        a = FakePending(1, ready=False)
        b = FakePending(2, ready=True)
        pipe._q.append((["a"], [0], a, time.perf_counter(), 0.0))
        pipe._q.append((["b"], [0], b, time.perf_counter(), 0.0))
        pipe.flush()
        assert committed == [["b"], ["a"]]

    def test_overlap_accounting(self):
        pipe, _, stats = self._pipe()
        p = FakePending(1, ready=True)
        pipe.push([1], [2], p)
        pipe.flush()
        # the future dwelled in flight (however briefly) and the host
        # never blocked: all device time was overlapped
        assert stats.overlap_ms > 0
        assert stats.overlap_ratio() > 0.0


class TestEmbedderPipeline:
    def test_multi_batch_drain_counters(self, store):
        emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64,
                       batch_cap=4)
        emb.attach()
        for i in range(32):
            _request(store, f"k{i}", f"text number {i}")
        assert emb.run_once() == 32
        # 32 rows / batch_cap 4 = 8 dispatched futures, all resolved
        assert emb.stats.futures_dispatched == 8
        assert emb.stats.futures_resolved == 8
        # stub futures are host memory: the wake handler must have
        # done ZERO blocking device fetches
        assert emb.stats.blocking_waits == 0
        assert emb.stats.ready_commits == 8
        assert emb.stats.overlap_ratio() > 0.0
        assert emb.stats.device_wait_ms >= 0.0

    def test_probe_lane_routes_small_drains(self, store):
        emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
        emb.attach()
        _request(store, "probe", "one hot key")
        assert emb.run_once() == 1
        assert emb.stats.probe_lane_hits == 1
        for i in range(20):            # > probe_batch_max: windowed lane
            _request(store, f"bulk{i}", f"bulk text {i}")
        assert emb.run_once() == 20
        assert emb.stats.probe_lane_hits == 1

    def test_probe_lane_threshold_configurable(self, store):
        emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64,
                       probe_batch_max=0)
        emb.attach()
        _request(store, "probe", "never short-circuited")
        assert emb.run_once() == 1
        assert emb.stats.probe_lane_hits == 0

    def test_probe_lane_still_guards_context(self, store):
        emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
        emb.attach()
        _request(store, "huge", "word " * 100)
        assert emb.run_once() == 0
        assert emb.stats.ctx_exceeded == 1
        assert store.labels("huge") & P.LBL_CTX_EXCEEDED

    def test_heartbeat_carries_pipeline_stats(self, store):
        emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
        emb.attach()
        for i in range(12):
            _request(store, f"h{i}", f"heartbeat text {i}")
        emb.run_once()
        emb.publish_stats()
        payload = json.loads(store.get(P.KEY_EMBED_STATS))
        for field in ("futures_dispatched", "futures_resolved",
                      "blocking_waits", "inflight_peak",
                      "overlap_ratio", "device_wait_ms", "overlap_ms",
                      "commit_host_ms", "probe_lane_hits"):
            assert field in payload, field
        assert payload["overlap_ratio"] > 0.0
        assert payload["blocking_waits"] == 0


@pytest.mark.slow
def test_pipeline_microbench_no_blocking_fetch_in_wake_handler(store):
    """CPU micro-bench regression guard: the event-driven daemon under
    a multi-wave load (bulk drains + single-key latency probes) must
    resolve every commit without one blocking device fetch inside the
    wake handler, and must report real overlap — catches a reintroduced
    inline device_get without TPU hardware."""
    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64,
                   batch_cap=8)
    emb.attach()
    t = threading.Thread(
        target=emb.run,
        kwargs=dict(idle_timeout_ms=20, stop_after=15.0,
                    sweep_interval_s=3600.0),
        daemon=True)
    t.start()
    try:
        time.sleep(0.05)
        client = Store.open(store.name)
        lat = []
        try:
            # three bulk waves with latency probes in between — the
            # shape of the bench's p50 loop, shrunk for CI
            for wave in range(3):
                for i in range(40):
                    _request(client, f"w{wave}/k{i}",
                             f"wave {wave} text {i}")
                key = f"probe/{wave}"
                t1 = time.perf_counter()
                _request(client, key, "latency probe text")
                idx = client.find_index(key)
                deadline = t1 + 10.0
                while client.labels_at(idx) & P.LBL_EMBED_REQ:
                    assert time.perf_counter() < deadline, \
                        "probe starved: wake path wedged"
                    time.sleep(0.0005)
                lat.append((time.perf_counter() - t1) * 1e3)
        finally:
            client.close()
    finally:
        emb.stop()
        t.join(timeout=5.0)
    assert emb.stats.embedded >= 123          # 3 x (40 + 1)
    assert emb.stats.futures_resolved == emb.stats.futures_dispatched
    # THE invariant: stub futures are always ready, so any blocking
    # wait means someone re-introduced a synchronous device fetch on
    # the wake->commit path
    assert emb.stats.blocking_waits == 0
    assert emb.stats.overlap_ratio() > 0.0
    assert emb.stats.probe_lane_hits >= 1     # probes short-circuited
    assert len(lat) == 3
