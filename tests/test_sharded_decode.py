"""Tensor-parallel completion serving (parallel/serve.py): the decoder
sharded over the virtual 8-device CPU mesh must generate EXACTLY the
same tokens as the single-device model from the same params — the
block psums XLA inserts from the shardings are mathematically the
identity on the unsharded computation."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig
from libsplinter_tpu.parallel import ShardedCompletionModel, make_mesh
from libsplinter_tpu.parallel.serve import decoder_param_pspec

CFG = DecoderConfig.tiny(dtype=jnp.float32)      # heads=4, kv_heads=2


@pytest.fixture(scope="module")
def pair():
    base = CompletionModel(CFG, buckets=(16,), temp=0.0)
    mesh = make_mesh(dp=4, tp=2, sp=1)
    tp = ShardedCompletionModel(CFG, mesh, params=base.params,
                                buckets=(16,), temp=0.0)
    return base, tp


def test_params_actually_sharded(pair):
    _, tp = pair
    qk = tp.params["params"]["layer_0"]["attn"]["q"]["kernel"]
    assert len(qk.sharding.device_set) == 8
    # column-parallel: the output dim is split over tp
    spec = qk.sharding.spec
    assert tuple(spec) == (None, "tp")


def test_prefill_logits_match(pair):
    base, tp = pair
    prompt = np.arange(1, 9, dtype=np.int32)
    la = base.prefill(prompt)
    lb = tp.prefill(prompt)
    base.reset()
    tp.reset()
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-5, atol=1e-5)


def test_greedy_generation_identical(pair):
    base, tp = pair
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    want = list(base.generate_tokens(prompt, 12, chunk=4))
    base.reset()
    got = list(tp.generate_tokens(prompt, 12, chunk=4))
    tp.reset()
    assert got == want


def test_head_divisibility_enforced():
    mesh = make_mesh(dp=1, tp=8, sp=1)           # kv_heads=2 % 8 != 0
    with pytest.raises(ValueError, match="divide"):
        ShardedCompletionModel(CFG, mesh)


def test_pspec_rules():
    class _K:
        def __init__(self, k):
            self.key = k

    import numpy as np
    two_d = np.zeros((4, 4))
    assert decoder_param_pspec(
        (_K("layer_0"), _K("attn"), _K("q"), _K("kernel")), two_d) \
        == jax.sharding.PartitionSpec(None, "tp")
    assert decoder_param_pspec(
        (_K("layer_0"), _K("attn"), _K("out"), _K("kernel")), two_d) \
        == jax.sharding.PartitionSpec("tp", None)
    assert decoder_param_pspec(
        (_K("lm_head"), _K("kernel")), two_d) \
        == jax.sharding.PartitionSpec()
