import jax


def test_backend_is_virtual_cpu_mesh():
    """conftest must pin tests to a virtual 8-device CPU mesh (the real TPU
    is reserved for bench.py; multi-chip sharding is tested virtually)."""
    assert jax.default_backend() == "cpu"
    assert len(jax.devices()) == 8
