"""Test config: force JAX onto a virtual 8-device CPU mesh (multi-chip
sharding is validated without TPU hardware; the driver separately
dry-run-compiles the multichip path) and provide per-test stores."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import uuid

import pytest

from libsplinter_tpu import Store


@pytest.fixture
def store():
    name = f"/spt-test-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=256, max_val=1024, vec_dim=32)
    yield st
    st.close()
    Store.unlink(name)


@pytest.fixture
def store_novec():
    name = f"/spt-test-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=64, max_val=256, vec_dim=0)
    yield st
    st.close()
    Store.unlink(name)
