"""Test config: force JAX onto a virtual 8-device CPU mesh (multi-chip
sharding is validated without TPU hardware; the driver separately
dry-run-compiles the multichip path) and provide per-test stores."""
import os

# The environment pre-sets JAX_PLATFORMS=axon (the tunneled TPU) and pytest
# plugin autoload imports jax before this conftest runs — but the backend
# initializes lazily, so jax.config still wins here.
os.environ["JAX_PLATFORMS"] = "cpu"

# The 8-device request must land before the CPU backend initializes.
# jax >= 0.5 exposes it as a config option; older jax only reads the
# XLA flag, which still works here because the backend is lazy.  Any
# inherited count is REPLACED — the suite's sharding tests assume 8.
import re as _re

os.environ["XLA_FLAGS"] = (_re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", ""))
    + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:      # jax < 0.5: the XLA flag above covers it
    pass

import uuid

import pytest

from libsplinter_tpu import Store


@pytest.fixture
def store():
    name = f"/spt-test-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=256, max_val=1024, vec_dim=32)
    yield st
    st.close()
    Store.unlink(name)


@pytest.fixture
def store_novec():
    name = f"/spt-test-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    st = Store.create(name, nslots=64, max_val=256, vec_dim=0)
    yield st
    st.close()
    Store.unlink(name)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: longer-running stress tiers")
    config.addinivalue_line(
        "markers", "obs: observability tier (histograms, flight "
        "recorder, exposition) — `make obs-check` runs these")
    config.addinivalue_line(
        "markers", "chaos: fault-injection / crash-recovery tier "
        "(SPTPU_FAULT, supervisor) — `make chaos-check` runs these")
