"""Multi-tenant QoS tier: admission policy units, deadline fast-fail
on all three lanes, weighted fairness under 10:1 offered-load skew,
typed shedding (overloaded + retry_after_ms) and shed-then-admit
recovery, the bounded join-backpressure memo, the slow:<ms>:<p> fault
action, the shared client retry wrapper, the open-loop loadgen, and
the chaos-under-load scenario (supervised full stack + SPTPU_FAULT
lane kill mid-run, zero admitted-request loss) — `make qos-check`
runs the fast tier."""
import json
import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.client import (call_with_retries,
                                           submit_completion)
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.engine.qos import (AdmissionController,
                                        TenantLedger, WaitingRow,
                                        parse_tenant_weights)
from libsplinter_tpu.engine.searcher import Searcher, submit_search
from libsplinter_tpu.utils import faults


# ---------------------------------------------------------------- policy

class TestAdmissionController:
    def test_expired_partition(self):
        c = AdmissionController()
        plan = c.plan([WaitingRow("a", 1, deadline=10.0),
                       WaitingRow("b", 1, deadline=2000.0),
                       WaitingRow("c", 1)], 8, now=1000.0)
        assert [r.item for r in plan.expired] == ["a"]
        assert [r.item for r in plan.admit] == ["b", "c"]
        assert not plan.shed and not plan.deferred

    def test_shed_beyond_high_water(self):
        c = AdmissionController(high_water=3)
        rows = [WaitingRow(i, 0) for i in range(10)]
        plan = c.plan(rows, 2)
        assert len(plan.admit) == 2
        assert len(plan.deferred) == 3
        assert len(plan.shed) == 5

    def test_no_high_water_never_sheds(self):
        c = AdmissionController()
        plan = c.plan([WaitingRow(i, 0) for i in range(10)], 2)
        assert len(plan.deferred) == 8 and not plan.shed

    def test_fair_interleave_two_tenants(self):
        c = AdmissionController()
        rows = [WaitingRow(f"a{i}", 1) for i in range(20)] \
            + [WaitingRow(f"b{i}", 2) for i in range(2)]
        plan = c.plan(rows, 6)
        # the minority tenant's two requests both make the admit set
        assert sum(1 for r in plan.admit if r.tenant == 2) == 2

    def test_weighted_share_converges(self):
        # tenant 1 weighted 3x tenant 2; both saturate.  Across many
        # drains the admitted ratio lands within 2x of 3:1.
        c = AdmissionController(weights={1: 3.0, 2: 1.0})
        served = {1: 0, 2: 0}
        for _ in range(40):
            rows = [WaitingRow(("t1", i), 1) for i in range(20)] \
                + [WaitingRow(("t2", i), 2) for i in range(20)]
            plan = c.plan(rows, 8)
            for r in plan.admit:
                served[r.tenant] += 1
        ratio = served[1] / served[2]
        assert 1.5 <= ratio <= 6.0, served

    def test_starved_tenant_leads_next_drain(self):
        # stride state persists: a tenant present-but-denied in one
        # drain keeps its low pass and leads the next one
        c = AdmissionController()
        rows = [WaitingRow(f"a{i}", 1) for i in range(4)] \
            + [WaitingRow("b0", 2)]
        plan = c.plan(rows, 1)
        assert plan.admit[0].tenant == 1      # tie broke to tenant 1
        rows = [WaitingRow(f"a{i}", 1) for i in range(1, 4)] \
            + [WaitingRow("b0", 2)]
        plan = c.plan(rows, 1)
        assert plan.admit[0].item == "b0"     # denied tenant leads

    def test_idle_tenant_banks_no_priority(self):
        c = AdmissionController()
        for _ in range(10):
            c.plan([WaitingRow("a", 1)], 1)
        # tenant 2 was idle throughout; when it arrives it may lead
        # one admission but must not monopolize a saturated drain
        rows = [WaitingRow(f"a{i}", 1) for i in range(10)] \
            + [WaitingRow(f"b{i}", 2) for i in range(10)]
        plan = c.plan(rows, 10)
        t1 = sum(1 for r in plan.admit if r.tenant == 1)
        assert 3 <= t1 <= 7, plan.admit

    def test_idle_after_heavy_service_no_monopoly(self):
        # the review repro: tenant 2 served once, goes idle; tenant 1
        # then serves heavily ALONE.  When tenant 2 returns under
        # saturation it must compete equally — neither monopolizing
        # (banked priority) nor being punished for tenant 1's
        # uncontended service
        c = AdmissionController()
        c.plan([WaitingRow("b0", 2)], 1)      # t2 served, goes idle
        for r in range(100):
            c.plan([WaitingRow(f"a{r}-{i}", 1) for i in range(10)], 4)
        rows = [WaitingRow(f"a{i}", 1) for i in range(20)] \
            + [WaitingRow(f"b{i}", 2) for i in range(20)]
        plan = c.plan(rows, 10)
        t1 = sum(1 for r in plan.admit if r.tenant == 1)
        assert 3 <= t1 <= 7, plan.admit

    def test_zero_capacity_still_expires_and_sheds(self):
        c = AdmissionController(high_water=1)
        plan = c.plan([WaitingRow("a", 1, deadline=1.0),
                       WaitingRow("b", 1), WaitingRow("c", 1)],
                      0, now=5.0)
        assert [r.item for r in plan.expired] == ["a"]
        assert not plan.admit
        assert len(plan.deferred) == 1 and len(plan.shed) == 1

    def test_parse_tenant_weights(self):
        assert parse_tenant_weights("1:3,2:1.5") == {1: 3.0, 2: 1.5}
        assert parse_tenant_weights(None) is None
        assert parse_tenant_weights("") is None
        with pytest.raises(ValueError):
            parse_tenant_weights("1=3")
        with pytest.raises(ValueError):
            parse_tenant_weights("1:0")

    def test_ledger(self):
        led = TenantLedger()
        led.bump(1, "admitted")
        led.bump(1, "served_tokens", 12)
        led.bump(2, "shed")
        snap = led.snapshot()
        assert snap["1"]["admitted"] == 1
        assert snap["1"]["served_tokens"] == 12
        assert snap["2"]["shed"] == 1
        assert snap["2"]["deadline_expired"] == 0


# ---------------------------------------------------------------- wire

class TestProtocolQoS:
    def test_tenant_label_round_trip(self, store):
        store.set("r", "x")
        P.stamp_tenant(store, "r", 7)
        assert P.read_tenant(store.labels("r")) == 7
        P.stamp_tenant(store, "r", 3)        # replaces, not ORs
        assert P.read_tenant(store.labels("r")) == 3
        with pytest.raises(ValueError):
            P.tenant_label(16)

    def test_deadline_stamp_round_trip(self, store):
        store.set("r", "x")
        idx = store.find_index("r")
        assert P.stamp_deadline(store, "r", 123.5)
        assert store.labels("r") & P.LBL_DEADLINE
        assert P.read_deadline(store, idx,
                               epoch=store.epoch_at(idx)) == 123.5
        # a rewrite invalidates the stamp (epoch moved)
        store.set("r", "y")
        assert P.read_deadline(store, idx,
                               epoch=store.epoch_at(idx)) is None
        # the stale stamp was consumed
        assert P.read_deadline(store, idx) is None

    def test_error_payloads(self):
        rec = P.parse_error_payload(P.overloaded_payload(350))
        assert rec == {"err": "overloaded", "retry_after_ms": 350}
        assert P.parse_error_payload(
            P.DEADLINE_EXPIRED_DIAGNOSTIC)["err"] == "deadline_expired"
        assert P.parse_error_payload(b"a normal completion") is None
        assert P.parse_error_payload(b"{not json") is None
        assert P.parse_error_payload(b'{"no_err": 1}') is None


# ---------------------------------------------------------------- faults

class TestSlowFaultAction:
    def test_slow_fires_probabilistically_with_jitter(self, monkeypatch):
        monkeypatch.setenv("SPTPU_FAULT_SEED", "11")
        faults.arm("x.s:slow:30:0.5")
        try:
            t0 = time.perf_counter()
            for _ in range(20):
                faults.fault("x.s")
            wall_ms = (time.perf_counter() - t0) * 1e3
            st = faults.stats()["x.s"]
            assert st["hits"] == 20
            assert 0 < st["fired"] < 20       # p gates inside the hits
            # each firing sleeps 15-30 ms
            assert wall_ms >= st["fired"] * 15 * 0.9
            assert st["spec"] == "x.s:slow:30:0.5"
            faults.arm(st["spec"])            # spec round-trips
        finally:
            faults.disarm()

    def test_slow_composes_with_hit_window(self, monkeypatch):
        monkeypatch.setenv("SPTPU_FAULT_SEED", "3")
        faults.arm("x.s:slow:5:1@2-3")
        try:
            for _ in range(6):
                faults.fault("x.s")
            assert faults.stats()["x.s"]["fired"] == 2
        finally:
            faults.disarm()

    def test_bad_slow_specs_fail_loudly(self):
        for bad in ("x:slow", "x:slow:abc:0.5", "x:slow:10:0",
                    "x:slow:10:2", "x:slow:0:0.5"):
            with pytest.raises(faults.FaultSpecError):
                faults.arm(bad)
        faults.disarm()


# ---------------------------------------------------------------- client

class TestRetryWrapper:
    def test_honors_retry_after_and_succeeds(self):
        calls = []

        def attempt(left_ms):
            calls.append(left_ms)
            if len(calls) < 3:
                return P.overloaded_record(20)
            return {"ok": True}

        t0 = time.monotonic()
        out = call_with_retries(attempt, timeout_ms=5000)
        assert out == {"ok": True} and len(calls) == 3
        # two waits of >= ~10ms (jitter floor 0.5x) happened
        assert (time.monotonic() - t0) >= 0.02

    def test_returns_overloaded_at_deadline(self):
        out = call_with_retries(
            lambda left: P.overloaded_record(10_000),
            timeout_ms=80)
        assert out["err"] == "overloaded"

    def test_terminal_results_not_retried(self):
        calls = []

        def attempt(left_ms):
            calls.append(1)
            return {"err": "deadline_expired"}

        out = call_with_retries(attempt, timeout_ms=500)
        assert out["err"] == "deadline_expired" and len(calls) == 1

    def test_lane_down_fails_fast(self, store):
        # a fresh supervisor heartbeat marking the lane down vetoes
        # the attempt entirely
        P.publish_heartbeat(store, P.KEY_SUPERVISOR_STATS, {
            "lanes": {"searcher": {"state": "down"}}})
        calls = []
        out = call_with_retries(lambda left: calls.append(1),
                                timeout_ms=500, store=store,
                                lane="searcher")
        assert out is None and not calls


# ---------------------------------------------------------------- searcher

def _seed_docs(store, n=8):
    rng = np.random.default_rng(0)
    for i in range(n):
        v = rng.standard_normal(store.vec_dim).astype(np.float32)
        store.set(f"doc{i}", f"doc {i}")
        store.vec_set(f"doc{i}", v / np.linalg.norm(v))


def _search_req(store, key, k=3, tenant=0, deadline=None):
    params = {"k": k}
    if deadline is not None:
        params["deadline"] = deadline
    store.set(key, json.dumps(params))
    qv = np.zeros(store.vec_dim, np.float32)
    qv[0] = 1.0
    store.vec_set(key, qv)
    if tenant:
        P.stamp_tenant(store, key, tenant)
    store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
    store.bump(key)


def _search_result(store, key):
    return json.loads(store.get(
        P.search_result_key(store.find_index(key))).rstrip(b"\0"))


class TestSearcherQoS:
    def test_deadline_expired_fast_fail(self, store):
        _seed_docs(store)
        sr = Searcher(store)
        sr.attach()
        _search_req(store, "q1", deadline=time.time() - 1.0)
        _search_req(store, "q2", deadline=time.time() + 60.0)
        sr.run_once()
        assert _search_result(store, "q1")["err"] == "deadline_expired"
        assert not store.labels("q1") & P.LBL_SEARCH_REQ
        assert "err" not in _search_result(store, "q2")
        assert sr.stats.deadline_expired == 1

    def test_deadline_via_companion_stamp(self, store):
        _seed_docs(store)
        sr = Searcher(store)
        sr.attach()
        _search_req(store, "q1")
        P.stamp_deadline(store, "q1", time.time() - 1.0)
        sr.run_once()
        assert _search_result(store, "q1")["err"] == "deadline_expired"

    def test_shed_then_admit_after_drain(self, store):
        _seed_docs(store)
        sr = Searcher(store, admit_cap=2, queue_high_water=1,
                      retry_after_ms=123)
        sr.attach()
        for i in range(6):
            _search_req(store, f"q{i}", tenant=1)
        served = sr.run_once()
        assert served == 2
        shed = [i for i in range(6)
                if (store.labels(f"q{i}") & P.LBL_SEARCH_REQ) == 0
                and _search_result(store, f"q{i}").get("err")
                == "overloaded"]
        assert len(shed) == 3 and sr.stats.shed == 3
        for i in shed:
            assert _search_result(store,
                                  f"q{i}")["retry_after_ms"] == 123
        # one deferred request still waits; the next drain admits it
        waiting = [i for i in range(6)
                   if store.labels(f"q{i}") & P.LBL_SEARCH_REQ]
        assert len(waiting) == 1 and sr._had_deferred
        assert sr.run_once() == 1
        assert "err" not in _search_result(store, f"q{waiting[0]}")
        # drained: a fresh request admits cleanly (shed-then-admit)
        _search_req(store, "fresh", tenant=2)
        assert sr.run_once() == 1
        assert "err" not in _search_result(store, "fresh")
        assert sr.tenants.get(1, "shed") == 3

    def test_fairness_10_to_1(self, store):
        """The acceptance property: a 10:1 offered-load tenant pair
        under equal weights both make progress, the starved tenant
        within 2x of its fair (half) share."""
        _seed_docs(store)
        sr = Searcher(store, admit_cap=4)
        sr.attach()
        n_heavy, n_light = 0, 0
        for round_ in range(6):
            for j in range(10):
                _search_req(store, f"h{round_}-{j}", tenant=1)
            _search_req(store, f"l{round_}", tenant=2)
            sr.run_once()
        heavy = sr.tenants.get(1, "admitted")
        light = sr.tenants.get(2, "admitted")
        assert light + heavy > 0
        # all 6 light requests served despite 10x heavy pressure;
        # fair share at equal weights is half the admitted capacity,
        # and the light tenant's whole offered load fits under it
        assert light == 6, (heavy, light)
        assert heavy >= light            # unused share flowed onward

    def test_heartbeat_carries_tenants_and_qos(self, store):
        _seed_docs(store)
        sr = Searcher(store, admit_cap=2, queue_high_water=0)
        sr.attach()
        for i in range(4):
            _search_req(store, f"q{i}", tenant=3)
        sr.run_once()
        sr.publish_stats()
        snap = json.loads(store.get(P.KEY_SEARCH_STATS).rstrip(b"\0"))
        assert snap["qos"]["admit_cap"] == 2
        assert snap["qos"]["queue_high_water"] == 0
        assert snap["tenants"]["3"]["admitted"] == 2
        assert snap["tenants"]["3"]["shed"] == 2
        assert snap["shed"] == 2

    def test_submit_search_retries_through_shed(self, store):
        """Client integration: a shed submit retries after the hint
        and lands once the queue drains."""
        _seed_docs(store)
        sr = Searcher(store, admit_cap=1, queue_high_water=0,
                      retry_after_ms=30)
        sr.attach()
        t = threading.Thread(
            target=sr.run,
            kwargs=dict(idle_timeout_ms=10, stop_after=30.0))
        t.start()
        try:
            results = {}
            qv = np.zeros(store.vec_dim, np.float32)
            qv[0] = 1.0
            for i in range(4):
                # submit_search's contract: the key's vector lane
                # already holds the embedded query
                store.set(f"c{i}", "query")
                store.vec_set(f"c{i}", qv)

            def client(name, tenant):
                results[name] = submit_search(
                    store, name, 3, timeout_ms=8000, tenant=tenant)

            ths = [threading.Thread(target=client,
                                    args=(f"c{i}", 1 + i % 2))
                   for i in range(4)]
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=20)
            ok = [r for r in results.values()
                  if r is not None and "err" not in r]
            assert len(ok) == 4, results
        finally:
            sr.stop()
            t.join(timeout=10)


# ---------------------------------------------------------------- embedder

def _embed_req(store, key, text, tenant=0, deadline=None):
    store.set(key, text)
    if tenant:
        P.stamp_tenant(store, key, tenant)
    if deadline is not None:
        P.stamp_deadline(store, key, deadline)
    store.label_or(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
    store.bump(key)


def _fake_encoder(store):
    def enc(texts):
        out = np.zeros((len(texts), store.vec_dim), np.float32)
        for i in range(len(texts)):
            out[i, 0] = 1.0
        return out
    return enc


class TestEmbedderQoS:
    def test_deadline_expired_fast_fail(self, store):
        emb = Embedder(store, encoder_fn=_fake_encoder(store),
                       max_ctx=64)
        emb.attach()
        _embed_req(store, "e1", "expired", tenant=1,
                   deadline=time.time() - 1.0)
        _embed_req(store, "e2", "live", tenant=1,
                   deadline=time.time() + 60.0)
        emb.run_once()
        assert not store.labels("e1") & P.LBL_EMBED_REQ
        assert np.abs(store.vec_get("e1")).max() == 0   # no vector
        assert np.abs(store.vec_get("e2")).max() > 0
        assert emb.stats.deadline_expired == 1
        assert emb.tenants.get(1, "deadline_expired") == 1
        # the deadline stamp was consumed, not leaked
        assert P.deadline_key(store.find_index("e1")) not in store

    def test_shed_then_admit(self, store):
        emb = Embedder(store, encoder_fn=_fake_encoder(store),
                       max_ctx=64, admit_cap=2, queue_high_water=1)
        emb.attach()
        for i in range(6):
            _embed_req(store, f"e{i}", f"text {i}", tenant=1)
        emb.run_once()
        assert emb.stats.shed == 3 and emb.stats.deferred == 1
        done = sum(1 for i in range(6)
                   if np.abs(store.vec_get(f"e{i}")).max() > 0)
        assert done == 2
        # deferred row still pending; the next drain embeds it
        emb.run_once()
        done = sum(1 for i in range(6)
                   if np.abs(store.vec_get(f"e{i}")).max() > 0)
        assert done == 3
        # drained lane admits fresh work (shed-then-admit)
        _embed_req(store, "fresh", "fresh text", tenant=2)
        emb.run_once()
        assert np.abs(store.vec_get("fresh")).max() > 0

    def test_fairness_10_to_1(self, store):
        emb = Embedder(store, encoder_fn=_fake_encoder(store),
                       max_ctx=64, admit_cap=4)
        emb.attach()
        for round_ in range(5):
            for j in range(10):
                _embed_req(store, f"h{round_}-{j}", f"heavy {j}",
                           tenant=1)
            _embed_req(store, f"l{round_}", "light", tenant=2)
            emb.run_once()
        light = sum(1 for r in range(5)
                    if np.abs(store.vec_get(f"l{r}")).max() > 0)
        assert light == 5                # every light round served
        assert emb.tenants.get(1, "admitted") >= 5

    def test_rejected_reembed_zeroes_stale_vector(self, store):
        """The review repro: a RE-embed request shed (or expired)
        must scrub the slot's PREVIOUS vector — otherwise the cleared
        label + surviving stale vector is indistinguishable from a
        successful embed of the new text."""
        emb = Embedder(store, encoder_fn=_fake_encoder(store),
                       max_ctx=64)
        emb.attach()
        _embed_req(store, "doc", "version one")
        emb.run_once()
        assert np.abs(store.vec_get("doc")).max() > 0
        # re-embed with an already-expired deadline: rejected
        _embed_req(store, "doc", "version two", tenant=1,
                   deadline=time.time() - 1.0)
        emb.run_once()
        assert not store.labels("doc") & P.LBL_EMBED_REQ
        assert np.abs(store.vec_get("doc")).max() == 0
        # and the shed path scrubs too
        emb2 = Embedder(store, encoder_fn=_fake_encoder(store),
                        max_ctx=64, admit_cap=1, queue_high_water=0)
        emb2.attach()
        _embed_req(store, "doc", "version three", tenant=1)
        _embed_req(store, "other", "filler a", tenant=1)
        _embed_req(store, "other2", "filler b", tenant=1)
        emb2.run_once()
        shed_keys = [k for k in ("doc", "other", "other2")
                     if not store.labels(k) & P.LBL_EMBED_REQ
                     and np.abs(store.vec_get(k)).max() == 0]
        assert len(shed_keys) == emb2.stats.shed == 2

    def test_deferred_request_keeps_trace_stamp(self, store):
        """A request deferred by admission keeps its trace stamp (and
        LBL_TRACED) for the drain that actually serves it — consuming
        at gather lost the flight record of every waiting request."""
        _seed_docs(store)
        sr = Searcher(store, admit_cap=1)
        sr.attach()
        _search_req(store, "q0", tenant=1)
        _search_req(store, "q1", tenant=1)
        tid = P.stamp_trace(store, "q1")
        assert tid is not None
        sr.run_once()                  # q0 admitted, q1 deferred
        waiting = [k for k in ("q0", "q1")
                   if store.labels(k) & P.LBL_SEARCH_REQ]
        assert len(waiting) == 1
        w = waiting[0]
        assert store.labels(w) & P.LBL_TRACED or w != "q1"
        if w == "q1":
            idx = store.find_index("q1")
            assert P.trace_stamp_key(idx) in store
        sr.run_once()                  # now served: stamp consumed
        idx = store.find_index("q1")
        assert P.trace_stamp_key(idx) not in store
        assert not store.labels("q1") & P.LBL_TRACED

    def test_untagged_traffic_is_pass_through(self, store):
        # no QoS config, no tenant/deadline stamps: the admission hook
        # must not change behavior or touch the planner
        emb = Embedder(store, encoder_fn=_fake_encoder(store),
                       max_ctx=64)
        emb.attach()
        for i in range(5):
            _embed_req(store, f"e{i}", f"text {i}")
        n = emb.run_once()
        assert n == 5
        assert emb.stats.deferred == 0 and emb.stats.shed == 0
        assert not emb.tenants.snapshot()


# ---------------------------------------------------------------- completer

def _infer_req(store, key, prompt, tenant=0, deadline=None):
    store.set(key, prompt)
    if tenant:
        P.stamp_tenant(store, key, tenant)
    if deadline is not None:
        P.stamp_deadline(store, key, deadline)
    store.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
    store.bump(key)


def _gen(prompt):
    yield b"pong"


class TestCompleterQoS:
    def test_deadline_expired_fast_fail(self, store):
        comp = Completer(store, generate_fn=_gen, template="none")
        comp.attach()
        _infer_req(store, "c1", "expired", tenant=2,
                   deadline=time.time() - 1.0)
        _infer_req(store, "c2", "live", tenant=2,
                   deadline=time.time() + 60.0)
        comp.run_once()
        labels = store.labels("c1")
        assert labels & P.LBL_READY
        assert not labels & (P.LBL_INFER_REQ | P.LBL_SERVICING)
        rec = P.parse_error_payload(store.get("c1"))
        assert rec["err"] == "deadline_expired"
        assert store.get_str("c2").endswith("pong")
        assert comp.stats.deadline_expired == 1
        assert comp.tenants.get(2, "deadline_expired") == 1
        assert comp.tenants.get(2, "served_tokens") >= 1

    def test_shed_with_typed_overloaded(self, store):
        comp = Completer(store, generate_fn=_gen, template="none",
                         queue_high_water=2, retry_after_ms=77)
        comp.attach()
        for i in range(6):
            _infer_req(store, f"c{i}", f"prompt {i}", tenant=1)
        comp.run_once()
        shed = []
        for i in range(6):
            rec = P.parse_error_payload(store.get(f"c{i}"))
            if rec and rec["err"] == "overloaded":
                assert rec["retry_after_ms"] == 77
                assert store.labels(f"c{i}") & P.LBL_READY
                shed.append(i)
        assert len(shed) == 2 and comp.stats.shed == 2
        # two admitted now, two deferred for the next drain
        assert comp.stats.deferred == 2
        comp.run_once()
        done = sum(1 for i in range(6)
                   if store.get_str(f"c{i}").endswith("pong"))
        assert done == 4
        # drained: fresh work admits cleanly
        _infer_req(store, "fresh", "hello", tenant=3)
        comp.run_once()
        assert store.get_str("fresh").endswith("pong")

    def test_fair_order_across_tenants(self, store):
        served = []

        def recording_gen(prompt):
            served.append(prompt)
            yield b"."

        comp = Completer(store, generate_fn=recording_gen,
                         template="none")
        comp.attach()
        for i in range(6):
            _infer_req(store, f"h{i}", f"heavy{i}", tenant=1)
        _infer_req(store, "lite", "light0", tenant=2)
        comp.run_once()
        # the single light request is served before the heavy tail
        assert "light0" in served[0] or "light0" in served[1], served

    def test_bp_memo_bounded(self, store):
        """The satellite: memo entries whose slot epoch moved or whose
        request label is gone are evicted by the sweep."""
        comp = Completer(store, generate_fn=_gen, template="none")
        comp.attach()
        for i in range(4):
            _infer_req(store, f"m{i}", f"prompt {i}")
            comp._bp_memo[store.find_index(f"m{i}")] = (
                store.epoch_at(store.find_index(f"m{i}")), 999)
        assert len(comp._bp_memo) == 4
        # m0: rewritten (epoch moves); m1: served (label cleared)
        store.set("m0", "rewritten")
        store.label_clear("m1", P.LBL_INFER_REQ | P.LBL_WAITING)
        dropped = comp._sweep_bp_memo()
        assert dropped == 2 and len(comp._bp_memo) == 2
        # hard cap backstop
        for i in range(5000):
            comp._bp_memo[10_000 + i] = (0, 1)
        comp._sweep_bp_memo()
        assert len(comp._bp_memo) <= 4096

    def test_submit_completion_client(self, store):
        comp = Completer(store, generate_fn=_gen, template="none")
        comp.attach()
        t = threading.Thread(
            target=comp.run,
            kwargs=dict(idle_timeout_ms=10, stop_after=20.0))
        t.start()
        try:
            out = submit_completion(store, "cq", "hello",
                                    timeout_ms=8000, tenant=4)
            assert isinstance(out, bytes) and out.endswith(b"pong")
        finally:
            comp.stop()
            t.join(timeout=10)

    def test_submit_completion_clears_stale_ready(self, store):
        """A recycled key (or a retry after a shed) may still carry
        READY from its previous terminal state — the submit must clear
        it or the wait loop returns the raw prompt instantly."""
        comp = Completer(store, generate_fn=_gen, template="none")
        comp.attach()
        store.set("cq", "old result")
        store.label_or("cq", P.LBL_READY)
        t = threading.Thread(
            target=comp.run,
            kwargs=dict(idle_timeout_ms=10, stop_after=20.0))
        t.start()
        try:
            out = submit_completion(store, "cq", "hello",
                                    timeout_ms=8000)
            assert isinstance(out, bytes) and out.endswith(b"pong")
        finally:
            comp.stop()
            t.join(timeout=10)

    def test_submit_completion_surfaces_typed_errors(self, store):
        comp = Completer(store, generate_fn=_gen, template="none",
                         queue_high_water=0, retry_after_ms=40)
        comp.attach()
        # saturate: high_water=0 sheds everything beyond the drain cap
        for i in range(3):
            _infer_req(store, f"bg{i}", "filler")
        out = submit_completion(store, "cq", "hello",
                                timeout_ms=250, retry=True)
        # nobody drains: timeout (None) — now drain once; the client's
        # record (if shed) is typed
        assert out is None
        comp.run_once()
        rec = P.parse_error_payload(store.get("cq"))
        if rec is not None:
            assert rec["err"] == "overloaded"


# ---------------------------------------------------------------- heartbeat

def test_metrics_renders_tenant_series(store, capsys):
    from libsplinter_tpu.cli.main import Session
    from libsplinter_tpu.cli.metrics import cmd_metrics

    _seed_docs(store)
    sr = Searcher(store, admit_cap=1, queue_high_water=0)
    sr.attach()
    for i in range(3):
        _search_req(store, f"q{i}", tenant=5)
    sr.run_once()
    sr.publish_stats()
    ses = Session(store.name)
    ses._store = store
    cmd_metrics(ses, [])
    out = capsys.readouterr().out
    assert 'sptpu_searcher_tenant_admitted{' in out
    assert 'tenant="5"' in out
    assert "sptpu_searcher_shed" in out
    assert "sptpu_searcher_qos_retry_after_ms" in out
    ses._store = None                 # fixture owns the handle


# ---------------------------------------------------------------- loadgen

def _lane_threads(store, stop_after=60.0, **searcher_kw):
    def enc(texts):
        out = np.zeros((len(texts), store.vec_dim), np.float32)
        for i, t in enumerate(texts):
            out[i, hash(t) % store.vec_dim] = 1.0
        return out

    emb = Embedder(store, encoder_fn=enc, max_ctx=64)
    emb.attach()
    sr = Searcher(store, **searcher_kw)
    sr.attach()
    comp = Completer(store, generate_fn=lambda p: iter([b"answer"]),
                     template="none")
    comp.attach()
    daemons = (emb, sr, comp)
    ths = [threading.Thread(
        target=d.run, kwargs=dict(idle_timeout_ms=10,
                                  stop_after=stop_after), daemon=True)
        for d in daemons]
    for t in ths:
        t.start()
    return daemons, ths


class TestLoadgen:
    def test_open_loop_mixed_run(self, store):
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec,
                                                 evaluate_slo)

        daemons, ths = _lane_threads(store)
        try:
            gen = LoadGenerator(
                store,
                [TenantSpec(1, 12.0, deadline_ms=5000),
                 TenantSpec(2, 4.0, deadline_ms=5000)],
                duration_s=1.5, corpus=8, seed=3)
            rep = gen.run()
            assert rep["issued"] > 5
            assert rep["lost"] == 0
            assert rep["ok"] >= rep["issued"] * 0.8, rep
            # per-tenant per-lane quantiles sourced from the log
            # histograms
            t1 = rep["per_tenant"]["1"]
            assert any("p99_ms" in row for row in t1.values())
            assert evaluate_slo(rep, goodput=0.5) == []
            assert evaluate_slo(rep, p99_ms=0.0001) != []
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)

    def test_rag_churn_scenario(self, store):
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        daemons, ths = _lane_threads(store)
        try:
            gen = LoadGenerator(
                store, [TenantSpec(1, 6.0, deadline_ms=6000)],
                duration_s=1.5, corpus=8, seed=5,
                scenario="rag-churn")
            rep = gen.run()
            assert rep["scenario"] == "rag-churn"
            assert rep["lost"] == 0
            assert rep["ok"] >= max(1, rep["issued"] - 1), rep
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)

    def test_tenants_flag_validated_at_parse(self, store):
        from libsplinter_tpu.cli.loadgen import cmd_loadgen
        from libsplinter_tpu.cli.main import CliError, Session

        ses = Session(store.name)
        ses._store = store
        with pytest.raises(CliError):
            cmd_loadgen(ses, ["--tenants", "16", "--duration", "0.1"])
        ses._store = None             # fixture owns the handle

    def test_fixed_arrivals_deterministic_schedule(self, store):
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        gen = LoadGenerator(store, [TenantSpec(1, 10.0)],
                            duration_s=1.0, arrivals="fixed", seed=1)
        sched = gen._schedule()
        # 0.1s stride inside 1s (float accumulation may land the last
        # arrival a hair under the cutoff)
        assert len(sched) in (9, 10)
        assert all(b[0] > a[0] for a, b in zip(sched, sched[1:]))


# ---------------------------------------------------------------- chaos

@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_under_load_rag_churn(store, monkeypatch):
    """The acceptance scenario: a `spt supervise`d full stack serves
    mixed 3-tenant open-loop rag-churn traffic while SPTPU_FAULT
    kills the searcher lane mid-run; the supervisor restarts it
    (fault stripped from the respawn), no admitted request is lost,
    and the post-restart SLOs hold."""
    from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                             TenantSpec, evaluate_slo)
    from libsplinter_tpu.engine.supervisor import Supervisor

    # the searcher's 3rd drain dies mid-gather — under rag-churn load
    # that is a crash with requests in every lane's queue
    monkeypatch.setenv("SPTPU_FAULT", "searcher.gather:crash@3")
    monkeypatch.setenv("SPTPU_FORCE_CPU", "1")
    sup = Supervisor(store.name,
                     lanes=("embedder", "searcher", "completer"),
                     store=store,
                     lane_args={
                         "completer": ["--max-new-tokens", "4"],
                     },
                     backoff_base_ms=100, backoff_max_ms=1500,
                     breaker_threshold=8, breaker_window_s=120,
                     startup_grace_s=300)
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 600.0})
    t.start()
    try:
        # wait for all three lanes to heartbeat before offering load
        deadline = time.monotonic() + 240
        keys = (P.KEY_EMBED_STATS, P.KEY_SEARCH_STATS,
                P.KEY_COMPLETE_STATS)
        while time.monotonic() < deadline:
            if all(P.heartbeat_live(store, k, max_age_s=30)
                   for k in keys):
                break
            time.sleep(0.5)
        else:
            pytest.fail("lanes never came up under supervision")

        tenants = [TenantSpec(1, 3.0, deadline_ms=60_000),
                   TenantSpec(2, 1.5, deadline_ms=60_000),
                   TenantSpec(3, 0.8, deadline_ms=60_000)]
        gen = LoadGenerator(store, tenants, duration_s=8.0,
                            corpus=8, seed=7, scenario="rag-churn",
                            drain_s=120.0)
        rep = gen.run()
        # the kill actually happened and the lane came back
        assert sup.lanes["searcher"].restarts >= 1, rep
        # zero admitted-request loss through the crash
        assert rep["lost"] == 0, rep
        # post-restart SLO: the run completes with real goodput
        violations = evaluate_slo(rep, goodput=0.9)
        assert not violations, (violations, rep)
        assert rep["ok"] >= 1
    finally:
        sup.stop()
        t.join(timeout=30)
        sup.shutdown()
