"""MRMW writers + live embedding daemon: the BASELINE.md "32-writer
signal-group → batched TPU embed" target, scaled to CI.

The reference's MRMW harness (splinter_chi_sao.c) proves disjoint-lane
writers never corrupt each other; here the additional claim is that a
CONCURRENT embedding daemon — draining via the dirty mask while
writers keep mutating — commits only epoch-consistent vectors: every
committed vector must correspond to a value the key actually held (the
fake encoder embeds a fingerprint of the text, so a torn read would
produce a vector matching NO version).  Threads, not processes: this
sandbox's exec'd siblings lack coherent MAP_SHARED views
(.claude/skills/verify/SKILL.md); same address space is fully coherent
and the seqlock protocol is identical.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store, T_VARTEXT
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.utils.fingerprint import DIM, lane_text
from libsplinter_tpu.utils.fingerprint import fingerprint as _fingerprint

N_WRITERS = 32                 # the reference harness's writer ceiling
KEYS_PER_LANE = 4
VERSIONS = 10


def _encoder(texts):
    return np.stack([_fingerprint(t) for t in texts])


@pytest.mark.slow
def test_mrmw_writers_with_live_embedder(tmp_path):
    name = f"/spt-mrmw-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=512, max_val=256, vec_dim=DIM)
    emb = Embedder(st, encoder_fn=_encoder, max_ctx=64, batch_cap=32)
    emb.attach()

    stop = threading.Event()
    errors: list[str] = []

    def writer(lane: int):
        # disjoint key lanes (the chi-sao construction): write-write
        # contention is zero by design; reader (embedder) races freely
        rng = np.random.default_rng(lane)
        for ver in range(VERSIONS):
            for i in range(KEYS_PER_LANE):
                k = f"lane{lane}/k{i}"
                st.set(k, lane_text(lane, i, ver))
                st.set_type(k, T_VARTEXT)
                st.label_or(k, P.LBL_EMBED_REQ)
                st.bump(k)
            time.sleep(float(rng.uniform(0.001, 0.01)))

    runner = threading.Thread(
        target=emb.run,
        kwargs=dict(idle_timeout_ms=20, stop_after=60.0,
                    sweep_interval_s=0.5),
        daemon=True)
    runner.start()
    threads = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(N_WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "writer wedged"

    # writers done: the daemon must converge every key to its FINAL
    # version's fingerprint (stale-but-consistent intermediates are
    # fine mid-run; the label protocol re-queues every overwrite, and
    # the epoch gate makes a commit for superseded text impossible)
    deadline = time.time() + 45
    remaining = {f"lane{w}/k{i}"
                 for w in range(N_WRITERS) for i in range(KEYS_PER_LANE)}
    while time.time() < deadline and remaining:
        for k in list(remaining):
            if st.labels(k) & P.LBL_EMBED_REQ:
                continue              # not yet serviced / re-queued
            got = st.vec_get(k)
            want = _fingerprint(st.get(k).rstrip(b"\0").decode())
            if np.array_equal(got, want):
                remaining.discard(k)
        if remaining:
            time.sleep(0.1)
    emb.stop()
    runner.join(timeout=5)

    for k in sorted(remaining):       # diagnose: torn vs merely late
        got = st.vec_get(k)
        w = int(k.split("/")[0].removeprefix("lane"))
        i = int(k.split("k")[-1])
        texts = [lane_text(w, i, v) for v in range(VERSIONS)]
        matches = [t for t in texts
                   if np.array_equal(got, _fingerprint(t))]
        errors.append(f"{k}: labels={st.labels(k):#x} "
                      f"vector_matches={matches or 'NO VERSION (torn!)'}")
    assert not remaining, errors[:6]
    assert emb.stats.embedded >= N_WRITERS * KEYS_PER_LANE
    # the race detector must have been exercised OR clean — but never
    # silently wrong: every final vector checked above is exact
    print(f"stats: {emb.stats}")
