"""Disaggregated prefill/decode lanes (ISSUE 18).

The serving contract under test: splitting the continuous completer
into a PrefillLane (dense bucket prefill + page handoff) and a
DecodeLane (adoption + ragged paged decode) must be INVISIBLE to
clients — greedy bytes identical to the unified lane (including a
joiner that lands mid-burst), zero admitted-request loss through a
crash on either side of the handoff, and phase-aware deadlines that
die typed BEFORE paying the phase they cannot finish in.

The crash drills spawn jax-importing children under `spt supervise`
(tests/chaos_child.py prefill_lane / decode_lane) and are marked
slow + chaos; `make disagg-check` runs the fast tier plus the
scripts/disagg_check.py isolation gate.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid

import pytest

jnp = pytest.importorskip("jax.numpy")

from libsplinter_tpu import Store  # noqa: E402
from libsplinter_tpu.engine import protocol as P  # noqa: E402
from libsplinter_tpu.engine.completer import Completer  # noqa: E402
from libsplinter_tpu.engine.disagg import (DecodeLane,  # noqa: E402
                                           PrefillLane)
from libsplinter_tpu.models.decoder import (CompletionModel,  # noqa: E402
                                            DecoderConfig)

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "chaos_child.py")

KW = dict(max_new_tokens=8, flush_tokens=4, template="none",
          batch_cap=4, page_size=8)


@pytest.fixture(scope="module")
def model():
    """One tiny model for the whole module: the jit caches live on
    the model object, so every lane after the first test runs warm."""
    return CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(32,), temp=0.0, seed=1,
                           suffix_buckets=(8,))


def _mkstore(tag: str, max_val: int = 16384):
    # max_val 16384 > page_wire_bytes(tiny f32, page=8) = 4096: wire
    # export/import is the default path; 4096 forces the re-prefill
    # fallback (the record's token ids) instead
    name = f"/spt-disagg-{tag}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    Store.unlink(name)
    return name, Store.create(name, nslots=128, max_val=max_val,
                              vec_dim=8)


def _submit(st, key, prompt, deadline=None):
    st.set(key, prompt)
    if deadline is not None:
        P.stamp_deadline(st, key, deadline)
    st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
    st.bump(key)


def _await(st, keys, bit=P.LBL_READY, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(st.labels(k) & bit for k in keys):
            return True
        time.sleep(0.02)
    return False


def _run_bg(daemon, stop_after=180.0):
    th = threading.Thread(
        target=daemon.run_continuous,
        kwargs=dict(idle_timeout_ms=20, stop_after=stop_after),
        daemon=True)
    th.start()
    return th


def _no_handoff_keys(st):
    """No `__ho_` record/page/scale key survives a finished request —
    the wire keys ride LBL_DEBUG, so enumerate that label."""
    for idx in st.enumerate_indices(P.LBL_DEBUG):
        key = st.key_at(idx)
        if key is not None and key.startswith(P.HANDOFF_PREFIX):
            return False
    return True


def _serve(tag, daemons_fn, model, prompts, joiner=None,
           max_val=16384):
    """Run `prompts` (plus an optional mid-burst `joiner` submitted
    after the first completion) to READY and return {key: bytes}."""
    name, st = _mkstore(tag, max_val=max_val)
    daemons = daemons_fn(st, model)
    ths = []
    try:
        for d in daemons:
            d.attach()
        ths = [_run_bg(d) for d in daemons]
        keys = []
        for i, prompt in enumerate(prompts):
            keys.append(f"q/{i}")
            _submit(st, keys[-1], prompt)
        if joiner is not None:
            # mid-burst joiner: lands after the first completion while
            # the rest of the burst is still in flight
            assert _await(st, keys[:1]), "first completion never READY"
            keys.append("q/join")
            _submit(st, "q/join", joiner)
        assert _await(st, keys), [
            (k, hex(st.labels(k))) for k in keys]
        out = {k: st.get(k).rstrip(b"\0") for k in keys}
        for d in daemons:
            d.stop()
        for th in ths:
            th.join(timeout=30)
        assert _no_handoff_keys(st)
        return out, [dict(getattr(d, "_lane_stats", {}))
                     for d in daemons]
    finally:
        for d in daemons:
            d.stop()
        for th in ths:
            th.join(timeout=30)
        st.close()
        Store.unlink(name)


def _unified(st, model):
    return [Completer(st, model=model, **KW)]


def _split(st, model):
    return [PrefillLane(st, model=model, **KW),
            DecodeLane(st, model=model, **KW)]


PROMPTS = ["say one thing", "list two colors ok", "count to three"]
JOINER = "and a late joiner arrives"


@pytest.fixture(scope="module")
def sharded_model():
    """tp=2 over the conftest's virtual 8-device CPU mesh: the wire
    handoff must round-trip kv-head-SHARDED pools byte-exactly."""
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)
    return ShardedCompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), make_mesh(dp=4, tp=2),
        buckets=(32,), temp=0.0, seed=1, suffix_buckets=(8,))


class TestByteExactness:
    def test_split_matches_unified_with_midburst_joiner(self, model):
        """Greedy bytes through the handoff — wire-page export/import
        path — are identical to the unified lane's, including a
        joiner admitted while the burst is mid-flight."""
        uni, _ = _serve("uni", _unified, model, PROMPTS, joiner=JOINER)
        spl, stats = _serve("spl", _split, model, PROMPTS,
                            joiner=JOINER)
        assert spl == uni
        pf, dl = stats
        assert pf["handoffs"] >= 4 and pf["handoff_failed"] == 0
        assert dl["adopted"] == pf["handoffs"]
        # the real wire path, not the fallback
        assert dl["handoff_refill"] == 0
        assert pf["handoff_wire_mb"] > 0

    def test_split_matches_unified_tp2_cpu_mesh(self, sharded_model):
        """The page handoff across a tp=2 mesh: exported wire pages
        gather the kv-head-sharded pool, adoption scatters it back
        under the same sharding, and greedy bytes through the split
        match the unified sharded lane (`make disagg-check` runs
        this — the multichip dry-run contract from conftest)."""
        uni, _ = _serve("uni-tp2", _unified, sharded_model, PROMPTS)
        spl, stats = _serve("spl-tp2", _split, sharded_model, PROMPTS)
        assert spl == uni
        pf, dl = stats
        assert pf["handoffs"] >= 3 and pf["handoff_failed"] == 0
        assert dl["adopted"] == pf["handoffs"]
        # the real wire path on the mesh, not the refill fallback
        assert dl["handoff_refill"] == 0
        assert pf["handoff_wire_mb"] > 0

    def test_split_matches_unified_int4_packed_wire(self, model):
        """PR 20: the handoff wire carries int4 pools in their NATIVE
        packed dtype — uint8 nibble pages plus f32 scale rows, half
        the int8 wire and an eighth of f32 — and split greedy bytes
        still match the unified int4 lane exactly (the wire is the
        pool's own bytes, so packed handoff is structurally exact,
        not tolerance-bounded)."""
        kw4 = dict(KW, kv_dtype="int4")

        def uni4(st, m):
            return [Completer(st, model=m, **kw4)]

        def spl4(st, m):
            return [PrefillLane(st, model=m, **kw4),
                    DecodeLane(st, model=m, **kw4)]

        uni, _ = _serve("uni-i4", uni4, model, PROMPTS, joiner=JOINER)
        spl, stats = _serve("spl-i4", spl4, model, PROMPTS,
                            joiner=JOINER)
        assert spl == uni
        pf, dl = stats
        assert pf["handoffs"] >= 4 and pf["handoff_failed"] == 0
        assert dl["adopted"] == pf["handoffs"]
        assert dl["handoff_refill"] == 0      # real wire, no fallback
        # the wire itself halves vs int8 at the same page count
        c4 = model.init_paged(2, page=8, kv_dtype="int4")
        c8 = model.init_paged(2, page=8, kv_dtype="int8")
        assert str(model._page_wire_dtype(c4)) == "uint8"
        assert model.page_wire_bytes(c4) * 2 == model.page_wire_bytes(c8)

    @pytest.mark.slow
    def test_refill_fallback_matches_unified_int4(self, model):
        """A store too small for even the PACKED wire page degrades
        the int4 handoff to re-prefill-from-record, byte-identically
        to the unified int4 lane — the fallback replays tokens, so it
        is layout-blind and must survive the packed geometry."""
        kw4 = dict(KW, kv_dtype="int4")
        wire = model.page_wire_bytes(
            model.init_paged(2, page=8, kv_dtype="int4"))

        def uni4(st, m):
            return [Completer(st, model=m, **kw4)]

        def spl4(st, m):
            return [PrefillLane(st, model=m, **kw4),
                    DecodeLane(st, model=m, **kw4)]

        uni, _ = _serve("uni-i4s", uni4, model, PROMPTS, max_val=wire)
        spl, stats = _serve("spl-i4s", spl4, model, PROMPTS,
                            max_val=wire)
        assert spl == uni
        pf, dl = stats
        assert pf["handoffs"] >= 3
        assert dl["handoff_refill"] == pf["handoffs"]
        assert pf["handoff_wire_mb"] == 0

    @pytest.mark.slow
    def test_refill_fallback_matches_unified(self, model):
        """A store too small for wire pages (max_val 4096 ==
        page_wire_bytes) degrades to re-prefill-from-record — and the
        bytes still match the unified lane exactly."""
        uni, _ = _serve("uni4k", _unified, model, PROMPTS,
                        max_val=4096)
        spl, stats = _serve("spl4k", _split, model, PROMPTS,
                            max_val=4096)
        assert spl == uni
        pf, dl = stats
        assert pf["handoffs"] >= 3
        assert dl["handoff_refill"] == pf["handoffs"]
        assert pf["handoff_wire_mb"] == 0


class TestPhaseAwareQoS:
    def test_prefill_fast_fails_deadline_inside_prefill_wall(
            self, model):
        """A deadline that lands inside the rolling prefill-wall EMA
        dies typed at admission — BEFORE paying prefill.  The
        no-deadline sibling sails through to DECODE_READY."""
        name, st = _mkstore("ff")
        pf = PrefillLane(st, model=model, **KW)
        th = None
        try:
            pf.attach()
            # a lane that has learned prefill costs ~10 s must reject
            # a deadline 2 s out without serving it
            pf.qos_slack_s = 10.0
            _submit(st, "doomed", "expires in prefill",
                    deadline=time.time() + 2.0)
            _submit(st, "live", "no deadline here")
            th = _run_bg(pf)
            assert _await(st, ["doomed"], timeout=60)
            rec = P.parse_error_payload(st.get("doomed"))
            assert rec["err"] == "deadline_expired"
            assert pf.stats.deadline_expired == 1
            # the live request got the full prefill + handoff
            assert _await(st, ["live"], bit=P.LBL_DECODE_READY,
                          timeout=60)
            assert pf._lane_stats["handoffs"] == 1
        finally:
            pf.stop()
            if th:
                th.join(timeout=30)
            st.close()
            Store.unlink(name)

    def test_decode_rejects_expired_handoff_before_adoption(
            self, model):
        """An expired DECODE_READY handoff dies typed at the adopt
        edge — before consuming pool pages or a batch slot — and its
        wire keys leave the store with it."""
        name, st = _mkstore("exp")
        pf = PrefillLane(st, model=model, **KW)
        dl = DecodeLane(st, model=model, **KW)
        tp = td = None
        try:
            pf.attach()
            dl.attach()
            _submit(st, "q", "soon to expire",
                    deadline=time.time() + 1.5)
            tp = _run_bg(pf)
            assert _await(st, ["q"], bit=P.LBL_DECODE_READY,
                          timeout=60)
            pf.stop()
            tp.join(timeout=30)
            time.sleep(1.6)           # let the deadline lapse
            td = _run_bg(dl)
            assert _await(st, ["q"], timeout=60)
            rec = P.parse_error_payload(st.get("q"))
            assert rec["err"] == "deadline_expired"
            assert dl.stats.deadline_expired == 1
            assert dl._lane_stats["adopted"] == 0
            assert _no_handoff_keys(st)
        finally:
            pf.stop()
            dl.stop()
            for th in (tp, td):
                if th:
                    th.join(timeout=30)
            st.close()
            Store.unlink(name)

    def test_adopt_backpressure_keeps_row_decode_ready(self, model):
        """A decode pool that cannot cover the worst-case reservation
        leaves the handoff DECODE_READY (counted, never stranded
        mid-decode) — the autoscaler's pool_occ signal is what turns
        this into capacity."""
        name, st = _mkstore("bp")
        pf = PrefillLane(st, model=model, **KW)
        kw = dict(KW)
        kw["pool_pages"] = 16         # the one-window floor
        dl = DecodeLane(st, model=model, **kw)
        tp = td = None
        try:
            pf.attach()
            dl.attach()
            # squat 15 of the 16 pool pages on a row the lane thinks
            # is free: the worst-case reservation (>= 2 pages) cannot
            # fit in the 1 remaining
            cache = dl._ensure_paged_cache()
            assert cache.ensure(KW["batch_cap"] - 1, 15 * KW["page_size"])
            _submit(st, "q", "too big for that pool")
            tp = _run_bg(pf)
            td = _run_bg(dl)
            assert _await(st, ["q"], bit=P.LBL_DECODE_READY,
                          timeout=60)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if dl._lane_stats["adopt_backpressure"] >= 2:
                    break
                time.sleep(0.05)
            assert dl._lane_stats["adopt_backpressure"] >= 2
            labels = st.labels("q")
            assert labels & P.LBL_DECODE_READY
            assert not labels & (P.LBL_SERVICING | P.LBL_READY)
            assert dl._lane_stats["adopted"] == 0
            # capacity returns -> the parked handoff is adopted and
            # finishes; nothing was stranded by the wait
            cache.free_row(KW["batch_cap"] - 1)
            assert _await(st, ["q"], timeout=60)
            assert dl._lane_stats["adopted"] == 1
        finally:
            pf.stop()
            dl.stop()
            for th in (tp, td):
                if th:
                    th.join(timeout=30)
            st.close()
            Store.unlink(name)


def _seed_handoff(st, key, *, servicing):
    """A handed-off row as the prefill lane leaves it: value bytes,
    DECODE_READY (plus SERVICING when a decode replica has adopted
    it), a v1 record, and one wire page."""
    st.set(key, "prompt bytes")
    st.label_or(key, P.LBL_DECODE_READY
                | (P.LBL_SERVICING if servicing else 0))
    idx = st.find_index(key)
    assert P.write_handoff_record(st, idx, {
        "len": 3, "ids": [1, 2, 3], "carry": 5, "n_tok": 1,
        "remaining": 7, "disp_left": 7, "plen": st.value_len(key),
        "t0": 0, "tenant": 0, "deadline": None, "wire_pages": 1,
        "quant": False})
    st.set(P.handoff_page_key(idx, 0), b"\x01" * 64)
    st.label_or(P.handoff_page_key(idx, 0), P.LBL_DEBUG)
    return idx


class TestCrossLaneReclaim:
    """The two lanes' stripe maps are independent over the SAME slot
    space, so each lane's restart-time reclaim must only touch rows
    on ITS side of the handoff flip: SERVICING-only rows belong to
    prefill, anything carrying DECODE_READY belongs to decode.  A
    sweep that crosses the line deletes a live replica's in-flight
    state and double-services the request."""

    def test_prefill_reclaim_skips_decode_owned_rows(self, model):
        """A restarted prefill replica must not clobber a row a live
        decode replica is mid-decode on (SERVICING|DECODE_READY):
        record and wire pages survive, labels untouched.  Its own
        died-mid-prefill SERVICING-only row is still re-queued."""
        name, st = _mkstore("pfskip")
        pf = PrefillLane(st, model=model, **KW)
        try:
            pf.attach()
            adopted = _seed_handoff(st, "adopted", servicing=True)
            st.set("mine", "died mid prefill")
            st.label_or("mine", P.LBL_SERVICING)
            assert pf._reclaim_stranded() == 1
            labels = st.labels("adopted")
            assert labels & P.LBL_DECODE_READY
            assert labels & P.LBL_SERVICING
            assert P.read_handoff_record(st, adopted) is not None
            assert P.handoff_page_key(adopted, 0) in st
            labels = st.labels("mine")
            assert labels & P.LBL_WAITING and labels & P.LBL_INFER_REQ
            assert not labels & P.LBL_SERVICING
        finally:
            st.close()
            Store.unlink(name)

    def test_decode_reclaim_skips_prefill_claims(self, model):
        """A decode replica attach/restart while prefill work is in
        flight must not touch SERVICING-only rows (a live prefill
        replica's claims).  Its own dead adopter's row rolls back to
        bare DECODE_READY with the slot truncated to plen."""
        name, st = _mkstore("dlskip")
        dl = DecodeLane(st, model=model, **KW)
        try:
            dl.attach()
            st.set("claim", "being prefilled right now")
            st.label_or("claim", P.LBL_SERVICING)
            mine = _seed_handoff(st, "mine", servicing=True)
            plen = P.read_handoff_record(st, mine)["plen"]
            st.set("mine", "prompt bytes plus a dead adopter tail")
            st.label_or("mine", P.LBL_SERVICING | P.LBL_DECODE_READY)
            assert dl._reclaim_stranded() == 1
            labels = st.labels("claim")
            assert labels & P.LBL_SERVICING
            assert not labels & P.LBL_WAITING
            labels = st.labels("mine")
            assert labels & P.LBL_DECODE_READY
            assert not labels & P.LBL_SERVICING
            assert st.value_len("mine") == plen
        finally:
            st.close()
            Store.unlink(name)

    def test_decode_reclaim_record_vanished_requeues(self, model):
        """The WAITING fallback applies ONLY to rows still carrying
        DECODE_READY whose record is gone — nothing to resume from,
        full re-prefill."""
        name, st = _mkstore("dlvan")
        dl = DecodeLane(st, model=model, **KW)
        try:
            dl.attach()
            idx = _seed_handoff(st, "mine", servicing=True)
            P.clear_handoff(st, idx, pages=1)
            assert dl._reclaim_stranded() == 1
            labels = st.labels("mine")
            assert labels & P.LBL_WAITING and labels & P.LBL_INFER_REQ
            assert not labels & (P.LBL_SERVICING | P.LBL_DECODE_READY)
        finally:
            st.close()
            Store.unlink(name)

    def test_handoff_survives_post_flip_bookkeeping_failure(
            self, model, monkeypatch):
        """An error AFTER the DECODE_READY flip (spans.commit here)
        must not reach run_continuous's failure handler — that would
        re-queue a row the decode lane already owns, leaving
        WAITING|DECODE_READY with no record and streaming the first
        token twice."""
        name, st = _mkstore("postflip")
        pf = PrefillLane(st, model=model, **KW)
        th = None

        def boom(*a, **k):
            raise OSError("spans ring full")

        try:
            pf.attach()
            monkeypatch.setattr(pf.spans, "commit", boom)
            _submit(st, "q", "post flip failure")
            th = _run_bg(pf)
            assert _await(st, ["q"], bit=P.LBL_DECODE_READY,
                          timeout=60)
            idx = st.find_index("q")
            assert P.read_handoff_record(st, idx) is not None
            labels = st.labels("q")
            assert not labels & (P.LBL_WAITING | P.LBL_SERVICING)
            assert pf._lane_stats["handoffs"] == 1
            assert pf._lane_stats["handoff_failed"] == 0
        finally:
            pf.stop()
            if th:
                th.join(timeout=30)
            st.close()
            Store.unlink(name)


# ------------------------------------------------------- crash drills

@pytest.fixture
def cstore():
    name = f"/spt-disagg-chaos-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    st = Store.create(name, nslots=128, max_val=16384, vec_dim=8)
    yield st
    st.close()
    Store.unlink(name)


def _supervised_pair_recovers(cstore, fault_spec, crashed_lane,
                              monkeypatch):
    """Both disaggregated lanes as restartable children under `spt
    supervise`, one of them armed to crash mid-handoff: every
    admitted request must still converge to READY with the prompt
    intact, the crashed lane must have been restarted, and no wire
    key may outlive its request (zero admitted loss, nothing
    stranded)."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    monkeypatch.setenv("SPTPU_FAULT", fault_spec)
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
    cstore.set("q", "hello disaggregated")
    cstore.label_or("q", P.LBL_INFER_REQ | P.LBL_WAITING)
    cstore.bump("q")

    holder: dict = {}

    def spawn(lane):
        role = ("prefill_lane" if lane.name == "prefill"
                else "decode_lane")
        return subprocess.Popen(
            [sys.executable, CHILD, role, cstore.name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(cstore.name, lanes=("prefill", "decode"),
                     spawn_fn=spawn, store=cstore,
                     backoff_base_ms=100, backoff_max_ms=2000,
                     breaker_threshold=8, breaker_window_s=240,
                     startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 420.0})
    t.start()
    try:
        deadline = time.monotonic() + 360
        while time.monotonic() < deadline:
            if cstore.labels("q") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q") & P.LBL_READY, sup.lanes
        assert sup.lanes[crashed_lane].restarts >= 1
        assert sup.lanes[crashed_lane].state != "down"
        assert cstore.get("q").rstrip(b"\0").startswith(
            b"hello disaggregated")
        # a request submitted AFTER the crash round-trips too (the
        # generation-2 child serves with the fault stripped)
        cstore.set("q2", "again, disaggregated")
        cstore.label_or("q2", P.LBL_INFER_REQ | P.LBL_WAITING)
        cstore.bump("q2")
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if cstore.labels("q2") & P.LBL_READY:
                break
            time.sleep(0.25)
        assert cstore.labels("q2") & P.LBL_READY
        assert cstore.get("q2").rstrip(b"\0").startswith(
            b"again, disaggregated")
        for k in ("q", "q2"):
            assert not cstore.labels(k) & (
                P.LBL_INFER_REQ | P.LBL_SERVICING
                | P.LBL_DECODE_READY)
        assert _no_handoff_keys(cstore)
    finally:
        sup.stop()
        t.join()
        sup.shutdown()


@pytest.mark.chaos
@pytest.mark.slow
def test_supervise_recovers_prefill_handoff_crash(cstore, monkeypatch):
    """The prefill lane crashes at prefill.handoff — wire pages
    written, NO record, row still SERVICING.  The restarted lane's
    stripe-scoped reclaim sweeps the orphan wire keys, re-queues the
    row WAITING, and the second pass hands it off cleanly."""
    _supervised_pair_recovers(cstore, "prefill.handoff:crash@1",
                              "prefill", monkeypatch)


@pytest.mark.chaos
@pytest.mark.slow
def test_supervise_recovers_decode_adopt_crash(cstore, monkeypatch):
    """The decode lane crashes at decode.adopt — the handoff claimed
    (SERVICING|DECODE_READY), nothing imported.  Recovery re-opens
    the row to bare DECODE_READY (slot truncated to the record's
    plen) and the restarted lane re-adopts from the surviving wire
    pages."""
    _supervised_pair_recovers(cstore, "decode.adopt:crash@1",
                              "decode", monkeypatch)
