"""Native (C) tokenizer fast path vs the pure-Python reference.

native/src/wptok.c must reproduce models/tokenizer.py bit for bit on
ASCII input — same split rules (str.isspace / punctuation ranges), same
greedy WordPiece, same FNV word hashing — and must cleanly hand
anything non-ASCII back to the Python path.  Every test here encodes
through BOTH paths and compares.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

from libsplinter_tpu.models.tokenizer import (HashTokenizer,
                                              WordPieceTokenizer)

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures")

EDGE_CASES = [
    "",
    " ",
    "hello world",
    "Hello, World!",
    "a  b\tc\nd\x1ce",                      # python isspace extras
    "a\x01b",                               # control chars join words
    "punct,,,runs!!!===",
    "x" * 100,                              # exactly the word bound
    "y" * 101,                              # beyond: UNK
    "mixed " + "z" * 150 + " tail",
    "trailing space ",
    " leading",
    "the seqlock store commits vectors epoch gated",
    "UPPER lower MiXeD",
    "[CLS] literal specials [SEP]",
    "1234 5678 90",
    "a-b_c.d/e\\f",
]

UNICODE_CASES = ["café au lait", "naïve", "日本語テスト", "emoji 🚀 path",
                 "Ωmega über"]


def _rand_texts(n=500, seed=0):
    rng = np.random.default_rng(seed)
    words = ["tpu", "vector", "store", "seqlock", "arena", "label,",
             "epoch!", "shard", "bloom.", "kernel", "mesh", "a", "I",
             "un", "##aff", "x" * 40, "12.5", "don't"]
    return [" ".join(rng.choice(words, size=int(rng.integers(0, 30))))
            for _ in range(n)]


@pytest.fixture(scope="module")
def wp():
    """Native-enabled tokenizer over the committed trained vocab, plus
    a forced-Python twin."""
    with open(os.path.join(FIXDIR, "golden_vocab.txt"),
              encoding="utf-8") as f:
        vocab = [ln.rstrip("\n") for ln in f]
    fast = WordPieceTokenizer.from_vocab_list(vocab)
    slow = WordPieceTokenizer.from_vocab_list(vocab)
    slow._native = None
    assert fast._native is not None, \
        "native tokenizer failed to initialize (build native/ first)"
    return fast, slow


@pytest.fixture(scope="module")
def ht():
    fast = HashTokenizer(4096)
    slow = HashTokenizer(4096)
    slow._native = None
    assert fast._native is not None
    return fast, slow


class TestWordPieceParity:
    def test_edge_cases(self, wp):
        fast, slow = wp
        for text in EDGE_CASES:
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_unicode_falls_back_identically(self, wp):
        fast, slow = wp
        for text in UNICODE_CASES:
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_random_corpus(self, wp):
        fast, slow = wp
        for text in _rand_texts():
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_max_len_truncation(self, wp):
        fast, slow = wp
        long = "word " * 200
        for m in (2, 5, 16, 64):
            a = fast.encode(long, max_len=m)
            assert a == slow.encode(long, max_len=m)
            assert len(a) == m and a[-1] == fast.sep_id


class TestHashParity:
    def test_edge_cases(self, ht):
        fast, slow = ht
        for text in EDGE_CASES:
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_unicode_falls_back_identically(self, ht):
        fast, slow = ht
        for text in UNICODE_CASES:
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_random_corpus(self, ht):
        fast, slow = ht
        for text in _rand_texts(seed=7):
            assert fast.encode(text) == slow.encode(text), repr(text)

    def test_id_range(self, ht):
        fast, _ = ht
        ids = fast.encode("some ordinary words")
        assert ids[0] == fast.cls_id and ids[-1] == fast.sep_id
        assert all(4 <= i < 4096 for i in ids[1:-1])


class TestBatch:
    def test_batch_matches_per_text(self, wp):
        fast, slow = wp
        texts = EDGE_CASES + UNICODE_CASES + _rand_texts(50)
        ids, lens = fast.encode_batch(texts, max_len=32)
        assert ids.shape == (len(texts), 32)
        for i, t in enumerate(texts):
            want = slow.encode(t, max_len=32)
            assert lens[i] == len(want), repr(t)
            assert list(ids[i, : lens[i]]) == want, repr(t)
            assert (ids[i, lens[i]:] == fast.pad_id).all()

    def test_batch_hash(self, ht):
        fast, slow = ht
        texts = ["alpha beta", "café", "gamma delta epsilon"]
        ids, lens = fast.encode_batch(texts, max_len=8)
        for i, t in enumerate(texts):
            want = slow.encode(t, max_len=8)
            assert list(ids[i, : lens[i]]) == want

    def test_pure_python_batch_when_no_native(self, wp):
        _, slow = wp
        texts = ["one two", "three"]
        ids, lens = slow.encode_batch(texts, max_len=16)
        for i, t in enumerate(texts):
            want = slow.encode(t, max_len=16)
            assert list(ids[i, : lens[i]]) == want
