"""Trainer checkpoint/resume (parallel/checkpoint.py, orbax-backed):
save -> restore must resume training bit-identically, including onto a
mesh-sharded trainer."""
from __future__ import annotations

import jax
import numpy as np
import pytest

from libsplinter_tpu.models import EncoderConfig
from libsplinter_tpu.parallel import (make_mesh, make_sharded_train_step,
                                      make_train_step)
from libsplinter_tpu.parallel import checkpoint as ckpt

CFG = EncoderConfig.tiny(out_dim=16)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, CFG.vocab_size, size=(8, 16)).astype(np.int32)
    mask = np.ones((8, 16), bool)
    return {"ids_a": ids, "mask_a": mask,
            "ids_b": ((ids + 1) % CFG.vocab_size).astype(np.int32),
            "mask_b": mask}


def test_save_restore_resumes_identically(tmp_path):
    init_fn, step_fn = make_train_step(CFG)
    b = _batch()
    state = init_fn(jax.random.PRNGKey(0), b["ids_a"], b["mask_a"])
    step_fn = jax.jit(step_fn)
    state, _ = step_fn(state, b)
    state, _ = step_fn(state, _batch(1))

    path = str(tmp_path / "ck")
    saved_step = ckpt.save(state, path)
    assert saved_step == 2
    assert ckpt.latest_step(path) == 2

    got = ckpt.restore(path, like=state)
    flat_a = jax.tree_util.tree_leaves_with_path(state._asdict())
    flat_b = jax.tree_util.tree_leaves_with_path(got._asdict())
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                      err_msg=str(pa))

    # resumed training == uninterrupted training
    cont_a, loss_a = step_fn(state, _batch(2))
    cont_b, loss_b = step_fn(got, _batch(2))
    assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-6)
    assert int(cont_b.step) == 3


def test_restore_onto_sharded_trainer(tmp_path):
    """Save from a single-device trainer, resume onto the (dp, tp)
    mesh-sharded trainer: the restored arrays take the sharded
    trainer's placements and the next step runs."""
    init_fn, step_fn = make_train_step(CFG)
    b = _batch()
    state = init_fn(jax.random.PRNGKey(0), b["ids_a"], b["mask_a"])
    state, _ = jax.jit(step_fn)(state, b)
    path = str(tmp_path / "ck")
    ckpt.save(state, path)

    mesh = make_mesh(dp=4, tp=2)
    sharded_init = make_sharded_train_step(CFG, mesh)
    like, sharded_step = sharded_init(jax.random.PRNGKey(0),
                                      b["ids_a"][:1], b["mask_a"][:1])
    got = ckpt.restore(path, like=like)
    assert int(got.step) == 1
    # params resumed with the sharded trainer's placement
    leaf = got.params["params"]["layer_0"]["mlp"]["up"]["kernel"]
    assert len(leaf.sharding.device_set) == 8
    state2, loss = sharded_step(got, _batch(3))
    assert np.isfinite(float(loss))
    assert int(state2.step) == 2


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        init_fn, _ = make_train_step(CFG)
        b = _batch()
        st = init_fn(jax.random.PRNGKey(0), b["ids_a"], b["mask_a"])
        ckpt.restore(str(tmp_path / "nope"), like=st)
    assert ckpt.latest_step(str(tmp_path / "nope")) is None


def test_npz_export(tmp_path):
    init_fn, _ = make_train_step(CFG)
    b = _batch()
    state = init_fn(jax.random.PRNGKey(0), b["ids_a"], b["mask_a"])
    p = tmp_path / "params.npz"
    ckpt.save_params_npz(state.params, str(p))
    loaded = np.load(p)
    assert any("tok_emb" in k for k in loaded.files)
