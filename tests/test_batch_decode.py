"""Batched completion serving: left-padded batch decode equals serial
decode row for row, and the completion daemon's batched drain preserves
the per-key protocol.

The reference is strictly serial (one llama.cpp context per request,
/root/reference/splainference.cpp:414-448); batching is this
framework's TPU-first aggregate-throughput design, so its correctness
bar is exact row-vs-serial equality (greedy) plus protocol parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig

PROMPTS = [np.arange(1, 6, dtype=np.int32),
           np.arange(3, 15, dtype=np.int32),
           np.array([7, 8, 9], np.int32)]


@pytest.fixture(scope="module")
def model():
    # f32 on CPU so greedy argmax comparisons are tie-stable
    return CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(16, 32), temp=0.0)


def _serial(model, prompts, n, chunk):
    out = []
    for p in prompts:
        toks = [int(t) for t in model.generate_tokens(p, n, chunk=chunk)]
        model.reset()
        out.append(toks)
    return out


def _batched(model, prompts, n, chunk):
    cols = [c for c in model.generate_batch(prompts, n, chunk=chunk)]
    model.reset()
    return [list(map(int, row)) for row in np.stack(cols, axis=1)]


def test_batched_greedy_equals_serial(model):
    """Mixed-length prompts, greedy: every row of the batch must decode
    the exact serial token sequence (left-pad masking + per-row rotary
    offsets are position-exact)."""
    assert _batched(model, PROMPTS, 12, 4) == _serial(model, PROMPTS, 12, 4)


def test_batch_of_one_equals_serial(model):
    assert _batched(model, PROMPTS[:1], 10, 4) == \
        _serial(model, PROMPTS[:1], 10, 4)


def test_batch_padding_isolation(model):
    """Padding the batch to a power of two (3 real rows + 1 dummy) must
    not perturb real rows, and neither must batch composition."""
    two = _batched(model, PROMPTS[:2], 10, 4)
    three = _batched(model, PROMPTS, 10, 4)
    assert two == three[:2]


def test_chunk_size_invariance(model):
    """The chunk cadence is a host-sync boundary, not a semantic one."""
    assert _batched(model, PROMPTS, 12, 3) == _batched(model, PROMPTS, 12, 6)


def test_completer_batched_drain_protocol(tmp_path):
    """N waiting keys drain through ONE batched decode; every key gets
    the full label trifecta, a completion appended after its rendered
    prompt, and a ctime stamp."""
    name = f"/spt-batchcomp-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=128, max_val=2048, vec_dim=8)
    try:
        # f32 + pinned weight seed: greedy argmax over random bf16
        # weights is tie-unstable under batch padding, and seed 0's
        # batched path emits eos as row 0's FIRST token on jax 0.4.x —
        # a numerics artifact, not a protocol bug.  seed 1 decodes
        # real tokens for every row, so the appended-completion
        # assertion stays strong.
        model = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                                buckets=(32,), temp=0.0, seed=1)
        comp = Completer(st, model=model, max_new_tokens=12,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        keys = [f"q/{i}" for i in range(5)]     # 5 > batch_cap: 2 batches
        for i, k in enumerate(keys):
            st.set(k, f"prompt number {i}")
            st.label_or(k, P.LBL_INFER_REQ | P.LBL_WAITING)
            st.bump(k)
        n = comp.run_once()
        assert n == 5
        assert comp.stats.completions == 5
        for i, k in enumerate(keys):
            labels = st.labels(k)
            assert labels & P.LBL_READY, k
            assert not labels & (P.LBL_INFER_REQ | P.LBL_WAITING |
                                 P.LBL_SERVICING), k
            val = st.get(k).rstrip(b"\0")
            assert val.startswith(f"prompt number {i}".encode()), k
            assert len(val) > len(f"prompt number {i}"), \
                f"{k}: no completion appended"
    finally:
        st.close()
        Store.unlink(name)


def test_completer_batch_long_prompt_keeps_decode_room(tmp_path):
    """A prompt that clips near the window must still receive real
    decode room: the batched budget is measured in PADDING BUCKETS
    (prefill_batch parks the decode position at the bucket width), so
    a raw-length budget would strand every row at ~1 token."""
    name = f"/spt-longp-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=4096, vec_dim=8)
    try:
        # window 128, max_new 24: fitting buckets are those <= 104
        model = CompletionModel(DecoderConfig.tiny(), buckets=(32, 64, 96),
                                temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=24,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        long_prompt = "word " * 300            # way past the window
        st.set("long", long_prompt.encode()[: 3500])
        st.set("short", b"hi there")
        for k in ("long", "short"):
            st.label_or(k, P.LBL_INFER_REQ)
            st.bump(k)
        assert comp.run_once() == 2
        assert comp.stats.tokens >= 2 * 10, \
            f"rows starved of decode room: {comp.stats}"
        for k in ("long", "short"):
            assert st.labels(k) & P.LBL_READY
    finally:
        st.close()
        Store.unlink(name)


def test_completer_batch_empty_prompt_isolated(tmp_path):
    """An empty prompt must fail alone — the other rows of its batch
    still get full completions (no batch poisoning through
    prefill_batch's empty-prompt ValueError)."""
    name = f"/spt-emptyp-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        model = CompletionModel(DecoderConfig.tiny(), buckets=(32,),
                                temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=10,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        st.set("empty", b"")
        st.set("good", b"a real question")
        for k in ("empty", "good"):
            st.label_or(k, P.LBL_INFER_REQ)
            st.bump(k)
        assert comp.run_once() == 2
        assert st.labels("empty") & P.LBL_READY
        assert st.labels("good") & P.LBL_READY
        good = st.get("good").rstrip(b"\0")
        assert len(good) > len(b"a real question"), \
            "valid row was poisoned by the empty one"
    finally:
        st.close()
        Store.unlink(name)


def test_completer_batch_key_deleted_mid_generation(tmp_path):
    """A client deleting its key mid-decode must fail only its own
    row: siblings still stream to completion and the daemon survives
    (no KeyError escaping through the batch tail)."""
    name = f"/spt-delmid-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        model = CompletionModel(DecoderConfig.tiny(), buckets=(32,),
                                temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=16,
                         flush_tokens=2, template="none", batch_cap=4)
        comp.attach()
        for k in ("victim", "survivor"):
            st.set(k, f"prompt for {k}")
            st.label_or(k, P.LBL_INFER_REQ)
            st.bump(k)
        orig_flush = comp._flush
        state = {"deleted": False}

        def sabotaged(key, data):
            if key == "victim" and not state["deleted"]:
                # this store's append is an upsert, so a plain unset
                # would be resurrected by the next flush; force the
                # "gone" outcome _flush reports when the slot truly
                # cannot take the append (key recycled mid-request)
                st.unset("victim")
                state["deleted"] = True
                return "gone"
            return orig_flush(key, data)

        comp._flush = sabotaged
        n = comp.run_once()           # must not raise
        assert n == 2
        assert state["deleted"]
        assert st.labels("survivor") & P.LBL_READY
        val = st.get("survivor").rstrip(b"\0")
        assert len(val) > len(b"prompt for survivor")
        # accounting: the vanished key is neither a completion nor a
        # max_val truncation
        assert comp.stats.vanished == 1, comp.stats
        assert comp.stats.truncated == 0, comp.stats
        assert comp.stats.completions == 1, comp.stats
    finally:
        st.close()
        Store.unlink(name)


def test_completer_window_only_bucket_falls_back_serial(tmp_path):
    """buckets == (max_len,) gives the batched path zero decode room
    (prefill parks at the bucket width); run_once must serve such
    geometries serially, where the raw budget leaves real room."""
    name = f"/spt-tinywin-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=64),
                                buckets=(64,), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=12,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        assert comp._batched_budget() is None
        long_prompt = ("tok " * 40).encode()   # clips at the raw budget
        st.set("a", long_prompt)
        st.set("b", b"short one")
        for k in ("a", "b"):
            st.label_or(k, P.LBL_INFER_REQ)
            st.bump(k)
        assert comp.run_once() == 2
        assert comp.stats.tokens >= 8, comp.stats
        for k in ("a", "b"):
            assert st.labels(k) & P.LBL_READY
    finally:
        st.close()
        Store.unlink(name)


def test_completer_batched_matches_serial_content(tmp_path):
    """Greedy completions must be byte-identical whether the daemon
    served the keys batched or one at a time."""
    out: dict[str, bytes] = {}
    for cap, tag in ((1, "serial"), (4, "batched")):
        name = f"/spt-bvs-{tag}-{tmp_path.name}"
        Store.unlink(name)
        st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
        try:
            model = CompletionModel(
                DecoderConfig.tiny(dtype=jnp.float32), buckets=(32,),
                temp=0.0)
            comp = Completer(st, model=model, max_new_tokens=10,
                             flush_tokens=4, template="none",
                             batch_cap=cap)
            comp.attach()
            for i in range(3):
                k = f"q/{i}"
                st.set(k, f"say {i} things")
                st.label_or(k, P.LBL_INFER_REQ)
                st.bump(k)
            assert comp.run_once() == 3
            out[tag] = b"|".join(
                st.get(f"q/{i}").rstrip(b"\0") for i in range(3))
        finally:
            st.close()
            Store.unlink(name)
    assert out["serial"] == out["batched"]
