"""Continuous batched serving (completer.run_continuous +
decoder.join_row): requests join the live batch at chunk boundaries,
finished rows free their slots, and outputs stay token-exact.
"""
from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig


def test_join_row_token_exact():
    """A row joining mid-decode produces exactly its serial tokens and
    does not perturb the already-running row."""
    m = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                        buckets=(16, 32), temp=0.0)
    A = np.arange(1, 8, dtype=np.int32)
    Bp = np.array([9, 2, 6], np.int32)
    sa = [int(x) for x in m.generate_tokens(A, 16, chunk=4)]
    m.reset()
    sb = [int(x) for x in m.generate_tokens(Bp, 10, chunk=4)]
    m.reset()

    logits = m.prefill_batch([A, np.array([1], np.int32)])
    toks = np.array([int(np.argmax(logits[0])), 0], np.int32)
    out_a = [int(toks[0])]
    blk = m.decode_chunk_batch(toks, 6)
    out_a += [int(x) for x in blk[0]]
    jl = m.join_row(Bp, row=1)
    tok_b = int(np.argmax(jl))
    out_b = [tok_b]
    toks = np.array([int(blk[0][-1]), tok_b], np.int32)
    for _ in range(3):
        blk = m.decode_chunk_batch(toks, 3)
        out_a += [int(x) for x in blk[0]]
        out_b += [int(x) for x in blk[1]]
        toks = blk[:, -1].astype(np.int32)
    m.reset()
    assert out_a[:16] == sa[:16]
    assert out_b[:10] == sb[:10]


def test_join_row_clips_to_position():
    """A joiner whose prompt is longer than the batch position keeps
    only the most recent context instead of reaching behind pos."""
    m = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                        buckets=(16,), temp=0.0)
    m.prefill_batch([np.array([1, 2, 3], np.int32),
                     np.array([1], np.int32)])    # pos = 16
    long_prompt = np.arange(1, 40, dtype=np.int32) % 900 + 1
    logits = m.join_row(long_prompt, row=1)
    assert np.isfinite(np.asarray(logits)).all()
    assert int(np.asarray(m._start)[1]) == 0      # 16 recent tokens kept
    m.reset()


def test_continuous_serves_staggered_arrivals(tmp_path):
    """Keys arriving WHILE the batch decodes are serviced in the same
    window (join path), and every key gets the full label protocol."""
    name = f"/spt-cont-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=24,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        runner = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=90.0),
            daemon=True)
        runner.start()
        time.sleep(0.2)
        # first wave starts the batch
        for i in range(2):
            st.set(f"w1/{i}", f"first wave {i}")
            st.label_or(f"w1/{i}", P.LBL_INFER_REQ)
            st.bump(f"w1/{i}")
        time.sleep(1.0)               # batch is (or was) decoding
        # second wave must join without waiting for a full drain
        for i in range(3):
            st.set(f"w2/{i}", f"second wave {i}")
            st.label_or(f"w2/{i}", P.LBL_INFER_REQ)
            st.bump(f"w2/{i}")
        keys = [f"w1/{i}" for i in range(2)] + [f"w2/{i}" for i in range(3)]
        deadline = time.time() + 75
        while time.time() < deadline:
            if all(st.labels(k) & P.LBL_READY for k in keys):
                break
            time.sleep(0.05)
        comp.stop()
        runner.join(timeout=5)
        for k in keys:
            labels = st.labels(k)
            assert labels & P.LBL_READY, (k, comp.stats)
            assert not labels & (P.LBL_INFER_REQ | P.LBL_SERVICING), k
            val = st.get(k).rstrip(b"\0")
            assert len(val) > len(k) + 8, f"{k}: no completion"
        assert comp.stats.completions == 5
    finally:
        st.close()
        Store.unlink(name)


def test_continuous_defers_oversized_joiner(tmp_path):
    """A prompt longer than the live batch's join budget must NOT be
    clipped into the running batch — it waits for a fresh batch and
    then completes with its full context."""
    name = f"/spt-defer-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=128, max_val=4096, vec_dim=8)
    try:
        # window 128, buckets (16, 64): a fresh short batch sits at
        # pos=16, so a ~40-token joiner exceeds join_budget()=16
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 64), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=30,
                         flush_tokens=4, template="none", batch_cap=2)
        comp.attach()
        runner = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=120.0),
            daemon=True)
        runner.start()
        time.sleep(0.2)
        st.set("short", b"hi")
        st.label_or("short", P.LBL_INFER_REQ)
        st.bump("short")
        time.sleep(0.8)               # batch live at pos ~16
        long_prompt = ("tok " * 40).encode()     # ~41 tokens > 16
        st.set("long", long_prompt)
        st.label_or("long", P.LBL_INFER_REQ)
        st.bump("long")
        deadline = time.time() + 100
        while time.time() < deadline:
            if all(st.labels(k) & P.LBL_READY for k in ("short", "long")):
                break
            time.sleep(0.05)
        comp.stop()
        runner.join(timeout=5)
        for k in ("short", "long"):
            assert st.labels(k) & P.LBL_READY, (k, comp.stats)
        # the long prompt's value retains its FULL prompt (not clipped)
        val = st.get("long").rstrip(b"\0")
        assert val.startswith(long_prompt.rstrip()), "prompt was clipped"
        assert len(val) > len(long_prompt), "no completion appended"
    finally:
        st.close()
        Store.unlink(name)


def test_continuous_over_quantized_model(tmp_path):
    """Feature lattice: the slot scheduler serves an int8-resident
    model (join_row included) with the full protocol."""
    name = f"/spt-contq-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        model = CompletionModel(
            DecoderConfig.tiny(max_len=128, quantized=True),
            buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=16,
                         flush_tokens=4, template="none", batch_cap=2)
        comp.attach()
        runner = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=90.0),
            daemon=True)
        runner.start()
        time.sleep(0.2)
        st.set("a", b"first question")
        st.label_or("a", P.LBL_INFER_REQ)
        st.bump("a")
        time.sleep(0.8)
        st.set("b", b"late arrival")    # joins the live batch
        st.label_or("b", P.LBL_INFER_REQ)
        st.bump("b")
        deadline = time.time() + 75
        while time.time() < deadline:
            if all(st.labels(k) & P.LBL_READY for k in ("a", "b")):
                break
            time.sleep(0.05)
        comp.stop()
        runner.join(timeout=5)
        for k in ("a", "b"):
            assert st.labels(k) & P.LBL_READY, (k, comp.stats)
    finally:
        st.close()
        Store.unlink(name)


def test_continuous_falls_back_for_serial_models(tmp_path):
    """Models without join_row (speculative) serve through run()."""
    from libsplinter_tpu.models import SpeculativeCompletionModel

    name = f"/spt-contfb-{tmp_path.name}"
    Store.unlink(name)
    st = Store.create(name, nslots=64, max_val=2048, vec_dim=8)
    try:
        t = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                            buckets=(16,), temp=0.0, seed=2)
        d = CompletionModel(
            DecoderConfig.tiny(dtype=jnp.float32, layers=1),
            buckets=(16,), temp=0.0, seed=99)
        spec = SpeculativeCompletionModel(t, d, gamma=3)
        comp = Completer(st, model=spec, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=4)
        comp.attach()
        st.set("q", "fallback prompt")
        st.label_or("q", P.LBL_INFER_REQ)
        runner = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
            daemon=True)
        runner.start()
        deadline = time.time() + 50
        while time.time() < deadline:
            if st.labels("q") & P.LBL_READY:
                break
            time.sleep(0.05)
        comp.stop()
        runner.join(timeout=5)
        assert st.labels("q") & P.LBL_READY
    finally:
        st.close()
        Store.unlink(name)
