"""Pipeline-parallel encoder (parallel/pipeline.py): GPipe schedule over
the pp mesh axis must be EXACTLY the dense Encoder forward, for every
stage count / microbatch split, and differentiable."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.encoder import Encoder, EncoderConfig
from libsplinter_tpu.parallel import make_mesh
from libsplinter_tpu.parallel.pipeline import (make_pipeline_encode_fn,
                                               pipeline_encode,
                                               stack_layer_params)

CFG = EncoderConfig.tiny(out_dim=16, layers=4, dtype=jnp.float32)


@pytest.fixture(scope="module")
def setup():
    module = Encoder(CFG)
    B, S = 8, 16
    rng = np.random.default_rng(0)
    ids = rng.integers(0, CFG.vocab_size, size=(B, S)).astype(np.int32)
    mask = np.ones((B, S), bool)
    mask[1, 10:] = False                      # ragged lengths
    mask[5, 4:] = False
    params = module.init(jax.random.PRNGKey(0), ids, mask)
    dense = module.apply(params, ids, mask)
    return params, ids, mask, np.asarray(dense)


@pytest.mark.parametrize("stages,micro", [(2, 2), (2, 4), (4, 2),
                                          (4, 8), (1, 1)])
def test_matches_dense_forward(setup, stages, micro):
    params, ids, mask, dense = setup
    mesh = make_mesh(pp=stages)
    got = pipeline_encode(CFG, mesh, params, ids, mask,
                          microbatches=micro)
    np.testing.assert_allclose(np.asarray(got), dense,
                               rtol=2e-5, atol=2e-5)


def test_jitted_and_differentiable(setup):
    params, ids, mask, dense = setup
    mesh = make_mesh(pp=2)
    # staged entry: params placed once (each device holds its stage)
    fn = make_pipeline_encode_fn(CFG, mesh, params, microbatches=4)
    got = fn(ids, mask)
    np.testing.assert_allclose(np.asarray(got), dense,
                               rtol=2e-5, atol=2e-5)

    # grads flow through ppermute/scan: compare against dense grads
    module = Encoder(CFG)

    def loss_pipe(p):
        return jnp.sum(pipeline_encode(CFG, mesh, p, ids, mask,
                                       microbatches=4) ** 2)

    def loss_dense(p):
        return jnp.sum(module.apply(p, ids, mask) ** 2)

    ga = jax.grad(loss_pipe)(params)
    gb = jax.grad(loss_dense)(params)
    flat_a = jax.tree_util.tree_leaves_with_path(ga)
    flat_b = jax.tree_util.tree_leaves_with_path(gb)
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   rtol=1e-3, atol=1e-4, err_msg=str(pa))


def test_stack_layer_params_shape(setup):
    params, *_ = setup
    stacked = stack_layer_params(params, CFG)
    qkv = stacked["attn"]["qkv"]["kernel"]
    assert qkv.shape[0] == CFG.layers


def test_guards(setup):
    params, ids, mask, _ = setup
    mesh = make_mesh(pp=8)                    # 4 layers / 8 stages
    with pytest.raises(ValueError, match="divide"):
        pipeline_encode(CFG, mesh, params, ids, mask, microbatches=2)
    mesh2 = make_mesh(pp=2)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_encode(CFG, mesh2, params, ids, mask, microbatches=3)


def test_ring_axis_rejected(setup):
    import dataclasses
    params, ids, mask, _ = setup
    rcfg = dataclasses.replace(CFG, ring_axis="sp")
    mesh = make_mesh(pp=2)
    with pytest.raises(ValueError, match="ring_axis"):
        pipeline_encode(rcfg, mesh, params, ids, mask, microbatches=2)


def test_staged_params_actually_distributed(setup):
    """stage_params places each stage's layers on its own device row —
    the HBM story the module exists for."""
    from libsplinter_tpu.parallel.pipeline import stage_params
    params, *_ = setup
    mesh = make_mesh(pp=4)
    outer, staged = stage_params(params, CFG, mesh)
    qkv = staged["attn"]["qkv"]["kernel"]       # (4 stages, 1, ...)
    assert qkv.shape[0] == 4
    assert tuple(qkv.sharding.spec)[0] == "pp"
    # each addressable shard holds 1/4 of the stage axis
    shard = qkv.addressable_shards[0]
    assert shard.data.shape[0] == 1
