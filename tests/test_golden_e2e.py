"""Pinned end-to-end checkpoint golden (VERDICT r2 #5).

tests/fixtures/golden_encoder.gguf is a committed checkpoint: tiny
nomic-geometry encoder weights + a REAL trained HF WordPiece vocab, all
embedded in one self-describing GGUF.  These tests open it COLD — the
config, tokenizer, and weights all come from the file, no side-channel
setup — and must reproduce the committed token ids and embedding
vectors exactly.  Any regression anywhere in the
load→tokenize→encode chain (container parse, vocab handling, config
derivation, param mapping, encoder forward, matryoshka truncation)
breaks this as one artifact.

Regenerate deliberately with scripts/make_golden_fixture.py (a diff in
the fixture is the signal that the pinned behavior changed).

Reference analog: executing a published GGUF checkpoint end to end
(splinference.cpp:423-447).
"""
from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures")
GGUF = os.path.join(FIXDIR, "golden_encoder.gguf")
EXPECTED = os.path.join(FIXDIR, "golden_expected.json")


@pytest.fixture(scope="module")
def golden():
    with open(EXPECTED) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def cold_model():
    """The entire chain bootstrapped from the .gguf alone."""
    from libsplinter_tpu.models.encoder import EmbeddingModel
    from libsplinter_tpu.models.gguf import (GgufFile,
                                             encoder_config_from_gguf,
                                             load_tokenizer)
    with GgufFile(GGUF) as gf:
        cfg = encoder_config_from_gguf(gf, out_dim=32, dtype=jnp.float32)
        tok = load_tokenizer(gf)
    model = EmbeddingModel(cfg, weights=GGUF, buckets=(32,))
    return cfg, tok, model


def test_config_derived_from_container(cold_model, golden):
    cfg, _, _ = cold_model
    assert cfg.vocab_size == golden["config"]["vocab_size"]
    assert cfg.hidden == golden["config"]["hidden"]
    assert cfg.layers == golden["config"]["layers"]
    assert cfg.variant == "nomic"


def test_token_ids_pinned(cold_model, golden):
    _, tok, _ = cold_model
    for case in golden["texts"]:
        assert tok.encode(case["text"]) == case["token_ids"], case["text"]


def test_vectors_pinned(cold_model, golden):
    _, tok, model = cold_model
    for case in golden["texts"]:
        ids = case["token_ids"]
        arr = np.full((1, 32), tok.pad_id, np.int32)
        arr[0, : len(ids)] = ids
        vec = model.encode_ids(arr, np.array([len(ids)], np.int32))[0]
        np.testing.assert_allclose(
            np.asarray(vec), np.asarray(case["vector"], np.float32),
            rtol=0, atol=2e-6, err_msg=case["text"])


def test_vectors_unit_norm(cold_model, golden):
    """The encoder L2-normalizes (matryoshka-truncated) outputs."""
    for case in golden["texts"]:
        assert np.linalg.norm(case["vector"]) == pytest.approx(1.0,
                                                               abs=1e-5)


def test_unseen_text_uses_subword_backoff(cold_model):
    """A word absent from the trained vocab must decompose into ##pieces
    (or [UNK]), not crash — the WordPiece contract on real vocabs."""
    _, tok, model = cold_model
    ids = tok.encode("quixotic zephyrs")
    assert len(ids) >= 2
    arr = np.full((1, 32), tok.pad_id, np.int32)
    arr[0, : len(ids)] = ids[:32]
    vec = model.encode_ids(arr, np.array([min(len(ids), 32)], np.int32))[0]
    assert np.isfinite(np.asarray(vec)).all()


@pytest.mark.slow
def test_fixture_regeneration_is_deterministic():
    """make_golden_fixture.py must reproduce the committed gguf byte for
    byte (same trained vocab, same seeded weights, same layout) — proof
    the fixture is regenerable, not a snowflake binary."""
    import subprocess
    import sys
    import tempfile

    root = os.path.dirname(FIXDIR.rstrip(os.sep))
    root = os.path.dirname(root)
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, SPTPU_GOLDEN_OUT=td)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(root, "scripts", "make_golden_fixture.py")],
            env=env, capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(os.path.join(td, "golden_encoder.gguf"), "rb") as f:
            fresh = f.read()
        with open(GGUF, "rb") as f:
            committed = f.read()
        assert fresh == committed, (
            "regenerated fixture differs from the committed one — the "
            "load/tokenize/encode chain changed; re-pin deliberately "
            "with scripts/make_golden_fixture.py")
