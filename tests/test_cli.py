"""CLI workflow tests — UX-level parity with splinterctl_tests.sh
(init/set/get/head/list/type/unset/config/export/bump/append/uuid/math/
label/shard/search), driven through the real entry point."""
import json
import os
import sys
import threading
import uuid as uuidlib

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.cli.main import main
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.embedder import Embedder


@pytest.fixture
def cli(monkeypatch):
    name = f"/spt-cli-{os.getpid()}-{uuidlib.uuid4().hex[:8]}"
    monkeypatch.setenv("SPTPU_DEFAULT_STORE", name)
    monkeypatch.delenv("SPTPU_NS_PREFIX", raising=False)

    def run(*args):
        return main(list(args))

    run("init", "128", "512", "32")
    yield run, name
    Store.unlink(name)


def out_of(capsys):
    return capsys.readouterr().out


def test_set_get(cli, capsys):
    run, _ = cli
    assert run("set", "greet", "hello", "world") == 0
    assert run("get", "greet") == 0
    assert out_of(capsys).endswith("hello world\n")


def test_get_missing_errors(cli, capsys):
    run, _ = cli
    assert run("get", "nope") == 1


def test_append(cli, capsys):
    run, _ = cli
    run("set", "log", "a")
    run("append", "log", "b")
    run("get", "log")
    assert out_of(capsys).endswith("ab\n")


def test_list_regex(cli, capsys):
    run, _ = cli
    run("set", "apple", "1")
    run("set", "banana", "2")
    run("list", "^app")
    out = out_of(capsys)
    assert "apple" in out and "banana" not in out


def test_type_roundtrip(cli, capsys):
    run, _ = cli
    run("set", "t", "text")
    run("type", "t", "VARTEXT")
    run("type", "t")
    assert "VARTEXT" in out_of(capsys)


def test_math(cli, capsys):
    run, _ = cli
    run("set", "n", "41")
    run("type", "n", "BIGUINT")
    run("math", "n", "inc")
    assert out_of(capsys).strip().endswith("42")


def test_label_names_from_rc(cli, capsys, tmp_path, monkeypatch):
    rc = tmp_path / "rc"
    rc.write_text("hot = 0x10\n# comment\n")
    monkeypatch.setenv("SPTPU_RC", str(rc))
    run, _ = cli
    run("set", "k", "v")
    run("label", "k", "+hot")
    run("label", "k")
    assert "0x" in out_of(capsys)
    st = Store.open(os.environ["SPTPU_DEFAULT_STORE"])
    assert st.labels("k") == 0x10
    st.close()


def test_head_shows_vector_stats(cli, capsys):
    run, name = cli
    run("set", "h", "x")
    st = Store.open(name)
    st.vec_set("h", np.ones(32, np.float32))
    st.close()
    run("head", "h")
    out = out_of(capsys)
    assert "epoch" in out and "|v|=" in out


def test_health_reports_daemon_vitals(cli, capsys):
    """`health` shows heartbeat ages, shard bids, and signal activity
    for an operator's one-look liveness check."""
    run, name = cli
    st = Store.open(name)
    emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
        (len(ts), 32), np.float32), max_ctx=64)
    emb.attach()
    st.set("k", "text")
    st.set_type("k", 0x80)
    st.label_or("k", P.LBL_EMBED_REQ)
    emb.run_once()
    emb.publish_stats()
    st.close()
    assert run("health") == 0
    out = out_of(capsys)
    assert "embedder" in out and "embedded=1" in out
    assert "no heartbeat" in out          # completer not attached
    assert "bid" in out and "0x5f10" in out
    assert "signals" in out


def test_health_ignores_ns_prefix(cli, capsys, monkeypatch):
    """Heartbeat keys are daemon-owned well-known names; a client-side
    namespace prefix must not make health report daemons down."""
    run, name = cli
    st = Store.open(name)
    emb = Embedder(st, encoder_fn=lambda ts: np.zeros(
        (len(ts), 32), np.float32), max_ctx=64)
    emb.attach()
    emb.publish_stats()
    st.close()
    monkeypatch.setenv("SPTPU_NS_PREFIX", "teamA.")
    assert run("health") == 0
    out = out_of(capsys)
    assert "no heartbeat" not in out.split("completer")[0], out


def test_config_dump_and_purge(cli, capsys):
    run, _ = cli
    run("config")
    out = out_of(capsys)
    assert "geometry" in out and "128 slots" in out
    run("config", "purge")
    assert "swept" in out_of(capsys)


def test_unset_tandem(cli, capsys):
    run, name = cli
    st = Store.open(name)
    st.tandem_set("doc", [b"a", b"b", b"c"])
    st.close()
    run("orders", "doc")
    assert "3 orders" in out_of(capsys)
    run("unset", "doc", "--tandem")
    assert "removed 3" in out_of(capsys)


def test_shard_workflow(cli, capsys):
    run, _ = cli
    run("shard", "claim", "0x5F10", "40")
    assert "bid" in out_of(capsys)
    run("shard", "who")
    assert "sovereign" in out_of(capsys)
    run("shard", "table")
    assert "5f10" in out_of(capsys).lower()
    run("shard", "advise", "0", "willneed")
    assert "advised" in out_of(capsys)
    run("shard", "release", "0")
    run("shard", "who")
    assert "no sovereign" in out_of(capsys)


def test_uuid(cli, capsys):
    run, name = cli
    run("uuid", "myid")
    u = out_of(capsys).strip()
    st = Store.open(name)
    assert st.get_str("myid") == u
    st.close()


def test_ns_prefix(cli, capsys, monkeypatch):
    run, name = cli
    monkeypatch.setenv("SPTPU_NS_PREFIX", "app1/")
    run("set", "k", "scoped")
    st = Store.open(name)
    assert st.get_str("app1/k") == "scoped"
    st.close()


def test_ingest_and_export(cli, capsys, tmp_path):
    run, name = cli
    doc = tmp_path / "doc.txt"
    doc.write_text("lorem ipsum " * 200)   # forces multiple chunks
    run("ingest", "docs/d1", str(doc), "--no-embed")
    out = out_of(capsys)
    assert "ingested" in out
    st = Store.open(name)
    n = st.tandem_count("docs/d1")
    assert n >= 2
    meta = json.loads(st.get_str("docs/d1.meta"))
    assert meta["chunks"] == n
    assert st.labels("docs/d1") & P.LBL_CHUNK
    st.close()
    run("export", "--regex", "docs/")
    dump = json.loads(out_of(capsys))
    recs = dump["slots"]
    assert dump["count"] == len(recs)
    keys = {r["key"] for r in recs}
    assert "docs/d1" in keys and "docs/d1.meta" in keys
    # epoch-descending order
    epochs = [r["epoch"] for r in recs]
    assert epochs == sorted(epochs, reverse=True)


def fake_encoder(texts):
    out = np.zeros((len(texts), 32), np.float32)
    for i, t in enumerate(texts):
        h = abs(hash(t)) % 997
        rng = np.random.default_rng(h)
        out[i] = rng.normal(size=32)
        out[i] /= np.linalg.norm(out[i])
    return out


def test_search_end_to_end(cli, capsys):
    """search writes the scratch key, the daemon embeds it, and ranked
    results come back — the reference's demo loop through the CLI."""
    run, name = cli
    st = Store.open(name)
    emb = Embedder(st, encoder_fn=fake_encoder, max_ctx=512)
    emb.attach()
    docs = {f"doc{i}": f"document number {i}" for i in range(8)}
    for k, v in docs.items():
        st.set(k, v)
        st.label_or(k, P.LBL_EMBED_REQ)
    emb.run_once()

    stop = threading.Event()

    def daemon():
        while not stop.is_set():
            emb.run_once()
            stop.wait(0.01)

    t = threading.Thread(target=daemon)
    t.start()
    try:
        rc = run("search", "--json", "--limit", "3", "document number 3")
        assert rc == 0
        rows = json.loads(out_of(capsys))
        assert len(rows) == 3
        assert rows[0]["key"] == "doc3"     # same text -> same fake vec
        assert rows[0]["similarity"] == pytest.approx(1.0, abs=1e-4)
        assert rows[0]["distance"] == pytest.approx(0.0, abs=1e-2)
    finally:
        stop.set()
        t.join()
    # scratch key cleaned up
    assert not any(k.startswith(P.SEARCH_SCRATCH_PREFIX) for k in st.list())
    st.close()


def test_sharded_search_grows_past_stale_scratch(cli, capsys):
    """Stale __sqtmp_ scratch rows (crashed searches, possibly other
    hosts') hold QUERY embeddings, so they rank at the very top of a
    repeated query; the sharded path must grow its fetch until --limit
    real results come back (ADVICE r2 / review finding)."""
    run, name = cli
    st = Store.open(name)
    emb = Embedder(st, encoder_fn=fake_encoder, max_ctx=512)
    emb.attach()
    query = "document number 3"
    for i in range(8):
        st.set(f"doc{i}", f"document number {i}")
        st.label_or(f"doc{i}", P.LBL_EMBED_REQ)
    # five stale scratch rows carrying the exact query text (=> exact
    # query embedding under the deterministic fake encoder)
    for i in range(5):
        k = f"{P.SEARCH_SCRATCH_PREFIX}{40000 + i}"
        st.set(k, query)
        st.label_or(k, P.LBL_EMBED_REQ)
    emb.run_once()

    stop = threading.Event()

    def daemon():
        while not stop.is_set():
            emb.run_once()
            stop.wait(0.01)

    t = threading.Thread(target=daemon)
    t.start()
    try:
        # limit 8 = all real docs; first fetch (8+4) is swamped by the
        # 5 stale scratch rows and must grow
        rc = run("search", "--sharded", "--json", "--limit", "8", query)
        assert rc == 0
        rows = json.loads(out_of(capsys))
        assert len(rows) == 8
        keys = {r["key"] for r in rows}
        assert keys == {f"doc{i}" for i in range(8)}
        assert rows[0]["key"] == "doc3"
    finally:
        stop.set()
        t.join()
    st.close()


def test_search_degrades_without_daemon(cli, capsys):
    run, name = cli
    st = Store.open(name)
    st.set("alone", "no daemon here")
    st.close()
    rc = run("search", "--timeout", "50", "--json", "anything")
    assert rc == 0
    rows = json.loads(out_of(capsys))
    assert any(r["key"] == "alone" for r in rows)
    assert all(r["similarity"] is None for r in rows)


@pytest.mark.slow
def test_cli_regression_script():
    """The shell workflow regression (reference: splinterctl_tests.sh run
    under CTest) — exercises the one-shot CLI as an operator would."""
    import pathlib
    import subprocess

    script = pathlib.Path(__file__).parent / "cli_regression.sh"
    env = dict(os.environ, PYTHON=sys.executable)
    r = subprocess.run(["sh", str(script)], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


# --------------------------------------------- watch (continuous, r2 #6)

def test_watch_oneshot_timeout(cli, capsys):
    run, _ = cli
    run("set", "w", "v0")
    assert run("watch", "w", "60") == 0
    assert out_of(capsys).endswith("timeout\n")


def test_watch_oneshot_catches_change(cli, capsys):
    run, name = cli
    run("set", "w", "v0")
    st = Store.open(name)

    def writer():
        import time as _t
        _t.sleep(0.1)
        st.set("w", "fresh value")

    t = threading.Thread(target=writer)
    t.start()
    try:
        assert run("watch", "w", "3000") == 0
    finally:
        t.join()
    st.close()
    assert "11:fresh value" in out_of(capsys)


def test_watch_continuous_streams_until_ctrl_bracket(cli, capsys,
                                                    monkeypatch):
    """Continuous loop: multiple changes stream as size:value lines;
    Ctrl-] (0x1d) on stdin ends the loop — driven through a real pipe
    exactly like the cli_regression.sh interactive check."""
    run, name = cli
    run("set", "w", "v0")
    st = Store.open(name)
    r, w = os.pipe()
    monkeypatch.setattr("sys.stdin", os.fdopen(r, "rb", buffering=0))

    rc_box = {}

    def watcher():
        rc_box["rc"] = run("watch", "w")

    t = threading.Thread(target=watcher)
    t.start()
    try:
        import time as _t
        deadline = _t.monotonic() + 5.0
        st.set("w", "one")
        st.set("w", "two")                  # may coalesce with "one"
        while "2:" not in _read_captured(capsys) and \
                _t.monotonic() < deadline:
            st.set("w", "two")
            _t.sleep(0.05)
        os.write(w, b"\x1d")                # Ctrl-]
        t.join(timeout=5)
        assert not t.is_alive(), "watch did not abort on Ctrl-]"
    finally:
        os.close(w)
        if t.is_alive():
            t.join(timeout=1)
    st.close()
    assert rc_box["rc"] == 0
    assert "3:two" in _CAPTURED["buf"]


_CAPTURED = {"buf": ""}


def _read_captured(capsys) -> str:
    out = capsys.readouterr().out
    _CAPTURED["buf"] += out
    return _CAPTURED["buf"]


def test_watch_group_oneshot(cli, capsys):
    run, name = cli
    st = Store.open(name)
    st.set("g", "x")
    st.watch_register("g", 5)

    def pulser():
        import time as _t
        _t.sleep(0.1)
        st.bump("g")

    t = threading.Thread(target=pulser)
    t.start()
    try:
        assert run("watch", "@5", "3000") == 0
    finally:
        t.join()
    st.close()
    assert "group 5 pulsed" in out_of(capsys)


def test_watch_oneshot_ignores_stdin_eof(cli, capsys, monkeypatch):
    """A backgrounded oneshot watch (stdin at EOF, e.g. /dev/null or an
    exhausted pipe) must honor its bounded wait — EOF-as-abort applies
    to the continuous loop only (review r3 finding)."""
    run, name = cli
    run("set", "w", "v0")
    r, w = os.pipe()
    os.close(w)                                # stdin is instantly EOF
    monkeypatch.setattr("sys.stdin", os.fdopen(r, "rb", buffering=0))
    st = Store.open(name)

    def writer():
        import time as _t
        _t.sleep(0.3)
        st.set("w", "late")

    t = threading.Thread(target=writer)
    t.start()
    try:
        assert run("watch", "w", "3000") == 0
    finally:
        t.join()
    st.close()
    assert "4:late" in out_of(capsys)


def test_watch_survives_unset_recreate(cli, capsys, monkeypatch):
    """unset + re-create may move the key to another slot; the watch
    loop must re-resolve, not pin a stale slot index."""
    run, name = cli
    run("set", "w", "v0")
    st = Store.open(name)
    r, w = os.pipe()
    monkeypatch.setattr("sys.stdin", os.fdopen(r, "rb", buffering=0))

    out_box = {}

    def watcher():
        out_box["rc"] = run("watch", "w")

    t = threading.Thread(target=watcher)
    t.start()
    try:
        import time as _t
        _t.sleep(0.1)
        st.unset("w")
        # occupy the freed slot region with fresh keys, then re-create
        for i in range(8):
            st.set(f"filler/{i}", "x")
        st.set("w", "reborn")
        # generous vs the watcher's 100 ms poll: under full-suite load
        # (XLA compiles saturating the box) 5 s has proven flaky
        deadline = _t.monotonic() + 15.0
        while "6:reborn" not in _read_captured(capsys) and \
                _t.monotonic() < deadline:
            st.bump("w")
            _t.sleep(0.05)
        os.write(w, b"\x1d")
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        os.close(w)
        if t.is_alive():
            t.join(timeout=1)
    st.close()
    assert "6:reborn" in _CAPTURED["buf"]
