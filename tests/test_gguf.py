"""GGUF container, dequantization, weight-mapping, and tokenizer tests.

Synthetic GGUF files are assembled by the writer below (no llama.cpp in
the image), covering the v3 container layout, every supported ggml dtype,
the llama.cpp tensor-name conventions for both model families, and the
embedded tokenizer metadata (bert WordPiece + llama unigram).
"""
from __future__ import annotations

import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.gguf import (
    GGML_BF16, GGML_F16, GGML_F32, GGML_Q4_0, GGML_Q4_1, GGML_Q8_0,
    GgufError, GgufFile, UnigramTokenizer, load_decoder_params,
    load_encoder_params, load_tokenizer,
)

# ---------------------------------------------------------- gguf writer
# The writer lives in the package now (models/gguf_writer.py — it also
# produces the committed golden fixture); these tests import it so the
# reader is exercised against the same byte layout users export.

from libsplinter_tpu.models.gguf_writer import (  # noqa: E402
    kv_f32_array, kv_i32_array, kv_str, kv_str_array, kv_u32, write_gguf,
)

_T_U32, _T_F32, _T_STRING, _T_ARRAY, _T_U64 = 4, 6, 8, 9, 10
_T_I32 = 5


def _s(txt: str) -> bytes:
    b = txt.encode()
    return struct.pack("<Q", len(b)) + b


def _kv(key: str, vtype: int, payload: bytes) -> bytes:
    return _s(key) + struct.pack("<I", vtype) + payload


# ------------------------------------------------------------- container

def test_container_metadata_and_tensor(tmp_path):
    p = tmp_path / "m.gguf"
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    write_gguf(p, {"t.weight": (arr, GGML_F32)},
               [kv_str("general.name", "demo"),
                kv_u32("demo.n_layer", 3),
                kv_f32_array("demo.scores", [0.5, -1.0])])
    with GgufFile(p) as gf:
        assert gf.metadata["general.name"] == "demo"
        assert gf.metadata["demo.n_layer"] == 3
        assert gf.metadata["demo.scores"] == [0.5, -1.0]
        np.testing.assert_array_equal(gf.tensor("t.weight"), arr)
        with pytest.raises(KeyError, match="no tensor"):
            gf.tensor("missing")


def test_bad_magic(tmp_path):
    p = tmp_path / "bad.gguf"
    p.write_bytes(b"NOPE" + b"\0" * 64)
    with pytest.raises(GgufError, match="magic"):
        GgufFile(p)


@pytest.mark.parametrize("gtype,atol", [
    (GGML_F32, 0), (GGML_F16, 2e-3), (GGML_BF16, 2e-2),
    (GGML_Q8_0, 2e-2), (GGML_Q4_0, 0.3), (GGML_Q4_1, 0.2),
])
def test_dequantization(tmp_path, gtype, atol):
    p = tmp_path / f"q{gtype}.gguf"
    rng = np.random.default_rng(5)
    arr = rng.standard_normal((4, 64)).astype(np.float32)
    write_gguf(p, {"w": (arr, gtype)})
    with GgufFile(p) as gf:
        got = gf.tensor("w")
    assert got.shape == arr.shape
    np.testing.assert_allclose(got, arr, atol=atol or 1e-7)


# ---------------------------------------------------------- weight mapping

def _decoder_gguf_from_params(path, params, cfg, *, tied=False,
                              gtype=GGML_F32):
    p = jax.tree.map(lambda x: np.asarray(x, np.float32), params["params"])
    t = {"token_embd.weight": (p["tok_emb"]["embedding"], gtype),
         "output_norm.weight": (p["ln_out"]["scale"], GGML_F32)}
    if not tied:
        t["output.weight"] = (p["lm_head"]["kernel"].T.copy(), gtype)
    for i in range(cfg.layers):
        lp = p[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_norm.weight"] = (lp["ln_attn"]["scale"], GGML_F32)
        t[f"{b}.ffn_norm.weight"] = (lp["ln_mlp"]["scale"], GGML_F32)
        for src, dst in (("q", "attn_q"), ("k", "attn_k"),
                         ("v", "attn_v"), ("out", "attn_output")):
            t[f"{b}.{dst}.weight"] = (lp["attn"][src]["kernel"].T.copy(),
                                      gtype)
        for name in ("gate", "up", "down"):
            t[f"{b}.ffn_{name}.weight"] = (lp[name]["kernel"].T.copy(),
                                           gtype)
    write_gguf(path, t)


def test_decoder_gguf_round_trip(tmp_path):
    from libsplinter_tpu.models.decoder import (
        CompletionModel, Decoder, DecoderConfig, init_cache,
    )
    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    params = Decoder(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32),
                               init_cache(cfg, 1), jnp.int32(0))
    p = tmp_path / "lm.gguf"
    _decoder_gguf_from_params(p, params, cfg)
    loaded = load_decoder_params(str(p), cfg)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [q for q, _ in flat_a] == [q for q, _ in flat_b]
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   err_msg=str(pa))
    # the weights= entry point routes .gguf correctly
    a = CompletionModel(cfg, params=params, temp=0.0)
    b = CompletionModel(cfg, weights=str(p), temp=0.0)
    prompt = np.arange(1, 9, dtype=np.int32)
    np.testing.assert_allclose(a.prefill(prompt), b.prefill(prompt),
                               rtol=1e-6)


def test_decoder_gguf_tied_and_quantized(tmp_path):
    from libsplinter_tpu.models.decoder import (
        Decoder, DecoderConfig, init_cache,
    )
    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    params = Decoder(cfg).init(jax.random.PRNGKey(1),
                               jnp.zeros((1, 8), jnp.int32),
                               init_cache(cfg, 1), jnp.int32(0))
    p = tmp_path / "lm-q8.gguf"
    _decoder_gguf_from_params(p, params, cfg, tied=True, gtype=GGML_Q8_0)
    loaded = load_decoder_params(str(p), cfg)
    # tied: lm_head = tok_emb^T (dequantized)
    np.testing.assert_allclose(
        np.asarray(loaded["params"]["lm_head"]["kernel"]),
        np.asarray(loaded["params"]["tok_emb"]["embedding"]).T)
    # Q8_0 dequant stays close to the original
    np.testing.assert_allclose(
        np.asarray(loaded["params"]["tok_emb"]["embedding"]),
        np.asarray(params["params"]["tok_emb"]["embedding"]), atol=2e-2)


def test_encoder_gguf_round_trip(tmp_path):
    from libsplinter_tpu.models.encoder import Encoder, EncoderConfig
    cfg = EncoderConfig.tiny(variant="nomic", dtype=jnp.float32)
    params = Encoder(cfg).init(jax.random.PRNGKey(2),
                               np.ones((1, 8), np.int32),
                               np.ones((1, 8), bool))
    p = jax.tree.map(lambda x: np.asarray(x, np.float32),
                     params["params"])
    t = {"token_embd.weight": (p["tok_emb"]["embedding"], GGML_F32),
         "token_embd_norm.weight": (p["ln_emb"]["scale"], GGML_F32),
         "token_embd_norm.bias": (p["ln_emb"]["bias"], GGML_F32)}
    for i in range(cfg.layers):
        lp = p[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_qkv.weight"] = (lp["attn"]["qkv"]["kernel"].T.copy(),
                                     GGML_F32)
        t[f"{b}.attn_qkv.bias"] = (lp["attn"]["qkv"]["bias"], GGML_F32)
        t[f"{b}.attn_output.weight"] = (
            lp["attn"]["out"]["kernel"].T.copy(), GGML_F32)
        t[f"{b}.attn_output.bias"] = (lp["attn"]["out"]["bias"], GGML_F32)
        t[f"{b}.attn_output_norm.weight"] = (lp["ln_attn"]["scale"],
                                             GGML_F32)
        t[f"{b}.attn_output_norm.bias"] = (lp["ln_attn"]["bias"],
                                           GGML_F32)
        t[f"{b}.layer_output_norm.weight"] = (lp["ln_mlp"]["scale"],
                                              GGML_F32)
        t[f"{b}.layer_output_norm.bias"] = (lp["ln_mlp"]["bias"],
                                            GGML_F32)
        for name in ("gate", "up", "down"):
            t[f"{b}.ffn_{name}.weight"] = (
                lp["mlp"][name]["kernel"].T.copy(), GGML_F32)
            t[f"{b}.ffn_{name}.bias"] = (lp["mlp"][name]["bias"],
                                         GGML_F32)
    path = tmp_path / "enc.gguf"
    write_gguf(path, t)
    loaded = load_encoder_params(str(path), cfg)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [q for q, _ in flat_a] == [q for q, _ in flat_b]
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   err_msg=str(pa))


# ------------------------------------------------------------- tokenizers

def test_bert_tokenizer_from_gguf(tmp_path):
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "hello", "world", "##ly"]
    p = tmp_path / "tok.gguf"
    write_gguf(p, {"dummy": (np.zeros((1, 1), np.float32), GGML_F32)},
               [kv_str("tokenizer.ggml.model", "bert"),
                kv_str_array("tokenizer.ggml.tokens", vocab)])
    tok = load_tokenizer(str(p))
    ids = tok.encode("hello worldly")
    assert [vocab[i] for i in ids] == ["[CLS]", "hello", "world", "##ly",
                                      "[SEP]"]


def test_unigram_tokenizer_from_gguf(tmp_path):
    tokens = ["<unk>", "<s>", "</s>", "▁", "▁hello", "▁world", "hell",
              "o", "wor", "ld", "▁h"]
    scores = [-10.0, 0.0, 0.0, -3.0, -1.0, -1.0, -4.0, -4.5, -4.0, -4.5,
              -4.0]
    p = tmp_path / "spm.gguf"
    write_gguf(p, {"dummy": (np.zeros((1, 1), np.float32), GGML_F32)},
               [kv_str("tokenizer.ggml.model", "llama"),
                kv_str_array("tokenizer.ggml.tokens", tokens),
                kv_f32_array("tokenizer.ggml.scores", scores),
                _kv("tokenizer.ggml.bos_token_id", _T_U32,
                    struct.pack("<I", 1)),
                _kv("tokenizer.ggml.eos_token_id", _T_U32,
                    struct.pack("<I", 2))])
    tok = load_tokenizer(str(p))
    ids = tok.encode("hello world")
    # viterbi picks the high-score whole-word pieces
    assert ids[0] == 1                       # BOS
    assert [tokens[i] for i in ids[1:]] == ["▁hello", "▁world"]
    assert tok.decode(ids) == "hello world"


def test_unigram_byte_fallback():
    tokens = ["<unk>", "<s>", "</s>", "▁a"] + \
        [f"<0x{b:02X}>" for b in range(256)]
    tok = UnigramTokenizer(tokens, None, bos_token_id=1, eos_token_id=2,
                           unknown_token_id=0)
    ids = tok.encode("aé", add_bos=False)   # é: not in vocab
    assert ids[0] == tokens.index("▁a")
    # é encodes to two utf-8 bytes via the byte pieces
    assert [tokens[i] for i in ids[1:]] == ["<0xC3>", "<0xA9>"]



def test_decoder_config_from_metadata(tmp_path):
    from libsplinter_tpu.models.gguf import decoder_config_from_gguf
    p = tmp_path / "cfg.gguf"
    write_gguf(p, {"token_embd.weight":
                   (np.zeros((1024, 64), np.float32), GGML_F32)},
               [kv_str("general.architecture", "llama"),
                kv_u32("llama.block_count", 2),
                kv_u32("llama.embedding_length", 64),
                kv_u32("llama.attention.head_count", 4),
                kv_u32("llama.attention.head_count_kv", 2),
                kv_u32("llama.feed_forward_length", 128),
                kv_u32("llama.context_length", 512),
                _kv("llama.rope.freq_base", _T_F32,
                    struct.pack("<f", 50000.0)),
                kv_str_array("tokenizer.ggml.tokens",
                             [f"t{i}" for i in range(1024)])])
    cfg = decoder_config_from_gguf(str(p))
    assert (cfg.vocab_size, cfg.hidden, cfg.layers, cfg.heads,
            cfg.kv_heads, cfg.mlp_dim, cfg.max_len) == \
        (1024, 64, 2, 4, 2, 128, 512)
    assert cfg.rope_base == 50000.0
    # overrides win (e.g. shorter KV cache than the trained window)
    assert decoder_config_from_gguf(str(p), max_len=128).max_len == 128


def test_decoder_config_missing_metadata_is_loud(tmp_path):
    from libsplinter_tpu.models.gguf import decoder_config_from_gguf
    p = tmp_path / "sparse.gguf"
    write_gguf(p, {"token_embd.weight":
                   (np.zeros((8, 4), np.float32), GGML_F32)},
               [kv_str("general.architecture", "llama")])
    with pytest.raises(GgufError, match="lacks"):
        decoder_config_from_gguf(str(p))


def test_unigram_stream_and_decode_byte_fallback():
    tokens = ["<unk>", "<s>", "</s>", "▁a", "▁b"] + \
        [f"<0x{b:02X}>" for b in range(256)]
    tok = UnigramTokenizer(tokens, None, bos_token_id=1, eos_token_id=2,
                           unknown_token_id=0)
    ids = tok.encode("a\nb", add_bos=False)
    # newline went through byte fallback; decode restores it exactly
    assert tok.decode(ids) == "a\nb"
    assert tok.token_to_piece(tokens.index("▁a")) == b" a"
    assert tok.token_to_piece(tokens.index("<0x0A>")) == b"\n"
    assert tok.token_to_piece(2) == b""          # EOS streams nothing


def test_completer_from_gguf_end_to_end(tmp_path):
    """Full --weights wiring: geometry from metadata, weights from
    tensors, unigram tokenizer from metadata, streamed through the store's
    completion protocol."""
    import os
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.models.decoder import (
        CompletionModel, Decoder, DecoderConfig, init_cache,
    )
    from libsplinter_tpu.models.gguf import (
        decoder_config_from_gguf, load_tokenizer,
    )
    from libsplinter_tpu.store import Store

    vocab = ["<unk>", "<s>", "</s>", "▁the", "▁cat", "▁sat", "▁mat",
             "▁on"] + [f"tok{i}" for i in range(120)]
    cfg0 = DecoderConfig.tiny(vocab_size=len(vocab), dtype=jnp.float32)
    params = Decoder(cfg0).init(jax.random.PRNGKey(9),
                                jnp.zeros((1, 8), jnp.int32),
                                init_cache(cfg0, 1), jnp.int32(0))
    p = tmp_path / "chat.gguf"
    pz = jax.tree.map(lambda x: np.asarray(x, np.float32),
                      params["params"])
    t = {"token_embd.weight": (pz["tok_emb"]["embedding"], GGML_F32),
         "output_norm.weight": (pz["ln_out"]["scale"], GGML_F32),
         "output.weight": (pz["lm_head"]["kernel"].T.copy(), GGML_F32)}
    for i in range(cfg0.layers):
        lp = pz[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_norm.weight"] = (lp["ln_attn"]["scale"], GGML_F32)
        t[f"{b}.ffn_norm.weight"] = (lp["ln_mlp"]["scale"], GGML_F32)
        for src, dst in (("q", "attn_q"), ("k", "attn_k"),
                         ("v", "attn_v"), ("out", "attn_output")):
            t[f"{b}.{dst}.weight"] = (lp["attn"][src]["kernel"].T.copy(),
                                      GGML_F32)
        for name in ("gate", "up", "down"):
            t[f"{b}.ffn_{name}.weight"] = (lp[name]["kernel"].T.copy(),
                                           GGML_F32)
    write_gguf(p, t, [
        kv_str("general.architecture", "llama"),
        kv_u32("llama.block_count", cfg0.layers),
        kv_u32("llama.embedding_length", cfg0.hidden),
        kv_u32("llama.attention.head_count", cfg0.heads),
        kv_u32("llama.attention.head_count_kv", cfg0.kv_heads),
        kv_u32("llama.feed_forward_length", cfg0.mlp_dim),
        kv_u32("llama.context_length", cfg0.max_len),
        kv_str("tokenizer.ggml.model", "llama"),
        kv_str_array("tokenizer.ggml.tokens", vocab),
        kv_f32_array("tokenizer.ggml.scores", [-1.0] * len(vocab)),
        _kv("tokenizer.ggml.bos_token_id", _T_U32, struct.pack("<I", 1)),
        _kv("tokenizer.ggml.eos_token_id", _T_U32, struct.pack("<I", 2)),
    ])

    cfg = decoder_config_from_gguf(str(p))
    assert (cfg.layers, cfg.hidden, cfg.vocab_size) == \
        (cfg0.layers, cfg0.hidden, len(vocab))
    model = CompletionModel(cfg, weights=str(p), temp=0.0)
    tok = load_tokenizer(str(p))

    name = f"gguf-comp-{os.getpid()}"
    st = Store.create(name, nslots=64, max_val=512, vec_dim=0)
    try:
        comp = Completer(st, model=model, tokenizer=tok,
                         max_new_tokens=8, template="none")
        comp.attach()
        st.set("ask", b"the cat sat")
        st.label_or("ask", P.LBL_INFER_REQ)
        st.bump("ask")
        n = comp.run_once()
        assert n == 1
        assert st.labels("ask") & P.LBL_READY
        out = st.get("ask").rstrip(b"\0")
        assert len(out) > 0              # streamed SOMETHING readable
    finally:
        st.close()
        Store.unlink(name)


def test_byte_bpe_tokenizer():
    from libsplinter_tpu.models.gguf import ByteBpeTokenizer, _gpt2_byte_map
    b2u = _gpt2_byte_map()
    # tiny vocab: single mapped bytes + a few merged pieces
    base = [b2u[b] for b in range(256)]
    space = b2u[ord(" ")]
    vocab = base + [space + "c", "at", space + "cat", "he", "llo",
                    "hello", space + "hello", "<|endoftext|>"]
    merges = [f"{space} c", "a t", f"{space}c at", "h e", "l l",
              "ll o", "he llo", f"{space} hello"]
    tok = ByteBpeTokenizer(vocab, merges, eos_token_id=len(vocab) - 1)
    ids = tok.encode("hello cat", add_bos=False)
    pieces = [vocab[i] for i in ids]
    assert pieces == ["hello", space + "cat"]
    assert tok.decode(ids) == "hello cat"
    # non-ascii round-trips through the byte table
    ids2 = tok.encode("héllo", add_bos=False)
    assert tok.decode(ids2) == "héllo"
    # streaming interface yields raw utf-8 bytes
    assert tok.token_to_piece(vocab.index("hello")) == b"hello"
    assert tok.token_to_piece(vocab.index(space + "cat")) == b" cat"
    assert tok.token_to_piece(len(vocab) - 1) == b""   # EOS


def test_byte_bpe_from_gguf(tmp_path):
    from libsplinter_tpu.models.gguf import _gpt2_byte_map
    b2u = _gpt2_byte_map()
    vocab = [b2u[b] for b in range(256)] + ["ab"]
    p = tmp_path / "bpe.gguf"
    write_gguf(p, {"dummy": (np.zeros((1, 1), np.float32), GGML_F32)},
               [kv_str("tokenizer.ggml.model", "gpt2"),
                kv_str_array("tokenizer.ggml.tokens", vocab),
                kv_str_array("tokenizer.ggml.merges", ["a b"])])
    tok = load_tokenizer(str(p))
    ids = tok.encode("ab", add_bos=False)
    assert [vocab[i] for i in ids] == ["ab"]
    assert tok.decode(ids) == "ab"


# ------------------------------------------- ADVICE r1: special tokens etc.


def test_unigram_special_tokens_parse_atomically():
    from libsplinter_tpu.models.gguf import (TOKTYPE_CONTROL,
                                             TOKTYPE_NORMAL)
    tokens = ["<unk>", "<s>", "</s>", "<|im_start|>", "<|im_end|>",
              "user", "▁hello", "▁user"]
    types = [TOKTYPE_NORMAL, TOKTYPE_CONTROL, TOKTYPE_CONTROL,
             TOKTYPE_CONTROL, TOKTYPE_CONTROL, TOKTYPE_NORMAL,
             TOKTYPE_NORMAL, TOKTYPE_NORMAL]
    tok = UnigramTokenizer(tokens, None, bos_token_id=1, eos_token_id=2,
                           unknown_token_id=0, token_types=types)
    ids = tok.encode("<|im_start|>user", add_bos=False)
    # SPM dummy-space prefix re-applies after a special token
    # (llama.cpp is_prev_special behavior)
    assert [tokens[i] for i in ids] == ["<|im_start|>", "▁user"]
    # without types the marker would shatter into unk/byte pieces
    tok_naive = UnigramTokenizer(tokens, None, bos_token_id=1,
                                 eos_token_id=2, unknown_token_id=0)
    assert tok_naive.encode("<|im_start|>user", add_bos=False) != ids
    # control tokens never leak into streamed text
    assert tok.token_to_piece(3) == b""
    # SPM space prefix still applies to leading ordinary text
    assert tok.encode("hello", add_bos=False) == [tokens.index("▁hello")]


def test_byte_bpe_special_tokens_parse_atomically(tmp_path):
    from libsplinter_tpu.models.gguf import (TOKTYPE_CONTROL,
                                             TOKTYPE_NORMAL, _gpt2_byte_map)
    b2u = _gpt2_byte_map()
    vocab = [b2u[b] for b in range(256)] + ["ab", "<|im_start|>"]
    types = [TOKTYPE_NORMAL] * 257 + [TOKTYPE_CONTROL]
    p = tmp_path / "bpe_special.gguf"
    write_gguf(p, {"dummy": (np.zeros((1, 1), np.float32), GGML_F32)},
               [kv_str("tokenizer.ggml.model", "gpt2"),
                kv_str_array("tokenizer.ggml.tokens", vocab),
                kv_str_array("tokenizer.ggml.merges", ["a b"]),
                kv_i32_array("tokenizer.ggml.token_type", types)])
    tok = load_tokenizer(str(p))
    ids = tok.encode("<|im_start|>ab", add_bos=False)
    assert [vocab[i] for i in ids] == ["<|im_start|>", "ab"]
    assert tok.decode(ids) == "ab"            # control piece not streamed
    # the marker must NOT be byte-BPE'd into <, |, im, ... fragments
    assert len(ids) == 2


def test_encoder_token_types_folded_into_embeddings(tmp_path):
    """bert GGUFs add token_types row 0 to every embedding before
    token_embd_norm (ADVICE r1); the loader folds it into tok_emb."""
    from libsplinter_tpu.models.encoder import Encoder, EncoderConfig
    from libsplinter_tpu.models.gguf import load_encoder_params
    cfg = EncoderConfig.tiny(variant="bert", dtype=jnp.float32)
    params = Encoder(cfg).init(jax.random.PRNGKey(4),
                               np.ones((1, 8), np.int32),
                               np.ones((1, 8), bool))
    p = jax.tree.map(lambda x: np.asarray(x, np.float32),
                     params["params"])
    ttypes = np.stack([np.full(cfg.hidden, 0.25, np.float32),
                       np.zeros(cfg.hidden, np.float32)])
    t = {"token_embd.weight": (p["tok_emb"]["embedding"], GGML_F32),
         "token_types.weight": (ttypes, GGML_F32),
         "position_embd.weight": (p["pos_emb"]["embedding"], GGML_F32),
         "token_embd_norm.weight": (p["ln_emb"]["scale"], GGML_F32),
         "token_embd_norm.bias": (p["ln_emb"]["bias"], GGML_F32)}
    for i in range(cfg.layers):
        lp = p[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_qkv.weight"] = (lp["attn"]["qkv"]["kernel"].T.copy(),
                                     GGML_F32)
        t[f"{b}.attn_qkv.bias"] = (lp["attn"]["qkv"]["bias"], GGML_F32)
        t[f"{b}.attn_output.weight"] = (
            lp["attn"]["out"]["kernel"].T.copy(), GGML_F32)
        t[f"{b}.attn_output.bias"] = (lp["attn"]["out"]["bias"], GGML_F32)
        t[f"{b}.attn_output_norm.weight"] = (lp["ln_attn"]["scale"],
                                             GGML_F32)
        t[f"{b}.attn_output_norm.bias"] = (lp["ln_attn"]["bias"],
                                           GGML_F32)
        t[f"{b}.layer_output_norm.weight"] = (lp["ln_mlp"]["scale"],
                                              GGML_F32)
        t[f"{b}.layer_output_norm.bias"] = (lp["ln_mlp"]["bias"],
                                            GGML_F32)
        for name in ("up", "down"):
            t[f"{b}.ffn_{name}.weight"] = (
                lp["mlp"][name]["kernel"].T.copy(), GGML_F32)
            t[f"{b}.ffn_{name}.bias"] = (lp["mlp"][name]["bias"],
                                         GGML_F32)
    path = tmp_path / "enc_tt.gguf"
    write_gguf(path, t)
    loaded = load_encoder_params(str(path), cfg)
    got = np.asarray(loaded["params"]["tok_emb"]["embedding"])
    want = p["tok_emb"]["embedding"] + 0.25
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_metadata_huge_array_count_fails_fast(tmp_path):
    """A corrupt u64 array count must raise GgufError before any
    allocation proportional to the claimed count (ADVICE r1)."""
    p = tmp_path / "evil.gguf"
    body = struct.pack("<IIQQ", 0x46554747, 3, 0, 1)     # 0 tensors, 1 kv
    body += _s("evil.key") + struct.pack("<I", _T_ARRAY)
    body += struct.pack("<IQ", _T_STRING, 1 << 60)       # absurd count
    p.write_bytes(body)
    with pytest.raises(GgufError, match="exceeds remaining"):
        GgufFile(p)


def test_metadata_huge_string_length_fails_fast(tmp_path):
    p = tmp_path / "evil2.gguf"
    body = struct.pack("<IIQQ", 0x46554747, 3, 0, 1)
    body += struct.pack("<Q", 1 << 62)                   # huge key length
    p.write_bytes(body)
    with pytest.raises(GgufError, match="exceeds remaining"):
        GgufFile(p)


def test_metadata_huge_kv_count_fails_fast(tmp_path):
    p = tmp_path / "evil3.gguf"
    body = struct.pack("<IIQQ", 0x46554747, 3, 0, 1 << 58)
    p.write_bytes(body)
    with pytest.raises(GgufError, match="exceeds remaining"):
        GgufFile(p)


def test_user_defined_tokens_parse_atomically_but_stream_text():
    """USER_DEFINED tokens match atomically in encode (like llama.cpp
    parse_special) but their surface text streams verbatim — only
    CONTROL tokens are suppressed from output."""
    from libsplinter_tpu.models.gguf import (TOKTYPE_CONTROL,
                                             TOKTYPE_NORMAL,
                                             TOKTYPE_USER_DEFINED)
    tokens = ["<unk>", "<s>", "</s>", "<CUSTOM>", "▁hi"]
    types = [TOKTYPE_NORMAL, TOKTYPE_CONTROL, TOKTYPE_CONTROL,
             TOKTYPE_USER_DEFINED, TOKTYPE_NORMAL]
    tok = UnigramTokenizer(tokens, None, bos_token_id=1, eos_token_id=2,
                           unknown_token_id=0, token_types=types)
    ids = tok.encode("<CUSTOM>hi", add_bos=False)
    assert [tokens[i] for i in ids] == ["<CUSTOM>", "▁hi"]
    assert tok.token_to_piece(3) == b"<CUSTOM>"     # streams verbatim
    assert tok.token_to_piece(1) == b""             # control suppressed
    assert tok.decode(ids) == "<CUSTOM> hi"


# ------------------------------------------------- MoE (Mixtral family)

def test_moe_decoder_gguf_round_trip(tmp_path):
    """Mixtral-style checkpoint: stacked blk.N.ffn_{gate,up,down}_exps
    + ffn_gate_inp router + llama.expert_count metadata must cold-load
    into the MoE family (config resolution AND tree mapping) and
    generate identically to the in-memory params."""
    from libsplinter_tpu.models.decoder import init_cache
    from libsplinter_tpu.models.moe import (MoeDecoder, MoeDecoderConfig,
                                            moe_completion_model)

    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32)
    params = MoeDecoder(cfg).init(jax.random.PRNGKey(5),
                                  jnp.zeros((1, 8), jnp.int32),
                                  init_cache(cfg, 1), jnp.int32(0))
    p = jax.tree.map(lambda x: np.asarray(x, np.float32),
                     params["params"])
    t = {"token_embd.weight": (p["tok_emb"]["embedding"], GGML_F32),
         "output_norm.weight": (p["ln_out"]["scale"], GGML_F32),
         "output.weight": (p["lm_head"]["kernel"].T.copy(), GGML_F32)}
    for i in range(cfg.layers):
        lp = p[f"layer_{i}"]
        b = f"blk.{i}"
        t[f"{b}.attn_norm.weight"] = (lp["ln_attn"]["scale"], GGML_F32)
        t[f"{b}.ffn_norm.weight"] = (lp["ln_mlp"]["scale"], GGML_F32)
        for src, dst in (("q", "attn_q"), ("k", "attn_k"),
                         ("v", "attn_v"), ("out", "attn_output")):
            t[f"{b}.{dst}.weight"] = (
                lp["attn"][src]["kernel"].T.copy(), GGML_F32)
        moe = lp["moe"]
        t[f"{b}.ffn_gate_inp.weight"] = (
            moe["router"]["kernel"].T.copy(), GGML_F32)
        # llama.cpp stacks experts (E, out, in) in the numpy view
        for src, dst in (("gate_experts", "ffn_gate_exps"),
                         ("up_experts", "ffn_up_exps"),
                         ("down_experts", "ffn_down_exps")):
            t[f"{b}.{dst}.weight"] = (
                np.ascontiguousarray(moe[src].transpose(0, 2, 1)),
                GGML_F32)
    path = tmp_path / "moe.gguf"
    vocab = [f"<t{i}>" for i in range(cfg.vocab_size)]
    write_gguf(path, t, [
        kv_str("general.architecture", "llama"),
        kv_u32("llama.embedding_length", cfg.hidden),
        kv_u32("llama.block_count", cfg.layers),
        kv_u32("llama.attention.head_count", cfg.heads),
        kv_u32("llama.attention.head_count_kv", cfg.kv_heads),
        kv_u32("llama.feed_forward_length", cfg.mlp_dim),
        kv_u32("llama.context_length", cfg.max_len),
        kv_u32("llama.expert_count", cfg.n_experts),
        kv_u32("llama.expert_used_count", cfg.top_k),
        kv_str_array("tokenizer.ggml.tokens", vocab),
    ])

    # config resolves to the MoE family from the metadata alone
    from libsplinter_tpu.models.gguf import decoder_config_from_gguf
    got_cfg = decoder_config_from_gguf(str(path), dtype=jnp.float32)
    assert isinstance(got_cfg, MoeDecoderConfig)
    assert got_cfg.n_experts == cfg.n_experts
    assert got_cfg.top_k == cfg.top_k
    assert got_cfg.hidden == cfg.hidden

    # tree round-trips exactly
    loaded = load_decoder_params(str(path), cfg)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [q for q, _ in flat_a] == [q for q, _ in flat_b]
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   err_msg=str(pa))

    # cold generation == in-memory generation
    a = moe_completion_model(cfg, params=params, buckets=(16,), temp=0.0)
    b = moe_completion_model(got_cfg, weights=str(path), buckets=(16,),
                             temp=0.0)
    prompt = np.array([4, 2, 7], np.int32)
    want = list(a.generate_tokens(prompt, 6, chunk=3))
    a.reset()
    got = list(b.generate_tokens(prompt, 6, chunk=3))
    b.reset()
    assert got == want
