"""Blockwise Pallas attention (ops/flash_attention.py): the kernel
(interpret mode on CPU) must match the naive masked-softmax math the
encoder otherwise runs, across shapes, masks, and padding."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.ops.flash_attention import (_mha_jnp,
                                                 flash_attention)


def _rand(shape, seed=0):
    return np.random.default_rng(seed).normal(
        0, 1, shape).astype(np.float32)


@pytest.mark.parametrize("B,S,H,D,bq", [
    (2, 64, 4, 16, 32),      # multi-block
    (1, 128, 2, 8, 128),     # single block
    (3, 48, 1, 32, 32),      # S not a multiple of block_q: padded
])
def test_kernel_matches_naive(B, S, H, D, bq):
    q, k, v = (_rand((B, S, H, D), s) for s in (1, 2, 3))
    lens = np.random.default_rng(4).integers(1, S + 1, B)
    mask = np.arange(S)[None, :] < lens[:, None]
    got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          jnp.asarray(mask), block_q=bq, interpret=True)
    want = _mha_jnp(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                    jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_row_is_finite():
    """A fully padded batch row (mask all False) must produce finite
    output (uniform softmax), matching the naive path's -1e9 bias
    behavior — pooling excludes the row anyway."""
    B, S, H, D = 2, 32, 2, 8
    q, k, v = (jnp.asarray(_rand((B, S, H, D), s)) for s in (1, 2, 3))
    mask = jnp.asarray(np.array([[True] * S, [False] * S]))
    out = flash_attention(q, k, v, mask, block_q=16, interpret=True)
    assert np.isfinite(np.asarray(out)).all()


def test_padded_keys_do_not_leak():
    """Scores behind the mask must not influence output: growing the
    padded tail with garbage leaves valid rows unchanged."""
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (_rand((B, S, H, D), s) for s in (1, 2, 3))
    valid = 20
    mask = np.arange(S)[None, :] < valid
    a = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        jnp.asarray(mask), block_q=16, interpret=True)
    k2, v2 = k.copy(), v.copy()
    k2[:, valid:] = 999.0
    v2[:, valid:] = -999.0
    b = flash_attention(jnp.asarray(q), jnp.asarray(k2),
                        jnp.asarray(v2), jnp.asarray(mask),
                        block_q=16, interpret=True)
    np.testing.assert_allclose(np.asarray(a)[:, :valid],
                               np.asarray(b)[:, :valid],
                               rtol=1e-6, atol=1e-6)


def test_flash_gradients_match_naive():
    """Training through the kernel: jax.grad over the Pallas forward
    (custom VJP recomputes the backward via the jnp reference) equals
    jax.grad through the naive math."""
    B, S, H, D = 2, 32, 2, 8
    q, k, v = (jnp.asarray(_rand((B, S, H, D), s)) for s in (1, 2, 3))
    mask = jnp.asarray(np.arange(S)[None, :] < np.array([[S], [20]])
                       .reshape(2, 1))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask, block_q=16,
                                       interpret=True) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(_mha_jnp(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("S,bq,lens", [
    (48, 32, (48, 20, 1)),    # S not a block multiple: padded backward
    (32, 16, (32, 0, 7)),     # one fully-masked row in the batch
    (48, 32, (48, 20, 0)),    # BOTH: padding + a fully-masked row
])
def test_flash_gradients_padded_and_masked(S, bq, lens):
    """Gradient parity under the module's contract: fully-masked rows
    are pooling-excluded don't-cares, so the loss (like the encoder's
    pool_normalize) multiplies outputs by row validity — their
    cotangents are zero and the padded-uniform fallback can't leak."""
    B, H, D = 3, 2, 8
    q, k, v = (jnp.asarray(_rand((B, S, H, D), s)) for s in (4, 5, 6))
    mask = jnp.asarray(np.arange(S)[None, :] <
                       np.asarray(lens).reshape(B, 1))
    roww = mask.any(axis=1).astype(jnp.float32)[:, None, None, None]

    def lf(q, k, v):
        return jnp.sum((flash_attention(q, k, v, mask, block_q=bq,
                                        interpret=True) * roww) ** 2)

    def ln(q, k, v):
        return jnp.sum((_mha_jnp(q, k, v, mask) * roww) ** 2)

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(ln, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_encoder_flash_path_matches_naive(monkeypatch):
    """Encoder-level: the same params produce (near-)identical pooled
    embeddings whether attention runs naive or through the ACTUAL
    Pallas kernel — on CPU flash_attention would silently fall back to
    jnp, so the test forces interpret mode through the encoder's own
    call site (covering the transpose/mask/padding plumbing)."""
    import functools

    import libsplinter_tpu.ops.flash_attention as fa
    from libsplinter_tpu.models import EmbeddingModel, EncoderConfig

    monkeypatch.setattr(
        fa, "flash_attention",
        functools.partial(fa.flash_attention, interpret=True))

    base = EncoderConfig.tiny(dtype=jnp.float32)          # naive (S<512)
    flash = EncoderConfig.tiny(dtype=jnp.float32, flash_min_seq=16)
    m_base = EmbeddingModel(base, buckets=(32,), seed=11)
    m_flash = EmbeddingModel(flash, buckets=(32,), seed=11,
                             params=m_base.params)
    ids = np.random.default_rng(5).integers(
        0, base.vocab_size, (4, 32)).astype(np.int32)
    lens = np.array([32, 7, 19, 1], np.int32)
    a = m_base.encode_ids(ids, lens)
    b = m_flash.encode_ids(ids, lens)
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
