"""The block-paged continuous-batching lane (completer.run_continuous
over PagedKVCache): token-exact paged-vs-dense serving, the
no-shared-window joiner guarantee, pool backpressure, page-leak
freedom across request lifecycles, heartbeat gauges, and speculative
demotion.  `make decode-check` runs this file +
tests/test_paged_attention.py.
"""
from __future__ import annotations

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig


def _mkstore(tmp_path, tag, **kw):
    name = f"/spt-{tag}-{tmp_path.name}"
    Store.unlink(name)
    kw.setdefault("nslots", 128)
    kw.setdefault("max_val", 4096)
    kw.setdefault("vec_dim", 8)
    return name, Store.create(name, **kw)


def _submit(st, key, prompt):
    st.set(key, prompt)
    st.label_or(key, P.LBL_INFER_REQ)
    st.bump(key)


def _await_ready(st, keys, timeout=75):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(st.labels(k) & P.LBL_READY for k in keys):
            return True
        time.sleep(0.05)
    return False


def _run_bg(comp, stop_after=90.0):
    th = threading.Thread(
        target=comp.run_continuous,
        kwargs=dict(idle_timeout_ms=20, stop_after=stop_after),
        daemon=True)
    th.start()
    time.sleep(0.2)
    return th


def test_paged_continuous_token_exact_vs_dense(tmp_path):
    """Greedy completions must be byte-identical whether the keys
    were served through the dense batched drain or the paged
    continuous lane — the paged-vs-dense token-exactness bar at a
    fixed weight seed (dense == serial is already pinned by
    tests/test_batch_decode.py)."""
    out: dict[str, bytes] = {}
    model = CompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), buckets=(32,),
        temp=0.0, seed=1)
    for tag in ("dense", "paged"):
        name, st = _mkstore(tmp_path, f"pvd-{tag}")
        try:
            comp = Completer(st, model=model, max_new_tokens=10,
                             flush_tokens=4, template="none",
                             batch_cap=4, page_size=16)
            comp.attach()
            for i in range(3):
                _submit(st, f"q/{i}", f"say {i} things")
            if tag == "paged":
                th = _run_bg(comp)
                assert _await_ready(st, [f"q/{i}" for i in range(3)])
                comp.stop()
                th.join(timeout=5)
            else:
                assert comp.run_once() == 3
            out[tag] = b"|".join(
                st.get(f"q/{i}").rstrip(b"\0") for i in range(3))
        finally:
            st.close()
            Store.unlink(name)
    assert out["dense"] == out["paged"]


@pytest.mark.slow
def test_paged_joiner_exceeding_dense_window_untruncated(tmp_path):
    """THE no-shared-window regression test: while a short row is
    mid-decode, a joiner arrives whose prompt is longer than the
    dense batch's remaining window would have allowed (dense
    join_budget would defer or clip it).  Paged serving admits it
    immediately, keeps the FULL prompt, and its completion is
    byte-identical to serving it alone."""
    model = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32,
                                               max_len=128),
                            buckets=(16, 64), temp=0.0, seed=1)
    # 160 byte tokens: far past the dense live batch's join_budget
    # (16 at pos=16), inside the paged lane's own per-row budget
    long_prompt = ("tok " * 40).encode()

    # ground truth: the long prompt served ALONE through the SAME
    # paged lane (identical context budget), nobody else in the batch
    name, st = _mkstore(tmp_path, "alone")
    try:
        comp = Completer(st, model=model, max_new_tokens=30,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        th = _run_bg(comp)
        _submit(st, "long", long_prompt)
        assert _await_ready(st, ["long"]), comp.stats
        comp.stop()
        th.join(timeout=5)
        alone = st.get("long").rstrip(b"\0")
    finally:
        st.close()
        Store.unlink(name)

    name, st = _mkstore(tmp_path, "joined")
    try:
        comp = Completer(st, model=model, max_new_tokens=30,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        th = _run_bg(comp, stop_after=120.0)
        _submit(st, "short", b"hi")
        time.sleep(0.8)                # batch live, short mid-decode
        _submit(st, "long", long_prompt)
        assert _await_ready(st, ["short", "long"], timeout=100), \
            comp.stats
        comp.stop()
        th.join(timeout=5)
        val = st.get("long").rstrip(b"\0")
        assert val.startswith(long_prompt.rstrip()), "prompt clipped"
        assert val == alone, \
            "joiner's completion differs from serving it alone"
        assert st.labels("short") & P.LBL_READY
    finally:
        st.close()
        Store.unlink(name)


def test_paged_pool_backpressure_and_recovery(tmp_path):
    """A pool too small for two concurrent worst-case rows admits one
    request, backpressures the second (it STAYS WAITING, untouched),
    and serves it after the first finishes — join_backpressure counts
    the deferral and no pages leak."""
    name, st = _mkstore(tmp_path, "bp")
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128,
                                                   dtype=jnp.float32),
                                buckets=(16, 32), temp=0.0)
        # 8 pages of 16 = one full window: the second worst-case
        # reservation (prompt + max_new) cannot fit while the first
        # row is live
        comp = Completer(st, model=model, max_new_tokens=100,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16, pool_pages=8)
        comp.attach()
        th = _run_bg(comp, stop_after=120.0)
        _submit(st, "first", b"aaaa bbbb cccc dddd")
        _submit(st, "second", b"eeee ffff gggg hhhh")
        assert _await_ready(st, ["first", "second"], timeout=100), \
            comp.stats
        comp.stop()
        th.join(timeout=5)
        assert comp.stats.completions == 2
        assert comp.stats.join_backpressure > 0, comp.stats
        assert comp._paged_cache.used_pages == 0, "pages leaked"
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_paged_lifecycle_frees_pages_and_counts(tmp_path):
    """Staggered arrivals across several chunks: every key gets the
    full label protocol, and after the drain the pool is empty (every
    finished row returned all its pages).  Slow tier: the fast sweep
    covers the same protocol via tests/test_continuous.py and the
    leak check via the backpressure test."""
    name, st = _mkstore(tmp_path, "life")
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=24,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=16)
        comp.attach()
        th = _run_bg(comp)
        for i in range(2):
            _submit(st, f"w1/{i}", f"first wave {i}")
        time.sleep(1.0)
        for i in range(3):
            _submit(st, f"w2/{i}", f"second wave {i}")
        keys = [f"w1/{i}" for i in range(2)] + \
            [f"w2/{i}" for i in range(3)]
        assert _await_ready(st, keys), comp.stats
        comp.stop()
        th.join(timeout=5)
        for k in keys:
            labels = st.labels(k)
            assert labels & P.LBL_READY, (k, comp.stats)
            assert not labels & (P.LBL_INFER_REQ | P.LBL_SERVICING), k
            assert len(st.get(k).rstrip(b"\0")) > len(k) + 8
        assert comp.stats.completions == 5
        assert comp._paged_cache.used_pages == 0, "pages leaked"
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_paged_heartbeat_pool_gauges(tmp_path):
    """The completer heartbeat carries the paged-pool gauges
    (pages_free / pages_used -> sptpu_completer_pages_{free,used})
    once the continuous lane has a pool.  Slow tier: warmup_paged
    dominates the runtime and the gauges ride every backpressure /
    churn assertion too (tier-1 870 s budget)."""
    name, st = _mkstore(tmp_path, "hb")
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16,), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        comp.warmup_paged()            # creates the pool
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert snap["pages_used"] == 0
        assert snap["pages_free"] == comp._paged_cache.free_pages
        assert "join_backpressure" in snap
        assert "live_tokens" in snap
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_paged_continuous_traces_requests(tmp_path, monkeypatch):
    """Satellite: the continuous lane stamps CONT_INFER_STAGES spans
    and records client-stamped (LBL_TRACED) requests in the flight
    recorder — `spt trace tail` works on the batched lane.  Slow
    tier: tier-1 870 s budget (`make check`'s full sweep runs it)."""
    from libsplinter_tpu.engine import completer as cmod

    monkeypatch.setattr(cmod.tracer, "enabled", True)
    cmod.tracer.reset()
    name, st = _mkstore(tmp_path, "trace")
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=12,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        st.set("traced", b"tell me a story")
        st.label_or("traced", P.LBL_INFER_REQ)
        tid = P.stamp_trace(st, "traced")
        assert tid is not None
        st.bump("traced")
        th = _run_bg(comp)
        assert _await_ready(st, ["traced"]), comp.stats
        comp.stop()
        th.join(timeout=5)
        recs = comp.recorder.tail(8)
        assert recs, "traced request missing from the flight recorder"
        rec = recs[-1]
        assert rec["id"] == tid and rec["key"] == "traced"
        stages = {name for name, _ in rec["events"]}
        assert "join" in stages and "decode" in stages, rec
        assert stages <= set(P.CONT_INFER_STAGES), rec
        # the span histograms publish under the infer.* prefix so the
        # heartbeat quantiles + `spt metrics` pick them up
        snap = cmod.tracer.snapshot()
        assert "infer.join" in snap and "infer.decode" in snap
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_spec_acceptance_heartbeat_and_demotion(tmp_path):
    """Satellite: a speculative model with hopeless acceptance
    publishes sptpu_completer_spec_acceptance and is demoted to its
    target below --spec-min-acceptance; serving continues.  Slow
    tier for the 870 s tier-1 budget — `make decode-check` runs the
    whole file (no slow filter), so the gate keeps this test."""
    from libsplinter_tpu.models import SpeculativeCompletionModel

    name, st = _mkstore(tmp_path, "spec")
    try:
        # disjoint seeds: the draft proposes junk the target rejects
        t = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                            buckets=(16,), temp=0.0, seed=2)
        d = CompletionModel(
            DecoderConfig.tiny(dtype=jnp.float32, layers=1),
            buckets=(16,), temp=0.0, seed=99)
        spec = SpeculativeCompletionModel(t, d, gamma=4)
        comp = Completer(st, model=spec, max_new_tokens=40,
                         flush_tokens=4, template="none", batch_cap=1,
                         spec_min_acceptance=0.95)
        comp.attach()
        _submit(st, "q1", b"first question")
        assert comp.run_once() == 1
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        assert "spec_acceptance" in snap
        assert snap["spec_acceptance"] < 0.95
        assert comp.stats.spec_demotions == 1, comp.stats
        assert comp._model is t, "completer still speculative"
        # plain decode keeps serving after the demotion
        _submit(st, "q2", b"second question")
        assert comp.run_once() == 1
        assert st.labels("q2") & P.LBL_READY
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_spec_demotion_respects_floor_zero(tmp_path):
    """--spec-min-acceptance 0 disables the demotion entirely.  Slow
    tier for the 870 s tier-1 budget (`make decode-check` and `make
    check` run it)."""
    from libsplinter_tpu.models import SpeculativeCompletionModel

    name, st = _mkstore(tmp_path, "spec0")
    try:
        t = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                            buckets=(16,), temp=0.0, seed=2)
        d = CompletionModel(
            DecoderConfig.tiny(dtype=jnp.float32, layers=1),
            buckets=(16,), temp=0.0, seed=99)
        spec = SpeculativeCompletionModel(t, d, gamma=4)
        comp = Completer(st, model=spec, max_new_tokens=40,
                         flush_tokens=4, template="none", batch_cap=1,
                         spec_min_acceptance=0.0)
        comp.attach()
        _submit(st, "q", b"a question")
        assert comp.run_once() == 1
        assert comp.stats.spec_demotions == 0
        assert comp._model is spec
    finally:
        st.close()
        Store.unlink(name)


@pytest.mark.slow
def test_paged_continuous_churn_no_leak(tmp_path):
    """Heavy tier: three waves of staggered joins/finishes through a
    deliberately tight pool — every request completes, backpressure
    engages, and the pool ends empty."""
    name, st = _mkstore(tmp_path, "churn", nslots=256)
    try:
        model = CompletionModel(DecoderConfig.tiny(max_len=128),
                                buckets=(16, 32), temp=0.0)
        comp = Completer(st, model=model, max_new_tokens=20,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=16, pool_pages=16)
        comp.attach()
        th = _run_bg(comp, stop_after=300.0)
        keys = []
        for wave in range(3):
            for i in range(5):
                k = f"c/{wave}/{i}"
                keys.append(k)
                _submit(st, k, f"wave {wave} question {i} ")
            time.sleep(0.5)
        assert _await_ready(st, keys, timeout=240), comp.stats
        comp.stop()
        th.join(timeout=5)
        assert comp.stats.completions == len(keys)
        assert comp._paged_cache.used_pages == 0, "pages leaked"
    finally:
        st.close()
        Store.unlink(name)
