"""Encoder model: shapes, determinism, masking invariance, bucketing,
tokenizer behavior."""
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models import (EmbeddingModel, EncoderConfig,
                                    HashTokenizer, batch_encode,
                                    default_tokenizer)
from libsplinter_tpu.models.tokenizer import WordPieceTokenizer, basic_split


@pytest.fixture(scope="module")
def model():
    cfg = EncoderConfig.tiny(out_dim=32)
    return EmbeddingModel(cfg, buckets=(16, 32, 64))


def test_encode_shape_and_norm(model):
    ids = np.random.default_rng(0).integers(0, 1024, (4, 16)).astype(np.int32)
    lens = np.array([16, 10, 5, 1], dtype=np.int32)
    out = model.encode_ids(ids, lens)
    assert out.shape == (4, 32)
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, atol=1e-4)


def test_encode_deterministic(model):
    ids = np.ones((2, 16), np.int32)
    lens = np.array([16, 16], np.int32)
    a = model.encode_ids(ids, lens)
    b = model.encode_ids(ids, lens)
    np.testing.assert_array_equal(a, b)


def test_padding_invariance(model):
    """Padding tokens beyond the valid length must not change the vector."""
    rng = np.random.default_rng(1)
    base = rng.integers(4, 1024, 10).astype(np.int32)
    a = np.zeros((1, 16), np.int32); a[0, :10] = base
    b = np.zeros((1, 32), np.int32); b[0, :10] = base
    b[0, 10:] = 999  # garbage in the padded tail
    va = model.encode_ids(a, np.array([10], np.int32))
    vb = model.encode_ids(b, np.array([10], np.int32))
    np.testing.assert_allclose(va, vb, atol=2e-2)  # bf16 tolerance


def test_bucket_for(model):
    assert model.bucket_for(3) == 16
    assert model.bucket_for(16) == 16
    assert model.bucket_for(17) == 32
    # the context window itself is always the last bucket: texts between
    # the configured buckets and the window must not silently truncate
    assert model.buckets[-1] == model.cfg.max_len == 128
    assert model.bucket_for(65) == 128
    assert model.bucket_for(999) == 128  # beyond window: clamps to it


@pytest.mark.parametrize("wire,bytes_,tol", [
    ("f16", 2, 2e-3),       # 2^-10 ulps in [-1, 1]
    ("bf16", 2, 1.6e-2),    # 2^-7
    ("int8", 1, 5e-3),      # half-step of the fixed x127 scale
])
def test_fetch_dtype_wire(model, wire, bytes_, tol):
    """Narrow wire fetch: caller still gets f32, values within the
    wire format's quantization of the f32 reference (unit vectors, so
    absolute tolerance ~= the format's step)."""
    cfg = EncoderConfig.tiny(out_dim=32)
    m2 = EmbeddingModel(cfg, buckets=(16, 32, 64), fetch_dtype=wire)
    ids = np.random.default_rng(3).integers(0, 1024, (4, 16)) \
        .astype(np.int32)
    lens = np.array([16, 10, 5, 1], np.int32)
    ref = model.encode_ids(ids, lens)
    got = m2.encode_ids(ids, lens)
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, ref, atol=tol)
    # the pending result really is this narrow on the wire
    pend = m2.encode_ids_async(ids, lens)
    assert jnp.asarray(pend._out).dtype.itemsize == bytes_
    assert pend.materialize().dtype == np.float32
    # retrieval sanity: each row's nearest neighbour among the f32
    # reference vectors is itself
    sims = got @ ref.T
    assert (np.argmax(sims, axis=1) == np.arange(4)).all()


def test_fetch_dtype_rejects_unknown():
    cfg = EncoderConfig.tiny(out_dim=32)
    with pytest.raises(ValueError):
        EmbeddingModel(cfg, buckets=(16,), fetch_dtype="f8")


def test_bert_variant_runs():
    cfg = EncoderConfig.tiny(variant="bert", out_dim=16)
    m = EmbeddingModel(cfg, buckets=(16,))
    out = m.encode_ids(np.ones((1, 16), np.int32),
                       np.array([8], np.int32))
    assert out.shape == (1, 16)


def test_basic_split():
    assert basic_split("Hello, world!") == ["hello", ",", "world", "!"]
    assert basic_split("a  b\tc\n") == ["a", "b", "c"]


def test_hash_tokenizer_deterministic():
    t = HashTokenizer(1024)
    a = t.encode("the quick brown fox")
    b = t.encode("the quick brown fox")
    assert a == b
    assert a[0] == t.cls_id and a[-1] == t.sep_id
    assert all(4 <= i < 1024 for i in a[1:-1])


def test_hash_tokenizer_truncation():
    t = HashTokenizer(1024)
    ids = t.encode("w " * 100, max_len=16)
    assert len(ids) == 16
    assert ids[-1] == t.sep_id


def test_wordpiece(tmp_path):
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "un", "##aff", "##able", "hello", "world", ","]
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(vocab) + "\n")
    t = WordPieceTokenizer(p)
    ids = t.encode("unaffable hello, world")
    toks = [vocab[i] for i in ids]
    assert toks == ["[CLS]", "un", "##aff", "##able", "hello", ",",
                    "world", "[SEP]"]
    assert t.encode("xyzzy")[1] == t.unk_id


def test_batch_encode_padding():
    t = HashTokenizer(1024)
    ids, lens = batch_encode(t, ["one two", "a b c d e"], bucket=16)
    assert ids.shape == (2, 16)
    assert lens[0] == 4 and lens[1] == 7  # CLS + words + SEP
    assert (ids[0, lens[0]:] == t.pad_id).all()


def test_default_tokenizer_falls_back():
    t = default_tokenizer(2048)
    assert t.encode("anything")  # runs regardless of vocab presence


# ------------------------------------------------- safetensors round-trip

def _forward(cfg, params, seed=3):
    import jax
    import numpy as np
    from libsplinter_tpu.models.encoder import Encoder
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), bool)
    return np.asarray(Encoder(cfg).apply(params, ids, mask))


@pytest.mark.parametrize("variant,family", [
    ("nomic", "nomic"),          # fused Wqkv + SwiGLU naming
    ("bert", "bert"),            # split q/k/v + classic naming
])
def test_safetensors_round_trip(tmp_path, variant, family):
    import jax
    import numpy as np
    from libsplinter_tpu.models.encoder import (
        Encoder, EncoderConfig, export_safetensors_params,
        load_safetensors_params,
    )
    cfg = EncoderConfig.tiny(variant=variant, dtype=jnp.float32)
    module = Encoder(cfg)
    ids = np.ones((1, 8), np.int32)
    params = module.init(jax.random.PRNGKey(0), ids, np.ones((1, 8), bool))

    path = str(tmp_path / "ckpt.safetensors")
    export_safetensors_params(params, cfg, path, family=family)
    loaded = load_safetensors_params(path, cfg)

    # tree structure identical, every leaf equal
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va, np.float32),
                                   np.asarray(vb, np.float32),
                                   err_msg=str(pa))
    # and the forward pass agrees exactly
    np.testing.assert_allclose(_forward(cfg, params),
                               _forward(cfg, loaded), rtol=1e-6)


def test_safetensors_missing_tensor_is_loud(tmp_path):
    import numpy as np
    from safetensors.numpy import save_file
    from libsplinter_tpu.models.encoder import (
        EncoderConfig, load_safetensors_params,
    )
    cfg = EncoderConfig.tiny()
    save_file({"embeddings.word_embeddings.weight":
               np.zeros((cfg.vocab_size, cfg.hidden), np.float32)},
              str(tmp_path / "partial.safetensors"))
    with pytest.raises(KeyError, match="has none of"):
        load_safetensors_params(str(tmp_path / "partial.safetensors"), cfg)


def test_embedding_model_loads_checkpoint(tmp_path):
    import jax
    import numpy as np
    from libsplinter_tpu.models.encoder import (
        EmbeddingModel, Encoder, EncoderConfig, export_safetensors_params,
    )
    cfg = EncoderConfig.tiny(dtype=jnp.float32)
    params = Encoder(cfg).init(jax.random.PRNGKey(7), np.ones((1, 8), np.int32),
                               np.ones((1, 8), bool))
    path = str(tmp_path / "m.safetensors")
    export_safetensors_params(params, cfg, path)
    m = EmbeddingModel(cfg, weights=path)
    ids = np.ones((2, 16), np.int32)
    lens = np.full((2,), 16, np.int32)
    out = m.encode_ids(ids, lens)
    # matryoshka truncation clamps to hidden for the tiny config
    assert out.shape == (2, min(cfg.out_dim, cfg.hidden))
    assert np.isfinite(out).all()
