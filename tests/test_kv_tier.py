"""Tiered KV with warm restarts (ISSUE 19 / ROADMAP item 3): the
host-DRAM spill tier under the radix prefix cache
(engine/kv_tier.HostTier + PrefixCache demote/readmit) and the
file-backed persistent warm layer (kv_tier.TierPersist) that lets a
supervised restart or scale-up replica attach WARM.

Covers: HostTier LRU/capacity mechanics, the write-through ->
demote -> readmit cycle pinned byte-exact against a cold prefill
(plus the page-accounting invariants at every step), the capacity-
overflow prune cascade, tier-on vs tier-off byte-identical continuous
serving, the two-generation warm restart (snapshot -> restore ->
readmit, heartbeat tier_* gauges), torn-snapshot recovery at every
byte-boundary class (header, mid-page, missing trailer, missing
record, geometry) with the typed degradation reason surfaced in the
heartbeat, and the three supervised chaos drills at the tier.spill /
tier.readmit / tier.restore fault sites.  `make warm-check` runs the
end-to-end restart gate (scripts/warm_restart_check.py) on top.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.kv_tier import (INDEX_KEY, HostTier,
                                            TierPersist, _entry_key,
                                            _page_key, tier_geometry)
from libsplinter_tpu.models.decoder import CompletionModel
from libsplinter_tpu.utils import faults
from test_prefix_cache import (CFG, HOT_PROMPT, PAGE, _attach_pc,
                               _await_ready, _check_invariants,
                               _mkstore, _submit)


@pytest.fixture(scope="module")
def model():
    return CompletionModel(CFG, buckets=(32, 64), temp=0.0, seed=1,
                           suffix_buckets=(8, 16))


def _drill_model():
    """The exact geometry tests/chaos_child.py `tier_completer` runs,
    so pre-seeded snapshots and greedy outputs line up across the
    parent/child process boundary."""
    return CompletionModel(CFG, buckets=(32,), temp=0.0, seed=1,
                           suffix_buckets=(8,))


# 24 tokens = 3 exact pages at PAGE=8
PROMPT24 = (np.arange(1, 25, dtype=np.int32) % 200) + 1


def _bind(model, cache, pc, capacity=32):
    tier = HostTier(capacity)
    pc.bind_tier(
        tier,
        export_page=lambda bid: model.export_page_bytes(cache, bid),
        import_page=lambda bid, buf, sbuf: model.import_page_bytes(
            cache, bid, buf, sbuf))
    return tier


def _seed_snapshot(model, pname):
    """One 3-page chain, write-through shadowed, checkpointed into a
    fresh persistent segment — the donor every torn-snapshot test
    mangles.  Uses the SAME (capacity, max_len) the completer passes
    so the segment is kept, not recreated, across a lane attach."""
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    tier = _bind(model, cache, pc)
    model.paged_prefill_row(cache, PROMPT24, 0)
    assert pc.insert(PROMPT24, cache, 0, tenant=3) == 3
    geom = tier_geometry(model, cache)
    persist = TierPersist(pname, capacity_pages=32,
                          max_len=model.cfg.max_len,
                          page_bytes=geom["page_bytes"])
    assert persist.save(pc, tier, geom)
    return persist, geom


def _cold_target(model):
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    tier = _bind(model, cache, pc)
    return cache, pc, tier


# ------------------------------------------------------------- host tier

def test_host_tier_lru_capacity_and_dirty():
    t = HostTier(2)
    assert len(t) == 0 and not t.dirty
    assert t.put("a", b"AA", None) == []
    assert t.put("b", b"BB", b"s") == []
    assert t.dirty and t.bytes_held() == 5
    t.dirty = False
    # has/peek are recency-pure (a denied lookup must not refresh)
    assert t.has("a") and t.peek("a") == (b"AA", None)
    assert not t.dirty
    assert t.get("a") == (b"AA", None)      # LRU touch: "a" newest
    assert t.put("c", b"CC", None) == ["b"]  # so "b" is the victim
    assert t.capacity_drops == 1 and t.dirty
    t.drop("b")                              # already gone: no-op
    t.drop("a")
    assert not t.has("a") and len(t) == 1
    t.clear()
    assert len(t) == 0 and t.bytes_held() == 0


# -------------------------------------------- spill / demote / readmit

def test_write_through_demote_readmit_byte_exact(model):
    """The tier cycle end to end at the cache level: insert takes the
    host shadow immediately (write-through), eviction DEMOTES (node
    survives, page returns to the pool), a later hit readmits with a
    device_put — and the decode over readmitted pages is byte-
    identical to a cold prefill.  Page-accounting invariants hold at
    every step."""
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    tier = _bind(model, cache, pc)
    model.paged_prefill_row(cache, PROMPT24, 0)
    assert pc.insert(PROMPT24, cache, 0, tenant=1) == 3
    assert tier.spills == 3 and len(tier) == 3   # write-through
    _check_invariants(cache, pc)
    cache.free_row(0)
    _check_invariants(cache, pc)
    free_before = len(cache._free)
    assert pc.reclaim(3) == 3
    assert tier.demotions == 3 and pc.demoted_pages() == 3
    assert pc.shared_pages() == 0
    assert len(cache._free) == free_before + 3
    _check_invariants(cache, pc)
    bids, match, nodes = pc.lookup_tiered(PROMPT24)
    assert bids == [] and match == 0 and len(nodes) == 3
    # readmit in path order; the refcount-1 return is transferred
    # into the row's block table exactly like the completer does
    got = pc.readmit(nodes, cache)
    assert len(got) == 3 and tier.readmits == 3
    assert pc.demoted_pages() == 0
    for b in got:
        cache._decref(b)
    _check_invariants(cache, pc)
    cache.map_shared(1, got)
    cache.lengths[1] = len(PROMPT24) - 1
    assert cache.ensure(1, 32)
    _check_invariants(cache, pc)
    toks = np.full((4,), -1, np.int32)
    toks[1] = int(PROMPT24[-1])              # the replay token
    out = model.paged_decode_chunk(cache, toks, 7)
    readmitted = [int(x) for x in out[1]]
    # baseline: cold prefill of the same prompt in a fresh pool
    cache_b = model.init_paged(4, page=PAGE)
    lb = model.paged_prefill_row(cache_b, PROMPT24, 0)
    tb = np.full((4,), -1, np.int32)
    tb[0] = int(np.argmax(lb))
    out_b = model.paged_decode_chunk(cache_b, tb, 7)
    cold = [int(tb[0])] + [int(x) for x in out_b[0][:6]]
    assert readmitted == cold


def test_capacity_drop_prunes_stranded_dram_chain(model):
    """LRU overflow at the host tier: dropping a DRAM-resident node's
    shadow makes it unservable, so the cache prunes it AND its
    subtree (a chain is only servable root-first).  Also covers the
    second-chance spill for a victim whose write-through shadow was
    itself the overflow victim."""
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    tier = _bind(model, cache, pc, capacity=2)
    model.paged_prefill_row(cache, PROMPT24, 0)
    assert pc.insert(PROMPT24, cache, 0) == 3
    # write-through at capacity 2: the chain ROOT's shadow was the
    # LRU victim (root still HBM-resident, so nothing to prune yet)
    assert tier.spills == 3 and tier.capacity_drops == 1
    assert len(tier) == 2
    cache.free_row(0)
    # leaf-first demotion shadows the tail; the root's second-chance
    # spill overflows the DRAM-resident middle node out — pruning it
    # strands its leaf, which is pruned with it
    assert pc.reclaim(3) == 3
    assert tier.spills == 4 and tier.capacity_drops == 2
    assert len(tier) == 1 and pc.demoted_pages() == 1
    _check_invariants(cache, pc)
    bids, match, nodes = pc.lookup_tiered(PROMPT24)
    assert bids == [] and match == 0 and len(nodes) == 1  # root only


# ------------------------------------------------ continuous lane A/B

def test_continuous_byte_identical_tier_on_vs_off(tmp_path, model):
    """Acceptance: greedy decode byte-identical with tiering on vs
    off — the spill tier is pure capacity machinery, never allowed
    to change served bytes."""
    outs = {}
    for tag, pages in (("off", 0), ("on", 32)):
        name, st = _mkstore(tmp_path, f"tier-{tag}")
        try:
            comp = Completer(st, model=model, max_new_tokens=24,
                             flush_tokens=2, template="none",
                             batch_cap=4, page_size=PAGE,
                             kv_tier_pages=pages)
            comp.attach()
            _submit(st, "donor", HOT_PROMPT)
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
                daemon=True)
            th.start()
            assert _await_ready(st, ["donor"])
            _submit(st, "joiner", HOT_PROMPT)
            assert _await_ready(st, ["joiner"])
            comp.stop()
            th.join(timeout=15)
            outs[tag] = (st.get("donor").rstrip(b"\0"),
                         st.get("joiner").rstrip(b"\0"))
            if pages:
                assert comp.kv_tier is not None
                assert comp.kv_tier.spills >= 3  # write-through ran
        finally:
            st.close()
            Store.unlink(name)
    assert outs["on"] == outs["off"]
    assert outs["on"][0] == outs["on"][1]


# ------------------------------------------------------- warm restart

def test_warm_restart_restores_and_readmits(tmp_path, model):
    """Two lane generations over one persistent segment: generation 1
    boots cold (typed missing_record — first boot has no snapshot),
    spills write-through, and its retirement demotes + checkpoints
    the warm set; generation 2 attaches WARM (pages adopted from the
    snapshot), serves the same prompt via readmission — not a
    re-prefill — and every tier_* gauge rides the heartbeat.  Greedy
    bytes identical across the restart."""
    name, st = _mkstore(tmp_path, "tier-warm", nslots=256)
    pname = f"/spt-tierwarm-{tmp_path.name}-kvtier"
    TierPersist.unlink(pname)
    try:
        outs, snaps = {}, {}
        for gen in (1, 2):
            comp = Completer(st, model=model, max_new_tokens=8,
                             flush_tokens=4, template="none",
                             batch_cap=4, page_size=PAGE,
                             kv_tier_pages=32, kv_tier_persist=pname)
            comp.attach()
            key = f"g{gen}"
            _submit(st, key, HOT_PROMPT)
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
                daemon=True)
            th.start()
            assert _await_ready(st, [key])
            comp.publish_stats()
            snaps[gen] = json.loads(
                st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
            comp.stop()
            th.join(timeout=15)
            if comp._tier_store is not None:
                comp._tier_store.close()
            outs[gen] = st.get(key).rstrip(b"\0")
        assert snaps[1]["tier_restored"] == 0
        assert snaps[1]["tier_restore_reason"] == "missing_record"
        assert snaps[1]["tier_spills"] >= 3
        # generation 2: warm attach + readmission, no re-prefill
        assert snaps[2]["tier_restored"] >= 3
        assert snaps[2]["tier_readmits"] >= 3
        assert snaps[2]["prefix_hits"] >= 1
        assert "tier_restore_reason" not in snaps[2]  # "" == warm
        assert snaps[2]["tier_snapshot_epoch"] >= 1
        for field in ("tier_pages", "tier_mb", "tier_demoted",
                      "tier_demotions", "tier_spill_failures",
                      "tier_readmit_failures", "tier_capacity_drops"):
            assert field in snaps[2]
        assert outs[1] == outs[2]
    finally:
        st.close()
        Store.unlink(name)
        TierPersist.unlink(pname)


# ---------------------------------------------------- torn snapshots

def _mangle_missing_record(st, epoch):
    st.unset(INDEX_KEY)


def _mangle_torn_header(st, epoch):
    st.set(INDEX_KEY, '{"v": 1, "epoch": ')


def _mangle_mid_page(st, epoch):
    buf = bytes(st.get(_page_key(epoch, 1)))
    st.set(_page_key(epoch, 1), buf[:len(buf) // 2])


def _mangle_missing_trailer(st, epoch):
    st.unset(_entry_key(epoch, 2))


@pytest.mark.parametrize("mangle,reason", [
    (_mangle_missing_record, "missing_record"),
    (_mangle_torn_header, "torn_header"),
    (_mangle_mid_page, "torn_page"),
    (_mangle_missing_trailer, "torn_page"),
], ids=["missing-record", "torn-header", "mid-page",
        "missing-trailer"])
def test_torn_snapshot_discarded_cold(tmp_path, model, mangle,
                                      reason):
    """Every byte-boundary class of a torn snapshot is detected,
    typed, and DISCARDED — nothing is adopted, the tree and tier
    stay empty (never half-loaded)."""
    pname = f"/spt-tiertorn-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, geom = _seed_snapshot(model, pname)
    try:
        mangle(persist.store, persist.epoch)
        cache2, pc2, tier2 = _cold_target(model)
        assert persist.load(pc2, tier2, geom) == (0, reason)
        assert pc2.demoted_pages() == 0 and len(tier2) == 0
        assert not pc2._children
        _check_invariants(cache2, pc2)
    finally:
        persist.close()
        TierPersist.unlink(pname)


def test_snapshot_geometry_mismatch_cold_then_warm(tmp_path, model):
    """A restored page is raw device bytes: the slightest geometry
    drift refuses the whole snapshot (silent garbage otherwise) —
    and the untouched snapshot still loads warm under the geometry
    it was taken with."""
    pname = f"/spt-tiergeom-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, geom = _seed_snapshot(model, pname)
    try:
        cache2, pc2, tier2 = _cold_target(model)
        bad = dict(geom, page=PAGE * 2)
        assert persist.load(pc2, tier2, bad) == (0,
                                                 "geometry_mismatch")
        assert pc2.demoted_pages() == 0 and len(tier2) == 0
        n, why = persist.load(pc2, tier2, geom)
        assert (n, why) == (3, "")
        assert pc2.demoted_pages() == 3 and len(tier2) == 3
        assert tier2.restored == 3
        _check_invariants(cache2, pc2)
    finally:
        persist.close()
        TierPersist.unlink(pname)


# ------------------------------------------- int4-PACKED shadows (PR 20)

def _packed_target(model, kvd):
    cache = model.init_paged(4, page=PAGE, kv_dtype=kvd)
    pc = _attach_pc(cache)
    tier = _bind(model, cache, pc)
    return cache, pc, tier


def _seed_snapshot_kvd(model, pname, kvd):
    """_seed_snapshot at an explicit kv dtype — the packed donor."""
    cache, pc, tier = _packed_target(model, kvd)
    model.paged_prefill_row(cache, PROMPT24, 0)
    assert pc.insert(PROMPT24, cache, 0, tenant=3) == 3
    geom = tier_geometry(model, cache)
    persist = TierPersist(pname, capacity_pages=32,
                          max_len=model.cfg.max_len,
                          page_bytes=geom["page_bytes"])
    assert persist.save(pc, tier, geom)
    return persist, geom


def test_packed_demote_readmit_decode_parity(model):
    """int4 shadows carry the PACKED bytes verbatim — the demote ->
    readmit cycle at the packed layout decodes byte-identically to a
    cold int4 prefill, and the snapshot geometry halves page_bytes vs
    int8 with a uint8 wire dtype."""
    cache, pc, tier = _packed_target(model, "int4")
    assert cache.packed
    geom = tier_geometry(model, cache)
    i8 = model.init_paged(4, page=PAGE, kv_dtype="int8")
    assert geom["wire_dtype"] == "uint8"
    assert geom["page_bytes"] * 2 == model.page_wire_bytes(i8)
    model.paged_prefill_row(cache, PROMPT24, 0)
    assert pc.insert(PROMPT24, cache, 0, tenant=1) == 3
    assert tier.spills == 3
    _check_invariants(cache, pc)
    cache.free_row(0)
    assert pc.reclaim(3) == 3
    assert tier.demotions == 3 and pc.demoted_pages() == 3
    _check_invariants(cache, pc)
    bids, match, nodes = pc.lookup_tiered(PROMPT24)
    assert bids == [] and match == 0 and len(nodes) == 3
    got = pc.readmit(nodes, cache)
    assert len(got) == 3 and tier.readmits == 3
    for b in got:
        cache._decref(b)
    cache.map_shared(1, got)
    cache.lengths[1] = len(PROMPT24) - 1
    assert cache.ensure(1, 32)
    _check_invariants(cache, pc)
    toks = np.full((4,), -1, np.int32)
    toks[1] = int(PROMPT24[-1])
    out = model.paged_decode_chunk(cache, toks, 7)
    readmitted = [int(x) for x in out[1]]
    cache_b = model.init_paged(4, page=PAGE, kv_dtype="int4")
    lb = model.paged_prefill_row(cache_b, PROMPT24, 0)
    tb = np.full((4,), -1, np.int32)
    tb[0] = int(np.argmax(lb))
    out_b = model.paged_decode_chunk(cache_b, tb, 7)
    cold = [int(tb[0])] + [int(x) for x in out_b[0][:6]]
    assert readmitted == cold


@pytest.mark.parametrize("mangle,reason", [
    (_mangle_missing_record, "missing_record"),
    (_mangle_torn_header, "torn_header"),
    (_mangle_mid_page, "torn_page"),
    (_mangle_missing_trailer, "torn_page"),
], ids=["missing-record", "torn-header", "mid-page",
        "missing-trailer"])
def test_packed_torn_snapshot_taxonomy(tmp_path, model, mangle,
                                       reason):
    """The torn-snapshot byte-boundary taxonomy holds unchanged at
    the PACKED page geometry (half-size pages shift every record
    boundary — the validation must not have byte offsets baked in)."""
    pname = f"/spt-tierp4-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, geom = _seed_snapshot_kvd(model, pname, "int4")
    try:
        mangle(persist.store, persist.epoch)
        cache2, pc2, tier2 = _packed_target(model, "int4")
        assert persist.load(pc2, tier2, geom) == (0, reason)
        assert pc2.demoted_pages() == 0 and len(tier2) == 0
        assert not pc2._children
        _check_invariants(cache2, pc2)
    finally:
        persist.close()
        TierPersist.unlink(pname)


def test_packed_snapshot_refuses_int8_geometry(tmp_path, model):
    """int8 and int4 snapshots are mutually unservable (wire dtype
    AND page_bytes differ): loading either under the other's geometry
    is a typed geometry_mismatch — and the untouched int4 snapshot
    still attaches warm under its own."""
    pname = f"/spt-tierx48-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, geom4 = _seed_snapshot_kvd(model, pname, "int4")
    try:
        c8, pc8, t8 = _packed_target(model, "int8")
        geom8 = tier_geometry(model, c8)
        assert geom8 != geom4
        assert persist.load(pc8, t8, geom8) == (0, "geometry_mismatch")
        assert pc8.demoted_pages() == 0 and len(t8) == 0
        cache2, pc2, tier2 = _packed_target(model, "int4")
        n, why = persist.load(pc2, tier2, geom4)
        assert (n, why) == (3, "")
        assert pc2.demoted_pages() == 3 and tier2.restored == 3
        _check_invariants(cache2, pc2)
    finally:
        persist.close()
        TierPersist.unlink(pname)


def test_restore_raise_falls_back_cold_typed(tmp_path, model):
    """The tier.restore fault site fires AFTER full validation,
    BEFORE adoption: a raise there proves the clean cold fallback
    (empty tree + tier, typed restore_failed) and leaves the
    snapshot itself untouched for the next attach."""
    pname = f"/spt-tierraise-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, geom = _seed_snapshot(model, pname)
    try:
        cache2, pc2, tier2 = _cold_target(model)
        faults.arm("tier.restore:raise@1")
        try:
            assert persist.load(pc2, tier2, geom) == \
                (0, "restore_failed")
        finally:
            faults.disarm()
        assert pc2.demoted_pages() == 0 and len(tier2) == 0
        assert not pc2._children
        # fault cleared: the SAME snapshot attaches warm
        assert persist.load(pc2, tier2, geom) == (3, "")
        assert pc2.demoted_pages() == 3
    finally:
        persist.close()
        TierPersist.unlink(pname)


def test_torn_snapshot_reason_reaches_heartbeat(tmp_path, model):
    """The typed degradation reason is an operator signal: a lane
    that attached cold off a torn snapshot says WHY in its heartbeat
    (tier_restore_reason) — and still serves, spilling fresh."""
    name, st = _mkstore(tmp_path, "tier-torn-hb")
    pname = f"/spt-tiertornhb-{tmp_path.name}"
    TierPersist.unlink(pname)
    persist, _geom = _seed_snapshot(model, pname)
    _mangle_torn_header(persist.store, persist.epoch)
    persist.close()
    try:
        comp = Completer(st, model=model, max_new_tokens=4,
                         flush_tokens=2, template="none",
                         batch_cap=4, page_size=PAGE,
                         kv_tier_pages=32, kv_tier_persist=pname)
        comp.attach()
        _submit(st, "t1", HOT_PROMPT)
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=30.0),
            daemon=True)
        th.start()
        assert _await_ready(st, ["t1"])     # cold service still works
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        comp.stop()
        th.join(timeout=15)
        if comp._tier_store is not None:
            comp._tier_store.close()
        assert snap["tier_restored"] == 0
        assert snap["tier_restore_reason"] == "torn_header"
        assert snap["tier_spills"] >= 3
    finally:
        st.close()
        Store.unlink(name)
        TierPersist.unlink(pname)


# ------------------------------------------------- supervised drills

def _run_drill(st, name, keys, extra_key="c3"):
    """The shared supervised window: spawn the tier_completer chaos
    child under `spt supervise`, await every submitted key, require
    at least one restart, then prove a post-crash round-trip and
    that nothing is stranded claimed."""
    from libsplinter_tpu.engine.supervisor import Supervisor

    child = os.path.join(os.path.dirname(__file__), "chaos_child.py")
    holder: dict = {}

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, child, "tier_completer", name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(name, lanes=("completer",), spawn_fn=spawn,
                     store=st, backoff_base_ms=100,
                     backoff_max_ms=2000, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 240.0})
    t.start()
    try:
        assert _await_ready(st, keys, timeout=180), sup.lanes
        assert sup.lanes["completer"].restarts >= 1
        _submit(st, extra_key, HOT_PROMPT)
        assert _await_ready(st, [extra_key], timeout=120)
        for k in list(keys) + [extra_key]:
            assert not st.labels(k) & (P.LBL_INFER_REQ
                                       | P.LBL_SERVICING)
    finally:
        sup.stop()
        t.join()
        sup.shutdown()


def _seed_warm_generation(st, name, pname, key="w0"):
    """Generation 0, in-process, BEFORE any fault env lands: serve
    the hot prompt once with persistence on; retirement demotes the
    warm set and force-checkpoints it, seeding the snapshot the
    supervised child attaches from.  Returns the greedy bytes."""
    comp = Completer(st, model=_drill_model(), max_new_tokens=8,
                     flush_tokens=4, template="none", batch_cap=4,
                     page_size=PAGE, kv_tier_pages=32,
                     kv_tier_persist=pname)
    comp.attach()
    _submit(st, key, HOT_PROMPT)
    th = threading.Thread(
        target=comp.run_continuous,
        kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
        daemon=True)
    th.start()
    assert _await_ready(st, [key])
    comp.stop()
    th.join(timeout=15)
    assert comp._tier_store is not None
    assert comp._tier_store.epoch >= 1   # the retire checkpoint
    comp._tier_store.close()
    return st.get(key).rstrip(b"\0")


@pytest.mark.chaos
@pytest.mark.slow
def test_supervised_mid_spill_crash_strands_nothing(tmp_path,
                                                    monkeypatch):
    """The tier.spill fault site: the lane dies taking its FIRST
    write-through shadow copy — request claimed, page bytes about to
    leave HBM.  The HBM copy was still authoritative (the fault
    fires before the export), so the restarted lane (fault stripped)
    serves everything cold and re-spills cleanly — zero admitted
    loss."""
    name, st = _mkstore(tmp_path, "tier-chaos-spill", nslots=256)
    pname = f"{name}-kvtier"
    TierPersist.unlink(pname)
    monkeypatch.setenv("SPTPU_FAULT", "tier.spill:crash@1")
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
    try:
        _submit(st, "c1", HOT_PROMPT)
        _submit(st, "c2", HOT_PROMPT)
        _run_drill(st, name, ["c1", "c2"])
    finally:
        st.close()
        Store.unlink(name)
        TierPersist.unlink(pname)


@pytest.mark.chaos
@pytest.mark.slow
def test_supervised_mid_readmit_crash_strands_nothing(tmp_path,
                                                      monkeypatch):
    """The tier.readmit fault site: a warm-attached lane dies between
    a DRAM hit and its device import (fault fires before the page
    alloc).  The host shadow and the persistent snapshot are both
    untouched, so the respawn attaches warm from the SAME snapshot,
    readmits cleanly, and the served bytes match the pre-crash
    generation's — zero admitted loss, no re-prefill."""
    name, st = _mkstore(tmp_path, "tier-chaos-readmit", nslots=256)
    pname = f"{name}-kvtier"
    TierPersist.unlink(pname)
    try:
        warm_out = _seed_warm_generation(st, name, pname)
        monkeypatch.setenv("SPTPU_FAULT", "tier.readmit:crash@1")
        monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
        _submit(st, "c1", HOT_PROMPT)
        _run_drill(st, name, ["c1"])
        assert st.get("c1").rstrip(b"\0") == warm_out
    finally:
        st.close()
        Store.unlink(name)
        TierPersist.unlink(pname)


@pytest.mark.chaos
@pytest.mark.slow
def test_supervised_mid_restore_crash_attaches_warm(tmp_path,
                                                    monkeypatch):
    """The tier.restore fault site: the lane dies INSIDE the warm
    attach — snapshot fully validated, adoption about to start.
    Nothing was mutated yet (validate-everything-first), so the
    supervised respawn (fault stripped) attaches warm from the SAME
    untouched snapshot and serves via readmission — zero admitted
    loss across a crash in the restore path itself."""
    name, st = _mkstore(tmp_path, "tier-chaos-restore", nslots=256)
    pname = f"{name}-kvtier"
    TierPersist.unlink(pname)
    try:
        warm_out = _seed_warm_generation(st, name, pname)
        monkeypatch.setenv("SPTPU_FAULT", "tier.restore:crash@1")
        monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
        _submit(st, "r1", HOT_PROMPT)
        _run_drill(st, name, ["r1"])
        assert st.get("r1").rstrip(b"\0") == warm_out
    finally:
        st.close()
        Store.unlink(name)
        TierPersist.unlink(pname)
