"""K-quant dequantization (Q2_K..Q8_K, Q5_0/Q5_1).

Ground truth here is an independent SCALAR implementation of each ggml
block format (written element-by-element from the block layout, the way
the C reference loops do) — the vectorized production decoders in
models/gguf.py must agree bit-exactly on random block bytes.  llama.cpp
itself is not installable in this image; agreement between two
independently-written decoders over random data is the strongest
offline check available (VERDICT r1 item 4).
"""
from __future__ import annotations

import struct

import numpy as np
import pytest

from libsplinter_tpu.models import gguf as G

rng = np.random.default_rng(7)


def f16(x: float) -> bytes:
    return struct.pack("<e", x)


def rand_scale() -> float:
    return float(rng.uniform(0.001, 0.1))


# ---------------------------------------------------- scalar references

def ref_q5_0(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 32):
        off = b * 22
        d = np.frombuffer(blob, "<f2", 1, off)[0]
        qh = struct.unpack_from("<I", blob, off + 2)[0]
        qs = blob[off + 6: off + 22]
        for j in range(16):
            x0 = (qs[j] & 0x0F) | (((qh >> j) & 1) << 4)
            x1 = (qs[j] >> 4) | (((qh >> (j + 16)) & 1) << 4)
            out[b * 32 + j] = (x0 - 16) * float(d)
            out[b * 32 + j + 16] = (x1 - 16) * float(d)
    return out


def ref_q5_1(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 32):
        off = b * 24
        d = float(np.frombuffer(blob, "<f2", 1, off)[0])
        m = float(np.frombuffer(blob, "<f2", 1, off + 2)[0])
        qh = struct.unpack_from("<I", blob, off + 4)[0]
        qs = blob[off + 8: off + 24]
        for j in range(16):
            x0 = (qs[j] & 0x0F) | (((qh >> j) & 1) << 4)
            x1 = (qs[j] >> 4) | (((qh >> (j + 16)) & 1) << 4)
            out[b * 32 + j] = x0 * d + m
            out[b * 32 + j + 16] = x1 * d + m
    return out


def _scale_min_k4_ref(q: bytes, j: int) -> tuple[int, int]:
    if j < 4:
        return q[j] & 63, q[j + 4] & 63
    return ((q[j + 4] & 0x0F) | ((q[j - 4] >> 6) << 4),
            (q[j + 4] >> 4) | ((q[j] >> 6) << 4))


def ref_q4_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 144
        d = float(np.frombuffer(blob, "<f2", 1, off)[0])
        dmin = float(np.frombuffer(blob, "<f2", 1, off + 2)[0])
        scales = blob[off + 4: off + 16]
        qs = blob[off + 16: off + 144]
        y = b * 256
        is_ = 0
        for j in range(0, 256, 64):
            sc1, m1 = _scale_min_k4_ref(scales, is_)
            sc2, m2 = _scale_min_k4_ref(scales, is_ + 1)
            q = qs[(j // 64) * 32:(j // 64) * 32 + 32]
            for el in range(32):
                out[y] = d * sc1 * (q[el] & 0x0F) - dmin * m1
                y += 1
            for el in range(32):
                out[y] = d * sc2 * (q[el] >> 4) - dmin * m2
                y += 1
            is_ += 2
    return out


def ref_q5_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 176
        d = float(np.frombuffer(blob, "<f2", 1, off)[0])
        dmin = float(np.frombuffer(blob, "<f2", 1, off + 2)[0])
        scales = blob[off + 4: off + 16]
        qh = blob[off + 16: off + 48]
        qs = blob[off + 48: off + 176]
        y = b * 256
        is_ = 0
        u1, u2 = 1, 2
        for j in range(0, 256, 64):
            sc1, m1 = _scale_min_k4_ref(scales, is_)
            sc2, m2 = _scale_min_k4_ref(scales, is_ + 1)
            q = qs[(j // 64) * 32:(j // 64) * 32 + 32]
            for el in range(32):
                hi = 16 if qh[el] & u1 else 0
                out[y] = d * sc1 * ((q[el] & 0x0F) + hi) - dmin * m1
                y += 1
            for el in range(32):
                hi = 16 if qh[el] & u2 else 0
                out[y] = d * sc2 * ((q[el] >> 4) + hi) - dmin * m2
                y += 1
            is_ += 2
            u1 <<= 2
            u2 <<= 2
    return out


def ref_q6_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 210
        ql = blob[off: off + 128]
        qh = blob[off + 128: off + 192]
        sc = struct.unpack_from("<16b", blob, off + 192)
        d = float(np.frombuffer(blob, "<f2", 1, off + 208)[0])
        y = b * 256
        for half in range(2):
            qlh = ql[half * 64: half * 64 + 64]
            qhh = qh[half * 32: half * 32 + 32]
            sch = sc[half * 8: half * 8 + 8]
            for el in range(32):
                is_ = el // 16
                q1 = ((qlh[el] & 0x0F) | (((qhh[el] >> 0) & 3) << 4)) - 32
                q2 = ((qlh[el + 32] & 0x0F) |
                      (((qhh[el] >> 2) & 3) << 4)) - 32
                q3 = ((qlh[el] >> 4) | (((qhh[el] >> 4) & 3) << 4)) - 32
                q4 = ((qlh[el + 32] >> 4) |
                      (((qhh[el] >> 6) & 3) << 4)) - 32
                out[y + el] = d * sch[is_ + 0] * q1
                out[y + el + 32] = d * sch[is_ + 2] * q2
                out[y + el + 64] = d * sch[is_ + 4] * q3
                out[y + el + 96] = d * sch[is_ + 6] * q4
            y += 128
    return out


def ref_q2_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 84
        scales = blob[off: off + 16]
        qs = blob[off + 16: off + 80]
        d = float(np.frombuffer(blob, "<f2", 1, off + 80)[0])
        dmin = float(np.frombuffer(blob, "<f2", 1, off + 82)[0])
        y = b * 256
        is_ = 0
        for half in range(2):
            q = qs[half * 32: half * 32 + 32]
            for j in range(4):
                shift = 2 * j
                sc = scales[is_]
                is_ += 1
                for el in range(16):
                    out[y] = (d * (sc & 0x0F) * ((q[el] >> shift) & 3) -
                              dmin * (sc >> 4))
                    y += 1
                sc = scales[is_]
                is_ += 1
                for el in range(16, 32):
                    out[y] = (d * (sc & 0x0F) * ((q[el] >> shift) & 3) -
                              dmin * (sc >> 4))
                    y += 1
    return out


def ref_q3_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 110
        hmask = blob[off: off + 32]
        qs = blob[off + 32: off + 96]
        raw_sc = blob[off + 96: off + 108]
        d = float(np.frombuffer(blob, "<f2", 1, off + 108)[0])
        a0, a1, t = struct.unpack("<3I", raw_sc)
        k1, k2 = 0x03030303, 0x0F0F0F0F
        words = [
            (a0 & k2) | (((t >> 0) & k1) << 4),
            (a1 & k2) | (((t >> 2) & k1) << 4),
            ((a0 >> 4) & k2) | (((t >> 4) & k1) << 4),
            ((a1 >> 4) & k2) | (((t >> 6) & k1) << 4),
        ]
        sc16 = [x - 32 if x < 128 else x - 288  # int8 view of each byte
                for w in words for x in struct.pack("<I", w)]
        y = b * 256
        is_ = 0
        m = 1
        for half in range(2):
            q = qs[half * 32: half * 32 + 32]
            for j in range(4):
                shift = 2 * j
                for grp, lo in ((0, 0), (1, 16)):
                    dl = d * sc16[is_]
                    is_ += 1
                    for el in range(lo, lo + 16):
                        hi = 0 if hmask[el] & m else 4
                        out[y] = dl * (((q[el] >> shift) & 3) - hi)
                        y += 1
                m <<= 1
    return out


def ref_q8_k(blob: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        off = b * 292
        d = struct.unpack_from("<f", blob, off)[0]
        qs = struct.unpack_from("<256b", blob, off + 4)
        out[b * 256: b * 256 + 256] = np.array(qs, np.float32) * d
    return out


# ------------------------------------------------------- random blocks

def _rand_block_bytes(fmt: str, nblocks: int) -> bytes:
    """Random-but-sane block bytes: random payload bits, bounded f16/f32
    scales (no inf/nan)."""
    out = b""
    for _ in range(nblocks):
        if fmt == "q5_0":
            out += (f16(rand_scale()) +
                    bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        elif fmt == "q5_1":
            out += (f16(rand_scale()) + f16(rand_scale() * 3) +
                    bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        elif fmt == "q4_k":
            out += (f16(rand_scale()) + f16(rand_scale()) +
                    bytes(rng.integers(0, 256, 140, dtype=np.uint8)))
        elif fmt == "q5_k":
            out += (f16(rand_scale()) + f16(rand_scale()) +
                    bytes(rng.integers(0, 256, 172, dtype=np.uint8)))
        elif fmt == "q6_k":
            out += (bytes(rng.integers(0, 256, 208, dtype=np.uint8)) +
                    f16(rand_scale()))
        elif fmt == "q2_k":
            out += (bytes(rng.integers(0, 256, 80, dtype=np.uint8)) +
                    f16(rand_scale()) + f16(rand_scale()))
        elif fmt == "q3_k":
            out += (bytes(rng.integers(0, 256, 108, dtype=np.uint8)) +
                    f16(rand_scale()))
        elif fmt == "q8_k":
            out += (struct.pack("<f", rand_scale()) +
                    bytes(rng.integers(0, 256, 288, dtype=np.uint8)))
        else:
            raise AssertionError(fmt)
    return out


CASES = [
    ("q5_0", 32, G._dequant_q5_0, ref_q5_0),
    ("q5_1", 32, G._dequant_q5_1, ref_q5_1),
    ("q2_k", 256, G._dequant_q2_k, ref_q2_k),
    ("q3_k", 256, G._dequant_q3_k, ref_q3_k),
    ("q4_k", 256, G._dequant_q4_k, ref_q4_k),
    ("q5_k", 256, G._dequant_q5_k, ref_q5_k),
    ("q6_k", 256, G._dequant_q6_k, ref_q6_k),
    ("q8_k", 256, G._dequant_q8_k, ref_q8_k),
]


@pytest.mark.parametrize("fmt,blk,vec_fn,ref_fn", CASES,
                         ids=[c[0] for c in CASES])
def test_vectorized_matches_scalar_reference(fmt, blk, vec_fn, ref_fn):
    nblocks = 7
    n = nblocks * blk
    blob = _rand_block_bytes(fmt, nblocks)
    got = vec_fn(blob, 0, n)
    want = ref_fn(blob, n)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7,
                               err_msg=fmt)


@pytest.mark.parametrize("fmt,blk,vec_fn,ref_fn", CASES,
                         ids=[c[0] for c in CASES])
def test_offset_and_padding(fmt, blk, vec_fn, ref_fn):
    """Decoders must honor a nonzero start offset into the buffer."""
    nblocks = 3
    n = nblocks * blk
    pad = b"\xAA" * 37
    blob = _rand_block_bytes(fmt, nblocks)
    got = vec_fn(pad + blob, len(pad), n)
    np.testing.assert_allclose(got, ref_fn(blob, n), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("fmt,blk,vec_fn,ref_fn", CASES,
                         ids=[c[0] for c in CASES])
def test_non_multiple_size_is_loud(fmt, blk, vec_fn, ref_fn):
    with pytest.raises(G.GgufError, match="not a multiple"):
        vec_fn(b"\0" * 1024, 0, blk + 1)


def test_container_reads_kquant_tensor(tmp_path):
    """A GGUF carrying a Q6_K tensor dequantizes through the normal
    GgufFile.tensor path (the round-1 gap: K-quants were unreadable,
    gguf.py:44-56)."""
    from tests.test_gguf import _kv, _s
    nblocks = 4
    n = nblocks * 256
    blob = _rand_block_bytes("q6_k", nblocks)
    header = struct.pack("<IIQQ", 0x46554747, 3, 1, 0)
    info = (_s("w") + struct.pack("<I", 1) + struct.pack("<Q", n) +
            struct.pack("<IQ", G.GGML_Q6_K, 0))
    head = header + info
    pad = (-len(head)) % 32
    p = tmp_path / "kq.gguf"
    p.write_bytes(head + b"\0" * pad + blob)
    with G.GgufFile(p) as gf:
        got = gf.tensor("w")
    np.testing.assert_allclose(got, ref_q6_k(blob, n), rtol=1e-6,
                               atol=1e-7)
