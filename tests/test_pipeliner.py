"""Pipeline-lane tier (`make pipeline-check`): sandbox containment
(hostile scripts die with typed records while sibling in-flight
scripts complete unharmed), the yielding-verb chain end-to-end against
a live in-process stack, per-tenant deadline enforcement observable in
`spt metrics`, the stored-script library + loadgen script scenarios,
the `pipeliner.exec` / `pipeliner.verb` fault sites (in-process
containment AND the supervised crash-recovery drill: stranded scripts
reclaimed + re-run, zero admitted loss), and the script-vs-client
chaining latency bar (rag-churn p50 >= 30% down)."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.client import submit_embed
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.embedder import Embedder
from libsplinter_tpu.engine.pipeliner import (Pipeliner,
                                              consume_script_result,
                                              store_script,
                                              submit_script)
from libsplinter_tpu.engine.searcher import Searcher
from libsplinter_tpu.scripting.library import (SCRIPT_LIBRARY,
                                               seed_library)
from libsplinter_tpu.scripting.sandbox import (ScriptBudget,
                                               ScriptKilled,
                                               SandboxedRuntime)
from libsplinter_tpu.utils import faults

CHILD = os.path.join(os.path.dirname(__file__), "chaos_child.py")


@pytest.fixture(autouse=True)
def _no_faults():
    faults.disarm()
    yield
    faults.disarm()


def _pump_until(pl, pred, timeout_s=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        pl.pump()
        if pred():
            return True
        time.sleep(0.002)
    return False


def _submit(store, key, *, script=None, name=None, args=None,
            tenant=0, deadline_ts=None):
    """Non-blocking submit (the loadgen wire form) for tests that
    drive the pipeliner synchronously via pump()."""
    req: dict = {"args": list(args or [])}
    if script is not None:
        req["script"] = script
    else:
        req["name"] = name
    if deadline_ts is not None:
        req["deadline"] = round(deadline_ts, 6)
    store.set(key, json.dumps(req))
    if tenant:
        P.stamp_tenant(store, key, tenant)
    store.label_or(key, P.LBL_SCRIPT_REQ | P.LBL_WAITING)
    store.bump(key)
    return store.find_index(key)


def _result(store, key):
    try:
        raw = store.get(P.script_result_key(store.find_index(key)))
        return json.loads(raw.rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None


def _done(store, key):
    try:
        return not store.labels(key) & P.LBL_SCRIPT_REQ
    except KeyError:
        return True


# ------------------------------------------------------- sandbox units

class TestSandbox:
    def test_step_budget_kills_infinite_loop(self):
        rt = SandboxedRuntime(ScriptBudget(max_steps=20_000))
        with pytest.raises(ScriptKilled) as ei:
            rt.run("while true do end")
        assert ei.value.reason == "budget_exceeded"
        assert rt.kill_reason == "budget_exceeded"

    def test_pcall_cannot_swallow_the_kill(self):
        rt = SandboxedRuntime(ScriptBudget(max_steps=20_000))
        with pytest.raises(ScriptKilled):
            rt.run("while true do "
                   "pcall(function() while true do end end) end")

    def test_deadline_kills_mid_compute(self):
        rt = SandboxedRuntime(ScriptBudget(
            max_steps=100_000_000, deadline_ts=time.time() + 0.15))
        t0 = time.monotonic()
        with pytest.raises(ScriptKilled) as ei:
            rt.run("while true do end")
        assert ei.value.reason == "deadline_expired"
        assert time.monotonic() - t0 < 5.0

    def test_huge_allocation_guarded(self):
        from libsplinter_tpu.scripting.microlua import LuaError
        rt = SandboxedRuntime(ScriptBudget(max_str_len=4096))
        with pytest.raises(LuaError, match="string budget"):
            rt.run("return string.rep('x', 1000000)")

    def test_os_removed_io_absent(self):
        rt = SandboxedRuntime(ScriptBudget())
        assert rt.run("return type(os), type(io)") == ("nil", "nil")

    def test_coroutine_cap(self):
        rt = SandboxedRuntime(ScriptBudget(max_coroutines=4))
        out = rt.run("""
            local cos = {}
            for i = 1, 8 do
              local co = coroutine.create(function()
                coroutine.yield()
              end)
              local ok = pcall(coroutine.resume, co)
              cos[#cos + 1] = ok
            end
            local n = 0
            for i = 1, #cos do if cos[i] then n = n + 1 end end
            return n
        """)
        rt.close()
        assert out[0] <= 4


class TestSleepClamp:
    def test_lua_host_sleep_clamped(self, store):
        # satellite: scripting/lua_host.py _sleep used to honor any
        # float — with a budget it is clamped to max_sleep_s and the
        # remaining deadline
        from libsplinter_tpu.scripting.sandbox import \
            make_sandboxed_runtime
        rt = make_sandboxed_runtime(
            store, ScriptBudget(max_sleep_s=0.05))
        t0 = time.monotonic()
        rt.run("splinter.sleep(1e9)")
        assert time.monotonic() - t0 < 2.0

    def test_cli_lua_budget_knobs(self, store, capsys):
        from libsplinter_tpu.cli.main import CliError, Session
        from libsplinter_tpu.cli.script import cmd_lua

        ses = Session(store.name)
        ses._store = store
        # the CLI host accepts the lane's budget knobs and reports a
        # typed kill — CLI and lane sandbox semantics cannot drift
        with pytest.raises(CliError, match="budget_exceeded"):
            cmd_lua(ses, ["--max-steps", "20000", "-e",
                          "while true do end"])
        # sleep clamp rides the same flags
        t0 = time.monotonic()
        cmd_lua(ses, ["--max-sleep-s", "0.05", "-e",
                      "splinter.sleep(1e9) print('ok')"])
        assert time.monotonic() - t0 < 2.0
        assert "ok" in capsys.readouterr().out
        ses._store = None             # fixture owns the handle


# -------------------------------------------------- lane containment

class TestContainment:
    """Hostile scripts die typed; a sibling in-flight script is
    unharmed.  Each hostile case runs CONCURRENTLY with a friendly
    script awaiting a verb the test resolves afterward."""

    def _friendly(self, store, pl, key="friendly"):
        _submit(store, key,
                script="local ok = splinter.submit_embed("
                       "'fdoc', 'hello') return ok and 1 or 0")
        assert _pump_until(
            pl, lambda: any(r.await_ is not None
                            for r in pl.runs.values()), 5.0)
        return key

    def _resolve_embed(self, store, doc="fdoc"):
        # play the embedder: commit a vector and clear the label
        v = np.zeros(store.vec_dim, np.float32)
        v[0] = 1.0
        store.vec_set(doc, v)
        store.label_clear(doc, P.LBL_EMBED_REQ | P.LBL_WAITING)
        store.bump(doc)

    def test_infinite_loop_dies_sibling_completes(self, store):
        pl = Pipeliner(store, max_steps=30_000)
        pl.attach()
        fk = self._friendly(store, pl)
        _submit(store, "hostile", script="while true do end")
        assert _pump_until(pl, lambda: _done(store, "hostile"), 20.0)
        rec = _result(store, "hostile")
        assert rec["err"] == "budget_exceeded"
        assert pl.stats.killed_budget == 1
        self._resolve_embed(store)
        assert _pump_until(pl, lambda: _done(store, fk), 5.0)
        assert _result(store, fk)["ok"] is True

    def test_deep_recursion_dies_typed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "rec",
                script="local function f() return f() end f()")
        assert _pump_until(pl, lambda: _done(store, "rec"), 20.0)
        rec = _result(store, "rec")
        assert rec["err"] in ("script_error", "budget_exceeded")
        assert "overflow" in rec.get("detail", "") \
            or rec["err"] == "budget_exceeded"

    def test_huge_allocation_dies_typed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "alloc",
                script="return string.rep('x', 100000000)")
        assert _pump_until(pl, lambda: _done(store, "alloc"), 10.0)
        rec = _result(store, "alloc")
        assert rec["err"] == "script_error"
        assert "string budget" in rec["detail"]

    def test_giant_sleep_clamped_by_deadline(self, store):
        pl = Pipeliner(store, max_sleep_s=0.1)
        pl.attach()
        fk = self._friendly(store, pl)
        _submit(store, "sleeper",
                script="splinter.sleep(1e9) return 1")
        assert _pump_until(pl, lambda: _done(store, "sleeper"), 10.0)
        assert _result(store, "sleeper")["ok"] is True  # woke clamped
        self._resolve_embed(store)
        assert _pump_until(pl, lambda: _done(store, fk), 5.0)

    def test_verb_storm_dies_typed(self, store):
        pl = Pipeliner(store, max_verbs=8)
        pl.attach()
        _submit(store, "storm", script="""
            for i = 1, 100 do
              splinter.submit_embed("st" .. i, "x")
            end
            return 1
        """)

        def drive():
            # resolve each embed instantly so the storm keeps going
            for key in store.list():
                if key.startswith("st"):
                    labels = store.labels(key)
                    if labels & P.LBL_EMBED_REQ:
                        v = np.zeros(store.vec_dim, np.float32)
                        v[0] = 1.0
                        store.vec_set(key, v)
                        store.label_clear(
                            key, P.LBL_EMBED_REQ | P.LBL_WAITING)
            return _done(store, "storm")

        assert _pump_until(pl, drive, 20.0)
        rec = _result(store, "storm")
        assert rec["err"] == "budget_exceeded"
        assert "verb budget" in rec["detail"]
        assert pl.stats.killed_budget == 1

    def test_parse_error_typed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "bad", script="this is (( not lua")
        assert _pump_until(pl, lambda: _done(store, "bad"), 5.0)
        assert _result(store, "bad")["err"] == "script_error"
        assert pl.stats.parse_errors == 1

    def test_unknown_stored_script_typed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "ghost", name="no-such-script")
        assert _pump_until(pl, lambda: _done(store, "ghost"), 5.0)
        assert "unknown stored script" in \
            _result(store, "ghost")["detail"]

    def test_yield_outside_verb_typed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "yielder", script="coroutine.yield(42)")
        assert _pump_until(pl, lambda: _done(store, "yielder"), 5.0)
        rec = _result(store, "yielder")
        assert rec["err"] == "script_error"
        assert "yield outside" in rec["detail"]

    def test_exec_fault_raise_contained(self, store):
        # pipeliner.exec raise: ONE script fails typed, the sibling
        # admitted in the same drain completes
        faults.arm("pipeliner.exec:raise@1")
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "victim", script="return 1")
        _submit(store, "survivor", script="return 2")
        assert _pump_until(
            pl, lambda: _done(store, "victim")
            and _done(store, "survivor"), 10.0)
        recs = {_result(store, "victim")["err"] if
                _result(store, "victim").get("err") else "ok",
                "ok" if _result(store, "survivor").get("ok")
                else _result(store, "survivor")["err"]}
        # exactly one died on the injected exec fault
        assert "script_error" in recs or "ok" in recs
        both = [_result(store, "victim"), _result(store, "survivor")]
        assert sum(1 for r in both if r.get("ok")) == 1
        assert sum(1 for r in both
                   if r.get("err") == "script_error") == 1

    def test_verb_fault_raise_contained(self, store):
        # pipeliner.verb raise: surfaces as a script error, lane lives
        faults.arm("pipeliner.verb:raise@1")
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "verbfault",
                script="splinter.submit_embed('vd', 'x') return 1")
        assert _pump_until(pl, lambda: _done(store, "verbfault"), 10.0)
        assert _result(store, "verbfault")["err"] == "script_error"
        _submit(store, "after", script="return 7")
        assert _pump_until(pl, lambda: _done(store, "after"), 5.0)
        assert _result(store, "after")["ok"] is True


# ------------------------------------------------------ lane behavior

class TestLaneProtocol:
    def test_deadline_killed_before_next_verb(self, store, capsys):
        """Acceptance: deadline-expired scripts are killed before
        dispatching further verbs, and the kill is observable in
        `spt metrics` (sptpu_pipeliner_killed_deadline)."""
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "dl", tenant=2,
                deadline_ts=time.time() + 0.15,
                script="splinter.sleep(60) "
                       "splinter.submit_embed('late', 'x') return 1")
        assert _pump_until(pl, lambda: _done(store, "dl"), 10.0)
        rec = _result(store, "dl")
        assert rec["err"] == P.ERR_DEADLINE
        assert pl.stats.killed_deadline == 1
        # the embed verb never dispatched: no request label on 'late'
        assert "late" not in store.list()
        pl.publish_stats()
        from libsplinter_tpu.cli.main import Session
        from libsplinter_tpu.cli.metrics import cmd_metrics
        ses = Session(store.name)
        ses._store = store
        cmd_metrics(ses, [])
        out = capsys.readouterr().out
        assert "sptpu_pipeliner_killed_deadline 1" in out
        assert "sptpu_pipeliner_scripts_active" in out
        ses._store = None             # fixture owns the handle

    def test_expired_at_admission_fast_fails(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "preexp", deadline_ts=time.time() - 1.0,
                script="return 1")
        assert _pump_until(pl, lambda: _done(store, "preexp"), 5.0)
        assert _result(store, "preexp")["err"] == P.ERR_DEADLINE
        assert pl.stats.deadline_expired == 1
        assert pl.stats.scripts_started == 0

    def test_shed_past_high_water_typed(self, store):
        pl = Pipeliner(store, max_scripts=1, queue_high_water=1,
                       retry_after_ms=99)
        pl.attach()
        # one long-running admit + backlog past the mark
        _submit(store, "busy", script="splinter.sleep(0.5) return 1")
        for i in range(4):
            _submit(store, f"q{i}", script="return 1")
        pl.pump()
        shed = 0
        for i in range(4):
            rec = _result(store, f"q{i}")
            if rec and rec.get("err") == P.ERR_OVERLOADED:
                assert rec["retry_after_ms"] == 99
                shed += 1
        assert shed >= 1
        assert pl.stats.shed == shed

    def test_raced_rewrite_not_committed(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "race", script="splinter.sleep(0.2) return 1")
        assert _pump_until(
            pl, lambda: any(r.await_ for r in pl.runs.values()), 5.0)
        # client rewrites the slot mid-script: the old run must not
        # commit over the new request
        store.set("race", json.dumps({"script": "return 99"}))
        store.label_or("race", P.LBL_SCRIPT_REQ | P.LBL_WAITING)
        store.bump("race")
        assert _pump_until(pl, lambda: _done(store, "race"), 10.0)
        rec = _result(store, "race")
        assert rec["ok"] is True and rec["ret"] == [99]
        assert pl.stats.raced >= 1

    def test_sweep_reaps_orphaned_results(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "orphan", script="return 1")
        assert _pump_until(pl, lambda: _done(store, "orphan"), 5.0)
        # client never consumes; slot rewritten -> epoch moves
        store.set("orphan", "something else")
        assert pl.sweep_results() >= 1
        assert _result(store, "orphan") is None

    def test_tenant_rides_verbs(self, store):
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "tt", tenant=5,
                script="splinter.submit_embed('tdoc', 'x') return 1")
        assert _pump_until(
            pl, lambda: "tdoc" in store.list()
            and store.labels("tdoc") & P.LBL_EMBED_REQ, 5.0)
        # the downstream embed request carries the script's tenant id
        assert P.read_tenant(store.labels("tdoc")) == 5
        assert pl.tenants.get(5, "admitted") == 1

    def test_reused_key_clears_stale_ctx_exceeded(self, store):
        """A key that once got a ctx_exceeded rejection must not
        misreport it after a successful re-embed (the embedder never
        clears the bit on later success — the submit side must)."""
        pl = Pipeliner(store)
        pl.attach()
        store.set("rk", "x")
        store.label_or("rk", P.LBL_CTX_EXCEEDED)   # previous rejection
        _submit(store, "ctxreq",
                script="return splinter.submit_embed('rk', 'short')"
                       " and 1 or 0")
        assert _pump_until(
            pl, lambda: "rk" in store.list()
            and store.labels("rk") & P.LBL_EMBED_REQ, 5.0)
        assert not store.labels("rk") & P.LBL_CTX_EXCEEDED
        v = np.zeros(store.vec_dim, np.float32)
        v[0] = 1.0
        store.vec_set("rk", v)
        store.label_clear("rk", P.LBL_EMBED_REQ | P.LBL_WAITING)
        assert _pump_until(pl, lambda: _done(store, "ctxreq"), 5.0)
        assert _result(store, "ctxreq")["ret"] == [1]

    def test_deferred_backlog_not_recounted(self, store):
        """The deferred-backlog memo: a row re-offered every re-plan
        is parsed and counted ONCE, not once per pump."""
        pl = Pipeliner(store, max_scripts=1)
        pl.attach()
        _submit(store, "hold", script="splinter.sleep(0.3) return 1")
        for i in range(3):
            _submit(store, f"wait{i}", script="return 1")
        for _ in range(50):
            pl.pump()
            time.sleep(0.002)
        assert _pump_until(
            pl, lambda: all(_done(store, f"wait{i}")
                            for i in range(3)), 10.0)
        assert pl.stats.requests == 4          # one per submission
        assert pl.stats.deferred <= 3          # first sights only
        assert not pl._parsed                  # memo drained

    def test_stored_script_lifecycle(self, store):
        seed_library(store)
        names = {k[len(P.SCRIPT_STORE_PREFIX):]
                 for k in store.list()
                 if k.startswith(P.SCRIPT_STORE_PREFIX)}
        assert names == set(SCRIPT_LIBRARY)
        store_script(store, "custom", "return 42")
        pl = Pipeliner(store)
        pl.attach()
        _submit(store, "creq", name="custom")
        assert _pump_until(pl, lambda: _done(store, "creq"), 5.0)
        assert _result(store, "creq")["ret"] == [42]


# ----------------------------------------------- full-stack e2e + CLI

def _stack(store, stop_after=90.0, **pl_kw):
    def enc(texts):
        out = np.zeros((len(texts), store.vec_dim), np.float32)
        for i, t in enumerate(texts):
            out[i, hash(t) % store.vec_dim] = 1.0
        return out

    emb = Embedder(store, encoder_fn=enc, max_ctx=64)
    sr = Searcher(store)
    comp = Completer(store, generate_fn=lambda p: iter([b"answer"]),
                     template="none")
    pl = Pipeliner(store, **pl_kw)
    daemons = (emb, sr, comp, pl)
    for d in daemons:
        d.attach()
    ths = [threading.Thread(
        target=d.run, kwargs=dict(idle_timeout_ms=10,
                                  stop_after=stop_after), daemon=True)
        for d in daemons]
    for t in ths:
        t.start()
    return daemons, ths


def _seed_docs(store, n=8):
    rng = np.random.default_rng(0)
    for i in range(n):
        k = f"lgd{i}"
        store.set(k, f"seed doc {i}")
        v = rng.standard_normal(store.vec_dim).astype(np.float32)
        store.vec_set(k, v / np.linalg.norm(v))


class TestEndToEnd:
    def test_submit_embed_client_helper(self, store):
        # satellite: the missing third client verb — tenant/deadline/
        # retry parity with submit_search/submit_completion
        daemons, ths = _stack(store)
        try:
            assert submit_embed(store, "ce", "hello world",
                                tenant=3, deadline_ms=8000,
                                timeout_ms=8000) is True
            assert np.abs(store.vec_get("ce")).max() > 0
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)

    def test_inline_chain_and_stored_scenarios(self, store):
        daemons, ths = _stack(store)
        _seed_docs(store)
        seed_library(store)
        try:
            rec = submit_script(store, "e2e", timeout_ms=20_000,
                                script="""
                local ok, err = splinter.submit_embed("ed", "doc")
                if not ok then error(err) end
                local q = "eq"
                splinter.set(q, "scratch")
                splinter.set_embedding(q, splinter.get_embedding("ed"))
                local hits, serr = splinter.submit_search(q, 3)
                splinter.unset(q)
                if not hits then error(serr) end
                local out, cerr = splinter.submit_completion(
                    "ec", "ctx: " .. table.concat(hits, ","))
                if not out then error(cerr) end
                return #hits, out
            """)
            assert rec["ok"] is True
            assert rec["ret"][0] >= 1
            assert "answer" in rec["ret"][1]
            consume_script_result(store, "e2e")
            for name in SCRIPT_LIBRARY:
                rec = submit_script(store, f"e2e_{name}", name=name,
                                    args=[f"doc_{name}", 3],
                                    timeout_ms=20_000, tenant=1,
                                    deadline_ms=15_000)
                assert rec.get("ok") is True, (name, rec)
                consume_script_result(store, f"e2e_{name}")
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)

    def test_loadgen_script_scenarios_end_to_end(self, store):
        """Acceptance: agent-loop / multi-hop / map-reduce run
        end-to-end from scripts only, per-tenant deadlines enforced,
        zero admitted loss."""
        from libsplinter_tpu.cli.loadgen import (LoadGenerator,
                                                 TenantSpec)

        daemons, ths = _stack(store)
        try:
            for scn in ("agent-loop", "multi-hop", "map-reduce"):
                gen = LoadGenerator(
                    store, [TenantSpec(1, 5.0, deadline_ms=8000)],
                    duration_s=1.2, corpus=8, seed=4, scenario=scn)
                rep = gen.run()
                assert rep["lost"] == 0, (scn, rep)
                assert rep["ok"] >= max(1, rep["issued"] - 1), \
                    (scn, rep)
                assert "p50_ms" in rep["per_tenant"]["1"]["script"]
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)

    def test_unknown_scenario_lists_registry(self, store):
        from libsplinter_tpu.cli.loadgen import LoadGenerator, \
            TenantSpec
        with pytest.raises(ValueError) as ei:
            LoadGenerator(store, [TenantSpec(1, 1.0)],
                          scenario="nope")
        msg = str(ei.value)
        for name in ("rag-churn", "rag-churn-script", "agent-loop",
                     "multi-hop", "map-reduce"):
            assert name in msg

    def test_cli_pipeline_store_management(self, store, capsys,
                                           tmp_path):
        from libsplinter_tpu.cli.main import CliError, Session
        from libsplinter_tpu.cli.pipeline import cmd_pipeline

        ses = Session(store.name)
        ses._store = store
        f = tmp_path / "s.lua"
        f.write_text("return 1")
        cmd_pipeline(ses, ["put", "mine", str(f)])
        cmd_pipeline(ses, ["seed"])
        cmd_pipeline(ses, ["ls"])
        out = capsys.readouterr().out
        assert "mine" in out and "rag-churn" in out
        cmd_pipeline(ses, ["cat", "mine"])
        assert "return 1" in capsys.readouterr().out
        cmd_pipeline(ses, ["rm", "mine"])
        with pytest.raises(CliError):
            cmd_pipeline(ses, ["cat", "mine"])
        # run without a live lane fails fast with guidance
        with pytest.raises(CliError, match="no live pipeline lane"):
            cmd_pipeline(ses, ["run", "-e", "return 1"])
        # double designation is a usage error, not a traceback
        with pytest.raises(CliError, match="already given"):
            cmd_pipeline(ses, ["run", "@rag-churn", "-e", "return 1"])
        ses._store = None             # fixture owns the handle

    def test_cli_pipeline_run_against_live_lane(self, store, capsys):
        from libsplinter_tpu.cli.main import Session
        from libsplinter_tpu.cli.pipeline import cmd_pipeline

        daemons, ths = _stack(store)
        try:
            # lane heartbeat must exist for daemon_live
            daemons[-1].publish_stats()
            ses = Session(store.name)
            ses._store = store
            cmd_pipeline(ses, ["run", "-e", "return 40 + 2",
                               "--timeout-ms", "10000"])
            assert "ok: 42" in capsys.readouterr().out
            ses._store = None         # fixture owns the handle
        finally:
            for d in daemons:
                d.stop()
            for t in ths:
                t.join(timeout=10)


# ------------------------------------------------------- chaos drills

@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_crash_reclaims_scripts(store, monkeypatch):
    """Acceptance: a mid-run `pipeliner.exec` crash under `spt
    supervise` loses ZERO admitted scripts — the restarted lane finds
    LBL_SCRIPT_REQ still up on the stranded requests, re-runs them,
    and the loadgen LOST counter stays 0."""
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec
    from libsplinter_tpu.engine.supervisor import Supervisor

    # the lane's 6th exec slice dies — mid-run, with admitted scripts
    # suspended on verbs
    monkeypatch.setenv("SPTPU_FAULT", "pipeliner.exec:crash@6")
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")

    daemons, ths = _stack(store, stop_after=240.0)
    pl_inproc = daemons[-1]
    pl_inproc.stop()                   # the SUPERVISED child serves
    seed_library(store)

    holder: dict = {}

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, CHILD, "pipeliner", store.name],
            env=holder["sup"]._child_env(lane))

    sup = Supervisor(store.name, lanes=("pipeliner",), spawn_fn=spawn,
                     store=store, backoff_base_ms=100,
                     backoff_max_ms=1500, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    holder["sup"] = sup
    t = threading.Thread(target=sup.run,
                         kwargs={"poll_interval_s": 0.1,
                                 "stop_after": 240.0})
    t.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if P.heartbeat_live(store, P.KEY_SCRIPT_STATS,
                                max_age_s=30):
                break
            time.sleep(0.2)
        else:
            pytest.fail("pipeliner never came up under supervision")
        gen = LoadGenerator(store,
                            [TenantSpec(1, 4.0, deadline_ms=60_000)],
                            duration_s=6.0, corpus=8, seed=9,
                            scenario="rag-churn-script",
                            drain_s=120.0)
        rep = gen.run()
        assert sup.lanes["pipeliner"].restarts >= 1, rep
        assert rep["lost"] == 0, rep
        assert rep["ok"] >= 1, rep
    finally:
        sup.stop()
        t.join(timeout=30)
        sup.shutdown()
        for d in daemons:
            d.stop()
        for th in ths:
            th.join(timeout=15)


@pytest.mark.slow
def test_script_chain_beats_client_chain(store):
    """Acceptance: rag-churn as a stored script shows p50 >= 30%
    below the client-side chain on the same in-process stack (the
    `make pipeline-check` gate runs the standalone version)."""
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec

    daemons, ths = _stack(store, stop_after=120.0)
    try:
        def p50(scn):
            gen = LoadGenerator(
                store, [TenantSpec(1, 10.0, deadline_ms=8000)],
                duration_s=2.5, corpus=8, seed=11, scenario=scn)
            rep = gen.run()
            assert rep["lost"] == 0, (scn, rep)
            lane = "rag" if scn == "rag-churn" else "script"
            # exact median: the report's log-bucketed p50 is too
            # coarse (~19% buckets) for a 30% A/B bar
            return float(np.median(gen.raw_ms[(1, lane)]))

        client = p50("rag-churn")
        script = p50("rag-churn-script")
        assert script <= 0.7 * client, (client, script)
    finally:
        for d in daemons:
            d.stop()
        for t in ths:
            t.join(timeout=15)
