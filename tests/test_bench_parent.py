"""bench.py's parent is the tunnel-discipline layer the round's
evidence depends on; its recovery path (a later series phase hangs →
the embed headline still gets reported, marked partial) must not
regress.  Driven as a real subprocess the way the driver/watcher run
it, with the BENCH_TEST_SLEEP_AFTER hook standing in for the round-3
on-chip hang."""
from __future__ import annotations

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lock_refusal_instead_of_second_client(tmp_path):
    """ADVICE r3: with the watcher's flock held for the whole window,
    bench.py must FAIL with an error JSON — never start a child that
    would be a second concurrent tunnel client."""
    import fcntl

    lock_path = tmp_path / "watch.lock"
    holder = open(lock_path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    env = dict(
        os.environ,
        SPTPU_BENCH_LOCK=str(lock_path),
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_TIMEOUT="75",
    )
    env.pop("BENCH_CPU", None)        # CPU mode would skip the lock
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    holder.close()
    assert proc.returncode == 0
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] == 0.0
    assert "lock not acquired" in rec["error"]
    assert rec["detail"]["attempts"] == 0     # no child ever spawned


def test_timeout_recovers_headline(tmp_path):
    env = dict(
        os.environ,
        BENCH_CPU="1",
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_PHASES="embed,profile",
        BENCH_TEST_SLEEP_AFTER="embed",      # profile never runs
        BENCH_TEXTS="8", BENCH_BATCH="4", BENCH_BUCKETS="32",
        BENCH_P50_PROBES="2",
        BENCH_TIMEOUT="240", BENCH_ATTEMPT_TIMEOUT="90",
        BENCH_BACKOFF="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=230)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    # the headline survived the hang, marked as an interrupted series
    assert rec["metric"] == "embeddings_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["series_complete"] is False
    assert "error" not in rec
    # and the ledger holds the embed record the child appended itself
    led = [json.loads(ln) for ln in
           (tmp_path / "ledger.jsonl").read_text().splitlines()]
    assert [r["metric"] for r in led] == ["embeddings_per_sec_per_chip"]
